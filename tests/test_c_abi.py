"""C ABI shim (zompi_mpi.h / libzompi_mpi.so) — SURVEY §7's commitment,
VERDICT round-2 item 8.

Proves: a C program compiles against the mpi.h-compatible header, links
the shim, and runs as real OS processes (pure-C universe); and a C rank
interoperates with Python TcpProc ranks in ONE universe (same modex,
framing, and barrier wire protocol)."""

import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from zhpe_ompi_tpu import native
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def shim():
    so = native.build_mpi_shim()
    return so


def _compile_example(shim, tmp_path_factory, src_name: str) -> str:
    """One shim link recipe for every acceptance binary."""
    stem = src_name.rsplit(".", 1)[0]
    out = tmp_path_factory.mktemp(f"cabi_{stem}") / stem
    libdir = os.path.dirname(shim)
    libname = os.path.basename(shim)[3:].rsplit(".so", 1)[0]  # lib<X>.so
    subprocess.run(
        ["gcc", os.path.join(REPO, "examples", src_name), "-o", str(out),
         "-I", native.mpi_header_dir(), "-L", libdir, f"-l{libname}",
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True,
    )
    return str(out)


@pytest.fixture(scope="module")
def ring_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "ring_c.c")



def _compile_c(shim, src, binpath):
    """Single link recipe for ad-hoc C sources (the _compile_example
    analog for tmp_path-generated programs)."""
    libdir = os.path.dirname(shim)
    libname = os.path.basename(shim)[3:].rsplit(".so", 1)[0]
    subprocess.run(
        ["gcc", str(src), "-o", str(binpath), "-I",
         native.mpi_header_dir(), "-L", libdir, f"-l{libname}",
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True,
    )

def _run_example(shim, tmp_path_factory, src_name, n, timeout=60):
    """Compile an examples/ C source against the shim and run it as n
    real processes; returns per-rank stdout (asserts every rank exits
    0).  The one launch recipe every acceptance test shares."""
    bin_ = _compile_example(shim, tmp_path_factory, src_name)
    port = _free_port()
    procs = [
        subprocess.Popen([bin_], env=_env(r, n, port),
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
        for r in range(n)
    ]
    outs = []
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
        outs.append(out)
    return outs


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(rank, size, port):
    env = dict(os.environ)
    env.update({
        "ZMPI_RANK": str(rank), "ZMPI_SIZE": str(size),
        "ZMPI_COORD_HOST": "127.0.0.1", "ZMPI_COORD_PORT": str(port),
        # force the shared-memory rings on (the hardware-aware default
        # disables them on this single-core CI host): every direct
        # multi-process test then exercises the sm transport, while
        # the zmpirun-launched tests keep the TCP default — both
        # transports stay covered
        "ZMPI_MCA_sm": "1",
    })
    return env


class TestPureC:
    @pytest.mark.parametrize("n", [2, 3, 4])
    def test_ring_example(self, ring_bin, n):
        """The reference's examples/ring_c.c acceptance shape: token ring
        + allreduce + bcast across n real C processes."""
        port = _free_port()
        procs = [
            subprocess.Popen([ring_bin], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        outs = []
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            outs.append(out)
        for r in range(n):
            assert f"ring_c rank {r}/{n} OK" in outs[r]

    @pytest.mark.parametrize("n", [3, 5])
    def test_hello_and_connectivity_examples(self, shim,
                                             tmp_path_factory, n):
        """The reference's examples/hello_c.c and connectivity_c.c
        acceptance shapes: identity + full NxN pairwise reachability."""
        outs = _run_example(shim, tmp_path_factory, "hello_c.c", n)
        for r in range(n):
            assert f"I am {r} of {n}" in outs[r]
        outs = _run_example(shim, tmp_path_factory, "connectivity_c.c",
                            n)
        assert f"Connectivity test on {n} processes PASSED." in outs[0]

    @pytest.mark.parametrize("n", [2, 3])
    def test_util_example(self, shim, tmp_path_factory, n):
        """Round-5 utility surface: versions/threads, error classes,
        Alloc_mem, Reduce_local, Request_get_status, Waitsome, Cancel,
        Get_elements, Sendrecv_replace, c2f/f2c (self-checking C
        program; every CHECK aborts on failure)."""
        outs = _run_example(shim, tmp_path_factory, "util_c.c", n)
        assert f"util_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 4])
    def test_objinfo_example(self, shim, tmp_path_factory, n):
        """Round-5 object tier: Info dictionaries, object naming,
        comm/win/file info snapshots, Comm_split_type(SHARED),
        Comm_create_group over a strict subset (odd ranks never call),
        Comm_dup_with_info, Comm_idup."""
        outs = _run_example(shim, tmp_path_factory, "objinfo_c.c", n)
        assert f"objinfo_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 3])
    def test_dtype2_example(self, shim, tmp_path_factory, n):
        """Round-5 datatype tier 2: struct/resized over the wire (a C
        struct with padding round-trips), hvector columns, subarray
        interior block, darray block+cyclic typemaps, dup, true extent,
        envelope/contents, deprecated MPI-1 forms."""
        outs = _run_example(shim, tmp_path_factory, "dtype2_c.c", n)
        assert f"dtype2_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 3])
    def test_winadv_example(self, shim, tmp_path_factory, n):
        """Round-5 win tier 2 + matched probe: lock_all epochs,
        Win_test polling, dynamic windows with absolute displacements,
        shared-memory windows with direct load/store through
        shared_query, win attributes, Mprobe/Mrecv incl. a 2 MB
        rendezvous message claimed by Improbe."""
        outs = _run_example(shim, tmp_path_factory, "winadv_c.c", n,
                            timeout=90)
        assert f"winadv_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 4])
    def test_errip_example(self, shim, tmp_path_factory, n):
        """Round-5 errhandlers + MPI_IN_PLACE: ERRORS_RETURN flips the
        fatal default, a user handler observes (comm, code),
        Comm_call_errhandler dispatches, file handlers default to
        ERRORS_RETURN; IN_PLACE across allreduce/reduce/allgather(v)/
        gather/scatter/alltoall/reduce_scatter_block/scan."""
        outs = _run_example(shim, tmp_path_factory, "errip_c.c", n)
        assert f"errip_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 4])
    def test_nbrw_example(self, shim, tmp_path_factory, n):
        """Round-5 generalized exchanges: Alltoallw with per-peer
        datatypes (+IN_PLACE, +Ialltoallw), neighbor allgatherv/
        alltoallv/alltoallw on a periodic Cartesian ring, the
        Ineighbor family, Cart_map/Graph_map."""
        outs = _run_example(shim, tmp_path_factory, "nbrw_c.c", n)
        assert f"nbrw_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 4])
    def test_io2_example(self, shim, tmp_path_factory, n):
        """Round-5 MPI-IO tier 2: strided file views (write through the
        view, verify raw interleaving), collective + split collective
        IO, shared-pointer appends (every record exactly once),
        rank-ordered shared IO, nonblocking IO, preallocate/atomicity,
        byte-offset query."""
        outs = _run_example(shim, tmp_path_factory, "io2_c.c", n,
                            timeout=90)
        assert f"io2_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 4])
    def test_misc2_example(self, shim, tmp_path_factory, n):
        """Round-5 batch 8: group range algebra/compare, MPI-1
        attribute names, datatype attributes with delete callbacks,
        persistent send modes over repeated Start rounds,
        request-based RMA, external32 canonical packing (big-endian
        bytes on the wire), size-matched + f90 types, generalized
        requests with query/free callbacks."""
        outs = _run_example(shim, tmp_path_factory, "misc2_c.c", n)
        assert f"misc2_c OK on {n} ranks" in outs[0]

    @pytest.mark.parametrize("n", [2, 4])
    def test_ports_example(self, tmp_path, n):
        """Round-5 dynamic-process tier 2 under zmpirun (the name
        server lives in the launcher): ports + publish/lookup/
        unpublish, Comm_accept/connect between the job's halves,
        Comm_join over a raw socket, general Dist_graph_create ring
        declared entirely by rank 0, predefined DUP_FN propagation."""
        binary = str(tmp_path / "ports")
        res = subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.zmpicc",
             os.path.join(REPO, "examples", "ports_c.c"), "-o", binary],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert res.returncode == 0, res.stderr
        run = subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.mpirun",
             "-n", str(n), binary],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert run.returncode == 0, run.stderr + run.stdout
        assert f"ports_c OK on {n} ranks" in run.stdout

    def test_spawn_multiple(self, shim, tmp_path):
        """MPI_Comm_spawn_multiple: two command blocks share ONE child
        world; each child reports its world rank and block identity
        back to the parent over the spawn intercomm."""
        child = tmp_path / "childm.c"
        child.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  MPI_Init(&argc, &argv);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  MPI_Comm parent;
  MPI_Comm_get_parent(&parent);
  if (parent == MPI_COMM_NULL) return 3;
  /* block identity arrives as argv[1] */
  int payload[2] = {rank * 10 + atoi(argv[1]), size};
  MPI_Send(payload, 2, MPI_INT, 0, 1, parent);
  MPI_Finalize();
  return 0;
}
''')
        parent = tmp_path / "parentm.c"
        parent.write_text(r'''
#include <stdio.h>
#include <string.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  MPI_Init(&argc, &argv);
  int rank;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  char *cmds[2] = {argv[1], argv[1]};
  char *a0[] = {(char *)"1", 0};
  char *a1[] = {(char *)"2", 0};
  char **argvs[2] = {a0, a1};
  int counts[2] = {1, 2};
  MPI_Comm inter;
  int codes[3];
  if (MPI_Comm_spawn_multiple(2, cmds, argvs, counts, 0, 0,
                              MPI_COMM_WORLD, &inter, codes)
      != MPI_SUCCESS) return 4;
  int rsz = -1;
  MPI_Comm_remote_size(inter, &rsz);
  if (rsz != 3) return 5;
  if (rank == 0) {
    int seen_block[4] = {0, 0, 0, 0};
    for (int k = 0; k < 3; k++) {
      int payload[2];
      MPI_Status st;
      MPI_Recv(payload, 2, MPI_INT, MPI_ANY_SOURCE, 1, inter, &st);
      if (payload[1] != 3) return 6;   /* ONE shared child world */
      seen_block[payload[0] % 10]++;
    }
    if (seen_block[1] != 1 || seen_block[2] != 2) return 7;
    printf("spawn_multiple OK\n");
  }
  MPI_Comm_free(&inter);
  MPI_Finalize();
  return 0;
}
''')
        childbin = tmp_path / "childm"
        parentbin = tmp_path / "parentm"
        _compile_c(shim, child, childbin)
        _compile_c(shim, parent, parentbin)
        port = _free_port()
        p = subprocess.Popen([str(parentbin), str(childbin)],
                             env=_env(0, 1, port),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
        out, err = p.communicate(timeout=90)
        assert p.returncode == 0, f"parent failed: {err}\n{out}"
        assert "spawn_multiple OK" in out

    def test_sm_soak(self, shim, tmp_path_factory):
        """Mixed concurrent traffic over the rings: overlapping
        nonblocking allreduces, a random-size pt2pt ring mixing eager
        and rendezvous payloads, and lock/accumulate RMA, 60
        iterations x 3 ranks — the race soak for the sm transport."""
        outs = _run_example(shim, tmp_path_factory, "smsoak_c.c", 3,
                            timeout=240)
        # the example takes the iteration count as argv[1]; the
        # compiled default (100) applies under _run_example
        assert "smsoak OK" in outs[0]

    @pytest.mark.parametrize("n", [2, 3])
    def test_crossed_large_gets_over_sm(self, shim, tmp_path_factory,
                                        n):
        """Crossed 6 MB MPI_Gets whose replies exceed the 4 MiB sm ring
        in both directions at once: the poll thread must spill its
        replies instead of blocking (a blocked poll thread would
        deadlock the pair AND freeze every other peer's inbound)."""
        outs = _run_example(shim, tmp_path_factory, "crossget_c.c", n,
                            timeout=120)
        assert "crossget OK" in outs[0]

    def test_pmpi_interposition(self, shim, tmp_path):
        """The PMPI profiling contract (send.c:37-39's weak-symbol
        pattern): an application-defined strong MPI_Send/MPI_Recv
        wrapper overrides the shim's weak symbol, counts the call, and
        reaches the real engine through PMPI_*; payloads still
        deliver."""
        src = tmp_path / "pmpi.c"
        src.write_text(r'''
#include <stdio.h>
#include "zompi_mpi.h"
#include "zompi_pmpi.h"

static int sends = 0, recvs = 0;

/* strong definitions override the shim's weak MPI_X */
int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest,
             int tag, MPI_Comm comm) {
  sends++;
  return PMPI_Send(buf, count, dt, dest, tag, comm);
}
int MPI_Recv(void *buf, int count, MPI_Datatype dt, int src, int tag,
             MPI_Comm comm, MPI_Status *st) {
  recvs++;
  return PMPI_Recv(buf, count, dt, src, tag, comm, st);
}

int main(int argc, char **argv) {
  MPI_Init(&argc, &argv);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  int v = rank * 3 + 7, got = -1;
  int peer = 1 - rank;
  if (rank == 0) {
    if (MPI_Send(&v, 1, MPI_INT, peer, 1, MPI_COMM_WORLD)
        != MPI_SUCCESS) return 2;
    if (MPI_Recv(&got, 1, MPI_INT, peer, 2, MPI_COMM_WORLD,
                 MPI_STATUS_IGNORE) != MPI_SUCCESS) return 3;
  } else {
    if (MPI_Recv(&got, 1, MPI_INT, peer, 1, MPI_COMM_WORLD,
                 MPI_STATUS_IGNORE) != MPI_SUCCESS) return 3;
    if (MPI_Send(&v, 1, MPI_INT, peer, 2, MPI_COMM_WORLD)
        != MPI_SUCCESS) return 2;
  }
  if (got != peer * 3 + 7) return 4;
  /* the wrappers saw the application calls (collectives use the
   * engine internally, not the profiled entry points) */
  if (sends != 1 || recvs != 1) return 5;
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("pmpi OK\n");
  MPI_Finalize();
  return 0;
}
''')
        binp = tmp_path / "pmpi"
        _compile_c(shim, src, binp)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binp)], env=_env(r, 2, port),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
            for r in range(2)
        ]
        outs = []
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            outs.append(out)
        assert "pmpi OK" in outs[0]

    def test_mpit_tool_interface(self, shim, tmp_path):
        """The C MPI_T surface (ompi/mpi/tool's C side): enumerate
        cvars/pvars, WRITE the eager-limit cvar and observe the
        protocol switch move (an eager-size send becomes a rendezvous
        send in the pvar counters), and watch the unexpected-queue
        level rise and fall."""
        src = tmp_path / "mpit.c"
        src.write_text(r'''
#include <stdio.h>
#include <string.h>
#include "zompi_mpi.h"

int main(int argc, char **argv) {
  int prov = -1;
  if (MPI_T_init_thread(MPI_THREAD_SINGLE, &prov) != MPI_SUCCESS)
    return 2;
  MPI_Init(&argc, &argv);
  int rank;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);

  int ncv = 0, npv = 0;
  if (MPI_T_cvar_get_num(&ncv) != MPI_SUCCESS || ncv < 2) return 3;
  if (MPI_T_pvar_get_num(&npv) != MPI_SUCCESS || npv < 5) return 4;

  /* find the eager-limit cvar by name */
  int eager_idx = -1;
  for (int i = 0; i < ncv; i++) {
    char name[64]; int nl = sizeof name;
    MPI_Datatype dt; int verb, bind, scope;
    if (MPI_T_cvar_get_info(i, name, &nl, &verb, &dt, 0, 0, 0, &bind,
                            &scope) != MPI_SUCCESS) return 5;
    if (!strcmp(name, "tcp_eager_limit")) {
      if (dt != MPI_LONG || scope != MPI_T_SCOPE_LOCAL) return 6;
      eager_idx = i;
    }
  }
  if (eager_idx < 0) return 7;

  MPI_T_cvar_handle ch; int cnt;
  if (MPI_T_cvar_handle_alloc(eager_idx, 0, &ch, &cnt) != MPI_SUCCESS)
    return 8;
  long lim = -1;
  if (MPI_T_cvar_read(ch, &lim) != MPI_SUCCESS || lim != (1L << 20))
    return 9;

  MPI_T_pvar_session ses;
  if (MPI_T_pvar_session_create(&ses) != MPI_SUCCESS) return 10;
  MPI_T_pvar_handle eager_h, rndv_h, unexp_h;
  /* pvar order: eager_sends, rndv_sends, bytes_sent, unexpected, posted */
  MPI_T_pvar_handle_alloc(ses, 0, 0, &eager_h, &cnt);
  MPI_T_pvar_handle_alloc(ses, 1, 0, &rndv_h, &cnt);
  MPI_T_pvar_handle_alloc(ses, 3, 0, &unexp_h, &cnt);

  int peer = 1 - rank;
  long long e0, e1, r0, r1;
  MPI_T_pvar_read(ses, eager_h, &e0);
  MPI_T_pvar_read(ses, rndv_h, &r0);
  int payload[256];
  memset(payload, rank, sizeof payload);
  if (rank == 0) {
    MPI_Send(payload, 256, MPI_INT, peer, 1, MPI_COMM_WORLD);
    MPI_Recv(payload, 256, MPI_INT, peer, 2, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
  } else {
    MPI_Recv(payload, 256, MPI_INT, peer, 1, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
    MPI_Send(payload, 256, MPI_INT, peer, 2, MPI_COMM_WORLD);
  }
  MPI_T_pvar_read(ses, eager_h, &e1);
  MPI_T_pvar_read(ses, rndv_h, &r1);
  if (e1 <= e0 || r1 != r0) return 11; /* 1 KiB goes eager */

  /* write the cvar: now the same payload goes rendezvous */
  long tiny = 64;
  if (MPI_T_cvar_write(ch, &tiny) != MPI_SUCCESS) return 12;
  MPI_T_cvar_read(ch, &lim);
  if (lim != 64) return 13;
  MPI_Barrier(MPI_COMM_WORLD);
  MPI_T_pvar_read(ses, rndv_h, &r0);
  if (rank == 0) {
    MPI_Send(payload, 256, MPI_INT, peer, 3, MPI_COMM_WORLD);
  } else {
    MPI_Recv(payload, 256, MPI_INT, peer, 3, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
  }
  MPI_T_pvar_read(ses, rndv_h, &r1);
  if (rank == 0 && r1 != r0 + 1) return 14; /* the switch moved */
  long big = 1 << 20;
  MPI_T_cvar_write(ch, &big);

  /* unexpected-queue LEVEL: rank 1 sends early, rank 0 reads the
   * level before and after receiving */
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 1) {
    MPI_Send(payload, 16, MPI_INT, 0, 4, MPI_COMM_WORLD);
    MPI_Barrier(MPI_COMM_WORLD);
    /* park until rank 0 finishes its level reads: running ahead would
     * land the NEXT barrier's internal frame in rank 0's unexpected
     * queue mid-assertion */
    MPI_Recv(payload, 1, MPI_INT, 0, 5, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
  } else {
    MPI_Barrier(MPI_COMM_WORLD); /* the send landed unexpected */
    long long lvl = -1;
    MPI_T_pvar_read(ses, unexp_h, &lvl);
    if (lvl < 1) return 15;
    MPI_Recv(payload, 16, MPI_INT, 1, 4, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
    MPI_T_pvar_read(ses, unexp_h, &lvl);
    if (lvl != 0) return 16;
    MPI_Send(payload, 1, MPI_INT, 1, 5, MPI_COMM_WORLD); /* release */
  }

  MPI_T_pvar_session_free(&ses);
  MPI_T_cvar_handle_free(&ch);
  if (MPI_T_finalize() != MPI_SUCCESS) return 17;
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("mpit OK\n");
  MPI_Finalize();
  return 0;
}
''')
        binp = tmp_path / "mpit"
        _compile_c(shim, src, binp)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binp)], env=_env(r, 2, port),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
            for r in range(2)
        ]
        outs = []
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            outs.append(out)
        assert "mpit OK" in outs[0]

    def test_are_fatal_default_aborts(self, shim, tmp_path):
        """The MPI default handler is ERRORS_ARE_FATAL: an invalid-rank
        send without an installed handler must kill the process with a
        diagnostic, not return a code."""
        src = tmp_path / "fatal.c"
        src.write_text(
            '#include "zompi_mpi.h"\n'
            "#include <stdio.h>\n"
            "int main(int argc, char **argv) {\n"
            "  MPI_Init(&argc, &argv);\n"
            "  int x = 0;\n"
            "  MPI_Send(&x, 1, MPI_INT, 99, 0, MPI_COMM_WORLD);\n"
            '  printf("unreachable\\n");\n'
            "  MPI_Finalize();\n"
            "  return 0;\n"
            "}\n")
        binp = tmp_path / "fatal"
        _compile_c(shim, src, binp)
        port = _free_port()
        p = subprocess.run([str(binp)], env=_env(0, 1, port),
                           capture_output=True, text=True, timeout=30)
        assert p.returncode != 0
        assert "MPI_ERRORS_ARE_FATAL" in p.stderr
        assert "unreachable" not in p.stdout


class TestInterop:
    def test_c_rank_joins_python_universe(self, shim, tmp_path):
        """One C rank + two Python TcpProc ranks in a single 3-rank
        universe: modex through the Python coordinator, pt2pt both
        directions, and a mixed barrier."""
        src = tmp_path / "interop.c"
        src.write_text(r'''
#include <stdio.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  /* receive a doubles payload from python rank 0, reply transformed */
  double buf[4];
  MPI_Status st;
  MPI_Recv(buf, 4, MPI_DOUBLE, 0, 7, MPI_COMM_WORLD, &st);
  int i, n;
  MPI_Get_count(&st, MPI_DOUBLE, &n);
  for (i = 0; i < 4; i++) buf[i] *= 10.0;
  MPI_Send(buf, 4, MPI_DOUBLE, 0, 8, MPI_COMM_WORLD);
  /* mixed-plane barrier with the python ranks */
  MPI_Barrier(MPI_COMM_WORLD);
  /* then message the OTHER python rank */
  long v = 12345 + rank;
  MPI_Send(&v, 1, MPI_LONG, 1, 9, MPI_COMM_WORLD);
  printf("interop rank %d/%d n=%d OK\n", rank, size, n);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "interop"
        _compile_c(shim, src, binpath)

        port = _free_port()
        n = 3  # ranks 0,1 = python; rank 2 = C
        results = {}
        excs = []

        def py_rank(rank):
            try:
                proc = TcpProc(rank, n, coordinator=("127.0.0.1", port))
                try:
                    if rank == 0:
                        proc.send(np.arange(4, dtype=np.float64),
                                  dest=2, tag=7)
                        got = proc.recv(source=2, tag=8)
                        results["reply"] = got.tolist()
                    proc.barrier()
                    if rank == 1:
                        results["long"] = proc.recv(source=2, tag=9)
                finally:
                    proc.close()
            except BaseException as e:  # noqa: BLE001
                excs.append(e)

        threads = [threading.Thread(target=py_rank, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        cproc = subprocess.Popen(
            [str(binpath)], env=_env(2, n, port),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        out, err = cproc.communicate(timeout=60)
        for t in threads:
            t.join(30)
            assert not t.is_alive(), "python rank hung"
        if excs:
            raise excs[0]
        assert cproc.returncode == 0, f"C rank failed: {err}\n{out}"
        assert "interop rank 2/3 n=4 OK" in out
        assert results["reply"] == [0.0, 10.0, 20.0, 30.0]
        got = results["long"]
        assert int(np.asarray(got).reshape(-1)[0]) == 12345 + 2


@pytest.fixture(scope="module")
def subcomm_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "subcomm_c.c")


@pytest.fixture(scope="module")
def probescan_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "probescan_c.c")


class TestRound4Surface:
    """VERDICT round-3 item 3: the broadened C ABI — split + sub-comm
    allreduce, dup/free, Isend/Irecv/Test/Waitall overlap, Sendrecv,
    rooted collectives, derived datatypes, logical/bitwise ops."""

    @pytest.mark.parametrize("n", [1, 2, 4, 5])
    def test_subcomm_example(self, subcomm_bin, n):
        port = _free_port()
        procs = [
            subprocess.Popen([subcomm_bin], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=90)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"subcomm_c rank {r}/{n} OK" in out

    @pytest.mark.parametrize("n", [1, 3, 4])
    def test_probescan_example(self, probescan_bin, n):
        """Probe/Iprobe, Waitany/Testall, Scan/Exscan, ragged
        v-collectives, Reduce_scatter_block, user-defined ops,
        Error_string, Type_get_extent."""
        port = _free_port()
        procs = [
            subprocess.Popen([probescan_bin], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=90)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"probescan_c rank {r}/{n} OK" in out

    def test_isend_truly_pending_until_recv(self, shim, tmp_path):
        """An Irecv posted with no matching send must stay incomplete
        through MPI_Test until the peer sends — the request engine is
        real, not a rename of blocking recv."""
        src = tmp_path / "pending.c"
        src.write_text(r'''
#include <stdio.h>
#include <unistd.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (rank == 0) {
    MPI_Request rq;
    long v = -1;
    int flag = -1;
    MPI_Irecv(&v, 1, MPI_LONG, 1, 5, MPI_COMM_WORLD, &rq);
    MPI_Test(&rq, &flag, MPI_STATUS_IGNORE);
    if (flag != 0) { fprintf(stderr, "completed too early\n"); return 1; }
    /* unblock the peer's delayed send */
    MPI_Barrier(MPI_COMM_WORLD);
    MPI_Wait(&rq, MPI_STATUS_IGNORE);
    if (v != 777) { fprintf(stderr, "bad payload %ld\n", v); return 1; }
    printf("pending OK\n");
  } else {
    MPI_Barrier(MPI_COMM_WORLD);
    long v = 777;
    MPI_Send(&v, 1, MPI_LONG, 0, 5, MPI_COMM_WORLD);
    printf("pending OK\n");
  }
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "pending"
        _compile_c(shim, src, binpath)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, 2, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(2)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert "pending OK" in out


@pytest.fixture(scope="module")
def fileio_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "fileio_c.c")


class TestFileIO:
    """The MPI-IO C surface (byte views over POSIX at-offset IO):
    collective open/close, disjoint stripes, cross-rank verification,
    pointers, derived-type images, set_size, DELETE_ON_CLOSE."""

    @pytest.mark.parametrize("n", [1, 2, 4])
    def test_fileio_example(self, fileio_bin, n, tmp_path):
        port = _free_port()
        path = str(tmp_path / f"data_{n}.bin")
        procs = [
            subprocess.Popen([fileio_bin, path], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=90)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"fileio_c rank {r}/{n} OK" in out
        # the truncated data file remains; scratch must be gone
        assert os.path.getsize(path) == 32 * n
        assert not os.path.exists(path + ".scratch")


class TestGroups:
    def test_group_algebra(self, shim, tmp_path):
        """MPI_Comm_group + incl/excl/union/intersection/difference/
        translate_ranks/Comm_compare — the ompi/group rank algebra."""
        src = tmp_path / "groups.c"
        src.write_text(r'''
#include <stdio.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);  /* run with 4 */
  MPI_Group world, evens, odds, first3, inter, uni, diff;
  int gsz = -1, grk = -1;
  if (MPI_Comm_group(MPI_COMM_WORLD, &world) != MPI_SUCCESS) return 1;
  MPI_Group_size(world, &gsz);
  MPI_Group_rank(world, &grk);
  if (gsz != size || grk != rank) return 3;
  int er[2] = {0, 2}, orr[2] = {1, 3}, f3[3] = {0, 1, 2};
  MPI_Group_incl(world, 2, er, &evens);
  MPI_Group_incl(world, 2, orr, &odds);
  MPI_Group_incl(world, 3, f3, &first3);
  MPI_Group_rank(evens, &grk);
  if (rank == 2 && grk != 1) return 4;
  if (rank == 1 && grk != MPI_UNDEFINED) return 5;
  MPI_Group_intersection(evens, first3, &inter);  /* {0,2} */
  MPI_Group_size(inter, &gsz);
  if (gsz != 2) return 6;
  MPI_Group_union(evens, odds, &uni);  /* {0,2,1,3} */
  MPI_Group_size(uni, &gsz);
  if (gsz != 4) return 7;
  MPI_Group_difference(world, evens, &diff);  /* {1,3} */
  MPI_Group_size(diff, &gsz);
  if (gsz != 2) return 8;
  /* translate: evens rank 1 (world 2) -> world group rank 2 */
  int r1[1] = {1}, r2[1] = {-5};
  MPI_Group_translate_ranks(evens, 1, r1, world, r2);
  if (r2[0] != 2) return 9;
  /* excl of everything -> MPI_GROUP_EMPTY */
  int all4[4] = {0, 1, 2, 3};
  MPI_Group e;
  MPI_Group_excl(world, 4, all4, &e);
  if (e != MPI_GROUP_EMPTY) return 10;
  MPI_Group_size(e, &gsz);
  if (gsz != 0) return 11;
  /* comm compare: dup is CONGRUENT, split-self is UNEQUAL */
  MPI_Comm dup;
  int cmp = -1;
  MPI_Comm_dup(MPI_COMM_WORLD, &dup);
  MPI_Comm_compare(MPI_COMM_WORLD, dup, &cmp);
  if (cmp != MPI_CONGRUENT) return 12;
  MPI_Comm_compare(MPI_COMM_WORLD, MPI_COMM_WORLD, &cmp);
  if (cmp != MPI_IDENT) return 13;
  MPI_Comm_compare(MPI_COMM_WORLD, MPI_COMM_SELF, &cmp);
  if (cmp != (size == 1 ? MPI_CONGRUENT : MPI_UNEQUAL)) return 14;
  MPI_Comm_free(&dup);
  MPI_Group_free(&world);
  MPI_Group_free(&evens);
  MPI_Group_free(&e);
  if (e != MPI_GROUP_NULL) return 15;
  MPI_Barrier(MPI_COMM_WORLD);
  printf("groups rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "groups"
        _compile_c(shim, src, binpath)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, 4, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(4)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, f"rank {r} rc={p.returncode}: {err}"
            assert f"groups rank {r}/4 OK" in out


class TestRendezvousLargeMessages:
    """VERDICT round-4 Missing #2 / Next #2: any-size delivery to and
    from C ranks.  The shim now speaks the RTS/CTS rendezvous leg
    (pml_ob1_sendreq.c:768's guarantee): ≥4 MB payloads flow Python→C,
    C→Python, and C→C, over dedicated bulk connections."""

    NDOUBLES = 1 << 19  # 4 MiB of float64 — 4x the 1 MB eager limit

    def test_python_to_c_and_back_4mb(self, shim, tmp_path):
        src = tmp_path / "bigmsg.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
#define N (1 << 19)
int main(int argc, char **argv) {
  int rank, size, i, n;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  double *buf = malloc(N * sizeof(double));
  MPI_Status st;
  /* 4 MB from python rank 0: arrives via RTS/CTS (the shim answers) */
  MPI_Recv(buf, N, MPI_DOUBLE, 0, 7, MPI_COMM_WORLD, &st);
  MPI_Get_count(&st, MPI_DOUBLE, &n);
  if (n != N) { fprintf(stderr, "short recv %d\n", n); return 3; }
  for (i = 0; i < N; i++) {
    if (buf[i] != (double)(i % 1000)) { fprintf(stderr, "bad data at %d\n", i); return 4; }
    buf[i] += 1.0;
  }
  /* 4 MB back: the shim's sender-side rendezvous */
  MPI_Send(buf, N, MPI_DOUBLE, 0, 8, MPI_COMM_WORLD);
  MPI_Barrier(MPI_COMM_WORLD);
  printf("bigmsg rank %d/%d OK\n", rank, size);
  free(buf);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "bigmsg"
        _compile_c(shim, src, binpath)

        port = _free_port()
        n = 2  # rank 0 = python, rank 1 = C
        results = {}
        excs = []
        payload = np.arange(self.NDOUBLES, dtype=np.float64) % 1000

        def py_rank():
            try:
                proc = TcpProc(0, n, coordinator=("127.0.0.1", port))
                try:
                    proc.send(payload, dest=1, tag=7)
                    results["reply"] = proc.recv(source=1, tag=8)
                    proc.barrier()
                finally:
                    proc.close()
            except BaseException as e:  # noqa: BLE001
                excs.append(e)

        t = threading.Thread(target=py_rank)
        t.start()
        cproc = subprocess.Popen(
            [str(binpath)], env=_env(1, n, port),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        out, err = cproc.communicate(timeout=120)
        t.join(60)
        assert not t.is_alive(), "python rank hung"
        if excs:
            raise excs[0]
        assert cproc.returncode == 0, f"C rank failed: {err}\n{out}"
        assert "bigmsg rank 1/2 OK" in out
        got = np.asarray(results["reply"])
        assert got.shape == (self.NDOUBLES,)
        np.testing.assert_array_equal(got, payload + 1.0)

    def test_derived_types_cross_plane(self, shim, tmp_path):
        """Derived datatypes across the wire boundary: a C rank packs a
        strided vector (element-sealed, wire dtype <f8) and a mixed
        struct (byte-flattened, wire dtype |u1) to a Python rank, then
        receives Python doubles into its strided layout — the convertor
        contract (packed base elements on the wire) holds between the
        two engines."""
        src = tmp_path / "dtinterop.c"
        src.write_text(r'''
#include <stdio.h>
#include <string.h>
#include "zompi_mpi.h"
struct rec { double x; int id; };
int main(int argc, char **argv) {
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  int rank;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  /* strided doubles: every OTHER element of a 8-double buffer
   * (Type_vector stays ELEMENT-sealed: wire dtype <f8; the byte
   * constructors flatten to |u1 — the struct below covers that) */
  MPI_Datatype hv;
  if (MPI_Type_vector(4, 1, 2, MPI_DOUBLE, &hv) != MPI_SUCCESS)
    return 3;
  MPI_Type_commit(&hv);
  double buf[8];
  for (int i = 0; i < 8; i++) buf[i] = i * 1.5;
  /* -> python sees the packed elements [0, 3, 6, 9] */
  if (MPI_Send(buf, 1, hv, 0, 11, MPI_COMM_WORLD) != MPI_SUCCESS)
    return 4;
  /* mixed struct -> byte-flattened payload on the wire */
  struct rec r2[2];
  memset(r2, 0, sizeof r2);
  r2[0].x = 2.5; r2[0].id = 7;
  r2[1].x = -4.25; r2[1].id = 9;
  int bl[2] = {1, 1};
  MPI_Aint dp[2];
  MPI_Aint base, a;
  MPI_Get_address(&r2[0], &base);
  MPI_Get_address(&r2[0].x, &a); dp[0] = a - base;
  MPI_Get_address(&r2[0].id, &a); dp[1] = a - base;
  MPI_Datatype fields[2] = {MPI_DOUBLE, MPI_INT}, st_t, rec_t;
  MPI_Type_create_struct(2, bl, dp, fields, &st_t);
  MPI_Type_create_resized(st_t, 0, sizeof(struct rec), &rec_t);
  MPI_Type_commit(&rec_t);
  if (MPI_Send(r2, 2, rec_t, 0, 12, MPI_COMM_WORLD) != MPI_SUCCESS)
    return 5;
  /* python doubles land in the strided layout through the unpack */
  double landing[8];
  for (int i = 0; i < 8; i++) landing[i] = -1.0;
  MPI_Status st;
  if (MPI_Recv(landing, 1, hv, 0, 13, MPI_COMM_WORLD, &st) !=
      MPI_SUCCESS) return 6;
  for (int i = 0; i < 4; i++) {
    if (landing[2 * i] != 100.0 + i) return 7;   /* typemap slots */
    if (landing[2 * i + 1] != -1.0) return 8;    /* gaps untouched */
  }
  MPI_Type_free(&hv);
  MPI_Type_free(&st_t);
  MPI_Type_free(&rec_t);
  MPI_Barrier(MPI_COMM_WORLD);
  printf("dtinterop OK\n");
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "dtinterop"
        _compile_c(shim, src, binpath)
        port = _free_port()
        results = {}
        excs = []

        def py_rank():
            try:
                proc = TcpProc(0, 2, coordinator=("127.0.0.1", port))
                try:
                    results["hv"] = proc.recv(source=1, tag=11)
                    results["struct"] = proc.recv(source=1, tag=12)
                    proc.send(np.arange(4, dtype=np.float64) + 100.0,
                              dest=1, tag=13)
                    proc.barrier()
                finally:
                    proc.close()
            except BaseException as e:  # noqa: BLE001
                excs.append(e)

        t = threading.Thread(target=py_rank)
        t.start()
        cproc = subprocess.Popen(
            [str(binpath)], env=_env(1, 2, port),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        out, err = cproc.communicate(timeout=60)
        t.join(30)
        assert not t.is_alive(), "python rank hung"
        if excs:
            raise excs[0]
        assert cproc.returncode == 0, f"C rank failed: {err}\n{out}"
        # element-sealed vector: packed doubles, every other element
        np.testing.assert_array_equal(
            np.asarray(results["hv"]),
            np.array([0.0, 3.0, 6.0, 9.0]))
        # byte-flattened struct: packed (double, int) pairs as raw bytes
        raw = np.asarray(results["struct"])
        assert raw.dtype == np.uint8 and raw.size == 2 * 12
        rec = np.frombuffer(raw.tobytes(), dtype=[("x", "<f8"),
                                                  ("id", "<i4")])
        assert rec["x"].tolist() == [2.5, -4.25]
        assert rec["id"].tolist() == [7, 9]

    def test_c_to_c_4mb_exchange(self, shim, tmp_path):
        """Both C legs at once: every rank rendezvous-sends 4 MB to its
        right neighbor while answering its left neighbor's RTS."""
        src = tmp_path / "bigring.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
#define N (1 << 19)
int main(int argc, char **argv) {
  int rank, size, i, n;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  double *snd = malloc(N * sizeof(double));
  double *rcv = malloc(N * sizeof(double));
  for (i = 0; i < N; i++) snd[i] = rank * 1000.0 + (i % 97);
  MPI_Status st;
  MPI_Sendrecv(snd, N, MPI_DOUBLE, (rank + 1) % size, 5,
               rcv, N, MPI_DOUBLE, (rank + size - 1) % size, 5,
               MPI_COMM_WORLD, &st);
  MPI_Get_count(&st, MPI_DOUBLE, &n);
  if (n != N) { fprintf(stderr, "short recv %d\n", n); return 3; }
  int left = (rank + size - 1) % size;
  for (i = 0; i < N; i++)
    if (rcv[i] != left * 1000.0 + (i % 97)) { fprintf(stderr, "bad at %d\n", i); return 4; }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("bigring rank %d/%d OK\n", rank, size);
  free(snd); free(rcv);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "bigring"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 3
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"bigring rank {r}/{n} OK" in out

    def test_non_overtaking_rndv_then_eager_same_tag(self, shim, tmp_path):
        """MPI non-overtaking across the protocol switch: a 4 MB
        rendezvous send followed by a small eager send on the SAME
        (src, tag) must be received in that order — the placeholder
        holds the announced message's place in the matching stream even
        though its bulk data arrives later on a slower connection."""
        src = tmp_path / "order.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
#define N (1 << 19)
int main(int argc, char **argv) {
  int rank, size, n1, n2;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  double *big = malloc(N * sizeof(double));
  double small[2];
  MPI_Status s1, s2;
  /* post BOTH receives before any data: first must take the big one */
  MPI_Request r1, r2;
  MPI_Irecv(big, N, MPI_DOUBLE, 0, 5, MPI_COMM_WORLD, &r1);
  MPI_Irecv(small, 2, MPI_DOUBLE, 0, 5, MPI_COMM_WORLD, &r2);
  MPI_Barrier(MPI_COMM_WORLD);  /* release the python sender */
  MPI_Wait(&r1, &s1);
  MPI_Wait(&r2, &s2);
  MPI_Get_count(&s1, MPI_DOUBLE, &n1);
  MPI_Get_count(&s2, MPI_DOUBLE, &n2);
  if (n1 != N || n2 != 2) { fprintf(stderr, "order broke: n1=%d n2=%d\n", n1, n2); return 3; }
  if (big[7] != 7.0 || small[0] != -1.0) { fprintf(stderr, "payload swapped\n"); return 4; }
  /* unposted path: big + small arrive with NO recv posted; recv in order */
  MPI_Barrier(MPI_COMM_WORLD);
  MPI_Barrier(MPI_COMM_WORLD);  /* python sent both between barriers */
  MPI_Recv(big, N, MPI_DOUBLE, 0, 6, MPI_COMM_WORLD, &s1);
  MPI_Recv(small, 2, MPI_DOUBLE, 0, 6, MPI_COMM_WORLD, &s2);
  MPI_Get_count(&s1, MPI_DOUBLE, &n1);
  MPI_Get_count(&s2, MPI_DOUBLE, &n2);
  if (n1 != N || n2 != 2) { fprintf(stderr, "unexpected-queue order broke: n1=%d n2=%d\n", n1, n2); return 5; }
  if (big[9] != 9.0 || small[0] != -2.0) { fprintf(stderr, "payload swapped 2\n"); return 6; }
  printf("order rank %d/%d OK\n", rank, size);
  free(big);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "order"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 2
        excs = []
        big = np.arange(self.NDOUBLES, dtype=np.float64)

        def py_rank():
            try:
                proc = TcpProc(0, n, coordinator=("127.0.0.1", port))
                try:
                    proc.barrier()  # C posted both receives
                    proc.send(big, dest=1, tag=5)                  # rndv
                    proc.send(np.asarray([-1.0, -1.0]), dest=1, tag=5)  # eager
                    proc.barrier()
                    proc.send(big, dest=1, tag=6)                  # rndv
                    proc.send(np.asarray([-2.0, -2.0]), dest=1, tag=6)  # eager
                    proc.barrier()
                finally:
                    proc.close()
            except BaseException as e:  # noqa: BLE001
                excs.append(e)

        t = threading.Thread(target=py_rank)
        t.start()
        cproc = subprocess.Popen(
            [str(binpath)], env=_env(1, n, port),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        out, err = cproc.communicate(timeout=120)
        t.join(60)
        assert not t.is_alive(), "python rank hung"
        if excs:
            raise excs[0]
        assert cproc.returncode == 0, f"C rank failed: {err}\n{out}"
        assert "order rank 1/2 OK" in out

    def test_crossed_large_isends_no_deadlock(self, shim, tmp_path):
        """The MPI-guaranteed idiom that inline rendezvous would
        deadlock: both ranks Isend 4 MB to each other FIRST, then post
        receives, then Waitall.  The background rendezvous thread waits
        for the peer's claim while the main thread posts the receive
        that produces it."""
        src = tmp_path / "crossed.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
#define N (1 << 19)
int main(int argc, char **argv) {
  int rank, size, i, n;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  int peer = 1 - rank;
  double *snd = malloc(N * sizeof(double));
  double *rcv = malloc(N * sizeof(double));
  for (i = 0; i < N; i++) snd[i] = rank + i * 0.001;
  MPI_Request reqs[2];
  MPI_Status sts[2];
  MPI_Isend(snd, N, MPI_DOUBLE, peer, 3, MPI_COMM_WORLD, &reqs[0]);
  MPI_Irecv(rcv, N, MPI_DOUBLE, peer, 3, MPI_COMM_WORLD, &reqs[1]);
  if (MPI_Waitall(2, reqs, sts) != MPI_SUCCESS) return 3;
  MPI_Get_count(&sts[1], MPI_DOUBLE, &n);
  if (n != N) { fprintf(stderr, "short %d\n", n); return 4; }
  for (i = 0; i < N; i++)
    if (rcv[i] != peer + i * 0.001) { fprintf(stderr, "bad %d\n", i); return 5; }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("crossed rank %d/%d OK\n", rank, size);
  free(snd); free(rcv);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "crossed"
        _compile_c(shim, src, binpath)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, 2, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(2)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"crossed rank {r}/2 OK" in out

    def test_isend_large_then_eager_same_tag_ordered(self, shim, tmp_path):
        """The RTS must leave on the CALLING thread: MPI_Isend(4MB) then
        MPI_Send(small) on one (dest, tag) must match two posted
        receives in that order even though the bulk push happens on a
        background thread."""
        src = tmp_path / "iorder.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
#define N (1 << 19)
int main(int argc, char **argv) {
  int rank, size, i, n1, n2;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (rank == 0) {
    double *big = malloc(N * sizeof(double));
    for (i = 0; i < N; i++) big[i] = i * 0.5;
    double small[2] = {42.0, 43.0};
    MPI_Request sreq;
    MPI_Isend(big, N, MPI_DOUBLE, 1, 5, MPI_COMM_WORLD, &sreq);
    MPI_Send(small, 2, MPI_DOUBLE, 1, 5, MPI_COMM_WORLD);
    MPI_Wait(&sreq, MPI_STATUS_IGNORE);
    free(big);
  } else {
    double *big = malloc(N * sizeof(double));
    double small[2];
    MPI_Status s1, s2;
    MPI_Recv(big, N, MPI_DOUBLE, 0, 5, MPI_COMM_WORLD, &s1);
    MPI_Recv(small, 2, MPI_DOUBLE, 0, 5, MPI_COMM_WORLD, &s2);
    MPI_Get_count(&s1, MPI_DOUBLE, &n1);
    MPI_Get_count(&s2, MPI_DOUBLE, &n2);
    if (n1 != N || n2 != 2) { fprintf(stderr, "overtook: n1=%d n2=%d\n", n1, n2); return 3; }
    if (big[10] != 5.0 || small[0] != 42.0) { fprintf(stderr, "swapped\n"); return 4; }
    free(big);
  }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("iorder rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "iorder"
        _compile_c(shim, src, binpath)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, 2, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(2)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"iorder rank {r}/2 OK" in out


@pytest.fixture(scope="module")
def halo_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "halo_c.c")


class TestTier3Surface:
    """VERDICT round-4 Next #3: RMA windows, nonblocking collectives,
    Cartesian topology, Pack/Unpack — the acceptance is a 2-D halo
    exchange on a Cart grid via RMA fences with an overlapped
    Iallreduce, across real processes."""

    @pytest.mark.parametrize("n", [4, 6, 9])
    def test_halo_example(self, halo_bin, n):
        port = _free_port()
        procs = [
            subprocess.Popen([halo_bin], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"halo_c rank {r}/{n} OK" in out

    def test_halo_via_zmpicc_and_zmpirun(self, tmp_path):
        """The whole C toolchain loop for the tier-3 surface: zmpicc
        compiles examples/halo_c.c with no manual flags and zmpirun
        launches it across 4 ranks."""
        binary = str(tmp_path / "halo")
        res = subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.zmpicc",
             os.path.join(REPO, "examples", "halo_c.c"), "-o", binary],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert res.returncode == 0, res.stderr
        run = subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.mpirun",
             "-n", "4", binary],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert run.returncode == 0, run.stderr
        for r in range(4):
            assert f"halo_c rank {r}/4 OK" in run.stdout

    def test_icoll_family_and_graph_topology(self, shim, tmp_path):
        """Multiple nonblocking collectives in flight in program order
        (their tag slots are reserved at call time), plus the graph
        topology surface and Topo_test."""
        src = tmp_path / "icoll.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size, i;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  /* four nonblocking collectives started back-to-back, waited in
     reverse order: slot reservation keeps their wires disjoint */
  long v = rank + 1, sum = 0, scan = 0;
  long *ga = malloc(size * sizeof(long));
  long *aa = malloc(size * sizeof(long));
  MPI_Request rq[4];
  MPI_Iallreduce(&v, &sum, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD, &rq[0]);
  MPI_Igather(&v, 1, MPI_LONG, ga, 1, MPI_LONG, 0, MPI_COMM_WORLD, &rq[1]);
  MPI_Iallgather(&v, 1, MPI_LONG, aa, 1, MPI_LONG, MPI_COMM_WORLD, &rq[2]);
  MPI_Iscan(&v, &scan, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD, &rq[3]);
  for (i = 3; i >= 0; i--)
    if (MPI_Wait(&rq[i], MPI_STATUS_IGNORE) != MPI_SUCCESS) return 3;
  long want = (long)size * (size + 1) / 2;
  if (sum != want) { fprintf(stderr, "sum %ld != %ld\n", sum, want); return 4; }
  if (scan != (long)(rank + 1) * (rank + 2) / 2) return 5;
  for (i = 0; i < size; i++)
    if (aa[i] != i + 1) return 6;
  if (rank == 0)
    for (i = 0; i < size; i++)
      if (ga[i] != i + 1) return 7;
  /* Ireduce_scatter_block reserves TWO slots; follow with a blocking
     bcast to prove the sequence stays aligned */
  long *contrib = malloc(size * sizeof(long));
  for (i = 0; i < size; i++) contrib[i] = rank + i;
  long mine = -1;
  MPI_Request rsb;
  MPI_Ireduce_scatter_block(contrib, &mine, 1, MPI_LONG, MPI_SUM,
                            MPI_COMM_WORLD, &rsb);
  long token = rank == 0 ? 77 : 0;
  MPI_Bcast(&token, 1, MPI_LONG, 0, MPI_COMM_WORLD);
  if (token != 77) return 8;
  MPI_Wait(&rsb, MPI_STATUS_IGNORE);
  /* sum over ranks of (rank + me) = size*me + size*(size-1)/2 */
  if (mine != (long)size * rank + (long)size * (size - 1) / 2) return 9;
  /* graph topology: ring graph, every node two neighbors */
  int *index = malloc(size * sizeof(int));
  int *edges = malloc(2 * size * sizeof(int));
  for (i = 0; i < size; i++) {
    index[i] = 2 * (i + 1);
    edges[2 * i] = (i + size - 1) % size;
    edges[2 * i + 1] = (i + 1) % size;
  }
  MPI_Comm gcomm;
  if (MPI_Graph_create(MPI_COMM_WORLD, size, index, edges, 0, &gcomm)
      != MPI_SUCCESS) return 10;
  int topo;
  MPI_Topo_test(gcomm, &topo);
  if (topo != MPI_GRAPH) return 11;
  int nn, nbrs[2];
  MPI_Graph_neighbors_count(gcomm, rank, &nn);
  if (nn != 2) return 12;
  MPI_Graph_neighbors(gcomm, rank, 2, nbrs);
  if (nbrs[0] != (rank + size - 1) % size || nbrs[1] != (rank + 1) % size)
    return 13;
  MPI_Topo_test(MPI_COMM_WORLD, &topo);
  if (topo != MPI_UNDEFINED) return 14;
  MPI_Barrier(MPI_COMM_WORLD);
  printf("icoll rank %d/%d OK\n", rank, size);
  free(ga); free(aa); free(contrib); free(index); free(edges);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "icoll"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 5
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"icoll rank {r}/{n} OK" in out


@pytest.fixture(scope="module")
def oshmem_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "oshmem_c.c")


@pytest.fixture(scope="module")
def spawn_example_bin(shim, tmp_path_factory):
    return _compile_example(shim, tmp_path_factory, "spawn_c.c")


class TestOshmemCSurface:
    """The C OpenSHMEM surface (zompi_shmem.h over the window engine —
    the reference's oshmem/shmem/c bindings): symmetric heap, ring put,
    all-PE fetch-add, wait_until, reductions, fcollect, locks,
    broadcast, across real processes."""

    @pytest.mark.parametrize("n", [2, 4, 5])
    def test_oshmem_example(self, oshmem_bin, n):
        port = _free_port()
        procs = [
            subprocess.Popen([oshmem_bin], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"oshmem_c PE {r}/{n} OK" in out

    def test_mixed_mpi_and_shmem_in_one_process(self, shim, tmp_path):
        """A process may be an MPI rank and a PE at once (the reference
        links ompi + oshmem into one runtime): shmem_init on top of an
        existing MPI_Init, MPI collectives + shmem RMA interleaved."""
        src = tmp_path / "mixed.c"
        src.write_text(r'''
#include <stdio.h>
#include "zompi_mpi.h"
#include "zompi_shmem.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (shmem_init() != 0) return 3;  /* rides the existing MPI runtime */
  if (shmem_my_pe() != rank || shmem_n_pes() != size) return 4;
  long *cell = shmem_malloc(sizeof(long));
  *cell = 0;
  shmem_barrier_all();
  shmem_long_atomic_add(cell, rank + 1, 0);
  long sum = 0, me = rank;
  MPI_Allreduce(&me, &sum, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD);
  shmem_barrier_all();
  if (sum != (long)size * (size - 1) / 2) return 5;
  if (rank == 0 && *cell != (long)size * (size + 1) / 2) return 6;
  shmem_finalize();  /* does NOT finalize MPI (we initialized it) */
  int fin = 0;
  MPI_Initialized(&fin);
  if (!fin) return 7;
  MPI_Barrier(MPI_COMM_WORLD);
  printf("mixed rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "mixed"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 3
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"mixed rank {r}/{n} OK" in out

    def test_passive_target_lock_counter(self, shim, tmp_path):
        """Passive-target RMA (win_lock.c): every rank lock/get/put/
        unlocks an exclusive counter on rank 0's Win_allocate'd window
        WITHOUT rank 0 participating in the epochs — the drain is the
        arbiter. Plus Comm_create from a reversed group."""
        src = tmp_path / "passive.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size, i;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  long *base = 0;
  MPI_Win win;
  if (MPI_Win_allocate(sizeof(long), sizeof(long), MPI_INFO_NULL,
                       MPI_COMM_WORLD, &base, &win) != MPI_SUCCESS)
    return 3;
  *base = 0;
  MPI_Barrier(MPI_COMM_WORLD);
  /* lock-protected read-modify-write: NOT atomics — exclusive lock is
     the serialization; 4 increments per rank */
  for (i = 0; i < 4; i++) {
    long cur = -1, next;
    MPI_Win_lock(MPI_LOCK_EXCLUSIVE, 0, 0, win);
    MPI_Get(&cur, 1, MPI_LONG, 0, 0, 1, MPI_LONG, win);
    next = cur + 1;
    MPI_Put(&next, 1, MPI_LONG, 0, 0, 1, MPI_LONG, win);
    MPI_Win_unlock(0, win);
  }
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0 && *base != 4L * size) {
    fprintf(stderr, "counter %ld != %ld\n", *base, 4L * size);
    return 4;
  }
  /* shared locks may coexist: everyone shared-locks rank 0, reads */
  MPI_Win_lock(MPI_LOCK_SHARED, 0, 0, win);
  long seen = -1;
  MPI_Get(&seen, 1, MPI_LONG, 0, 0, 1, MPI_LONG, win);
  MPI_Win_unlock(0, win);
  if (seen != 4L * size) return 5;
  MPI_Win_free(&win);
  /* Comm_create from the REVERSED group: rank order flips */
  MPI_Group world_grp, rev_grp;
  MPI_Comm_group(MPI_COMM_WORLD, &world_grp);
  int *order = malloc(size * sizeof(int));
  for (i = 0; i < size; i++) order[i] = size - 1 - i;
  MPI_Group_incl(world_grp, size, order, &rev_grp);
  MPI_Comm rev;
  if (MPI_Comm_create(MPI_COMM_WORLD, rev_grp, &rev) != MPI_SUCCESS)
    return 6;
  int rrank;
  MPI_Comm_rank(rev, &rrank);
  if (rrank != size - 1 - rank) return 7;
  long probe = rrank, rsum = 0;
  MPI_Allreduce(&probe, &rsum, 1, MPI_LONG, MPI_SUM, rev);
  if (rsum != (long)size * (size - 1) / 2) return 8;
  MPI_Barrier(MPI_COMM_WORLD);
  printf("passive rank %d/%d OK\n", rank, size);
  free(order);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "passive"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 4
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"passive rank {r}/{n} OK" in out

    def test_asymmetric_window_amo(self, shim, tmp_path):
        """Windows are per-rank sized: only rank 0 exposes memory (the
        others pass size 0); remote AMOs to rank 0 must succeed — the
        TARGET validates displacements, not the origin's local size."""
        src = tmp_path / "asym.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
extern int zompi_win_amo(MPI_Win, int, long long, const char *,
                         MPI_Datatype, const void *, int, void *);
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  long cell = 0;
  MPI_Win win;
  /* only rank 0 exposes its cell */
  if (MPI_Win_create(rank == 0 ? (void *)&cell : NULL,
                     rank == 0 ? (MPI_Aint)sizeof(long) : 0,
                     sizeof(long), MPI_INFO_NULL, MPI_COMM_WORLD, &win)
      != MPI_SUCCESS) return 3;
  MPI_Win_fence(0, win);
  long one = 1, old = -1;
  if (zompi_win_amo(win, 0, 0, "add", MPI_LONG, &one, 1, &old)
      != MPI_SUCCESS) return 4;  /* origin size 0 must not matter */
  if (old < 0 || old >= size) return 5;
  MPI_Win_fence(0, win);
  if (rank == 0 && cell != size) { fprintf(stderr, "cell %ld\n", cell); return 6; }
  MPI_Win_free(&win);
  MPI_Barrier(MPI_COMM_WORLD);
  printf("asym rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "asym"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 4
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"asym rank {r}/{n} OK" in out

    @pytest.mark.parametrize("n", [2, 4])
    def test_fetch_rma_and_neighbor_colls(self, shim, tmp_path, n):
        """MPI_Fetch_and_op (SUM/MAX/REPLACE/NO_OP), Compare_and_swap,
        and neighbor collectives on a periodic 1-D cart ring — n=2 is
        the degenerate ring where the minus and plus neighbor are the
        SAME process, exercising the complementary-slot tag pairing."""
        src = tmp_path / "fneigh.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size, i;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  /* ---- fetch-RMA on rank 0's window ---- */
  long *base = 0;
  MPI_Win win;
  MPI_Win_allocate(2 * sizeof(long), sizeof(long), MPI_INFO_NULL,
                   MPI_COMM_WORLD, &base, &win);
  base[0] = 0; base[1] = 5;
  MPI_Barrier(MPI_COMM_WORLD);
  long mine = rank + 1, old = -1;
  MPI_Fetch_and_op(&mine, &old, MPI_LONG, 0, 0, MPI_SUM, win);
  if (old < 0) return 3;
  MPI_Fetch_and_op(&mine, &old, MPI_LONG, 0, 1, MPI_MAX, win);
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) {
    if (base[0] != (long)size * (size + 1) / 2) return 4;
    if (base[1] != (size > 5 ? size : 5)) return 5;  /* max(5, max rank+1) */
  }
  MPI_Barrier(MPI_COMM_WORLD);
  /* NO_OP = atomic read; REPLACE = swap */
  long seen = -1;
  MPI_Fetch_and_op(NULL, &seen, MPI_LONG, 0, 0, MPI_NO_OP, win);
  if (seen != (long)size * (size + 1) / 2) return 6;
  MPI_Barrier(MPI_COMM_WORLD);  /* all reads done before the REPLACE */
  if (rank == 0) {
    long nine = 9;
    MPI_Fetch_and_op(&nine, &old, MPI_LONG, 0, 0, MPI_REPLACE, win);
    if (old != (long)size * (size + 1) / 2 || base[0] != 9) return 7;
    /* CAS: succeed then fail */
    long cmp = 9, val = 11, res = -1;
    MPI_Compare_and_swap(&val, &cmp, &res, MPI_LONG, 0, 0, win);
    if (res != 9 || base[0] != 11) return 8;
    MPI_Compare_and_swap(&val, &cmp, &res, MPI_LONG, 0, 0, win);
    if (res != 11 || base[0] != 11) return 9;
    /* MPI_Accumulate with MPI_REPLACE = atomic put (MPI-3.1 11.3) */
    long forty = 40;
    MPI_Accumulate(&forty, 1, MPI_LONG, 0, 0, 1, MPI_LONG, MPI_REPLACE,
                   win);
    MPI_Win_fence(0, win);
    if (base[0] != 40) return 12;
  } else {
    MPI_Win_fence(0, win);
  }
  /* PROC_NULL targets are no-ops, never errors */
  long dummy = 1, dres = -1;
  if (MPI_Fetch_and_op(&dummy, &dres, MPI_LONG, MPI_PROC_NULL, 0,
                       MPI_SUM, win) != MPI_SUCCESS) return 13;
  /* multi-element Get_accumulate: atomically fetch BOTH cells while
     adding {5,5}; then a NO_OP fetch of the pair */
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) { base[0] = 3; base[1] = 4; }
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 1 || size == 1) {
    long add2[2] = {5, 5}, got2[2] = {-1, -1};
    if (MPI_Get_accumulate(add2, 2, MPI_LONG, got2, 2, MPI_LONG, 0, 0,
                           2, MPI_LONG, MPI_SUM, win) != MPI_SUCCESS)
      return 17;
    if (got2[0] != 3 || got2[1] != 4) return 18;
    long seen2[2] = {-1, -1};
    if (MPI_Get_accumulate(NULL, 0, MPI_LONG, seen2, 2, MPI_LONG, 0, 0,
                           2, MPI_LONG, MPI_NO_OP, win) != MPI_SUCCESS)
      return 19;
    if (seen2[0] != 8 || seen2[1] != 9) return 20;
  }
  MPI_Barrier(MPI_COMM_WORLD);
  MPI_Win_free(&win);
  /* ---- neighbor collectives on a periodic ring ---- */
  int dims[1] = {size}, periods[1] = {1};
  MPI_Comm ring;
  MPI_Cart_create(MPI_COMM_WORLD, 1, dims, periods, 0, &ring);
  long sval = 100 + rank, ngat[2] = {-1, -1};
  MPI_Neighbor_allgather(&sval, 1, MPI_LONG, ngat, 1, MPI_LONG, ring);
  int left = (rank + size - 1) % size, right = (rank + 1) % size;
  if (ngat[0] != 100 + left || ngat[1] != 100 + right) {
    fprintf(stderr, "rank %d allgather [%ld,%ld]\n", rank, ngat[0], ngat[1]);
    return 10;
  }
  long sblk[2] = {1000 + rank * 10, 1000 + rank * 10 + 1};  /* to left, to right */
  long rblk[2] = {-1, -1};
  MPI_Neighbor_alltoall(sblk, 1, MPI_LONG, rblk, 1, MPI_LONG, ring);
  /* my left block gets left neighbor's TO-RIGHT block; right gets
     right neighbor's TO-LEFT block */
  if (rblk[0] != 1000 + left * 10 + 1 || rblk[1] != 1000 + right * 10) {
    fprintf(stderr, "rank %d alltoall [%ld,%ld]\n", rank, rblk[0], rblk[1]);
    return 11;
  }
  /* distributed graph, adjacent form: a DIRECTED ring — send right
     only, receive from left only (asymmetric in/out lists) */
  {
    int src1 = left, dst1 = right;
    MPI_Comm dg;
    if (MPI_Dist_graph_create_adjacent(MPI_COMM_WORLD, 1, &src1,
                                       MPI_UNWEIGHTED, 1, &dst1,
                                       MPI_UNWEIGHTED, MPI_INFO_NULL, 0,
                                       &dg) != MPI_SUCCESS) return 21;
    int topo, ind, outd, wtd;
    MPI_Topo_test(dg, &topo);
    if (topo != MPI_DIST_GRAPH) return 22;
    MPI_Dist_graph_neighbors_count(dg, &ind, &outd, &wtd);
    if (ind != 1 || outd != 1 || wtd != 0) return 23;
    int gs = -1, gd = -1;
    MPI_Dist_graph_neighbors(dg, 1, &gs, NULL, 1, &gd, NULL);
    if (gs != left || gd != right) return 24;
    long dv = 500 + rank, dres = -1;
    MPI_Neighbor_allgather(&dv, 1, MPI_LONG, &dres, 1, MPI_LONG, dg);
    if (dres != 500 + left) {
      fprintf(stderr, "rank %d dist ring got %ld\n", rank, dres);
      return 25;
    }
  }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("fneigh rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "fneigh"
        _compile_c(shim, src, binpath)
        port = _free_port()
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"fneigh rank {r}/{n} OK" in out

    def test_attrs_and_indexed_types(self, shim, tmp_path):
        """Attribute caching (keyval copy/delete through dup/free) and
        MPI_Type_indexed round-trip including a declaration-order
        (non-ascending) typemap."""
        src = tmp_path / "attridx.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
static int copies = 0, deletes = 0;
static int copy_fn(MPI_Comm c, int k, void *es, void *in, void *out, int *flag) {
  copies++;
  *(void **)out = (char *)in + 1;  /* transformed copy */
  *flag = 1;
  return MPI_SUCCESS;
}
static int del_fn(MPI_Comm c, int k, void *val, void *es) {
  deletes++;
  return MPI_SUCCESS;
}
int main(int argc, char **argv) {
  int rank, size, i;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  /* ---- attributes ---- */
  int kv;
  MPI_Comm_create_keyval(copy_fn, del_fn, &kv, NULL);
  MPI_Comm_set_attr(MPI_COMM_WORLD, kv, (void *)1000);
  MPI_Comm dup;
  MPI_Comm_dup(MPI_COMM_WORLD, &dup);
  void *got = NULL;
  int flag = 0;
  MPI_Comm_get_attr(dup, kv, &got, &flag);
  if (!flag || (long)got != 1001) return 3;  /* transformed */
  if (copies != 1) return 4;
  MPI_Comm_free(&dup);
  if (deletes != 1) return 5;  /* dup's attr deleted with it */
  MPI_Comm_delete_attr(MPI_COMM_WORLD, kv);
  if (deletes != 2) return 6;
  MPI_Comm_get_attr(MPI_COMM_WORLD, kv, &got, &flag);
  if (flag) return 7;
  /* ---- indexed datatype: pick columns 5,1,3 of an 8-vector ---- */
  double srcv[8], dstv[8];
  for (i = 0; i < 8; i++) { srcv[i] = i; dstv[i] = -1; }
  int lens[3] = {1, 1, 1}, disps[3] = {5, 1, 3};
  MPI_Datatype idx;
  MPI_Type_indexed(3, lens, disps, MPI_DOUBLE, &idx);
  MPI_Type_commit(&idx);
  int tsize;
  MPI_Type_size(idx, &tsize);
  if (tsize != 3 * (int)sizeof(double)) return 8;
  /* MPI-3.1 4.1.6: lb = min disp = 1 elem, extent = ub - lb = 5 elems */
  long lb = -1, ext = -1;
  MPI_Type_get_extent(idx, &lb, &ext);
  if (lb != 1 * (long)sizeof(double) || ext != 5 * (long)sizeof(double))
    return 14;
  /* count=2 concatenation strides by the extent: item 1's typemap is
     {5,1,3} + 5 = {10,6,8}; buffer must span lb + 2*extent = 11 */
  double two[12], back[12];
  for (i = 0; i < 12; i++) { two[i] = 100 + i; back[i] = -1; }
  int pos = 0;
  double packed2[6];
  MPI_Pack(two, 2, idx, packed2, (int)sizeof packed2, &pos, MPI_COMM_WORLD);
  if (packed2[0] != 105 || packed2[1] != 101 || packed2[2] != 103 ||
      packed2[3] != 110 || packed2[4] != 106 || packed2[5] != 108)
    return 15;
  pos = 0;
  MPI_Unpack(packed2, (int)sizeof packed2, &pos, back, 2, idx,
             MPI_COMM_WORLD);
  if (back[5] != 105 || back[1] != 101 || back[3] != 103 ||
      back[10] != 110 || back[6] != 106 || back[8] != 108) return 16;
  if (size >= 2) {
    if (rank == 0) {
      /* declaration order on the wire: 5.0, 1.0, 3.0 */
      MPI_Send(srcv, 1, idx, 1, 4, MPI_COMM_WORLD);
    } else if (rank == 1) {
      double flat[3] = {-1, -1, -1};
      MPI_Recv(flat, 3, MPI_DOUBLE, 0, 4, MPI_COMM_WORLD,
               MPI_STATUS_IGNORE);
      if (flat[0] != 5 || flat[1] != 1 || flat[2] != 3) return 9;
      /* and scatter back through the same typemap (self loopback) */
      MPI_Sendrecv(flat, 3, MPI_DOUBLE, 0, 5, dstv, 1, idx, 0, 5,
                   MPI_COMM_SELF, MPI_STATUS_IGNORE);
      if (dstv[5] != 5 || dstv[1] != 1 || dstv[3] != 3) return 10;
    }
  }
  /* indexed_block convenience form */
  MPI_Datatype blk;
  int bd[2] = {6, 0};
  MPI_Type_create_indexed_block(2, 2, bd, MPI_DOUBLE, &blk);
  MPI_Type_size(blk, &tsize);
  if (tsize != 4 * (int)sizeof(double)) return 11;
  MPI_Type_free(&blk);
  MPI_Type_free(&idx);
  MPI_Barrier(MPI_COMM_WORLD);
  printf("attridx rank %d/%d OK\n", rank, size);
  /* the finalize-hook idiom: a WORLD attribute's delete callback must
     fire inside MPI_Finalize (MPI-3.1 8.7.1) */
  MPI_Comm_set_attr(MPI_COMM_WORLD, kv, (void *)7777);
  int deletes_before = deletes;
  MPI_Finalize();
  if (deletes != deletes_before + 1) return 17;
  return 0;
}
''')
        binpath = tmp_path / "attridx"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 2
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"attridx rank {r}/{n} OK" in out

    def test_persistent_requests(self, shim, tmp_path):
        """Persistent requests (send_init.c family): a frozen halo
        pattern re-Started 5 times; handles survive completion, Wait
        deactivates, Request_free destroys."""
        src = tmp_path / "persist.c"
        src.write_text(r'''
#include <stdio.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size, it;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  int right = (rank + 1) % size, left = (rank + size - 1) % size;
  long sbuf, rbuf;
  MPI_Request reqs[2];
  /* frozen argument sets: ring shift of a mutating buffer */
  MPI_Send_init(&sbuf, 1, MPI_LONG, right, 3, MPI_COMM_WORLD, &reqs[0]);
  MPI_Recv_init(&rbuf, 1, MPI_LONG, left, 3, MPI_COMM_WORLD, &reqs[1]);
  for (it = 0; it < 5; it++) {
    sbuf = rank * 100 + it;
    rbuf = -1;
    MPI_Startall(2, reqs);
    MPI_Waitall(2, reqs, MPI_STATUSES_IGNORE);
    if (rbuf != left * 100 + it) {
      fprintf(stderr, "rank %d iter %d: rbuf=%ld\n", rank, it, rbuf);
      return 3;
    }
    /* handles must still be valid (not nulled by Wait) */
    if (reqs[0] == MPI_REQUEST_NULL || reqs[1] == MPI_REQUEST_NULL)
      return 4;
  }
  /* waiting an INACTIVE persistent request returns immediately */
  if (MPI_Wait(&reqs[0], MPI_STATUS_IGNORE) != MPI_SUCCESS) return 5;
  /* double-Start without completion is an error */
  MPI_Start(&reqs[1]);
  if (MPI_Start(&reqs[1]) == MPI_SUCCESS) return 6;
  MPI_Send(&sbuf, 1, MPI_LONG, right, 3, MPI_COMM_WORLD); /* match it */
  MPI_Wait(&reqs[1], MPI_STATUS_IGNORE);
  if (MPI_Request_free(&reqs[0]) != MPI_SUCCESS) return 7;
  if (MPI_Request_free(&reqs[1]) != MPI_SUCCESS) return 8;
  if (reqs[0] != MPI_REQUEST_NULL) return 9;
  MPI_Barrier(MPI_COMM_WORLD);
  printf("persist rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "persist"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 3
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"persist rank {r}/{n} OK" in out

    def test_pscw_epochs(self, shim, tmp_path):
        """PSCW generalized active target (win_post.c family): even
        ranks access their odd right-neighbor's window in a
        start/complete epoch the target brackets with post/wait — no
        global fence involved."""
        src = tmp_path / "pscw.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (size % 2) { /* pairs required */ MPI_Finalize(); return 0; }
  long *base = 0;
  MPI_Win win;
  MPI_Win_allocate(4 * sizeof(long), sizeof(long), MPI_INFO_NULL,
                   MPI_COMM_WORLD, &base, &win);
  for (int i = 0; i < 4; i++) base[i] = -1;
  MPI_Barrier(MPI_COMM_WORLD);
  MPI_Group world_grp;
  MPI_Comm_group(MPI_COMM_WORLD, &world_grp);
  if (rank % 2 == 0) {
    /* origin: access epoch toward the odd partner */
    int partner = rank + 1;
    MPI_Group tgt;
    MPI_Group_incl(world_grp, 1, &partner, &tgt);
    MPI_Win_start(tgt, 0, win);
    long vals[4];
    for (int i = 0; i < 4; i++) vals[i] = rank * 100 + i;
    /* target addressing uses the window comm's ranks */
    MPI_Put(vals, 4, MPI_LONG, partner, 0, 4, MPI_LONG, win);
    MPI_Win_complete(win);
  } else {
    /* target: exposure epoch to the even partner */
    int partner = rank - 1;
    MPI_Group org;
    MPI_Group_incl(world_grp, 1, &partner, &org);
    MPI_Win_post(org, 0, win);
    MPI_Win_wait(win);
    for (int i = 0; i < 4; i++)
      if (base[i] != (rank - 1) * 100 + i) {
        fprintf(stderr, "rank %d: base[%d]=%ld\n", rank, i, base[i]);
        return 3;
      }
  }
  MPI_Win_free(&win);
  MPI_Barrier(MPI_COMM_WORLD);
  printf("pscw rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "pscw"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 4
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"pscw rank {r}/{n} OK" in out

    def test_ssend_completes_at_match(self, shim, tmp_path):
        """MPI_Ssend (forced rendezvous): a SMALL synchronous send must
        not complete until the receiver matches — measured against a
        deliberately late receive; Testany polls a pending then a
        completed request."""
        src = tmp_path / "ssend.c"
        src.write_text(r'''
#include <stdio.h>
#include <unistd.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  long v = 77;
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) {
    /* Bsend must NOT wait for the late receiver (buffered contract) */
    static char bbuf[4096];
    long bv = 55;
    MPI_Buffer_attach(bbuf, sizeof bbuf);
    double b0 = MPI_Wtime();
    MPI_Bsend(&bv, 1, MPI_LONG, 1, 7, MPI_COMM_WORLD);
    if (MPI_Wtime() - b0 > 0.2) {
      fprintf(stderr, "Bsend blocked on the receiver\n");
      return 6;
    }
    void *db; int ds;
    MPI_Buffer_detach(&db, &ds);
    if (db != (void *)bbuf || ds != (int)sizeof bbuf) return 7;
    /* Issend: returns immediately, request pends until the match */
    long iv = 88;
    MPI_Request isr;
    double i0 = MPI_Wtime();
    MPI_Issend(&iv, 1, MPI_LONG, 1, 9, MPI_COMM_WORLD, &isr);
    if (MPI_Wtime() - i0 > 0.2) return 9;  /* must not block */
    int iflag = -1;
    MPI_Test(&isr, &iflag, MPI_STATUS_IGNORE);
    if (iflag) return 10;  /* receiver not there yet */
    double t0 = MPI_Wtime();
    MPI_Ssend(&v, 1, MPI_LONG, 1, 6, MPI_COMM_WORLD);
    double dt = MPI_Wtime() - t0;
    if (dt < 0.25) {  /* receiver posts after 400ms */
      fprintf(stderr, "Ssend returned in %.3fs before the match\n", dt);
      return 3;
    }
    MPI_Wait(&isr, MPI_STATUS_IGNORE);  /* its receiver matched too */
  } else if (rank == 1) {
    usleep(400000);
    long bgot = 0;
    MPI_Recv(&bgot, 1, MPI_LONG, 0, 7, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
    if (bgot != 55) return 8;
    long igot = -1;
    MPI_Recv(&igot, 1, MPI_LONG, 0, 9, MPI_COMM_WORLD,
             MPI_STATUS_IGNORE);
    if (igot != 88) return 11;
    long got = 0;
    /* Testany on a pending request first */
    MPI_Request rq;
    MPI_Irecv(&got, 1, MPI_LONG, 0, 6, MPI_COMM_WORLD, &rq);
    int idx = -2, flag = -1, spins = 0;
    do {
      if (MPI_Testany(1, &rq, &idx, &flag, MPI_STATUS_IGNORE)
          != MPI_SUCCESS) return 4;
      spins++;
    } while (!flag && spins < 4000000);
    if (!flag || idx != 0 || got != 77) return 5;
  }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("ssend rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "ssend"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 2
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"ssend rank {r}/{n} OK" in out

    def test_alltoallv_and_reduce_scatter(self, shim, tmp_path):
        """Ragged MPI_Alltoallv (rank r sends r+1 items to each peer)
        and MPI_Reduce_scatter with per-rank counts."""
        src = tmp_path / "ragged.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size, r, i;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  /* alltoallv: rank r sends (rank+1) longs to each peer, value
     rank*1000 + dest */
  int *scnt = malloc(size * sizeof(int)), *sdis = malloc(size * sizeof(int));
  int *rcnt = malloc(size * sizeof(int)), *rdis = malloc(size * sizeof(int));
  int stot = 0, rtot = 0;
  for (r = 0; r < size; r++) {
    scnt[r] = rank + 1; sdis[r] = stot; stot += scnt[r];
    rcnt[r] = r + 1;    rdis[r] = rtot; rtot += rcnt[r];
  }
  long *sb = malloc(stot * sizeof(long)), *rb = malloc(rtot * sizeof(long));
  for (r = 0; r < size; r++)
    for (i = 0; i < scnt[r]; i++) sb[sdis[r] + i] = rank * 1000 + r;
  for (i = 0; i < rtot; i++) rb[i] = -1;
  if (MPI_Alltoallv(sb, scnt, sdis, MPI_LONG, rb, rcnt, rdis, MPI_LONG,
                    MPI_COMM_WORLD) != MPI_SUCCESS) return 3;
  for (r = 0; r < size; r++)
    for (i = 0; i < rcnt[r]; i++)
      if (rb[rdis[r] + i] != r * 1000 + rank) {
        fprintf(stderr, "rank %d: from %d item %d = %ld\n", rank, r, i,
                rb[rdis[r] + i]);
        return 4;
      }
  /* reduce_scatter: ragged slices, slice r has r+1 elements */
  int total = size * (size + 1) / 2;
  long *contrib = malloc(total * sizeof(long));
  for (i = 0; i < total; i++) contrib[i] = rank + i;
  long *mine = malloc((rank + 1) * sizeof(long));
  int *counts = malloc(size * sizeof(int));
  for (r = 0; r < size; r++) counts[r] = r + 1;
  if (MPI_Reduce_scatter(contrib, mine, counts, MPI_LONG, MPI_SUM,
                         MPI_COMM_WORLD) != MPI_SUCCESS) return 5;
  /* sum over ranks of (rank + idx) = size*idx + size*(size-1)/2 */
  int base = rank * (rank + 1) / 2;
  for (i = 0; i < rank + 1; i++) {
    long want = (long)size * (base + i) + (long)size * (size - 1) / 2;
    if (mine[i] != want) {
      fprintf(stderr, "rank %d: slice[%d]=%ld want %ld\n", rank, i,
              mine[i], want);
      return 6;
    }
  }
  /* nonblocking forms of both, overlapped then waited */
  for (i = 0; i < rtot; i++) rb[i] = -1;
  MPI_Request nv[2];
  if (MPI_Ialltoallv(sb, scnt, sdis, MPI_LONG, rb, rcnt, rdis, MPI_LONG,
                     MPI_COMM_WORLD, &nv[0]) != MPI_SUCCESS) return 7;
  long *mine2 = malloc((rank + 1) * sizeof(long));
  if (MPI_Ireduce_scatter(contrib, mine2, counts, MPI_LONG, MPI_SUM,
                          MPI_COMM_WORLD, &nv[1]) != MPI_SUCCESS)
    return 8;
  if (MPI_Waitall(2, nv, MPI_STATUSES_IGNORE) != MPI_SUCCESS) return 9;
  for (r = 0; r < size; r++)
    for (i = 0; i < rcnt[r]; i++)
      if (rb[rdis[r] + i] != r * 1000 + rank) return 10;
  for (i = 0; i < rank + 1; i++)
    if (mine2[i] != mine[i]) return 11;
  /* nonblocking v-gather/scatter/allgather: ragged blocks, root 0 */
  {
    long *mysend = malloc((rank + 1) * sizeof(long));
    int k;
    for (k = 0; k < rank + 1; k++) mysend[k] = rank * 100 + k;
    long *gath = NULL; int *gc = NULL, *gd = NULL;
    if (rank == 0) {
      gc = malloc(size * sizeof(int)); gd = malloc(size * sizeof(int));
      int off = 0;
      for (r = 0; r < size; r++) { gc[r] = r + 1; gd[r] = off; off += r + 1; }
      gath = malloc(off * sizeof(long));
      for (k = 0; k < off; k++) gath[k] = -1;
    }
    MPI_Request vr;
    if (MPI_Igatherv(mysend, rank + 1, MPI_LONG, gath, gc, gd, MPI_LONG,
                     0, MPI_COMM_WORLD, &vr) != MPI_SUCCESS) return 12;
    MPI_Wait(&vr, MPI_STATUS_IGNORE);
    if (rank == 0) {
      for (r = 0; r < size; r++)
        for (k = 0; k < r + 1; k++)
          if (gath[gd[r] + k] != r * 100 + k) return 13;
      /* scatter it back, each rank gets its own ragged block */
    }
    long *back2 = malloc((rank + 1) * sizeof(long));
    MPI_Request sv;
    if (MPI_Iscatterv(gath, gc, gd, MPI_LONG, back2, rank + 1, MPI_LONG,
                      0, MPI_COMM_WORLD, &sv) != MPI_SUCCESS) return 14;
    MPI_Wait(&sv, MPI_STATUS_IGNORE);
    for (k = 0; k < rank + 1; k++)
      if (back2[k] != rank * 100 + k) return 15;
    /* allgatherv: every rank ends with the full ragged layout */
    int *ac = malloc(size * sizeof(int)), *ad = malloc(size * sizeof(int));
    int off2 = 0;
    for (r = 0; r < size; r++) { ac[r] = r + 1; ad[r] = off2; off2 += r + 1; }
    long *all = malloc(off2 * sizeof(long));
    for (k = 0; k < off2; k++) all[k] = -1;
    MPI_Request av;
    if (MPI_Iallgatherv(mysend, rank + 1, MPI_LONG, all, ac, ad, MPI_LONG,
                        MPI_COMM_WORLD, &av) != MPI_SUCCESS) return 16;
    MPI_Wait(&av, MPI_STATUS_IGNORE);
    for (r = 0; r < size; r++)
      for (k = 0; k < r + 1; k++)
        if (all[ad[r] + k] != r * 100 + k) return 17;
  }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("ragged rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "ragged"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 4
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"ragged rank {r}/{n} OK" in out

    def test_intercommunicators(self, shim, tmp_path):
        """MPI_Intercomm_create between the two halves of a split world:
        remote-group pt2pt both ways (ranks address the REMOTE group),
        remote_size/test_inter, collectives rejected on the intercomm,
        and Intercomm_merge reconstructing a working intracommunicator
        with the high group second."""
        src = tmp_path / "inter.c"
        src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (size < 4 || size % 2) { MPI_Finalize(); return 0; }
  int half = size / 2, low = rank < half;
  MPI_Comm mine;
  MPI_Comm_split(MPI_COMM_WORLD, low, rank, &mine);
  /* leaders: local rank 0 of each half; peer_comm = WORLD */
  MPI_Comm inter;
  if (MPI_Intercomm_create(mine, 0, MPI_COMM_WORLD, low ? half : 0, 99,
                           &inter) != MPI_SUCCESS) return 3;
  int flag = 0, rsize = -1, lrank = -1, lsize = -1;
  MPI_Comm_test_inter(inter, &flag);
  if (!flag) return 4;
  MPI_Comm_remote_size(inter, &rsize);
  if (rsize != half) return 5;
  MPI_Comm_rank(inter, &lrank);
  MPI_Comm_size(inter, &lsize);
  if (lsize != half) return 6;
  /* pt2pt across: low rank i <-> high rank i (REMOTE addressing) */
  long v = rank * 11, got = -1;
  MPI_Status st;
  if (low) {
    MPI_Send(&v, 1, MPI_LONG, lrank, 5, inter);
    MPI_Recv(&got, 1, MPI_LONG, lrank, 6, inter, &st);
    if (got != (lrank + half) * 11L) return 7;
    if (st.MPI_SOURCE != lrank) return 8;  /* remote-group rank */
  } else {
    MPI_Recv(&got, 1, MPI_LONG, lrank, 5, inter, &st);
    if (got != (long)lrank * 11) return 9;
    MPI_Send(&v, 1, MPI_LONG, lrank, 6, inter);
  }
  /* collectives are an intra surface: loudly rejected here (install
   * ERRORS_RETURN first — the default handler is ARE_FATAL) */
  MPI_Comm_set_errhandler(inter, MPI_ERRORS_RETURN);
  long s1 = 1, s2 = 0;
  if (MPI_Allreduce(&s1, &s2, 1, MPI_LONG, MPI_SUM, inter)
      != MPI_ERR_COMM) return 10;
  /* merge: low group passes high=0, high group high=1 -> world order */
  MPI_Comm flat;
  if (MPI_Intercomm_merge(inter, low ? 0 : 1, &flat) != MPI_SUCCESS)
    return 11;
  int frank = -1, fsize = -1;
  MPI_Comm_rank(flat, &frank);
  MPI_Comm_size(flat, &fsize);
  if (fsize != size || frank != rank) return 12;
  long fv = rank + 1, fsum = 0;
  if (MPI_Allreduce(&fv, &fsum, 1, MPI_LONG, MPI_SUM, flat)
      != MPI_SUCCESS) return 13;
  if (fsum != (long)size * (size + 1) / 2) return 14;
  /* a SECOND merge of the same intercomm with EQUAL (erroneous) flags:
     the leaders detect it and both sides fall back to the same
     deterministic order (low world ranks first), on fresh cids */
  MPI_Comm flat2;
  if (MPI_Intercomm_merge(inter, 1, &flat2) != MPI_SUCCESS) return 15;
  int f2rank = -1;
  MPI_Comm_rank(flat2, &f2rank);
  if (f2rank != rank) return 16;  /* low group first -> world order */
  long f2sum = 0;
  if (MPI_Allreduce(&fv, &f2sum, 1, MPI_LONG, MPI_SUM, flat2)
      != MPI_SUCCESS) return 17;
  if (f2sum != (long)size * (size + 1) / 2) return 18;
  MPI_Barrier(MPI_COMM_WORLD);
  printf("inter rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "inter"
        _compile_c(shim, src, binpath)
        port = _free_port()
        n = 4
        procs = [
            subprocess.Popen([str(binpath)], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"inter rank {r}/{n} OK" in out

    def test_comm_spawn(self, shim, tmp_path):
        """MPI_Comm_spawn: the parent universe launches 2 children that
        form their OWN MPI_COMM_WORLD (ids offset into the shared book);
        parent<->child pt2pt crosses the spawn intercomm both ways and
        the children synchronize on their own world without touching
        the parents' contexts."""
        child_src = tmp_path / "child.c"
        child_src.write_text(r'''
#include <stdio.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (size != 2) return 3;  /* children's world is the 2 children */
  MPI_Comm parent;
  MPI_Comm_get_parent(&parent);
  if (parent == MPI_COMM_NULL) return 4;
  int prsize = -1, flag = 0;
  MPI_Comm_test_inter(parent, &flag);
  if (!flag) return 5;
  MPI_Comm_remote_size(parent, &prsize);
  /* child world collective on its own contexts */
  long v = rank + 1, sum = 0;
  MPI_Allreduce(&v, &sum, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD);
  if (sum != 3) return 6;
  /* receive a probe from parent rank 0, reply transformed */
  long got = -1;
  MPI_Recv(&got, 1, MPI_LONG, 0, 40, parent, MPI_STATUS_IGNORE);
  got = got * 10 + rank;
  MPI_Send(&got, 1, MPI_LONG, 0, 41, parent);
  MPI_Finalize();
  return 0;
}
''')
        child_bin = tmp_path / "spawn_child"
        _compile_c(shim, child_src, child_bin)

        parent_src = tmp_path / "parent.c"
        parent_src.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"
int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  /* a failed launch must fail on EVERY rank (collective agreement),
     leave no partial universe, and not poison later spawns */
  MPI_Comm dead;
  if (MPI_Comm_spawn("/nonexistent/zompi-child", NULL, 2, MPI_INFO_NULL,
                     0, MPI_COMM_WORLD, &dead, NULL) != MPI_ERR_OTHER)
    return 13;
  /* a child that execs but dies before joining the modex (crash before
     MPI_Init) must also become an agreed failure, not a hang */
  if (MPI_Comm_spawn("/bin/true", NULL, 2, MPI_INFO_NULL, 0,
                     MPI_COMM_WORLD, &dead, NULL) != MPI_ERR_OTHER)
    return 14;
  MPI_Comm kids;
  int errs[2] = {-1, -1};
  if (MPI_Comm_spawn(getenv("SPAWN_CHILD"), NULL, 2, MPI_INFO_NULL, 0,
                     MPI_COMM_WORLD, &kids, errs) != MPI_SUCCESS)
    return 3;
  if (errs[0] != MPI_SUCCESS || errs[1] != MPI_SUCCESS) return 4;
  int rsize = -1;
  MPI_Comm_remote_size(kids, &rsize);
  if (rsize != 2) return 5;
  if (rank == 0) {
    /* message each child over the intercomm, read the replies */
    for (int k = 0; k < 2; k++) {
      long v = 7 + k;
      MPI_Send(&v, 1, MPI_LONG, k, 40, kids);
    }
    for (int k = 0; k < 2; k++) {
      long got = -1;
      MPI_Recv(&got, 1, MPI_LONG, k, 41, kids, MPI_STATUS_IGNORE);
      if (got != (7 + k) * 10 + k) {
        fprintf(stderr, "child %d replied %ld\n", k, got);
        return 6;
      }
    }
  }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("spawn rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
''')
        binpath = tmp_path / "spawn_parent"
        _compile_c(shim, parent_src, binpath)
        port = _free_port()
        n = 2
        procs = []
        for r in range(n):
            env = _env(r, n, port)
            env["SPAWN_CHILD"] = str(child_bin)
            procs.append(subprocess.Popen(
                [str(binpath)], env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"spawn rank {r}/{n} OK" in out

    @pytest.mark.parametrize("n", [1, 3])
    def test_spawn_example(self, spawn_example_bin, n):
        """examples/spawn_c.c: the self-re-exec'ing spawn acceptance."""
        binpath = spawn_example_bin
        port = _free_port()
        procs = [
            subprocess.Popen([binpath, binpath], env=_env(r, n, port),
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
            for r in range(n)
        ]
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed: {err}\n{out}"
            assert f"spawn_c rank {r}/{n} OK" in out
