"""Multi-tenant DVM tests — admission queueing, placement isolation,
the always-on device prober, and the tenant-isolation drill.

Four altitudes:

- **unit (pure threads)**: the admission queue's policy order, cap
  blocking, dead-client reap, and close-under-waiter semantics;
  :func:`~zhpe_ompi_tpu.runtime.dvmtree.place_job`'s pack/spread/
  exclusive ladder and the per-job placement audit's typed violations.
- **thread-fast daemon integration**: real in-process daemons running
  cheap non-wire-up rank scripts — FIFO/priority admission order
  observed end to end, ``[queued, pos]`` frames on the client, the
  dead-queued-client reap regression over a raw socket, exclusive
  fallback loud + counted, audit failing a colliding launch.
- **prober unit**: a fake liveness probe wedged OUTSIDE any guarded
  region classifies in bounded time; an active region silences the
  background thread entirely.
- **slow real-process drill**: two tenants on a daemon tree, a rank of
  job A killed -9 mid-collective — job B's checked allreduces never
  see a fault event, both rcs are exactly the fault plan's.
"""

import io
import os
import socket
import textwrap
import threading
import time

import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.mca import var as mca_var
from zhpe_ompi_tpu.parallel import mesh as mesh_mod
from zhpe_ompi_tpu.runtime import dvm as dvm_mod
from zhpe_ompi_tpu.runtime import dvmtree
from zhpe_ompi_tpu.runtime import spc

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script(tmp_path, body: str, name: str = "prog.py") -> str:
    p = tmp_path / name
    p.write_text(
        "import sys\n"
        f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(body)
    )
    return str(p)


# no zhpe wire-up: admission/placement are daemon-side machinery, so
# the matrix rides bare scripts (fast) — the slow drill uses real ranks
_PARK_BODY = """
import os, time
deadline = time.monotonic() + 60.0
while not os.path.exists(sys.argv[1]):
    assert time.monotonic() < deadline, "parker never released"
    time.sleep(0.02)
"""

_APPEND_BODY = """
with open(sys.argv[1], "a") as f:
    f.write(sys.argv[2] + chr(10))
"""


def _wait(pred, timeout=30.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, msg
        time.sleep(0.02)


def _bg_launch(addr, n, argv, **kw):
    cli = dvm_mod.DvmClient(addr)
    out, err, res = io.StringIO(), io.StringIO(), {}
    kw.setdefault("timeout", 60.0)

    def run():
        try:
            res["rc"] = cli.launch(n, argv, stdout=out, stderr=err,
                                   **kw)
        except errors.MpiError as e:
            res["error"] = str(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return {"cli": cli, "thread": t, "out": out, "err": err,
            "res": res}


def _finish(h, timeout=60.0):
    h["thread"].join(timeout=timeout)
    assert not h["thread"].is_alive(), (h["out"].getvalue(),
                                        h["err"].getvalue())
    h["cli"].close()
    return h["res"]


# ---------------------------------------------------- admission queue (unit)


class TestAdmissionQueueUnit:
    def test_no_cap_admits_immediately(self, fresh_vars):
        q = dvm_mod._AdmissionQueue()
        t1, t2 = q.enqueue(), q.enqueue()
        assert q.admit(t1) is not None
        assert q.admit(t2) is not None  # cap 0: both run concurrently
        assert not t1.was_queued and not t2.was_queued
        assert q.stat_view()["running"] == 2
        q.release(t1)
        q.release(t2)
        assert q.stat_view()["running"] == 0

    def test_cap_blocks_fifo_order(self, fresh_vars):
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        q = dvm_mod._AdmissionQueue()
        t1 = q.enqueue()
        assert q.admit(t1) is not None
        t2, t3 = q.enqueue(), q.enqueue()
        admitted = []
        positions = {2: [], 3: []}

        def waiter(ticket, tag):
            q.admit(ticket,
                    on_position=lambda p: positions[tag].append(p))
            admitted.append(tag)

        th2 = threading.Thread(target=waiter, args=(t2, 2), daemon=True)
        th2.start()
        _wait(lambda: positions[2] == [1])
        th3 = threading.Thread(target=waiter, args=(t3, 3), daemon=True)
        th3.start()
        _wait(lambda: positions[3] == [2])
        assert q.stat_view() == {"policy": "fifo", "cap": 1,
                                 "running": 1, "waiting": 2}
        assert admitted == []  # both parked while the slot is held
        q.release(t1)
        _wait(lambda: admitted == [2])
        q.release(t2)
        _wait(lambda: admitted == [2, 3])
        q.release(t3)
        assert q.queued() == []

    def test_priority_reorders_live_queue(self, fresh_vars):
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        fresh_vars.set("dvm_admission_policy", "priority")
        q = dvm_mod._AdmissionQueue()
        t1 = q.enqueue(priority=0)
        assert q.admit(t1) is not None
        low, high = q.enqueue(priority=1), None
        admitted = []
        low_pos = []

        def wait_low():
            q.admit(low, on_position=low_pos.append)
            admitted.append("low")

        threading.Thread(target=wait_low, daemon=True).start()
        _wait(lambda: low_pos[-1:] == [1])
        high = q.enqueue(priority=9)

        def wait_high():
            q.admit(high)
            admitted.append("high")

        threading.Thread(target=wait_high, daemon=True).start()
        # the later, higher-priority ticket jumps the live queue — the
        # parked low ticket hears its demotion as a position frame
        _wait(lambda: low_pos[-1:] == [2])
        q.release(t1)
        _wait(lambda: admitted == ["high"])
        q.release(high)
        _wait(lambda: admitted == ["high", "low"])
        q.release(low)

    def test_dead_client_ticket_cancelled(self, fresh_vars):
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        q = dvm_mod._AdmissionQueue()
        t1 = q.enqueue()
        assert q.admit(t1) is not None
        t2 = q.enqueue()
        assert q.admit(t2, alive=lambda: False) is None
        assert q.queued() == []  # reaped, not wedging the head
        q.release(t1)
        q.release(t2)  # idempotent on a cancelled ticket

    def test_close_raises_under_waiter(self, fresh_vars):
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        q = dvm_mod._AdmissionQueue()
        t1 = q.enqueue()
        assert q.admit(t1) is not None
        t2 = q.enqueue()
        res = {}

        def waiter():
            try:
                q.admit(t2)
            except errors.MpiError as e:
                res["error"] = str(e)

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        _wait(lambda: q.stat_view()["waiting"] == 1)
        q.close()
        th.join(timeout=10.0)
        assert "stopping" in res["error"]
        assert q.queued() == []
        q.release(t1)


# ------------------------------------------------ placement ladder (unit)


class TestPlacementUnit:
    DAEMONS = ["h:1", "h:2", "h:3", "h:4"]

    def test_pack_is_block_placement(self):
        placed, fell_back = dvmtree.place_job(
            [0, 1], self.DAEMONS, {}, "pack")
        assert placed == {0: "h:1", 1: "h:3"}
        assert not fell_back

    def test_spread_claims_least_loaded_minimal_prefix(self):
        busy = {"h:1": 2, "h:2": 1}
        placed, fell_back = dvmtree.place_job(
            [0, 1], self.DAEMONS, busy, "spread")
        # by_load: h:3, h:4 (idle, attach order), then h:2, h:1 — the
        # 2-rank job claims exactly the two idle daemons, never
        # reaching back into the busy tail
        assert placed == {0: "h:3", 1: "h:4"}
        assert not fell_back

    def test_spread_tenants_disjoint_while_capacity(self):
        a, _ = dvmtree.place_job([0, 1], self.DAEMONS, {}, "spread")
        busy = {d: 1 for d in a.values()}
        b, _ = dvmtree.place_job([0, 1], self.DAEMONS, busy, "spread")
        assert not (set(a.values()) & set(b.values())), (a, b)

    def test_spread_oversubscribed_covers_whole_tree(self):
        placed, _ = dvmtree.place_job(
            list(range(8)), self.DAEMONS, {}, "spread")
        assert set(placed.values()) == set(self.DAEMONS)

    def test_exclusive_claims_minimal_free_prefix(self):
        busy = {"h:1": 1}
        placed, fell_back = dvmtree.place_job(
            [0], self.DAEMONS, busy, "exclusive")
        assert placed == {0: "h:2"}  # one rank claims ONE free daemon
        assert not fell_back

    def test_exclusive_fallback_when_no_free_daemon(self):
        busy = {d: 1 for d in self.DAEMONS}
        placed, fell_back = dvmtree.place_job(
            [0, 1], self.DAEMONS, busy, "exclusive")
        assert fell_back
        assert set(placed.values()) <= set(self.DAEMONS)

    def test_unknown_policy_typed(self):
        with pytest.raises(errors.ArgError, match="unknown policy"):
            dvmtree.place_job([0], self.DAEMONS, {}, "anywhere")

    def test_empty_tree_typed(self):
        with pytest.raises(errors.InternalError, match="no daemons"):
            dvmtree.place_job([0], [], {}, "pack")


class TestPlacementAudit:
    def _jobs(self):
        a = {"id": "job1", "session": "d1_job1", "daemons": ["h:1"],
             "exclusive": False}
        b = {"id": "job2", "session": "d1_job2", "daemons": ["h:2"],
             "exclusive": False}
        return a, b

    def test_disjoint_tenants_pass(self):
        a, b = self._jobs()
        dvmtree.audit_placement(a, [b])  # no raise, nothing recorded
        assert dvmtree.placement_audit_failures() == []

    def test_namespace_collision_typed_counted(self):
        a, b = self._jobs()
        b["id"] = a["id"]
        before = spc.read("dvm_placement_audit_failures")
        try:
            with pytest.raises(errors.PlacementViolation,
                               match="cid windows") as ei:
                dvmtree.audit_placement(a, [b])
            assert ei.value.prop == "namespace"
            assert dvmtree.placement_audit_failures()
            assert spc.read("dvm_placement_audit_failures") \
                == before + 1
        finally:
            dvmtree.clear_placement_audit_failures()

    def test_session_prefix_collision_typed(self):
        a, b = self._jobs()
        b["session"] = a["session"] + "_sub"  # sweep-prefix overlap
        try:
            with pytest.raises(errors.PlacementViolation,
                               match="sm segments") as ei:
                dvmtree.audit_placement(a, [b])
            assert ei.value.prop == "session"
        finally:
            dvmtree.clear_placement_audit_failures()

    def test_exclusive_subtree_overlap_typed(self):
        a, b = self._jobs()
        a["exclusive"] = True
        b["daemons"] = ["h:1", "h:2"]
        try:
            with pytest.raises(errors.PlacementViolation,
                               match="exclusive subtree") as ei:
                dvmtree.audit_placement(a, [b])
            assert ei.value.prop == "subtree"
            assert set(ei.value.jobs) == {"job1", "job2"}
        finally:
            dvmtree.clear_placement_audit_failures()


# ----------------------------------------- /dev/shm sweep isolation (unit)


class TestSweepIsolation:
    """The cross-tenant sweep property (and why it needed no fix): the
    sweep keys on ``<prefix>_{session}_`` WITH the trailing
    underscore, so ``job1`` can never reach ``job10``'s files — only a
    prefix-with-underscore session relation could, and the placement
    audit rejects exactly that shape."""

    def test_sibling_job_sessions_never_collide(self):
        assert not dvmtree._sessions_collide("d1_job1", "d1_job10")
        assert not dvmtree._sessions_collide("d1_job2", "d1_job21")

    def test_colliding_shapes(self):
        assert dvmtree._sessions_collide("d1_job1", "d1_job1")
        assert dvmtree._sessions_collide("d1_job1", "d1_job1_x")
        assert dvmtree._sessions_collide("d1_job1_x", "d1_job1")

    @pytest.mark.skipif(not os.path.isdir("/dev/shm"),
                        reason="no /dev/shm")
    def test_sweep_respects_sibling_tenant_files(self):
        mine = "/dev/shm/zompi_ring_ztenancy_job1_0_0"
        sibling = "/dev/shm/zompi_ring_ztenancy_job10_0_0"
        for p in (mine, sibling):
            with open(p, "w"):
                pass
        try:
            dvm_mod._sweep_shm("ztenancy_job1")
            assert not os.path.exists(mine)
            assert os.path.exists(sibling), \
                "job1's sweep reached job10's segment"
        finally:
            for p in (mine, sibling):
                try:
                    os.unlink(p)
                except OSError:
                    pass


# -------------------------------------- daemon integration (thread-fast)


class TestAdmissionDaemon:
    def _park(self, tmp_path, addr, flag):
        prog = _script(tmp_path, _PARK_BODY, name="park.py")
        h = _bg_launch(addr, 1, [prog, flag])
        _wait(lambda: h["cli"].last_job_id is not None
              or not h["thread"].is_alive(),
              msg="parker job never started")
        return h

    def test_fifo_order_and_queued_frames(self, tmp_path, fresh_vars):
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        append = _script(tmp_path, _APPEND_BODY, name="append.py")
        log = str(tmp_path / "order.log")
        flag = str(tmp_path / "flag")
        q0 = spc.read("dvm_jobs_queued")
        d = dvm_mod.Dvm()
        try:
            parker = self._park(tmp_path, d.address, flag)
            h2 = _bg_launch(d.address, 1, [append, log, "J2"])
            _wait(lambda: h2["cli"].last_queue_position == 1)
            h3 = _bg_launch(d.address, 1, [append, log, "J3"])
            _wait(lambda: h3["cli"].last_queue_position == 2)
            stat = dvm_mod.DvmClient(d.address)
            view = stat.stat()["admission"]
            stat.close()
            assert view == {"policy": "fifo", "cap": 1, "running": 1,
                            "waiting": 2}
            assert "queued at position 1" in h2["err"].getvalue()
            assert "queued at position 2" in h3["err"].getvalue()
            with open(flag, "w"):
                pass
            assert _finish(parker)["rc"] == 0
            assert _finish(h2)["rc"] == 0
            assert _finish(h3)["rc"] == 0
            with open(log) as f:
                assert f.read().split() == ["J2", "J3"]
            assert spc.read("dvm_jobs_queued") - q0 == 2
            assert spc.read("dvm_queue_wait_ms") >= 0  # watermark set
        finally:
            d.stop()
        assert dvm_mod.queued_admission_tickets() == []

    def test_priority_preempts_fifo(self, tmp_path, fresh_vars):
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        fresh_vars.set("dvm_admission_policy", "priority")
        append = _script(tmp_path, _APPEND_BODY, name="append.py")
        log = str(tmp_path / "order.log")
        flag = str(tmp_path / "flag")
        d = dvm_mod.Dvm()
        try:
            parker = self._park(tmp_path, d.address, flag)
            h_low = _bg_launch(d.address, 1, [append, log, "LOW"],
                               priority=1)
            _wait(lambda: h_low["cli"].last_queue_position == 1)
            h_high = _bg_launch(d.address, 1, [append, log, "HIGH"],
                                priority=9)
            # the high-priority launch takes the head; the parked low
            # launch hears its demotion as a fresh [queued, 2] frame
            _wait(lambda: h_low["cli"].last_queue_position == 2)
            assert h_high["cli"].last_queue_position == 1
            with open(flag, "w"):
                pass
            assert _finish(parker)["rc"] == 0
            assert _finish(h_high)["rc"] == 0
            assert _finish(h_low)["rc"] == 0
            with open(log) as f:
                assert f.read().split() == ["HIGH", "LOW"]
        finally:
            d.stop()
        assert dvm_mod.queued_admission_tickets() == []

    def test_queued_launch_holds_no_setup_lock(self, tmp_path,
                                               fresh_vars):
        """Respawn/resize take setup() directly — they ride their
        job's admission.  A QUEUED launch must therefore hold no lock
        at all, or a parked launch would wedge a running job's
        recovery."""
        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        append = _script(tmp_path, _APPEND_BODY, name="append.py")
        flag = str(tmp_path / "flag")
        d = dvm_mod.Dvm()
        try:
            parker = self._park(tmp_path, d.address, flag)
            h2 = _bg_launch(d.address, 1,
                            [append, str(tmp_path / "l"), "X"])
            _wait(lambda: h2["cli"].last_queue_position == 1)
            lock = d._admission.setup()
            assert lock.acquire(timeout=2.0), \
                "a queued launch holds the setup lock"
            lock.release()
            with open(flag, "w"):
                pass
            assert _finish(parker)["rc"] == 0
            assert _finish(h2)["rc"] == 0
        finally:
            d.stop()

    def test_dead_queued_client_reaped(self, tmp_path, fresh_vars):
        """The satellite regression: connect, queue behind a running
        job, DIE.  The dead ticket must be reaped — the next launch
        admits instead of wedging behind a ghost at the queue head."""
        from zhpe_ompi_tpu.pt2pt.tcp import _recv_frame, _send_frame
        from zhpe_ompi_tpu.utils import dss

        fresh_vars.set("dvm_max_concurrent_jobs", 1)
        append = _script(tmp_path, _APPEND_BODY, name="append.py")
        flag = str(tmp_path / "flag")
        launched0 = spc.read("dvm_jobs_launched")
        d = dvm_mod.Dvm()
        try:
            parker = self._park(tmp_path, d.address, flag)
            # a raw launch client: parks in the queue, then dies
            s = socket.create_connection(d.address, 10.0)
            prog = _script(tmp_path, _APPEND_BODY, name="a2.py")
            _send_frame(s, dss.pack(["launch", {
                "n": 1, "argv": [prog, str(tmp_path / "ghost"), "G"],
                "mca": [], "ft": False, "timeout": 30.0}]))
            deadline = time.monotonic() + 30.0
            while True:
                frame = _recv_frame(s)
                assert frame is not None and \
                    time.monotonic() < deadline
                [msg] = dss.unpack(frame)
                if msg[0] == "queued":
                    break
            s.close()  # the client is gone; its ticket must not wedge
            _wait(lambda: dvm_mod.queued_admission_tickets() == [],
                  msg="dead client's ticket never reaped")
            h3 = _bg_launch(d.address, 1,
                            [append, str(tmp_path / "l3"), "J3"])
            with open(flag, "w"):
                pass
            assert _finish(parker)["rc"] == 0
            assert _finish(h3)["rc"] == 0
            # the ghost's job never launched — only parker + J3 did
            assert spc.read("dvm_jobs_launched") - launched0 == 2
            assert not os.path.exists(str(tmp_path / "ghost"))
        finally:
            d.stop()
        assert dvm_mod.queued_admission_tickets() == []


class TestPlacementDaemon:
    def test_spread_tenants_disjoint_subtrees(self, tmp_path):
        park = _script(tmp_path, _PARK_BODY, name="park.py")
        flag = str(tmp_path / "flag")
        tree = dvmtree.spawn_tree(4, in_process=True)
        try:
            addr = tree.root_address
            h1 = _bg_launch(addr, 2, [park, flag], placement="spread")
            _wait(lambda: h1["cli"].last_job_id is not None)
            h2 = _bg_launch(addr, 2, [park, flag], placement="spread")
            _wait(lambda: h2["cli"].last_job_id is not None)
            cli = dvm_mod.DvmClient(addr)
            jobs = cli.stat()["jobs"]
            cli.close()
            d1 = {d for _, d in jobs[h1["cli"].last_job_id]["placement"]}
            d2 = {d for _, d in jobs[h2["cli"].last_job_id]["placement"]}
            assert d1 and d2 and not (d1 & d2), (d1, d2)
            with open(flag, "w"):
                pass
            assert _finish(h1)["rc"] == 0
            assert _finish(h2)["rc"] == 0
        finally:
            tree.stop()
        assert dvmtree.placement_audit_failures() == []

    def test_exclusive_fallback_loud_and_counted(self, tmp_path):
        """One daemon, one live pack tenant: an exclusive launch finds
        no free daemon — it must fall back to spread LOUDLY (a note
        frame + dvm_placement_fallbacks), never silently, and never as
        an audit failure (capacity, not collision)."""
        park = _script(tmp_path, _PARK_BODY, name="park.py")
        flag = str(tmp_path / "flag")
        fb0 = spc.read("dvm_placement_fallbacks")
        d = dvm_mod.Dvm()
        try:
            h1 = _bg_launch(d.address, 1, [park, flag])
            _wait(lambda: h1["cli"].last_job_id is not None)
            h2 = _bg_launch(d.address, 1, [park, flag],
                            placement="exclusive")
            _wait(lambda: h2["cli"].last_job_id is not None)
            assert "falling back to spread" in h2["err"].getvalue()
            assert spc.read("dvm_placement_fallbacks") - fb0 == 1
            with open(flag, "w"):
                pass
            assert _finish(h1)["rc"] == 0
            assert _finish(h2)["rc"] == 0
        finally:
            d.stop()
        assert dvmtree.placement_audit_failures() == []

    def test_exclusive_tenant_protected_by_audit(self, tmp_path):
        """An exclusive tenant HOLDS its subtree: a later launch whose
        fallback would land on it must fail loudly with the typed
        audit violation, not silently co-locate."""
        park = _script(tmp_path, _PARK_BODY, name="park.py")
        flag = str(tmp_path / "flag")
        d = dvm_mod.Dvm()
        try:
            h1 = _bg_launch(d.address, 1, [park, flag],
                            placement="exclusive")
            _wait(lambda: h1["cli"].last_job_id is not None)
            with pytest.raises(errors.MpiError,
                               match="exclusive subtree"):
                cli = dvm_mod.DvmClient(d.address)
                try:
                    cli.launch(1, [park, flag], placement="exclusive",
                               timeout=30.0, stdout=io.StringIO(),
                               stderr=io.StringIO())
                finally:
                    cli.close()
            assert dvmtree.placement_audit_failures()
            with open(flag, "w"):
                pass
            assert _finish(h1)["rc"] == 0
        finally:
            dvmtree.clear_placement_audit_failures()  # intentional trip
            d.stop()
        assert dvm_mod.queued_admission_tickets() == []


# --------------------------------------------- device prober (thread-fast)


class _FakeProbe:
    """DeviceLivenessProbe stand-in: probe_once() reports the wedge
    flag, classify() records and latches ``fault`` (the real probe's
    recovery-owns-the-plane contract)."""

    def __init__(self):
        self.rank = 0
        self.fault = None
        self.probes = 0
        self.wedged = False
        self.classified = []

    def probe_once(self):
        self.probes += 1
        return ("hung", "fake-wedge") if self.wedged else ("ok", "")

    def classify(self, kind, detail):
        self.classified.append((kind, detail))
        self.fault = errors.DeviceFault(
            detail, failed_ranks=(self.rank,), kind=kind)


class TestDeviceProber:
    def test_interval_zero_is_off(self):
        probe = _FakeProbe()
        prober = mesh_mod.DeviceProber(probe, interval_ms=0)
        prober.start()
        assert not prober.running
        assert mesh_mod.live_prober_threads() == []

    def test_out_of_region_wedge_classifies_bounded(self):
        probe = _FakeProbe()
        prober = mesh_mod.DeviceProber(probe, interval_ms=10)
        p0 = spc.read("device_probes")
        f0 = spc.read("device_probe_faults")
        prober.start()
        try:
            assert prober.running
            _wait(lambda: probe.probes >= 2, timeout=5.0,
                  msg="background prober never probed")
            probe.wedged = True
            _wait(lambda: probe.classified, timeout=5.0,
                  msg="out-of-region wedge never classified")
            assert probe.classified[0][0] == "hung"
            time.sleep(0.1)
            # the latched fault gates re-classification: recovery owns
            # the plane until it clears
            assert len(probe.classified) == 1
            assert spc.read("device_probes") - p0 >= 2
            assert spc.read("device_probe_faults") - f0 == 1
        finally:
            prober.stop()
        assert mesh_mod.live_prober_threads() == []

    def test_region_silences_background_probing(self):
        probe = _FakeProbe()
        prober = mesh_mod.DeviceProber(probe, interval_ms=10)
        prober.start()
        try:
            with prober.region():
                time.sleep(0.05)  # let any in-flight probe drain
                before = probe.probes
                time.sleep(0.15)
                assert probe.probes == before, \
                    "prober probed inside a guarded region"
            _wait(lambda: probe.probes > before, timeout=5.0,
                  msg="prober never resumed after the region")
        finally:
            prober.stop()
        assert mesh_mod.live_prober_threads() == []

    def test_region_wraps_inner_guard(self):
        probe = _FakeProbe()
        prober = mesh_mod.DeviceProber(probe, interval_ms=0)
        entered = []

        class _Guard:
            def __enter__(self):
                entered.append("in")

            def __exit__(self, *a):
                entered.append("out")

        with prober.region(_Guard()):
            assert entered == ["in"]
            assert prober._busy == 1
        assert entered == ["in", "out"]
        assert prober._busy == 0


# ------------------------------------------ two-tenant drill (slow, real)


_TENANT_A_BODY = """
import os, time
import numpy as np
import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu import ops

victim = int(sys.argv[1])
proc = zmpi.host_init()
proc.barrier()
print(f"READY rank={proc.rank}", flush=True)
if proc.rank == victim:
    time.sleep(300.0)
    raise SystemExit(0)
deadline = time.monotonic() + 60.0
while time.monotonic() < deadline:
    if proc.ft_state.is_failed(victim):
        break
    time.sleep(0.01)
else:
    raise SystemExit(1)
cause = proc.ft_state.cause_of(victim)
proc.failure_ack()
sh = proc.shrink()
total = float(np.asarray(sh.allreduce(np.float64(1.0), ops.SUM)))
print(f"SURVIVOR-OK rank={proc.rank} cause={cause} total={total}",
      flush=True)
zmpi.host_finalize()
"""

_TENANT_B_BODY = """
import os, time
import numpy as np
import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu import ops

flag = sys.argv[1]
proc = zmpi.host_init()
proc.barrier()
print(f"READY rank={proc.rank}", flush=True)
iters = 0
deadline = time.monotonic() + 90.0
while True:
    assert time.monotonic() < deadline, "never released"
    # the stop decision rides the allreduce (rank 0 polls the flag,
    # contributes +1): every rank leaves in the SAME iteration, so no
    # rank is abandoned mid-collective by a peer that saw the flag
    stop = proc.rank == 0 and os.path.exists(flag)
    total = float(np.asarray(proc.allreduce(
        np.float64(2.0 if stop else 1.0), ops.SUM)))
    assert total in (float(proc.size), float(proc.size) + 1.0), \\
        (total, proc.size)
    iters += 1
    if total > float(proc.size):
        break
    time.sleep(0.02)
assert not proc.ft_state.failed(), proc.ft_state.failed()
from zhpe_ompi_tpu.runtime import spc
assert spc.read("dvm_fault_events") == 0, "tenant saw a foreign fault"
print(f"CLEAN-OK rank={proc.rank} iters={iters}", flush=True)
zmpi.host_finalize()
"""


@pytest.mark.slow
class TestTwoTenantDrill:
    def test_fault_in_job_a_invisible_to_job_b(self, tmp_path):
        """Kill -9 a rank of tenant A mid-collective-loop: tenant B —
        ft too, checked allreduces the whole window, disjoint
        exclusive subtree — must see ZERO fault events and ZERO
        detector suspicions; both rcs are exactly the fault plan's."""
        import signal as sig

        prog_a = _script(tmp_path, _TENANT_A_BODY, name="a.py")
        prog_b = _script(tmp_path, _TENANT_B_BODY, name="b.py")
        flag = str(tmp_path / "flag")
        victim = 1
        mca = [("ft_detector_period", "2.0"),
               ("ft_detector_timeout", "60.0")]
        tree = dvmtree.spawn_tree(3, in_process=True)
        try:
            addr = tree.root_address
            h_b = _bg_launch(addr, 2, [prog_b, flag], ft=True, mca=mca,
                             placement="spread", timeout=150.0)
            _wait(lambda: h_b["out"].getvalue().count("READY") == 2,
                  timeout=60.0)
            h_a = _bg_launch(addr, 2, [prog_a, str(victim)], ft=True,
                             mca=mca, placement="exclusive",
                             timeout=150.0)
            _wait(lambda: h_a["out"].getvalue().count("READY") == 2,
                  timeout=60.0)
            cli = dvm_mod.DvmClient(addr)
            jobs = cli.stat()["jobs"]
            da = {d for _, d in
                  jobs[h_a["cli"].last_job_id]["placement"]}
            db = {d for _, d in
                  jobs[h_b["cli"].last_job_id]["placement"]}
            assert da and db and not (da & db), (da, db)
            pid = cli.pids(h_a["cli"].last_job_id)[victim]
            os.kill(pid, sig.SIGKILL)
            cli.close()
            _wait(lambda: "SURVIVOR-OK" in h_a["out"].getvalue(),
                  timeout=90.0)
            with open(flag, "w"):
                pass
            res_a = _finish(h_a, timeout=120.0)
            res_b = _finish(h_b, timeout=120.0)
            # A's rc carries the victim's 128+SIGKILL; B is spotless
            assert res_a["rc"] == 137, (res_a, h_a["out"].getvalue())
            assert res_b["rc"] == 0, (res_b, h_b["out"].getvalue())
            text_a = h_a["out"].getvalue()
            assert "SURVIVOR-OK rank=0 cause=daemon total=1.0" \
                in text_a, text_a
            text_b = h_b["out"].getvalue() + h_b["err"].getvalue()
            assert "CLEAN-OK rank=0" in text_b, text_b
            for needle in ("SURVIVOR", "fault"):
                assert needle not in text_b, (needle, text_b)
        finally:
            tree.stop()
        assert dvmtree.placement_audit_failures() == []
        assert dvm_mod.queued_admission_tickets() == []
