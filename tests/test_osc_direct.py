"""Direct-map one-sided plane (osc/direct.py): sm-region-backed
windows — the direct-vs-AM byte-identical matrix, lock-word
fetch-atomics, futex passive-target locks over threads AND real
processes, mixed-topology counter splits, the shmem symmetric-heap
seam, and the region lock-word protocol itself."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from test_sm_plane import run_sm
from test_tcp import run_tcp
from zhpe_ompi_tpu import ops as zops
from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.mca import var as mca_var
from zhpe_ompi_tpu.osc.am import LOCK_EXCLUSIVE, LOCK_SHARED
from zhpe_ompi_tpu.osc.direct import DirectWindow, allocate_window
from zhpe_ompi_tpu.pt2pt import sm as sm_mod
from zhpe_ompi_tpu.runtime import spc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _matrix_prog(p):
    """The op matrix both planes must answer identically: contiguous,
    strided-source, zero-size, overlapping put-get, offset gets, every
    fetch-atomic op."""
    win = allocate_window(p, 64 * 8, np.float64)
    win.fence()
    t = 1 - p.rank
    win.put(np.arange(8.0) + p.rank, t, 0)                  # contiguous
    win.put(np.arange(32.0)[::4] * (p.rank + 1), t, 8)      # strided src
    win.put(np.zeros(0), t, 16)                             # zero-size
    win.fence()
    win.lock(t, LOCK_EXCLUSIVE)
    a = win.get(t, 4, 8)
    win.put(a * 2, t, 6)  # overlapping span [6,14) over read [4,12)
    win.unlock(t)
    win.fence()
    olds = [
        float(win.get_accumulate(np.float64(2.0), t, 20,
                                 op=zops.SUM)[0]),
        float(win.get_accumulate(np.float64(3.0), t, 20,
                                 op=zops.MAX)[0]),
        float(win.fetch_and_op(1.5, target=t, offset=21)),
        float(win.compare_and_swap(7.0, compare=0.0, target=t,
                                   offset=22)),
        float(np.asarray(win.rget_accumulate(
            np.float64(1.0), t, 23).wait(timeout=20.0))[0]),
        float(win.rget(t, 0, 4).wait(timeout=20.0)[0]),
    ]
    win.accumulate(np.full(4, float(p.rank + 1)), t, 24, op=zops.SUM)
    win.accumulate(np.full(4, 2.0), t, 24, op=zops.PROD)
    win.fence()
    got = win.get(t, 0, 32).tolist()
    win.fence()
    mine = np.asarray(win.base[:32]).tolist()
    win.free()
    return got, mine, olds


class TestDirectVsAmByteIdentical:
    """The same program, direct vs forced-AM (osc_direct=0), must
    produce byte-identical window contents, gets, and atomic
    pre-values."""

    def test_matrix_identical_across_planes(self, fresh_vars):
        d0 = spc.read("osc_direct_bytes")
        am0 = spc.read("osc_am_applied")
        fb0 = spc.read("osc_am_fallbacks")
        direct = run_sm(2, _matrix_prog, sm=True)
        d1 = spc.read("osc_direct_bytes")
        # the direct run moved direct bytes, applied nothing at the AM
        # service, and fell back on nothing (same-host, both mapped)
        assert d1 > d0
        assert spc.read("osc_am_applied") == am0
        assert spc.read("osc_am_fallbacks") == fb0
        mca_var.set_var("osc_direct", 0)
        forced = run_sm(2, _matrix_prog, sm=True)
        assert spc.read("osc_direct_bytes") == d1  # AM run: zero direct
        assert forced == direct

    def test_create_with_user_buffer_stays_am(self, fresh_vars):
        """MPI_Win_create over a USER buffer cannot be region-backed
        (the user's memory is not mappable) — it rides AM unchanged
        and counts no fallbacks (not a direct-capable window)."""
        fb0 = spc.read("osc_am_fallbacks")

        def prog(p):
            buf = np.zeros(8, np.float64)
            win = DirectWindow.create(p, buf)
            win.fence()
            win.put(np.float64(p.rank + 1), 0, offset=p.rank)
            win.fence()
            out = buf[:2].tolist() if p.rank == 0 else None
            win.free()
            return out

        assert run_sm(2, prog, sm=True)[0] == [1.0, 2.0]
        assert spc.read("osc_am_fallbacks") == fb0


class TestFetchAtomics:
    """Lock-word atomics: concurrent updates from every rank must not
    lose increments, and the pre-values must be distinct (the
    atomicity proof), all with ZERO AM service involvement."""

    def test_concurrent_accumulates_direct(self, fresh_vars):
        iters = 25
        am0 = spc.read("osc_am_applied")
        at0 = spc.read("osc_direct_atomics")

        def prog(p):
            win = allocate_window(p, 8, np.int64)
            win.fence()
            for _ in range(iters):
                win.accumulate(np.int64(1), target=0, offset=0)
            win.fence()
            out = int(win.base[0]) if p.rank == 0 else None
            win.free()
            return out

        assert run_sm(4, prog, sm=True, timeout=90.0)[0] == 4 * iters
        assert spc.read("osc_am_applied") == am0
        assert spc.read("osc_direct_atomics") - at0 >= 4 * iters

    def test_get_accumulate_prevalues_distinct(self, fresh_vars):
        def prog(p):
            win = allocate_window(p, 8, np.int64)
            win.fence()
            old = win.get_accumulate(np.int64(1), target=0, offset=0)
            win.fence()
            win.free()
            return int(old[0])

        assert sorted(run_sm(4, prog, sm=True, timeout=90.0)) == \
            [0, 1, 2, 3]

    def test_compare_and_swap_single_winner(self, fresh_vars):
        def prog(p):
            win = allocate_window(p, 8, np.int64)
            win.fence()
            old = win.compare_and_swap(p.rank + 1, compare=0, target=0)
            win.fence()
            win.free()
            return int(old)

        assert run_sm(4, prog, sm=True, timeout=90.0).count(0) == 1


class TestPassiveLocks:
    """Passive-target epochs on the region header: exclusive
    serializes read-modify-write, shared coexist, writers are not
    starved, and AM origins bridge into the same header words."""

    def test_exclusive_lock_counter_threads(self, fresh_vars):
        iters = 10

        def prog(p):
            win = allocate_window(p, 8, np.float64)
            win.fence()
            for _ in range(iters):
                win.lock(0, LOCK_EXCLUSIVE)
                v = win.get(0, 0, 1)[0]
                win.put(np.float64(v + 1), 0, 0)
                win.unlock(0)
            win.fence()
            out = float(win.base[0]) if p.rank == 0 else None
            win.free()
            return out

        assert run_sm(4, prog, sm=True, timeout=90.0)[0] == 4.0 * iters

    def test_shared_locks_coexist(self, fresh_vars):
        def prog(p):
            win = allocate_window(p, 8, np.float64)
            win.fence()
            readers = list(range(1, p.size))
            if p.rank == 0:
                for r in readers:
                    p.recv(source=r, tag=60, timeout=30.0)
                for r in readers:
                    p.send(b"go", dest=r, tag=61)
            else:
                win.lock(0, LOCK_SHARED)
                p.send(b"held", dest=0, tag=60)
                p.recv(source=0, tag=61, timeout=30.0)
                win.unlock(0)
            win.fence()
            win.free()
            return True

        assert run_sm(3, prog, sm=True) == [True] * 3

    def test_queued_writer_blocks_later_shared(self, fresh_vars):
        """Writer priority on the header: once an exclusive waiter is
        recorded (the WAITW slot), a later shared request defers until
        the writer ran."""

        def prog(p):
            win = allocate_window(p, 8, np.float64)
            win.fence()
            if p.rank == 0:
                win.lock(0, LOCK_SHARED)
                p.send(b"held", dest=1, tag=80)
                p.recv(source=1, tag=81, timeout=30.0)  # writer queued
                p.send(b"go", dest=2, tag=82)
                p.recv(source=2, tag=83, timeout=30.0)
                time.sleep(0.2)  # let reader 2's attempt hit the header
                win.unlock(0)
                win.fence()
                win.free()
                return None
            if p.rank == 1:
                p.recv(source=0, tag=80, timeout=30.0)
                granted = threading.Event()

                def writer():
                    win.lock(0, LOCK_EXCLUSIVE)
                    granted.set()
                    win.put(np.float64(1), 0, 0)
                    win.unlock(0)

                th = threading.Thread(target=writer)
                th.start()
                time.sleep(0.2)  # the WAITW slot is recorded
                p.send(b"queued", dest=0, tag=81)
                th.join(20)
                win.fence()
                win.free()
                return granted.is_set()
            p.recv(source=0, tag=82, timeout=30.0)
            p.send(b"queuing", dest=0, tag=83)
            win.lock(0, LOCK_SHARED)
            got = float(win.get(0, 0, 1)[0])
            win.unlock(0)
            win.fence()
            win.free()
            return got

        res = run_sm(3, prog, sm=True)
        assert res[1] is True
        assert res[2] == 1.0  # saw the writer's value: did not overtake

    def test_am_origin_locks_bridge_into_the_header(self, fresh_vars):
        """MIXED lock contention on one region-backed target: a
        cross-boot (AM) origin's lock excludes direct origins — the
        service grants against the same header words, and direct
        unlocks poke queued AM waiters via lock_scan."""
        iters = 8
        kw = {3: {"sm_boot_id": "feedfacef00d"}}  # rank 3 is "remote"

        def prog(p):
            win = allocate_window(p, 8, np.float64)
            win.fence()
            for _ in range(iters):
                win.lock(0, LOCK_EXCLUSIVE)
                v = win.get(0, 0, 1)[0]
                win.put(np.float64(v + 1), 0, 0)
                win.unlock(0)
            win.fence()
            out = float(win.base[0]) if p.rank == 0 else None
            # rank 3's ops all rode AM (loud: fallbacks counted)
            direct = win._direct(0) is not None
            win.free()
            return out, direct

        fb0 = spc.read("osc_am_fallbacks")
        res = run_sm(4, prog, kw, timeout=120.0)
        assert res[0][0] == 4.0 * iters
        assert res[3][1] is False and res[0][1] is True
        assert spc.read("osc_am_fallbacks") > fb0

    def test_am_waiter_granted_after_direct_holder_dies(self,
                                                        fresh_vars):
        """A queued AM-origin lock waiter must not ride out its RPC
        timeout when the DIRECT holder blocking it dies: the owner's
        classification-time recovery re-scans the service's waiter
        queue (no unlock/lock_scan message ever arrives from a
        corpse)."""
        from test_ulfm import run_tcp_ft
        from zhpe_ompi_tpu.ft import ulfm

        mca_var.set_var("ft_detector_period", 0.05)
        mca_var.set_var("ft_detector_timeout", 0.8)
        kw = {2: {"sm_boot_id": "feedfacef00d"}}  # rank 2 = AM origin

        def prog(p):
            from zhpe_ompi_tpu.core import errhandler as errh

            p.set_errhandler(errh.ERRORS_RETURN)
            win = allocate_window(p, 8, np.float64)
            win.fence()
            if p.rank == 1:
                ulfm.expect_failure(p.ft_state, 1)
                win.lock(0, LOCK_EXCLUSIVE)  # direct header hold
                assert win._direct(0) is not None
                p.send(b"holding", dest=2, tag=95)
                p.recv(source=2, tag=96, timeout=30.0)  # AM req queued
                p.sever()  # die holding: nobody ever unlocks
                return "gone"
            if p.rank == 2:
                assert win._direct(0) is None  # cross-boot: AM origin
                p.recv(source=1, tag=95, timeout=30.0)
                ulfm.expect_failure(p.ft_state, 1)
                queued = threading.Event()

                def announce():
                    time.sleep(0.5)  # the lock AM is queued by then
                    queued.set()
                    p.send(b"queued", dest=1, tag=96)

                th = threading.Thread(target=announce)
                th.start()
                t0 = time.monotonic()
                win.lock(0, LOCK_EXCLUSIVE)  # blocks at rank 0's svc
                waited = time.monotonic() - t0
                win.put(np.float64(42.0), 0, 0)
                win.unlock(0)
                th.join(5)
                p.send(b"done", dest=0, tag=97)
                return waited
            # rank 0: the window owner — just stay alive and verify
            ulfm.expect_failure(p.ft_state, 1)
            p.recv(source=2, tag=97, timeout=30.0)
            return float(win.base[0])

        res = run_tcp_ft(3, prog, sm=True, kwargs_by_rank=kw,
                         timeout=90.0)
        assert res[1] == "gone"
        # granted by the recovery-time rescan, far below the 30 s RPC
        # deadline the bug rode out
        assert res[2] < 20.0, res
        assert res[0] == 42.0

    def test_exclusive_lock_counter_real_processes(self, fresh_vars):
        """The cross-PROCESS case the lock word exists for: real OS
        ranks hammer one exclusive counter through the header."""
        worker = (
            "import sys, numpy as np\n"
            "from zhpe_ompi_tpu.pt2pt.tcp import TcpProc\n"
            "from zhpe_ompi_tpu.osc.direct import allocate_window\n"
            "from zhpe_ompi_tpu.osc.am import LOCK_EXCLUSIVE,"
            " LOCK_SHARED\n"
            "rank, n, port, iters = map(int, sys.argv[1:5])\n"
            "p = TcpProc(rank, n, coordinator=('127.0.0.1', port),\n"
            "            timeout=60.0, sm=True)\n"
            "try:\n"
            "    win = allocate_window(p, 8, np.int64)\n"
            "    win.fence()\n"
            "    assert win._direct(0) is not None\n"
            "    for _ in range(iters):\n"
            "        win.lock(0, LOCK_EXCLUSIVE)\n"
            "        v = win.get(0, 0, 1)[0]\n"
            "        win.put(np.int64(v + 1), 0, 0)\n"
            "        win.unlock(0)\n"
            "    win.fence()\n"
            "    win.lock(0, LOCK_SHARED)  # shared grant cross-process\n"
            "    shared_view = int(win.get(0, 0, 1)[0])\n"
            "    win.unlock(0)\n"
            "    assert shared_view == n * iters, shared_view\n"
            "    if rank == 0:\n"
            "        print('TOTAL', int(win.base[0]), flush=True)\n"
            "    win.free()\n"
            "finally:\n"
            "    p.close()\n"
        )
        n, iters = 2, 12
        last = None
        for _attempt in range(3):
            import socket as _socket

            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            procs = [subprocess.Popen(
                [sys.executable, "-c", worker, str(r), str(n),
                 str(port), str(iters)],
                cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            ) for r in range(n)]
            outs = []
            try:
                for pr in procs:
                    out, err = pr.communicate(timeout=120)
                    outs.append((pr.returncode, out, err))
            finally:
                for pr in procs:
                    if pr.poll() is None:
                        pr.kill()
                        pr.wait()
            if all(rc == 0 for rc, _, _ in outs):
                assert f"TOTAL {n * iters}" in outs[0][1], outs
                return
            last = outs
        raise AssertionError(f"real-process lock workers failed: {last}")


class TestMixedTopologyWindows:
    """Some peers direct, some AM — same answers, counters split."""

    def test_counter_split_and_answers(self, fresh_vars):
        kw = {0: {"sm_boot_id": "aaaaaaaaaaaa"},
              1: {"sm_boot_id": "aaaaaaaaaaaa"},
              2: {"sm_boot_id": "bbbbbbbbbbbb"},
              3: {"sm_boot_id": "bbbbbbbbbbbb"}}
        d0 = spc.read("osc_direct_puts")
        fb0 = spc.read("osc_am_fallbacks")
        am0 = spc.read("osc_am_applied")

        def prog(p):
            win = allocate_window(p, p.size * 8, np.float64)
            win.fence()
            for t in range(p.size):
                win.put(np.float64(p.rank + 1), target=t, offset=p.rank)
            win.fence()
            out = np.asarray(win.base[:p.size]).tolist()
            win.free()
            return out

        res = run_sm(4, prog, kw, timeout=90.0)
        for out in res:
            assert out == [1.0, 2.0, 3.0, 4.0]
        # 4 ranks x 4 targets: 2 direct (same-boot incl. self) + 2 AM
        assert spc.read("osc_direct_puts") - d0 == 8
        assert spc.read("osc_am_fallbacks") - fb0 == 8
        assert spc.read("osc_am_applied") - am0 == 8


class TestShmemDirectSeam:
    """The symmetric heap rides the same seam: put/get/iput/iget/
    *_nbi/AMO over a region-backed arena take the direct path — and
    the forced-AM reference answers identically."""

    @staticmethod
    def _prog(p):
        from zhpe_ompi_tpu.shmem.api import shmem_wire_pe

        pe = shmem_wire_pe(p, heap_bytes=1 << 16)
        sym = pe.shmalloc(16, np.float64)
        pe.local(sym)[...] = float(p.rank + 1)
        pe.barrier_all()
        other = 1 - p.rank
        got = pe.get(sym, other).tolist()
        pe.put(sym, np.arange(16.0) * (p.rank + 1), other)
        pe.iput(sym, np.full(4, 99.0), other, tst=2)
        pe.barrier_all()
        strided = pe.iget(sym, other, 4, sst=2).tolist()
        old = float(pe.atomic_fetch_add(sym, 0.5, pe=other, index=15))
        cas = float(pe.atomic_compare_swap(
            sym, 99.0, -1.0, pe=other, index=0))
        tgt = np.empty(16, np.float64)
        pe.get_nbi(sym, other, tgt)
        pe.put_nbi(sym, np.full(16, 5.0), other)
        pe.quiet()
        pe.barrier_all()
        mine = pe.local(sym).tolist()
        out = (got, strided, old, cas, tgt.tolist(), mine)
        pe.barrier_all()
        pe.finalize()
        return out

    def test_direct_vs_am_identical_and_counted(self, fresh_vars):
        d0 = spc.read("osc_direct_bytes")
        direct = run_sm(2, self._prog, sm=True)
        d1 = spc.read("osc_direct_bytes")
        assert d1 > d0
        mca_var.set_var("osc_direct", 0)
        forced = run_sm(2, self._prog, sm=True)
        assert spc.read("osc_direct_bytes") == d1
        assert forced == direct


class TestRevokePoisonsDirectPath:
    """A revoke landing AFTER a target was mapped must poison the
    DIRECT path too: every subsequent op re-routes to the AM path and
    raises typed Revoked — post-revoke mapped load/store silently
    mutating a poisoned window would break ULFM."""

    def test_put_after_revoke_raises(self, fresh_vars):
        from test_ulfm import run_tcp_ft
        from zhpe_ompi_tpu.osc import am as am_mod

        def prog(p):
            from zhpe_ompi_tpu.core import errhandler as errh

            p.set_errhandler(errh.ERRORS_RETURN)
            win = allocate_window(p, 64, np.float64)
            win.fence()
            t = 1 - p.rank
            win.put(np.float64(1.0), t, 0)  # mapped + direct: works
            # asserted BEFORE the fence: rank 0 revokes right after its
            # fence returns, and _direct() checks revocation ahead of
            # the memo — a slower rank asserting post-fence races the
            # revoke's arrival and sees None
            assert win._direct(t) is not None
            win.fence()
            if p.rank == 0:
                p.revoke(am_mod.AM_CID)
            deadline = time.monotonic() + 10
            while not p.ft_state.is_revoked(am_mod.AM_CID) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            try:
                win.put(np.float64(2.0), t, 0)
                return "silent"
            except errors.Revoked:
                return "revoked"

        assert run_tcp_ft(2, prog, sm=True) == ["revoked", "revoked"]


class TestRpcTypedFailure:
    """Satellite bugfix: osc/am.py's RPC path classifies known-failed
    targets as typed ProcFailed at ISSUE time and keeps the blocked
    wait failure-aware — never a bare 30 s timeout."""

    def test_known_failed_target_raises_at_issue(self, fresh_vars):
        from test_ulfm import run_tcp_ft
        from zhpe_ompi_tpu.ft import ulfm

        def prog(p):
            win = allocate_window(p, 64, np.float64)
            win.fence()
            if p.rank == 0:
                ulfm.expect_failure(p.ft_state, 1)
                p.ft_state.mark_failed(1, cause="transport")
                t0 = time.monotonic()
                with pytest.raises(errors.ProcFailed):
                    win.get(1, 0, 4)
                took = time.monotonic() - t0
                assert took < 5.0, f"bare-timeout path took {took:.1f}s"
                return "typed"
            time.sleep(2.5)  # stay alive while rank 0 asserts
            return "peer"

        res = run_tcp_ft(2, prog, sm=False)
        assert res[0] == "typed"

    def test_wait_classifies_mid_rpc(self, fresh_vars):
        from test_ulfm import run_tcp_ft
        from zhpe_ompi_tpu.ft import ulfm

        def prog(p):
            from zhpe_ompi_tpu.core import errhandler as errh

            p.set_errhandler(errh.ERRORS_RETURN)
            win = allocate_window(p, 64, np.float64)
            win.fence()
            if p.rank == 0:
                ulfm.expect_failure(p.ft_state, 1)
                # peer's service is already down when this arrives
                p.recv(source=1, tag=7, timeout=30.0)

                def classify():
                    time.sleep(0.8)
                    p.ft_state.mark_failed(1, cause="transport")

                th = threading.Thread(target=classify)
                th.start()
                t0 = time.monotonic()
                with pytest.raises(errors.ProcFailed):
                    win.get(1, 0, 4)  # blocked: the target never answers
                took = time.monotonic() - t0
                th.join(5)
                assert took < 10.0, f"wait was deadline-only: {took:.1f}s"
                return "typed"
            # wedge the TARGET side of the RPC: the service loop stops
            # consuming (the sockets stay up — no transport-death
            # signal), so only the failure-aware wait unblocks the
            # origin
            win.svc.shutdown()
            p.send(b"wedged", dest=0, tag=7)
            time.sleep(4.0)  # stay alive while rank 0 asserts
            return "wedged"

        res = run_tcp_ft(2, prog, sm=False)
        assert res[0] == "typed"


class TestRegionProtocol:
    """The region lock word below the window API: cross-mapping
    atomicity, crash recovery, waiting-writer cleanup, and the flock
    fallback when the native kernel library is absent."""

    def _pair(self):
        seg = sm_mod.SmSegment(0, 4, on_frame=lambda s, f: None)
        region = seg.alloc_rma_region(4096)
        return seg, region

    def test_atomicity_across_mappings(self):
        seg, r = self._pair()
        try:
            m2 = sm_mod.RmaMapping(r.path, my_rank=1)

            def worker(m):
                for _ in range(400):
                    with m.atomic():
                        m.view(np.int64)[0] += 1

            ts = [threading.Thread(target=worker, args=(m,))
                  for m in (r, m2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
            assert int(r.view(np.int64)[0]) == 800
            m2.close()
        finally:
            seg.close()

    def test_flock_fallback_is_still_atomic(self):
        seg, r = self._pair()
        try:
            m2 = sm_mod.RmaMapping(r.path, my_rank=1)
            r._use_native = m2._use_native = False  # force flock path

            def worker(m):
                for _ in range(200):
                    with m.atomic():
                        m.view(np.int64)[0] += 1

            ts = [threading.Thread(target=worker, args=(m,))
                  for m in (r, m2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
            assert int(r.view(np.int64)[0]) == 400
            m2.close()
        finally:
            seg.close()

    def test_flock_fallback_honors_abort(self):
        """The degraded (no-native-library) mutex must keep the same
        abort/stall contract as the lock word: a wedged holder cannot
        hang a survivor past its classification hook."""
        seg, r = self._pair()
        try:
            m2 = sm_mod.RmaMapping(r.path, my_rank=1)
            r._use_native = m2._use_native = False
            entered = threading.Event()
            release = threading.Event()

            def holder():
                with m2.atomic():
                    entered.set()
                    release.wait(10)

            th = threading.Thread(target=holder)
            th.start()
            assert entered.wait(5)
            calls = []

            def abort():
                calls.append(1)
                if len(calls) > 3:
                    raise errors.ProcFailed("holder classified dead")

            with pytest.raises(errors.ProcFailed):
                with r.atomic(abort=abort, timeout=30.0):
                    pass
            release.set()
            th.join(5)
            m2.close()
        finally:
            seg.close()

    def test_recover_dead_releases_holder_and_mutex(self):
        seg, r = self._pair()
        try:
            m3 = sm_mod.RmaMapping(r.path, my_rank=3)
            m3.lock(3, exclusive=True)
            # simulate dying INSIDE the lock word's critical section too
            if r._use_native:
                assert r._amo32(sm_mod._RH_MUTEX, sm_mod._AMO_CAS,
                                value=4, compare=0) == 0
            assert r.recover_dead(3) is True
            r.lock(0, exclusive=True, timeout=5.0)
            r.unlock(0)
            assert r.recover_dead(3) is False  # idempotent
            m3.close()
        finally:
            seg.close()

    def test_shared_count_recovered(self):
        seg, r = self._pair()
        try:
            m1 = sm_mod.RmaMapping(r.path, my_rank=1)
            m1.lock(1, exclusive=False)
            m3 = sm_mod.RmaMapping(r.path, my_rank=3)
            m3.lock(3, exclusive=False)
            r.recover_dead(3)
            # one reader remains: exclusive still blocked
            got = []
            th = threading.Thread(
                target=lambda: (r.lock(0, True, timeout=10.0),
                                got.append(1), r.unlock(0)))
            th.start()
            time.sleep(0.2)
            assert not got
            m1.unlock(1)
            th.join(10)
            assert got == [1]
            m1.close()
            m3.close()
        finally:
            seg.close()

    def test_abandoned_writer_wait_cleans_its_slot(self):
        seg, r = self._pair()
        try:
            m1 = sm_mod.RmaMapping(r.path, my_rank=1)
            m1.lock(1, exclusive=False)
            with pytest.raises(errors.InternalError):
                r.lock(0, exclusive=True, timeout=0.3)
            # the ghost WAITW slot must not starve later readers
            m2 = sm_mod.RmaMapping(r.path, my_rank=2)
            m2.lock(2, exclusive=False, timeout=2.0)
            m2.unlock(2)
            m1.unlock(1)
            m1.close()
            m2.close()
        finally:
            seg.close()


class TestPerPeerFiles:
    """Satellite: layout v3 — physically separate per-peer files bound
    the VIRTUAL reservation; the audit and zero-orphan gates cover
    ring and region files alike."""

    def test_control_file_is_header_only(self, fresh_vars):
        seg = sm_mod.SmSegment(0, 512, on_frame=lambda s, f: None)
        try:
            # v2 reserved size x worst-class span (gigabytes at this
            # universe size); v3's control file is the O(size) header
            assert os.path.getsize(seg.path) == seg._hdr
            ring = int(mca_var.get("sm_ring_bytes", 4 << 20))
            assert os.path.getsize(seg.path) < ring
        finally:
            seg.close()

    def test_ring_files_materialize_and_unlink(self, fresh_vars):
        seg = sm_mod.SmSegment(0, 4, on_frame=lambda s, f: None)
        rpath = seg._ring_path(2)
        try:
            assert not os.path.exists(rpath)
            tx = sm_mod.SmSender(seg.name, src_rank=2, dest_rank=0)
            try:
                assert os.path.exists(rpath)
                assert os.path.getsize(rpath) == sm_mod._ring_span(
                    tx.nslots, tx.slot_bytes)
            finally:
                tx.close()
        finally:
            seg.close()
        assert not os.path.exists(rpath)
        assert sm_mod.segment_audit_failures() == []

    def test_sever_leaves_files_close_sweeps(self, fresh_vars):
        seg = sm_mod.SmSegment(0, 2, on_frame=lambda s, f: None)
        region = seg.alloc_rma_region(1024)
        tx = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
        tx.close()
        rpath = seg._ring_path(1)
        seg.sever()
        # a crash honors no invariants: everything stays on disk
        assert os.path.exists(seg.path)
        assert os.path.exists(rpath)
        assert os.path.exists(region.path)
        seg.close()  # the harness close owns the sweep
        assert not os.path.exists(seg.path)
        assert not os.path.exists(rpath)
        assert not os.path.exists(region.path)
        assert sm_mod.orphaned_ring_files() == []

    def test_window_free_unlinks_its_region(self, fresh_vars):
        paths = []

        def prog(p):
            win = allocate_window(p, 256, np.float64)
            win.fence()
            if win._region is not None:
                paths.append(win._region.path)
                assert os.path.exists(win._region.path)
            win.free()
            p.barrier()
            return True

        assert run_sm(2, prog, sm=True) == [True, True]
        assert len(paths) == 2
        for path in paths:
            assert not os.path.exists(path), path


# ----------------------------------------------- stage handoff (PR 20)


def _handoff_prog(p, epochs=3, width=8):
    """Producer (rank 0) streams `epochs` KV-shaped payloads to the
    consumer (rank 1) through one persistent StageHandoff."""
    from zhpe_ompi_tpu.osc.direct import StageHandoff

    win = allocate_window(p, width * 8, np.float64)
    win.fence()
    hoff = StageHandoff(win, producer=0, consumer=1)
    got = []
    for e in range(epochs):
        if p.rank == 1:
            hoff.post()
            hoff.wait()
            got.append(np.asarray(hoff.recv(0, width)).tolist())
        else:
            hoff.start()
            hoff.put(np.full(width, float(100 + e)), 0)
            hoff.complete()
    p.barrier()
    direct = hoff.direct
    win.free()
    return direct, hoff.epochs, got


class TestStageHandoff:
    """The RMA stage-handoff acceptance gate (PR 20): same-host
    pipeline epochs ride the region doorbell — the doorbell counters
    move while the AM apply counter stays FLAT — and the forced-AM
    twin answers the identical payload stream."""

    def test_doorbell_epochs_direct_am_flat(self, fresh_vars):
        posts0 = spc.read("osc_doorbell_posts")
        comps0 = spc.read("osc_doorbell_completes")
        am0 = spc.read("osc_am_applied")
        fb0 = spc.read("osc_am_fallbacks")
        res = run_sm(2, _handoff_prog, sm=True)
        assert res[0] == (True, 3, [])  # producer: direct, 3 epochs
        direct, epochs, got = res[1]
        assert direct and epochs == 3
        assert got == [[float(100 + e)] * 8 for e in range(3)]
        # the gate: doorbells rang, the AM service applied NOTHING,
        # nothing fell back
        assert spc.read("osc_doorbell_posts") - posts0 == 3
        assert spc.read("osc_doorbell_completes") - comps0 == 3
        assert spc.read("osc_am_applied") == am0
        assert spc.read("osc_am_fallbacks") == fb0

    def test_forced_am_same_payloads_plain(self, fresh_vars):
        """osc_direct=0: no regions anywhere, so the handshake pins
        BOTH sides to AM PSCW as a plain AM window — zero doorbell
        rings, identical payloads, and NOT counted as a fallback
        (only direct-capable windows routing to AM are loud)."""
        mca_var.set_var("osc_direct", 0)
        posts0 = spc.read("osc_doorbell_posts")
        fb0 = spc.read("osc_am_fallbacks")
        res = run_sm(2, _handoff_prog, sm=True)
        assert res[0][0] is False and res[1][0] is False
        assert res[1][2] == [[float(100 + e)] * 8 for e in range(3)]
        assert spc.read("osc_doorbell_posts") == posts0
        assert spc.read("osc_am_fallbacks") == fb0

    def test_unmappable_peer_pins_both_to_am_loud(self, fresh_vars):
        """One side of a direct-capable window cannot map the peer:
        the handshake pins BOTH sides to AM PSCW (no split-brain
        schedule) and the reroute is LOUD on each side."""
        from zhpe_ompi_tpu.osc.direct import StageHandoff

        def prog(p, epochs=3, width=8):
            win = allocate_window(p, width * 8, np.float64)
            win.fence()
            if p.rank == 0:
                win._direct = lambda target: None  # producer can't map
            hoff = StageHandoff(win, producer=0, consumer=1)
            got = []
            for e in range(epochs):
                if p.rank == 1:
                    hoff.post()
                    hoff.wait()
                    got.append(np.asarray(hoff.recv(0, width)).tolist())
                else:
                    hoff.start()
                    hoff.put(np.full(width, float(100 + e)), 0)
                    hoff.complete()
            p.barrier()
            direct = hoff.direct
            win.free()
            return direct, got

        posts0 = spc.read("osc_doorbell_posts")
        fb0 = spc.read("osc_am_fallbacks")
        res = run_sm(2, prog, sm=True)
        # the consumer COULD map (its own region) — the handshake still
        # pins it to AM so neither side parks on a doorbell the other
        # never rings
        assert res[0][0] is False and res[1][0] is False
        assert res[1][1] == [[float(100 + e)] * 8 for e in range(3)]
        assert spc.read("osc_doorbell_posts") == posts0
        assert spc.read("osc_am_fallbacks") >= fb0 + 2

    def test_handoff_role_verbs_enforced(self, fresh_vars):
        from zhpe_ompi_tpu.osc.direct import StageHandoff

        def prog(p):
            win = allocate_window(p, 64, np.float64)
            win.fence()
            hoff = StageHandoff(win, producer=0, consumer=1)
            wrong = hoff.post if p.rank == 0 else hoff.start
            with pytest.raises(errors.WinError):
                wrong()
            with pytest.raises(errors.WinError):
                StageHandoff(win, producer=1, consumer=1)
            p.barrier()
            win.free()
            return True

        assert run_sm(2, prog, sm=True) == [True, True]

    def test_pipeline_schedule_chains_stages(self, fresh_vars):
        """3-stage chain: each epoch's activation flows 0 -> 1 -> 2
        through per-pair doorbells; middle rank holds BOTH ends."""
        from zhpe_ompi_tpu.osc.direct import pipeline_schedule

        def prog(p):
            win = allocate_window(p, 64, np.float64)
            win.fence()
            sched = pipeline_schedule(win)
            out = []
            for e in range(2):
                val = float(10 * (e + 1))
                if "up" in sched:
                    sched["up"].post()
                    sched["up"].wait()
                    val = float(np.asarray(sched["up"].recv(0, 1))[0]) + 1
                    out.append(val)
                if "down" in sched:
                    sched["down"].start()
                    sched["down"].put(np.float64(val), 0)
                    sched["down"].complete()
            p.barrier()
            keys = sorted(sched)
            win.free()
            return keys, out

        res = run_sm(3, prog, sm=True)
        assert res[0] == (["down"], [])
        assert res[1] == (["down", "up"], [11.0, 21.0])
        assert res[2] == (["up"], [12.0, 22.0])

    def test_window_bcast_direct_payload(self, fresh_vars):
        """Weight broadcast on the RMA plane: every rank pulls the
        root's region; the payload rides direct gets (osc_direct_bytes
        moves, osc_am_applied flat on the same-host mesh)."""
        from zhpe_ompi_tpu.osc.direct import window_bcast

        am0 = spc.read("osc_am_applied")
        d0 = spc.read("osc_direct_bytes")

        def prog(p):
            win = allocate_window(p, 16 * 8, np.float64)
            win.fence()
            w = np.arange(16.0) if p.rank == 0 else None
            got = window_bcast(win, w, root=0)
            p.barrier()
            win.free()
            return np.asarray(got).tolist()

        res = run_sm(2, prog, sm=True)
        assert res[0] == res[1] == np.arange(16.0).tolist()
        assert spc.read("osc_direct_bytes") > d0
        assert spc.read("osc_am_applied") == am0
