"""Host-plane collectives: the full collective set over endpoint
send/recv, for universe (thread) and TCP (socket/DCN) ranks.

The reference's collective algorithms run over the PML regardless of which
BTL carries the bytes — ``coll_base_allreduce.c:130-225`` is written in
``MCA_PML_CALL(send/recv)`` and therefore works over tcp for free.  This
module restores that layering property for the host plane: every function
takes any endpoint exposing ``rank``/``size``/``send``/``recv``/
``sendrecv`` (universe ``RankContext``, ``TcpProc``) and speaks only that
surface — so a DCN-connected job can allreduce over sockets exactly like a
thread universe.  (The device plane keeps its own XLA-native algorithms in
``coll/tpu.py``/``coll/algorithms.py``; this is the control/host plane the
reference runs EVERYTHING on.)

The same layering is what hands these algorithms the shared-memory fast
path for free: ``TcpProc.send`` dispatches per peer (self → sm → tcp),
so the ring allreduce's ``(idx, block)`` chunks and the pipeline
bcast/reduce segments of same-host ranks ride the mmap rings of
``pt2pt/sm.py`` with zero changes here — the coll-rides-the-PML property
doing exactly the work the reference's BTL selection does (benchmarked
by ``osu_zmpi --plane sm``, regression-gated by
``tests/test_sm_plane.py::TestTransportMatrix``).

Algorithm choices mirror coll_base (re-derived, not transliterated):
binomial bcast/reduce (``coll_base_bcast.c``, in-order linear reduce for
non-commutative ops), recursive-doubling allreduce with the non-power-of-2
pre/post fold (``coll_base_allreduce.c:130-225``), ring allgather
(``coll_base_allgather.c``), pairwise-exchange alltoall
(``coll_base_alltoall.c``), linear scan/exscan.

Payloads are arbitrary Python/numpy objects; reductions use the framework
``Op`` combine (``a ⊕ b``), applied elementwise through lists/tuples so a
list-of-blocks reduces blockwise (what reduce_scatter needs).  Operand
order is preserved for non-commutative ops: every combine keeps the
lower-rank contribution on the left.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..utils.payload import payload_size_estimate as payload_bytes

_stream = mca_output.open_stream("coll_host")

mca_var.register(
    "host_coll_large_msg", 256 * 1024,
    "Array payload size (bytes) above which host-plane collectives switch "
    "to bandwidth-optimal algorithms (ring allreduce).  Provenance: the "
    "committed pt2pt ladder (benchmarks/baseline_cpu8.json) crosses from "
    "latency- to bandwidth-dominated between 16KB and 256KB one-way",
    type=int,
)
mca_var.register(
    "coll_han_enable", "auto",
    "Hierarchical (han) host collectives: auto = two-level schedules "
    "when the modex-derived locality topology has >= 2 same-host groups "
    "with >= 2 members each; on = forced (degenerate topologies fall "
    "back to the flat algorithms loudly via han_flat_fallbacks); off = "
    "always flat.  A 'han' line in coll_tuned_dynamic_rules requests "
    "the hierarchical path per op/size like a forced enable",
    enum=("auto", "on", "off"),
)
mca_var.register(
    "coll_tuned_dynamic_rules", "",
    "Path to a dynamic decision-rules file "
    "(<op> <comm_size_min> <msg_bytes_min> <algorithm> per line); the "
    "host-plane han decision honors 'han' lines, so the var registers "
    "with the host collectives too (the tuned component re-registers "
    "idempotently with its own surface)",
)


# the collectives with a hierarchical (coll/han) two-level schedule —
# the canonical set: the dispatch seam below, coll/han.py's decision,
# and coll/tuned.py's rules-line validation all read THIS name
HAN_OPS = frozenset((
    "allreduce", "bcast", "reduce", "barrier", "allgather",
    "reduce_scatter", "alltoall", "alltoallv",
))


def _han_route(ctx, opname: str, payload: Any = None, op=None):
    """The coll/han dispatch seam (the comm_select interposition point
    of the host plane): returns the han module when this collective
    should take the hierarchical two-level schedule, None for the flat
    algorithms below.  Kept UPSTREAM of the algorithm bodies so han's
    own phases — GroupView sub-endpoints, marked ``_han_subview`` —
    re-enter the flat paths unconditionally (no recursive hierarchy)."""
    if getattr(ctx, "_han_subview", False):
        return None
    mode = str(mca_var.get("coll_han_enable", "auto"))
    if mode == "off":
        return None
    if mode == "auto" and getattr(ctx, "size", 1) < 4 \
            and not mca_var.get("coll_tuned_dynamic_rules", ""):
        # cheap pre-topology out: < 4 ranks cannot hold two >=2-member
        # groups, and no rules file means nothing can request han
        return None
    from . import han as han_mod

    if han_mod.wants_han(ctx, opname, payload, op, mode):
        return han_mod
    return None


# Flat host-plane algorithms a tuned decision table may name per op —
# the ztune candidate surface (besides "han", which routes through
# _han_route/wants_han above).  Every name here maps onto an existing
# eligibility-guarded body below; a rule naming one for an INELIGIBLE
# call (non-commutative op, scalar payload) degrades loudly to the
# builtin decision, never to a wrong answer.
HOST_RULE_ALGS = {
    "allreduce": ("recursive_doubling", "ring"),
    "reduce": ("binomial", "pipeline"),
    "alltoall": ("pairwise", "bruck"),
    "alltoallv": ("pairwise",),
}


def _rule_alg(ctx, opname: str, payload: Any = None) -> "str | None":
    """The host plane's tuned-table consult (the coll/ztable.py ladder:
    store-served ztune table, then the rules file), topology-keyed from
    this endpoint's locality probe.  Returns a flat algorithm name from
    ``HOST_RULE_ALGS`` or None — builtin thresholds and the auto han
    decision apply.  "han" rules return None HERE: the _han_route seam
    owns them (via han's ``_rule_requests_han``)."""
    if getattr(ctx, "_han_subview", False):
        return None  # phase traffic re-enters the builtin decisions
    from . import ztable

    if not ztable.active():
        return None
    from . import han as han_mod

    algname = ztable.resolve_rule(
        opname, getattr(ctx, "size", 0), payload_bytes(payload),
        han_mod.topology_key(ctx),
    )
    if algname is not None and algname in HOST_RULE_ALGS.get(opname, ()):
        return algname
    return None

# Reserved context id for host-plane collective traffic (the
# MCA_COLL_BASE_TAG_* space; barrier already uses cid 0x7FFF).
COLL_CID = 0x7FFD

# Per-operation base tags (the MCA_COLL_BASE_TAG_* table).
TAG_BCAST = 0x7E01
TAG_REDUCE = 0x7E02
TAG_ALLREDUCE = 0x7E03
TAG_ALLGATHER = 0x7E04
TAG_GATHER = 0x7E05
TAG_SCATTER = 0x7E06
TAG_ALLTOALL = 0x7E07
TAG_SCAN = 0x7E08
TAG_RSCAT = 0x7E09
TAG_GATHERV = 0x7E0B
TAG_SCATTERV = 0x7E0C
TAG_ALLGATHERV = 0x7E0D
TAG_ALLTOALLV = 0x7E0E
TAG_NEIGHBOR = 0x7E0F


def _next_tag(ctx, base: int) -> int:
    """Instance tag = base kind tag + a per-endpoint collective sequence
    number.

    MPI requires every rank to issue collectives on a communicator in the
    same program order, so the k-th collective gets the same tag on every
    rank — and two overlapping collectives (a nonblocking one outstanding
    across a blocking one, two outstanding nonblocking ones progressed in
    different orders) can never cross-match, even though their rounds
    interleave arbitrarily on the wire.  Base tags alone are NOT enough:
    round numbering differs per rank (a fold rank's round 1 is a
    non-fold rank's round 0), so posted-recv order need not match send
    order across instances.  The reference solves this the same way via
    libnbc's schedule tags (nbc.c `schedule->tag`)."""
    seq = getattr(ctx, "_coll_seq", 0)
    ctx._coll_seq = seq + 1
    return ((seq % 0x8000) << 16) | base


def _combine(op, a: Any, b: Any) -> Any:
    """a ⊕ b, mapped elementwise through lists/tuples (blockwise reduce)."""
    if isinstance(a, (list, tuple)):
        if not isinstance(b, (list, tuple)) or len(a) != len(b):
            raise errors.ArgError("blockwise reduce of mismatched sequences")
        return type(a)(_combine(op, x, y) for x, y in zip(a, b))
    return op(a, b)


def _ordered(op, lo_val, hi_val):
    """Combine preserving rank order: lo ⊕ hi."""
    return _combine(op, lo_val, hi_val)


# -------------------------------------------------------------- broadcast


mca_var.register(
    "host_coll_segment", 64 * 1024,
    "Segment size (bytes) of pipelined host-plane collectives (the "
    "reference's per-algorithm segsize knobs)",
    type=int,
)
mca_var.register(
    "host_reduce_algorithm", "auto",
    "Host-plane reduce algorithm: auto (binomial tree; in-order linear "
    "for non-commutative ops) or pipeline (chain-pipelined segments for "
    "large commutative array reductions)",
    enum=("auto", "pipeline"),
)
mca_var.register(
    "host_bcast_algorithm", "binomial",
    "Host-plane bcast algorithm: binomial (latency-optimal tree) or "
    "pipeline (chain-pipelined segments, bandwidth-optimal for large "
    "arrays).  Unlike MPI, non-root ranks don't pass a count here, so "
    "size-based auto-selection has no size to look at — selection is "
    "explicit, by this var or the algorithm argument",
    enum=("binomial", "pipeline"),
)


def bcast(ctx, obj: Any = None, root: int = 0,
          algorithm: str | None = None) -> Any:
    """Broadcast; ``obj`` is significant at root only; every rank
    returns the payload.

    binomial: coll_base_bcast.c:207-259 shape.  pipeline:
    coll_base_bcast.c:273 shape — the payload streams through a
    root-rotated chain in ``host_coll_segment``-byte pieces so link i
    forwards piece k while receiving piece k+1 (requires an ndarray
    payload at root; every rank must select the same algorithm)."""
    alg = algorithm or mca_var.get("host_bcast_algorithm", "binomial")
    if alg not in ("binomial", "pipeline"):
        raise errors.ArgError(
            f"unknown bcast algorithm {alg!r} (binomial|pipeline)"
        )
    if algorithm is None and alg == "binomial":
        # explicit algorithm selection — argument OR a non-default
        # host_bcast_algorithm var — outranks the topology layer
        # (forced algorithms are the user's responsibility, as in
        # tuned).  The payload is significant at root ONLY, so the
        # size-matched dynamic-rules check sees 0 bytes on every rank
        # (a root-only size would split the decision across ranks);
        # han bcast rules therefore use msg_bytes_min 0.
        han = _han_route(ctx, "bcast", None)
        if han is not None:
            return han.bcast(ctx, obj, root)
    size, rank = ctx.size, ctx.rank
    if size == 1:
        return obj
    if alg == "pipeline":
        return _bcast_pipeline(ctx, obj, root)
    tag = _next_tag(ctx, TAG_BCAST)
    vrank = (rank - root) % size
    # receive from parent (clear lowest set bit of vrank)
    if vrank != 0:
        parent = ((vrank & (vrank - 1)) + root) % size
        obj = ctx.recv(parent, tag=tag, cid=COLL_CID)
    # forward to children: set bits above the lowest set bit
    mask = 1
    while mask < size:
        if vrank & (mask - 1) == 0 and vrank | mask != vrank:
            child = vrank | mask
            if child < size:
                ctx.send(obj, (child + root) % size, tag=tag,
                         cid=COLL_CID)
        mask <<= 1
    return obj


def _bcast_pipeline(ctx, obj: Any, root: int) -> Any:
    """Chain-pipelined broadcast: root-rotated chain, segment stream.
    2(p-1)+nseg-1 message steps vs binomial's log2(p) — wins when
    nbytes/bandwidth dominates latency (large arrays over sockets)."""
    from ..pt2pt.requests import wait_all

    size, rank = ctx.size, ctx.rank
    vrank = (rank - root) % size
    succ = (rank + 1) % size
    pred = (rank - 1) % size
    tag = _next_tag(ctx, TAG_BCAST)
    last = vrank == size - 1
    if vrank == 0:
        # only the root's segment size matters: receivers take nseg from
        # the header and reassemble whatever piece sizes arrive
        seg = max(1, int(mca_var.get("host_coll_segment", 64 * 1024)))
        arr = np.ascontiguousarray(obj)
        flat = arr.reshape(-1).view(np.uint8)
        nseg = max(1, -(-flat.size // seg))
        ctx.send((arr.dtype.str, arr.shape, nseg), succ, tag=tag,
                 cid=COLL_CID)
        # segment VIEWS: the zero-copy wire path references them as
        # out-of-band buffers; root never mutates obj mid-broadcast, and
        # the thread plane's eager/handoff copy preserves buffer reuse
        reqs = [
            ctx.isend(flat[i * seg : (i + 1) * seg], succ,
                      tag=tag, cid=COLL_CID)
            for i in range(nseg)
        ]
        wait_all(reqs)
        return obj
    dtype_str, shape, nseg = ctx.recv(pred, tag=tag, cid=COLL_CID)
    if not last:
        ctx.send((dtype_str, shape, nseg), succ, tag=tag, cid=COLL_CID)
    dt = np.dtype(dtype_str)
    # single preallocated buffer: pieces fill slices as they arrive (a
    # parts-list + concatenate would hold ~2x the payload at peak, on
    # exactly the large-array workloads this algorithm targets)
    flat = np.empty(int(np.prod(shape or (1,))) * dt.itemsize, np.uint8)
    pos, reqs = 0, []
    for _ in range(nseg):
        piece = ctx.recv(pred, tag=tag, cid=COLL_CID)
        raw = np.asarray(piece, np.uint8).reshape(-1)
        flat[pos : pos + raw.size] = raw
        pos += raw.size
        if not last:
            # forward while the next segment is still in flight — the
            # pipeline overlap that makes the chain bandwidth-optimal
            reqs.append(ctx.isend(piece, succ, tag=tag, cid=COLL_CID))
    wait_all(reqs)
    if pos != flat.size:
        raise errors.TruncateError(
            f"pipelined bcast: got {pos}B of {flat.size}B"
        )
    return flat.view(dt).reshape(shape)


# ----------------------------------------------------------------- reduce


def _reduce_linear(ctx, value, op, root, tag):
    """In-order linear reduce: rank order is preserved exactly, so this is
    the non-commutative path (the reference's in-order variants)."""
    size, rank = ctx.size, ctx.rank
    if rank != root:
        ctx.send(value, root, tag=tag, cid=COLL_CID)
        return None
    acc = None
    for r in range(size):
        contrib = value if r == root else ctx.recv(r, tag=tag, cid=COLL_CID)
        acc = contrib if acc is None else _ordered(op, acc, contrib)
    return acc


def _reduce_pipeline(ctx, value, op, root: int):
    """Chain-pipelined reduce (coll_base_reduce.c:409 pipeline shape):
    segments flow down a root-rotated chain, each hop combining its own
    slice before forwarding — bandwidth-optimal for large arrays.
    Chain combine order is vrank-descending onto ascending, which only
    equals rank order for commutative ops; callers route non-commutative
    ops to the in-order variants."""
    from ..pt2pt.requests import wait_all

    size, rank = ctx.size, ctx.rank
    vrank = (rank - root) % size
    # chain orientation: segments flow from the far end (vrank size-1)
    # toward the root (vrank 0)
    toward_root = (rank - 1) % size
    away = (rank + 1) % size
    tag = _next_tag(ctx, TAG_REDUCE)
    arr = np.ascontiguousarray(value)
    flat = arr.reshape(-1)
    if vrank == size - 1:
        # the stream originator decides the geometry and announces it in
        # a header (the bcast-pipeline discipline): per-rank
        # host_coll_segment or dtype skew must not desynchronize the
        # chain's message counts
        seg = max(1, int(mca_var.get("host_coll_segment", 64 * 1024)))
        elems = max(1, -(-seg // max(arr.dtype.itemsize, 1)))
        nseg = max(1, -(-flat.size // elems))
        ctx.send(("hdr", arr.dtype.str, arr.shape, nseg, elems),
                 toward_root, tag=tag, cid=COLL_CID)
        # segment views (see _bcast_pipeline): the originator only reads
        # flat until wait_all returns, so the per-segment copy was waste
        reqs = [
            ctx.isend(flat[i * elems : (i + 1) * elems],
                      toward_root, tag=tag, cid=COLL_CID)
            for i in range(nseg)
        ]
        wait_all(reqs)
        return None
    header = ctx.recv(away, tag=tag, cid=COLL_CID)
    if header[0] == "err":
        # upstream congruence failure: poison the rest of the chain so
        # every downstream rank raises instead of blocking on segments
        # that will never come
        if vrank != 0:
            ctx.send(header, toward_root, tag=tag, cid=COLL_CID)
        raise errors.TypeError_(f"pipelined reduce: {header[1]}")
    _hdr, dtype_str, shape, nseg, elems = header
    if tuple(shape) != arr.shape or np.dtype(dtype_str) != arr.dtype:
        reason = (
            f"payload mismatch — local {arr.shape}/{arr.dtype} vs chain "
            f"{tuple(shape)}/{dtype_str} (reduce requires congruent "
            "arrays on every rank)"
        )
        if vrank != 0:
            ctx.send(("err", reason), toward_root, tag=tag, cid=COLL_CID)
        # NOTE: ranks upstream of this one (toward the originator) may
        # still block in their segment sends until timeout — an
        # erroneous program; the err header bounds the damage downstream
        raise errors.TypeError_(f"pipelined reduce: {reason}")
    if vrank != 0:
        ctx.send(header, toward_root, tag=tag, cid=COLL_CID)
    # only the root materializes a result buffer; intermediates forward
    out = np.empty_like(flat) if vrank == 0 else None
    reqs = []
    for i in range(nseg):
        sl = slice(i * elems, (i + 1) * elems)
        contrib = ctx.recv(away, tag=tag, cid=COLL_CID)
        # combine own slice with the accumulated higher-vrank slice,
        # keeping the lower contribution on the left
        merged = _combine(op, flat[sl], np.asarray(contrib))
        if vrank == 0:
            out[sl] = merged
        else:
            reqs.append(ctx.isend(merged, toward_root, tag=tag,
                                  cid=COLL_CID))
    wait_all(reqs)
    if vrank != 0:
        return None
    return out.reshape(arr.shape)


def reduce(ctx, value: Any, op, root: int = 0,
           algorithm: str | None = None) -> Any:
    """Reduce to root; binomial tree for commutative ops, in-order linear
    otherwise; ``algorithm="pipeline"`` selects the chain-pipelined
    large-array variant (commutative ops + ndarray payloads).  Result
    significant at root (others return None)."""
    size, rank = ctx.size, ctx.rank
    alg = algorithm or mca_var.get("host_reduce_algorithm", "auto")
    if alg not in ("auto", "pipeline"):
        raise errors.ArgError(
            f"unknown reduce algorithm {alg!r} (auto|pipeline)"
        )
    if algorithm is None and alg == "auto":
        # tuned-table consult first (see allreduce): an explicit rule
        # outranks the auto han decision; explicit user/var algorithm
        # selection above outranks BOTH
        ruled = _rule_alg(ctx, "reduce", value)
        if ruled == "pipeline":
            if getattr(op, "commute", True):
                alg = "pipeline"
            else:
                mca_output.verbose(
                    2, _stream,
                    "tuned rule names pipeline reduce but the op is "
                    "non-commutative (chain order != rank order); "
                    "builtin decision applies",
                )
                ruled = None
        if ruled is None:
            han = _han_route(ctx, "reduce", value, op)
            if han is not None:
                return han.reduce(ctx, value, op, root)
    if size == 1:
        return value
    if alg == "pipeline":
        if not getattr(op, "commute", True):
            raise errors.ArgError(
                "pipeline reduce requires a commutative op (chain order "
                "!= rank order); use the default in-order path"
            )
        return _reduce_pipeline(ctx, value, op, root)
    tag = _next_tag(ctx, TAG_REDUCE)
    if not getattr(op, "commute", True):
        return _reduce_linear(ctx, value, op, root, tag)
    vrank = (rank - root) % size
    acc = value
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            ctx.send((vrank, acc), parent, tag=tag, cid=COLL_CID)
            return None
        child = vrank | mask
        if child < size:
            cvrank, contrib = ctx.recv(
                (child + root) % size, tag=tag, cid=COLL_CID
            )
            # child subtree covers higher vranks: acc ⊕ contrib
            acc = _ordered(op, acc, contrib)
        mask <<= 1
    return acc


# -------------------------------------------------------------- allreduce


def _allreduce_ring(ctx, value: np.ndarray, op, tag: int) -> np.ndarray:
    """Ring allreduce (reduce-scatter + allgather,
    coll_base_allreduce.c:341 shape): 2(p-1) steps moving ~2·nbytes/p per
    step — the bandwidth-optimal choice for large arrays on a wire.
    Commutative ops only (ring combine order is ring order, not rank
    order); the caller guards."""
    size, rank = ctx.size, ctx.rank
    flat = np.ascontiguousarray(value).reshape(-1)
    bounds = np.linspace(0, flat.size, size + 1).astype(np.int64)
    # chunk VIEWS, not copies: the wire plane ships contiguous slices as
    # out-of-band segments (dss.pack_frames) and the combine below
    # rebinds list entries with fresh op() results, so the full-payload
    # copy the seed made bought nothing — EXCEPT this rank's own chunk,
    # the only entry still aliasing the caller's buffer when sent (the
    # thread plane parks rendezvous payloads by reference past
    # sendrecv's return, so an aliased chunk could see a post-collective
    # caller mutation); one 1/p-sized copy keeps that contract
    chunks = [flat[bounds[i] : bounds[i + 1]] for i in range(size)]
    chunks[rank] = chunks[rank].copy()
    right, left = (rank + 1) % size, (rank - 1) % size
    # reduce-scatter phase: after p-1 steps, chunk (rank+1)%size is done
    for step in range(size - 1):
        send_idx = (rank - step) % size
        recv_idx = (rank - step - 1) % size
        got = ctx.sendrecv(
            chunks[send_idx], right, source=left,
            sendtag=tag, recvtag=tag, cid=COLL_CID,
        )
        chunks[recv_idx] = op(got, chunks[recv_idx])
    # allgather phase: circulate the finished chunks
    for step in range(size - 1):
        send_idx = (rank + 1 - step) % size
        recv_idx = (rank - step) % size
        chunks[recv_idx] = ctx.sendrecv(
            chunks[send_idx], right, source=left,
            sendtag=tag, recvtag=tag, cid=COLL_CID,
        )
    return np.concatenate(chunks).reshape(value.shape).astype(
        value.dtype, copy=False
    )


def allreduce(ctx, value: Any, op) -> Any:
    """Allreduce with host-plane algorithm selection (the Weak-#8 fix:
    one hardwired algorithm per op was a conscious round-2 scope line) —
    recursive doubling with the non-power-of-two pre/post fold
    (coll_base_allreduce.c:130-225 shape) for latency-bound payloads,
    ring reduce-scatter+allgather above ``host_coll_large_msg`` for
    large commutative array payloads.  In-order combines keep
    non-commutative ops correct on the doubling path."""
    size, rank = ctx.size, ctx.rank
    if size == 1:
        return value
    # tuned-table consult first: an explicit per-cell rule outranks the
    # auto han decision AND the builtin size thresholds (the reference's
    # dynamic-rules precedence); "han" rules still route below
    ruled = _rule_alg(ctx, "allreduce", value)
    if ruled is None:
        han = _han_route(ctx, "allreduce", value, op)
        if han is not None:
            return han.allreduce(ctx, value, op)
    tag = _next_tag(ctx, TAG_ALLREDUCE)
    large = int(mca_var.get("host_coll_large_msg", 256 * 1024))
    ring_eligible = (
        size > 2
        and isinstance(value, np.ndarray)
        and value.size >= size
        and getattr(op, "commute", False)
    )
    if ruled == "ring" and not ring_eligible:
        mca_output.verbose(
            2, _stream,
            "tuned rule names ring allreduce but the call is ineligible "
            "(need > 2 ranks, commutative op, ndarray with >= %d "
            "elements); builtin decision applies", size,
        )
        ruled = None
    if ruled == "ring" or (
        ruled is None and ring_eligible and value.nbytes >= large
    ):
        return _allreduce_ring(ctx, value, op, tag)
    pof2 = 1
    while pof2 * 2 <= size:
        pof2 *= 2
    rem = size - pof2
    acc = value
    # fold phase: the first 2*rem ranks pair up; odd member carries on
    if rank < 2 * rem:
        if rank % 2 == 0:
            ctx.send(acc, rank + 1, tag=tag, cid=COLL_CID)
            newrank = -1
        else:
            other = ctx.recv(rank - 1, tag=tag, cid=COLL_CID)
            acc = _ordered(op, other, acc)  # lower rank's operand left
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        mask = 1
        while mask < pof2:
            pnew = newrank ^ mask
            partner = pnew * 2 + 1 if pnew < rem else pnew + rem
            other = ctx.sendrecv(
                acc, partner, source=partner,
                sendtag=tag, recvtag=tag, cid=COLL_CID,
            )
            if partner < rank:
                acc = _ordered(op, other, acc)
            else:
                acc = _ordered(op, acc, other)
            mask <<= 1
    # unfold: odd members hand the result back to their even partner
    if rank < 2 * rem:
        if rank % 2 == 0:
            acc = ctx.recv(rank + 1, tag=tag, cid=COLL_CID)
        else:
            ctx.send(acc, rank - 1, tag=tag, cid=COLL_CID)
    return acc


# -------------------------------------------------------------- allgather


def allgather(ctx, value: Any) -> list:
    """Ring allgather (coll_base_allgather.c ring): p-1 steps, each rank
    forwards the block it just received.  Returns the rank-indexed list."""
    size, rank = ctx.size, ctx.rank
    if size == 1:
        return [value]
    # size-matched rules see 0 bytes here: allgather payloads need not
    # be congruent across ranks (arbitrary per-rank objects), so a
    # local size would split the han/flat decision and deadlock —
    # allgather han rules therefore use msg_bytes_min 0 (the bcast
    # discipline; reduce/allreduce payloads ARE congruent by contract)
    han = _han_route(ctx, "allgather", None)
    if han is not None:
        return han.allgather(ctx, value)
    out: list = [None] * size
    out[rank] = value
    tag = _next_tag(ctx, TAG_ALLGATHER)
    right = (rank + 1) % size
    left = (rank - 1) % size
    blk_idx, blk = rank, value
    for _ in range(size - 1):
        recv_idx, recv_blk = ctx.sendrecv(
            (blk_idx, blk), right, source=left,
            sendtag=tag, recvtag=tag, cid=COLL_CID,
        )
        out[recv_idx] = recv_blk
        blk_idx, blk = recv_idx, recv_blk
    return out


# --------------------------------------------------------- gather/scatter


def gather(ctx, value: Any, root: int = 0) -> list | None:
    """Linear gather (coll_base_gather.c basic_linear): rank-indexed list
    at root, None elsewhere."""
    size, rank = ctx.size, ctx.rank
    tag = _next_tag(ctx, TAG_GATHER)
    if rank != root:
        ctx.send(value, root, tag=tag, cid=COLL_CID)
        return None
    out = [None] * size
    out[root] = value
    for r in range(size):
        if r != root:
            out[r] = ctx.recv(r, tag=tag, cid=COLL_CID)
    return out


def scatter(ctx, values: list | None = None, root: int = 0) -> Any:
    """Linear scatter from root; ``values`` (rank-indexed, significant at
    root) must have one entry per rank.  Returns this rank's block."""
    size, rank = ctx.size, ctx.rank
    tag = _next_tag(ctx, TAG_SCATTER)
    if rank == root:
        if values is None or len(values) != size:
            raise errors.ArgError(
                f"scatter root needs {size} blocks, got "
                f"{'None' if values is None else len(values)}"
            )
        for r in range(size):
            if r != root:
                ctx.send(values[r], r, tag=tag, cid=COLL_CID)
        return values[root]
    return ctx.recv(root, tag=tag, cid=COLL_CID)


# --------------------------------------------------------------- alltoall


def _alltoall_bruck(ctx, blocks: list, tag: int) -> list:
    """Bruck alltoall (coll_base_alltoall.c:191 shape): local rotation,
    then ceil(log2(p)) store-and-forward rounds — round k ships every
    slot whose index has bit k set to rank+k — then an inverse rotation.
    O(log p) messages per rank against pairwise's O(p), each carrying up
    to half the slots: the latency-bound regime's trade, and the leader
    exchange coll/han's alltoall family uses above a leader-count bar."""
    size, rank = ctx.size, ctx.rank
    tmp = [blocks[(rank + i) % size] for i in range(size)]
    k = 1
    while k < size:
        idxs = [i for i in range(size) if i & k]
        got = ctx.sendrecv(
            [tmp[i] for i in idxs], (rank + k) % size,
            source=(rank - k) % size, sendtag=tag, recvtag=tag,
            cid=COLL_CID,
        )
        for i, blk in zip(idxs, got):
            tmp[i] = blk
        k <<= 1
    return [tmp[(rank - src) % size] for src in range(size)]


def alltoall(ctx, values: list) -> list:
    """Pairwise-exchange alltoall (coll_base_alltoall.c:383-444 shape):
    p-1 rounds, round i exchanges with rank±i.  ``values`` is the
    rank-indexed send list; returns the rank-indexed receive list.
    A tuned rule may pin "bruck" (log-round store-and-forward) or "han"
    (hierarchical two-level schedule) instead."""
    size, rank = ctx.size, ctx.rank
    if len(values) != size:
        raise errors.ArgError(f"alltoall needs {size} blocks")
    # Payloads are per-rank send lists — never congruent across ranks —
    # so the size-matched dynamic-rules consult sees 0 bytes everywhere
    # (the bcast discipline): alltoall rules use msg_bytes_min 0.  An
    # explicit flat rule outranks the auto han decision (the reference's
    # dynamic-rules precedence, same as allreduce/reduce above).
    ruled = _rule_alg(ctx, "alltoall", None)
    if ruled is None:
        han = _han_route(ctx, "alltoall", None)
        if han is not None:
            return han.alltoall(ctx, values)
    if size == 1:
        return [values[0]]
    tag = _next_tag(ctx, TAG_ALLTOALL)
    if ruled == "bruck":
        return _alltoall_bruck(ctx, list(values), tag)
    out: list = [None] * size
    out[rank] = values[rank]
    for i in range(1, size):
        sendto = (rank + i) % size
        recvfrom = (rank - i) % size
        out[recvfrom] = ctx.sendrecv(
            values[sendto], sendto, source=recvfrom,
            sendtag=tag, recvtag=tag, cid=COLL_CID,
        )
    return out


# ------------------------------------------------------- v-variants
# Variable-count collectives (coll_base_allgatherv.c:93,
# coll_base_alltoallv.c:125 shapes).  The host plane carries arbitrary
# objects, so blocks may differ per rank freely; the *v surface exists so
# MPI-shaped programs (flat buffer + counts/displacements) port directly.


def _displs_from(counts):
    out, acc = [], 0
    for c in counts:
        out.append(acc)
        acc += c
    return out


def _blocks_from(sendbuf, counts, displs, size):
    """Slice a flat buffer into per-rank blocks by (counts, displs) — the
    shared *v decomposition (displacements default to the running sum)."""
    if len(counts) != size:
        raise errors.ArgError(f"v-collective needs {size} counts")
    displs = _displs_from(counts) if displs is None else displs
    if len(displs) != size:
        raise errors.ArgError(f"v-collective needs {size} displacements")
    return [sendbuf[displs[r] : displs[r] + counts[r]] for r in range(size)]


def gatherv(ctx, value: Any, root: int = 0) -> list | None:
    """Linear gatherv: per-rank variable-size blocks, rank-indexed list at
    root (object payloads carry their own size — MPI's recvcounts are
    implicit)."""
    size, rank = ctx.size, ctx.rank
    tag = _next_tag(ctx, TAG_GATHERV)
    if rank != root:
        ctx.send(value, root, tag=tag, cid=COLL_CID)
        return None
    out = [None] * size
    out[root] = value
    for r in range(size):
        if r != root:
            out[r] = ctx.recv(r, tag=tag, cid=COLL_CID)
    return out


def scatterv(ctx, sendbuf=None, counts: list | None = None,
             displs: list | None = None, root: int = 0):
    """Linear scatterv: root slices a flat buffer by (counts, displs) —
    the MPI signature — and ships each rank its block."""
    size, rank = ctx.size, ctx.rank
    tag = _next_tag(ctx, TAG_SCATTERV)
    if rank == root:
        if sendbuf is None or counts is None:
            raise errors.ArgError(
                f"scatterv root needs a buffer and {size} counts"
            )
        blocks = _blocks_from(sendbuf, counts, displs, size)
        for r in range(size):
            if r != root:
                ctx.send(blocks[r], r, tag=tag, cid=COLL_CID)
        return blocks[root]
    return ctx.recv(root, tag=tag, cid=COLL_CID)


def allgatherv(ctx, value: Any) -> list:
    """Ring allgatherv (coll_base_allgatherv.c ring): identical schedule
    to allgather — blocks ride with their sizes, so no recvcounts
    negotiation round is needed."""
    size, rank = ctx.size, ctx.rank
    out: list = [None] * size
    out[rank] = value
    if size == 1:
        return out
    tag = _next_tag(ctx, TAG_ALLGATHERV)
    right = (rank + 1) % size
    left = (rank - 1) % size
    blk_idx, blk = rank, value
    for _ in range(size - 1):
        recv_idx, recv_blk = ctx.sendrecv(
            (blk_idx, blk), right, source=left,
            sendtag=tag, recvtag=tag, cid=COLL_CID,
        )
        out[recv_idx] = recv_blk
        blk_idx, blk = recv_idx, recv_blk
    return out


def alltoallv(ctx, sendbuf, counts: list, displs: list | None = None
              ) -> list:
    """Pairwise-exchange alltoallv (coll_base_alltoallv.c:125 shape):
    `sendbuf` is flat, `counts[r]` elements go to rank r (displacements
    default to the running sum).  Returns the rank-indexed list of
    received blocks."""
    size, rank = ctx.size, ctx.rank
    blocks = _blocks_from(sendbuf, counts, displs, size)
    # same non-congruent-payload discipline as alltoall above:
    # alltoallv rules match with msg_bytes_min 0, and an explicit flat
    # rule outranks the auto han decision
    ruled = _rule_alg(ctx, "alltoallv", None)
    if ruled is None:
        han = _han_route(ctx, "alltoallv", None)
        if han is not None:
            return han.alltoallv(ctx, sendbuf, counts, displs)
    tag = _next_tag(ctx, TAG_ALLTOALLV)
    out: list = [None] * size
    out[rank] = blocks[rank]
    for i in range(1, size):
        sendto = (rank + i) % size
        recvfrom = (rank - i) % size
        out[recvfrom] = ctx.sendrecv(
            blocks[sendto], sendto, source=recvfrom,
            sendtag=tag, recvtag=tag, cid=COLL_CID,
        )
    return out


# ------------------------------------------------------------ scan/exscan


def scan(ctx, value: Any, op) -> Any:
    """Inclusive prefix reduction, linear chain (coll_base_scan shape):
    rank r returns buf_0 ⊕ ... ⊕ buf_r."""
    rank = ctx.rank
    tag = _next_tag(ctx, TAG_SCAN)
    acc = value
    if rank > 0:
        prev = ctx.recv(rank - 1, tag=tag, cid=COLL_CID)
        acc = _ordered(op, prev, acc)
    if rank + 1 < ctx.size:
        ctx.send(acc, rank + 1, tag=tag, cid=COLL_CID)
    return acc


def exscan(ctx, value: Any, op) -> Any:
    """Exclusive prefix reduction: rank r returns buf_0 ⊕ ... ⊕ buf_{r-1};
    rank 0's result is undefined (None)."""
    rank = ctx.rank
    tag = _next_tag(ctx, TAG_SCAN)
    prev = None
    if rank > 0:
        prev = ctx.recv(rank - 1, tag=tag, cid=COLL_CID)
    if rank + 1 < ctx.size:
        mine = value if prev is None else _ordered(op, prev, value)
        ctx.send(mine, rank + 1, tag=tag, cid=COLL_CID)
    return prev


# ---------------------------------------------------------- reduce_scatter


def reduce_scatter(ctx, values: list, op) -> Any:
    """Blockwise reduce + scatter (coll_base_reduce_scatter.c
    non-overlapping shape): ``values`` is the rank-indexed list of blocks;
    rank r returns the fully-reduced block r."""
    size = ctx.size
    if len(values) != size:
        raise errors.ArgError(f"reduce_scatter needs {size} blocks")
    han = _han_route(ctx, "reduce_scatter", values, op)
    if han is not None:
        return han.reduce_scatter(ctx, values, op)
    reduced = reduce(ctx, values, op, root=0, algorithm="auto")
    return scatter(ctx, reduced, root=0)


class HostCollectives:
    """Mixin giving any send/recv endpoint the collective API (the
    mca_coll_base_comm_select analog for host endpoints: one composed
    table, methods delegate to the module algorithms)."""

    def bcast(self, obj: Any = None, root: int = 0,
              algorithm: str | None = None) -> Any:
        return bcast(self, obj, root, algorithm)

    def reduce(self, value: Any, op, root: int = 0,
               algorithm: str | None = None) -> Any:
        return reduce(self, value, op, root, algorithm)

    def allreduce(self, value: Any, op) -> Any:
        return allreduce(self, value, op)

    def allgather(self, value: Any) -> list:
        return allgather(self, value)

    def gather(self, value: Any, root: int = 0):
        return gather(self, value, root)

    def scatter(self, values: list | None = None, root: int = 0) -> Any:
        return scatter(self, values, root)

    def alltoall(self, values: list) -> list:
        return alltoall(self, values)

    def scan(self, value: Any, op) -> Any:
        return scan(self, value, op)

    def exscan(self, value: Any, op) -> Any:
        return exscan(self, value, op)

    def reduce_scatter(self, values: list, op) -> Any:
        return reduce_scatter(self, values, op)

    def gatherv(self, value: Any, root: int = 0):
        return gatherv(self, value, root)

    def scatterv(self, sendbuf=None, counts: list | None = None,
                 displs: list | None = None, root: int = 0):
        return scatterv(self, sendbuf, counts, displs, root)

    def allgatherv(self, value: Any) -> list:
        return allgatherv(self, value)

    def alltoallv(self, sendbuf, counts: list,
                  displs: list | None = None) -> list:
        return alltoallv(self, sendbuf, counts, displs)
