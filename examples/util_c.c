/* util_c.c — round-5 utility-surface acceptance: versions/threads,
 * error classes, Alloc_mem, Reduce_local, Request_get_status,
 * Waitsome, Cancel, Get_elements, Sendrecv_replace, handle c2f/f2c.
 * Reference shapes: ompi/mpi/c/{get_version,init_thread,
 * add_error_class,reduce_local,request_get_status,waitsome,cancel,
 * get_elements,sendrecv_replace,comm_c2f}.c.  Run with >= 2 ranks. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
      MPI_Abort(MPI_COMM_WORLD, 2);                                    \
    }                                                                  \
  } while (0)

int main(int argc, char **argv) {
  int provided = -1;
  CHECK(MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided) ==
        MPI_SUCCESS);
  CHECK(provided >= MPI_THREAD_SINGLE && provided <= MPI_THREAD_MULTIPLE);

  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  CHECK(size >= 2);

  /* versions */
  int ver, subver;
  CHECK(MPI_Get_version(&ver, &subver) == MPI_SUCCESS && ver == 3);
  char lib[MPI_MAX_LIBRARY_VERSION_STRING];
  int len = 0;
  CHECK(MPI_Get_library_version(lib, &len) == MPI_SUCCESS && len > 0);

  /* thread identity */
  int qt = -1, main_th = 0, fin = -1;
  CHECK(MPI_Query_thread(&qt) == MPI_SUCCESS && qt == provided);
  CHECK(MPI_Is_thread_main(&main_th) == MPI_SUCCESS && main_th == 1);
  CHECK(MPI_Finalized(&fin) == MPI_SUCCESS && fin == 0);

  /* error classes */
  int eclass = -1, ecode = -1, out = -1;
  CHECK(MPI_Add_error_class(&eclass) == MPI_SUCCESS &&
        eclass > MPI_ERR_LASTCODE);
  CHECK(MPI_Add_error_code(eclass, &ecode) == MPI_SUCCESS);
  CHECK(MPI_Add_error_string(ecode, "app-level frobnication error") ==
        MPI_SUCCESS);
  CHECK(MPI_Error_class(ecode, &out) == MPI_SUCCESS && out == eclass);
  char es[MPI_MAX_ERROR_STRING];
  CHECK(MPI_Error_string(ecode, es, &len) == MPI_SUCCESS);
  CHECK(strstr(es, "frobnication") != NULL);
  CHECK(MPI_Error_class(MPI_ERR_COMM, &out) == MPI_SUCCESS &&
        out == MPI_ERR_COMM);

  /* memory */
  void *mem = NULL;
  CHECK(MPI_Alloc_mem(4096, MPI_INFO_NULL, &mem) == MPI_SUCCESS && mem);
  memset(mem, 0x5A, 4096);
  CHECK(MPI_Free_mem(mem) == MPI_SUCCESS);
  MPI_Aint addr = 0;
  int probe_target = 7;
  CHECK(MPI_Get_address(&probe_target, &addr) == MPI_SUCCESS && addr != 0);

  /* op introspection + local reduction */
  int comm_flag = -1;
  CHECK(MPI_Op_commutative(MPI_SUM, &comm_flag) == MPI_SUCCESS &&
        comm_flag == 1);
  double a[3] = {1, 2, 3}, b[3] = {10, 20, 30};
  CHECK(MPI_Reduce_local(a, b, 3, MPI_DOUBLE, MPI_SUM) == MPI_SUCCESS);
  CHECK(b[0] == 11 && b[1] == 22 && b[2] == 33);

  /* predefined WORLD attributes + Aint arithmetic + MPI_BOTTOM */
  {
    void *pv = NULL;
    int pf = 0;
    CHECK(MPI_Comm_get_attr(MPI_COMM_WORLD, MPI_TAG_UB, &pv, &pf) ==
          MPI_SUCCESS && pf == 1 && *(int *)pv >= 32767);
    CHECK(MPI_Comm_get_attr(MPI_COMM_WORLD, MPI_WTIME_IS_GLOBAL, &pv,
                            &pf) == MPI_SUCCESS && pf == 1);
    MPI_Aint a1 = 0;
    int anchor[4];
    CHECK(MPI_Get_address(&anchor[0], &a1) == MPI_SUCCESS);
    MPI_Aint a2 = MPI_Aint_add(a1, 2 * (MPI_Aint)sizeof(int));
    CHECK(MPI_Aint_diff(a2, a1) == 2 * (MPI_Aint)sizeof(int));
    /* absolute-address send: hindexed over MPI_BOTTOM */
    if (rank < 2) {
      int pr = 1 - rank;
      anchor[0] = 9100 + rank;
      anchor[2] = 9200 + rank;
      int bl2[2] = {1, 1};
      MPI_Aint ad[2];
      MPI_Get_address(&anchor[0], &ad[0]);
      MPI_Get_address(&anchor[2], &ad[1]);
      MPI_Datatype abs_t;
      CHECK(MPI_Type_create_hindexed(2, bl2, ad, MPI_INT, &abs_t) ==
            MPI_SUCCESS);
      CHECK(MPI_Type_commit(&abs_t) == MPI_SUCCESS);
      int got2[2] = {-1, -1};
      MPI_Status ast;
      CHECK(MPI_Sendrecv(MPI_BOTTOM, 1, abs_t, pr, 40, got2, 2,
                         MPI_INT, pr, 40, MPI_COMM_WORLD, &ast) ==
            MPI_SUCCESS);
      CHECK(got2[0] == 9100 + pr && got2[1] == 9200 + pr);
      MPI_Type_free(&abs_t);
    }
  }

  /* handle conversion is the identity on this ABI */
  CHECK(MPI_Comm_f2c(MPI_Comm_c2f(MPI_COMM_WORLD)) == MPI_COMM_WORLD);
  CHECK(MPI_Type_f2c(MPI_Type_c2f(MPI_DOUBLE)) == MPI_DOUBLE);
  CHECK(MPI_Pcontrol(0) == MPI_SUCCESS);

  /* request_get_status (non-destructive) + waitsome + cancel +
   * get_elements + sendrecv_replace: a 0<->1 exchange.  The pair
   * synchronizes on its own subcommunicator so ranks >= 2 never see a
   * mismatched barrier count. */
  MPI_Comm pair;
  CHECK(MPI_Comm_split(MPI_COMM_WORLD, rank < 2 ? 0 : 1, rank, &pair) ==
        MPI_SUCCESS);
  if (rank < 2) {
    int peer = 1 - rank;

    /* sendrecv_replace swaps payloads */
    int v[4] = {rank * 100 + 1, rank * 100 + 2, rank * 100 + 3,
                rank * 100 + 4};
    MPI_Status st;
    memset(&st, 0, sizeof st);
    CHECK(MPI_Sendrecv_replace(v, 4, MPI_INT, peer, 7, peer, 7,
                               MPI_COMM_WORLD, &st) == MPI_SUCCESS);
    CHECK(v[0] == peer * 100 + 1 && v[3] == peer * 100 + 4);
    CHECK(st.MPI_SOURCE == peer);
    int elems = -1;
    CHECK(MPI_Get_elements(&st, MPI_INT, &elems) == MPI_SUCCESS &&
          elems == 4);
    int cnt = -1;
    CHECK(MPI_Get_count(&st, MPI_INT, &cnt) == MPI_SUCCESS && cnt == 4);

    /* sendrecv_replace with a strided vector type: only typemap
     * positions swap; the stride gap stays untouched */
    MPI_Datatype vec;
    CHECK(MPI_Type_vector(2, 2, 3, MPI_INT, &vec) == MPI_SUCCESS);
    CHECK(MPI_Type_commit(&vec) == MPI_SUCCESS);
    int sv5[5] = {rank * 10 + 0, rank * 10 + 1, -777, rank * 10 + 3,
                  rank * 10 + 4};
    memset(&st, 0, sizeof st);
    CHECK(MPI_Sendrecv_replace(sv5, 1, vec, peer, 8, peer, 8,
                               MPI_COMM_WORLD, &st) == MPI_SUCCESS);
    CHECK(sv5[0] == peer * 10 + 0 && sv5[1] == peer * 10 + 1);
    CHECK(sv5[2] == -777); /* the gap is not part of the typemap */
    CHECK(sv5[3] == peer * 10 + 3 && sv5[4] == peer * 10 + 4);
    CHECK(MPI_Type_free(&vec) == MPI_SUCCESS);

    /* status_set_elements / set_cancelled round-trip */
    MPI_Status fake;
    memset(&fake, 0, sizeof fake);
    CHECK(MPI_Status_set_elements(&fake, MPI_DOUBLE, 5) == MPI_SUCCESS);
    MPI_Count ce = -1;
    CHECK(MPI_Get_elements_x(&fake, MPI_DOUBLE, &ce) == MPI_SUCCESS &&
          ce == 5);
    int cflag = -1;
    CHECK(MPI_Status_set_cancelled(&fake, 1) == MPI_SUCCESS);
    CHECK(MPI_Test_cancelled(&fake, &cflag) == MPI_SUCCESS && cflag == 1);

    /* status c2f/f2c round-trip */
    MPI_Fint fst[MPI_F_STATUS_SIZE];
    MPI_Status back;
    CHECK(MPI_Status_c2f(&fake, fst) == MPI_SUCCESS);
    CHECK(MPI_Status_f2c(fst, &back) == MPI_SUCCESS);
    CHECK(back._count == fake._count && back._cancelled == 1);

    /* request_get_status leaves the request live; waitsome retires */
    int rbuf[2] = {-1, -1};
    MPI_Request reqs[2];
    CHECK(MPI_Irecv(&rbuf[0], 1, MPI_INT, peer, 21, MPI_COMM_WORLD,
                    &reqs[0]) == MPI_SUCCESS);
    CHECK(MPI_Irecv(&rbuf[1], 1, MPI_INT, peer, 22, MPI_COMM_WORLD,
                    &reqs[1]) == MPI_SUCCESS);
    MPI_Barrier(pair); /* both posted before any send */
    int sv = rank + 40;
    CHECK(MPI_Send(&sv, 1, MPI_INT, peer, 21, MPI_COMM_WORLD) ==
          MPI_SUCCESS);
    sv = rank + 50;
    CHECK(MPI_Send(&sv, 1, MPI_INT, peer, 22, MPI_COMM_WORLD) ==
          MPI_SUCCESS);

    /* poll non-destructively until the first request completes */
    int gflag = 0;
    while (!gflag)
      CHECK(MPI_Request_get_status(reqs[0], &gflag, &st) == MPI_SUCCESS);
    CHECK(reqs[0] != MPI_REQUEST_NULL); /* NOT freed by get_status */

    int done = 0;
    while (done < 2) {
      int outcount = 0, idx[2];
      MPI_Status sts[2];
      CHECK(MPI_Waitsome(2, reqs, &outcount, idx, sts) == MPI_SUCCESS);
      CHECK(outcount != MPI_UNDEFINED && outcount >= 1);
      done += outcount;
    }
    CHECK(rbuf[0] == peer + 40 && rbuf[1] == peer + 50);
    CHECK(reqs[0] == MPI_REQUEST_NULL && reqs[1] == MPI_REQUEST_NULL);
    int outcount = 0, idx[2];
    CHECK(MPI_Waitsome(2, reqs, &outcount, idx, NULL) == MPI_SUCCESS);
    CHECK(outcount == MPI_UNDEFINED); /* nothing active */

    /* waitsome over only-inactive persistent handles: MPI_UNDEFINED
     * (an inactive handle is not an active participant) */
    MPI_Request preq;
    int pb = 0;
    CHECK(MPI_Recv_init(&pb, 1, MPI_INT, peer, 33, MPI_COMM_WORLD,
                        &preq) == MPI_SUCCESS);
    outcount = -5;
    CHECK(MPI_Testsome(1, &preq, &outcount, idx, NULL) == MPI_SUCCESS);
    CHECK(outcount == MPI_UNDEFINED);
    CHECK(MPI_Waitsome(1, &preq, &outcount, idx, NULL) == MPI_SUCCESS);
    CHECK(outcount == MPI_UNDEFINED);
    CHECK(preq != MPI_REQUEST_NULL); /* handle survives for Start */
    CHECK(MPI_Request_free(&preq) == MPI_SUCCESS);

    /* cancel an unmatched receive */
    MPI_Request creq;
    int cb = 0;
    CHECK(MPI_Irecv(&cb, 1, MPI_INT, peer, 999, MPI_COMM_WORLD, &creq) ==
          MPI_SUCCESS);
    CHECK(MPI_Cancel(&creq) == MPI_SUCCESS);
    memset(&st, 0, sizeof st);
    CHECK(MPI_Wait(&creq, &st) == MPI_SUCCESS);
    CHECK(MPI_Test_cancelled(&st, &cflag) == MPI_SUCCESS && cflag == 1);
  }

  MPI_Comm_free(&pair);
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("util_c OK on %d ranks\n", size);
  MPI_Finalize();
  CHECK(MPI_Finalized(&fin) == MPI_SUCCESS && fin == 1);
  return 0;
}
