/* libzompi_mpi — the C ABI shim's engine (SURVEY.md §7's "C ABI
 * mpi.h-compatible shim" commitment; breadth per VERDICT round-3
 * Missing #1).
 *
 * Speaks the SAME wire protocol as the Python host plane
 * (zhpe_ompi_tpu/pt2pt/tcp.py):
 *   - modex: connect to the coordinator, send pack(rank, [host, port]),
 *     receive pack(address_book); rank 0 IS the coordinator (binds the
 *     agreed address, gathers, replies) — ompi_mpi_init.c:667-700's
 *     business-card exchange.
 *   - data frames: 4-byte LE length + DSS(src, tag, cid, seq, payload);
 *     payloads are DSS ndarrays (dtype tags '<i4','<i8','<f4','<f8','|u1')
 *     so numpy on the Python side round-trips them natively.
 *   - hello frame on each new connection announces the peer rank.
 *   - barrier: dissemination rounds, tag 0x7FFD cid 0x7FFD, empty-bytes
 *     payload — bit-identical to TcpProc.barrier, so mixed C/Python jobs
 *     synchronize together.
 *
 * Protocol note: the shim speaks BOTH protocol legs.  Below
 * ZMPI_MCA_tcp_eager_limit (default 1 MB) user sends are eager; above it
 * they follow the same RTS/CTS rendezvous as the Python plane
 * (pml_ob1_sendreq.c:768's delivery guarantee at any size up to the
 * shared 4-byte frame bound of ~4 GiB, enforced with MPI_ERR_COUNT):
 * the sender
 * parks the payload, announces with a small RTS tuple, and pushes the
 * data frame over a dedicated bulk connection (hello ["d"]) once the
 * receiver's CTS arrives.  The receiving engine enters a PLACEHOLDER
 * into the matching stream at RTS position (non-overtaking) and sends
 * CTS only when a receive CLAIMS it — the Python plane's flow-control
 * contract (unmatched bulk parks at the SENDER).  Large MPI_Isend runs
 * its rendezvous on a background thread (crossed-Isend deadlock
 * freedom); collective-internal exchanges stay eager at any size, their
 * receives being posted by the same synchronized algorithm on all ranks.
 *
 * Matching: a posted-receive engine (the pml_ob1_recvfrag.c:295-513
 * contract): posted requests are matched in post order against arriving
 * fragments, the unexpected queue holds arrivals with no posted match,
 * and wildcards (ANY_SOURCE/ANY_TAG) resolve in arrival order.  Blocking
 * receive is Irecv+Wait over the same engine, so ordering between
 * blocking and nonblocking receives follows the MPI posting-order rule.
 *
 * Communicators: WORLD and SELF are predefined; Comm_split/dup derive
 * new contexts whose cid triples (pt2pt / collective / barrier context)
 * are computed deterministically from the parent's cid and a per-parent
 * creation sequence — every member runs the identical computation, so no
 * wire agreement round is needed (the ompi_comm_nextcid analog,
 * ompi/communicator/comm_cid.c, collapsed to a hash because disjoint
 * sibling groups can safely share a context id).
 *
 * Collectives: recursive-doubling allreduce with the non-power-of-two
 * fold (coll_base_allreduce.c:130-225 shape), binomial bcast
 * (coll_base_bcast.c:329), linear rooted reduce/gather/scatter
 * (coll/basic's linear algorithms, coll_base_gather.c:41 family), ring
 * allgather, and pairwise alltoall (coll_base_alltoall.c:132 shape) on a
 * reserved cid, element-typed kernels for the predefined ops including
 * the logical/bitwise set (op_base_functions.c analog).
 *
 * Derived datatypes: contiguous and vector typemaps with a resumable
 * pack/unpack into base-typed contiguous wire buffers — the convertor
 * shape (opal_convertor_pack, opal/datatype/opal_convertor.c:218-276)
 * reduced to the two constructors the C surface exposes.
 */

#include "zompi_mpi.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/file.h>
#include <netinet/in.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <deque>
#include <functional>
#include <list>
#include <map>
#include <memory>
#include <set>
#include <mutex>
#include <string>
#include <thread>
#include <vector>
#include <chrono>

namespace {

// ---------------------------------------------------------------- DSS
// Subset of zhpe_ompi_tpu/utils/dss.py: varints, zigzag ints, str,
// bytes, list, ndarray.  Type tags must match dss.py exactly.
enum DssTag : uint8_t {
  T_NONE = 0, T_BOOL = 1, T_INT = 2, T_FLOAT = 3, T_STR = 4,
  T_BYTES = 5, T_LIST = 6, T_TUPLE = 7, T_DICT = 8, T_NDARRAY = 9,
  // out-of-band twins (dss.pack_frames): header carries the metadata
  // plus an 8-byte little-endian offset-from-frame-END; the raw
  // payload sits in the frame's trailing segment region.  The parser
  // normalizes them to T_NDARRAY/T_BYTES so downstream dispatch is
  // agnostic to which framing the (Python) sender chose.
  T_NDARRAY_OOB = 10, T_BYTES_OOB = 11,
};

void put_varint(std::string &out, uint64_t n) {
  while (true) {
    uint8_t b = n & 0x7F;
    n >>= 7;
    if (n) out.push_back((char)(b | 0x80));
    else { out.push_back((char)b); return; }
  }
}

bool get_varint(const uint8_t *buf, size_t len, size_t &pos, uint64_t &n) {
  n = 0;
  int shift = 0;
  while (pos < len) {
    uint8_t b = buf[pos++];
    n |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
  }
  return false;
}

void put_int(std::string &out, int64_t v) {
  out.push_back((char)T_INT);
  uint64_t z = v >= 0 ? ((uint64_t)v << 1) : ((uint64_t)(-v) << 1 | 1);
  put_varint(out, z);
}

void put_str(std::string &out, const std::string &s) {
  out.push_back((char)T_STR);
  put_varint(out, s.size());
  out += s;
}

void put_bytes(std::string &out, const void *p, size_t n) {
  out.push_back((char)T_BYTES);
  put_varint(out, n);
  out.append((const char *)p, n);
}

void put_float(std::string &out, double v) {
  // dss.py float: T_FLOAT + struct "<d" (little-endian hosts only,
  // same assumption the OOB offset codec already makes)
  out.push_back((char)T_FLOAT);
  char b[8];
  memcpy(b, &v, 8);
  out.append(b, 8);
}

void put_ndarray_1d(std::string &out, const char *dtstr, const void *data,
                    uint64_t count, uint64_t itemsize) {
  out.push_back((char)T_NDARRAY);
  size_t dl = strlen(dtstr);
  put_varint(out, dl);
  out.append(dtstr, dl);
  put_varint(out, 1);          // ndim
  put_varint(out, count);      // shape[0]
  put_varint(out, count * itemsize);
  out.append((const char *)data, count * itemsize);
}

// Parsed DSS value (only what the shim needs).
struct DssVal {
  uint8_t tag = T_NONE;
  int64_t i = 0;
  std::string s;            // str/bytes raw
  std::string dt;           // ndarray dtype
  std::vector<uint64_t> shape;
  std::string data;         // ndarray raw bytes
  std::vector<DssVal> items;  // list/tuple
};

bool parse_one(const uint8_t *buf, size_t len, size_t &pos, DssVal &v) {
  if (pos >= len) return false;
  v.tag = buf[pos++];
  uint64_t n;
  switch (v.tag) {
    case T_NONE: return true;
    case T_BOOL: v.i = buf[pos++]; return true;
    case T_INT: {
      if (!get_varint(buf, len, pos, n)) return false;
      v.i = (n & 1) ? -(int64_t)(n >> 1) : (int64_t)(n >> 1);
      return true;
    }
    case T_FLOAT: {
      if (pos + 8 > len) return false;
      double d;
      memcpy(&d, buf + pos, 8);
      pos += 8;
      v.i = (int64_t)d;
      return true;
    }
    case T_STR:
    case T_BYTES: {
      if (!get_varint(buf, len, pos, n) || pos + n > len) return false;
      v.s.assign((const char *)buf + pos, n);
      pos += n;
      return true;
    }
    case T_BYTES_OOB: {
      if (!get_varint(buf, len, pos, n)) return false;
      if (pos + 8 > len) return false;
      uint64_t ofe;
      memcpy(&ofe, buf + pos, 8);
      pos += 8;
      if (ofe > len || n > ofe) return false;
      v.s.assign((const char *)buf + (len - ofe), n);
      v.tag = T_BYTES;
      return true;
    }
    case T_NDARRAY:
    case T_NDARRAY_OOB: {
      if (!get_varint(buf, len, pos, n) || pos + n > len) return false;
      v.dt.assign((const char *)buf + pos, n);
      pos += n;
      uint64_t ndim;
      if (!get_varint(buf, len, pos, ndim)) return false;
      for (uint64_t k = 0; k < ndim; k++) {
        uint64_t d;
        if (!get_varint(buf, len, pos, d)) return false;
        v.shape.push_back(d);
      }
      if (!get_varint(buf, len, pos, n)) return false;
      if (v.tag == T_NDARRAY_OOB) {
        if (pos + 8 > len) return false;
        uint64_t ofe;
        memcpy(&ofe, buf + pos, 8);  // little-endian hosts only (x86/arm)
        pos += 8;
        if (ofe > len || n > ofe) return false;
        v.data.assign((const char *)buf + (len - ofe), n);
        v.tag = T_NDARRAY;
        return true;
      }
      if (pos + n > len) return false;
      v.data.assign((const char *)buf + pos, n);
      pos += n;
      return true;
    }
    case T_LIST:
    case T_TUPLE: {
      if (!get_varint(buf, len, pos, n)) return false;
      v.items.resize(n);
      for (uint64_t k = 0; k < n; k++)
        if (!parse_one(buf, len, pos, v.items[k])) return false;
      return true;
    }
    default:
      return false;  // dict etc: not needed by the shim
  }
}

bool parse_all(const std::string &frame, std::vector<DssVal> &out) {
  const uint8_t *buf = (const uint8_t *)frame.data();
  size_t len = frame.size(), pos = 0;
  uint64_t count;
  if (!get_varint(buf, len, pos, count)) return false;
  out.resize(count);
  for (uint64_t k = 0; k < count; k++)
    if (!parse_one(buf, len, pos, out[k])) return false;
  return true;
}

// ------------------------------------------------------------- sockets

bool send_all(int fd, const void *p, size_t n) {
  const char *c = (const char *)p;
  while (n) {
    ssize_t w = ::send(fd, c, n, 0);
    if (w <= 0) return false;
    c += w;
    n -= (size_t)w;
  }
  return true;
}

bool recv_all(int fd, void *p, size_t n) {
  char *c = (char *)p;
  while (n) {
    ssize_t r = ::recv(fd, c, n, 0);
    if (r <= 0) return false;
    c += r;
    n -= (size_t)r;
  }
  return true;
}

bool send_frame(int fd, const std::string &payload) {
  // the wire protocol is 4-byte length-framed (matching the Python
  // plane's struct "<I"); a frame at or past 4 GiB cannot be framed —
  // fail loudly instead of wrapping the length and shearing the stream
  if (payload.size() > 0xFFFFFFFFull) return false;
  uint32_t len = (uint32_t)payload.size();
  uint8_t hdr[4] = {(uint8_t)(len), (uint8_t)(len >> 8),
                    (uint8_t)(len >> 16), (uint8_t)(len >> 24)};
  return send_all(fd, hdr, 4) && send_all(fd, payload.data(), len);
}

bool recv_frame(int fd, std::string &out) {
  uint8_t hdr[4];
  if (!recv_all(fd, hdr, 4)) return false;
  uint32_t len = hdr[0] | hdr[1] << 8 | hdr[2] << 16 | hdr[3] << 24;
  out.resize(len);
  return len == 0 || recv_all(fd, &out[0], len);
}

// children must not inherit the engine's sockets across execve: an
// exec'd child holding duplicates of our connections converts peer
// death into a silent hang for everyone blocked on those sockets
void set_cloexec(int fd) { fcntl(fd, F_SETFD, FD_CLOEXEC); }

int tcp_connect(const std::string &host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  set_cloexec(fd);
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, host.c_str(), &a.sin_addr);
  for (int tries = 0; tries < 200; tries++) {
    if (connect(fd, (sockaddr *)&a, sizeof a) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      return fd;
    }
    usleep(50 * 1000);
    close(fd);
    fd = socket(AF_INET, SOCK_STREAM, 0);
  }
  close(fd);
  return -1;
}

// ------------------------------------------------------------ datatypes

struct DtInfo { const char *tag; size_t item; };

bool base_dtinfo(MPI_Datatype dt, DtInfo &out) {
  switch (dt) {
    case MPI_BYTE:           out = {"|u1", 1}; return true;
    case MPI_INT:            out = {"<i4", 4}; return true;
    case MPI_LONG:           out = {"<i8", 8}; return true;
    case MPI_FLOAT:          out = {"<f4", 4}; return true;
    case MPI_DOUBLE:         out = {"<f8", 8}; return true;
    case MPI_CHAR:           out = {"<i1", 1}; return true;
    case MPI_SIGNED_CHAR:    out = {"<i1", 1}; return true;
    case MPI_SHORT:          out = {"<i2", 2}; return true;
    case MPI_LONG_LONG:      out = {"<i8", 8}; return true;
    case MPI_UNSIGNED_CHAR:  out = {"<u1", 1}; return true;
    case MPI_UNSIGNED_SHORT: out = {"<u2", 2}; return true;
    case MPI_UNSIGNED:       out = {"<u4", 4}; return true;
    case MPI_UNSIGNED_LONG:  out = {"<u8", 8}; return true;
    // MINLOC/MAXLOC pair types: opaque fixed-size records on the wire
    // (C struct layouts, padding included — op.h's ompi_op pair reds)
    case MPI_2INT:           out = {"|V8", 8}; return true;
    case MPI_FLOAT_INT:      out = {"|V8", 8}; return true;
    case MPI_DOUBLE_INT:     out = {"|V16", 16}; return true;
    case MPI_LONG_INT:       out = {"|V16", 16}; return true;
    case MPI_SHORT_INT:      out = {"|V8", 8}; return true;
  }
  return false;
}

bool is_pair_dtype(MPI_Datatype dt) {
  return dt >= MPI_2INT && dt <= MPI_SHORT_INT;
}

// the TYPEMAP size of a pair record (value + int, padding excluded);
// 0 for non-pair types
int pair_typemap_size(MPI_Datatype dt) {
  switch (dt) {
    case MPI_2INT:       return 8;
    case MPI_FLOAT_INT:  return 8;
    case MPI_DOUBLE_INT: return 12;
    case MPI_LONG_INT:   return 12;
    case MPI_SHORT_INT:  return 6;
  }
  return 0;
}

// the op/dtype pairing must fail at the ORIGIN of every accumulate-
// family call: the remote apply is fire-and-forget, so a target-side
// reduce_buf error would otherwise vanish (pair types take only
// MINLOC/MAXLOC/REPLACE/NO_OP; loc ops REQUIRE a pair type)
int check_acc_op_pairing(MPI_Datatype base, MPI_Op op) {
  bool pair = is_pair_dtype(base);
  bool loc_op = op == MPI_MINLOC || op == MPI_MAXLOC;
  if (pair && !loc_op && op != MPI_REPLACE && op != MPI_NO_OP)
    return MPI_ERR_OP;
  if (!pair && loc_op) return MPI_ERR_OP;
  return MPI_SUCCESS;
}

// Derived typemap: blocks of base elements within one extent, the
// convertor's description (opal_datatype_optimize.c) reduced to the
// contiguous/vector constructors.
struct DtypeObj {
  MPI_Datatype base = MPI_BYTE;
  std::vector<std::pair<int64_t, int64_t>> blocks;  // (offset, n) in elems
  int64_t extent = 0;   // ub - lb, in base elems (the item stride)
  int64_t lb = 0;       // lower bound (min displacement), in base elems
  int64_t elems = 0;    // base elems per one item (sum of block n)
  bool committed = false;
  // canonical-packing element unit for byte-sealed typemaps: the
  // packed stream of a single-oldtype byte constructor is whole base
  // elements of that oldtype (external32 swaps at this unit); 0 means
  // heterogeneous (struct) — canonical packing is then unsupported
  int swap_unit = 1;
  // constructor envelope (type_get_envelope.c / type_get_contents.c)
  int combiner = 0;  // MPI_COMBINER_NAMED until a constructor stamps it
  std::vector<int> env_ints;
  std::vector<long long> env_aints;
  std::vector<int> env_types;
};

constexpr MPI_Datatype DERIVED_BASE = 0x40;
std::map<MPI_Datatype, DtypeObj> g_dtypes;
MPI_Datatype g_next_dtype = DERIVED_BASE;

// canonical packed element unit of a type's packed stream: predefined
// and element-sealed derived = base item size; byte-sealed derived =
// the unit recorded at construction (0 = heterogeneous struct)
int packed_unit_of(const DtypeObj *derived, MPI_Datatype dt,
                   size_t item) {
  MPI_Datatype base = derived ? derived->base : dt;
  if (is_pair_dtype(base)) return 0;  // heterogeneous record: no unit
  if (derived && base == 0 /* MPI_BYTE */) return derived->swap_unit;
  return (int)item;
}

// A resolved view: base info + typemap (identity map for predefined).
struct DtView {
  DtInfo di;
  const DtypeObj *derived = nullptr;  // null => predefined (contiguous)
  int64_t elems_per_item() const { return derived ? derived->elems : 1; }
  bool contiguous() const {
    if (!derived) return true;
    return derived->blocks.size() == 1 && derived->blocks[0].first == 0 &&
           derived->extent == derived->elems;
  }
};

// merge adjacent typemap blocks (opal_datatype_optimize.c's job)
void coalesce_blocks(std::vector<std::pair<int64_t, int64_t>> &blocks) {
  std::vector<std::pair<int64_t, int64_t>> merged;
  for (auto &b : blocks) {
    if (!merged.empty() &&
        merged.back().first + merged.back().second == b.first)
      merged.back().second += b.second;
    else
      merged.push_back(b);
  }
  blocks = std::move(merged);
}

// memory footprint of `count` items laid out per MPI extent rules —
// block r of a gather-family buffer starts at r * slot_bytes
size_t slot_bytes(const DtView &v, int count) {
  int64_t ext = v.derived ? v.derived->extent : 1;
  return (size_t)count * (size_t)ext * v.di.item;
}

bool resolve_dtype(MPI_Datatype dt, DtView &v) {
  if (dt < DERIVED_BASE) return base_dtinfo(dt, v.di);
  auto it = g_dtypes.find(dt);
  if (it == g_dtypes.end() || !it->second.committed) return false;
  v.derived = &it->second;
  return base_dtinfo(it->second.base, v.di);
}

// pack `count` items described by `v` from user memory into a
// contiguous base-element buffer (the convertor's pack direction)
void pack_dtype(const void *user, int count, const DtView &v,
                std::vector<char> &out) {
  size_t item = v.di.item;
  out.resize((size_t)count * v.elems_per_item() * item);
  if (v.contiguous()) {
    memcpy(out.data(), user, out.size());
    return;
  }
  const char *src = (const char *)user;
  char *dst = out.data();
  for (int c = 0; c < count; c++) {
    const char *base = src + (size_t)c * v.derived->extent * item;
    for (auto &b : v.derived->blocks) {
      memcpy(dst, base + (size_t)b.first * item, (size_t)b.second * item);
      dst += (size_t)b.second * item;
    }
  }
}

// unpack up to `avail_bytes` of contiguous base elements into user
// memory laid out per `v` (the convertor's unpack direction)
void unpack_dtype(void *user, int count, const DtView &v,
                  const char *wire, size_t avail_bytes) {
  size_t item = v.di.item;
  if (v.contiguous()) {
    size_t want = (size_t)count * v.elems_per_item() * item;
    memcpy(user, wire, avail_bytes < want ? avail_bytes : want);
    return;
  }
  char *dst = (char *)user;
  size_t taken = 0;
  for (int c = 0; c < count; c++) {
    char *base = dst + (size_t)c * v.derived->extent * item;
    for (auto &b : v.derived->blocks) {
      size_t n = (size_t)b.second * item;
      if (taken >= avail_bytes) return;
      if (taken + n > avail_bytes) n = avail_bytes - taken;
      memcpy(base + (size_t)b.first * item, wire + taken, n);
      taken += n;
    }
  }
}

// ------------------------------------------------------ matching engine

struct Message {
  int64_t src, tag, cid, seq;
  std::string dt;     // ndarray dtype or "" for bytes payload
  std::string data;   // raw payload bytes
  // rendezvous placeholder: entered into the matching stream at RTS
  // arrival (so a later eager frame can never overtake the announced
  // message — MPI non-overtaking); the bulk data fills it in place
  bool rndv_pending = false;
  int64_t rndv_id = 0;
  int64_t rndv_nbytes = 0;  // announced size, for Probe's count
  // matched-probe extraction (mprobe.c): a nonzero handle means an
  // Improbe owns this message — ordinary matching and probing skip it
  int64_t mhandle = 0;
};

// A receive request registered with the engine.  Blocking receives are
// Irecv+Wait over the same posted list, preserving MPI posting order.
struct Req {
  bool complete = false;
  bool is_recv = false;
  bool heap = false;               // user-facing (Isend/Irecv) vs stack
  int comm = MPI_COMM_WORLD;       // for MPI_SOURCE translation
  void *user_buf = nullptr;
  int count = 0;
  std::vector<char> scratch;       // landing zone for derived-type recvs
  bool needs_unpack = false;
  // Unpack plan captured AT POST TIME: MPI allows MPI_Type_free while a
  // receive is pending, so completion must not consult the dtype table.
  DtInfo plan_di{"|u1", 1};
  DtypeObj plan;
  MPI_Status status{};
};

struct Posted {
  Req *req;
  int64_t cid;
  int src_world;   // -1 = ANY
  int64_t tag;     // -1 = ANY
  char *land;      // where arriving bytes go (user buf or scratch)
  size_t want_bytes;
  size_t item;     // base element size (status._count unit)
};

// Imrecv claims parked on a rendezvous-pending extracted message:
// mhandle -> landing plan (guarded by match_mu)
std::map<int64_t, Posted> g_mrecv_wait;

struct Shim {
  int rank = -1, size = 0;
  int listen_fd = -1;
  static constexpr size_t BOOK_CAP = 4096;  // universe bound (see init)
  std::string host = "127.0.0.1";
  int listen_port = 0;
  std::vector<std::pair<std::string, int>> book;
  // modex capability strings, aligned with book ("" = none; "sm" =
  // the rank maps same-host shared-memory rings)
  std::vector<std::string> caps;
  std::map<int, int> conns;  // peer rank -> fd
  std::mutex conn_mu;
  std::mutex send_mu;
  std::deque<Message> unexpected;
  std::list<Posted> posted;
  std::map<int, Req *> reqs;
  int next_req = 1;
  std::mutex match_mu;
  std::condition_variable match_cv;
  std::atomic<bool> closing{false};
  std::thread accept_thread;            // joined FIRST at finalize
  std::vector<std::thread> threads;     // drain threads (joinable)
  std::vector<int> drain_fds;           // every fd a drain thread reads
  std::vector<int> bulk_fds;            // RECV side: peers' bulk-data fds
  std::atomic<int> bulk_closing{0};     // self-closes still in flight
  std::map<int, int> bulk_conns;        // SEND side: peer -> cached fd
  // bulk_mu guards the MAPS only; each peer's pushes serialize on its
  // own mutex so concurrent transfers to different peers stream in
  // parallel (the per-transfer-socket property the cache must keep)
  std::map<int, std::unique_ptr<std::mutex>> bulk_peer_mu;
  std::mutex bulk_mu;
  std::mutex threads_mu;
  // atomic: drain threads stamp CTS frames concurrently with app sends
  std::atomic<int64_t> seq{0};
  bool initialized = false;
  // rendezvous: sender-side id counter; receiver-side map of announced
  // transfers (src, rndv_id) -> original (tag, cid, seq) envelope, and
  // receives already matched to a placeholder awaiting bulk data
  // (rndv_wait is guarded by match_mu — it is part of matching state)
  // atomic: MPI_T_cvar_write mutates it at runtime while rendezvous
  // pushers and icoll threads read it concurrently
  std::atomic<int64_t> eager_limit{1 << 20};
  // atomic for the same reason: the rndv_cts_timeout cvar is writable
  // at runtime while rendezvous waiters read it from their threads
  std::atomic<double> cts_timeout{-1.0};  // <0: wait forever
  // SPC-style engine counters, surfaced as MPI_T pvars
  std::atomic<long long> ctr_eager_sends{0};
  std::atomic<long long> ctr_rndv_sends{0};
  std::atomic<long long> ctr_bytes_sent{0};
  std::atomic<int> inflight_isends{0};
  std::atomic<int64_t> next_rndv{1};
  std::map<std::pair<int64_t, int64_t>, std::array<int64_t, 3>> rndv_in;
  std::mutex rndv_mu;
  std::map<std::pair<int64_t, int64_t>, Posted> rndv_wait;

  ~Shim() {
    // error-path exit without MPI_Finalize: joinable std::threads would
    // std::terminate in their destructors — detach them (the process is
    // dying anyway; Finalize remains the clean path)
    if (accept_thread.joinable()) accept_thread.detach();
    for (auto &t : threads)
      if (t.joinable()) t.detach();
  }
};

// Intentionally leaked: detached bulk/rendezvous threads may still be
// unwinding when main() returns, and a static Shim destructor running
// under them (mutexes included) would be UB at process exit.  Finalize
// does the real cleanup; the one Shim's memory dies with the process.
Shim &g = *new Shim;

// ------------------------- same-host shared-memory transport --------
// The btl/sm role for the C plane (opal/mca/btl/sm's fast-box/FIFO,
// re-designed as one SPSC byte-stream ring per DIRECTED same-host
// pair).  The ENTIRE main channel of an sm-activated direction rides
// the ring — eager data, RTS, CTS, window tuples, barrier signals —
// so per-pair FIFO holds with no cross-transport reordering (the
// reference needs PML sequence numbers for exactly this; one
// transport per direction needs none).  Rendezvous BULK data keeps
// its dedicated TCP connections: a separate channel whose arrival
// order the placeholder design already decouples.
//
// Activation: both ranks advertise "sm" in their modex card, share a
// host string, and belong to the same init cohort (the contiguous
// WORLD block that initialized together — spawn joins stay TCP).
// Each rank creates its outbound rings, then waits briefly for the
// matching inbound files; a mapped inbound ring proves the shared
// /dev/shm namespace, which gates the OUTBOUND activation.  Inbound
// rings that appear late are still polled (pending list), so an
// asymmetric activation can never lose frames.

struct SmRingHdr {
  std::atomic<uint64_t> magic;
  char pad0[56];
  std::atomic<uint64_t> head;  // bytes produced (monotonic)
  char pad1[56];
  std::atomic<uint64_t> tail;  // bytes consumed (monotonic)
  char pad2[56];
};
constexpr uint64_t SM_MAGIC = 0x5A4F4D5049534D31ULL;  // "ZOMPISM1"
constexpr size_t SM_RING_BYTES = (size_t)4 << 20;     // stream capacity

struct SmRing {
  SmRingHdr *hdr = nullptr;
  char *data = nullptr;
  std::string path;
  bool creator = false;
  std::mutex wmu;     // outbound: serialize concurrent senders
  std::string rbuf;   // inbound: frame assembly across poll cycles
  int src = -1;       // inbound: the writing peer (diagnostics)
  // outbound overflow queue: the POLL thread must never block on a
  // full ring (it is the consumer that frees every OTHER ring — a
  // blocked poll thread deadlocks crossed large replies), so its
  // writes spill here and the poll loop itself drains the spill as
  // space appears.  Order: once non-empty, EVERY later frame to this
  // ring appends behind it (guarded by wmu).
  std::string pending;
};

// set inside sm_poll_loop: sends from the dispatch path must not block
thread_local bool tl_sm_poll_thread = false;

void sm_release(SmRing &r) {
  if (r.hdr) {
    munmap((void *)r.hdr, sizeof(SmRingHdr) + SM_RING_BYTES);
    r.hdr = nullptr;
  }
  if (r.creator && !r.path.empty()) shm_unlink(r.path.c_str());
}

std::map<int, std::unique_ptr<SmRing>> g_sm_out;   // dest -> ring
std::vector<std::unique_ptr<SmRing>> g_sm_in;
std::vector<std::pair<int, std::string>> g_sm_pending;  // late inbound
std::mutex g_sm_pending_mu;
std::thread g_sm_poll;
std::atomic<bool> g_sm_poll_up{false};

void dispatch_frame(const std::string &frame);  // defined with drains

bool sm_map(const std::string &path, bool create, SmRing &out) {
  int fd;
  size_t len = sizeof(SmRingHdr) + SM_RING_BYTES;
  if (create) {
    shm_unlink(path.c_str());  // stale ring from a crashed job
    fd = shm_open(path.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0 || ftruncate(fd, (off_t)len) != 0) {
      if (fd >= 0) close(fd);
      return false;
    }
  } else {
    fd = shm_open(path.c_str(), O_RDWR, 0600);
    if (fd < 0) return false;
    struct stat st{};
    if (fstat(fd, &st) != 0 || st.st_size < (off_t)len) {
      close(fd);  // peer still truncating: caller retries
      return false;
    }
  }
  char *m = (char *)mmap(nullptr, len, PROT_READ | PROT_WRITE,
                         MAP_SHARED, fd, 0);
  close(fd);
  if (m == MAP_FAILED) return false;
  out.hdr = (SmRingHdr *)m;
  out.data = m + sizeof(SmRingHdr);
  out.path = path;
  out.creator = create;
  if (create) {
    out.hdr->head.store(0, std::memory_order_relaxed);
    out.hdr->tail.store(0, std::memory_order_relaxed);
    out.hdr->magic.store(SM_MAGIC, std::memory_order_release);
  } else if (out.hdr->magic.load(std::memory_order_acquire) !=
             SM_MAGIC) {
    munmap(m, len);
    out.hdr = nullptr;
    return false;  // creator has not finished stamping
  }
  return true;
}

// the selection policy (shared by the modex card and sm_setup): rings
// on multi-core hosts, TCP on single-core, ZMPI_MCA_sm forces either
bool sm_enabled() {
  const char *force = getenv("ZMPI_MCA_sm");
  if (force && force[0]) return force[0] == '1';
  return sysconf(_SC_NPROCESSORS_ONLN) > 1;
}

// segment-name session tag: the launcher's ZMPI_SESSION when present
// (inherited by spawn children, whose coordinator port differs —
// keeps the whole job tree under ONE sweepable prefix), else the
// coordinator port (direct launches)
const char *session_tag() {
  const char *t = getenv("ZMPI_SESSION");
  if (t && t[0]) return t;
  t = getenv("ZMPI_COORD_PORT");
  return t && t[0] ? t : "0";
}

std::string sm_ring_path(int src, int dst) {
  char buf[96];
  snprintf(buf, sizeof buf, "/zompi_ring_%s_%d_%d", session_tag(), src,
           dst);
  return buf;
}

// stream `n` bytes into the ring, wrapping and waiting on the
// consumer; frames larger than the ring flow through in pieces (the
// reader frees space as it assembles)
int sm_write_bytes(SmRing *r, const char *p, size_t n) {
  size_t done = 0;
  int spins = 0;
  while (done < n) {
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    size_t free_ = SM_RING_BYTES - (size_t)(head - tail);
    if (free_ == 0) {
      if (g.closing.load()) return MPI_ERR_OTHER;
      if (++spins > 2000)
        std::this_thread::sleep_for(std::chrono::microseconds(50));
      continue;
    }
    spins = 0;
    size_t chunk = n - done < free_ ? n - done : free_;
    size_t off = (size_t)(head % SM_RING_BYTES);
    size_t first = chunk < SM_RING_BYTES - off ? chunk
                                               : SM_RING_BYTES - off;
    memcpy(r->data + off, p + done, first);
    memcpy(r->data, p + done + first, chunk - first);
    r->hdr->head.store(head + chunk, std::memory_order_release);
    done += chunk;
  }
  return MPI_SUCCESS;
}

// write whatever fits RIGHT NOW; returns bytes written (never waits)
size_t sm_write_avail(SmRing *r, const char *p, size_t n) {
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  size_t free_ = SM_RING_BYTES - (size_t)(head - tail);
  size_t chunk = n < free_ ? n : free_;
  if (chunk == 0) return 0;
  size_t off = (size_t)(head % SM_RING_BYTES);
  size_t first = chunk < SM_RING_BYTES - off ? chunk
                                             : SM_RING_BYTES - off;
  memcpy(r->data + off, p, first);
  memcpy(r->data, p + first, chunk - first);
  r->hdr->head.store(head + chunk, std::memory_order_release);
  return chunk;
}

// wmu must be held; pushes as much spilled data as fits
void sm_flush_pending_locked(SmRing *r) {
  if (r->pending.empty()) return;
  size_t put = sm_write_avail(r, r->pending.data(), r->pending.size());
  if (put) r->pending.erase(0, put);
}

int sm_send_frame(SmRing *r, const std::string &payload) {
  // same 4-byte little-endian length prefix as the TCP framing
  uint32_t len = (uint32_t)payload.size();
  char hdr[4] = {(char)(len & 0xFF), (char)((len >> 8) & 0xFF),
                 (char)((len >> 16) & 0xFF), (char)((len >> 24) & 0xFF)};
  std::lock_guard<std::mutex> lk(r->wmu);
  sm_flush_pending_locked(r);
  if (tl_sm_poll_thread) {
    // the poll thread NEVER blocks here (deadlock analysis above):
    // whatever does not fit spills behind any existing backlog
    if (r->pending.empty()) {
      size_t put = sm_write_avail(r, hdr, 4);
      if (put == 4) {
        size_t put2 =
            sm_write_avail(r, payload.data(), payload.size());
        if (put2 < payload.size())
          r->pending.append(payload, put2, std::string::npos);
        return MPI_SUCCESS;
      }
      r->pending.append(hdr + put, 4 - put);
      r->pending += payload;
      return MPI_SUCCESS;
    }
    r->pending.append(hdr, 4);
    r->pending += payload;
    return MPI_SUCCESS;
  }
  // app threads drain the spill first (order), then block as needed
  while (!r->pending.empty()) {
    sm_flush_pending_locked(r);
    if (r->pending.empty()) break;
    if (g.closing.load()) return MPI_ERR_OTHER;
    std::this_thread::sleep_for(std::chrono::microseconds(20));
  }
  int rc = sm_write_bytes(r, hdr, 4);
  if (rc != MPI_SUCCESS) return rc;
  return sm_write_bytes(r, payload.data(), payload.size());
}

// drain whatever the producer published; dispatch completed frames
bool sm_poll_ring(SmRing *r) {
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (head == tail) return false;
  size_t n = (size_t)(head - tail);
  size_t off = (size_t)(tail % SM_RING_BYTES);
  size_t first = n < SM_RING_BYTES - off ? n : SM_RING_BYTES - off;
  r->rbuf.append(r->data + off, first);
  r->rbuf.append(r->data, n - first);
  r->hdr->tail.store(head, std::memory_order_release);
  size_t pos = 0;
  while (r->rbuf.size() - pos >= 4) {
    const unsigned char *b = (const unsigned char *)r->rbuf.data() + pos;
    uint32_t len = (uint32_t)b[0] | ((uint32_t)b[1] << 8) |
                   ((uint32_t)b[2] << 16) | ((uint32_t)b[3] << 24);
    if (r->rbuf.size() - pos - 4 < len) break;
    dispatch_frame(r->rbuf.substr(pos + 4, len));
    pos += 4 + (size_t)len;
  }
  r->rbuf.erase(0, pos);
  return true;
}

void sm_poll_loop() {
  tl_sm_poll_thread = true;
  auto last_active = std::chrono::steady_clock::now();
  auto last_pending = last_active;
  while (!g.closing.load()) {
    bool any = false;
    for (auto &r : g_sm_in) any |= sm_poll_ring(r.get());
    // drain outbound spills (frames the dispatch path could not fit)
    for (auto &e : g_sm_out) {
      SmRing *r = e.second.get();
      if (r->pending.empty()) continue;
      std::lock_guard<std::mutex> lk(r->wmu);
      sm_flush_pending_locked(r);
      any = true;
    }
    auto now = std::chrono::steady_clock::now();
    // late inbound rings (peer activated after our init window)
    if (now - last_pending > std::chrono::milliseconds(100)) {
      last_pending = now;
      std::lock_guard<std::mutex> lk(g_sm_pending_mu);
      for (auto it = g_sm_pending.begin(); it != g_sm_pending.end();) {
        auto r = std::make_unique<SmRing>();
        if (sm_map(it->second, false, *r)) {
          r->src = it->first;
          g_sm_in.push_back(std::move(r));
          it = g_sm_pending.erase(it);
        } else {
          ++it;
        }
      }
    }
    if (any) {
      last_active = now;
      continue;
    }
    // stay HOT for a generous window after traffic: a ping-pong's
    // inter-arrival gap is a full RTT, and dozing inside it puts the
    // sleep latency ON the critical path of every message (measured:
    // a 200us window turned 2us rings into 208us).  Escalate only
    // through genuinely idle phases.
    auto idle = now - last_active;
    if (idle < std::chrono::milliseconds(20)) {
      // hot, but YIELD: a hard spin on a shared host steals the app
      // thread's core and puts a scheduler quantum (~ms) on every
      // message (measured both ways: hard spin 3.6ms, 100us dozes
      // 208us; yield keeps the poll sub-10us hot without starving)
      sched_yield();
      continue;
    }
    if (idle < std::chrono::milliseconds(200))
      std::this_thread::sleep_for(std::chrono::microseconds(20));
    else
      std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
}

// init-time cohort wiring; returns only after outbound rings exist and
// inbound rings were awaited (missing ones go to the pending list)
void sm_setup(int cohort_base, int cohort_size) {
  // hardware-aware default (the component-selection policy the
  // reference's MCA priorities exist for): the ring's polling thread
  // pays a scheduler quantum per handoff when there is only ONE core
  // (measured on this host: small messages 2x faster, 256 KB 5x
  // slower), so single-core hosts keep the kernel-blocking TCP path.
  // ZMPI_MCA_sm=1 forces the rings on, =0 forces them off; both
  // sides decide independently and asymmetric choices degrade safely
  // to TCP (activation requires the peer's mapped ring).
  if (!sm_enabled()) return;
  double wait_s = 5.0;
  if (const char *w = getenv("ZMPI_MCA_sm_wait"))
    if (w[0]) wait_s = atof(w);
  std::vector<int> peers;
  for (int j = cohort_base; j < cohort_base + cohort_size; j++) {
    if (j == g.rank || j >= (int)g.book.size()) continue;
    if (j >= (int)g.caps.size() ||
        g.caps[(size_t)j].find("sm") == std::string::npos)
      continue;
    if (g.book[(size_t)j].first != g.host) continue;  // other host
    auto r = std::make_unique<SmRing>();
    if (sm_map(sm_ring_path(g.rank, j), true, *r))
      g_sm_out[j] = std::move(r);  // activated after namespace proof
    peers.push_back(j);
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(wait_s);
  for (int j : peers) {
    bool mapped = false;
    while (std::chrono::steady_clock::now() < deadline) {
      auto r = std::make_unique<SmRing>();
      if (sm_map(sm_ring_path(j, g.rank), false, *r)) {
        r->src = j;
        g_sm_in.push_back(std::move(r));
        mapped = true;
        break;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    if (!mapped) {
      // shared namespace unproven: never WRITE to this peer via sm,
      // but keep looking for its ring so its frames are never lost
      auto oit = g_sm_out.find(j);
      if (oit != g_sm_out.end()) {
        sm_release(*oit->second);  // unmap AND unlink the orphan file
        g_sm_out.erase(oit);
      }
      std::lock_guard<std::mutex> lk(g_sm_pending_mu);
      g_sm_pending.push_back({j, sm_ring_path(j, g.rank)});
    }
  }
  if (!g_sm_out.empty() || !g_sm_in.empty() || !g_sm_pending.empty()) {
    g_sm_poll = std::thread(sm_poll_loop);
    g_sm_poll_up.store(true);
  }
}

void sm_teardown() {
  if (g_sm_poll_up.load()) {
    if (g_sm_poll.joinable()) g_sm_poll.join();  // closing already set
    g_sm_poll_up.store(false);
  }
  for (auto &e : g_sm_out) sm_release(*e.second);
  g_sm_out.clear();
  for (auto &r : g_sm_in) sm_release(*r);
  g_sm_in.clear();
  {
    std::lock_guard<std::mutex> lk(g_sm_pending_mu);
    g_sm_pending.clear();
  }
}

SmRing *sm_ring_to(int dest) {
  auto it = g_sm_out.find(dest);
  return it == g_sm_out.end() ? nullptr : it->second.get();
}

// fill a posted request from an arriving/unexpected message.
// match_mu must be held.
void deliver(const Posted &p, const Message &m) {
  size_t have = m.data.size();
  size_t copied = have > p.want_bytes ? p.want_bytes : have;
  memcpy(p.land, m.data.data(), copied);
  Req *r = p.req;
  r->status.MPI_SOURCE = (int)m.src;  // world rank; translated at Wait
  r->status.MPI_TAG = (int)m.tag;
  r->status.MPI_ERROR =
      have > p.want_bytes ? MPI_ERR_TRUNCATE : MPI_SUCCESS;
  // _count carries BYTES (dtype-agnostic, so MPI_Probe can fill it
  // without knowing the eventual receive type); Get_count converts
  r->status._count = (long long)copied;
  r->complete = true;
}

void send_cts(int64_t sender, int64_t rndv_id);

// Arrival path (drain threads + self-sends): posted list first, in post
// order; otherwise the unexpected queue (pml_ob1_recvfrag.c:342 shape).
// A rendezvous placeholder that matches a posted receive PARKS it in
// rndv_wait instead of completing — the bulk data finishes it later,
// but the match decision is made NOW, at announce position, so later
// eager frames cannot overtake (MPI non-overtaking).  The claim is what
// releases the sender (CTS), sent after match_mu drops.
void push_message(Message &&m) {
  int64_t cts_src = -1, cts_rid = -1;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (auto it = g.posted.begin(); it != g.posted.end(); ++it) {
      if (it->cid != m.cid) continue;
      if (it->src_world != MPI_ANY_SOURCE && it->src_world != m.src)
        continue;
      if (it->tag != MPI_ANY_TAG && it->tag != m.tag) continue;
      if (m.rndv_pending) {
        g.rndv_wait[{m.src, m.rndv_id}] = *it;
        g.posted.erase(it);
        cts_src = m.src;
        cts_rid = m.rndv_id;
        break;
      }
      deliver(*it, m);
      g.posted.erase(it);
      g.match_cv.notify_all();
      return;
    }
    if (cts_src < 0) g.unexpected.push_back(std::move(m));
  }
  if (cts_src >= 0) send_cts(cts_src, cts_rid);
  g.match_cv.notify_all();
}

// Post a receive: unexpected queue first (arrival order), else posted.
// Returns the request handle.
// capture the landing plan for a receive into `r`: contiguous types
// land in the user buffer, derived types in scratch with the typemap
// snapshotted (survives MPI_Type_free).  Shared by post_recv and the
// matched-probe receive so the two paths can never diverge.
char *prepare_landing(Req *r, const DtView &v, size_t &want_bytes) {
  want_bytes = (size_t)r->count * v.elems_per_item() * v.di.item;
  r->plan_di = v.di;
  if (v.contiguous()) return (char *)r->user_buf;
  r->scratch.resize(want_bytes);
  r->needs_unpack = true;
  r->plan = *v.derived;
  return r->scratch.data();
}

int post_recv(Req *r, const DtView &v, int64_t cid, int src_world,
              int64_t tag) {
  size_t base_bytes;
  char *land = prepare_landing(r, v, base_bytes);
  Posted p{r, cid, src_world, tag, land, base_bytes, v.di.item};
  int handle;
  int64_t cts_src = -1, cts_rid = -1;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    handle = g.next_req++;
    g.reqs[handle] = r;
    bool matched = false;
    for (auto it = g.unexpected.begin(); it != g.unexpected.end(); ++it) {
      if (it->mhandle) continue;  // owned by a matched probe
      if (it->cid != cid) continue;
      if (src_world != MPI_ANY_SOURCE && it->src != src_world) continue;
      if (tag != MPI_ANY_TAG && it->tag != tag) continue;
      if (it->rndv_pending) {
        // the first matching message is an announced (not yet arrived)
        // rendezvous: claim it — this is the moment the sender may
        // release the payload (CTS after the lock drops)
        g.rndv_wait[{it->src, it->rndv_id}] = p;
        cts_src = it->src;
        cts_rid = it->rndv_id;
        g.unexpected.erase(it);
        matched = true;
        break;
      }
      deliver(p, *it);
      g.unexpected.erase(it);
      matched = true;
      break;
    }
    if (!matched) g.posted.push_back(p);
  }
  if (cts_src >= 0) send_cts(cts_src, cts_rid);
  return handle;
}

// finish a completed receive on the calling thread (derived unpack,
// from the plan captured at post time)
void finish_recv(Req *r) {
  if (r->needs_unpack) {
    DtView v;
    v.di = r->plan_di;
    v.derived = &r->plan;
    // _count is BYTES (the probe-compatible unit) — cap the unpack at
    // exactly the received payload, not a multiple of it
    size_t avail = (size_t)r->status._count;
    unpack_dtype(r->user_buf, r->count, v, r->scratch.data(), avail);
    r->needs_unpack = false;
    r->scratch.clear();
  }
}

// remove every engine registration of `r` (posted entry, parked
// rendezvous claim, handle slot); match_mu must be held.  Keeps a
// stack-allocated Req from outliving its registration on error paths.
void deregister_locked(int handle, Req *r) {
  g.posted.remove_if([r](const Posted &p) { return p.req == r; });
  for (auto it = g_mrecv_wait.begin(); it != g_mrecv_wait.end();) {
    if (it->second.req == r) it = g_mrecv_wait.erase(it);
    else ++it;
  }
  for (auto it = g.rndv_wait.begin(); it != g.rndv_wait.end();) {
    if (it->second.req == r) it = g.rndv_wait.erase(it);
    else ++it;
  }
  g.reqs.erase(handle);
}

// wait for handle; fills status (world-rank source), frees the slot.
// On shutdown — or past `timeout_sec` when >= 0 — the request is fully
// deregistered before returning, so a stack-allocated Req never
// outlives its registration.
int wait_handle_impl(int handle, MPI_Status *status,
                     double timeout_sec = -1.0) {
  Req *r;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(
                      timeout_sec < 0 ? 0.0 : timeout_sec);
  {
    std::unique_lock<std::mutex> lk(g.match_mu);
    auto it = g.reqs.find(handle);
    if (it == g.reqs.end()) return MPI_ERR_REQUEST;
    r = it->second;
    while (!r->complete) {
      g.match_cv.wait_for(lk, std::chrono::milliseconds(100));
      bool expired = timeout_sec >= 0 &&
                     std::chrono::steady_clock::now() > deadline;
      if (g.closing.load() || (expired && !r->complete)) {
        deregister_locked(handle, r);
        bool heap = r->heap;
        if (heap) delete r;
        return MPI_ERR_OTHER;
      }
    }
    g.reqs.erase(it);
  }
  finish_recv(r);
  int rc = r->status.MPI_ERROR;
  if (status) *status = r->status;
  if (r->heap) delete r;
  return rc;
}

// internal (collectives): stack Req, world-rank statuses
int wait_handle(int handle, MPI_Status *status) {
  return wait_handle_impl(handle, status);
}

// ------------------------------------------------------------ endpoints

void drain_loop(int fd);

void start_drain(int fd) {
  std::lock_guard<std::mutex> lk(g.threads_mu);
  if (g.closing.load()) {
    // Finalize already swept drain_fds: a drain started now would never
    // be shut down and would hang the join loop
    close(fd);
    return;
  }
  g.drain_fds.push_back(fd);
  g.threads.emplace_back(drain_loop, fd);
}

// Receiver side of bulk-data connections (hello ["d"]): one per
// SENDING peer (the sender caches and reuses it across transfers), EOF
// when that sender's Finalize closes its cache.  A joinable thread +
// a Finalize-swept fd per connection would accumulate (pthread stacks
// of exited joinable threads are retained until join), so these drains
// run detached, register in bulk_fds only for the Finalize shutdown
// sweep, and deregister + close their own fd on exit — the self-close
// is safe because the closing thread is the only reader.
void start_bulk_drain(int fd) {
  {
    std::lock_guard<std::mutex> lk(g.threads_mu);
    g.bulk_fds.push_back(fd);
  }
  std::thread([fd]() {
    drain_loop(fd);
    // deregister (so Finalize's shutdown sweep can't touch a reused fd
    // number) while flagging the close as in-flight — Finalize waits
    // for BOTH lists to drain, so a straggler's close-by-number can
    // never hit a descriptor the application opens after Finalize
    {
      std::lock_guard<std::mutex> lk(g.threads_mu);
      auto &v = g.bulk_fds;
      v.erase(std::remove(v.begin(), v.end(), fd), v.end());
      g.bulk_closing.fetch_add(1);
    }
    close(fd);
    g.bulk_closing.fetch_sub(1);
  }).detach();
}

int endpoint(int dest);
int peer_send_frame(int dest, const std::string &payload);

// rendezvous constants — wire-identical to pt2pt/tcp.py:62-66
constexpr int64_t RNDV_DATA_CID = 0x7FF9;
constexpr int64_t RNDV_CTS_CID = 0x7FFA;
constexpr const char *RTS_MARK = "__zmpi_rndv_rts__";

// one-sided plane: request frames are tuples on this reserved cid,
// applied by the drain (the AM-window shape of osc/am.py, C-side);
// replies are plain messages on the same cid matched by reply tag
constexpr int64_t WIN_CID = 0x7FF8;
void handle_win_frame(int64_t src, const DssVal &t);

// CTS leaves only when a receive CLAIMS the announced message — the
// Python plane's flow-control contract ("an unmatched multi-GB send
// must park at the SENDER, not in the receiver's unexpected queue",
// tcp.py send docstring; _resolve_rndv runs from on_match).  Called
// AFTER match_mu is released by the claiming path.
void send_cts(int64_t sender, int64_t rndv_id) {
  if (g.closing.load()) return;
  std::string cts;
  put_varint(cts, 5);
  put_int(cts, g.rank);
  put_int(cts, rndv_id);
  put_int(cts, RNDV_CTS_CID);
  put_int(cts, g.seq++);
  put_bytes(cts, "", 0);
  peer_send_frame((int)sender, cts);
  // NOTE: a sender dying AFTER this CTS (bulk connect/push failure)
  // leaves the claimed receive parked — the peer-death-without-fault-
  // tolerance class, surfaced on the sender as an error; job-level
  // recovery is the errhandler's business, as on the Python plane.
}

// Engine-level RTS note (the match half of TcpProc._resolve_rndv):
// record the announce and enter a PLACEHOLDER into the matching stream
// at this position, so the announced message keeps its place in the
// (src, tag, cid) order.  No CTS yet — the payload stays parked at the
// sender until a receive claims the placeholder.
void answer_rts(const std::vector<DssVal> &vals) {
  int64_t sender = vals[4].items[1].i;
  int64_t rndv_id = vals[4].items[2].i;
  {
    std::lock_guard<std::mutex> lk(g.rndv_mu);
    g.rndv_in[{sender, rndv_id}] = {vals[1].i, vals[2].i, vals[3].i};
  }
  Message ph;
  ph.src = vals[0].i;
  ph.tag = vals[1].i;
  ph.cid = vals[2].i;
  ph.seq = vals[3].i;
  ph.rndv_pending = true;
  ph.rndv_id = rndv_id;
  ph.rndv_nbytes = vals[4].items[3].i;
  push_message(std::move(ph));
}

// Bulk-data arrival: complete the receive the placeholder claimed, or
// fill the placeholder where it sits in the unexpected queue (position
// preserved either way).
void land_rndv_data(Message &&m, int64_t rid) {
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    auto w = g.rndv_wait.find({m.src, rid});
    if (w != g.rndv_wait.end()) {
      deliver(w->second, m);
      g.rndv_wait.erase(w);
      g.match_cv.notify_all();
      return;
    }
    for (auto it = g.unexpected.begin(); it != g.unexpected.end();
         ++it) {
      Message &u = *it;
      if (u.rndv_pending && u.src == m.src && u.rndv_id == rid) {
        u.dt = std::move(m.dt);
        u.data = std::move(m.data);
        u.rndv_pending = false;
        // an Imrecv may already be parked on this extracted message:
        // deliver into its buffer now and retire the placeholder
        if (u.mhandle) {
          auto mw = g_mrecv_wait.find(u.mhandle);
          if (mw != g_mrecv_wait.end()) {
            deliver(mw->second, u);
            g_mrecv_wait.erase(mw);
            g.unexpected.erase(it);
          }
        }
        g.match_cv.notify_all();
        return;
      }
    }
  }
  // placeholder vanished (shutdown race): deliver by normal matching
  push_message(std::move(m));
}

// one inbound frame, from EITHER transport (TCP drains and the sm
// poll loop feed the identical dispatch)
void dispatch_frame(const std::string &frame) {
  std::vector<DssVal> vals;
  if (!parse_all(frame, vals) || vals.size() != 5) return;
  if (vals[4].tag == T_TUPLE && vals[4].items.size() == 4 &&
      vals[4].items[0].tag == T_STR && vals[4].items[0].s == RTS_MARK) {
    answer_rts(vals);
    return;
  }
  if (vals[2].i == WIN_CID && vals[4].tag == T_TUPLE) {
    handle_win_frame(vals[0].i, vals[4]);
    return;
  }
  Message m;
  m.src = vals[0].i;
  m.tag = vals[1].i;
  m.cid = vals[2].i;
  m.seq = vals[3].i;
  if (vals[4].tag == T_NDARRAY) {
    m.dt = vals[4].dt;
    m.data = vals[4].data;
  } else if (vals[4].tag == T_BYTES || vals[4].tag == T_STR) {
    m.data = vals[4].s;
  }
  if (m.cid == RNDV_DATA_CID) {
    // bulk data of an announced transfer: re-frame under the envelope
    // the RTS carried, then land it on the placeholder/claimed recv
    int64_t rid = m.tag;
    std::array<int64_t, 3> env;
    {
      std::lock_guard<std::mutex> lk(g.rndv_mu);
      auto it = g.rndv_in.find({m.src, rid});
      if (it == g.rndv_in.end()) return;  // unannounced: drop
      env = it->second;
      g.rndv_in.erase(it);
    }
    m.tag = env[0];
    m.cid = env[1];
    m.seq = env[2];
    land_rndv_data(std::move(m), rid);
    return;
  }
  push_message(std::move(m));
}

void drain_loop(int fd) {
  std::string frame;
  while (!g.closing.load()) {
    if (!recv_frame(fd, frame)) return;
    dispatch_frame(frame);
  }
}

void accept_loop() {
  while (!g.closing.load()) {
    int fd = accept(g.listen_fd, nullptr, nullptr);
    if (fd < 0) return;
    set_cloexec(fd);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::string hello;
    if (!recv_frame(fd, hello)) { close(fd); continue; }
    std::vector<DssVal> vals;
    if (!parse_all(hello, vals) || vals.empty()) { close(fd); continue; }
    if (vals[0].tag == T_INT) {
      std::lock_guard<std::mutex> lk(g.conn_mu);
      if (!g.conns.count((int)vals[0].i)) g.conns[(int)vals[0].i] = fd;
    } else if (vals[0].tag == T_LIST) {
      // rendezvous bulk connection (hello ["d"]): transient,
      // self-closing, never registered for sends
      start_bulk_drain(fd);
      continue;
    }
    start_drain(fd);
  }
}

int endpoint(int dest) {
  {
    std::lock_guard<std::mutex> lk(g.conn_mu);
    auto it = g.conns.find(dest);
    if (it != g.conns.end()) return it->second;
  }
  int fd = tcp_connect(g.book[dest].first, g.book[dest].second);
  if (fd < 0) return -1;
  std::string hello;
  put_varint(hello, 1);
  put_int(hello, g.rank);
  if (!send_frame(fd, hello)) { close(fd); return -1; }
  {
    std::lock_guard<std::mutex> lk(g.conn_mu);
    auto it = g.conns.find(dest);
    if (it != g.conns.end()) {
      // crossed simultaneous connect: the peer may have registered OUR
      // socket (it saw the hello) — closing it would RST the peer's
      // first frames.  Keep both; each side sends on its own choice.
      start_drain(fd);
      return it->second;
    }
    g.conns[dest] = fd;
  }
  start_drain(fd);
  return fd;
}

// ONE main-channel frame to a peer: the sm ring when the direction is
// activated (entire channel, preserving per-direction FIFO), else the
// TCP endpoint under the global send lock.  Every main-channel
// producer routes through here — mixing transports per direction
// would break the matching order.
int peer_send_frame(int dest, const std::string &payload) {
  if (SmRing *r = sm_ring_to(dest)) return sm_send_frame(r, payload);
  int fd = endpoint(dest);
  if (fd < 0) return MPI_ERR_OTHER;
  std::lock_guard<std::mutex> lk(g.send_mu);
  return send_frame(fd, payload) ? MPI_SUCCESS : MPI_ERR_OTHER;
}

// RTS/CTS rendezvous send (pml_ob1_sendreq.c:768's protocol, the wire
// shape of TcpProc._send_rndv), split in two so MPI_Isend can put the
// ANNOUNCE on the wire from the calling thread — the RTS's position on
// the control socket is what fixes the message's matching order
// (non-overtaking), so it must precede any later frame to the peer —
// while the CTS wait + bulk push run wherever convenient.

// Announce: post the CTS receive, then send the RTS inline.  On success
// fills rid/handle; the heap CTS Req is owned by the handle machinery.
int rndv_announce(size_t count, const DtInfo &di, int dest, int64_t tag,
                  int64_t cid, int64_t &rid_out, int &handle_out) {
  int64_t rid = g.next_rndv.fetch_add(1);
  static char dummy;  // zero-byte CTS landing, shared is fine
  Req *r = new Req;
  r->is_recv = true;
  r->heap = true;
  r->user_buf = &dummy;
  r->count = 0;
  DtView v;  // byte view; CTS payload is empty
  v.di = {"|u1", 1};
  int handle = post_recv(r, v, RNDV_CTS_CID, dest, rid);
  // every early return must deregister: a stale posted entry would let
  // a late CTS write through a freed request
  auto abort_cts = [&]() {
    std::lock_guard<std::mutex> lk(g.match_mu);
    deregister_locked(handle, r);
    delete r;
    return MPI_ERR_OTHER;
  };
  std::string rts;
  put_varint(rts, 5);
  put_int(rts, g.rank);
  put_int(rts, tag);
  put_int(rts, cid);
  put_int(rts, g.seq++);
  rts.push_back((char)T_TUPLE);
  put_varint(rts, 4);
  put_str(rts, RTS_MARK);
  put_int(rts, g.rank);
  put_int(rts, rid);
  put_int(rts, (int64_t)(count * di.item));
  if (peer_send_frame(dest, rts) != MPI_SUCCESS) return abort_cts();
  rid_out = rid;
  handle_out = handle;
  return MPI_SUCCESS;
}

// Complete: wait for the receiver's CTS (it arrives when a receive
// MATCHES the announce, so a blocking send legally waits as long as the
// receiver computes — infinite by default, MPI blocking-send law;
// ZMPI_MCA_rndv_cts_timeout bounds it for jobs preferring typed errors
// over peer-death hangs), then push the data frame over a dedicated
// bulk connection so the control socket never carries a multi-MB write.
// Cached per-peer bulk connections: a TCP connect + slow-start per
// multi-MB transfer costs more than the transfer at larger sizes, so
// the first rendezvous to a peer opens the hello-["d"] connection and
// later ones reuse it (frames serialize under bulk_mu; the receiver's
// bulk drain loops over frames and self-closes on our Finalize EOF).
int bulk_endpoint_locked(int dest) {
  auto it = g.bulk_conns.find(dest);
  if (it != g.bulk_conns.end()) return it->second;
  int dfd = tcp_connect(g.book[dest].first, g.book[dest].second);
  if (dfd < 0) return -1;
  std::string hello;
  put_varint(hello, 1);
  hello.push_back((char)T_LIST);
  put_varint(hello, 1);
  put_str(hello, "d");
  if (!send_frame(dfd, hello)) {
    close(dfd);
    return -1;
  }
  g.bulk_conns[dest] = dfd;
  return dfd;
}

int rndv_complete(const void *buf, size_t count, const DtInfo &di,
                  int dest, int64_t rid, int handle) {
  MPI_Status st{};
  int rc = wait_handle_impl(handle, &st, g.cts_timeout);
  if (rc != MPI_SUCCESS) return rc;
  std::string payload;
  put_varint(payload, 5);
  put_int(payload, g.rank);
  put_int(payload, rid);
  put_int(payload, RNDV_DATA_CID);
  put_int(payload, g.seq++);
  put_ndarray_1d(payload, di.tag, buf, count, di.item);
  std::mutex *peer_mu;
  {
    std::lock_guard<std::mutex> lk(g.bulk_mu);
    auto &slot = g.bulk_peer_mu[dest];
    if (!slot) slot.reset(new std::mutex);
    peer_mu = slot.get();
  }
  std::lock_guard<std::mutex> plk(*peer_mu);
  int dfd;
  {
    std::lock_guard<std::mutex> lk(g.bulk_mu);
    dfd = bulk_endpoint_locked(dest);
  }
  bool ok = dfd >= 0 && send_frame(dfd, payload);
  if (!ok && dfd >= 0) {
    // a broken cached connection gets one fresh retry
    std::lock_guard<std::mutex> lk(g.bulk_mu);
    close(dfd);
    g.bulk_conns.erase(dest);
    dfd = bulk_endpoint_locked(dest);
    ok = dfd >= 0 && send_frame(dfd, payload);
  }
  return ok ? MPI_SUCCESS : MPI_ERR_OTHER;
}

int wire_send_rndv(const void *buf, size_t count, const DtInfo &di,
                   int dest, int64_t tag, int64_t cid) {
  int64_t rid;
  int handle;
  int rc = rndv_announce(count, di, dest, tag, cid, rid, handle);
  if (rc != MPI_SUCCESS) return rc;
  return rndv_complete(buf, count, di, dest, rid, handle);
}

// DSS reply carrying an address book (the modex coordinator's answer,
// shared by MPI_Init's rank-0 coordinator and the spawn coordinator)
std::string pack_address_book(
    const std::vector<std::pair<std::string, int>> &book,
    const std::vector<std::string> *caps = nullptr) {
  std::string reply;
  put_varint(reply, 1);
  reply.push_back((char)T_LIST);
  put_varint(reply, book.size());
  for (size_t i = 0; i < book.size(); i++) {
    const std::string cap =
        caps && i < caps->size() ? (*caps)[i] : std::string();
    reply.push_back((char)T_LIST);
    put_varint(reply, cap.empty() ? 2 : 3);
    put_str(reply, book[i].first);
    put_int(reply, book[i].second);
    if (!cap.empty()) put_str(reply, cap);
  }
  return reply;
}

// wire-send `count` contiguous base elements (world-rank addressing).
// allow_rndv selects the protocol split: USER point-to-point sends
// rendezvous above the eager limit (flow control for unmatched sends);
// collective-internal sends stay eager at any size — their receives are
// posted by the same synchronized algorithm on every rank, so the
// unexpected-queue exposure is one round's worth by construction, and
// eager keeps the ring/pairwise exchanges deadlock-free (the same
// reasoning as the allgather ring's buffered-eager note below).
int wire_send(const void *buf, size_t count, const DtInfo &di, int dest,
              int64_t tag, int64_t cid, bool allow_rndv = false,
              bool force_rndv = false) {
  if (dest == g.rank) {
    if (force_rndv) {
      // synchronous self-send: completion must imply the receive is
      // matched, so wait until a matching receive is POSTED before
      // delivering (unmatched single-threaded self-Ssend deadlocks,
      // as the spec's contract implies; a concurrent thread's recv
      // releases it)
      std::unique_lock<std::mutex> lk(g.match_mu);
      for (;;) {
        bool posted = false;
        for (auto &pp : g.posted) {
          if (pp.cid != cid) continue;
          if (pp.src_world != MPI_ANY_SOURCE && pp.src_world != g.rank)
            continue;
          if (pp.tag != MPI_ANY_TAG && pp.tag != tag) continue;
          posted = true;
          break;
        }
        if (posted) break;
        g.match_cv.wait_for(lk, std::chrono::milliseconds(100));
        if (g.closing.load()) return MPI_ERR_OTHER;
      }
    }
    Message m;
    m.src = g.rank; m.tag = tag; m.cid = cid; m.seq = g.seq++;
    m.dt = di.tag;
    m.data.assign((const char *)buf, count * di.item);
    push_message(std::move(m));
    return MPI_SUCCESS;
  }
  // 4-byte framing bounds any single message below 4 GiB (the Python
  // plane shares the limit — struct "<I"); reject with a typed error
  // rather than let send_frame fail opaquely after the RTS handshake
  if (count * di.item > 0xFFFF0000ull) return MPI_ERR_COUNT;
  if (force_rndv ||
      (allow_rndv && (int64_t)(count * di.item) > g.eager_limit)) {
    int rc = wire_send_rndv(buf, count, di, dest, tag, cid);
    if (rc == MPI_SUCCESS) {  // pvars count sends that reached the wire
      g.ctr_rndv_sends.fetch_add(1, std::memory_order_relaxed);
      g.ctr_bytes_sent.fetch_add((long long)(count * di.item),
                                 std::memory_order_relaxed);
    }
    return rc;
  }
  std::string payload;
  put_varint(payload, 5);
  put_int(payload, g.rank);
  put_int(payload, tag);
  put_int(payload, cid);
  put_int(payload, g.seq++);
  put_ndarray_1d(payload, di.tag, buf, count, di.item);
  if (peer_send_frame(dest, payload) != MPI_SUCCESS)
    return MPI_ERR_OTHER;
  g.ctr_eager_sends.fetch_add(1, std::memory_order_relaxed);
  g.ctr_bytes_sent.fetch_add((long long)(count * di.item),
                             std::memory_order_relaxed);
  return MPI_SUCCESS;
}

// blocking internal recv of contiguous base elements (world addressing);
// used by the collective algorithms
int raw_recv(void *buf, int count, MPI_Datatype dt, int source, int64_t tag,
             int64_t cid, MPI_Status *status) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  Req r;
  r.is_recv = true;
  r.user_buf = buf;
  r.count = count;
  int handle = post_recv(&r, v, cid, source, tag);
  return wait_handle(handle, status);
}

int raw_send(const void *buf, int count, MPI_Datatype dt, int dest,
             int64_t tag, int64_t cid, bool allow_rndv = false,
             bool force_rndv = false) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  if (v.contiguous())
    return wire_send(buf, (size_t)count * v.elems_per_item(), v.di, dest,
                     tag, cid, allow_rndv, force_rndv);
  std::vector<char> packed;
  pack_dtype(buf, count, v, packed);
  return wire_send(packed.data(), packed.size() / v.di.item, v.di, dest,
                   tag, cid, allow_rndv, force_rndv);
}

// --------------------------------------------------------- communicators

struct CommObj {
  std::vector<int> group;   // local rank -> world rank
  int local_rank = 0;
  int64_t cid_pt2pt, cid_coll, cid_bar;
  int64_t coll_seq = 0;
  uint64_t child_seq = 0;
  uint64_t win_seq = 0;               // per-comm window-id sequence
  // intercommunicator: non-empty => pt2pt addresses THIS group (remote
  // ranks), local_rank/group stay the local side (intercomm_create.c)
  std::vector<int> remote;
  std::vector<int> cart_dims;         // non-empty => Cartesian topology
  std::vector<int> cart_periods;
  std::vector<int> graph_index;       // non-empty => graph topology
  std::vector<int> graph_edges;
  bool dist = false;                  // distributed graph (adjacent form)
  bool dist_weighted = false;
  std::vector<int> dist_src;          // recv neighbors, in order
  std::vector<int> dist_dst;          // send neighbors, in order
  std::vector<int> dist_srcw;         // weights (when dist_weighted)
  std::vector<int> dist_dstw;
};

std::map<int, CommObj> g_comms;
int g_next_comm = 2;  // 0 = WORLD, 1 = SELF

// group table: a group is a list of world ranks (the ompi/group analog
// with int handles)
struct GroupObj {
  std::vector<int> ranks;  // group rank -> world rank
};
std::map<int, GroupObj> g_groups;
int g_next_group = 1;

GroupObj *lookup_group(int grp) {
  auto it = g_groups.find(grp);
  return it == g_groups.end() ? nullptr : &it->second;
}

int register_group(std::vector<int> ranks) {
  int handle = g_next_group++;
  g_groups[handle] = GroupObj{std::move(ranks)};
  return handle;
}

// MPI-IO file table (definitions with the other global state so
// MPI_Finalize can sweep leaked fds)
struct FileObj {
  int fd = -1;
  int amode = 0;
  int comm = MPI_COMM_WORLD;
  int64_t pointer = 0;  // individual pointer, ETYPES (bytes w/ default view)
  std::string path;
  // file view (io_ompio's etype/filetype template, byte-flattened):
  // the filetype tiles the file from `disp`; IO addresses payload
  // bytes inside the tiles.  Default view = identity (etype BYTE,
  // filetype BYTE) — offsets are then plain bytes.
  int64_t view_disp = 0;
  MPI_Datatype view_etype = 0 /* MPI_BYTE */;
  MPI_Datatype view_ftype = 0;
  std::vector<std::pair<int64_t, int64_t>> vblocks;  // (off,len) bytes
  int64_t vtile = 1;      // filetype extent (bytes)
  int64_t vpayload = 1;   // payload bytes per tile
  int64_t etype_size = 1;
  bool identity_view = true;
  // shared file pointer (sharedfp/lockedfile's shape): sidecar file,
  // flock-serialized fetch-and-add; value in ETYPES
  std::string sfp_path;
  bool atomic_mode = false;
  // one outstanding split collective (read/write_all|ordered_begin)
  bool split_active = false;
  MPI_Status split_status{};
};

std::map<int, FileObj> g_files;
int g_next_file = 1;
// guards map MUTATION vs the nonblocking-IO threads' lookups; node
// pointers stay valid across inserts (std::map), and closing a file
// with IO in flight is erroneous per MPI, so held FileObj*s are safe
std::mutex g_files_mu;

CommObj *lookup_comm(MPI_Comm c) {
  auto it = g_comms.find(c);
  return it == g_comms.end() ? nullptr : &it->second;
}

int world_of(const CommObj &c, int local) {
  return (local >= 0 && local < (int)c.group.size()) ? c.group[local] : -1;
}

int local_of(const CommObj &c, int world) {
  for (size_t i = 0; i < c.group.size(); i++)
    if (c.group[i] == world) return (int)i;
  return MPI_ANY_SOURCE;
}

// point-to-point PEER group: on an intercommunicator ranks address the
// REMOTE group (MPI-3.1 6.6.1); intracommunicators address themselves
const std::vector<int> &peer_group(const CommObj &c) {
  return c.remote.empty() ? c.group : c.remote;
}

int peer_world_of(const CommObj &c, int rank) {
  const std::vector<int> &pg = peer_group(c);
  return (rank >= 0 && rank < (int)pg.size()) ? pg[rank] : -1;
}

int peer_local_of(const CommObj &c, int world) {
  const std::vector<int> &pg = peer_group(c);
  for (size_t i = 0; i < pg.size(); i++)
    if (pg[i] == world) return (int)i;
  return MPI_ANY_SOURCE;
}

uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Deterministic child context ids: every member of the parent computes
// the same triple, so no agreement round is needed.  Disjoint sibling
// groups may share a cid — harmless, they share no endpoints.  The low
// reserved ids (0 pt2pt, 0x7FFC coll, 0x7FFD barrier, SELF block) are
// below 0x10000; derived cids are forced above it.
void derive_cids(const CommObj &parent, uint64_t salt, CommObj &child) {
  uint64_t base =
      mix64(mix64((uint64_t)parent.cid_pt2pt) ^
            (parent.child_seq * 0x100000001B3ULL) ^ salt);
  base = (base & 0x3FFFFFFFFFFFULL) | 0x10000ULL;
  child.cid_pt2pt = (int64_t)base;
  child.cid_coll = (int64_t)base + 1;
  child.cid_bar = (int64_t)base + 2;
}

// ----------------------------------------------------------- reductions

template <typename T>
int reduce_arith(T *acc, const T *in, int n, MPI_Op op) {
  switch (op) {
    case MPI_SUM:
      for (int i = 0; i < n; i++) acc[i] = acc[i] + in[i];
      return MPI_SUCCESS;
    case MPI_PROD:
      for (int i = 0; i < n; i++) acc[i] = acc[i] * in[i];
      return MPI_SUCCESS;
    case MPI_MAX:
      for (int i = 0; i < n; i++) acc[i] = acc[i] > in[i] ? acc[i] : in[i];
      return MPI_SUCCESS;
    case MPI_MIN:
      for (int i = 0; i < n; i++) acc[i] = acc[i] < in[i] ? acc[i] : in[i];
      return MPI_SUCCESS;
    case MPI_LAND:
      for (int i = 0; i < n; i++) acc[i] = (T)(acc[i] && in[i]);
      return MPI_SUCCESS;
    case MPI_LOR:
      for (int i = 0; i < n; i++) acc[i] = (T)(acc[i] || in[i]);
      return MPI_SUCCESS;
    case MPI_LXOR:
      for (int i = 0; i < n; i++) acc[i] = (T)(!acc[i] != !in[i]);
      return MPI_SUCCESS;
  }
  return MPI_ERR_OP;
}

template <typename T>
int reduce_int(T *acc, const T *in, int n, MPI_Op op) {
  switch (op) {
    case MPI_BAND:
      for (int i = 0; i < n; i++) acc[i] = acc[i] & in[i];
      return MPI_SUCCESS;
    case MPI_BOR:
      for (int i = 0; i < n; i++) acc[i] = acc[i] | in[i];
      return MPI_SUCCESS;
    case MPI_BXOR:
      for (int i = 0; i < n; i++) acc[i] = acc[i] ^ in[i];
      return MPI_SUCCESS;
  }
  return reduce_arith(acc, in, n, op);
}

// user-defined reduction operators (ompi/op/op.c:243-287's table,
// reduced to a map); handles from 0x20 up
struct UserOp {
  MPI_User_function *fn;
  bool commute;
};
std::map<MPI_Op, UserOp> g_user_ops;
MPI_Op g_next_op = 0x20;

// MINLOC/MAXLOC over (value, index) pair structs: winner by value,
// ties broken by the LOWER index (MPI-3.1 §5.9.4)
template <typename Pair>
void reduce_loc(Pair *acc, const Pair *in, int n, bool maxloc) {
  for (int i = 0; i < n; i++) {
    bool take = maxloc ? in[i].v > acc[i].v : in[i].v < acc[i].v;
    if (in[i].v == acc[i].v) take = in[i].i < acc[i].i;
    if (take) acc[i] = in[i];
  }
}

struct PairFloatInt { float v; int i; };
struct PairDoubleInt { double v; int i; };
struct PairLongInt { long v; int i; };
struct PairShortInt { short v; int i; };
struct Pair2Int { int v; int i; };

int reduce_loc_buf(void *acc, const void *in, int n, MPI_Datatype dt,
                   bool maxloc) {
  switch (dt) {
    case MPI_2INT:
      reduce_loc((Pair2Int *)acc, (const Pair2Int *)in, n, maxloc);
      return MPI_SUCCESS;
    case MPI_FLOAT_INT:
      reduce_loc((PairFloatInt *)acc, (const PairFloatInt *)in,
                        n, maxloc);
      return MPI_SUCCESS;
    case MPI_DOUBLE_INT:
      reduce_loc((PairDoubleInt *)acc, (const PairDoubleInt *)in,
                         n, maxloc);
      return MPI_SUCCESS;
    case MPI_LONG_INT:
      reduce_loc((PairLongInt *)acc, (const PairLongInt *)in, n,
                       maxloc);
      return MPI_SUCCESS;
    case MPI_SHORT_INT:
      reduce_loc((PairShortInt *)acc, (const PairShortInt *)in,
                        n, maxloc);
      return MPI_SUCCESS;
  }
  return MPI_ERR_TYPE;  // MINLOC/MAXLOC require a pair type
}

// acc = acc ⊕ in elementwise, acc as the LEFT operand (rank order is
// the caller's responsibility; op.h:547-605's in-order contract)
int reduce_buf(void *acc, const void *in, int n, MPI_Datatype dt,
               MPI_Op op) {
  // the RMA identity ops (MPI-3.1 §11.3): REPLACE = atomic put,
  // NO_OP = leave the accumulator untouched
  if (op == MPI_REPLACE) {
    DtInfo di;
    if (!base_dtinfo(dt, di)) return MPI_ERR_TYPE;
    memcpy(acc, in, (size_t)n * di.item);
    return MPI_SUCCESS;
  }
  if (op == MPI_NO_OP) return MPI_SUCCESS;
  if (op == MPI_MINLOC || op == MPI_MAXLOC)
    return reduce_loc_buf(acc, in, n, dt, op == MPI_MAXLOC);
  auto uit = g_user_ops.find(op);
  if (uit != g_user_ops.end()) {
    // MPI user fn computes inoutvec = invec ∘ inoutvec (invec LEFT);
    // feed invec=acc, inoutvec=copy(in), copy back — acc ∘ in lands
    // in acc per this function's contract
    DtInfo di;
    if (!base_dtinfo(dt, di)) return MPI_ERR_TYPE;
    std::vector<char> tmp((size_t)n * di.item);
    memcpy(tmp.data(), in, tmp.size());
    int len = n;
    MPI_Datatype d = dt;
    uit->second.fn(acc, tmp.data(), &len, &d);
    memcpy(acc, tmp.data(), tmp.size());
    return MPI_SUCCESS;
  }
  switch (dt) {
    case MPI_INT:
      return reduce_int((int32_t *)acc, (const int32_t *)in, n, op);
    case MPI_LONG:
    case MPI_LONG_LONG:
      return reduce_int((int64_t *)acc, (const int64_t *)in, n, op);
    case MPI_CHAR:
    case MPI_SIGNED_CHAR:
      return reduce_int((int8_t *)acc, (const int8_t *)in, n, op);
    case MPI_SHORT:
      return reduce_int((int16_t *)acc, (const int16_t *)in, n, op);
    case MPI_BYTE:
    case MPI_UNSIGNED_CHAR:
      return reduce_int((uint8_t *)acc, (const uint8_t *)in, n, op);
    case MPI_UNSIGNED_SHORT:
      return reduce_int((uint16_t *)acc, (const uint16_t *)in, n, op);
    case MPI_UNSIGNED:
      return reduce_int((uint32_t *)acc, (const uint32_t *)in, n, op);
    case MPI_UNSIGNED_LONG:
      return reduce_int((uint64_t *)acc, (const uint64_t *)in, n, op);
    case MPI_FLOAT:
      // bitwise ops on floats are invalid (MPI-4.1 §6.9.2)
      return reduce_arith((float *)acc, (const float *)in, n, op);
    case MPI_DOUBLE:
      return reduce_arith((double *)acc, (const double *)in, n, op);
  }
  return MPI_ERR_TYPE;
}

// ------------------------------------------------------------- windows
// Active-target RMA (win_create.c:44 / osc_rdma's fence epoch, reduced
// to the AM shape the Python plane's osc/am.py uses): the window is the
// target's local buffer; put/accumulate are fire-and-forget tuples the
// target's drain applies under the window lock; get/flush are RPCs.
// Per-origin FIFO on a connection means a flush reply proves every
// earlier op from that origin has been applied — fence is flush-all
// plus the communicator barrier.

struct WinObj {
  char *base = nullptr;
  int64_t size = 0;  // bytes
  int disp_unit = 1;
  bool owns_base = false;  // Win_allocate: free the buffer at Win_free
  CommObj comm;      // snapshot at creation
  std::mutex mu;     // apply lock (drains from several origins)
  std::set<int> dirty;  // world ranks with unflushed ops from us
  std::mutex dirty_mu;
  // passive-target lock manager (osc/am.py _LockManager's shape): the
  // target's drain arbitrates; waiters park their reply tag until a
  // release grants them, FIFO
  std::mutex lock_mu;
  int lock_excl_holder = -1;        // world rank or -1
  int lock_shared = 0;              // count of shared holders
  std::deque<std::array<int64_t, 3>> lock_waiters;  // (origin, type, rtag)
  // PSCW epochs: the start group (targets we access) and post group
  // (origins exposed to), world ranks.  The open flags distinguish an
  // EMPTY epoch (legal, MPI_GROUP_EMPTY) from no epoch at all.
  std::vector<int> pscw_start;
  std::vector<int> pscw_post;
  bool pscw_start_open = false;
  bool pscw_post_open = false;
  // dynamic windows (win_create_dynamic.c): ops address ABSOLUTE byte
  // displacements that must land inside a locally attached region
  bool dynamic = false;
  std::vector<std::pair<uint64_t, uint64_t>> attached;  // (addr, len)
  std::mutex attach_mu;
  // shared-memory windows (win_allocate_shared.c): one mmap'd segment
  // per comm, every rank maps the whole thing
  bool shm = false;
  char *shm_map = nullptr;
  size_t shm_len = 0;
  std::string shm_path;
  std::vector<int64_t> shm_sizes;   // per comm rank
  std::vector<int> shm_units;
  std::vector<int64_t> shm_offsets;
};

// resolve the target-side destination of an RMA op: normal windows
// bound-check against [0, size); dynamic windows take absolute
// displacements validated against the attached-region list
char *win_dst(WinObj *w, int64_t disp, int64_t nbytes) {
  if (nbytes < 0) return nullptr;
  if (!w->dynamic) {
    if (disp < 0 || disp + nbytes > w->size) return nullptr;
    return w->base + disp;
  }
  std::lock_guard<std::mutex> lk(w->attach_mu);
  for (auto &r : w->attached) {
    uint64_t d = (uint64_t)disp;
    if (d >= r.first && d + (uint64_t)nbytes <= r.first + r.second)
      return (char *)(uintptr_t)d;
  }
  return nullptr;
}

std::map<int64_t, WinObj *> g_wins;      // wire win-id -> obj
std::map<int, int64_t> g_win_handles;    // local MPI_Win -> wire win-id
int g_next_win_handle = 0;
std::mutex g_wins_mu;

std::atomic<int64_t> g_next_reply_tag{1};

// send a 5-frame whose payload is a window tuple (tag 0: requests are
// dispatched by cid+tuple, never matched)
int win_send_tuple(int dest_world, const std::string &tuple_payload) {
  if (dest_world == g.rank) return MPI_ERR_OTHER;  // caller handles self
  std::string f;
  put_varint(f, 5);
  put_int(f, g.rank);
  put_int(f, 0);
  put_int(f, WIN_CID);
  put_int(f, g.seq++);
  f += tuple_payload;
  return peer_send_frame(dest_world, f);
}

void win_reply(int64_t origin, int64_t reply_tag, const void *data,
               size_t nbytes) {
  if (origin == g.rank) return;
  std::string f;
  put_varint(f, 5);
  put_int(f, g.rank);
  put_int(f, reply_tag);
  put_int(f, WIN_CID);
  put_int(f, g.seq++);
  put_ndarray_1d(f, "|u1", data, nbytes, 1);
  peer_send_frame((int)origin, f);
}

// The one lock-release path (wunlock wire handler AND the self-target
// MPI_Win_unlock): drop `unlocker`'s hold, then grant waiters FIFO — a
// head exclusive waits for full drain and blocks everyone behind it,
// shared waiters are granted as a run.  Returns the granted
// (origin, type, reply_tag) rows; the caller sends the replies.
std::vector<std::array<int64_t, 3>> release_and_grants(WinObj *w,
                                                       int unlocker) {
  std::vector<std::array<int64_t, 3>> grants;
  std::lock_guard<std::mutex> lk(w->lock_mu);
  if (w->lock_excl_holder == unlocker) w->lock_excl_holder = -1;
  else if (w->lock_shared > 0) w->lock_shared--;
  while (!w->lock_waiters.empty()) {
    auto next = w->lock_waiters.front();
    if (next[1] == 1) {  // exclusive waiter
      if (w->lock_excl_holder < 0 && w->lock_shared == 0) {
        w->lock_excl_holder = (int)next[0];
        grants.push_back(next);
        w->lock_waiters.pop_front();
      }
      break;  // exclusive at the head blocks everyone behind it
    }
    if (w->lock_excl_holder >= 0) break;
    w->lock_shared++;
    grants.push_back(next);
    w->lock_waiters.pop_front();
  }
  return grants;
}

// The one AMO apply path (local fast path AND the wamo wire handler):
// validates displacement and operand shape, applies under the window
// lock, fills `old` with the pre-op value.  subkind: add | set | swap |
// cas ([compare][value] operand) | fetch (no operand) | "aop:<N>"
// (cell = cell OP operand for predefined op N).  Every subkind except
// cas operates on `nelems` elements atomically — the Get_accumulate
// general form; fetch takes nelems from the caller since it has no
// operand.  User ops are rejected at the origin, per MPI.
bool apply_amo(WinObj *w, int64_t disp, const std::string &sub,
               MPI_Datatype dt, const char *opnd, size_t opnd_len,
               std::vector<char> &old, int64_t fetch_elems = 1) {
  DtInfo di;
  if (!base_dtinfo(dt, di)) return false;
  int64_t nelems;
  if (sub == "cas") {
    if (opnd_len != 2 * di.item || opnd == nullptr) return false;
    nelems = 1;
  } else if (sub == "fetch") {
    if (opnd_len != 0) return false;
    nelems = fetch_elems;
  } else {
    if (opnd_len == 0 || opnd == nullptr || opnd_len % di.item)
      return false;
    nelems = (int64_t)(opnd_len / di.item);
  }
  if (nelems <= 0) return false;
  char *cell = win_dst(w, disp, nelems * (int64_t)di.item);
  if (!cell) return false;
  old.resize((size_t)nelems * di.item);
  std::lock_guard<std::mutex> lk(w->mu);
  memcpy(old.data(), cell, old.size());
  if (sub == "add") {
    reduce_buf(cell, opnd, (int)nelems, dt, MPI_SUM);
  } else if (sub == "set" || sub == "swap") {
    memcpy(cell, opnd, old.size());
  } else if (sub == "cas") {
    if (memcmp(cell, opnd, di.item) == 0)
      memcpy(cell, opnd + di.item, di.item);
  } else if (sub.rfind("aop:", 0) == 0) {
    MPI_Op op = (MPI_Op)atoi(sub.c_str() + 4);
    if (g_user_ops.count(op)) return false;
    if (reduce_buf(cell, opnd, (int)nelems, dt, op) != MPI_SUCCESS)
      return false;
  } else if (sub != "fetch") {
    return false;
  }
  return true;
}

// Drain-side dispatch of ("wput"|"wacc"|"wget"|"wflush", win_id, ...)
void handle_win_frame(int64_t src, const DssVal &t) {
  if (t.items.empty() || t.items[0].tag != T_STR) return;
  const std::string &kind = t.items[0].s;
  if (t.items.size() < 2) return;
  WinObj *w = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_wins_mu);
    auto it = g_wins.find(t.items[1].i);
    if (it == g_wins.end()) return;  // freed or never created: drop
    w = it->second;
  }
  if (kind == "wput" && t.items.size() == 4) {
    int64_t disp = t.items[2].i;
    const std::string &data = t.items[3].data;
    char *dst = win_dst(w, disp, (int64_t)data.size());
    if (!dst) return;
    std::lock_guard<std::mutex> lk(w->mu);
    memcpy(dst, data.data(), data.size());
  } else if (kind == "wacc" && t.items.size() == 6) {
    int64_t disp = t.items[2].i;
    MPI_Op op = (MPI_Op)t.items[3].i;
    MPI_Datatype dt = (MPI_Datatype)t.items[4].i;
    const std::string &data = t.items[5].data;
    DtInfo di;
    if (!base_dtinfo(dt, di)) return;
    int64_t n = (int64_t)(data.size() / di.item);
    char *dst = win_dst(w, disp, (int64_t)data.size());
    if (!dst) return;
    std::lock_guard<std::mutex> lk(w->mu);
    // MPI_Accumulate: target = target op origin (the service loop is
    // the serialization point, as in osc/am.py's apply_acc)
    reduce_buf(dst, data.data(), (int)n, dt, op);
  } else if (kind == "wget" && t.items.size() == 5) {
    int64_t disp = t.items[2].i;
    int64_t nbytes = t.items[3].i;
    int64_t reply_tag = t.items[4].i;
    char *src_p = win_dst(w, disp, nbytes);
    if (!src_p) {
      win_reply(src, reply_tag, "", 0);
      return;
    }
    std::vector<char> out((size_t)nbytes);
    {
      std::lock_guard<std::mutex> lk(w->mu);
      memcpy(out.data(), src_p, (size_t)nbytes);
    }
    win_reply(src, reply_tag, out.data(), out.size());
  } else if (kind == "wflush" && t.items.size() == 3) {
    // FIFO per connection: by the time the drain reaches this frame,
    // every earlier op from `src` has been applied
    win_reply(src, t.items[2].i, "", 0);
  } else if (kind == "wlock" && t.items.size() == 4) {
    // passive-target lock request: grant now or park the reply until a
    // release frees the window (the drain is the arbiter)
    int lock_type = (int)t.items[2].i;
    int64_t reply_tag = t.items[3].i;
    bool grant;
    {
      std::lock_guard<std::mutex> lk(w->lock_mu);
      if (lock_type == 1) {  // exclusive
        grant = w->lock_excl_holder < 0 && w->lock_shared == 0 &&
                w->lock_waiters.empty();
        if (grant) w->lock_excl_holder = (int)src;
      } else {               // shared
        grant = w->lock_excl_holder < 0 && w->lock_waiters.empty();
        if (grant) w->lock_shared++;
      }
      if (!grant) w->lock_waiters.push_back({src, lock_type, reply_tag});
    }
    if (grant) win_reply(src, reply_tag, "", 0);
  } else if (kind == "wunlock" && t.items.size() == 3) {
    // FIFO ordering means every op the holder issued before the unlock
    // is already applied — release, grant waiters, ack the unlocker
    auto grants = release_and_grants(w, (int)src);
    win_reply(src, t.items[2].i, "", 0);
    for (auto &gr : grants) win_reply(gr[0], gr[2], "", 0);
  } else if (kind == "wamo" && t.items.size() == 7) {
    // fetch-AMO RPC (the shmem_atomic substrate, oshmem/shmem/c/
    // shmem_fadd.c): ("wamo", wid, disp, subkind, dt, operand-bytes,
    // reply_tag) -> old value; applied atomically under the window
    // lock (the drain is the serialization point)
    int64_t reply_tag = t.items[6].i;
    std::string sub = t.items[3].s;
    int64_t fetch_n = 1;
    if (sub.rfind("fetch:", 0) == 0) {
      fetch_n = atoll(sub.c_str() + 6);
      sub = "fetch";
    }
    std::vector<char> old;
    if (!apply_amo(w, t.items[2].i, sub, (MPI_Datatype)t.items[4].i,
                   t.items[5].data.data(), t.items[5].data.size(), old,
                   fetch_n)) {
      win_reply(src, reply_tag, "", 0);
      return;
    }
    win_reply(src, reply_tag, old.data(), old.size());
  }
}

// --------------------------------------------- comm-generic collectives
// All take local-rank addressing and translate through comm.group;
// WORLD keeps the round-3 wire format (cid 0x7FFC/0x7FFD) so mixed
// C/Python jobs stay bit-compatible.

// barrier signal frame: empty T_BYTES payload, bit-identical to
// TcpProc.barrier's wire format (NOT a zero-length ndarray)
int send_barrier_signal(CommObj &c, int dest_world) {
  if (dest_world == g.rank) {
    Message m;
    m.src = g.rank; m.tag = 0x7FFD; m.cid = c.cid_bar; m.seq = g.seq++;
    push_message(std::move(m));
    return MPI_SUCCESS;
  }
  std::string payload;
  put_varint(payload, 5);
  put_int(payload, g.rank);
  put_int(payload, 0x7FFD);
  put_int(payload, c.cid_bar);
  put_int(payload, g.seq++);
  put_bytes(payload, "", 0);
  return peer_send_frame(dest_world, payload);
}

int c_barrier(CommObj &c) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // dissemination rounds (tag 0x7FFD), wire-identical to TcpProc.barrier
  int n = (int)c.group.size(), me = c.local_rank;
  for (int64_t k = 1; k < n; k <<= 1) {
    int dest = (int)((me + k) % n);
    int rc = send_barrier_signal(c, world_of(c, dest));
    if (rc) return rc;
    int src = (int)((me - k % n + n) % n);
    uint8_t dummy[1];
    rc = raw_recv(dummy, 0, MPI_BYTE, world_of(c, src), 0x7FFD, c.cid_bar,
                  nullptr);
    if (rc) return rc;
  }
  return MPI_SUCCESS;
}

int c_bcast(CommObj &c, void *buf, int count, MPI_Datatype dt, int root,
            int64_t opcode) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // binomial tree (coll_base_bcast.c:329 shape)
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | opcode;
  int vrank = (me - root + n) % n;
  if (vrank != 0) {
    int parent = ((vrank & (vrank - 1)) + root) % n;
    int rc = raw_recv(buf, count, dt, world_of(c, parent), tag, c.cid_coll,
                      nullptr);
    if (rc) return rc;
  }
  for (int mask = 1; mask < n; mask <<= 1) {
    if ((vrank & (mask - 1)) == 0 && (vrank | mask) != vrank) {
      int child = vrank | mask;
      if (child < n) {
        int rc = raw_send(buf, count, dt, world_of(c, (child + root) % n),
                          tag, c.cid_coll);
        if (rc) return rc;
      }
    }
  }
  return MPI_SUCCESS;
}

int c_allreduce(CommObj &c, const void *sendbuf, void *recvbuf, int count,
                MPI_Datatype dt, MPI_Op op) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // recursive doubling with the non-power-of-two pre/post fold
  // (in-order combines: lower rank's operand left)
  DtView v;
  if (!resolve_dtype(dt, v) || v.derived) return MPI_ERR_TYPE;
  size_t nbytes = (size_t)count * v.di.item;
  memcpy(recvbuf, sendbuf, nbytes);
  int n = (int)c.group.size(), me = c.local_rank;
  if (n == 1) return MPI_SUCCESS;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E03;
  std::vector<char> other(nbytes);

  int pof2 = 1;
  while (pof2 * 2 <= n) pof2 *= 2;
  int rem = n - pof2;
  int newrank;
  int rc;
  if (me < 2 * rem) {
    if (me % 2 == 0) {
      rc = raw_send(recvbuf, count, dt, world_of(c, me + 1), tag,
                    c.cid_coll);
      if (rc) return rc;
      newrank = -1;
    } else {
      rc = raw_recv(other.data(), count, dt, world_of(c, me - 1), tag,
                    c.cid_coll, nullptr);
      if (rc) return rc;
      // lower rank's operand left: acc = other ⊕ acc
      std::vector<char> tmp(other);
      rc = reduce_buf(tmp.data(), recvbuf, count, dt, op);
      if (rc) return rc;
      memcpy(recvbuf, tmp.data(), nbytes);
      newrank = me / 2;
    }
  } else {
    newrank = me - rem;
  }
  if (newrank >= 0) {
    for (int mask = 1; mask < pof2; mask <<= 1) {
      int pnew = newrank ^ mask;
      int partner = pnew < rem ? pnew * 2 + 1 : pnew + rem;
      rc = raw_send(recvbuf, count, dt, world_of(c, partner), tag,
                    c.cid_coll);
      if (rc) return rc;
      rc = raw_recv(other.data(), count, dt, world_of(c, partner), tag,
                    c.cid_coll, nullptr);
      if (rc) return rc;
      if (partner < me) {
        std::vector<char> tmp(other);
        rc = reduce_buf(tmp.data(), recvbuf, count, dt, op);
        if (rc) return rc;
        memcpy(recvbuf, tmp.data(), nbytes);
      } else {
        rc = reduce_buf(recvbuf, other.data(), count, dt, op);
        if (rc) return rc;
      }
    }
  }
  if (me < 2 * rem) {
    if (me % 2 == 0) {
      rc = raw_recv(recvbuf, count, dt, world_of(c, me + 1), tag,
                    c.cid_coll, nullptr);
      if (rc) return rc;
    } else {
      rc = raw_send(recvbuf, count, dt, world_of(c, me - 1), tag,
                    c.cid_coll);
      if (rc) return rc;
    }
  }
  return MPI_SUCCESS;
}

int c_reduce(CommObj &c, const void *sendbuf, void *recvbuf, int count,
             MPI_Datatype dt, MPI_Op op, int root) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // linear with rank-ordered combine (coll/basic shape): correct for
  // non-commutative user expectations, O(p) small messages at root
  DtView v;
  if (!resolve_dtype(dt, v) || v.derived) return MPI_ERR_TYPE;
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E04;
  size_t nbytes = (size_t)count * v.di.item;
  if (me != root)
    return raw_send(sendbuf, count, dt, world_of(c, root), tag,
                    c.cid_coll);
  std::vector<char> acc(nbytes), contrib(nbytes);
  for (int r = 0; r < n; r++) {
    const char *part;
    if (r == me) {
      part = (const char *)sendbuf;
    } else {
      int rc = raw_recv(contrib.data(), count, dt, world_of(c, r), tag,
                        c.cid_coll, nullptr);
      if (rc) return rc;
      part = contrib.data();
    }
    if (r == 0) {
      memcpy(acc.data(), part, nbytes);
    } else {
      int rc = reduce_buf(acc.data(), part, count, dt, op);
      if (rc) return rc;
    }
  }
  memcpy(recvbuf, acc.data(), nbytes);
  return MPI_SUCCESS;
}

int c_gather(CommObj &c, const void *sendbuf, int sendcount,
             MPI_Datatype sendtype, void *recvbuf, int recvcount,
             MPI_Datatype recvtype, int root) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // linear (coll_base_gather.c:41's basic shape)
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E05;
  if (me != root)
    return raw_send(sendbuf, sendcount, sendtype, world_of(c, root), tag,
                    c.cid_coll);
  DtView rv;
  if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
  size_t slot = slot_bytes(rv, recvcount);
  for (int r = 0; r < n; r++) {
    char *dst = (char *)recvbuf + (size_t)r * slot;
    if (r == me) {
      DtView sv;
      if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
      std::vector<char> packed;
      pack_dtype(sendbuf, sendcount, sv, packed);
      unpack_dtype(dst, recvcount, rv, packed.data(), packed.size());
    } else {
      int rc = raw_recv(dst, recvcount, recvtype, world_of(c, r), tag,
                        c.cid_coll, nullptr);
      if (rc) return rc;
    }
  }
  return MPI_SUCCESS;
}

int c_scatter(CommObj &c, const void *sendbuf, int sendcount,
              MPI_Datatype sendtype, void *recvbuf, int recvcount,
              MPI_Datatype recvtype, int root) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // linear (coll_base_scatter.c's basic shape)
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E06;
  if (me != root)
    return raw_recv(recvbuf, recvcount, recvtype, world_of(c, root), tag,
                    c.cid_coll, nullptr);
  DtView sv;
  if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
  size_t slot = slot_bytes(sv, sendcount);
  for (int r = 0; r < n; r++) {
    const char *src = (const char *)sendbuf + (size_t)r * slot;
    if (r == me) {
      DtView rv;
      if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
      std::vector<char> packed;
      pack_dtype(src, sendcount, sv, packed);
      unpack_dtype(recvbuf, recvcount, rv, packed.data(), packed.size());
    } else {
      int rc = raw_send(src, sendcount, sendtype, world_of(c, r), tag,
                        c.cid_coll);
      if (rc) return rc;
    }
  }
  return MPI_SUCCESS;
}

int c_allgather(CommObj &c, const void *sendbuf, int sendcount,
                MPI_Datatype sendtype, void *recvbuf, int recvcount,
                MPI_Datatype recvtype) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // ring (coll_base_allgather.c:358 shape): n-1 rounds of pass-along
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E07;
  DtView rv;
  if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
  size_t slot = slot_bytes(rv, recvcount);
  // place own contribution
  DtView sv;
  if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
  std::vector<char> packed;
  pack_dtype(sendbuf, sendcount, sv, packed);
  unpack_dtype((char *)recvbuf + (size_t)me * slot, recvcount, rv,
               packed.data(), packed.size());
  int right = (me + 1) % n, left = (me - 1 + n) % n;
  for (int round = 0; round < n - 1; round++) {
    int send_block = (me - round + n) % n;
    int recv_block = (me - round - 1 + n) % n;
    // eager sends are buffered by the drain threads, so the ring cannot
    // deadlock even though every rank sends before receiving
    int rc = raw_send((char *)recvbuf + (size_t)send_block * slot,
                      recvcount, recvtype, world_of(c, right), tag,
                      c.cid_coll);
    if (rc) return rc;
    rc = raw_recv((char *)recvbuf + (size_t)recv_block * slot, recvcount,
                  recvtype, world_of(c, left), tag, c.cid_coll, nullptr);
    if (rc) return rc;
  }
  return MPI_SUCCESS;
}

int c_alltoall(CommObj &c, const void *sendbuf, int sendcount,
               MPI_Datatype sendtype, void *recvbuf, int recvcount,
               MPI_Datatype recvtype) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // pairwise exchange (coll_base_alltoall.c:132 shape); distinct tag
  // per round keeps matching unambiguous
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E08;
  DtView sv, rv;
  if (!resolve_dtype(sendtype, sv) || !resolve_dtype(recvtype, rv))
    return MPI_ERR_TYPE;
  size_t sslot = slot_bytes(sv, sendcount);
  size_t rslot = slot_bytes(rv, recvcount);
  // self block
  {
    std::vector<char> packed;
    pack_dtype((const char *)sendbuf + (size_t)me * sslot, sendcount, sv,
               packed);
    unpack_dtype((char *)recvbuf + (size_t)me * rslot, recvcount, rv,
                 packed.data(), packed.size());
  }
  for (int k = 1; k < n; k++) {
    int to = (me + k) % n, from = (me - k + n) % n;
    int rc = raw_send((const char *)sendbuf + (size_t)to * sslot,
                      sendcount, sendtype, world_of(c, to), tag,
                      c.cid_coll);
    if (rc) return rc;
    rc = raw_recv((char *)recvbuf + (size_t)from * rslot, recvcount,
                  recvtype, world_of(c, from), tag, c.cid_coll, nullptr);
    if (rc) return rc;
  }
  return MPI_SUCCESS;
}

int c_scan(CommObj &c, const void *sendbuf, void *recvbuf, int count,
           MPI_Datatype dt, MPI_Op op, bool exclusive) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // linear chain (coll_base_scan.c:35 / coll_base_exscan.c:35): rank r
  // receives the prefix of ranks < r, combines in rank order, forwards
  DtView v;
  if (!resolve_dtype(dt, v) || v.derived) return MPI_ERR_TYPE;
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E09;
  size_t nbytes = (size_t)count * v.di.item;
  std::vector<char> acc(nbytes);
  if (me == 0) {
    if (!exclusive) memcpy(recvbuf, sendbuf, nbytes);
    memcpy(acc.data(), sendbuf, nbytes);
  } else {
    int rc = raw_recv(acc.data(), count, dt, world_of(c, me - 1), tag,
                      c.cid_coll, nullptr);
    if (rc) return rc;
    if (exclusive) {
      memcpy(recvbuf, acc.data(), nbytes);  // prefix of ranks < me
      int rc2 = reduce_buf(acc.data(), sendbuf, count, dt, op);
      if (rc2) return rc2;
    } else {
      int rc2 = reduce_buf(acc.data(), sendbuf, count, dt, op);
      if (rc2) return rc2;
      memcpy(recvbuf, acc.data(), nbytes);
    }
  }
  if (me + 1 < n) {
    // acc holds the inclusive prefix of ranks <= me (for rank 0 in the
    // exclusive form: just its own value) — the next rank's prefix
    int rc = raw_send(acc.data(), count, dt, world_of(c, me + 1), tag,
                      c.cid_coll);
    if (rc) return rc;
  }
  return MPI_SUCCESS;
}

int c_gatherv(CommObj &c, const void *sendbuf, int sendcount,
              MPI_Datatype sendtype, void *recvbuf, const int recvcounts[],
              const int displs[], MPI_Datatype recvtype, int root) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // linear with per-rank counts/displacements (displs in recvtype
  // extent units, the MPI contract)
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E0A;
  if (me != root)
    return raw_send(sendbuf, sendcount, sendtype, world_of(c, root), tag,
                    c.cid_coll);
  DtView rv;
  if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
  size_t unit = slot_bytes(rv, 1);
  for (int r = 0; r < n; r++) {
    char *dst = (char *)recvbuf + (size_t)displs[r] * unit;
    if (r == me) {
      DtView sv;
      if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
      std::vector<char> packed;
      pack_dtype(sendbuf, sendcount, sv, packed);
      unpack_dtype(dst, recvcounts[r], rv, packed.data(), packed.size());
    } else {
      int rc = raw_recv(dst, recvcounts[r], recvtype, world_of(c, r), tag,
                        c.cid_coll, nullptr);
      if (rc) return rc;
    }
  }
  return MPI_SUCCESS;
}

int c_scatterv(CommObj &c, const void *sendbuf, const int sendcounts[],
               const int displs[], MPI_Datatype sendtype, void *recvbuf,
               int recvcount, MPI_Datatype recvtype, int root) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  int n = (int)c.group.size(), me = c.local_rank;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E0B;
  if (me != root)
    return raw_recv(recvbuf, recvcount, recvtype, world_of(c, root), tag,
                    c.cid_coll, nullptr);
  DtView sv;
  if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
  size_t unit = slot_bytes(sv, 1);
  for (int r = 0; r < n; r++) {
    const char *blk = (const char *)sendbuf + (size_t)displs[r] * unit;
    if (r == me) {
      DtView rv;
      if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
      std::vector<char> packed;
      pack_dtype(blk, sendcounts[r], sv, packed);
      unpack_dtype(recvbuf, recvcount, rv, packed.data(), packed.size());
    } else {
      int rc = raw_send(blk, sendcounts[r], sendtype, world_of(c, r), tag,
                        c.cid_coll);
      if (rc) return rc;
    }
  }
  return MPI_SUCCESS;
}

int c_allgatherv(CommObj &c, const void *sendbuf, int sendcount,
                 MPI_Datatype sendtype, void *recvbuf,
                 const int recvcounts[], const int displs[],
                 MPI_Datatype recvtype) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // n rooted broadcasts of each rank's block into the (identical)
  // recv layout — simple and displacement-safe (gaps never touched)
  int n = (int)c.group.size(), me = c.local_rank;
  DtView rv;
  if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
  size_t unit = slot_bytes(rv, 1);
  // own contribution into own block first
  {
    DtView sv;
    if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
    std::vector<char> packed;
    pack_dtype(sendbuf, sendcount, sv, packed);
    unpack_dtype((char *)recvbuf + (size_t)displs[me] * unit,
                 recvcounts[me], rv, packed.data(), packed.size());
  }
  for (int r = 0; r < n; r++) {
    int rc = c_bcast(c, (char *)recvbuf + (size_t)displs[r] * unit,
                     recvcounts[r], recvtype, r, 0x7E0C);
    if (rc) return rc;
  }
  return MPI_SUCCESS;
}

int c_reduce_scatter(CommObj &c, const void *sendbuf, void *recvbuf,
                     const int recvcounts[], MPI_Datatype dt, MPI_Op op);

int c_reduce_scatter_block(CommObj &c, const void *sendbuf, void *recvbuf,
                           int recvcount, MPI_Datatype dt, MPI_Op op) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // the uniform-counts case of the ragged form (same 2 coll_seq slots)
  std::vector<int> counts(c.group.size(), recvcount);
  return c_reduce_scatter(c, sendbuf, recvbuf, counts.data(), dt, op);
}

int c_reduce_scatter(CommObj &c, const void *sendbuf, void *recvbuf,
                     const int recvcounts[], MPI_Datatype dt, MPI_Op op) {
  // reduce_scatter.c's ragged form: full reduce at 0, then scatterv of
  // the per-rank slices (coll/basic's composition)
  DtView v;
  if (!resolve_dtype(dt, v) || v.derived) return MPI_ERR_TYPE;
  int n = (int)c.group.size();
  int64_t total = 0;
  std::vector<int> displs(n);
  for (int r = 0; r < n; r++) {
    if (recvcounts[r] < 0) return MPI_ERR_ARG;
    displs[r] = (int)total;
    total += recvcounts[r];
  }
  if (total * (int64_t)v.di.item > 0x7FFFFFFFll) return MPI_ERR_COUNT;
  // only the root touches the full reduction (the rsb helper's shape)
  std::vector<char> full(
      c.local_rank == 0 ? (size_t)total * v.di.item : 0);
  int rc = c_reduce(c, sendbuf, full.data(), (int)total, dt, op, 0);
  if (rc != MPI_SUCCESS) return rc;
  return c_scatterv(c, full.data(), recvcounts, displs.data(), dt,
                    recvbuf, recvcounts[c.local_rank], dt, 0);
}

int c_alltoallv(CommObj &c, const void *sendbuf, const int sendcounts[],
                const int sdispls[], MPI_Datatype sendtype, void *recvbuf,
                const int recvcounts[], const int rdispls[],
                MPI_Datatype recvtype) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  // alltoallv.c: ragged pairwise exchange — one message per ordered
  // pair under one reserved tag; receives post first, sends are eager
  DtView sv, rv;
  if (!resolve_dtype(sendtype, sv) || !resolve_dtype(recvtype, rv))
    return MPI_ERR_TYPE;
  int n = (int)c.group.size(), me = c.local_rank;
  for (int r = 0; r < n; r++)
    if (sendcounts[r] < 0 || recvcounts[r] < 0 || sdispls[r] < 0 ||
        rdispls[r] < 0)
      return MPI_ERR_ARG;
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E11;
  size_t sstride = slot_bytes(sv, 1), rstride = slot_bytes(rv, 1);
  std::vector<Req> reqs(n);
  std::vector<int> handles(n, -1);
  auto abort_all = [&](int err) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < n; i++)
      if (handles[i] >= 0) deregister_locked(handles[i], &reqs[i]);
    return err;
  };
  for (int r = 0; r < n; r++) {
    if (r == me || recvcounts[r] == 0) continue;
    reqs[r].is_recv = true;
    reqs[r].user_buf = (char *)recvbuf + (size_t)rdispls[r] * rstride;
    reqs[r].count = recvcounts[r];
    handles[r] = post_recv(&reqs[r], rv, c.cid_coll, world_of(c, r),
                           tag);
  }
  for (int r = 0; r < n; r++) {
    if (r == me || sendcounts[r] == 0) continue;
    int rc = raw_send((const char *)sendbuf + (size_t)sdispls[r] * sstride,
                      sendcounts[r], sendtype, world_of(c, r), tag,
                      c.cid_coll);
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  // self block: straight pack/unpack through the convertor
  if (sendcounts[me] > 0 || recvcounts[me] > 0) {
    std::vector<char> packed;
    pack_dtype((const char *)sendbuf + (size_t)sdispls[me] * sstride,
               sendcounts[me], sv, packed);
    unpack_dtype((char *)recvbuf + (size_t)rdispls[me] * rstride,
                 recvcounts[me], rv, packed.data(), packed.size());
  }
  for (int r = 0; r < n; r++) {
    if (handles[r] < 0) continue;
    int rc = wait_handle(handles[r], nullptr);
    handles[r] = -1;
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  return MPI_SUCCESS;
}

int c_alltoallw(CommObj &c, const void *sendbuf, const int sendcounts[],
                const int sdispls[], const MPI_Datatype sendtypes[],
                void *recvbuf, const int recvcounts[],
                const int rdispls[], const MPI_Datatype recvtypes[]) {
  // alltoallw.c: the fully general exchange — per-peer datatypes and
  // BYTE displacements (the one collective whose displacements are not
  // scaled by an extent, MPI-3.1 §5.8)
  if (!c.remote.empty()) return MPI_ERR_COMM;
  int n = (int)c.group.size(), me = c.local_rank;
  std::vector<DtView> sv((size_t)n), rv((size_t)n);
  for (int r = 0; r < n; r++) {
    if (sendcounts[r] < 0 || recvcounts[r] < 0 || sdispls[r] < 0 ||
        rdispls[r] < 0)
      return MPI_ERR_ARG;
    if (sendcounts[r] > 0 &&
        !resolve_dtype(sendtypes[r], sv[(size_t)r]))
      return MPI_ERR_TYPE;
    if (recvcounts[r] > 0 &&
        !resolve_dtype(recvtypes[r], rv[(size_t)r]))
      return MPI_ERR_TYPE;
  }
  int64_t tag = (c.coll_seq++ % 0x8000) << 16 | 0x7E12;
  std::vector<Req> reqs((size_t)n);
  std::vector<int> handles((size_t)n, -1);
  auto abort_all = [&](int err) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < n; i++)
      if (handles[(size_t)i] >= 0)
        deregister_locked(handles[(size_t)i], &reqs[(size_t)i]);
    return err;
  };
  for (int r = 0; r < n; r++) {
    if (r == me || recvcounts[r] == 0) continue;
    reqs[(size_t)r].is_recv = true;
    reqs[(size_t)r].user_buf = (char *)recvbuf + (size_t)rdispls[r];
    reqs[(size_t)r].count = recvcounts[r];
    handles[(size_t)r] = post_recv(&reqs[(size_t)r], rv[(size_t)r],
                                   c.cid_coll, world_of(c, r), tag);
  }
  for (int r = 0; r < n; r++) {
    if (r == me || sendcounts[r] == 0) continue;
    int rc = raw_send((const char *)sendbuf + (size_t)sdispls[r],
                      sendcounts[r], sendtypes[r], world_of(c, r), tag,
                      c.cid_coll);
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  if (sendcounts[me] > 0 && recvcounts[me] > 0) {
    std::vector<char> packed;
    pack_dtype((const char *)sendbuf + (size_t)sdispls[me],
               sendcounts[me], sv[(size_t)me], packed);
    unpack_dtype((char *)recvbuf + (size_t)rdispls[me], recvcounts[me],
                 rv[(size_t)me], packed.data(), packed.size());
  }
  for (int r = 0; r < n; r++) {
    if (handles[(size_t)r] < 0) continue;
    int rc = wait_handle(handles[(size_t)r], nullptr);
    handles[(size_t)r] = -1;
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  return MPI_SUCCESS;
}

}  // namespace

// ----------------------------------------------- error handlers core
// comm_create_errhandler.c family.  The comm plane dispatches through
// the installed handler at the pt2pt/collective entry points; win and
// file carry the full surface (create/set/get/call) with their MPI
// defaults (windows: ARE_FATAL, files: ERRORS_RETURN).

struct ErrhObj {
  int kind;  // 0 comm, 1 win, 2 file
  void *fn;
  // MPI-3.1 8.3.4: a freed handler stays in effect while any object
  // still references it; the object tables below hold the references
  bool freed = false;
};
std::map<int, ErrhObj> g_errhandlers;
int g_next_errh = 0x10;  // 0 = ARE_FATAL, 1 = ERRORS_RETURN
std::map<int, int> g_comm_errh, g_win_errh, g_file_errh;

bool errh_referenced(int h) {
  for (auto &e : g_comm_errh)
    if (e.second == h) return true;
  for (auto &e : g_win_errh)
    if (e.second == h) return true;
  for (auto &e : g_file_errh)
    if (e.second == h) return true;
  return false;
}

void reap_errh(int h) {
  if (h < 0x10) return;
  auto it = g_errhandlers.find(h);
  if (it != g_errhandlers.end() && it->second.freed &&
      !errh_referenced(h))
    g_errhandlers.erase(it);
}

// drop an object's handler reference (object free/close paths)
void release_errh_ref(std::map<int, int> &table, int handle) {
  auto it = table.find(handle);
  if (it == table.end()) return;
  int h = it->second;
  table.erase(it);
  reap_errh(h);
}

// a handler id is settable iff predefined or a live entry of `kind`
bool valid_errh(int h, int kind) {
  if (h == 0 /*ARE_FATAL*/ || h == 1 /*ERRORS_RETURN*/) return true;
  auto it = g_errhandlers.find(h);
  return it != g_errhandlers.end() && !it->second.freed &&
         it->second.kind == kind;
}

int errh_of_comm(int comm) {
  auto it = g_comm_errh.find(comm);
  if (it != g_comm_errh.end()) return it->second;
  // unset comms fall back to WORLD's handler (the reference inherits
  // from the parent at creation; the WORLD fallback reaches the same
  // observable behavior for the common set-on-WORLD idiom)
  it = g_comm_errh.find(0 /* MPI_COMM_WORLD */);
  return it != g_comm_errh.end() ? it->second : 0 /* ARE_FATAL */;
}

// defined after the ABI (needs MPI_Error_string); the definition sits
// inside the extern "C" block, so the declaration matches that linkage
extern "C" int dispatch_comm_err(int comm, int code);

// ----------------------------------------------- PMIx store client
// A zmpirun --dvm job modexes through the resident daemon's PMIx
// store (runtime/pmix.py) instead of a per-job coordinator: 4-byte
// length-framed dss.pack([op, *args]) requests, ["ok", value] /
// ["err", message] replies.  C ranks speak the same verbs the Python
// plane's _modex_pmix uses (mkns/put/commit/fence/get), so mixed
// C/Python jobs share one store-served wire-up — and on a DVM tree
// the address in ZMPI_PMIX is THIS host's daemon, whose routed store
// forwards writes up and serves gets from its leaf cache.

void pmix_req(std::string &f, const char *op, size_t argc) {
  put_varint(f, 1);            // dss.pack of ONE value: the request
  f.push_back((char)T_LIST);
  put_varint(f, argc + 1);     // [op, *args]
  put_str(f, op);
}

bool pmix_call(int fd, const std::string &req, DssVal &out,
               std::string &err) {
  if (!send_frame(fd, req)) {
    err = "request send failed";
    return false;
  }
  std::string reply;
  if (!recv_frame(fd, reply)) {
    err = "store connection lost";
    return false;
  }
  std::vector<DssVal> vals;
  if (!parse_all(reply, vals) || vals.size() != 1 ||
      vals[0].tag != T_LIST || vals[0].items.size() != 2 ||
      vals[0].items[0].tag != T_STR) {
    err = "malformed reply";
    return false;
  }
  if (vals[0].items[0].s != "ok") {
    err = vals[0].items[1].s;
    return false;
  }
  out = vals[0].items[1];
  return true;
}

// the ZMPI_LIFELINE contract (runtime/dvm.py): one connection parked
// on the host daemon's control port for this process's whole life —
// the daemon never replies, EOF means the daemon died, and a rank
// must not outlive the daemon that owns its store, fault routing, and
// exit accounting (the PRRTE local-procs-die-with-their-prted
// contract).  Exit 143 mirrors the SIGTERM teardown the daemon itself
// would have applied.  No farewell on stderr: that IS the dead
// daemon's IOF pipe.
void arm_lifeline(const char *address) {
  std::string addr = address;
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) return;
  int fd = tcp_connect(addr.substr(0, colon),
                       atoi(addr.c_str() + colon + 1));
  if (fd < 0) _exit(143);  // daemon already gone: a teardown race
  std::string f;
  put_varint(f, 1);
  f.push_back((char)T_LIST);
  put_varint(f, 1);
  put_str(f, "lifeline");
  if (!send_frame(fd, f)) _exit(143);
  std::thread([fd] {
    std::string frame;
    while (recv_frame(fd, frame)) {
    }
    _exit(143);
  }).detach();
}

// The store-served modex (tcp.py _modex_pmix, C side): publish this
// rank's card, fence the namespace, read every peer's card into the
// book.  uri = "host:port/ns" (the ZMPI_PMIX contract).
bool pmix_modex(const char *uri_c) {
  std::string uri = uri_c;
  size_t slash = uri.rfind('/');
  size_t colon = slash == std::string::npos
                     ? std::string::npos
                     : uri.rfind(':', slash);
  if (slash == std::string::npos || colon == std::string::npos) {
    fprintf(stderr, "zompi: malformed ZMPI_PMIX '%s' "
                    "(want host:port/ns)\n", uri_c);
    return false;
  }
  std::string host = uri.substr(0, colon);
  int port = atoi(uri.substr(colon + 1, slash - colon - 1).c_str());
  std::string ns = uri.substr(slash + 1);
  const double timeout = 30.0;  // the Python plane's host_init default
  int fd = tcp_connect(host, port);
  if (fd < 0) {
    fprintf(stderr, "zompi: no PMIx store at %s:%d\n",
            host.c_str(), port);
    return false;
  }
  DssVal out;
  std::string err, f;
  bool ok = true;
  // mkns is idempotent — the daemon created the job's namespace at
  // launch; this call just asserts the size contract
  pmix_req(f, "mkns", 2);
  put_str(f, ns);
  put_int(f, g.size);
  ok = pmix_call(fd, f, out, err);
  if (ok) {
    // card:<rank> = [host, port(, "sm")] — same capability shape the
    // coordinator modex sends (sm: this rank maps same-host rings)
    f.clear();
    pmix_req(f, "put", 4);
    put_str(f, ns);
    put_int(f, g.rank);
    put_str(f, "card:" + std::to_string(g.rank));
    bool sm = sm_enabled();
    f.push_back((char)T_LIST);
    put_varint(f, sm ? 3 : 2);
    put_str(f, g.host);
    put_int(f, g.listen_port);
    if (sm) put_str(f, "sm");
    ok = pmix_call(fd, f, out, err);
  }
  if (ok) {
    f.clear();
    pmix_req(f, "commit", 2);
    put_str(f, ns);
    put_int(f, g.rank);
    ok = pmix_call(fd, f, out, err);
  }
  if (ok) {
    // the modex barrier: parks until every rank of the namespace
    // committed (the store's fence verb)
    f.clear();
    pmix_req(f, "fence", 3);
    put_str(f, ns);
    put_int(f, g.rank);
    put_float(f, timeout);
    ok = pmix_call(fd, f, out, err);
  }
  if (ok) {
    g.book.assign(g.size, {"", 0});
    g.caps.assign(g.size, "");
    for (int r = 0; r < g.size && ok; r++) {
      f.clear();
      pmix_req(f, "get", 4);
      put_str(f, ns);
      put_str(f, "card:" + std::to_string(r));
      put_float(f, timeout);
      put_int(f, 0);  // min_generation: launch cards are gen 0
      ok = pmix_call(fd, f, out, err);
      // reply value = [card, generation]; card = [host, port, caps...]
      if (ok && (out.tag != T_LIST || out.items.size() < 2 ||
                 out.items[0].tag != T_LIST ||
                 out.items[0].items.size() < 2)) {
        err = "malformed card for rank " + std::to_string(r);
        ok = false;
      }
      if (ok) {
        DssVal &card = out.items[0];
        g.book[r] = {card.items[0].s, (int)card.items[1].i};
        if (card.items.size() >= 3 && card.items[2].tag == T_STR)
          g.caps[r] = card.items[2].s;
      }
    }
  }
  close(fd);
  if (!ok)
    fprintf(stderr, "zompi: pmix modex via %s:%d/%s failed: %s\n",
            host.c_str(), port, ns.c_str(), err.c_str());
  return ok;
}

// ------------------------------------------------------------ C ABI

// thread-level / finalized bookkeeping (init_thread.c, finalized.c);
// definitions here so Init/Finalize can stamp them, used by the
// utilities section below
static bool g_finalized_flag = false;
static std::thread::id g_main_tid;
static int g_thread_level = 0;  // MPI_THREAD_SINGLE

extern "C" {

// the MPI_IN_PLACE sentinel (never dereferenced; identity by address)
char zompi_in_place_[1];

int MPI_Init(int *, char ***) {
  if (g.initialized) return MPI_ERR_OTHER;
  g_main_tid = std::this_thread::get_id();
  g_thread_level = 0;
  const char *r = getenv("ZMPI_RANK");
  const char *s = getenv("ZMPI_SIZE");
  const char *ch = getenv("ZMPI_COORD_HOST");
  const char *cp = getenv("ZMPI_COORD_PORT");
  // a zmpirun --dvm job carries no coordinator at all: the resident
  // daemon's PMIx store serves the modex (ZMPI_PMIX = host:port/ns)
  const char *px = getenv("ZMPI_PMIX");
  bool dvm_store = px && px[0];
  if (!r || !s || (!dvm_store && (!ch || !cp))) {
    fprintf(stderr, "zompi: ZMPI_RANK/SIZE plus ZMPI_COORD_HOST/PORT "
                    "(or ZMPI_PMIX) unset\n");
    return MPI_ERR_OTHER;
  }
  g.rank = atoi(r);
  g.size = atoi(s);
  std::string coord_host = ch ? ch : "";
  int coord_port = cp ? atoi(cp) : 0;
  // same MCA var (and default) as the Python plane's protocol switch
  const char *el = getenv("ZMPI_MCA_tcp_eager_limit");
  if (el && el[0]) g.eager_limit = atoll(el);
  const char *ct = getenv("ZMPI_MCA_rndv_cts_timeout");
  if (ct && ct[0]) g.cts_timeout = atof(ct);

  // listener (btl_tcp's per-proc endpoint)
  g.listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  set_cloexec(g.listen_fd);
  int one = 1;
  setsockopt(g.listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = 0;
  inet_pton(AF_INET, g.host.c_str(), &a.sin_addr);
  if (bind(g.listen_fd, (sockaddr *)&a, sizeof a) != 0) return MPI_ERR_OTHER;
  socklen_t alen = sizeof a;
  getsockname(g.listen_fd, (sockaddr *)&a, &alen);
  g.listen_port = ntohs(a.sin_port);
  listen(g.listen_fd, g.size + 4);
  g.accept_thread = std::thread(accept_loop);

  // modex (tcp.py _modex wire protocol).  ZMPI_COORD_EXTERNAL=1 means a
  // launcher (zmpirun) hosts the rendezvous and EVERY rank — including
  // rank 0 — joins as a client.
  const char *ext = getenv("ZMPI_COORD_EXTERNAL");
  bool external_coord = ext && ext[0] == '1';
  if (dvm_store) {
    // store-served modex (the --dvm shape): every rank is a store
    // client; the daemon hosting this rank holds (or leaf-caches) the
    // whole job's cards.  The lifeline then ties this process's life
    // to its daemon's.
    if (!pmix_modex(px)) return MPI_ERR_OTHER;
    const char *ll = getenv("ZMPI_LIFELINE");
    if (ll && ll[0]) arm_lifeline(ll);
  } else if (g.rank == 0 && !external_coord) {
    int srv = socket(AF_INET, SOCK_STREAM, 0);
    setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in ca{};
    ca.sin_family = AF_INET;
    ca.sin_port = htons((uint16_t)coord_port);
    inet_pton(AF_INET, coord_host.c_str(), &ca.sin_addr);
    if (bind(srv, (sockaddr *)&ca, sizeof ca) != 0) return MPI_ERR_OTHER;
    listen(srv, g.size + 4);
    g.book.assign(g.size, {"", 0});
    g.caps.assign(g.size, "");
    g.book[0] = {g.host, g.listen_port};
    if (sm_enabled()) g.caps[0] = "sm";
    std::vector<int> peers;
    for (int i = 0; i < g.size - 1; i++) {
      int c = accept(srv, nullptr, nullptr);
      std::string f;
      if (!recv_frame(c, f)) return MPI_ERR_OTHER;
      std::vector<DssVal> vals;
      if (!parse_all(f, vals) || vals.size() != 2) return MPI_ERR_OTHER;
      int peer = (int)vals[0].i;
      if (vals[1].items.size() < 2) return MPI_ERR_OTHER;
      g.book[peer] = {vals[1].items[0].s, (int)vals[1].items[1].i};
      // optional third card item: capability string (Python ranks
      // send 2-item cards and get "" — never routed to rings)
      if (vals[1].items.size() >= 3) g.caps[peer] = vals[1].items[2].s;
      peers.push_back(c);
    }
    std::string reply = pack_address_book(g.book, &g.caps);
    for (int c : peers) {
      send_frame(c, reply);
      close(c);
    }
    close(srv);
  } else {
    int c = tcp_connect(coord_host, coord_port);
    if (c < 0) return MPI_ERR_OTHER;
    std::string f;
    put_varint(f, 2);
    put_int(f, g.rank);
    f.push_back((char)T_LIST);
    bool sm = sm_enabled();
    put_varint(f, sm ? 3 : 2);
    put_str(f, g.host);
    put_int(f, g.listen_port);
    if (sm) put_str(f, "sm");  // this rank maps same-host rings
    if (!send_frame(c, f)) return MPI_ERR_OTHER;
    std::string reply;
    if (!recv_frame(c, reply)) return MPI_ERR_OTHER;
    close(c);
    std::vector<DssVal> vals;
    if (!parse_all(reply, vals) || vals.size() != 1) return MPI_ERR_OTHER;
    g.book.clear();
    g.caps.clear();
    for (auto &e : vals[0].items) {
      if (e.items.size() < 2) return MPI_ERR_OTHER;
      g.book.push_back({e.items[0].s, (int)e.items[1].i});
      g.caps.push_back(e.items.size() >= 3 ? e.items[2].s
                                           : std::string());
    }
  }

  // endpoint() reads g.book unlocked from several threads; reserving
  // once caps the universe (init ranks + spawned children) at BOOK_CAP
  // and guarantees spawn's push_back never reallocates under a reader
  g.book.reserve(Shim::BOOK_CAP);
  g.caps.resize(g.book.size(), "");
  g.caps.reserve(Shim::BOOK_CAP);

  // predefined communicators.  WORLD keeps the round-3 wire cids for
  // Python interop; SELF's context never leaves the process.
  g_comms.clear();
  g_next_comm = 2;
  CommObj world;
  const char *wb = getenv("ZMPI_WORLD_BASE");
  if (wb && wb[0]) {
    // SPAWNED process (comm_spawn.c's child side): the universe book
    // spans parent + children, but MPI_COMM_WORLD is the CHILDREN only
    // — a contiguous id block at `base`, with context ids the spawner
    // chose (so parent WORLD traffic and child WORLD traffic never
    // share a context)
    int base = atoi(wb);
    int wsize = atoi(getenv("ZMPI_WORLD_SIZE"));
    int64_t scid = atoll(getenv("ZMPI_SPAWN_CID"));
    world.group.resize(wsize);
    for (int i = 0; i < wsize; i++) world.group[i] = base + i;
    world.local_rank = g.rank - base;
    world.cid_pt2pt = scid + 3;  // the spawn intercomm owns scid..+2
    world.cid_coll = scid + 4;
    world.cid_bar = scid + 5;
  } else {
    world.group.resize(g.size);
    for (int i = 0; i < g.size; i++) world.group[i] = i;
    world.local_rank = g.rank;
    world.cid_pt2pt = 0;
    world.cid_coll = 0x7FFC;
    world.cid_bar = 0x7FFD;
  }
  g_comms[MPI_COMM_WORLD] = world;
  CommObj self;
  self.group = {g.rank};
  self.local_rank = 0;
  self.cid_pt2pt = 0x7F00;
  self.cid_coll = 0x7F01;
  self.cid_bar = 0x7F02;
  g_comms[MPI_COMM_SELF] = self;

  // same-host shared-memory transport for this init cohort (the
  // contiguous WORLD block that initialized together; spawn joins
  // stay TCP — see the sm design block)
  {
    int cohort_base = 0, cohort_size = g.size;
    if (wb && wb[0]) {
      cohort_base = atoi(wb);
      cohort_size = atoi(getenv("ZMPI_WORLD_SIZE"));
    }
    sm_setup(cohort_base, cohort_size);
  }

  extern void build_env_info_hook(void);
  build_env_info_hook();  // MPI_INFO_ENV startup snapshot (10.5.3)

  g.initialized = true;
  return MPI_SUCCESS;
}

int MPI_Initialized(int *flag) {
  *flag = g.initialized ? 1 : 0;
  return MPI_SUCCESS;
}

void finalize_attr_sweep(void);  // defined with the attribute machinery
void reap_spawned(void);         // defined with the spawn machinery

int MPI_Finalize(void) {
  reap_spawned();
  // Attribute delete callbacks fire for EVERY comm that still carries
  // attributes — including WORLD/SELF, the canonical library
  // finalize-hook idiom (MPI-3.1 §8.7.1 requires these deletions)
  finalize_attr_sweep();
  // Tear down without an implicit barrier: MPI allows but does not
  // require Finalize to synchronize, and an implicit barrier would
  // deadlock mixed C/Python jobs whose Python endpoints close() without
  // one.  Programs needing quiescence call MPI_Barrier themselves (the
  // examples do).
  g.closing.store(true);
  // shutdown -> join -> close: drain threads are blocked in recv on
  // these fds; shutdown delivers EOF on the still-valid descriptor, the
  // join guarantees no reader is parked on the fd when it is freed, and
  // only then is the descriptor closed (fd-reuse byte-stealing guard,
  // same discipline as the Python plane's close)
  shutdown(g.listen_fd, SHUT_RDWR);
  // join the accept loop FIRST: after it exits, no new drain can be
  // started, so the drain_fds sweep below cannot miss a late-accepted
  // connection and the threads vector can no longer be mutated under us
  if (g.accept_thread.joinable()) g.accept_thread.join();
  // correct programs have Wait-ed every send request, so inflight
  // rendezvous pushers are in their last few instructions; give them a
  // moment rather than racing their g accesses
  for (int i = 0; i < 500 && g.inflight_isends.load() > 0; i++)
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  {
    // close our cached bulk-send connections: the peers' bulk drains
    // see EOF and self-close (no local reader ever holds these fds)
    std::lock_guard<std::mutex> lk(g.bulk_mu);
    for (auto &e : g.bulk_conns) close(e.second);
    g.bulk_conns.clear();
  }
  {
    std::lock_guard<std::mutex> lk(g.threads_mu);
    for (int fd : g.drain_fds) shutdown(fd, SHUT_RDWR);
    // transient bulk drains self-close; only unblock them here
    for (int fd : g.bulk_fds) shutdown(fd, SHUT_RDWR);
  }
  // index-snapshot join: a drain processing a late RTS can still create
  // a connection (endpoint -> start_drain appends under threads_mu), so
  // the vector may grow while we join — never iterate it unlocked
  for (size_t i = 0;;) {
    std::thread t;
    {
      std::lock_guard<std::mutex> lk(g.threads_mu);
      if (i >= g.threads.size()) break;
      t = std::move(g.threads[i]);
      ++i;
    }
    if (t.joinable()) t.join();
  }
  close(g.listen_fd);
  // late-started drains were shut down by the closing guard in
  // start_drain; sweep whatever registered before the guard flipped
  {
    std::lock_guard<std::mutex> lk(g.threads_mu);
    for (int fd : g.drain_fds) close(fd);
    g.drain_fds.clear();
    g.threads.clear();
  }
  // wait for self-closing bulk drains: both the registered list and the
  // in-flight closes must drain before the application may reuse fd
  // numbers.  Shutdown already unblocked every reader, so this is
  // scheduler latency, not network time; warn if it somehow exceeds 10s.
  bool drained = false;
  for (int i = 0; i < 1000 && !drained; i++) {
    {
      std::lock_guard<std::mutex> lk(g.threads_mu);
      drained = g.bulk_fds.empty() && g.bulk_closing.load() == 0;
    }
    if (!drained)
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  if (!drained)
    fprintf(stderr,
            "zompi: warning: bulk-data drains still closing at "
            "MPI_Finalize exit\n");
  sm_teardown();  // poll thread saw g.closing; unmap + unlink rings
  {
    std::lock_guard<std::mutex> lk(g.conn_mu);
    g.conns.clear();
  }
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    g.posted.clear();
    for (auto &kv : g.reqs)
      if (kv.second->heap) delete kv.second;  // un-waited Isend/Irecv
    g.reqs.clear();
    g.unexpected.clear();
  }
  for (auto &kv : g_files) ::close(kv.second.fd);
  g_files.clear();
  g_groups.clear();
  g_next_group = 1;
  g_comms.clear();
  g_dtypes.clear();
  g_next_dtype = DERIVED_BASE;
  extern void clear_info_naming_state(void);
  clear_info_naming_state();
  g_errhandlers.clear();
  g_next_errh = 0x10;
  g_comm_errh.clear();
  g_win_errh.clear();
  g_file_errh.clear();
  g.initialized = false;
  g_finalized_flag = true;
  return MPI_SUCCESS;
}

int MPI_Comm_rank(MPI_Comm comm, int *rank) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  *rank = c->local_rank;
  return MPI_SUCCESS;
}

int MPI_Comm_size(MPI_Comm comm, int *size) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  *size = (int)c->group.size();
  return MPI_SUCCESS;
}

int MPI_Get_processor_name(char *name, int *resultlen) {
  if (gethostname(name, MPI_MAX_PROCESSOR_NAME - 1) != 0)
    return MPI_ERR_OTHER;
  name[MPI_MAX_PROCESSOR_NAME - 1] = '\0';
  *resultlen = (int)strlen(name);
  return MPI_SUCCESS;
}

// --------------------------------------------------------- communicator

int MPI_Comm_split(MPI_Comm comm, int color, int key, MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int n = (int)c->group.size();
  // allgather (color, key) over the parent (comm_split.c:40 gathers the
  // same tuples before sorting)
  std::vector<int64_t> mine = {color, key};
  std::vector<int64_t> all(2 * (size_t)n);
  int rc = c_allgather(*c, mine.data(), 2, MPI_LONG, all.data(), 2,
                       MPI_LONG);
  if (rc) return rc;
  uint64_t salt = color == MPI_UNDEFINED ? 0 : (uint64_t)(int64_t)color;
  // members of my color, ordered by (key, parent rank)
  std::vector<std::pair<int64_t, int>> members;  // (key, parent local)
  for (int r = 0; r < n; r++)
    if (all[2 * r] == color) members.push_back({all[2 * r + 1], r});
  std::stable_sort(members.begin(), members.end());
  // every parent member advances the creation sequence identically,
  // color or not — the deterministic-cid contract
  CommObj child;
  derive_cids(*c, salt, child);
  c->child_seq++;
  if (color == MPI_UNDEFINED) {
    *newcomm = MPI_COMM_NULL;
    return MPI_SUCCESS;
  }
  for (size_t i = 0; i < members.size(); i++) {
    child.group.push_back(c->group[members[i].second]);
    if (members[i].second == c->local_rank) child.local_rank = (int)i;
  }
  int handle = g_next_comm++;
  g_comms[handle] = child;
  *newcomm = handle;
  return MPI_SUCCESS;
}

// -------------------------------------------------- attribute caching
// comm_create_keyval.c family: keyvals with copy/delete callbacks, the
// MPI library-composition mechanism (attribute/attribute.c reduced to
// two maps — the object system is absorbed by STL).

struct KeyvalObj {
  MPI_Comm_copy_attr_function *copy_fn;
  MPI_Comm_delete_attr_function *delete_fn;
  void *extra_state;
  // MPI-3.1 6.7.2: a freed keyval's callbacks stay in effect until the
  // last attribute referencing it is deleted
  bool freed = false;
};
std::map<int, KeyvalObj> g_keyvals;
int g_next_keyval = 0;
// (comm handle, keyval) -> attribute pointer
std::map<std::pair<int, int>, void *> g_attrs;

bool keyval_referenced(int keyval) {
  for (auto &e : g_attrs)
    if (e.first.second == keyval) return true;
  return false;
}

void reap_keyval(int keyval) {
  auto it = g_keyvals.find(keyval);
  if (it != g_keyvals.end() && it->second.freed &&
      !keyval_referenced(keyval))
    g_keyvals.erase(it);
}

// delete every attribute cached on `comm`, running the delete
// callbacks (comm_free.c order); shared by Comm_free, the Comm_dup
// error unwind, and the Finalize sweep
void delete_comm_attrs(int comm) {
  for (auto it = g_attrs.begin(); it != g_attrs.end();) {
    if (it->first.first == comm) {
      int kvid = it->first.second;
      auto kv = g_keyvals.find(kvid);
      if (kv != g_keyvals.end() && kv->second.delete_fn)
        kv->second.delete_fn(comm, kvid, it->second,
                             kv->second.extra_state);
      it = g_attrs.erase(it);
      reap_keyval(kvid);
    } else {
      ++it;
    }
  }
}

void finalize_attr_sweep(void) {
  // MPI-3.1 8.7.1: Finalize behaves as if MPI_COMM_FREE(COMM_SELF) is
  // executed FIRST — the finalize-hook ordering libraries rely on
  delete_comm_attrs(MPI_COMM_SELF);
  std::vector<int> with_attrs;
  for (auto &e : g_attrs)
    if (with_attrs.empty() || with_attrs.back() != e.first.first)
      with_attrs.push_back(e.first.first);
  for (int comm : with_attrs) delete_comm_attrs(comm);
}

int MPI_Comm_create_keyval(MPI_Comm_copy_attr_function *copy_fn,
                           MPI_Comm_delete_attr_function *delete_fn,
                           int *keyval, void *extra_state) {
  if (!keyval) return MPI_ERR_ARG;
  int kv = g_next_keyval++;
  g_keyvals[kv] = {copy_fn, delete_fn, extra_state};
  *keyval = kv;
  return MPI_SUCCESS;
}

int MPI_Comm_free_keyval(int *keyval) {
  if (!keyval) return MPI_ERR_ARG;
  auto it = g_keyvals.find(*keyval);
  if (it == g_keyvals.end()) return MPI_ERR_ARG;
  // callbacks stay live while attributes still reference the keyval
  it->second.freed = true;
  reap_keyval(*keyval);
  *keyval = MPI_KEYVAL_INVALID;
  return MPI_SUCCESS;
}

int MPI_Comm_set_attr(MPI_Comm comm, int keyval, void *attribute_val) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  auto kv = g_keyvals.find(keyval);
  if (kv == g_keyvals.end() || kv->second.freed) return MPI_ERR_ARG;
  auto key = std::make_pair(comm, keyval);
  auto it = g_attrs.find(key);
  if (it != g_attrs.end() && kv->second.delete_fn) {
    int rc = kv->second.delete_fn(comm, keyval, it->second,
                                  kv->second.extra_state);
    if (rc != MPI_SUCCESS) return rc;
  }
  g_attrs[key] = attribute_val;
  return MPI_SUCCESS;
}

int MPI_Comm_get_attr(MPI_Comm comm, int keyval, void *attribute_val,
                      int *flag) {
  // predefined WORLD attributes (reserved keyvals; the value cells
  // live for the process, per the attribute-pointer contract)
  static int tag_ub = 0x7FFFFFFF;       // tags are int64 on the wire
  static int host_val = MPI_PROC_NULL;  // no distinguished host proc
  static int io_val = MPI_ANY_SOURCE;   // every rank can do IO
  static int wtime_global = 0;          // steady_clock is per-process
  if (keyval >= MPI_TAG_UB && keyval <= MPI_WTIME_IS_GLOBAL) {
    if (!lookup_comm(comm)) return MPI_ERR_COMM;
    if (comm != MPI_COMM_WORLD) {
      *flag = 0;  // cached on WORLD only (attribute.c's contract)
      return MPI_SUCCESS;
    }
    *flag = 1;
    switch (keyval) {
      case MPI_TAG_UB: *(void **)attribute_val = &tag_ub; break;
      case MPI_HOST: *(void **)attribute_val = &host_val; break;
      case MPI_IO: *(void **)attribute_val = &io_val; break;
      default: *(void **)attribute_val = &wtime_global; break;
    }
    return MPI_SUCCESS;
  }

  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  auto it = g_attrs.find({comm, keyval});
  *flag = it != g_attrs.end() ? 1 : 0;
  if (*flag) *(void **)attribute_val = it->second;
  return MPI_SUCCESS;
}

int MPI_Comm_delete_attr(MPI_Comm comm, int keyval) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  auto it = g_attrs.find({comm, keyval});
  if (it == g_attrs.end()) return MPI_ERR_ARG;
  auto kv = g_keyvals.find(keyval);
  if (kv != g_keyvals.end() && kv->second.delete_fn) {
    int rc = kv->second.delete_fn(comm, keyval, it->second,
                                  kv->second.extra_state);
    if (rc != MPI_SUCCESS) return rc;
  }
  g_attrs.erase(it);
  reap_keyval(keyval);
  return MPI_SUCCESS;
}

int MPI_Comm_dup(MPI_Comm comm, MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  CommObj child;
  derive_cids(*c, 0xD0B, child);
  c->child_seq++;
  child.group = c->group;
  child.local_rank = c->local_rank;
  child.remote = c->remote;  // dup of an intercomm stays an intercomm
  int handle = g_next_comm++;
  g_comms[handle] = child;
  *newcomm = handle;
  // attribute propagation through copy callbacks (MPI dup semantics:
  // the callback decides whether and what to copy)
  for (auto &e : g_attrs) {
    if (e.first.first != comm) continue;
    auto kv = g_keyvals.find(e.first.second);
    if (kv == g_keyvals.end() || !kv->second.copy_fn) continue;
    void *out = nullptr;
    int flag = 0;
    int rc = kv->second.copy_fn(comm, e.first.second,
                                kv->second.extra_state, e.second, &out,
                                &flag);
    if (rc != MPI_SUCCESS) {
      // unwind: already-copied attrs get their delete callbacks, then
      // the half-built comm dies (comm_dup.c's error contract)
      delete_comm_attrs(handle);
      g_comms.erase(handle);
      return rc;
    }
    if (flag) g_attrs[{handle, e.first.second}] = out;
  }
  return MPI_SUCCESS;
}

int MPI_Comm_free(MPI_Comm *comm) {
  if (!comm || *comm == MPI_COMM_WORLD || *comm == MPI_COMM_SELF)
    return MPI_ERR_COMM;
  if (!g_comms.count(*comm)) return MPI_ERR_COMM;
  // delete callbacks run BEFORE the handle dies (comm_free.c order)
  delete_comm_attrs(*comm);
  release_errh_ref(g_comm_errh, *comm);
  g_comms.erase(*comm);
  *comm = MPI_COMM_NULL;
  return MPI_SUCCESS;
}

// --------------------------------------------------------------- groups
// ompi/group reduced to rank-list algebra; set ops preserve the
// first-group order (the MPI-defined ordering for union/intersection/
// difference).

namespace {

const std::vector<int> *group_ranks(MPI_Group grp) {
  static const std::vector<int> empty;
  if (grp == MPI_GROUP_EMPTY) return &empty;
  GroupObj *g2 = lookup_group(grp);
  return g2 ? &g2->ranks : nullptr;
}

}  // namespace

int MPI_Comm_group(MPI_Comm comm, MPI_Group *group) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  *group = register_group(c->group);
  return MPI_SUCCESS;
}

int MPI_Group_size(MPI_Group group, int *size) {
  if (group == MPI_GROUP_EMPTY) {
    *size = 0;
    return MPI_SUCCESS;
  }
  GroupObj *gr = lookup_group(group);
  if (!gr) return MPI_ERR_GROUP;
  *size = (int)gr->ranks.size();
  return MPI_SUCCESS;
}

int MPI_Group_rank(MPI_Group group, int *rank) {
  if (group == MPI_GROUP_EMPTY) {
    *rank = MPI_UNDEFINED;
    return MPI_SUCCESS;
  }
  GroupObj *gr = lookup_group(group);
  if (!gr) return MPI_ERR_GROUP;
  *rank = MPI_UNDEFINED;
  for (size_t i = 0; i < gr->ranks.size(); i++)
    if (gr->ranks[i] == g.rank) *rank = (int)i;
  return MPI_SUCCESS;
}

int MPI_Group_incl(MPI_Group group, int n, const int ranks[],
                   MPI_Group *newgroup) {
  const std::vector<int> *base = group_ranks(group);
  if (!base) return MPI_ERR_GROUP;
  if (n == 0) {
    *newgroup = MPI_GROUP_EMPTY;
    return MPI_SUCCESS;
  }
  std::vector<bool> seen(base->size(), false);
  std::vector<int> out;
  for (int i = 0; i < n; i++) {
    if (ranks[i] < 0 || ranks[i] >= (int)base->size())
      return MPI_ERR_ARG;
    if (seen[ranks[i]]) return MPI_ERR_ARG;  // MPI: ranks distinct
    seen[ranks[i]] = true;
    out.push_back((*base)[ranks[i]]);
  }
  *newgroup = register_group(std::move(out));
  return MPI_SUCCESS;
}

int MPI_Group_excl(MPI_Group group, int n, const int ranks[],
                   MPI_Group *newgroup) {
  const std::vector<int> *base = group_ranks(group);
  if (!base) return MPI_ERR_GROUP;
  std::vector<bool> drop(base->size(), false);
  for (int i = 0; i < n; i++) {
    if (ranks[i] < 0 || ranks[i] >= (int)base->size())
      return MPI_ERR_ARG;
    if (drop[ranks[i]]) return MPI_ERR_ARG;  // MPI: ranks distinct
    drop[ranks[i]] = true;
  }
  std::vector<int> out;
  for (size_t i = 0; i < base->size(); i++)
    if (!drop[i]) out.push_back((*base)[i]);
  if (out.empty()) {
    *newgroup = MPI_GROUP_EMPTY;
    return MPI_SUCCESS;
  }
  *newgroup = register_group(std::move(out));
  return MPI_SUCCESS;
}

int MPI_Group_union(MPI_Group group1, MPI_Group group2,
                    MPI_Group *newgroup) {
  const std::vector<int> *a = group_ranks(group1);
  const std::vector<int> *b = group_ranks(group2);
  if (!a || !b) return MPI_ERR_GROUP;
  std::vector<int> out(*a);
  for (int r : *b)
    if (std::find(out.begin(), out.end(), r) == out.end())
      out.push_back(r);
  *newgroup = out.empty() ? MPI_GROUP_EMPTY
                          : register_group(std::move(out));
  return MPI_SUCCESS;
}

int MPI_Group_intersection(MPI_Group group1, MPI_Group group2,
                           MPI_Group *newgroup) {
  const std::vector<int> *a = group_ranks(group1);
  const std::vector<int> *b = group_ranks(group2);
  if (!a || !b) return MPI_ERR_GROUP;
  std::vector<int> out;
  for (int r : *a)
    if (std::find(b->begin(), b->end(), r) != b->end())
      out.push_back(r);
  *newgroup = out.empty() ? MPI_GROUP_EMPTY
                          : register_group(std::move(out));
  return MPI_SUCCESS;
}

int MPI_Group_difference(MPI_Group group1, MPI_Group group2,
                         MPI_Group *newgroup) {
  const std::vector<int> *a = group_ranks(group1);
  const std::vector<int> *b = group_ranks(group2);
  if (!a || !b) return MPI_ERR_GROUP;
  std::vector<int> out;
  for (int r : *a)
    if (std::find(b->begin(), b->end(), r) == b->end())
      out.push_back(r);
  *newgroup = out.empty() ? MPI_GROUP_EMPTY
                          : register_group(std::move(out));
  return MPI_SUCCESS;
}

int MPI_Group_translate_ranks(MPI_Group group1, int n, const int ranks1[],
                              MPI_Group group2, int ranks2[]) {
  const std::vector<int> *a = group_ranks(group1);
  const std::vector<int> *b = group_ranks(group2);
  if (!a || !b) return MPI_ERR_GROUP;
  for (int i = 0; i < n; i++) {
    if (ranks1[i] == MPI_PROC_NULL) {
      ranks2[i] = MPI_PROC_NULL;  // MPI-2.2: passes through
      continue;
    }
    if (ranks1[i] < 0 || ranks1[i] >= (int)a->size())
      return MPI_ERR_ARG;
    int world = (*a)[ranks1[i]];
    ranks2[i] = MPI_UNDEFINED;
    for (size_t j = 0; j < b->size(); j++)
      if ((*b)[j] == world) ranks2[i] = (int)j;
  }
  return MPI_SUCCESS;
}

int MPI_Group_free(MPI_Group *group) {
  if (!group) return MPI_ERR_GROUP;
  if (*group == MPI_GROUP_EMPTY) {
    *group = MPI_GROUP_NULL;
    return MPI_SUCCESS;
  }
  if (!g_groups.erase(*group)) return MPI_ERR_GROUP;
  *group = MPI_GROUP_NULL;
  return MPI_SUCCESS;
}

int MPI_Comm_compare(MPI_Comm comm1, MPI_Comm comm2, int *result) {
  CommObj *a = lookup_comm(comm1), *b = lookup_comm(comm2);
  if (!a || !b) return MPI_ERR_COMM;
  if (comm1 == comm2) {
    *result = MPI_IDENT;
    return MPI_SUCCESS;
  }
  if (a->group == b->group) {
    *result = MPI_CONGRUENT;  // same ranks, same order, distinct context
    return MPI_SUCCESS;
  }
  std::vector<int> sa(a->group), sb(b->group);
  std::sort(sa.begin(), sa.end());
  std::sort(sb.begin(), sb.end());
  *result = sa == sb ? MPI_SIMILAR : MPI_UNEQUAL;
  return MPI_SUCCESS;
}

// -------------------------------------------------------- point-to-point

int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest,
             int tag, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (dest == MPI_PROC_NULL) return MPI_SUCCESS;
  if (tag < 0) return dispatch_comm_err(comm, MPI_ERR_ARG);
  if (dest < 0 || dest >= (int)peer_group(*c).size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  return dispatch_comm_err(
      comm, raw_send(buf, count, dt, peer_world_of(*c, dest), tag,
                     c->cid_pt2pt, /*allow_rndv=*/true));
}

static int make_completed_req(MPI_Comm comm, Req **out = nullptr);
static int isend_rndv(const void *buf, int count, const DtView &v,
                      int dest, int tag, MPI_Comm comm, CommObj *c,
                      MPI_Request *request);

int MPI_Ssend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm) {
  // ssend.c: completion implies the receive is MATCHED — exactly the
  // rendezvous contract (CTS leaves at claim time), so a synchronous
  // send is a forced-rendezvous send at any size
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (dest == MPI_PROC_NULL) return MPI_SUCCESS;
  if (tag < 0) return MPI_ERR_ARG;
  if (dest < 0 || dest >= (int)peer_group(*c).size()) return MPI_ERR_ARG;
  return raw_send(buf, count, dt, peer_world_of(*c, dest), tag,
                  c->cid_pt2pt, /*allow_rndv=*/true, /*force_rndv=*/true);
}

int MPI_Rsend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm) {
  // rsend.c: ready-send may legally be implemented as standard send
  return MPI_Send(buf, count, dt, dest, tag, comm);
}

int MPI_Issend(const void *buf, int count, MPI_Datatype dt, int dest,
               int tag, MPI_Comm comm, MPI_Request *request) {
  // issend.c: the request completes when the receive is MATCHED — the
  // shared rendezvous-isend lifecycle, forced at any size
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (dest == MPI_PROC_NULL) {
    *request = make_completed_req(comm);
    return MPI_SUCCESS;
  }
  if (tag < 0) return MPI_ERR_ARG;
  if (dest < 0 || dest >= (int)peer_group(*c).size()) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  return isend_rndv(buf, count, v, dest, tag, comm, c, request);
}

int MPI_Irsend(const void *buf, int count, MPI_Datatype dt, int dest,
               int tag, MPI_Comm comm, MPI_Request *request) {
  return MPI_Isend(buf, count, dt, dest, tag, comm, request);
}

// allocate an already-completed heap request and register it (the
// eager-send/PROC_NULL request shape shared by Isend/Irecv/Ibsend);
// hands the Req back so callers can stamp status without a re-lookup
static int make_completed_req(MPI_Comm comm, Req **out) {
  Req *r = new Req;
  r->complete = true;
  r->heap = true;
  r->comm = comm;
  if (out) *out = r;
  std::lock_guard<std::mutex> lk(g.match_mu);
  int handle = g.next_req++;
  g.reqs[handle] = r;
  return handle;
}

// bsend.c family: buffered sends must complete without the receiver.
// The engine buffers internally (payloads serialize at send time and
// eager frames never wait for a match), so Bsend is an eager-forced
// send at any size below the frame bound; the user's attached buffer
// is tracked for the attach/detach contract but the internal buffering
// does the work (MPI allows the implementation to buffer elsewhere).
static void *g_bsend_buf = nullptr;
static int g_bsend_size = 0;

int MPI_Buffer_attach(void *buffer, int size) {
  if (g_bsend_buf) return MPI_ERR_ARG;  // one buffer at a time
  g_bsend_buf = buffer;
  g_bsend_size = size;
  return MPI_SUCCESS;
}

int MPI_Buffer_detach(void *buffer_addr, int *size) {
  // blocks until pending buffered sends complete — eager frames are on
  // the wire before Bsend returns, so nothing is pending here
  *(void **)buffer_addr = g_bsend_buf;
  *size = g_bsend_size;
  g_bsend_buf = nullptr;
  g_bsend_size = 0;
  return MPI_SUCCESS;
}

int MPI_Bsend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (dest == MPI_PROC_NULL) return MPI_SUCCESS;
  if (tag < 0) return MPI_ERR_ARG;
  if (dest < 0 || dest >= (int)peer_group(*c).size()) return MPI_ERR_ARG;
  // eager at any size: never blocks on the receiver
  return raw_send(buf, count, dt, peer_world_of(*c, dest), tag,
                  c->cid_pt2pt);
}

int MPI_Ibsend(const void *buf, int count, MPI_Datatype dt, int dest,
               int tag, MPI_Comm comm, MPI_Request *request) {
  int rc = MPI_Bsend(buf, count, dt, dest, tag, comm);
  if (rc != MPI_SUCCESS) return rc;
  *request = make_completed_req(comm);
  return MPI_SUCCESS;
}

// the MPI-defined "empty" status (request.h's completed-null shape):
// no source, no tag, zero payload, not cancelled
static void empty_status(MPI_Status *status, int source = MPI_ANY_SOURCE) {
  if (!status) return;
  status->MPI_SOURCE = source;
  status->MPI_TAG = MPI_ANY_TAG;
  status->MPI_ERROR = MPI_SUCCESS;
  status->_count = 0;
  status->_cancelled = 0;
}

static int translate_status(CommObj *c, MPI_Status *status) {
  if (status && c) {
    // sources arrive as world ranks; on an intercommunicator they are
    // ranks of the REMOTE group
    int local = peer_local_of(*c, status->MPI_SOURCE);
    if (local != MPI_ANY_SOURCE) status->MPI_SOURCE = local;
  }
  return status ? status->MPI_ERROR : MPI_SUCCESS;
}

int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (source == MPI_PROC_NULL) {
    empty_status(status, MPI_PROC_NULL);
    return MPI_SUCCESS;
  }
  DtView v;
  if (!resolve_dtype(dt, v))
    return dispatch_comm_err(comm, MPI_ERR_TYPE);
  int src_world = source == MPI_ANY_SOURCE
                      ? MPI_ANY_SOURCE
                      : peer_world_of(*c, source);
  if (source != MPI_ANY_SOURCE && src_world < 0)
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  MPI_Status st{};
  int rc = raw_recv(buf, count, dt, src_world, tag, c->cid_pt2pt, &st);
  if (status) {
    *status = st;
    translate_status(c, status);
  }
  return dispatch_comm_err(comm, rc);
}

int MPI_Get_count(const MPI_Status *status, MPI_Datatype dt, int *count) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t per_bytes = v.elems_per_item() * (int64_t)v.di.item;
  if (per_bytes == 0 || status->_count % per_bytes) {
    *count = MPI_UNDEFINED;
    return MPI_SUCCESS;
  }
  long long n = status->_count / per_bytes;
  // element counts above INT_MAX are unrepresentable in the int API
  *count = n > 2147483647LL ? MPI_UNDEFINED : (int)n;
  return MPI_SUCCESS;
}

// The rendezvous-isend lifecycle (pack-or-inplace, request
// registration, inline ANNOUNCE for wire order, detached CTS-wait +
// bulk push), shared by large MPI_Isend and every-size MPI_Issend.
static int isend_rndv(const void *buf, int count, const DtView &v,
                      int dest, int tag, MPI_Comm comm, CommObj *c,
                      MPI_Request *request) {
  auto *packed = new std::vector<char>;
  const void *src = buf;
  size_t n = (size_t)count * v.elems_per_item();
  if (!v.contiguous()) {
    pack_dtype(buf, count, v, *packed);
    src = packed->data();
    n = packed->size() / v.di.item;
  }
  Req *r = new Req;
  r->heap = true;
  r->comm = comm;
  int handle;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    handle = g.next_req++;
    g.reqs[handle] = r;
  }
  int dest_world = peer_world_of(*c, dest);
  int64_t cid = c->cid_pt2pt;
  DtInfo di = v.di;
  int64_t rid;
  int cts_handle;
  int rc = rndv_announce(n, di, dest_world, tag, cid, rid, cts_handle);
  if (rc != MPI_SUCCESS) {
    delete packed;
    std::lock_guard<std::mutex> lk(g.match_mu);
    g.reqs.erase(handle);
    delete r;
    return rc;
  }
  g.inflight_isends.fetch_add(1);
  std::thread([=]() {
    int src_rc = rndv_complete(src, n, di, dest_world, rid, cts_handle);
    {
      std::lock_guard<std::mutex> lk(g.match_mu);
      r->status.MPI_ERROR = src_rc;
      r->status._count = (long long)(n * di.item);
      r->complete = true;
    }
    g.match_cv.notify_all();
    delete packed;
    g.inflight_isends.fetch_sub(1);
  }).detach();
  *request = handle;
  return MPI_SUCCESS;
}

int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm, MPI_Request *request) {
  // Below the eager limit the payload is on the wire (or in the peer's
  // unexpected queue) before return, so the request is born complete —
  // pml_ob1's start_copy fast path (pml_ob1_sendreq.h:399-405).  Above
  // it the rendezvous runs on a background thread (CTS arrives only
  // when the receiver matches, so completing it inline would deadlock
  // the crossed-Isend idiom MPI guarantees): the request completes when
  // the bulk push lands, exactly pml_ob1's progressed send request.
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  int rc = MPI_SUCCESS;
  if (dest != MPI_PROC_NULL) {
    if (tag < 0) return dispatch_comm_err(comm, MPI_ERR_ARG);
    if (dest < 0 || dest >= (int)peer_group(*c).size())
      return dispatch_comm_err(comm, MPI_ERR_ARG);
    DtView v;
    if (!resolve_dtype(dt, v))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    int64_t nbytes =
        (int64_t)count * v.elems_per_item() * (int64_t)v.di.item;
    if (nbytes > g.eager_limit)
      return dispatch_comm_err(
          comm, isend_rndv(buf, count, v, dest, tag, comm, c, request));
    rc = raw_send(buf, count, dt, peer_world_of(*c, dest), tag,
                  c->cid_pt2pt, /*allow_rndv=*/true);
    if (rc) return dispatch_comm_err(comm, rc);
  }
  *request = make_completed_req(comm);
  return rc;
}

int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  DtView v;
  if (!resolve_dtype(dt, v))
    return dispatch_comm_err(comm, MPI_ERR_TYPE);
  if (source == MPI_PROC_NULL) {
    Req *r;
    int handle = make_completed_req(comm, &r);
    r->status.MPI_SOURCE = MPI_PROC_NULL;
    r->status.MPI_TAG = MPI_ANY_TAG;
    *request = handle;
    return MPI_SUCCESS;
  }
  int src_world = source == MPI_ANY_SOURCE
                      ? MPI_ANY_SOURCE
                      : peer_world_of(*c, source);
  if (source != MPI_ANY_SOURCE && src_world < 0)
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  Req *r = new Req;
  r->is_recv = true;
  r->heap = true;
  r->comm = comm;
  r->user_buf = buf;
  r->count = count;
  *request = post_recv(r, v, c->cid_pt2pt, src_world, tag);
  return MPI_SUCCESS;
}

// ------------------------------------------------- persistent requests
// send_init.c / recv_init.c: the argument set is frozen once, Start
// re-fires it.  Persistent handles are NEGATIVE (disjoint from the
// active-request space), stay allocated across completions (Wait
// deactivates, never frees), and die at MPI_Request_free.

struct PersistentReq {
  bool is_recv;
  const void *sbuf;
  void *rbuf;
  int count;
  MPI_Datatype dt;
  int peer;
  int tag;
  MPI_Comm comm;
  MPI_Request active = MPI_REQUEST_NULL;  // inner handle when started
  int mode = 0;  // 0 standard, 1 synchronous, 2 buffered, 3 ready
};
std::map<int, PersistentReq> g_persistent;
int g_next_persistent = 2;  // public handle = -id (MPI_REQUEST_NULL=-1)

// MPI allows MPI_Type_free between init and Start: pin the typemap by
// registering a PRIVATE duplicate handle the request owns (freed with
// the request), so the user's handle may die independently.
static MPI_Datatype pin_dtype(MPI_Datatype dt) {
  if (dt < DERIVED_BASE) return dt;  // predefined: nothing to pin
  auto it = g_dtypes.find(dt);
  if (it == g_dtypes.end()) return MPI_DATATYPE_NULL;
  MPI_Datatype priv = g_next_dtype++;
  g_dtypes[priv] = it->second;
  g_dtypes[priv].committed = true;
  return priv;
}

int MPI_Send_init(const void *buf, int count, MPI_Datatype dt, int dest,
                  int tag, MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (dest != MPI_PROC_NULL &&
      (dest < 0 || dest >= (int)peer_group(*c).size()))
    return MPI_ERR_ARG;
  MPI_Datatype pinned = pin_dtype(dt);
  if (pinned == MPI_DATATYPE_NULL) return MPI_ERR_TYPE;
  int id = g_next_persistent++;
  g_persistent[id] = {false, buf, nullptr, count, pinned, dest, tag,
                      comm};
  *request = -id;
  return MPI_SUCCESS;
}

// send-mode persistent variants (ssend_init.c / bsend_init.c /
// rsend_init.c): same frozen argument set, Start fires the matching
// nonblocking mode
static int send_init_mode(const void *buf, int count, MPI_Datatype dt,
                          int dest, int tag, MPI_Comm comm,
                          MPI_Request *request, int mode) {
  int rc = MPI_Send_init(buf, count, dt, dest, tag, comm, request);
  if (rc != MPI_SUCCESS) return rc;
  g_persistent[-*request].mode = mode;
  return MPI_SUCCESS;
}

int MPI_Ssend_init(const void *buf, int count, MPI_Datatype dt, int dest,
                   int tag, MPI_Comm comm, MPI_Request *request) {
  return send_init_mode(buf, count, dt, dest, tag, comm, request, 1);
}

int MPI_Bsend_init(const void *buf, int count, MPI_Datatype dt, int dest,
                   int tag, MPI_Comm comm, MPI_Request *request) {
  return send_init_mode(buf, count, dt, dest, tag, comm, request, 2);
}

int MPI_Rsend_init(const void *buf, int count, MPI_Datatype dt, int dest,
                   int tag, MPI_Comm comm, MPI_Request *request) {
  return send_init_mode(buf, count, dt, dest, tag, comm, request, 3);
}

int MPI_Recv_init(void *buf, int count, MPI_Datatype dt, int source,
                  int tag, MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (source != MPI_ANY_SOURCE && source != MPI_PROC_NULL &&
      (source < 0 || source >= (int)peer_group(*c).size()))
    return MPI_ERR_ARG;
  MPI_Datatype pinned = pin_dtype(dt);
  if (pinned == MPI_DATATYPE_NULL) return MPI_ERR_TYPE;
  int id = g_next_persistent++;
  g_persistent[id] = {true, nullptr, buf, count, pinned, source, tag,
                      comm};
  *request = -id;
  return MPI_SUCCESS;
}

int MPI_Start(MPI_Request *request) {
  if (!request || *request >= MPI_REQUEST_NULL) return MPI_ERR_REQUEST;
  auto it = g_persistent.find(-*request);
  if (it == g_persistent.end()) return MPI_ERR_REQUEST;
  PersistentReq &p = it->second;
  if (p.active != MPI_REQUEST_NULL) return MPI_ERR_REQUEST;  // running
  if (p.is_recv)
    return MPI_Irecv(p.rbuf, p.count, p.dt, p.peer, p.tag, p.comm,
                     &p.active);
  switch (p.mode) {
    case 1:
      return MPI_Issend(p.sbuf, p.count, p.dt, p.peer, p.tag, p.comm,
                        &p.active);
    case 2:
      return MPI_Ibsend(p.sbuf, p.count, p.dt, p.peer, p.tag, p.comm,
                        &p.active);
    case 3:
      return MPI_Irsend(p.sbuf, p.count, p.dt, p.peer, p.tag, p.comm,
                        &p.active);
  }
  return MPI_Isend(p.sbuf, p.count, p.dt, p.peer, p.tag, p.comm,
                   &p.active);
}

int MPI_Startall(int count, MPI_Request requests[]) {
  for (int i = 0; i < count; i++) {
    int rc = MPI_Start(&requests[i]);
    if (rc != MPI_SUCCESS) return rc;
  }
  return MPI_SUCCESS;
}

int MPI_Request_free(MPI_Request *request) {
  if (!request || *request == MPI_REQUEST_NULL) return MPI_ERR_REQUEST;
  if (*request < MPI_REQUEST_NULL) {
    auto it = g_persistent.find(-*request);
    if (it == g_persistent.end()) return MPI_ERR_REQUEST;
    if (it->second.active != MPI_REQUEST_NULL)
      return MPI_ERR_REQUEST;  // complete it first (the safe subset)
    if (it->second.dt >= DERIVED_BASE)
      g_dtypes.erase(it->second.dt);  // the request's private pin
    g_persistent.erase(it);
    *request = MPI_REQUEST_NULL;
    return MPI_SUCCESS;
  }
  // non-persistent: only a completed request may be freed here
  Req *r;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    auto it = g.reqs.find(*request);
    if (it == g.reqs.end() || !it->second->complete)
      return MPI_ERR_REQUEST;
    r = it->second;
    g.reqs.erase(it);
  }
  // the receive must still complete into the user buffer (MPI-3.1
  // 3.7.3): a derived-type recv parked in scratch gets its unpack
  finish_recv(r);
  if (r->heap) delete r;
  *request = MPI_REQUEST_NULL;
  return MPI_SUCCESS;
}

int MPI_Wait(MPI_Request *request, MPI_Status *status) {
  if (!request || *request == MPI_REQUEST_NULL) {
    empty_status(status);
    return MPI_SUCCESS;
  }
  if (*request < MPI_REQUEST_NULL) {
    // persistent: wait the inner active op, DEACTIVATE but never free
    auto it = g_persistent.find(-*request);
    if (it == g_persistent.end()) return MPI_ERR_REQUEST;
    PersistentReq &p = it->second;
    if (p.active == MPI_REQUEST_NULL) {
      // inactive persistent request: empty status, immediate return
      MPI_Request null_req = MPI_REQUEST_NULL;
      return MPI_Wait(&null_req, status);
    }
    int rc = MPI_Wait(&p.active, status);
    p.active = MPI_REQUEST_NULL;
    return rc;  // handle stays valid for the next Start
  }
  int comm_handle;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    auto it = g.reqs.find(*request);
    if (it == g.reqs.end()) return MPI_ERR_REQUEST;
    comm_handle = it->second->comm;
  }
  MPI_Status st{};
  int rc = wait_handle_impl(*request, &st);
  if (status) {
    *status = st;
    translate_status(lookup_comm(comm_handle), status);
  }
  *request = MPI_REQUEST_NULL;
  return rc;
}

int MPI_Test(MPI_Request *request, int *flag, MPI_Status *status) {
  if (!request || *request == MPI_REQUEST_NULL) {
    *flag = 1;
    empty_status(status);
    return MPI_SUCCESS;
  }
  if (*request < MPI_REQUEST_NULL) {
    auto it = g_persistent.find(-*request);
    if (it == g_persistent.end()) return MPI_ERR_REQUEST;
    PersistentReq &p = it->second;
    if (p.active == MPI_REQUEST_NULL) {
      *flag = 1;
      empty_status(status);
      return MPI_SUCCESS;
    }
    *flag = 0;
    int rc = MPI_Test(&p.active, flag, status);
    if (rc == MPI_SUCCESS && *flag) p.active = MPI_REQUEST_NULL;
    return rc;
  }
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    auto it = g.reqs.find(*request);
    if (it == g.reqs.end()) return MPI_ERR_REQUEST;
    if (!it->second->complete) {
      *flag = 0;
      return MPI_SUCCESS;
    }
  }
  *flag = 1;
  return MPI_Wait(request, status);
}

int MPI_Waitall(int count, MPI_Request requests[], MPI_Status statuses[]) {
  int rc = MPI_SUCCESS;
  for (int i = 0; i < count; i++) {
    int r = MPI_Wait(&requests[i],
                     statuses ? &statuses[i] : MPI_STATUS_IGNORE);
    if (r != MPI_SUCCESS) rc = r;
  }
  return rc;
}

int MPI_Sendrecv(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 int dest, int sendtag, void *recvbuf, int recvcount,
                 MPI_Datatype recvtype, int source, int recvtag,
                 MPI_Comm comm, MPI_Status *status) {
  // irecv-first so crossed Sendrecv pairs cannot deadlock
  // (coll_base_util.h:70-98's sendrecv primitive)
  MPI_Request rreq;
  int rc = MPI_Irecv(recvbuf, recvcount, recvtype, source, recvtag, comm,
                     &rreq);
  if (rc) return rc;
  rc = MPI_Send(sendbuf, sendcount, sendtype, dest, sendtag, comm);
  if (rc) return rc;
  return MPI_Wait(&rreq, status);
}

// ----------------------------------------------------------- collectives

int MPI_Barrier(MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  return dispatch_comm_err(comm, c_barrier(*c));
}

int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root,
              MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (root < 0 || root >= (int)c->group.size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  return dispatch_comm_err(comm, c_bcast(*c, buf, count, dt, root,
                                         0x7E01));
}

// IN_PLACE substitution (MPI-3.1 ch.5): clone the receive-side
// contribution into an extent-layout temp via pack/unpack — pack
// touches only typemap bytes, so the clone never overreads a strided
// type's trailing gap.
// NOTE: the per-collective slice/span arithmetic below is MIRRORED in
// the nonblocking wrappers (MPI_Iallreduce ... MPI_Ialltoallv, search
// icoll_inplace) — fix BOTH copies or extract a helper when touching
// either.
static int clone_region(const void *src, int count, MPI_Datatype dt,
                        std::vector<char> &tmp) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  std::vector<char> packed;
  pack_dtype(src, count, v, packed);
  tmp.assign(slot_bytes(v, count), 0);
  unpack_dtype(tmp.data(), count, v, packed.data(), packed.size());
  return MPI_SUCCESS;
}

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    int rc = clone_region(recvbuf, count, dt, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
  }
  return dispatch_comm_err(
      comm, c_allreduce(*c, sendbuf, recvbuf, count, dt, op));
}

int MPI_Reduce(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype dt, MPI_Op op, int root, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (root < 0 || root >= (int)c->group.size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    // IN_PLACE is legal at the ROOT only (reduce.c)
    if (c->local_rank != root)
      return dispatch_comm_err(comm, MPI_ERR_ARG);
    int rc = clone_region(recvbuf, count, dt, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
  }
  return dispatch_comm_err(
      comm, c_reduce(*c, sendbuf, recvbuf, count, dt, op, root));
}

int MPI_Gather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
               void *recvbuf, int recvcount, MPI_Datatype recvtype,
               int root, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (root < 0 || root >= (int)c->group.size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    // root's contribution already sits at its slot of recvbuf
    if (c->local_rank != root)
      return dispatch_comm_err(comm, MPI_ERR_ARG);
    DtView rv;
    if (!resolve_dtype(recvtype, rv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    const char *slice =
        (const char *)recvbuf + (size_t)root * slot_bytes(rv, recvcount);
    int rc = clone_region(slice, recvcount, recvtype, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcount = recvcount;
    sendtype = recvtype;
  }
  return dispatch_comm_err(
      comm, c_gather(*c, sendbuf, sendcount, sendtype, recvbuf,
                     recvcount, recvtype, root));
}

int MPI_Scatter(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, int recvcount, MPI_Datatype recvtype,
                int root, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (root < 0 || root >= (int)c->group.size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  std::vector<char> scratch;
  if (recvbuf == MPI_IN_PLACE) {
    // scatter.c: IN_PLACE recvbuf at the root — its slice stays in
    // sendbuf; receive into scratch and discard
    if (c->local_rank != root)
      return dispatch_comm_err(comm, MPI_ERR_ARG);
    DtView sv;
    if (!resolve_dtype(sendtype, sv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    scratch.resize(slot_bytes(sv, sendcount));
    recvbuf = scratch.data();
    recvcount = sendcount;
    recvtype = sendtype;
  }
  return dispatch_comm_err(
      comm, c_scatter(*c, sendbuf, sendcount, sendtype, recvbuf,
                      recvcount, recvtype, root));
}

int MPI_Allgather(const void *sendbuf, int sendcount,
                  MPI_Datatype sendtype, void *recvbuf, int recvcount,
                  MPI_Datatype recvtype, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    DtView rv;
    if (!resolve_dtype(recvtype, rv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    const char *slice = (const char *)recvbuf +
                        (size_t)c->local_rank *
                            slot_bytes(rv, recvcount);
    int rc = clone_region(slice, recvcount, recvtype, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcount = recvcount;
    sendtype = recvtype;
  }
  return dispatch_comm_err(
      comm, c_allgather(*c, sendbuf, sendcount, sendtype, recvbuf,
                        recvcount, recvtype));
}

int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    int n = (int)c->group.size();
    DtView rv;
    if (!resolve_dtype(recvtype, rv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    int rc = clone_region(recvbuf, n * recvcount, recvtype, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcount = recvcount;
    sendtype = recvtype;
  }
  return dispatch_comm_err(
      comm, c_alltoall(*c, sendbuf, sendcount, sendtype, recvbuf,
                       recvcount, recvtype));
}

// ------------------------------------------------------------- datatypes

int MPI_Type_contiguous(int count, MPI_Datatype oldtype,
                        MPI_Datatype *newtype) {
  // type_contiguous.c analog; nesting flattens (old derived types
  // expand into their base blocks)
  if (count < 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(oldtype, v)) return MPI_ERR_TYPE;
  DtypeObj d;
  d.base = v.derived ? v.derived->base : oldtype;
  int64_t old_extent = v.derived ? v.derived->extent : 1;
  for (int c = 0; c < count; c++) {
    int64_t off = c * old_extent;
    if (v.derived) {
      for (auto &b : v.derived->blocks)
        d.blocks.push_back({off + b.first, b.second});
    } else {
      d.blocks.push_back({off, 1});
    }
  }
  coalesce_blocks(d.blocks);
  d.extent = count * old_extent;
  d.elems = count * v.elems_per_item();
  d.combiner = MPI_COMBINER_CONTIGUOUS;
  d.env_ints = {count};
  d.env_types = {oldtype};
  MPI_Datatype handle = g_next_dtype++;
  g_dtypes[handle] = d;
  *newtype = handle;
  return MPI_SUCCESS;
}

int MPI_Type_vector(int count, int blocklength, int stride,
                    MPI_Datatype oldtype, MPI_Datatype *newtype) {
  // type_vector.c analog; stride in units of oldtype extent
  if (count < 0 || blocklength < 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(oldtype, v)) return MPI_ERR_TYPE;
  DtypeObj d;
  d.base = v.derived ? v.derived->base : oldtype;
  int64_t old_extent = v.derived ? v.derived->extent : 1;
  d.lb = v.derived ? v.derived->lb : 0;  // min disp is 0; inner lb adds
  int64_t max_off = 0;
  for (int c = 0; c < count; c++) {
    for (int b = 0; b < blocklength; b++) {
      int64_t off = ((int64_t)c * stride + b) * old_extent;
      if (off < 0) return MPI_ERR_ARG;  // negative stride unsupported
      if (v.derived) {
        for (auto &bb : v.derived->blocks)
          d.blocks.push_back({off + bb.first, bb.second});
      } else {
        d.blocks.push_back({off, 1});
      }
      if (off + old_extent > max_off) max_off = off + old_extent;
    }
  }
  coalesce_blocks(d.blocks);
  d.extent = max_off;
  d.elems = (int64_t)count * blocklength * v.elems_per_item();
  d.combiner = MPI_COMBINER_VECTOR;
  d.env_ints = {count, blocklength, stride};
  d.env_types = {oldtype};
  MPI_Datatype handle = g_next_dtype++;
  g_dtypes[handle] = d;
  *newtype = handle;
  return MPI_SUCCESS;
}

int MPI_Type_indexed(int count, const int blocklengths[],
                     const int displacements[], MPI_Datatype oldtype,
                     MPI_Datatype *newtype) {
  // type_indexed.c analog: per-block lengths and displacements, both in
  // units of oldtype extent
  if (count < 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(oldtype, v)) return MPI_ERR_TYPE;
  DtypeObj d;
  d.base = v.derived ? v.derived->base : oldtype;
  int64_t old_extent = v.derived ? v.derived->extent : 1;
  int64_t old_lb = v.derived ? v.derived->lb : 0;
  int64_t max_off = 0, min_off = INT64_MAX;
  int64_t total = 0;
  for (int c = 0; c < count; c++) {
    if (blocklengths[c] < 0) return MPI_ERR_ARG;
    if (blocklengths[c] == 0) continue;
    if (!v.derived) {
      // predefined oldtype: the whole block is ONE contiguous run
      int64_t off = (int64_t)displacements[c];
      if (off < 0) return MPI_ERR_ARG;  // negative disp unsupported
      d.blocks.push_back({off, blocklengths[c]});
      int64_t end = off + blocklengths[c];
      if (end > max_off) max_off = end;
      if (off < min_off) min_off = off;
    } else {
      for (int b = 0; b < blocklengths[c]; b++) {
        int64_t off = ((int64_t)displacements[c] + b) * old_extent;
        if (off < 0) return MPI_ERR_ARG;
        for (auto &bb : v.derived->blocks)
          d.blocks.push_back({off + bb.first, bb.second});
        if (off + old_lb + old_extent > max_off)
          max_off = off + old_lb + old_extent;
        if (off + old_lb < min_off) min_off = off + old_lb;
      }
    }
    total += blocklengths[c];
  }
  if (total == 0) min_off = 0;
  // typemap order is DECLARATION order (pack serializes in this order,
  // MPI-3.1 §4.1) — never sort; coalescing only merges adjacent runs
  coalesce_blocks(d.blocks);
  // extent = ub - lb (MPI-3.1 §4.1.6), the oldtype's own lb included;
  // block offsets stay ABSOLUTE, so item k's typemap is d_i + k*extent,
  // exactly the standard's concatenation
  d.lb = min_off;
  d.extent = max_off - min_off;
  d.elems = total * v.elems_per_item();
  d.combiner = MPI_COMBINER_INDEXED;
  d.env_ints.push_back(count);
  for (int c2 = 0; c2 < count; c2++) d.env_ints.push_back(blocklengths[c2]);
  for (int c2 = 0; c2 < count; c2++)
    d.env_ints.push_back(displacements[c2]);
  d.env_types = {oldtype};
  MPI_Datatype handle = g_next_dtype++;
  g_dtypes[handle] = d;
  *newtype = handle;
  return MPI_SUCCESS;
}

int MPI_Type_create_indexed_block(int count, int blocklength,
                                  const int displacements[],
                                  MPI_Datatype oldtype,
                                  MPI_Datatype *newtype) {
  if (count < 0 || blocklength < 0) return MPI_ERR_ARG;
  std::vector<int> lens((size_t)count, blocklength);
  int rc = MPI_Type_indexed(count, lens.data(), displacements, oldtype,
                            newtype);
  if (rc != MPI_SUCCESS) return rc;
  DtypeObj &d = g_dtypes[*newtype];
  d.combiner = MPI_COMBINER_INDEXED_BLOCK;
  d.env_ints.assign({count, blocklength});
  for (int c2 = 0; c2 < count; c2++)
    d.env_ints.push_back(displacements[c2]);
  d.env_types = {oldtype};
  return MPI_SUCCESS;
}

int MPI_Type_commit(MPI_Datatype *datatype) {
  if (!datatype) return MPI_ERR_TYPE;
  if (*datatype < DERIVED_BASE) return MPI_SUCCESS;  // predefined
  auto it = g_dtypes.find(*datatype);
  if (it == g_dtypes.end()) return MPI_ERR_TYPE;
  it->second.committed = true;
  return MPI_SUCCESS;
}

void delete_type_attrs(MPI_Datatype dt);  // batch-8 section

int MPI_Type_free(MPI_Datatype *datatype) {
  if (!datatype || *datatype < DERIVED_BASE) return MPI_ERR_TYPE;
  if (!g_dtypes.count(*datatype)) return MPI_ERR_TYPE;
  // attribute delete callbacks run before the handle dies
  delete_type_attrs(*datatype);
  g_dtypes.erase(*datatype);
  *datatype = MPI_DATATYPE_NULL;
  return MPI_SUCCESS;
}

int MPI_Type_size(MPI_Datatype datatype, int *size) {
  DtView v;
  if (datatype >= DERIVED_BASE) {
    // committed not required for size queries
    auto it = g_dtypes.find(datatype);
    if (it == g_dtypes.end()) return MPI_ERR_TYPE;
    DtInfo di;
    if (!base_dtinfo(it->second.base, di)) return MPI_ERR_TYPE;
    int ptm = pair_typemap_size(it->second.base);
    *size = (int)(it->second.elems * (ptm ? (size_t)ptm : di.item));
    return MPI_SUCCESS;
  }
  if (!resolve_dtype(datatype, v)) return MPI_ERR_TYPE;
  // pair types: the TYPEMAP size (value + int), not the padded extent
  // (type_size.c: MPI_DOUBLE_INT is 12, its extent 16)
  int ptm = pair_typemap_size(datatype);
  *size = ptm ? ptm : (int)v.di.item;
  return MPI_SUCCESS;
}

// ------------------------------------------- datatype tier 2 (round 5)
// Byte-displacement constructors (type_create_hvector.c,
// type_create_struct.c, ...) flatten to BYTE typemaps: displacements
// need not be multiples of the base item, so the byte unit is the one
// common denominator.  The cluster is homogeneous (same reduction the
// convertor's external32 path documents), so no per-element identity
// is lost on the wire.

namespace {

// resolve a type for CONSTRUCTION (committed not required, unlike the
// communication-path resolve_dtype)
bool resolve_for_build(MPI_Datatype dt, DtView &v) {
  if (dt < DERIVED_BASE) return base_dtinfo(dt, v.di);
  auto it = g_dtypes.find(dt);
  if (it == g_dtypes.end()) return false;
  v.derived = &it->second;
  return base_dtinfo(it->second.base, v.di);
}

// one item of `v` as BYTE blocks appended at byte offset `at`
void append_item_bytes(std::vector<std::pair<int64_t, int64_t>> &blocks,
                       const DtView &v, int64_t at) {
  int64_t item = (int64_t)v.di.item;
  if (!v.derived) {
    blocks.push_back({at, item});
    return;
  }
  for (auto &b : v.derived->blocks)
    blocks.push_back({at + b.first * item, b.second * item});
}

// extent/lb of one item in BYTES
int64_t extent_bytes_of(const DtView &v) {
  return (v.derived ? v.derived->extent : 1) * (int64_t)v.di.item;
}
int64_t lb_bytes_of(const DtView &v) {
  return (v.derived ? v.derived->lb : 0) * (int64_t)v.di.item;
}

// finalize a byte-based DtypeObj: elems = total bytes, base = BYTE.
// `swap_unit` records the uniform element size of the packed stream
// (0 for heterogeneous structs — external32 rejects those).
void seal_byte_type(DtypeObj &d, int swap_unit) {
  coalesce_blocks(d.blocks);
  d.base = MPI_BYTE;
  d.swap_unit = swap_unit;
  int64_t total = 0;
  for (auto &b : d.blocks) total += b.second;
  d.elems = total;
}

int register_dtype(DtypeObj d, MPI_Datatype *newtype) {
  MPI_Datatype handle = g_next_dtype++;
  g_dtypes[handle] = std::move(d);
  *newtype = handle;
  return MPI_SUCCESS;
}

}  // namespace

int MPI_Type_dup(MPI_Datatype oldtype, MPI_Datatype *newtype) {
  if (oldtype < DERIVED_BASE) {
    DtInfo di;
    if (!base_dtinfo(oldtype, di)) return MPI_ERR_TYPE;
    DtypeObj d;
    d.base = oldtype;
    d.blocks = {{0, 1}};
    d.extent = 1;
    d.elems = 1;
    d.combiner = MPI_COMBINER_DUP;
    d.env_types = {oldtype};
    return register_dtype(std::move(d), newtype);
  }
  auto it = g_dtypes.find(oldtype);
  if (it == g_dtypes.end()) return MPI_ERR_TYPE;
  DtypeObj d = it->second;
  d.combiner = MPI_COMBINER_DUP;
  d.env_ints.clear();
  d.env_aints.clear();
  d.env_types = {oldtype};
  d.committed = it->second.committed;  // dup of committed is committed
  return register_dtype(std::move(d), newtype);
}

int MPI_Type_create_resized(MPI_Datatype oldtype, MPI_Aint lb,
                            MPI_Aint extent, MPI_Datatype *newtype) {
  // type_create_resized.c: same typemap, caller-chosen lb/extent
  // (bytes) — the packing stride changes, the data does not
  DtView v;
  if (!resolve_for_build(oldtype, v)) return MPI_ERR_TYPE;
  DtypeObj d;
  append_item_bytes(d.blocks, v, 0);
  seal_byte_type(d, packed_unit_of(v.derived, oldtype, v.di.item));
  d.lb = lb;
  d.extent = extent;
  d.combiner = MPI_COMBINER_RESIZED;
  d.env_aints = {(long long)lb, (long long)extent};
  d.env_types = {oldtype};
  return register_dtype(std::move(d), newtype);
}

int MPI_Type_create_hvector(int count, int blocklength, MPI_Aint stride,
                            MPI_Datatype oldtype, MPI_Datatype *newtype) {
  // type_create_hvector.c: stride in BYTES
  if (count < 0 || blocklength < 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_for_build(oldtype, v)) return MPI_ERR_TYPE;
  int64_t oext = extent_bytes_of(v);
  DtypeObj d;
  int64_t max_ub = 0, min_lb = 0;
  for (int c = 0; c < count; c++) {
    int64_t base_off = (int64_t)c * stride;
    for (int b = 0; b < blocklength; b++) {
      int64_t off = base_off + (int64_t)b * oext;
      if (off < 0) return MPI_ERR_ARG;
      append_item_bytes(d.blocks, v, off);
      int64_t ilb = off + lb_bytes_of(v);
      if (ilb < min_lb) min_lb = ilb;
      if (ilb + oext > max_ub) max_ub = ilb + oext;
    }
  }
  seal_byte_type(d, packed_unit_of(v.derived, oldtype, v.di.item));
  d.lb = min_lb;
  d.extent = max_ub - min_lb;
  d.combiner = MPI_COMBINER_HVECTOR;
  d.env_ints = {count, blocklength};
  d.env_aints = {(long long)stride};
  d.env_types = {oldtype};
  return register_dtype(std::move(d), newtype);
}

static int hindexed_impl(int count, const int blocklengths[],
                         const MPI_Aint displacements[],
                         MPI_Datatype oldtype, MPI_Datatype *newtype,
                         int combiner) {
  // type_create_hindexed.c: displacements in BYTES
  if (count < 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_for_build(oldtype, v)) return MPI_ERR_TYPE;
  int64_t oext = extent_bytes_of(v);
  DtypeObj d;
  int64_t max_ub = INT64_MIN, min_lb = INT64_MAX;
  int64_t total = 0;
  for (int c = 0; c < count; c++) {
    if (blocklengths[c] < 0) return MPI_ERR_ARG;
    for (int b = 0; b < blocklengths[c]; b++) {
      int64_t off = (int64_t)displacements[c] + (int64_t)b * oext;
      if (off < 0) return MPI_ERR_ARG;
      append_item_bytes(d.blocks, v, off);
      int64_t ilb = off + lb_bytes_of(v);
      if (ilb < min_lb) min_lb = ilb;
      if (ilb + oext > max_ub) max_ub = ilb + oext;
    }
    total += blocklengths[c];
  }
  if (total == 0) { min_lb = 0; max_ub = 0; }
  seal_byte_type(d, packed_unit_of(v.derived, oldtype, v.di.item));
  d.lb = min_lb;
  d.extent = max_ub - min_lb;
  d.combiner = combiner;
  d.env_ints.push_back(count);
  if (combiner == MPI_COMBINER_HINDEXED_BLOCK) {
    d.env_ints.push_back(count ? blocklengths[0] : 0);
  } else {
    for (int c = 0; c < count; c++) d.env_ints.push_back(blocklengths[c]);
  }
  for (int c = 0; c < count; c++)
    d.env_aints.push_back((long long)displacements[c]);
  d.env_types = {oldtype};
  return register_dtype(std::move(d), newtype);
}

int MPI_Type_create_hindexed(int count, const int blocklengths[],
                             const MPI_Aint displacements[],
                             MPI_Datatype oldtype,
                             MPI_Datatype *newtype) {
  return hindexed_impl(count, blocklengths, displacements, oldtype,
                       newtype, MPI_COMBINER_HINDEXED);
}

int MPI_Type_create_hindexed_block(int count, int blocklength,
                                   const MPI_Aint displacements[],
                                   MPI_Datatype oldtype,
                                   MPI_Datatype *newtype) {
  if (count < 0 || blocklength < 0) return MPI_ERR_ARG;
  std::vector<int> lens((size_t)count, blocklength);
  return hindexed_impl(count, lens.data(), displacements, oldtype,
                       newtype, MPI_COMBINER_HINDEXED_BLOCK);
}

int MPI_Type_create_struct(int count, const int blocklengths[],
                           const MPI_Aint displacements[],
                           const MPI_Datatype types[],
                           MPI_Datatype *newtype) {
  // type_create_struct.c: heterogeneous fields — the one constructor
  // that FORCES the byte flattening
  if (count < 0) return MPI_ERR_ARG;
  DtypeObj d;
  int64_t max_ub = INT64_MIN, min_lb = INT64_MAX;
  int64_t total = 0;
  for (int c = 0; c < count; c++) {
    if (blocklengths[c] < 0) return MPI_ERR_ARG;
    DtView v;
    if (!resolve_for_build(types[c], v)) return MPI_ERR_TYPE;
    int64_t oext = extent_bytes_of(v);
    for (int b = 0; b < blocklengths[c]; b++) {
      int64_t off = (int64_t)displacements[c] + (int64_t)b * oext;
      if (off < 0) return MPI_ERR_ARG;
      append_item_bytes(d.blocks, v, off);
      int64_t ilb = off + lb_bytes_of(v);
      if (ilb < min_lb) min_lb = ilb;
      if (ilb + oext > max_ub) max_ub = ilb + oext;
    }
    total += blocklengths[c];
  }
  if (total == 0) { min_lb = 0; max_ub = 0; }
  // typemap stays in DECLARATION order (pack serializes field order);
  // a uniform field unit survives for canonical packing, mixed -> 0
  int su = -1;
  for (int c = 0; c < count; c++) {
    if (blocklengths[c] == 0) continue;
    DtView fv;
    resolve_for_build(types[c], fv);
    int u = packed_unit_of(fv.derived, types[c], fv.di.item);
    if (su < 0) su = u;
    else if (su != u) su = 0;
  }
  seal_byte_type(d, su < 0 ? 1 : su);
  d.lb = min_lb;
  d.extent = max_ub - min_lb;
  d.combiner = MPI_COMBINER_STRUCT;
  d.env_ints.push_back(count);
  for (int c = 0; c < count; c++) d.env_ints.push_back(blocklengths[c]);
  for (int c = 0; c < count; c++)
    d.env_aints.push_back((long long)displacements[c]);
  d.env_types.assign(types, types + count);
  return register_dtype(std::move(d), newtype);
}

namespace {

// shared emitter for subarray/darray: per-dimension index RUNS over a
// full array of `sizes`, emitted as oldtype-unit blocks.  `order`
// fixes which dimension is unit-stride (C: last, Fortran: first).
void emit_runs(const std::vector<std::vector<std::pair<int, int>>> &runs,
               const std::vector<int> &sizes, int order, const DtView &v,
               DtypeObj &d) {
  int nd = (int)sizes.size();
  std::vector<int64_t> stride((size_t)nd);  // in oldtype units
  int contig;
  if (order == MPI_ORDER_C) {
    contig = nd - 1;
    stride[(size_t)nd - 1] = 1;
    for (int i = nd - 2; i >= 0; i--)
      stride[(size_t)i] = stride[(size_t)i + 1] * sizes[(size_t)i + 1];
  } else {
    contig = 0;
    stride[0] = 1;
    for (int i = 1; i < nd; i++)
      stride[(size_t)i] = stride[(size_t)i - 1] * sizes[(size_t)i - 1];
  }
  int64_t oext = extent_bytes_of(v);
  // odometer over every non-contiguous dimension's individual indices;
  // the contiguous dimension emits whole runs
  std::function<void(int, int64_t)> rec = [&](int dim, int64_t off) {
    if (dim == nd) {
      for (auto &r : runs[(size_t)contig]) {
        int64_t at = (off + (int64_t)r.first * stride[(size_t)contig]) *
                     oext;
        for (int k = 0; k < r.second; k++)
          append_item_bytes(d.blocks, v, at + (int64_t)k * oext);
      }
      return;
    }
    if (dim == contig) {
      rec(dim + 1, off);
      return;
    }
    for (auto &r : runs[(size_t)dim])
      for (int k = 0; k < r.second; k++)
        rec(dim + 1, off + ((int64_t)r.first + k) * stride[(size_t)dim]);
  };
  rec(0, 0);
}

}  // namespace

int MPI_Type_create_subarray(int ndims, const int sizes[],
                             const int subsizes[], const int starts[],
                             int order, MPI_Datatype oldtype,
                             MPI_Datatype *newtype) {
  // type_create_subarray.c: extent spans the FULL array (lb 0), the
  // typemap covers the subarray block
  if (ndims <= 0) return MPI_ERR_ARG;
  if (order != MPI_ORDER_C && order != MPI_ORDER_FORTRAN)
    return MPI_ERR_ARG;
  DtView v;
  if (!resolve_for_build(oldtype, v)) return MPI_ERR_TYPE;
  std::vector<std::vector<std::pair<int, int>>> runs((size_t)ndims);
  int64_t full = 1;
  for (int i = 0; i < ndims; i++) {
    if (sizes[i] <= 0 || subsizes[i] < 0 || starts[i] < 0 ||
        starts[i] + subsizes[i] > sizes[i])
      return MPI_ERR_ARG;
    if (subsizes[i] > 0) runs[(size_t)i] = {{starts[i], subsizes[i]}};
    full *= sizes[i];
  }
  DtypeObj d;
  emit_runs(runs, std::vector<int>(sizes, sizes + ndims), order, v, d);
  seal_byte_type(d, packed_unit_of(v.derived, oldtype, v.di.item));
  d.lb = 0;
  d.extent = full * extent_bytes_of(v);
  d.combiner = MPI_COMBINER_SUBARRAY;
  d.env_ints.push_back(ndims);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(sizes[i]);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(subsizes[i]);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(starts[i]);
  d.env_ints.push_back(order);
  d.env_types = {oldtype};
  return register_dtype(std::move(d), newtype);
}

int MPI_Type_create_darray(int size, int rank, int ndims,
                           const int gsizes[], const int distribs[],
                           const int dargs[], const int psizes[],
                           int order, MPI_Datatype oldtype,
                           MPI_Datatype *newtype) {
  // type_create_darray.c: HPF-style distributions.  The process grid
  // is ALWAYS row-major over psizes (MPI-3.1 §4.1.4); `order` governs
  // only the array storage order.
  if (ndims <= 0 || size <= 0 || rank < 0 || rank >= size)
    return MPI_ERR_ARG;
  if (order != MPI_ORDER_C && order != MPI_ORDER_FORTRAN)
    return MPI_ERR_ARG;
  int64_t grid = 1;
  for (int i = 0; i < ndims; i++) {
    if (psizes[i] <= 0 || gsizes[i] <= 0) return MPI_ERR_ARG;
    if (distribs[i] == MPI_DISTRIBUTE_NONE && psizes[i] != 1)
      return MPI_ERR_ARG;
    grid *= psizes[i];
  }
  if (grid != size) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_for_build(oldtype, v)) return MPI_ERR_TYPE;
  // my coordinates, row-major
  std::vector<int> coord((size_t)ndims);
  int rem = rank;
  for (int i = ndims - 1; i >= 0; i--) {
    coord[(size_t)i] = rem % psizes[i];
    rem /= psizes[i];
  }
  std::vector<std::vector<std::pair<int, int>>> runs((size_t)ndims);
  int64_t full = 1;
  for (int i = 0; i < ndims; i++) {
    full *= gsizes[i];
    int n = gsizes[i], p = psizes[i], c = coord[(size_t)i];
    switch (distribs[i]) {
      case MPI_DISTRIBUTE_NONE:
        runs[(size_t)i] = {{0, n}};
        break;
      case MPI_DISTRIBUTE_BLOCK: {
        int b = dargs[i] == MPI_DISTRIBUTE_DFLT_DARG
                    ? (n + p - 1) / p
                    : dargs[i];
        if (b <= 0 || (int64_t)b * p < n) return MPI_ERR_ARG;
        int start = c * b;
        int len = start < n ? (start + b > n ? n - start : b) : 0;
        if (len > 0) runs[(size_t)i] = {{start, len}};
        break;
      }
      case MPI_DISTRIBUTE_CYCLIC: {
        int b = dargs[i] == MPI_DISTRIBUTE_DFLT_DARG ? 1 : dargs[i];
        if (b <= 0) return MPI_ERR_ARG;
        for (int64_t start = (int64_t)c * b; start < n;
             start += (int64_t)p * b) {
          int len = (int)(start + b > n ? n - start : b);
          runs[(size_t)i].push_back({(int)start, len});
        }
        break;
      }
      default:
        return MPI_ERR_ARG;
    }
  }
  DtypeObj d;
  emit_runs(runs, std::vector<int>(gsizes, gsizes + ndims), order, v, d);
  seal_byte_type(d, packed_unit_of(v.derived, oldtype, v.di.item));
  d.lb = 0;
  d.extent = full * extent_bytes_of(v);
  d.combiner = MPI_COMBINER_DARRAY;
  d.env_ints.push_back(size);
  d.env_ints.push_back(rank);
  d.env_ints.push_back(ndims);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(gsizes[i]);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(distribs[i]);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(dargs[i]);
  for (int i = 0; i < ndims; i++) d.env_ints.push_back(psizes[i]);
  d.env_ints.push_back(order);
  d.env_types = {oldtype};
  return register_dtype(std::move(d), newtype);
}

namespace {

// true extent: the typemap's actual byte span, resized lb/ub ignored
// (type_get_true_extent.c)
int true_extent_impl(MPI_Datatype dt, int64_t &tlb, int64_t &text) {
  if (dt < DERIVED_BASE) {
    DtInfo di;
    if (!base_dtinfo(dt, di)) return MPI_ERR_TYPE;
    tlb = 0;
    text = (int64_t)di.item;
    return MPI_SUCCESS;
  }
  auto it = g_dtypes.find(dt);
  if (it == g_dtypes.end()) return MPI_ERR_TYPE;
  DtInfo di;
  if (!base_dtinfo(it->second.base, di)) return MPI_ERR_TYPE;
  if (it->second.blocks.empty()) {
    tlb = 0;
    text = 0;
    return MPI_SUCCESS;
  }
  int64_t lo = INT64_MAX, hi = INT64_MIN;
  for (auto &b : it->second.blocks) {
    if (b.first < lo) lo = b.first;
    if (b.first + b.second > hi) hi = b.first + b.second;
  }
  tlb = lo * (int64_t)di.item;
  text = (hi - lo) * (int64_t)di.item;
  return MPI_SUCCESS;
}

}  // namespace

int MPI_Type_get_true_extent(MPI_Datatype dt, MPI_Aint *true_lb,
                             MPI_Aint *true_extent) {
  int64_t tlb, text;
  int rc = true_extent_impl(dt, tlb, text);
  if (rc != MPI_SUCCESS) return rc;
  *true_lb = (MPI_Aint)tlb;
  *true_extent = (MPI_Aint)text;
  return MPI_SUCCESS;
}

int MPI_Type_get_true_extent_x(MPI_Datatype dt, MPI_Count *true_lb,
                               MPI_Count *true_extent) {
  int64_t tlb, text;
  int rc = true_extent_impl(dt, tlb, text);
  if (rc != MPI_SUCCESS) return rc;
  *true_lb = (MPI_Count)tlb;
  *true_extent = (MPI_Count)text;
  return MPI_SUCCESS;
}

int MPI_Type_get_extent_x(MPI_Datatype dt, MPI_Count *lb,
                          MPI_Count *extent) {
  long l, e;
  int rc = MPI_Type_get_extent(dt, &l, &e);
  if (rc != MPI_SUCCESS) return rc;
  *lb = (MPI_Count)l;
  *extent = (MPI_Count)e;
  return MPI_SUCCESS;
}

int MPI_Type_size_x(MPI_Datatype dt, MPI_Count *size) {
  int s;
  int rc = MPI_Type_size(dt, &s);
  if (rc != MPI_SUCCESS) return rc;
  *size = (MPI_Count)s;
  return MPI_SUCCESS;
}

int MPI_Type_get_envelope(MPI_Datatype dt, int *num_integers,
                          int *num_addresses, int *num_datatypes,
                          int *combiner) {
  if (dt < DERIVED_BASE) {
    DtInfo di;
    if (!base_dtinfo(dt, di)) return MPI_ERR_TYPE;
    *num_integers = *num_addresses = *num_datatypes = 0;
    *combiner = MPI_COMBINER_NAMED;
    return MPI_SUCCESS;
  }
  auto it = g_dtypes.find(dt);
  if (it == g_dtypes.end()) return MPI_ERR_TYPE;
  *num_integers = (int)it->second.env_ints.size();
  *num_addresses = (int)it->second.env_aints.size();
  *num_datatypes = (int)it->second.env_types.size();
  *combiner = it->second.combiner;
  return MPI_SUCCESS;
}

int MPI_Type_get_contents(MPI_Datatype dt, int max_integers,
                          int max_addresses, int max_datatypes,
                          int integers[], MPI_Aint addresses[],
                          MPI_Datatype datatypes[]) {
  if (dt < DERIVED_BASE) return MPI_ERR_TYPE;  // NAMED has no contents
  auto it = g_dtypes.find(dt);
  if (it == g_dtypes.end()) return MPI_ERR_TYPE;
  DtypeObj &d = it->second;
  if (max_integers < (int)d.env_ints.size() ||
      max_addresses < (int)d.env_aints.size() ||
      max_datatypes < (int)d.env_types.size())
    return MPI_ERR_ARG;
  for (size_t i = 0; i < d.env_ints.size(); i++)
    integers[i] = d.env_ints[i];
  for (size_t i = 0; i < d.env_aints.size(); i++)
    addresses[i] = (MPI_Aint)d.env_aints[i];
  for (size_t i = 0; i < d.env_types.size(); i++)
    datatypes[i] = d.env_types[i];
  return MPI_SUCCESS;
}

int MPI_Type_hvector(int count, int blocklength, MPI_Aint stride,
                     MPI_Datatype oldtype, MPI_Datatype *newtype) {
  return MPI_Type_create_hvector(count, blocklength, stride, oldtype,
                                 newtype);
}

int MPI_Type_hindexed(int count, int blocklengths[],
                      MPI_Aint displacements[], MPI_Datatype oldtype,
                      MPI_Datatype *newtype) {
  return MPI_Type_create_hindexed(count, blocklengths, displacements,
                                  oldtype, newtype);
}

int MPI_Type_struct(int count, int blocklengths[],
                    MPI_Aint displacements[], MPI_Datatype types[],
                    MPI_Datatype *newtype) {
  return MPI_Type_create_struct(count, blocklengths, displacements,
                                types, newtype);
}

int MPI_Type_extent(MPI_Datatype dt, MPI_Aint *extent) {
  long lb, e;
  int rc = MPI_Type_get_extent(dt, &lb, &e);
  if (rc != MPI_SUCCESS) return rc;
  *extent = (MPI_Aint)e;
  return MPI_SUCCESS;
}

int MPI_Type_lb(MPI_Datatype dt, MPI_Aint *lb) {
  long l, e;
  int rc = MPI_Type_get_extent(dt, &l, &e);
  if (rc != MPI_SUCCESS) return rc;
  *lb = (MPI_Aint)l;
  return MPI_SUCCESS;
}

int MPI_Type_ub(MPI_Datatype dt, MPI_Aint *ub) {
  long l, e;
  int rc = MPI_Type_get_extent(dt, &l, &e);
  if (rc != MPI_SUCCESS) return rc;
  *ub = (MPI_Aint)(l + e);
  return MPI_SUCCESS;
}

// ---------------------------------------------------- probe / any / all

namespace {

int probe_impl(int source, int tag, CommObj *c, int *flag,
               MPI_Status *status, bool blocking) {
  int src_world = source == MPI_ANY_SOURCE ? MPI_ANY_SOURCE
                                           : peer_world_of(*c, source);
  if (source != MPI_ANY_SOURCE && src_world < 0) return MPI_ERR_ARG;
  std::unique_lock<std::mutex> lk(g.match_mu);
  while (true) {
    for (auto &m : g.unexpected) {
      if (m.mhandle) continue;  // owned by a matched probe
      if (m.cid != c->cid_pt2pt) continue;
      if (src_world != MPI_ANY_SOURCE && m.src != src_world) continue;
      if (tag != MPI_ANY_TAG && m.tag != tag) continue;
      if (status) {
        status->MPI_SOURCE = (int)m.src;
        status->MPI_TAG = (int)m.tag;
        status->MPI_ERROR = MPI_SUCCESS;
        // bytes (Get_count converts); an announced-but-not-landed
        // rendezvous reports the size its RTS declared
        status->_count = m.rndv_pending ? (long long)m.rndv_nbytes
                                         : (long long)m.data.size();
        status->_cancelled = 0;
      }
      if (flag) *flag = 1;
      return MPI_SUCCESS;
    }
    if (!blocking) {
      if (flag) *flag = 0;
      return MPI_SUCCESS;
    }
    g.match_cv.wait_for(lk, std::chrono::milliseconds(100));
    if (g.closing.load()) return MPI_ERR_OTHER;
  }
}

}  // namespace

int MPI_Probe(int source, int tag, MPI_Comm comm, MPI_Status *status) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  MPI_Status st{};
  int rc = probe_impl(source, tag, c, nullptr, &st, true);
  if (rc == MPI_SUCCESS && status) {
    *status = st;
    translate_status(c, status);
  }
  return rc;
}

int MPI_Iprobe(int source, int tag, MPI_Comm comm, int *flag,
               MPI_Status *status) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  MPI_Status st{};
  int rc = probe_impl(source, tag, c, flag, &st, false);
  if (rc == MPI_SUCCESS && *flag && status) {
    *status = st;
    translate_status(c, status);
  }
  return rc;
}

// ----------------------------------------- matched probe (round 5)
// mprobe.c family: Improbe EXTRACTS the first matching message from
// the unexpected queue (marks it owned; ordinary matching skips it)
// so a later Mrecv receives exactly that message — the thread-safe
// probe+recv idiom.  A rendezvous-pending message is claimed at
// Improbe time (the CTS goes out immediately); its payload lands in
// place and Mrecv/Imrecv completes from there.

static int64_t g_next_msg = 1;
// extracted-message bookkeeping: mhandle -> owning comm handle
static std::map<int64_t, int> g_msgs;

int MPI_Improbe(int source, int tag, MPI_Comm comm, int *flag,
                MPI_Message *message, MPI_Status *status) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (source == MPI_PROC_NULL) {
    *flag = 1;
    *message = MPI_MESSAGE_NO_PROC;
    empty_status(status);
    if (status) status->MPI_SOURCE = MPI_PROC_NULL;
    return MPI_SUCCESS;
  }
  int src_world = source == MPI_ANY_SOURCE ? MPI_ANY_SOURCE
                                           : peer_world_of(*c, source);
  if (source != MPI_ANY_SOURCE && src_world < 0) return MPI_ERR_ARG;
  int64_t cts_src = -1, cts_rid = -1;
  bool found = false;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (auto &m : g.unexpected) {
      if (m.mhandle) continue;
      if (m.cid != c->cid_pt2pt) continue;
      if (src_world != MPI_ANY_SOURCE && m.src != src_world) continue;
      if (tag != MPI_ANY_TAG && m.tag != tag) continue;
      m.mhandle = g_next_msg++;
      g_msgs[m.mhandle] = comm;
      if (m.rndv_pending) {
        // extraction IS the claim: release the sender; the bulk data
        // fills this message in place (CTS goes out after the lock
        // drops — the engine's ordering invariant)
        cts_src = m.src;
        cts_rid = m.rndv_id;
      }
      *message = (MPI_Message)m.mhandle;
      if (status) {
        status->MPI_SOURCE = (int)m.src;
        status->MPI_TAG = (int)m.tag;
        status->MPI_ERROR = MPI_SUCCESS;
        status->_count = m.rndv_pending ? (long long)m.rndv_nbytes
                                        : (long long)m.data.size();
        status->_cancelled = 0;
        translate_status(c, status);
      }
      found = true;
      break;
    }
  }
  if (found) {
    if (cts_src >= 0) send_cts(cts_src, cts_rid);
    *flag = 1;
    return MPI_SUCCESS;
  }
  *flag = 0;
  *message = MPI_MESSAGE_NULL;
  return MPI_SUCCESS;
}

int MPI_Mprobe(int source, int tag, MPI_Comm comm, MPI_Message *message,
               MPI_Status *status) {
  while (true) {
    int flag = 0;
    int rc = MPI_Improbe(source, tag, comm, &flag, message, status);
    if (rc != MPI_SUCCESS || flag) return rc;
    std::unique_lock<std::mutex> lk(g.match_mu);
    g.match_cv.wait_for(lk, std::chrono::milliseconds(100));
    if (g.closing.load()) return MPI_ERR_OTHER;
  }
}

int MPI_Imrecv(void *buf, int count, MPI_Datatype dt,
               MPI_Message *message, MPI_Request *request) {
  if (!message) return MPI_ERR_ARG;
  if (*message == MPI_MESSAGE_NO_PROC) {
    *message = MPI_MESSAGE_NULL;
    Req *r0;
    *request = make_completed_req(MPI_COMM_WORLD, &r0);
    r0->status.MPI_SOURCE = MPI_PROC_NULL;  // the Irecv PROC_NULL shape
    r0->status.MPI_TAG = MPI_ANY_TAG;
    return MPI_SUCCESS;
  }
  if (*message == MPI_MESSAGE_NULL) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  auto gm = g_msgs.find(*message);
  if (gm == g_msgs.end()) return MPI_ERR_ARG;
  int comm = gm->second;
  Req *r = new Req;
  r->heap = true;
  r->is_recv = true;
  r->comm = comm;
  r->user_buf = buf;
  r->count = count;
  size_t want;
  char *land = prepare_landing(r, v, want);
  int handle;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    handle = g.next_req++;
    g.reqs[handle] = r;
    Posted p{r, 0, MPI_ANY_SOURCE, MPI_ANY_TAG, land, want, v.di.item};
    for (auto it = g.unexpected.begin(); it != g.unexpected.end();
         ++it) {
      if (it->mhandle != (int64_t)*message) continue;
      if (it->rndv_pending) {
        // payload still in flight: park the landing plan; the fill
        // path (land_rndv_data) completes it
        g_mrecv_wait[it->mhandle] = p;
      } else {
        deliver(p, *it);
        g.unexpected.erase(it);
      }
      g_msgs.erase(gm);
      *message = MPI_MESSAGE_NULL;
      *request = handle;
      return MPI_SUCCESS;
    }
  }
  // extracted message vanished: only possible via engine teardown
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    g.reqs.erase(handle);
  }
  delete r;
  return MPI_ERR_OTHER;
}

int MPI_Mrecv(void *buf, int count, MPI_Datatype dt,
              MPI_Message *message, MPI_Status *status) {
  if (message && *message == MPI_MESSAGE_NO_PROC) {
    *message = MPI_MESSAGE_NULL;
    empty_status(status);
    if (status) status->MPI_SOURCE = MPI_PROC_NULL;
    return MPI_SUCCESS;
  }
  MPI_Request req;
  int rc = MPI_Imrecv(buf, count, dt, message, &req);
  if (rc != MPI_SUCCESS) return rc;
  return MPI_Wait(&req, status);
}

MPI_Fint MPI_Message_c2f(MPI_Message message) {
  return (MPI_Fint)message;
}
MPI_Message MPI_Message_f2c(MPI_Fint message) {
  return (MPI_Message)message;
}

int MPI_Testany(int count, MPI_Request requests[], int *index, int *flag,
                MPI_Status *status) {
  // testany.c: one non-blocking scan of the set; persistent handles
  // (< MPI_REQUEST_NULL) count as ready when inactive or when their
  // inner active op completed
  bool any_active = false;
  int ready = -1;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < count && ready < 0; i++) {
      MPI_Request h = requests[i];
      if (h == MPI_REQUEST_NULL) continue;
      any_active = true;
      if (h < MPI_REQUEST_NULL) {
        auto pit = g_persistent.find(-h);
        if (pit == g_persistent.end()) return MPI_ERR_REQUEST;
        if (pit->second.active == MPI_REQUEST_NULL) {
          ready = i;  // inactive persistent tests as complete
        } else {
          auto it = g.reqs.find(pit->second.active);
          if (it == g.reqs.end()) return MPI_ERR_REQUEST;
          if (it->second->complete) ready = i;
        }
        continue;
      }
      auto it = g.reqs.find(h);
      if (it == g.reqs.end()) return MPI_ERR_REQUEST;
      if (it->second->complete) ready = i;
    }
  }
  if (!any_active) {
    *index = MPI_UNDEFINED;
    *flag = 1;
    empty_status(status);
    return MPI_SUCCESS;
  }
  if (ready < 0) {
    *flag = 0;
    *index = MPI_UNDEFINED;
    return MPI_SUCCESS;
  }
  *flag = 1;
  *index = ready;
  return MPI_Wait(&requests[ready], status);
}

int MPI_Waitany(int count, MPI_Request requests[], int *index,
                MPI_Status *status) {
  bool any_active = false;
  for (int i = 0; i < count; i++)
    if (requests[i] != MPI_REQUEST_NULL) any_active = true;
  if (!any_active) {
    *index = MPI_UNDEFINED;
    return MPI_SUCCESS;
  }
  while (true) {
    int ready = -1;
    {
      std::unique_lock<std::mutex> lk(g.match_mu);
      for (int i = 0; i < count && ready < 0; i++) {
        if (requests[i] == MPI_REQUEST_NULL) continue;
        auto it = g.reqs.find(requests[i]);
        if (it == g.reqs.end()) return MPI_ERR_REQUEST;
        if (it->second->complete) ready = i;
      }
      if (ready < 0) {
        g.match_cv.wait_for(lk, std::chrono::milliseconds(100));
        if (g.closing.load()) return MPI_ERR_OTHER;
      }
    }
    if (ready >= 0) {
      *index = ready;
      return MPI_Wait(&requests[ready], status);
    }
  }
}

int MPI_Testall(int count, MPI_Request requests[], int *flag,
                MPI_Status statuses[]) {
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < count; i++) {
      if (requests[i] == MPI_REQUEST_NULL) continue;
      auto it = g.reqs.find(requests[i]);
      if (it == g.reqs.end()) return MPI_ERR_REQUEST;
      if (!it->second->complete) {
        *flag = 0;
        return MPI_SUCCESS;
      }
    }
  }
  *flag = 1;
  return MPI_Waitall(count, requests,
                     statuses ? statuses : MPI_STATUSES_IGNORE);
}

// ------------------------------------------------- scan/v-collectives

static int scan_wrapper(const void *sendbuf, void *recvbuf, int count,
                        MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                        bool exclusive) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    int rc = clone_region(recvbuf, count, dt, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
  }
  return dispatch_comm_err(
      comm, c_scan(*c, sendbuf, recvbuf, count, dt, op, exclusive));
}

int MPI_Scan(const void *sendbuf, void *recvbuf, int count,
             MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
  return scan_wrapper(sendbuf, recvbuf, count, dt, op, comm, false);
}

int MPI_Exscan(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
  return scan_wrapper(sendbuf, recvbuf, count, dt, op, comm, true);
}

int MPI_Gatherv(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, const int recvcounts[], const int displs[],
                MPI_Datatype recvtype, int root, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (root < 0 || root >= (int)c->group.size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    if (c->local_rank != root)
      return dispatch_comm_err(comm, MPI_ERR_ARG);
    DtView rv;
    if (!resolve_dtype(recvtype, rv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    const char *slice = (const char *)recvbuf +
                        (size_t)displs[root] * slot_bytes(rv, 1);
    int rc = clone_region(slice, recvcounts[root], recvtype, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcount = recvcounts[root];
    sendtype = recvtype;
  }
  return dispatch_comm_err(
      comm, c_gatherv(*c, sendbuf, sendcount, sendtype, recvbuf,
                      recvcounts, displs, recvtype, root));
}

int MPI_Allgatherv(const void *sendbuf, int sendcount,
                   MPI_Datatype sendtype, void *recvbuf,
                   const int recvcounts[], const int displs[],
                   MPI_Datatype recvtype, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    int me = c->local_rank;
    DtView rv;
    if (!resolve_dtype(recvtype, rv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    const char *slice = (const char *)recvbuf +
                        (size_t)displs[me] * slot_bytes(rv, 1);
    int rc = clone_region(slice, recvcounts[me], recvtype, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcount = recvcounts[me];
    sendtype = recvtype;
  }
  return dispatch_comm_err(
      comm, c_allgatherv(*c, sendbuf, sendcount, sendtype, recvbuf,
                         recvcounts, displs, recvtype));
}

int MPI_Scatterv(const void *sendbuf, const int sendcounts[],
                 const int displs[], MPI_Datatype sendtype, void *recvbuf,
                 int recvcount, MPI_Datatype recvtype, int root,
                 MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  if (root < 0 || root >= (int)c->group.size())
    return dispatch_comm_err(comm, MPI_ERR_ARG);
  std::vector<char> scratch;
  if (recvbuf == MPI_IN_PLACE) {
    if (c->local_rank != root)
      return dispatch_comm_err(comm, MPI_ERR_ARG);
    DtView sv;
    if (!resolve_dtype(sendtype, sv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    scratch.resize(slot_bytes(sv, sendcounts[root]));
    recvbuf = scratch.data();
    recvcount = sendcounts[root];
    recvtype = sendtype;
  }
  return dispatch_comm_err(
      comm, c_scatterv(*c, sendbuf, sendcounts, displs, sendtype,
                       recvbuf, recvcount, recvtype, root));
}

int MPI_Reduce_scatter_block(const void *sendbuf, void *recvbuf,
                             int recvcount, MPI_Datatype dt, MPI_Op op,
                             MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    // reduce_scatter_block.c: input is the FULL n*recvcount vector in
    // recvbuf
    int rc = clone_region(recvbuf,
                          (int)c->group.size() * recvcount, dt, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
  }
  return dispatch_comm_err(
      comm,
      c_reduce_scatter_block(*c, sendbuf, recvbuf, recvcount, dt, op));
}

int MPI_Reduce_scatter(const void *sendbuf, void *recvbuf,
                       const int recvcounts[], MPI_Datatype dt, MPI_Op op,
                       MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    int total = 0;
    for (int r = 0; r < (int)c->group.size(); r++)
      total += recvcounts[r];
    int rc = clone_region(recvbuf, total, dt, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
  }
  return dispatch_comm_err(
      comm, c_reduce_scatter(*c, sendbuf, recvbuf, recvcounts, dt, op));
}

int MPI_Alltoallv(const void *sendbuf, const int sendcounts[],
                  const int sdispls[], MPI_Datatype sendtype,
                  void *recvbuf, const int recvcounts[],
                  const int rdispls[], MPI_Datatype recvtype,
                  MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    // alltoallv.c IN_PLACE: counts/displacements/type come from the
    // receive side; clone the full spanned region
    int n = (int)c->group.size();
    DtView rv;
    if (!resolve_dtype(recvtype, rv))
      return dispatch_comm_err(comm, MPI_ERR_TYPE);
    int span = 0;
    for (int r = 0; r < n; r++)
      if (rdispls[r] + recvcounts[r] > span)
        span = rdispls[r] + recvcounts[r];
    int rc = clone_region(recvbuf, span, recvtype, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcounts = recvcounts;
    sdispls = rdispls;
    sendtype = recvtype;
  }
  return dispatch_comm_err(
      comm, c_alltoallv(*c, sendbuf, sendcounts, sdispls, sendtype,
                        recvbuf, recvcounts, rdispls, recvtype));
}

// alltoallw.c IN_PLACE: everything comes from the receive side; clone
// each peer's block (byte displacements, per-peer types) into `tmp`.
// Validates counts/displacements BEFORE dereferencing anything.
static int alltoallw_inplace_clone(int n, const void *recvbuf,
                                   const int recvcounts[],
                                   const int rdispls[],
                                   const MPI_Datatype recvtypes[],
                                   std::vector<char> &tmp) {
  int64_t span = 0;
  for (int r = 0; r < n; r++) {
    if (recvcounts[r] < 0 || rdispls[r] < 0) return MPI_ERR_ARG;
    DtView rv;
    if (recvcounts[r] == 0) continue;
    if (!resolve_dtype(recvtypes[r], rv)) return MPI_ERR_TYPE;
    int64_t end = rdispls[r] + (int64_t)slot_bytes(rv, recvcounts[r]);
    if (end > span) span = end;
  }
  tmp.assign((size_t)span, 0);
  for (int r = 0; r < n; r++) {
    if (recvcounts[r] == 0) continue;
    DtView rv;
    resolve_dtype(recvtypes[r], rv);
    std::vector<char> packed;
    pack_dtype((const char *)recvbuf + rdispls[r], recvcounts[r], rv,
               packed);
    unpack_dtype(tmp.data() + rdispls[r], recvcounts[r], rv,
                 packed.data(), packed.size());
  }
  return MPI_SUCCESS;
}

int MPI_Alltoallw(const void *sendbuf, const int sendcounts[],
                  const int sdispls[], const MPI_Datatype sendtypes[],
                  void *recvbuf, const int recvcounts[],
                  const int rdispls[], const MPI_Datatype recvtypes[],
                  MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  int n = (int)c->group.size();
  std::vector<char> tmp;
  if (sendbuf == MPI_IN_PLACE) {
    int rc = alltoallw_inplace_clone(n, recvbuf, recvcounts, rdispls,
                                     recvtypes, tmp);
    if (rc != MPI_SUCCESS) return dispatch_comm_err(comm, rc);
    sendbuf = tmp.data();
    sendcounts = recvcounts;
    sdispls = rdispls;
    sendtypes = recvtypes;
  }
  return dispatch_comm_err(
      comm, c_alltoallw(*c, sendbuf, sendcounts, sdispls, sendtypes,
                        recvbuf, recvcounts, rdispls, recvtypes));
}

// ------------------------------------------------------------ user ops

int MPI_Op_create(MPI_User_function *function, int commute, MPI_Op *op) {
  if (!function || !op) return MPI_ERR_ARG;
  MPI_Op handle = g_next_op++;
  g_user_ops[handle] = UserOp{function, commute != 0};
  *op = handle;
  return MPI_SUCCESS;
}

int MPI_Op_free(MPI_Op *op) {
  if (!op || !g_user_ops.erase(*op)) return MPI_ERR_OP;
  *op = MPI_OP_NULL;
  return MPI_SUCCESS;
}

// --------------------------------------------------------- diagnostics

// user-added error classes/codes/strings (add_error_class.c family)
static std::map<int, std::string> g_err_strings;
static std::map<int, int> g_err_class;  // user code -> its class
static int g_next_err = MPI_ERR_LASTCODE + 1;

int MPI_Error_string(int errorcode, char *string, int *resultlen) {
  auto uit = g_err_strings.find(errorcode);
  if (uit != g_err_strings.end()) {
    snprintf(string, MPI_MAX_ERROR_STRING, "%s", uit->second.c_str());
    *resultlen = (int)strlen(string);
    return MPI_SUCCESS;
  }
  const char *s;
  switch (errorcode) {
    case MPI_SUCCESS:      s = "MPI_SUCCESS: no error"; break;
    case MPI_ERR_COMM:     s = "MPI_ERR_COMM: invalid communicator"; break;
    case MPI_ERR_TYPE:     s = "MPI_ERR_TYPE: invalid datatype"; break;
    case MPI_ERR_OP:       s = "MPI_ERR_OP: invalid reduction operation";
                           break;
    case MPI_ERR_REQUEST:  s = "MPI_ERR_REQUEST: invalid request"; break;
    case MPI_ERR_ARG:      s = "MPI_ERR_ARG: invalid argument"; break;
    case MPI_ERR_COUNT:    s = "MPI_ERR_COUNT: invalid count (message "
                               "exceeds the 4 GiB frame bound)"; break;
    case MPI_ERR_TRUNCATE: s = "MPI_ERR_TRUNCATE: message truncated";
                           break;
    case MPI_ERR_IN_STATUS: s = "MPI_ERR_IN_STATUS: see the status "
                                "array for per-request error codes";
                            break;
    case MPI_ERR_OTHER:    s = "MPI_ERR_OTHER: known error not in list";
                           break;
    default:               s = "unknown error code"; break;
  }
  snprintf(string, MPI_MAX_ERROR_STRING, "%s", s);
  *resultlen = (int)strlen(string);
  return MPI_SUCCESS;
}

int MPI_Type_get_extent(MPI_Datatype dt, long *lb, long *extent) {
  DtView v;
  if (!resolve_dtype(dt, v)) {
    // allow uncommitted derived types for extent queries
    auto it = g_dtypes.find(dt);
    if (it == g_dtypes.end()) return MPI_ERR_TYPE;
    DtInfo di;
    if (!base_dtinfo(it->second.base, di)) return MPI_ERR_TYPE;
    *lb = (long)(it->second.lb * (int64_t)di.item);
    *extent = (long)(it->second.extent * (int64_t)di.item);
    return MPI_SUCCESS;
  }
  *lb = (long)((v.derived ? v.derived->lb : 0) * (int64_t)v.di.item);
  *extent = (long)slot_bytes(v, 1);
  return MPI_SUCCESS;
}

// --------------------------------------------------------------- MPI-IO
// Byte-view file surface over POSIX at-offset IO (the romio-level C
// semantics with the default MPI_BYTE etype; collective open/close via
// the communicator's barrier, matching io_ompio_file_open.c's shape).

namespace {

FileObj *lookup_file(MPI_File fh) {
  std::lock_guard<std::mutex> lk(g_files_mu);
  auto it = g_files.find(fh);
  return it == g_files.end() ? nullptr : &it->second;
}

// fill an MPI_Status for a file transfer of `nbytes`
void file_status(MPI_Status *status, size_t nbytes) {
  if (status) {
    status->MPI_SOURCE = MPI_ANY_SOURCE;
    status->MPI_TAG = MPI_ANY_TAG;
    status->MPI_ERROR = MPI_SUCCESS;
    status->_count = (long long)nbytes;
    status->_cancelled = 0;
  }
}

// ---- file views (io_ompio's etype/filetype template) ----
// Map payload byte `pos` within the tiled filetype to its absolute
// file offset runs; fn(file_off, payload_delta, len) per run.  The
// identity view short-circuits to one run.  (std::function rather
// than a template: this sits inside the extern "C" block.)
void view_runs(FileObj *f, int64_t payload_off, int64_t nbytes,
               const std::function<void(int64_t, int64_t, int64_t)> &fn) {
  if (f->identity_view) {
    fn(f->view_disp + payload_off, (int64_t)0, nbytes);
    return;
  }
  int64_t done = 0;
  while (done < nbytes) {
    int64_t pos = payload_off + done;
    int64_t tile = pos / f->vpayload;
    int64_t rem = pos % f->vpayload;
    int64_t acc = 0;
    for (auto &b : f->vblocks) {
      if (rem < acc + b.second) {
        int64_t inblk = rem - acc;
        int64_t len = b.second - inblk;
        if (len > nbytes - done) len = nbytes - done;
        fn(f->view_disp + tile * f->vtile + b.first + inblk, done, len);
        done += len;
        break;
      }
      acc += b.second;
    }
  }
}

// view-aware positioned IO on PAYLOAD bytes; reads stop at the first
// short read (EOF semantics), writes demand completeness
// returns bytes read (stopping at EOF), or -1 on a REAL IO error —
// EBADF/EIO must surface as errors, not as success-at-EOF
int64_t view_pread(FileObj *f, int64_t payload_off, char *buf,
                   int64_t nbytes) {
  int64_t total = 0;
  bool stop = false, err = false;
  view_runs(f, payload_off, nbytes,
            [&](int64_t off, int64_t delta, int64_t len) {
              if (stop) return;
              ssize_t got = pread(f->fd, buf + delta, (size_t)len,
                                  (off_t)off);
              if (got < 0) {
                err = true;
                stop = true;
                return;
              }
              total += got;
              if (got < len) stop = true;
            });
  return err ? -1 : total;
}

int view_pwrite(FileObj *f, int64_t payload_off, const char *buf,
                int64_t nbytes, int64_t *wrote) {
  int64_t total = 0;
  bool fail = false;
  view_runs(f, payload_off, nbytes,
            [&](int64_t off, int64_t delta, int64_t len) {
              if (fail) return;
              ssize_t put = pwrite(f->fd, buf + delta, (size_t)len,
                                   (off_t)off);
              if (put != (ssize_t)len) {
                fail = true;
                if (put > 0) total += put;
                return;
              }
              total += put;
            });
  *wrote = total;
  return fail ? MPI_ERR_OTHER : MPI_SUCCESS;
}

// ---- shared file pointer (sharedfp/lockedfile's shape) ----
// flock-serialized sidecar holding the pointer in ETYPES; every rank
// of every process sees one serialization point.
int sfp_update(FileObj *f, int64_t delta, bool set, int64_t setval,
               int64_t *old_out) {
  int sfd = ::open(f->sfp_path.c_str(), O_CREAT | O_RDWR, 0644);
  if (sfd < 0) return MPI_ERR_FILE;
  if (flock(sfd, LOCK_EX) != 0) {
    ::close(sfd);
    return MPI_ERR_OTHER;
  }
  int64_t cur = 0;
  ssize_t got = pread(sfd, &cur, sizeof cur, 0);
  if (got != (ssize_t)sizeof cur) cur = 0;
  if (old_out) *old_out = cur;
  int64_t next = set ? setval : cur + delta;
  int rc = MPI_SUCCESS;
  if (pwrite(sfd, &next, sizeof next, 0) != (ssize_t)sizeof next)
    rc = MPI_ERR_OTHER;
  flock(sfd, LOCK_UN);
  ::close(sfd);
  return rc;
}

}  // namespace

int MPI_File_open(MPI_Comm comm, const char *filename, int amode,
                  MPI_Info, MPI_File *fh) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int rw = amode & (MPI_MODE_RDONLY | MPI_MODE_WRONLY | MPI_MODE_RDWR);
  int flags;
  if (rw == MPI_MODE_RDONLY) flags = O_RDONLY;
  else if (rw == MPI_MODE_WRONLY) flags = O_WRONLY;
  else if (rw == MPI_MODE_RDWR) flags = O_RDWR;
  else return MPI_ERR_AMODE;
  // collective create: rank 0 creates (EXCL honored there), peers open
  // the existing file after the barrier — no O_CREAT races
  int fd = -1;
  if (c->local_rank == 0) {
    int f0 = flags;
    if (amode & MPI_MODE_CREATE) f0 |= O_CREAT;
    if (amode & MPI_MODE_EXCL) f0 |= O_EXCL;
    fd = ::open(filename, f0, 0644);
  }
  int rc = c_barrier(*c);
  if (rc) return rc;
  if (c->local_rank != 0) fd = ::open(filename, flags);
  // collective agreement: if ANY rank failed (rank 0's EEXIST under
  // EXCL, a peer's EMFILE...), every rank fails — divergent outcomes
  // would deadlock the next collective file op
  int32_t ok = fd >= 0 ? 1 : 0, all_ok = 0;
  rc = c_allreduce(*c, &ok, &all_ok, 1, MPI_INT, MPI_MIN);
  if (rc) return rc;
  if (!all_ok) {
    if (fd >= 0) ::close(fd);
    return MPI_ERR_NO_SUCH_FILE;
  }
  FileObj f;
  f.fd = fd;
  f.amode = amode;
  f.comm = comm;
  f.path = filename;
  // shared file pointer sidecar: rank 0 resets it (the shared pointer
  // starts at zero on open, MPI-3.1 13.6.4), peers see it post-barrier
  f.sfp_path = std::string(filename) + ".zsfp";
  if (c->local_rank == 0) {
    int sfd = ::open(f.sfp_path.c_str(), O_CREAT | O_RDWR | O_TRUNC,
                     0644);
    if (sfd >= 0) {
      int64_t zero = 0;
      (void)!write(sfd, &zero, sizeof zero);
      ::close(sfd);
    }
  }
  rc = c_barrier(*c);
  if (rc) return rc;
  if (amode & MPI_MODE_APPEND) {
    struct stat st{};
    if (fstat(fd, &st) == 0) f.pointer = (int64_t)st.st_size;
  }
  int handle;
  {
    std::lock_guard<std::mutex> lk(g_files_mu);
    handle = g_next_file++;
    g_files[handle] = f;
  }
  *fh = handle;
  return MPI_SUCCESS;
}

int MPI_File_close(MPI_File *fh) {
  FileObj *f = fh ? lookup_file(*fh) : nullptr;
  if (!f) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(f->comm);
  if (c) c_barrier(*c);  // all IO quiescent before any unlink
  ::close(f->fd);
  if (c && c->local_rank == 0) {
    ::unlink(f->sfp_path.c_str());  // sidecar dies with the handle
    if (f->amode & MPI_MODE_DELETE_ON_CLOSE) ::unlink(f->path.c_str());
  }
  if (c) c_barrier(*c);
  release_errh_ref(g_file_errh, *fh);
  {
    std::lock_guard<std::mutex> lk(g_files_mu);
    g_files.erase(*fh);
  }
  *fh = MPI_FILE_NULL;
  return MPI_SUCCESS;
}

int MPI_File_delete(const char *filename, MPI_Info) {
  return ::unlink(filename) == 0 ? MPI_SUCCESS : MPI_ERR_NO_SUCH_FILE;
}

int MPI_File_read_at(MPI_File fh, MPI_Offset offset, void *buf, int count,
                     MPI_Datatype dt, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  size_t want = (size_t)count * v.elems_per_item() * v.di.item;
  // `offset` is in ETYPES of the current view (bytes for the default)
  int64_t payload = offset * f->etype_size;
  int64_t got;
  if (v.contiguous()) {
    got = view_pread(f, payload, (char *)buf, (int64_t)want);
    if (got < 0) return MPI_ERR_OTHER;
  } else {
    std::vector<char> tmp(want);
    got = view_pread(f, payload, tmp.data(), (int64_t)want);
    if (got < 0) return MPI_ERR_OTHER;
    // short read past EOF: deliver what exists (MPI count semantics)
    unpack_dtype(buf, count, v, tmp.data(), (size_t)got);
  }
  file_status(status, (size_t)got);
  return MPI_SUCCESS;
}

int MPI_File_write_at(MPI_File fh, MPI_Offset offset, const void *buf,
                      int count, MPI_Datatype dt, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t payload = offset * f->etype_size;
  int64_t put = 0;
  int rc;
  if (v.contiguous()) {
    size_t nbytes = (size_t)count * v.elems_per_item() * v.di.item;
    rc = view_pwrite(f, payload, (const char *)buf, (int64_t)nbytes,
                     &put);
  } else {
    std::vector<char> packed;
    pack_dtype(buf, count, v, packed);
    rc = view_pwrite(f, payload, packed.data(), (int64_t)packed.size(),
                     &put);
  }
  if (rc != MPI_SUCCESS) return rc;
  file_status(status, (size_t)put);
  return MPI_SUCCESS;
}

int MPI_File_read(MPI_File fh, void *buf, int count, MPI_Datatype dt,
                  MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  int64_t off = f->pointer;
  // always read through a real status: the pointer advances by bytes
  // ACTUALLY read (short reads at EOF must not strand the pointer past
  // the data), whether or not the caller passed MPI_STATUS_IGNORE
  MPI_Status st{};
  int rc = MPI_File_read_at(fh, off, buf, count, dt, &st);
  if (rc == MPI_SUCCESS) {
    // the pointer advances in ETYPES; the status carries bytes
    f->pointer = off + st._count / (f->etype_size ? f->etype_size : 1);
    if (status) *status = st;
  }
  return rc;
}

int MPI_File_write(MPI_File fh, const void *buf, int count,
                   MPI_Datatype dt, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  int64_t off = f->pointer;
  MPI_Status st{};
  int rc = MPI_File_write_at(fh, off, buf, count, dt, &st);
  if (rc == MPI_SUCCESS) {
    f->pointer = off + st._count / (f->etype_size ? f->etype_size : 1);
    if (status) *status = st;
  }
  return rc;
}

int MPI_File_seek(MPI_File fh, MPI_Offset offset, int whence) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (whence == MPI_SEEK_SET) {
    f->pointer = (int64_t)offset;
  } else if (whence == MPI_SEEK_CUR) {
    f->pointer += (int64_t)offset;
  } else if (whence == MPI_SEEK_END) {
    struct stat st{};
    if (fstat(f->fd, &st) != 0) return MPI_ERR_OTHER;
    // the pointer is in ETYPES of the current view
    f->pointer = (int64_t)st.st_size /
                     (f->etype_size ? f->etype_size : 1) +
                 (int64_t)offset;
  } else {
    return MPI_ERR_ARG;
  }
  return f->pointer < 0 ? MPI_ERR_ARG : MPI_SUCCESS;
}

int MPI_File_get_position(MPI_File fh, MPI_Offset *offset) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  *offset = (MPI_Offset)f->pointer;
  return MPI_SUCCESS;
}

int MPI_File_get_size(MPI_File fh, MPI_Offset *size) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  struct stat st{};
  if (fstat(f->fd, &st) != 0) return MPI_ERR_OTHER;
  *size = (MPI_Offset)st.st_size;
  return MPI_SUCCESS;
}

int MPI_File_set_size(MPI_File fh, MPI_Offset size) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(f->comm);
  if (c) {
    int rc = c_barrier(*c);  // collective
    if (rc) return rc;
  }
  int rc = MPI_SUCCESS;
  if (!c || c->local_rank == 0)
    if (ftruncate(f->fd, (off_t)size) != 0) rc = MPI_ERR_OTHER;
  if (c) c_barrier(*c);
  return rc;
}

int MPI_File_sync(MPI_File fh) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  fsync(f->fd);
  CommObj *c = lookup_comm(f->comm);
  return c ? c_barrier(*c) : MPI_SUCCESS;
}

// -------------------------------------------- MPI-IO tier 2 (round 5)
// Views (file_set_view.c), collective and split collective IO
// (file_read_all.c, file_read_all_begin.c), shared-pointer IO
// (file_read_shared.c, file_read_ordered.c), nonblocking IO
// (file_iread.c family), preallocate/atomicity.

int MPI_File_set_view(MPI_File fh, MPI_Offset disp, MPI_Datatype etype,
                      MPI_Datatype filetype, const char *datarep,
                      MPI_Info) {
  // file_set_view.c: collective; resets both pointers.  The filetype
  // tiles the file from `disp`; only "native" representation (the
  // cluster is homogeneous — external32 lives on the Python plane).
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (datarep && strcmp(datarep, "native") != 0) return MPI_ERR_ARG;
  DtView ev, fv;
  if (!resolve_dtype(etype, ev) || !resolve_dtype(filetype, fv))
    return MPI_ERR_TYPE;
  if (disp < 0) return MPI_ERR_ARG;
  int64_t esize = ev.elems_per_item() * (int64_t)ev.di.item;
  if (esize <= 0) return MPI_ERR_TYPE;
  // byte-flatten one filetype item
  std::vector<std::pair<int64_t, int64_t>> blocks;
  int64_t item = (int64_t)fv.di.item;
  if (!fv.derived) {
    blocks.push_back({0, item});
  } else {
    for (auto &b : fv.derived->blocks)
      blocks.push_back({b.first * item, b.second * item});
  }
  int64_t tile = (fv.derived ? fv.derived->extent : 1) * item;
  int64_t payload = 0;
  for (auto &b : blocks) payload += b.second;
  if (payload <= 0 || payload % esize)
    return MPI_ERR_ARG;  // filetype must hold whole etypes
  f->view_disp = (int64_t)disp;
  f->view_etype = etype;
  f->view_ftype = filetype;
  f->vblocks = std::move(blocks);
  f->vtile = tile;
  f->vpayload = payload;
  f->etype_size = esize;
  // identity = one gap-free block tiling the file: the single-run
  // fast path already adds view_disp, and the etype size only scales
  // offsets (callers convert before mapping), so neither disqualifies
  f->identity_view = f->vblocks.size() == 1 &&
                     f->vblocks[0].first == 0 &&
                     f->vpayload == f->vtile;
  f->pointer = 0;
  int rc = sfp_update(f, 0, true, 0, nullptr);  // shared ptr resets too
  if (rc != MPI_SUCCESS) return rc;
  CommObj *c = lookup_comm(f->comm);
  return c ? c_barrier(*c) : MPI_SUCCESS;
}

int MPI_File_get_view(MPI_File fh, MPI_Offset *disp, MPI_Datatype *etype,
                      MPI_Datatype *filetype, char *datarep) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  *disp = (MPI_Offset)f->view_disp;
  *etype = f->view_etype;
  *filetype = f->view_ftype;
  if (datarep) strcpy(datarep, "native");
  return MPI_SUCCESS;
}

int MPI_File_get_byte_offset(MPI_File fh, MPI_Offset offset,
                             MPI_Offset *byte_offset) {
  // file_get_byte_offset.c: absolute byte of a view offset (etypes)
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  int64_t payload = offset * f->etype_size;
  if (f->identity_view) {
    *byte_offset = (MPI_Offset)(f->view_disp + payload);
    return MPI_SUCCESS;
  }
  int64_t tile = payload / f->vpayload;
  int64_t rem = payload % f->vpayload;
  int64_t acc = 0, inoff = 0;
  for (auto &b : f->vblocks) {
    if (rem < acc + b.second) {
      inoff = b.first + (rem - acc);
      break;
    }
    acc += b.second;
  }
  *byte_offset = (MPI_Offset)(f->view_disp + tile * f->vtile + inoff);
  return MPI_SUCCESS;
}

int MPI_File_get_type_extent(MPI_File fh, MPI_Datatype dt,
                             MPI_Offset *extent) {
  // native representation: file extent == memory extent
  if (!lookup_file(fh)) return MPI_ERR_FILE;
  long lb, ext;
  int rc = MPI_Type_get_extent(dt, &lb, &ext);
  if (rc != MPI_SUCCESS) return rc;
  *extent = (MPI_Offset)ext;
  return MPI_SUCCESS;
}

int MPI_File_preallocate(MPI_File fh, MPI_Offset size) {
  // collective; grows the file to at least `size` bytes
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (size < 0) return MPI_ERR_ARG;
  CommObj *c = lookup_comm(f->comm);
  int64_t rc = MPI_SUCCESS;
  if (!c || c->local_rank == 0) {
    struct stat st{};
    if (fstat(f->fd, &st) != 0) rc = MPI_ERR_OTHER;
    else if (st.st_size < (off_t)size &&
             ftruncate(f->fd, (off_t)size) != 0)
      rc = MPI_ERR_OTHER;
  }
  if (!c) return (int)rc;
  // rank 0's outcome is everyone's outcome (collective uniformity)
  int brc = c_bcast(*c, &rc, 1, MPI_LONG, 0, 0x7E32);
  return brc != MPI_SUCCESS ? brc : (int)rc;
}

int MPI_File_set_atomicity(MPI_File fh, int flag) {
  // every write here is one positioned syscall (kernel-atomic), so
  // atomic mode is a recorded promise the engine already keeps
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  f->atomic_mode = flag != 0;
  CommObj *c = lookup_comm(f->comm);
  return c ? c_barrier(*c) : MPI_SUCCESS;
}

int MPI_File_get_atomicity(MPI_File fh, int *flag) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  *flag = f->atomic_mode ? 1 : 0;
  return MPI_SUCCESS;
}

// ---- collective IO: the engine's independent IO is already safe for
// concurrent disjoint accesses; the collective forms add the
// synchronization the interface promises (fcoll/individual's shape) ----

int MPI_File_read_at_all(MPI_File fh, MPI_Offset offset, void *buf,
                         int count, MPI_Datatype dt,
                         MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(f->comm);
  if (c) c_barrier(*c);  // writers before this collective are visible
  return MPI_File_read_at(fh, offset, buf, count, dt, status);
}

int MPI_File_write_at_all(MPI_File fh, MPI_Offset offset,
                          const void *buf, int count, MPI_Datatype dt,
                          MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  int rc = MPI_File_write_at(fh, offset, buf, count, dt, status);
  CommObj *c = lookup_comm(f->comm);
  if (c) c_barrier(*c);  // all blocks on disk before anyone returns
  return rc;
}

int MPI_File_read_all(MPI_File fh, void *buf, int count, MPI_Datatype dt,
                      MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(f->comm);
  if (c) c_barrier(*c);
  return MPI_File_read(fh, buf, count, dt, status);
}

int MPI_File_write_all(MPI_File fh, const void *buf, int count,
                       MPI_Datatype dt, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  int rc = MPI_File_write(fh, buf, count, dt, status);
  CommObj *c = lookup_comm(f->comm);
  if (c) c_barrier(*c);
  return rc;
}

// ---- split collectives: begin performs the operation, end hands the
// stashed status back (file_read_all_begin.c semantics allow the
// implementation to complete eagerly; one outstanding pair per file) ----

namespace {

int split_begin(FileObj *f, int rc, const MPI_Status &st) {
  if (f->split_active) return MPI_ERR_OTHER;  // one pair at a time
  f->split_active = true;
  f->split_status = st;
  return rc;
}

int split_end(MPI_File fh, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (!f->split_active) return MPI_ERR_OTHER;
  f->split_active = false;
  if (status) *status = f->split_status;
  return MPI_SUCCESS;
}

}  // namespace

int MPI_File_read_all_begin(MPI_File fh, void *buf, int count,
                            MPI_Datatype dt) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (f->split_active) return MPI_ERR_OTHER;  // before any side effect
  MPI_Status st{};
  int rc = MPI_File_read_all(fh, buf, count, dt, &st);
  return split_begin(f, rc, st);
}

int MPI_File_read_all_end(MPI_File fh, void *, MPI_Status *status) {
  return split_end(fh, status);
}

int MPI_File_write_all_begin(MPI_File fh, const void *buf, int count,
                             MPI_Datatype dt) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (f->split_active) return MPI_ERR_OTHER;
  MPI_Status st{};
  int rc = MPI_File_write_all(fh, buf, count, dt, &st);
  return split_begin(f, rc, st);
}

int MPI_File_write_all_end(MPI_File fh, const void *, MPI_Status *status) {
  return split_end(fh, status);
}

int MPI_File_read_at_all_begin(MPI_File fh, MPI_Offset offset, void *buf,
                               int count, MPI_Datatype dt) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (f->split_active) return MPI_ERR_OTHER;
  MPI_Status st{};
  int rc = MPI_File_read_at_all(fh, offset, buf, count, dt, &st);
  return split_begin(f, rc, st);
}

int MPI_File_read_at_all_end(MPI_File fh, void *, MPI_Status *status) {
  return split_end(fh, status);
}

int MPI_File_write_at_all_begin(MPI_File fh, MPI_Offset offset,
                                const void *buf, int count,
                                MPI_Datatype dt) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (f->split_active) return MPI_ERR_OTHER;
  MPI_Status st{};
  int rc = MPI_File_write_at_all(fh, offset, buf, count, dt, &st);
  return split_begin(f, rc, st);
}

int MPI_File_write_at_all_end(MPI_File fh, const void *,
                              MPI_Status *status) {
  return split_end(fh, status);
}

// ---- shared file pointer IO ----

int MPI_File_read_shared(MPI_File fh, void *buf, int count,
                         MPI_Datatype dt, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t want = (int64_t)count * v.elems_per_item() * v.di.item;
  int64_t etypes = want / (f->etype_size ? f->etype_size : 1);
  int64_t old = 0;
  int rc = sfp_update(f, etypes, false, 0, &old);
  if (rc != MPI_SUCCESS) return rc;
  return MPI_File_read_at(fh, (MPI_Offset)old, buf, count, dt, status);
}

int MPI_File_write_shared(MPI_File fh, const void *buf, int count,
                          MPI_Datatype dt, MPI_Status *status) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t want = (int64_t)count * v.elems_per_item() * v.di.item;
  int64_t etypes = want / (f->etype_size ? f->etype_size : 1);
  int64_t old = 0;
  int rc = sfp_update(f, etypes, false, 0, &old);
  if (rc != MPI_SUCCESS) return rc;
  return MPI_File_write_at(fh, (MPI_Offset)old, buf, count, dt, status);
}

int MPI_File_seek_shared(MPI_File fh, MPI_Offset offset, int whence) {
  // collective (file_seek_shared.c); rank 0 applies, all synchronize
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(f->comm);
  int64_t rc = MPI_SUCCESS;
  if (!c || c->local_rank == 0) {
    int64_t base = 0;
    if (whence == MPI_SEEK_CUR) {
      rc = sfp_update(f, 0, false, 0, &base);
    } else if (whence == MPI_SEEK_END) {
      struct stat st{};
      if (fstat(f->fd, &st) != 0) rc = MPI_ERR_OTHER;
      else
        base = (int64_t)st.st_size /
               (f->etype_size ? f->etype_size : 1);
    } else if (whence != MPI_SEEK_SET) {
      rc = MPI_ERR_ARG;
    }
    if (rc == MPI_SUCCESS)
      rc = sfp_update(f, 0, true, base + (int64_t)offset, nullptr);
  }
  if (!c) return (int)rc;
  // rank 0's outcome rides to everyone (an early divergence would
  // leave peers believing the shared pointer moved)
  int brc = c_bcast(*c, &rc, 1, MPI_LONG, 0, 0x7E33);
  return brc != MPI_SUCCESS ? brc : (int)rc;
}

int MPI_File_get_position_shared(MPI_File fh, MPI_Offset *offset) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  int64_t cur = 0;
  int rc = sfp_update(f, 0, false, 0, &cur);
  if (rc != MPI_SUCCESS) return rc;
  *offset = (MPI_Offset)cur;
  return MPI_SUCCESS;
}

// ---- ordered (rank-sequential) shared IO: exscan computes each
// rank's slice of the shared region, the last total advances the
// pointer once (file_read_ordered.c semantics without serialization) ----

namespace {

int ordered_io(MPI_File fh, void *buf, int count, MPI_Datatype dt,
               MPI_Status *status, bool writing) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(f->comm);
  if (!c) return MPI_ERR_COMM;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t mine = ((int64_t)count * v.elems_per_item() * v.di.item) /
                 (f->etype_size ? f->etype_size : 1);
  int64_t prefix = 0, total = 0;
  int rc = c_scan(*c, &mine, &prefix, 1, MPI_LONG, MPI_SUM, true);
  if (rc != MPI_SUCCESS) return rc;
  rc = c_allreduce(*c, &mine, &total, 1, MPI_LONG, MPI_SUM);
  if (rc != MPI_SUCCESS) return rc;
  // rank 0 advances the shared pointer; its outcome rides the bcast so
  // a sidecar failure is UNIFORM (an early return would strand the
  // other ranks inside the bcast)
  int64_t msg[2] = {0, MPI_SUCCESS};
  if (c->local_rank == 0)
    msg[1] = sfp_update(f, total, false, 0, &msg[0]);
  rc = c_bcast(*c, msg, 2, MPI_LONG, 0, 0x7E31);
  if (rc != MPI_SUCCESS) return rc;
  if (msg[1] != MPI_SUCCESS) return (int)msg[1];
  MPI_Offset at = (MPI_Offset)(msg[0] + prefix);
  rc = writing ? MPI_File_write_at(fh, at, buf, count, dt, status)
               : MPI_File_read_at(fh, at, buf, count, dt, status);
  int rc2 = c_barrier(*c);  // ordered IO is collective
  return rc != MPI_SUCCESS ? rc : rc2;
}

}  // namespace

int MPI_File_read_ordered(MPI_File fh, void *buf, int count,
                          MPI_Datatype dt, MPI_Status *status) {
  return ordered_io(fh, buf, count, dt, status, false);
}

int MPI_File_write_ordered(MPI_File fh, const void *buf, int count,
                           MPI_Datatype dt, MPI_Status *status) {
  return ordered_io(fh, (void *)buf, count, dt, status, true);
}

int MPI_File_read_ordered_begin(MPI_File fh, void *buf, int count,
                                MPI_Datatype dt) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (f->split_active) return MPI_ERR_OTHER;
  MPI_Status st{};
  int rc = ordered_io(fh, buf, count, dt, &st, false);
  return split_begin(f, rc, st);
}

int MPI_File_read_ordered_end(MPI_File fh, void *, MPI_Status *status) {
  return split_end(fh, status);
}

int MPI_File_write_ordered_begin(MPI_File fh, const void *buf, int count,
                                 MPI_Datatype dt) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  if (f->split_active) return MPI_ERR_OTHER;
  MPI_Status st{};
  int rc = ordered_io(fh, (void *)buf, count, dt, &st, true);
  return split_begin(f, rc, st);
}

int MPI_File_write_ordered_end(MPI_File fh, const void *,
                               MPI_Status *status) {
  return split_end(fh, status);
}

// ---- nonblocking IO (file_iread.c family): the blocking form runs on
// a background thread and retires through the request engine, exactly
// the fbtl_posix ipreadv shape ----

namespace {

int file_ispawn(std::function<int(MPI_Status *)> body,
                MPI_Request *request) {
  Req *r = new Req;
  r->heap = true;
  r->comm = MPI_COMM_WORLD;
  int handle;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    handle = g.next_req++;
    g.reqs[handle] = r;
  }
  std::thread t([r, body]() {
    MPI_Status st{};
    int rc = body(&st);
    std::lock_guard<std::mutex> lk(g.match_mu);
    r->status = st;
    r->status.MPI_ERROR = rc;
    r->complete = true;
    g.match_cv.notify_all();
  });
  {
    std::lock_guard<std::mutex> lk(g.threads_mu);
    g.threads.push_back(std::move(t));
  }
  *request = handle;
  return MPI_SUCCESS;
}

}  // namespace

int MPI_File_iread_at(MPI_File fh, MPI_Offset offset, void *buf,
                      int count, MPI_Datatype dt, MPI_Request *request) {
  if (!lookup_file(fh)) return MPI_ERR_FILE;
  return file_ispawn(
      [fh, offset, buf, count, dt](MPI_Status *st) {
        return MPI_File_read_at(fh, offset, buf, count, dt, st);
      },
      request);
}

int MPI_File_iwrite_at(MPI_File fh, MPI_Offset offset, const void *buf,
                       int count, MPI_Datatype dt,
                       MPI_Request *request) {
  if (!lookup_file(fh)) return MPI_ERR_FILE;
  return file_ispawn(
      [fh, offset, buf, count, dt](MPI_Status *st) {
        return MPI_File_write_at(fh, offset, buf, count, dt, st);
      },
      request);
}

int MPI_File_iread(MPI_File fh, void *buf, int count, MPI_Datatype dt,
                   MPI_Request *request) {
  // the pointer advances NOW (the op owns its slice; a later iread
  // must not overlap it) — the data lands when the request completes
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t off = f->pointer;
  f->pointer += ((int64_t)count * v.elems_per_item() * v.di.item) /
                (f->etype_size ? f->etype_size : 1);
  return MPI_File_iread_at(fh, (MPI_Offset)off, buf, count, dt,
                           request);
}

int MPI_File_iwrite(MPI_File fh, const void *buf, int count,
                    MPI_Datatype dt, MPI_Request *request) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t off = f->pointer;
  f->pointer += ((int64_t)count * v.elems_per_item() * v.di.item) /
                (f->etype_size ? f->etype_size : 1);
  return MPI_File_iwrite_at(fh, (MPI_Offset)off, buf, count, dt,
                            request);
}

int MPI_File_iread_shared(MPI_File fh, void *buf, int count,
                          MPI_Datatype dt, MPI_Request *request) {
  // claim the shared slice NOW, read it in the background
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t etypes = ((int64_t)count * v.elems_per_item() * v.di.item) /
                   (f->etype_size ? f->etype_size : 1);
  int64_t old = 0;
  int rc = sfp_update(f, etypes, false, 0, &old);
  if (rc != MPI_SUCCESS) return rc;
  return MPI_File_iread_at(fh, (MPI_Offset)old, buf, count, dt,
                           request);
}

int MPI_File_iwrite_shared(MPI_File fh, const void *buf, int count,
                           MPI_Datatype dt, MPI_Request *request) {
  FileObj *f = lookup_file(fh);
  if (!f) return MPI_ERR_FILE;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  int64_t etypes = ((int64_t)count * v.elems_per_item() * v.di.item) /
                   (f->etype_size ? f->etype_size : 1);
  int64_t old = 0;
  int rc = sfp_update(f, etypes, false, 0, &old);
  if (rc != MPI_SUCCESS) return rc;
  return MPI_File_iwrite_at(fh, (MPI_Offset)old, buf, count, dt,
                            request);
}

int MPI_File_iread_at_all(MPI_File fh, MPI_Offset offset, void *buf,
                          int count, MPI_Datatype dt,
                          MPI_Request *request) {
  // "all" adds collectivity to completion, not initiation; the
  // independent nonblocking form satisfies both here
  return MPI_File_iread_at(fh, offset, buf, count, dt, request);
}

int MPI_File_iwrite_at_all(MPI_File fh, MPI_Offset offset,
                           const void *buf, int count, MPI_Datatype dt,
                           MPI_Request *request) {
  return MPI_File_iwrite_at(fh, offset, buf, count, dt, request);
}

int MPI_File_iread_all(MPI_File fh, void *buf, int count,
                       MPI_Datatype dt, MPI_Request *request) {
  return MPI_File_iread(fh, buf, count, dt, request);
}

int MPI_File_iwrite_all(MPI_File fh, const void *buf, int count,
                        MPI_Datatype dt, MPI_Request *request) {
  return MPI_File_iwrite(fh, buf, count, dt, request);
}

int MPI_Register_datarep(const char *datarep, void *, void *, void *,
                         void *) {
  // register_datarep.c surface: only "native" exists on this
  // homogeneous engine; registering it is idempotent, anything else
  // is rejected loudly rather than silently unconverted
  if (datarep && strcmp(datarep, "native") == 0) return MPI_SUCCESS;
  return MPI_ERR_ARG;
}

// ------------------------------------------------------- pack / unpack
// The convertor surface (ompi/mpi/c/pack.c:45): positions advance in
// bytes through a caller-owned packing buffer.

int MPI_Pack_size(int incount, MPI_Datatype dt, MPI_Comm, int *size) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  *size = (int)((int64_t)incount * v.elems_per_item() * v.di.item);
  return MPI_SUCCESS;
}

int MPI_Pack(const void *inbuf, int incount, MPI_Datatype dt,
             void *outbuf, int outsize, int *position, MPI_Comm) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  if (!position || *position < 0) return MPI_ERR_ARG;
  size_t nbytes = (size_t)incount * v.elems_per_item() * v.di.item;
  if ((size_t)*position + nbytes > (size_t)outsize) return MPI_ERR_TRUNCATE;
  char *dst = (char *)outbuf + *position;
  if (v.contiguous()) {
    memcpy(dst, inbuf, nbytes);
  } else {
    std::vector<char> packed;
    pack_dtype(inbuf, incount, v, packed);
    memcpy(dst, packed.data(), packed.size());
  }
  *position += (int)nbytes;
  return MPI_SUCCESS;
}

int MPI_Unpack(const void *inbuf, int insize, int *position, void *outbuf,
               int outcount, MPI_Datatype dt, MPI_Comm) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  if (!position || *position < 0) return MPI_ERR_ARG;
  size_t nbytes = (size_t)outcount * v.elems_per_item() * v.di.item;
  if ((size_t)*position + nbytes > (size_t)insize) return MPI_ERR_TRUNCATE;
  const char *src = (const char *)inbuf + *position;
  if (v.contiguous()) {
    memcpy(outbuf, src, nbytes);
  } else {
    unpack_dtype(outbuf, outcount, v, src, nbytes);
  }
  *position += (int)nbytes;
  return MPI_SUCCESS;
}

// --------------------------------------------- nonblocking collectives
// ibcast.c:36 family: the tag sequence is RESERVED at call time (fixing
// the op's place in the comm's collective order, MPI's same-order law),
// then the blocking algorithm runs against a comm snapshot on a
// background thread and retires through the request engine.

namespace {

int icoll_spawn(std::function<int()> body, MPI_Comm comm,
                MPI_Request *request) {
  Req *r = new Req;
  r->heap = true;
  r->comm = comm;
  int handle;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    handle = g.next_req++;
    g.reqs[handle] = r;
  }
  g.inflight_isends.fetch_add(1);
  std::thread([body, r]() {
    int rc = body();
    {
      std::lock_guard<std::mutex> lk(g.match_mu);
      r->status.MPI_ERROR = rc;
      r->complete = true;
    }
    g.match_cv.notify_all();
    g.inflight_isends.fetch_sub(1);
  }).detach();
  *request = handle;
  return MPI_SUCCESS;
}

}  // namespace

namespace {

// snapshot the comm with this op's tag slot(s) RESERVED in program
// order; `slots` = number of coll_seq increments the algorithm performs
std::shared_ptr<CommObj> icoll_reserve(CommObj *c, int slots = 1) {
  auto snap = std::make_shared<CommObj>(*c);
  c->coll_seq += slots;
  return snap;
}

}  // namespace

int MPI_Ibcast(void *buf, int count, MPI_Datatype dt, int root,
               MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, buf, count, dt, root]() {
        return c_bcast(*snap, buf, count, dt, root, 0x7E01);
      },
      comm, request);
}

// MPI-3.1 5.12: IN_PLACE extends to every nonblocking collective.
// The receive-side contribution is cloned NOW (the caller may touch
// nothing until completion, but the engine must not read the sentinel
// address) and the clone is owned by each closure — captured
// EXPLICITLY, since [=] would not keep a shared_ptr the body never
// names alive.
// NOTE: the slice/span arithmetic MIRRORS the blocking wrappers
// (MPI_Allreduce ... MPI_Alltoallv above) — fix BOTH copies or
// extract a helper when touching either.
static int icoll_inplace(const void *&sendbuf, const void *src,
                         int count, MPI_Datatype dt,
                         std::shared_ptr<std::vector<char>> &keep) {
  if (sendbuf != MPI_IN_PLACE) return MPI_SUCCESS;
  keep = std::make_shared<std::vector<char>>();
  int rc = clone_region(src, count, dt, *keep);
  if (rc != MPI_SUCCESS) return rc;
  sendbuf = keep->data();
  return MPI_SUCCESS;
}

int MPI_Iallreduce(const void *sendbuf, void *recvbuf, int count,
                   MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                   MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::shared_ptr<std::vector<char>> keep;
  int rc = icoll_inplace(sendbuf, recvbuf, count, dt, keep);
  if (rc != MPI_SUCCESS) return rc;
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sendbuf, recvbuf, count, dt, op]() {
        return c_allreduce(*snap, sendbuf, recvbuf, count, dt, op);
      },
      comm, request);
}

int MPI_Ireduce(const void *sendbuf, void *recvbuf, int count,
                MPI_Datatype dt, MPI_Op op, int root, MPI_Comm comm,
                MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    if (c->local_rank != root) return MPI_ERR_ARG;  // root only
    int rc = icoll_inplace(sendbuf, recvbuf, count, dt, keep);
    if (rc != MPI_SUCCESS) return rc;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sendbuf, recvbuf, count, dt, op, root]() {
        return c_reduce(*snap, sendbuf, recvbuf, count, dt, op, root);
      },
      comm, request);
}

int MPI_Igather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, int recvcount, MPI_Datatype recvtype,
                int root, MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    if (c->local_rank != root) return MPI_ERR_ARG;
    DtView rv;
    if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
    const char *slice =
        (const char *)recvbuf + (size_t)root * slot_bytes(rv, recvcount);
    int rc = icoll_inplace(sendbuf, slice, recvcount, recvtype, keep);
    if (rc != MPI_SUCCESS) return rc;
    sendcount = recvcount;
    sendtype = recvtype;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sendbuf, sendcount, sendtype, recvbuf, recvcount,
       recvtype, root]() {
        return c_gather(*snap, sendbuf, sendcount, sendtype, recvbuf,
                        recvcount, recvtype, root);
      },
      comm, request);
}

int MPI_Iscatter(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 int root, MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  std::shared_ptr<std::vector<char>> scratch;
  if (recvbuf == MPI_IN_PLACE) {
    if (c->local_rank != root) return MPI_ERR_ARG;
    DtView sv;
    if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
    scratch = std::make_shared<std::vector<char>>(
        slot_bytes(sv, sendcount));
    recvbuf = scratch->data();
    recvcount = sendcount;
    recvtype = sendtype;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, scratch, sendbuf, sendcount, sendtype, recvbuf, recvcount,
       recvtype, root]() {
        return c_scatter(*snap, sendbuf, sendcount, sendtype, recvbuf,
                         recvcount, recvtype, root);
      },
      comm, request);
}

int MPI_Iallgather(const void *sendbuf, int sendcount,
                   MPI_Datatype sendtype, void *recvbuf, int recvcount,
                   MPI_Datatype recvtype, MPI_Comm comm,
                   MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    DtView rv;
    if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
    const char *slice = (const char *)recvbuf +
                        (size_t)c->local_rank *
                            slot_bytes(rv, recvcount);
    int rc = icoll_inplace(sendbuf, slice, recvcount, recvtype, keep);
    if (rc != MPI_SUCCESS) return rc;
    sendcount = recvcount;
    sendtype = recvtype;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sendbuf, sendcount, sendtype, recvbuf, recvcount,
       recvtype]() {
        return c_allgather(*snap, sendbuf, sendcount, sendtype, recvbuf,
                           recvcount, recvtype);
      },
      comm, request);
}

int MPI_Ialltoall(const void *sendbuf, int sendcount,
                  MPI_Datatype sendtype, void *recvbuf, int recvcount,
                  MPI_Datatype recvtype, MPI_Comm comm,
                  MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    int rc = icoll_inplace(sendbuf, recvbuf,
                           (int)c->group.size() * recvcount, recvtype,
                           keep);
    if (rc != MPI_SUCCESS) return rc;
    sendcount = recvcount;
    sendtype = recvtype;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sendbuf, sendcount, sendtype, recvbuf, recvcount,
       recvtype]() {
        return c_alltoall(*snap, sendbuf, sendcount, sendtype, recvbuf,
                          recvcount, recvtype);
      },
      comm, request);
}

static int iscan_impl(const void *sendbuf, void *recvbuf, int count,
                      MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                      MPI_Request *request, bool exclusive) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::shared_ptr<std::vector<char>> keep;
  int rc = icoll_inplace(sendbuf, recvbuf, count, dt, keep);
  if (rc != MPI_SUCCESS) return rc;
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sendbuf, recvbuf, count, dt, op, exclusive]() {
        return c_scan(*snap, sendbuf, recvbuf, count, dt, op,
                      exclusive);
      },
      comm, request);
}

int MPI_Iscan(const void *sendbuf, void *recvbuf, int count,
              MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
              MPI_Request *request) {
  return iscan_impl(sendbuf, recvbuf, count, dt, op, comm, request,
                    false);
}

int MPI_Iexscan(const void *sendbuf, void *recvbuf, int count,
                MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                MPI_Request *request) {
  return iscan_impl(sendbuf, recvbuf, count, dt, op, comm, request,
                    true);
}

int MPI_Ireduce_scatter_block(const void *sendbuf, void *recvbuf,
                              int recvcount, MPI_Datatype dt, MPI_Op op,
                              MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    int rc = icoll_inplace(sendbuf, recvbuf,
                           (int)c->group.size() * recvcount, dt, keep);
    if (rc != MPI_SUCCESS) return rc;
  }
  auto snap = icoll_reserve(c, 2);  // reduce + scatter under the hood
  return icoll_spawn(
      [snap, keep, sendbuf, recvbuf, recvcount, dt, op]() {
        return c_reduce_scatter_block(*snap, sendbuf, recvbuf, recvcount,
                                      dt, op);
      },
      comm, request);
}

namespace {

// snapshot an int array the caller may reuse at return (MPI rule);
// roots_only captures nothing on non-roots (they may legally pass
// NULL).  data_or_null() is the unwrap the c_* helpers expect.
struct IcollArray {
  std::shared_ptr<std::vector<int>> v;
  IcollArray(const int *p, int n, bool capture)
      : v(std::make_shared<std::vector<int>>(
            capture ? std::vector<int>(p, p + n) : std::vector<int>())) {}
  const int *data_or_null() const {
    return v->empty() ? nullptr : v->data();
  }
};

}  // namespace

int MPI_Igatherv(const void *sendbuf, int sendcount,
                 MPI_Datatype sendtype, void *recvbuf,
                 const int recvcounts[], const int displs[],
                 MPI_Datatype recvtype, int root, MPI_Comm comm,
                 MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  int n = (int)c->group.size();
  bool im_root = c->local_rank == root;
  IcollArray rc_(recvcounts, n, im_root), dp(displs, n, im_root);
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    if (!im_root) return MPI_ERR_ARG;
    DtView rv;
    if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
    const char *slice = (const char *)recvbuf +
                        (size_t)displs[root] * slot_bytes(rv, 1);
    int rc = icoll_inplace(sendbuf, slice, recvcounts[root], recvtype,
                           keep);
    if (rc != MPI_SUCCESS) return rc;
    sendcount = recvcounts[root];
    sendtype = recvtype;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, rc_, dp, sendbuf, sendcount, sendtype, recvbuf,
       recvtype, root]() {
        return c_gatherv(*snap, sendbuf, sendcount, sendtype, recvbuf,
                         rc_.data_or_null(), dp.data_or_null(), recvtype,
                         root);
      },
      comm, request);
}

int MPI_Iscatterv(const void *sendbuf, const int sendcounts[],
                  const int displs[], MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  int root, MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  int n = (int)c->group.size();
  bool im_root = c->local_rank == root;
  IcollArray sc(sendcounts, n, im_root), dp(displs, n, im_root);
  std::shared_ptr<std::vector<char>> scratch;
  if (recvbuf == MPI_IN_PLACE) {
    if (!im_root) return MPI_ERR_ARG;
    DtView sv;
    if (!resolve_dtype(sendtype, sv)) return MPI_ERR_TYPE;
    scratch = std::make_shared<std::vector<char>>(
        slot_bytes(sv, sendcounts[root]));
    recvbuf = scratch->data();
    recvcount = sendcounts[root];
    recvtype = sendtype;
  }
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, scratch, sc, dp, sendbuf, sendtype, recvbuf, recvcount,
       recvtype, root]() {
        return c_scatterv(*snap, sendbuf, sc.data_or_null(),
                          dp.data_or_null(), sendtype, recvbuf,
                          recvcount, recvtype, root);
      },
      comm, request);
}

int MPI_Iallgatherv(const void *sendbuf, int sendcount,
                    MPI_Datatype sendtype, void *recvbuf,
                    const int recvcounts[], const int displs[],
                    MPI_Datatype recvtype, MPI_Comm comm,
                    MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int n = (int)c->group.size();
  IcollArray rc_(recvcounts, n, true), dp(displs, n, true);
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    int me = c->local_rank;
    DtView rv;
    if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
    const char *slice = (const char *)recvbuf +
                        (size_t)displs[me] * slot_bytes(rv, 1);
    int rc = icoll_inplace(sendbuf, slice, recvcounts[me], recvtype,
                           keep);
    if (rc != MPI_SUCCESS) return rc;
    sendcount = recvcounts[me];
    sendtype = recvtype;
  }
  auto snap = icoll_reserve(c, n);  // n rooted broadcasts inside
  return icoll_spawn(
      [snap, keep, rc_, dp, sendbuf, sendcount, sendtype, recvbuf,
       recvtype]() {
        return c_allgatherv(*snap, sendbuf, sendcount, sendtype, recvbuf,
                            rc_.data_or_null(), dp.data_or_null(),
                            recvtype);
      },
      comm, request);
}

int MPI_Ireduce_scatter(const void *sendbuf, void *recvbuf,
                        const int recvcounts[], MPI_Datatype dt,
                        MPI_Op op, MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int n = (int)c->group.size();
  auto counts = std::make_shared<std::vector<int>>(recvcounts,
                                                   recvcounts + n);
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    int total = 0;
    for (int r = 0; r < n; r++) total += recvcounts[r];
    int rc = icoll_inplace(sendbuf, recvbuf, total, dt, keep);
    if (rc != MPI_SUCCESS) return rc;
  }
  auto snap = icoll_reserve(c, 2);  // reduce + scatterv under the hood
  return icoll_spawn(
      [snap, keep, counts, sendbuf, recvbuf, dt, op]() {
        return c_reduce_scatter(*snap, sendbuf, recvbuf, counts->data(),
                                dt, op);
      },
      comm, request);
}

int MPI_Ialltoallv(const void *sendbuf, const int sendcounts[],
                   const int sdispls[], MPI_Datatype sendtype,
                   void *recvbuf, const int recvcounts[],
                   const int rdispls[], MPI_Datatype recvtype,
                   MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int n = (int)c->group.size();
  std::shared_ptr<std::vector<char>> keep;
  if (sendbuf == MPI_IN_PLACE) {
    // the receive side defines everything (alltoallv.c IN_PLACE)
    DtView rv;
    if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
    int span = 0;
    for (int r = 0; r < n; r++)
      if (rdispls[r] + recvcounts[r] > span)
        span = rdispls[r] + recvcounts[r];
    int rc = icoll_inplace(sendbuf, recvbuf, span, recvtype, keep);
    if (rc != MPI_SUCCESS) return rc;
    sendcounts = recvcounts;
    sdispls = rdispls;
    sendtype = recvtype;
  }
  // MPI lets the caller reuse the count/displacement arrays the moment
  // the call returns — snapshot them for the background thread
  auto sc = std::make_shared<std::vector<int>>(sendcounts, sendcounts + n);
  auto sd = std::make_shared<std::vector<int>>(sdispls, sdispls + n);
  auto rc_ = std::make_shared<std::vector<int>>(recvcounts,
                                                recvcounts + n);
  auto rd = std::make_shared<std::vector<int>>(rdispls, rdispls + n);
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [snap, keep, sc, sd, rc_, rd, sendbuf, sendtype, recvbuf,
       recvtype]() {
        return c_alltoallv(*snap, sendbuf, sc->data(), sd->data(),
                           sendtype, recvbuf, rc_->data(), rd->data(),
                           recvtype);
      },
      comm, request);
}

int MPI_Ialltoallw(const void *sendbuf, const int sendcounts[],
                   const int sdispls[], const MPI_Datatype sendtypes[],
                   void *recvbuf, const int recvcounts[],
                   const int rdispls[], const MPI_Datatype recvtypes[],
                   MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int n = (int)c->group.size();
  // MPI-3.1 5.12 extends IN_PLACE to the nonblocking collectives: the
  // send arrays are then absent (often NULL) — same clone as the
  // blocking wrapper, owned by the lambda so it outlives the run
  auto tmp = std::make_shared<std::vector<char>>();
  if (sendbuf == MPI_IN_PLACE) {
    int rc = alltoallw_inplace_clone(n, recvbuf, recvcounts, rdispls,
                                     recvtypes, *tmp);
    if (rc != MPI_SUCCESS) return rc;
    sendbuf = tmp->data();
    sendcounts = recvcounts;
    sdispls = rdispls;
    sendtypes = recvtypes;
  }
  auto sc = std::make_shared<std::vector<int>>(sendcounts,
                                               sendcounts + n);
  auto sd = std::make_shared<std::vector<int>>(sdispls, sdispls + n);
  auto st2 = std::make_shared<std::vector<MPI_Datatype>>(sendtypes,
                                                         sendtypes + n);
  auto rc_ = std::make_shared<std::vector<int>>(recvcounts,
                                                recvcounts + n);
  auto rd = std::make_shared<std::vector<int>>(rdispls, rdispls + n);
  auto rt2 = std::make_shared<std::vector<MPI_Datatype>>(recvtypes,
                                                         recvtypes + n);
  auto snap = icoll_reserve(c);
  // tmp is captured EXPLICITLY: sendbuf aliases tmp->data() on the
  // IN_PLACE path, and [=] alone would not keep the clone alive (the
  // lambda body never names tmp)
  return icoll_spawn(
      [snap, tmp, sendbuf, recvbuf, sc, sd, st2, rc_, rd, rt2]() {
        return c_alltoallw(*snap, sendbuf, sc->data(), sd->data(),
                           st2->data(), recvbuf, rc_->data(),
                           rd->data(), rt2->data());
      },
      comm, request);
}

int MPI_Ibarrier(MPI_Comm comm, MPI_Request *request) {
  // as a 1-int allreduce: the plain dissemination barrier's fixed tag
  // cannot distinguish overlapping instances, the reserved-seq
  // allreduce can (libnbc implements ibarrier the same way)
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  auto snap = std::make_shared<CommObj>(*c);
  c->coll_seq++;
  auto buf = std::make_shared<std::array<int, 2>>();
  return icoll_spawn(
      [snap, buf]() {
        (*buf)[0] = 1;
        return c_allreduce(*snap, buf->data(), buf->data() + 1, 1,
                           MPI_INT, MPI_SUM);
      },
      comm, request);
}

// -------------------------------------------------- Cartesian topology
// cart_create.c:45 family — pure index arithmetic over a derived comm.

int MPI_Dims_create(int nnodes, int ndims, int dims[]) {
  // balanced factorization honoring pre-set (nonzero) entries
  int fixed = 1, free_slots = 0;
  for (int i = 0; i < ndims; i++) {
    if (dims[i] > 0) fixed *= dims[i];
    else free_slots++;
  }
  if (fixed <= 0 || nnodes % fixed) return MPI_ERR_ARG;
  int rem = nnodes / fixed;
  if (free_slots == 0) return rem == 1 ? MPI_SUCCESS : MPI_ERR_ARG;
  // greedy: largest factor first into the earliest free slot
  std::vector<int> fill(free_slots, 1);
  for (int slot = 0; slot < free_slots; slot++) {
    int want = (int)std::round(
        std::pow((double)rem, 1.0 / (free_slots - slot)));
    int best = 1;
    for (int f = 1; f <= want; f++)
      if (rem % f == 0) best = f;
    fill[slot] = slot == free_slots - 1 ? rem : best;
    rem /= fill[slot];
  }
  std::sort(fill.rbegin(), fill.rend());
  int j = 0;
  for (int i = 0; i < ndims; i++)
    if (dims[i] <= 0) dims[i] = fill[j++];
  return MPI_SUCCESS;
}

int MPI_Cart_map(MPI_Comm comm, int ndims, const int dims[],
                 const int periods[], int *newrank) {
  // cart_map.c: the no-reorder mapping this shim's Cart_create also
  // uses — ranks below the grid size keep their rank, the rest get
  // MPI_UNDEFINED
  (void)periods;
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (ndims <= 0) return MPI_ERR_ARG;
  int64_t nnodes = 1;
  for (int d = 0; d < ndims; d++) {
    if (dims[d] <= 0) return MPI_ERR_ARG;
    nnodes *= dims[d];
  }
  if (nnodes > (int64_t)c->group.size()) return MPI_ERR_ARG;
  *newrank = c->local_rank < nnodes ? c->local_rank : MPI_UNDEFINED;
  return MPI_SUCCESS;
}

int MPI_Graph_map(MPI_Comm comm, int nnodes, const int index[],
                  const int edges[], int *newrank) {
  (void)index;
  (void)edges;
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (nnodes <= 0 || nnodes > (int)c->group.size()) return MPI_ERR_ARG;
  *newrank = c->local_rank < nnodes ? c->local_rank : MPI_UNDEFINED;
  return MPI_SUCCESS;
}

int MPI_Cart_create(MPI_Comm comm, int ndims, const int dims[],
                    const int periods[], int /*reorder*/,
                    MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (ndims <= 0) return MPI_ERR_ARG;
  int64_t total = 1;
  for (int i = 0; i < ndims; i++) {
    if (dims[i] <= 0) return MPI_ERR_ARG;
    total *= dims[i];
  }
  if (total > (int64_t)c->group.size()) return MPI_ERR_ARG;
  // ranks beyond the grid get MPI_COMM_NULL (cart_create.c's contract);
  // reorder is accepted and ignored (ranks are already arbitrary here)
  int color = c->local_rank < total ? 0 : MPI_UNDEFINED;
  int rc = MPI_Comm_split(comm, color, c->local_rank, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  if (*newcomm == MPI_COMM_NULL) return MPI_SUCCESS;
  CommObj *nc = lookup_comm(*newcomm);
  nc->cart_dims.assign(dims, dims + ndims);
  nc->cart_periods.assign(ndims, 0);
  if (periods)
    for (int i = 0; i < ndims; i++) nc->cart_periods[i] = periods[i] != 0;
  return MPI_SUCCESS;
}

int MPI_Cartdim_get(MPI_Comm comm, int *ndims) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (c->cart_dims.empty()) return MPI_ERR_ARG;
  *ndims = (int)c->cart_dims.size();
  return MPI_SUCCESS;
}

int MPI_Cart_get(MPI_Comm comm, int maxdims, int dims[], int periods[],
                 int coords[]) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nd = (int)c->cart_dims.size();
  if (nd == 0 || maxdims < nd) return MPI_ERR_ARG;
  for (int i = 0; i < nd; i++) {
    dims[i] = c->cart_dims[i];
    periods[i] = c->cart_periods[i];
  }
  return MPI_Cart_coords(comm, c->local_rank, maxdims, coords);
}

int MPI_Cart_rank(MPI_Comm comm, const int coords[], int *rank) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nd = (int)c->cart_dims.size();
  if (nd == 0) return MPI_ERR_ARG;
  int64_t r = 0;
  for (int i = 0; i < nd; i++) {
    int64_t coord = coords[i];
    int dim = c->cart_dims[i];
    if (coord < 0 || coord >= dim) {
      if (!c->cart_periods[i]) return MPI_ERR_ARG;  // out of a wall
      coord = ((coord % dim) + dim) % dim;
    }
    r = r * dim + coord;
  }
  *rank = (int)r;
  return MPI_SUCCESS;
}

int MPI_Cart_coords(MPI_Comm comm, int rank, int maxdims, int coords[]) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nd = (int)c->cart_dims.size();
  if (nd == 0 || maxdims < nd) return MPI_ERR_ARG;
  if (rank < 0 || rank >= (int)c->group.size()) return MPI_ERR_ARG;
  for (int i = nd - 1; i >= 0; i--) {
    coords[i] = rank % c->cart_dims[i];
    rank /= c->cart_dims[i];
  }
  return MPI_SUCCESS;
}

int MPI_Cart_sub(MPI_Comm comm, const int remain_dims[],
                 MPI_Comm *newcomm) {
  // cart_sub.c: slice the grid — ranks sharing the coordinates of the
  // DROPPED dimensions form a sub-grid over the kept ones
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nd = (int)c->cart_dims.size();
  if (nd == 0) return MPI_ERR_ARG;
  std::vector<int> coords(nd);
  int rc = MPI_Cart_coords(comm, c->local_rank, nd, coords.data());
  if (rc != MPI_SUCCESS) return rc;
  // color = the dropped-dim coordinates; key = row-major rank within
  // the kept dims (so the sub-grid keeps cartesian order)
  int color = 0, key = 0;
  for (int d = 0; d < nd; d++) {
    if (remain_dims[d]) key = key * c->cart_dims[d] + coords[d];
    else color = color * c->cart_dims[d] + coords[d];
  }
  rc = MPI_Comm_split(comm, color, key, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  CommObj *nc = lookup_comm(*newcomm);
  nc->cart_dims.clear();
  nc->cart_periods.clear();
  for (int d = 0; d < nd; d++) {
    if (remain_dims[d]) {
      nc->cart_dims.push_back(c->cart_dims[d]);
      nc->cart_periods.push_back(c->cart_periods[d]);
    }
  }
  if (nc->cart_dims.empty()) {
    // all dims dropped: a 1-rank "grid" of dimension 1 (cart_sub.c
    // returns a zero-dim cart comm; a single cell keeps the API total)
    nc->cart_dims.push_back(1);
    nc->cart_periods.push_back(0);
  }
  return MPI_SUCCESS;
}

int MPI_Cart_shift(MPI_Comm comm, int direction, int disp,
                   int *rank_source, int *rank_dest) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nd = (int)c->cart_dims.size();
  if (nd == 0 || direction < 0 || direction >= nd) return MPI_ERR_ARG;
  std::vector<int> coords(nd);
  int rc = MPI_Cart_coords(comm, c->local_rank, nd, coords.data());
  if (rc != MPI_SUCCESS) return rc;
  auto neighbor = [&](int delta, int *out) {
    std::vector<int> nb = coords;
    nb[direction] += delta;
    int dim = c->cart_dims[direction];
    if (nb[direction] < 0 || nb[direction] >= dim) {
      if (!c->cart_periods[direction]) {
        *out = MPI_PROC_NULL;
        return;
      }
      nb[direction] = ((nb[direction] % dim) + dim) % dim;
    }
    MPI_Cart_rank(comm, nb.data(), out);
  };
  neighbor(-disp, rank_source);
  neighbor(disp, rank_dest);
  return MPI_SUCCESS;
}

int MPI_Comm_create(MPI_Comm comm, MPI_Group group, MPI_Comm *newcomm) {
  // comm_create.c:40's semantics by reduction to split: members color
  // together, keyed by GROUP rank so the new comm preserves the
  // group's ordering; non-members get MPI_COMM_NULL
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  GroupObj *gr = lookup_group(group);
  if (!gr) return MPI_ERR_GROUP;
  int my_world = c->group[c->local_rank];
  int color = MPI_UNDEFINED, key = 0;
  for (size_t i = 0; i < gr->ranks.size(); i++) {
    if (gr->ranks[i] == my_world) {
      color = 0;
      key = (int)i;
      break;
    }
  }
  return MPI_Comm_split(comm, color, key, newcomm);
}

// ----------------------------------------------------- intercommunicators
// intercomm_create.c / intercomm_merge.c: two disjoint groups of ONE
// universe joined for remote-group point-to-point.  The context ids are
// computed, not negotiated: both sides hash the same (sorted union of
// world ranks, tag) so no extra agreement round exists — the same
// collapse as the deterministic-cid communicator algebra.

namespace {

void intercomm_cids(const std::vector<int> &a, const std::vector<int> &b,
                    int tag, CommObj &out) {
  std::vector<int> all(a);
  all.insert(all.end(), b.begin(), b.end());
  std::sort(all.begin(), all.end());
  uint64_t h = 0xCBF29CE484222325ULL ^ (uint64_t)(uint32_t)tag;
  for (int r : all) h = mix64(h ^ (uint64_t)(uint32_t)r);
  h = (h & 0x3FFFFFFFFFFFULL) | 0x10000ULL;
  out.cid_pt2pt = (int64_t)h;
  out.cid_coll = (int64_t)h + 1;
  out.cid_bar = (int64_t)h + 2;
}

}  // namespace

int MPI_Intercomm_create(MPI_Comm local_comm, int local_leader,
                         MPI_Comm peer_comm, int remote_leader, int tag,
                         MPI_Comm *newintercomm) {
  CommObj *lc = lookup_comm(local_comm);
  if (!lc || !lc->remote.empty()) return MPI_ERR_COMM;
  if (local_leader < 0 || local_leader >= (int)lc->group.size())
    return MPI_ERR_ARG;
  int n = (int)lc->group.size(), me = lc->local_rank;
  // the leaders swap group lists over peer_comm, then broadcast them
  // inside their local comms (intercomm_create.c's two-phase shape)
  std::vector<int> remote;
  if (me == local_leader) {
    CommObj *pc = lookup_comm(peer_comm);
    if (!pc) return MPI_ERR_COMM;
    long my_n = n;
    long their_n = 0;
    MPI_Status st{};
    int rc = MPI_Sendrecv(&my_n, 1, MPI_LONG, remote_leader, tag,
                          &their_n, 1, MPI_LONG, remote_leader, tag,
                          peer_comm, &st);
    if (rc != MPI_SUCCESS) return rc;
    remote.resize((size_t)their_n);
    rc = MPI_Sendrecv(lc->group.data(), n, MPI_INT, remote_leader, tag,
                      remote.data(), (int)their_n, MPI_INT,
                      remote_leader, tag, peer_comm, &st);
    if (rc != MPI_SUCCESS) return rc;
  }
  long rn = (long)remote.size();
  int rc = c_bcast(*lc, &rn, 1, MPI_LONG, local_leader, 0x7E12);
  if (rc != MPI_SUCCESS) return rc;
  remote.resize((size_t)rn);
  rc = c_bcast(*lc, remote.data(), (int)rn, MPI_INT, local_leader,
               0x7E13);
  if (rc != MPI_SUCCESS) return rc;
  CommObj inter;
  inter.group = lc->group;
  inter.local_rank = me;
  inter.remote = remote;
  intercomm_cids(lc->group, remote, tag, inter);
  int handle = g_next_comm++;
  g_comms[handle] = inter;
  *newintercomm = handle;
  return MPI_SUCCESS;
}

int MPI_Comm_remote_size(MPI_Comm comm, int *size) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (c->remote.empty()) return MPI_ERR_COMM;  // intracommunicator
  *size = (int)c->remote.size();
  return MPI_SUCCESS;
}

int MPI_Comm_test_inter(MPI_Comm comm, int *flag) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  *flag = c->remote.empty() ? 0 : 1;
  return MPI_SUCCESS;
}

int MPI_Intercomm_merge(MPI_Comm intercomm, int high, MPI_Comm *newintra) {
  // intercomm_merge.c: concatenate the two groups into one
  // intracommunicator; the `high` group goes SECOND.  Both sides must
  // pass complementary flags (spec requirement); equal flags fall back
  // to a deterministic order (smaller leading world rank first) so the
  // two sides still agree.
  CommObj *c = lookup_comm(intercomm);
  if (!c || c->remote.empty()) return MPI_ERR_COMM;
  // the two sides' flags must actually be COMPARED: deciding the order
  // from one side's flag alone silently diverges when both sides pass
  // the same value (the cids still agree — the union hash is
  // order-independent — so the corruption would be silent).  Leaders
  // swap flags over the intercomm, then broadcast inside each group
  // through a per-side local context derived from the intercomm cid.
  long my_flag = high ? 1 : 0, their_flag = -1;
  if (c->local_rank == 0) {
    // reserved context (cid_bar), NOT the user pt2pt cid: tag 0x7E14
    // is a legal user tag and an eager user message could otherwise
    // match this internal recv
    int remote_leader = c->remote[0];
    int rc = raw_send(&my_flag, 1, MPI_LONG, remote_leader, 0x7E14,
                      c->cid_bar);
    if (rc != MPI_SUCCESS) return rc;
    rc = raw_recv(&their_flag, 1, MPI_LONG, remote_leader, 0x7E14,
                  c->cid_bar, nullptr);
    if (rc != MPI_SUCCESS) return rc;
  }
  CommObj local_side;
  local_side.group = c->group;
  local_side.local_rank = c->local_rank;
  intercomm_cids(c->group, {},
                 (int)((c->cid_pt2pt ^ c->group.front()) & 0x3FFFFFFF),
                 local_side);
  int rc = c_bcast(local_side, &their_flag, 1, MPI_LONG, 0, 0x7E15);
  if (rc != MPI_SUCCESS) return rc;
  bool im_second;
  if (my_flag != their_flag) {
    im_second = my_flag == 1;  // the high group goes second (the spec)
  } else {
    // equal flags (erroneous per MPI, but detectable here): both sides
    // fall back to the same deterministic order — smaller leading
    // world rank first
    im_second = !(c->group.front() < c->remote.front());
  }
  std::vector<int> first = im_second ? c->remote : c->group;
  std::vector<int> second = im_second ? c->group : c->remote;
  CommObj merged;
  merged.group = first;
  merged.group.insert(merged.group.end(), second.begin(), second.end());
  int my_world = c->group[c->local_rank];
  for (size_t i = 0; i < merged.group.size(); i++)
    if (merged.group[i] == my_world) merged.local_rank = (int)i;
  // cids keyed by the parent intercomm's cid AND a per-merge sequence
  // (both sides advance it on every collective merge call), so repeated
  // merges of one intercomm get distinct contexts — the comm_split
  // child_seq discipline
  intercomm_cids(first, second,
                 (int)((c->cid_pt2pt ^
                        (int64_t)(c->child_seq * 0x9E3779B1ULL)) &
                       0x3FFFFFFF) ^
                     0x4D52,
                 merged);
  c->child_seq++;
  int handle = g_next_comm++;
  g_comms[handle] = merged;
  *newintra = handle;
  return MPI_SUCCESS;
}

// ------------------------------------------------------ dynamic spawn
// comm_spawn.c re-designed over universe EXTENSION: children join the
// SAME address book at offset ids (base..base+n), with their own
// MPI_COMM_WORLD context handed down by the spawner — so no second
// wire namespace exists and the spawn intercomm's remote-group pt2pt
// rides the ordinary endpoint machinery.  The root runs the children's
// modex coordinator inline (the standard init handshake, unchanged).
// Constraint (documented): spawns must be serialized across the
// universe — disjoint comms spawning concurrently would fork the book.

namespace {

int g_parent_comm_handle = -2;  // lazily built from the ZMPI_* env
std::vector<pid_t> g_spawned_pids;

}  // namespace

// reap exited children non-blockingly (called per spawn + at Finalize)
void reap_spawned(void) {
  for (auto it = g_spawned_pids.begin(); it != g_spawned_pids.end();) {
    if (waitpid(*it, nullptr, WNOHANG) > 0) it = g_spawned_pids.erase(it);
    else ++it;
  }
}

// one spawn engine for MPI_Comm_spawn AND MPI_Comm_spawn_multiple
// (comm_spawn_multiple.c): all blocks share ONE child world; child i
// runs the command of the block it falls into.
static int spawn_impl(int count, const char *commands[], char ***argvs,
                      const int maxprocs_arr[], int root, MPI_Comm comm,
                      MPI_Comm *intercomm, int errcodes[]) {
  CommObj *c = lookup_comm(comm);
  if (!c || !c->remote.empty()) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  int me = c->local_rank;
  reap_spawned();
  // hdr[0] < 0 signals a root-side failure to EVERY rank through the
  // broadcasts below — the collective-error-agreement discipline (the
  // Python plane's _rank0_collective): no early root return may strand
  // the other ranks inside c_bcast.
  long hdr[3] = {-1, 0, 0};  // maxprocs, spawn cid, base
  std::string flat;          // "host:port\n" per child
  int maxprocs = 0;  // total across blocks (root-significant)
  if (me == root) {
    // commands/argvs/maxprocs are root-significant (MPI-3.1 10.3.2)
    if (count <= 0) goto root_done;
    for (int b = 0; b < count; b++) {
      if (maxprocs_arr[b] <= 0 || !commands[b]) goto root_done;
      maxprocs += maxprocs_arr[b];
    }
    {
      int base = (int)g.book.size();
      // the bound is the CONSTANT, not capacity(): reserve guarantees
      // >= BOOK_CAP, and the no-reallocation invariant must hold on
      // every rank, not just wherever capacity happens to be larger
      if (base + maxprocs > (int)Shim::BOOK_CAP) goto root_done;
      int64_t scid =
          (int64_t)((mix64((uint64_t)base * 0x9E3779B97F4A7C15ULL) &
                     0x3FFFFFFFFFFFULL) |
                    0x200000000000ULL);
      // the children's modex coordinator (standard init handshake)
      int srv = socket(AF_INET, SOCK_STREAM, 0);
      set_cloexec(srv);
      int one = 1;
      setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
      sockaddr_in ca{};
      ca.sin_family = AF_INET;
      ca.sin_port = 0;
      inet_pton(AF_INET, g.host.c_str(), &ca.sin_addr);
      if (bind(srv, (sockaddr *)&ca, sizeof ca) != 0) {
        close(srv);
        goto root_done;
      }
      socklen_t alen = sizeof ca;
      getsockname(srv, (sockaddr *)&ca, &alen);
      int spawn_port = ntohs(ca.sin_port);
      listen(srv, maxprocs + 2);
      std::string pgroup;
      for (size_t i = 0; i < c->group.size(); i++) {
        if (i) pgroup += ",";
        pgroup += std::to_string(c->group[i]);
      }
      // argv/envp built BEFORE fork (threads hold malloc locks); the
      // filtered base environment is shared by every child.  One argv
      // vector per block; child i uses its block's.
      std::vector<std::vector<char *>> avs((size_t)count);
      std::vector<int> block_of((size_t)maxprocs);
      {
        int at = 0;
        for (int b = 0; b < count; b++) {
          avs[(size_t)b].push_back(const_cast<char *>(commands[b]));
          char **bargv = argvs ? argvs[b] : nullptr;
          if (bargv)
            for (int i = 0; bargv[i]; i++)
              avs[(size_t)b].push_back(bargv[i]);
          avs[(size_t)b].push_back(nullptr);
          for (int i = 0; i < maxprocs_arr[b]; i++)
            block_of[(size_t)at++] = b;
        }
      }
      extern char **environ;
      std::vector<std::string> base_envs;
      for (char **e = environ; *e; e++) {
        if (strncmp(*e, "ZMPI_RANK=", 10) &&
            strncmp(*e, "ZMPI_SIZE=", 10) &&
            strncmp(*e, "ZMPI_COORD_", 11) &&
            strncmp(*e, "ZMPI_WORLD_", 11) &&
            strncmp(*e, "ZMPI_SPAWN_", 11) &&
            strncmp(*e, "ZMPI_PARENT_", 12))
          base_envs.push_back(*e);
      }
      base_envs.push_back("ZMPI_SIZE=" + std::to_string(base + maxprocs));
      base_envs.push_back("ZMPI_COORD_HOST=" + g.host);
      base_envs.push_back("ZMPI_COORD_PORT=" + std::to_string(spawn_port));
      base_envs.push_back("ZMPI_WORLD_BASE=" + std::to_string(base));
      base_envs.push_back("ZMPI_WORLD_SIZE=" + std::to_string(maxprocs));
      base_envs.push_back("ZMPI_SPAWN_CID=" + std::to_string(scid));
      base_envs.push_back("ZMPI_PARENT_GROUP=" + pgroup);
      std::vector<pid_t> pids;
      std::vector<int> errpipes;  // CLOEXEC: closes on exec success
      bool launch_failed = false;
      for (int i = 0; i < maxprocs && !launch_failed; i++) {
        std::string rank_env = "ZMPI_RANK=" + std::to_string(base + i);
        std::vector<char *> ev;
        for (auto &x : base_envs) ev.push_back(const_cast<char *>(x.c_str()));
        ev.push_back(const_cast<char *>(rank_env.c_str()));
        ev.push_back(nullptr);
        int pfd[2];
        if (pipe(pfd) != 0) {
          launch_failed = true;
          break;
        }
        set_cloexec(pfd[0]);  // later siblings must not inherit it
        set_cloexec(pfd[1]);
        int blk = block_of[(size_t)i];
        pid_t pid = fork();
        if (pid == 0) {
          close(pfd[0]);
          execve(commands[blk], avs[(size_t)blk].data(), ev.data());
          // exec failed: the CLOEXEC pipe survived — report and die
          // (write is async-signal-safe)
          int err = errno;
          ssize_t ignored = write(pfd[1], &err, sizeof err);
          (void)ignored;
          _exit(127);
        }
        close(pfd[1]);
        if (pid < 0) {
          close(pfd[0]);
          launch_failed = true;
          break;
        }
        pids.push_back(pid);
        errpipes.push_back(pfd[0]);
      }
      // exec verdicts: EOF on the pipe = exec succeeded
      std::vector<int> codes((size_t)maxprocs, MPI_SUCCESS);
      for (size_t i = 0; i < errpipes.size(); i++) {
        int err = 0;
        if (read(errpipes[i], &err, sizeof err) > 0) {
          codes[i] = MPI_ERR_OTHER;
          launch_failed = true;
        }
        close(errpipes[i]);
      }
      if (launch_failed) {
        // no partial universes: kill whatever launched, reap, fail
        for (pid_t pid : pids) kill(pid, SIGKILL);
        for (pid_t pid : pids) waitpid(pid, nullptr, 0);
        close(srv);
        if (errcodes)
          for (int i = 0; i < maxprocs; i++) errcodes[i] = codes[(size_t)i];
        goto root_done;
      }
      for (pid_t pid : pids) g_spawned_pids.push_back(pid);
      // gather the children's cards, reply with the EXTENDED book.
      // accept() is POLLED so a child dying after exec but before its
      // modex connect (crash before MPI_Init) turns into an agreed
      // failure rather than an accept() that waits forever.
      std::vector<std::pair<std::string, int>> kids(maxprocs, {"", 0});
      std::vector<std::string> kidcaps((size_t)maxprocs, "");
      std::vector<int> conns;
      bool modex_ok = true;
      for (int i = 0; i < maxprocs && modex_ok; i++) {
        int fd = -1;
        for (;;) {
          fd_set rf;
          FD_ZERO(&rf);
          FD_SET(srv, &rf);
          timeval tv{1, 0};
          int sel = select(srv + 1, &rf, nullptr, nullptr, &tv);
          if (sel > 0) {
            fd = accept(srv, nullptr, nullptr);
            break;
          }
          // a second of silence: is any child already dead?
          bool died = false;
          for (pid_t pid : pids)
            if (waitpid(pid, nullptr, WNOHANG) > 0) died = true;
          if (died || sel < 0) break;
        }
        if (fd < 0) {
          modex_ok = false;
          break;
        }
        set_cloexec(fd);
        std::string f;
        std::vector<DssVal> vals;
        if (!recv_frame(fd, f) || !parse_all(f, vals) ||
            vals.size() != 2 || vals[1].tag != T_LIST ||
            vals[1].items.size() < 2 || vals[1].items[0].tag != T_STR ||
            vals[1].items[1].tag != T_INT) {
          close(fd);
          modex_ok = false;
          break;
        }
        int kr = (int)vals[0].i - base;
        if (kr >= 0 && kr < maxprocs) {
          kids[kr] = {vals[1].items[0].s, (int)vals[1].items[1].i};
          if (vals[1].items.size() >= 3)
            kidcaps[(size_t)kr] = vals[1].items[2].s;  // sibling sm
        }
        conns.push_back(fd);
      }
      if (!modex_ok) {
        for (int fd : conns) close(fd);
        close(srv);
        goto root_done;
      }
      auto book = g.book;
      auto caps = g.caps;
      caps.resize(book.size(), "");
      for (size_t k = 0; k < kids.size(); k++) {
        book.push_back(kids[k]);
        caps.push_back(kidcaps[k]);  // siblings ring each other
      }
      std::string reply = pack_address_book(book, &caps);
      for (int fd : conns) {
        send_frame(fd, reply);
        close(fd);
      }
      close(srv);
      // the ROOT extends its own book here; every other participant
      // extends from the broadcast below
      for (auto &k : kids) {
        g.book.push_back(k);
        g.caps.push_back("");  // cross-cohort stays TCP (see sm design)
      }
      hdr[0] = maxprocs;
      hdr[1] = scid;
      hdr[2] = base;
      for (auto &k : kids)
        flat += k.first + ":" + std::to_string(k.second) + "\n";
    }
  }
root_done:
  // distribute the outcome to every participant (hdr[0] < 0 = failure)
  int rc = c_bcast(*c, hdr, 3, MPI_LONG, root, 0x7E16);
  if (rc != MPI_SUCCESS) return rc;
  if (hdr[0] < 0) return MPI_ERR_OTHER;  // agreed failure, no deadlock
  long flen = (long)flat.size();
  rc = c_bcast(*c, &flen, 1, MPI_LONG, root, 0x7E17);
  if (rc != MPI_SUCCESS) return rc;
  flat.resize((size_t)flen);
  rc = c_bcast(*c, flat.data(), (int)flen, MPI_BYTE, root, 0x7E18);
  if (rc != MPI_SUCCESS) return rc;
  int base = (int)hdr[2];
  int nkids = (int)hdr[0];  // root-significant maxprocs, agreed via hdr
  if (me != root) {
    if ((int)g.book.size() != base) return MPI_ERR_OTHER;  // serialized-
    // spawn contract broken (see the section comment)
    if (base + nkids > (int)Shim::BOOK_CAP) return MPI_ERR_OTHER;
    size_t pos = 0;
    for (int i = 0; i < nkids; i++) {
      size_t nl = flat.find('\n', pos);
      std::string entry = flat.substr(pos, nl - pos);
      pos = nl + 1;
      size_t colon = entry.rfind(':');
      g.book.push_back({entry.substr(0, colon),
                        atoi(entry.c_str() + colon + 1)});
      g.caps.push_back("");  // cross-cohort stays TCP
    }
  }
  // the spawn intercommunicator: local = the spawn comm, remote = kids
  CommObj inter;
  inter.group = c->group;
  inter.local_rank = me;
  for (int i = 0; i < nkids; i++) inter.remote.push_back(base + i);
  inter.cid_pt2pt = hdr[1];
  inter.cid_coll = hdr[1] + 1;
  inter.cid_bar = hdr[1] + 2;
  int handle = g_next_comm++;
  g_comms[handle] = inter;
  *intercomm = handle;
  if (errcodes)
    for (int i = 0; i < nkids; i++) errcodes[i] = MPI_SUCCESS;
  return MPI_SUCCESS;
}

int MPI_Comm_spawn(const char *command, char *argv[], int maxprocs,
                   MPI_Info /*info*/, int root, MPI_Comm comm,
                   MPI_Comm *intercomm, int errcodes[]) {
  char **argvs1[1] = {argv};
  return spawn_impl(1, &command, argvs1, &maxprocs, root, comm,
                    intercomm, errcodes);
}

int MPI_Comm_spawn_multiple(int count, char *commands[],
                            char **argvs[], const int maxprocs[],
                            const MPI_Info /*infos*/[], int root,
                            MPI_Comm comm, MPI_Comm *intercomm,
                            int errcodes[]) {
  // comm_spawn_multiple.c: one child WORLD spanning every block.
  // count/commands/argvs/maxprocs are ROOT-significant (MPI-3.1
  // 10.3.2) — non-root ranks must not touch them
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::vector<const char *> cmds;
  if (c->local_rank == root && count > 0 && commands) {
    cmds.resize((size_t)count);
    for (int b = 0; b < count; b++) cmds[(size_t)b] = commands[b];
  }
  return spawn_impl(count, cmds.data(), argvs, maxprocs, root, comm,
                    intercomm, errcodes);
}

int MPI_Comm_get_parent(MPI_Comm *parent) {
  const char *wb = getenv("ZMPI_WORLD_BASE");
  if (!wb || !wb[0]) {
    *parent = MPI_COMM_NULL;
    return MPI_SUCCESS;
  }
  if (g_parent_comm_handle >= 0) {
    *parent = g_parent_comm_handle;
    return MPI_SUCCESS;
  }
  CommObj *w = lookup_comm(MPI_COMM_WORLD);
  if (!w) return MPI_ERR_COMM;
  CommObj inter;
  inter.group = w->group;
  inter.local_rank = w->local_rank;
  const char *pg = getenv("ZMPI_PARENT_GROUP");
  for (const char *p = pg; p && *p;) {
    inter.remote.push_back(atoi(p));
    const char *comma = strchr(p, ',');
    p = comma ? comma + 1 : nullptr;
  }
  int64_t scid = atoll(getenv("ZMPI_SPAWN_CID"));
  inter.cid_pt2pt = scid;
  inter.cid_coll = scid + 1;
  inter.cid_bar = scid + 2;
  g_parent_comm_handle = g_next_comm++;
  g_comms[g_parent_comm_handle] = inter;
  *parent = g_parent_comm_handle;
  return MPI_SUCCESS;
}

// ------------------------------------------------------ graph topology
// graph_create.c family: arbitrary neighbor lists in the standard
// index/edges encoding (index[i] = cumulative edge count through node i)

int MPI_Graph_create(MPI_Comm comm, int nnodes, const int index[],
                     const int edges[], int /*reorder*/,
                     MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (nnodes <= 0 || nnodes > (int)c->group.size()) return MPI_ERR_ARG;
  int nedges = index[nnodes - 1];
  for (int i = 0; i < nnodes; i++) {
    if (index[i] < (i ? index[i - 1] : 0)) return MPI_ERR_ARG;
  }
  for (int e = 0; e < nedges; e++)
    if (edges[e] < 0 || edges[e] >= nnodes) return MPI_ERR_ARG;
  int color = c->local_rank < nnodes ? 0 : MPI_UNDEFINED;
  int rc = MPI_Comm_split(comm, color, c->local_rank, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  if (*newcomm == MPI_COMM_NULL) return MPI_SUCCESS;
  CommObj *nc = lookup_comm(*newcomm);
  nc->graph_index.assign(index, index + nnodes);
  nc->graph_edges.assign(edges, edges + nedges);
  return MPI_SUCCESS;
}

int MPI_Graphdims_get(MPI_Comm comm, int *nnodes, int *nedges) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (c->graph_index.empty()) return MPI_ERR_ARG;
  *nnodes = (int)c->graph_index.size();
  *nedges = c->graph_index.back();
  return MPI_SUCCESS;
}

int MPI_Graph_get(MPI_Comm comm, int maxindex, int maxedges, int index[],
                  int edges[]) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (c->graph_index.empty()) return MPI_ERR_ARG;
  if (maxindex < (int)c->graph_index.size() ||
      maxedges < c->graph_index.back())
    return MPI_ERR_ARG;
  std::copy(c->graph_index.begin(), c->graph_index.end(), index);
  std::copy(c->graph_edges.begin(), c->graph_edges.end(), edges);
  return MPI_SUCCESS;
}

int MPI_Graph_neighbors_count(MPI_Comm comm, int rank, int *nneighbors) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nn = (int)c->graph_index.size();
  if (nn == 0 || rank < 0 || rank >= nn) return MPI_ERR_ARG;
  *nneighbors = c->graph_index[rank] - (rank ? c->graph_index[rank - 1]
                                             : 0);
  return MPI_SUCCESS;
}

int MPI_Graph_neighbors(MPI_Comm comm, int rank, int maxneighbors,
                        int neighbors[]) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  int nn = (int)c->graph_index.size();
  if (nn == 0 || rank < 0 || rank >= nn) return MPI_ERR_ARG;
  int lo = rank ? c->graph_index[rank - 1] : 0;
  int hi = c->graph_index[rank];
  if (maxneighbors < hi - lo) return MPI_ERR_ARG;
  for (int e = lo; e < hi; e++) neighbors[e - lo] = c->graph_edges[e];
  return MPI_SUCCESS;
}

int MPI_Topo_test(MPI_Comm comm, int *status) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (!c->cart_dims.empty()) *status = MPI_CART;
  else if (c->dist) *status = MPI_DIST_GRAPH;
  else if (!c->graph_index.empty()) *status = MPI_GRAPH;
  else *status = MPI_UNDEFINED;
  return MPI_SUCCESS;
}

int zompi_unweighted_[1];
int zompi_weights_empty_[1];

int MPI_Dist_graph_create_adjacent(
    MPI_Comm comm, int indegree, const int sources[],
    const int sourceweights[], int outdegree, const int destinations[],
    const int destweights[], MPI_Info /*info*/, int /*reorder*/,
    MPI_Comm *newcomm) {
  // dist_graph_create_adjacent.c: the adjacent form is fully LOCAL —
  // every rank already knows its own in/out lists, so the derived comm
  // needs no neighbor exchange at all (weights are accepted and
  // ignored, as coll components may)
  CommObj *c = lookup_comm(comm);
  if (!c || !c->remote.empty()) return MPI_ERR_COMM;
  if (indegree < 0 || outdegree < 0) return MPI_ERR_ARG;
  int n = (int)c->group.size();
  for (int i = 0; i < indegree; i++)
    if (sources[i] < 0 || sources[i] >= n) return MPI_ERR_ARG;
  for (int i = 0; i < outdegree; i++)
    if (destinations[i] < 0 || destinations[i] >= n) return MPI_ERR_ARG;
  // derive like Graph_create (split, NOT dup: topology constructors
  // must not run attribute copy callbacks)
  int rc = MPI_Comm_split(comm, 0, c->local_rank, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  CommObj *nc = lookup_comm(*newcomm);
  nc->dist = true;
  nc->dist_src.assign(sources, sources + indegree);
  nc->dist_dst.assign(destinations, destinations + outdegree);
  // MPI_UNWEIGHTED / MPI_WEIGHTS_EMPTY are distinct sentinel
  // addresses; a topology is weighted unless BOTH args say unweighted
  // (a zero-degree side passes WEIGHTS_EMPTY and stays
  // weighted-compatible, per the spec's adjacent-form contract)
  auto is_unw = [](const int *w) { return w == MPI_UNWEIGHTED; };
  auto is_empty = [](const int *w) { return w == MPI_WEIGHTS_EMPTY; };
  nc->dist_weighted = !is_unw(sourceweights) || !is_unw(destweights);
  if (nc->dist_weighted) {
    if (indegree > 0 && !is_unw(sourceweights) &&
        !is_empty(sourceweights) && sourceweights)
      nc->dist_srcw.assign(sourceweights, sourceweights + indegree);
    if (outdegree > 0 && !is_unw(destweights) && !is_empty(destweights) &&
        destweights)
      nc->dist_dstw.assign(destweights, destweights + outdegree);
  }
  return MPI_SUCCESS;
}

int MPI_Dist_graph_neighbors_count(MPI_Comm comm, int *indegree,
                                   int *outdegree, int *weighted) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (!c->dist) return MPI_ERR_ARG;
  *indegree = (int)c->dist_src.size();
  *outdegree = (int)c->dist_dst.size();
  *weighted = c->dist_weighted ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Dist_graph_neighbors(MPI_Comm comm, int maxindegree,
                             int sources[], int sourceweights[],
                             int maxoutdegree, int destinations[],
                             int destweights[]) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (!c->dist) return MPI_ERR_ARG;
  if (maxindegree < (int)c->dist_src.size() ||
      maxoutdegree < (int)c->dist_dst.size())
    return MPI_ERR_ARG;
  std::copy(c->dist_src.begin(), c->dist_src.end(), sources);
  std::copy(c->dist_dst.begin(), c->dist_dst.end(), destinations);
  if (c->dist_weighted) {
    if (sourceweights && sourceweights != MPI_UNWEIGHTED &&
        sourceweights != MPI_WEIGHTS_EMPTY)
      std::copy(c->dist_srcw.begin(), c->dist_srcw.end(), sourceweights);
    if (destweights && destweights != MPI_UNWEIGHTED &&
        destweights != MPI_WEIGHTS_EMPTY)
      std::copy(c->dist_dstw.begin(), c->dist_dstw.end(), destweights);
  }
  return MPI_SUCCESS;
}

// ------------------------------------------ neighborhood collectives
// neighbor_allgather.c / neighbor_alltoall.c over the cart/graph
// topologies: standard neighbor order (cart: for each dim, -1 then +1;
// graph: the node's edge list).  Tag pairing makes the exchange exact
// even in degenerate topologies (a size-2 periodic ring where the
// minus and plus neighbor are the SAME process): cart sends carry the
// RECEIVER's slot (the complementary direction, slot^1); graph sends
// carry the edge's ordinal among the parallel edges to that neighbor
// (the symmetric-multiplicity convention).

namespace {

// local-rank neighbor lists in standard order; MPI_PROC_NULL at walls.
// For cart/graph the send and recv lists coincide; a distributed graph
// (adjacent form) has directed lists.  Cart neighbors come from
// MPI_Cart_shift — ONE copy of the wrap/encode rules.
int neighbor_list(MPI_Comm comm, CommObj &c, std::vector<int> &nbrs);

int neighbor_lists(MPI_Comm comm, CommObj &c, std::vector<int> &recv_from,
                   std::vector<int> &send_to) {
  if (c.dist) {
    recv_from = c.dist_src;
    send_to = c.dist_dst;
    return MPI_SUCCESS;
  }
  std::vector<int> nbrs;
  int rc = neighbor_list(comm, c, nbrs);
  if (rc != MPI_SUCCESS) return rc;
  recv_from = nbrs;
  send_to = nbrs;
  return MPI_SUCCESS;
}

int neighbor_list(MPI_Comm comm, CommObj &c, std::vector<int> &nbrs) {
  nbrs.clear();
  if (!c.cart_dims.empty()) {
    for (int d = 0; d < (int)c.cart_dims.size(); d++) {
      int minus, plus;
      int rc = MPI_Cart_shift(comm, d, 1, &minus, &plus);
      if (rc != MPI_SUCCESS) return rc;
      nbrs.push_back(minus);
      nbrs.push_back(plus);
    }
    return MPI_SUCCESS;
  }
  if (!c.graph_index.empty()) {
    int me = c.local_rank;
    int lo = me ? c.graph_index[me - 1] : 0;
    for (int e = lo; e < c.graph_index[me]; e++)
      nbrs.push_back(c.graph_edges[e]);
    return MPI_SUCCESS;
  }
  return MPI_ERR_ARG;  // no topology attached
}

// tag codes: receiver's slot for cart, parallel-edge ordinal for
// (dist) graphs — the i-th out-edge to a peer pairs with its i-th
// in-edge from us, the symmetric-multiplicity convention
void neighbor_codes(CommObj &c, const std::vector<int> &recv_from,
                    const std::vector<int> &send_to,
                    std::vector<int> &send_code,
                    std::vector<int> &recv_code) {
  bool cart = !c.cart_dims.empty();
  send_code.resize(send_to.size());
  recv_code.resize(recv_from.size());
  std::map<int, int> seen_s, seen_r;
  for (size_t i = 0; i < send_to.size(); i++)
    send_code[i] = cart ? ((int)i ^ 1) : seen_s[send_to[i]]++;
  for (size_t i = 0; i < recv_from.size(); i++)
    recv_code[i] = cart ? (int)i : seen_r[recv_from[i]]++;
}

int c_neighbor_exchange(MPI_Comm comm, CommObj &c, const void *sendbuf,
                        int scount, MPI_Datatype stype, void *recvbuf,
                        int rcount, MPI_Datatype rtype, bool alltoall) {
  if (!c.remote.empty()) return MPI_ERR_COMM;  // intercomm: pt2pt surface
  DtView sv, rv;
  if (!resolve_dtype(stype, sv) || !resolve_dtype(rtype, rv))
    return MPI_ERR_TYPE;
  std::vector<int> recv_from, send_to;
  int rc = neighbor_lists(comm, c, recv_from, send_to);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<int> send_code, recv_code;
  neighbor_codes(c, recv_from, send_to, send_code, recv_code);
  int nr = (int)recv_from.size(), ns = (int)send_to.size();
  int64_t base = (c.coll_seq++ % 0x8000) << 16;
  // slot stride follows the EXTENT rule like every gather-family
  // collective (block i starts at i * slot_bytes), not the packed size
  size_t sslot = slot_bytes(sv, scount);
  size_t rslot = slot_bytes(rv, rcount);
  // post every receive first (the PROC_NULL blocks stay untouched)
  std::vector<Req> reqs(nr);
  std::vector<int> handles(nr, -1);
  // the stack Reqs must not outlive their registrations: every exit
  // path past this point deregisters whatever is still pending
  auto abort_all = [&](int err) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < nr; i++)
      if (handles[i] >= 0) deregister_locked(handles[i], &reqs[i]);
    return err;
  };
  for (int i = 0; i < nr; i++) {
    if (recv_from[i] == MPI_PROC_NULL) continue;
    reqs[i].is_recv = true;
    reqs[i].user_buf = (char *)recvbuf + (size_t)i * rslot;
    reqs[i].count = rcount;
    handles[i] = post_recv(&reqs[i], rv, c.cid_coll,
                           world_of(c, recv_from[i]),
                           base | (0x7E20 + recv_code[i]));
  }
  for (int i = 0; i < ns; i++) {
    if (send_to[i] == MPI_PROC_NULL) continue;
    const char *blk = alltoall ? (const char *)sendbuf + (size_t)i * sslot
                               : (const char *)sendbuf;
    rc = raw_send(blk, scount, stype, world_of(c, send_to[i]),
                  base | (0x7E20 + send_code[i]), c.cid_coll);
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  for (int i = 0; i < nr; i++) {
    if (handles[i] < 0) continue;
    rc = wait_handle(handles[i], nullptr);
    handles[i] = -1;  // consumed (success or not), never re-deregister
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  return MPI_SUCCESS;
}

// generalized neighborhood exchange: per-edge buffers/counts/types.
// The v/w variants (neighbor_allgatherv.c, neighbor_alltoallv.c,
// neighbor_alltoallw.c) all reduce to this shape; the callers compute
// the per-edge pointers (extent-scaled for v, byte displacements for
// w — MPI-3.1 §7.7).
int c_neighbor_general(MPI_Comm comm, CommObj &c,
                       const std::vector<const char *> &sptr,
                       const std::vector<int> &scnt,
                       const std::vector<MPI_Datatype> &stype,
                       const std::vector<char *> &rptr,
                       const std::vector<int> &rcnt,
                       const std::vector<MPI_Datatype> &rtype,
                       const std::vector<int> &recv_from,
                       const std::vector<int> &send_to) {
  std::vector<int> send_code, recv_code;
  neighbor_codes(c, recv_from, send_to, send_code, recv_code);
  int nr = (int)recv_from.size(), ns = (int)send_to.size();
  int64_t base = (c.coll_seq++ % 0x8000) << 16;
  std::vector<Req> reqs((size_t)nr);
  std::vector<int> handles((size_t)nr, -1);
  auto abort_all = [&](int err) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < nr; i++)
      if (handles[(size_t)i] >= 0)
        deregister_locked(handles[(size_t)i], &reqs[(size_t)i]);
    return err;
  };
  for (int i = 0; i < nr; i++) {
    if (recv_from[(size_t)i] == MPI_PROC_NULL) continue;
    DtView rv;
    if (!resolve_dtype(rtype[(size_t)i], rv))
      return abort_all(MPI_ERR_TYPE);
    reqs[(size_t)i].is_recv = true;
    reqs[(size_t)i].user_buf = rptr[(size_t)i];
    reqs[(size_t)i].count = rcnt[(size_t)i];
    handles[(size_t)i] =
        post_recv(&reqs[(size_t)i], rv, c.cid_coll,
                  world_of(c, recv_from[(size_t)i]),
                  base | (0x7E20 + recv_code[(size_t)i]));
  }
  for (int i = 0; i < ns; i++) {
    if (send_to[(size_t)i] == MPI_PROC_NULL) continue;
    int rc = raw_send(sptr[(size_t)i], scnt[(size_t)i],
                      stype[(size_t)i],
                      world_of(c, send_to[(size_t)i]),
                      base | (0x7E20 + send_code[(size_t)i]),
                      c.cid_coll);
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  for (int i = 0; i < nr; i++) {
    if (handles[(size_t)i] < 0) continue;
    int rc = wait_handle(handles[(size_t)i], nullptr);
    handles[(size_t)i] = -1;
    if (rc != MPI_SUCCESS) return abort_all(rc);
  }
  return MPI_SUCCESS;
}

int c_neighbor_allgatherv(MPI_Comm comm, CommObj &c, const void *sendbuf,
                          int sendcount, MPI_Datatype sendtype,
                          void *recvbuf, const int recvcounts[],
                          const int displs[], MPI_Datatype recvtype) {
  if (!c.remote.empty()) return MPI_ERR_COMM;
  DtView rv;
  if (!resolve_dtype(recvtype, rv)) return MPI_ERR_TYPE;
  std::vector<int> recv_from, send_to;
  int rc = neighbor_lists(comm, c, recv_from, send_to);
  if (rc != MPI_SUCCESS) return rc;
  size_t rstride = slot_bytes(rv, 1);
  int nr = (int)recv_from.size(), ns = (int)send_to.size();
  std::vector<const char *> sptr((size_t)ns, (const char *)sendbuf);
  std::vector<int> scnt((size_t)ns, sendcount);
  std::vector<MPI_Datatype> stypes((size_t)ns, sendtype);
  std::vector<char *> rptr((size_t)nr);
  std::vector<int> rcnt((size_t)nr);
  std::vector<MPI_Datatype> rtypes((size_t)nr, recvtype);
  for (int i = 0; i < nr; i++) {
    rptr[(size_t)i] = (char *)recvbuf + (size_t)displs[i] * rstride;
    rcnt[(size_t)i] = recvcounts[i];
  }
  return c_neighbor_general(comm, c, sptr, scnt, stypes, rptr, rcnt,
                            rtypes, recv_from, send_to);
}

int c_neighbor_alltoallv(MPI_Comm comm, CommObj &c, const void *sendbuf,
                         const int sendcounts[], const int sdispls[],
                         MPI_Datatype sendtype, void *recvbuf,
                         const int recvcounts[], const int rdispls[],
                         MPI_Datatype recvtype) {
  if (!c.remote.empty()) return MPI_ERR_COMM;
  DtView sv, rv;
  if (!resolve_dtype(sendtype, sv) || !resolve_dtype(recvtype, rv))
    return MPI_ERR_TYPE;
  std::vector<int> recv_from, send_to;
  int rc = neighbor_lists(comm, c, recv_from, send_to);
  if (rc != MPI_SUCCESS) return rc;
  size_t sstride = slot_bytes(sv, 1), rstride = slot_bytes(rv, 1);
  int nr = (int)recv_from.size(), ns = (int)send_to.size();
  std::vector<const char *> sptr((size_t)ns);
  std::vector<int> scnt((size_t)ns);
  std::vector<MPI_Datatype> stypes((size_t)ns, sendtype);
  std::vector<char *> rptr((size_t)nr);
  std::vector<int> rcnt((size_t)nr);
  std::vector<MPI_Datatype> rtypes((size_t)nr, recvtype);
  for (int i = 0; i < ns; i++) {
    sptr[(size_t)i] =
        (const char *)sendbuf + (size_t)sdispls[i] * sstride;
    scnt[(size_t)i] = sendcounts[i];
  }
  for (int i = 0; i < nr; i++) {
    rptr[(size_t)i] = (char *)recvbuf + (size_t)rdispls[i] * rstride;
    rcnt[(size_t)i] = recvcounts[i];
  }
  return c_neighbor_general(comm, c, sptr, scnt, stypes, rptr, rcnt,
                            rtypes, recv_from, send_to);
}

int c_neighbor_alltoallw(MPI_Comm comm, CommObj &c, const void *sendbuf,
                         const int sendcounts[],
                         const MPI_Aint sdispls[],
                         const MPI_Datatype sendtypes[], void *recvbuf,
                         const int recvcounts[],
                         const MPI_Aint rdispls[],
                         const MPI_Datatype recvtypes[]) {
  if (!c.remote.empty()) return MPI_ERR_COMM;
  std::vector<int> recv_from, send_to;
  int rc = neighbor_lists(comm, c, recv_from, send_to);
  if (rc != MPI_SUCCESS) return rc;
  int nr = (int)recv_from.size(), ns = (int)send_to.size();
  std::vector<const char *> sptr((size_t)ns);
  std::vector<int> scnt((size_t)ns);
  std::vector<MPI_Datatype> stypes((size_t)ns);
  std::vector<char *> rptr((size_t)nr);
  std::vector<int> rcnt((size_t)nr);
  std::vector<MPI_Datatype> rtypes((size_t)nr);
  for (int i = 0; i < ns; i++) {
    sptr[(size_t)i] = (const char *)sendbuf + (size_t)sdispls[i];
    scnt[(size_t)i] = sendcounts[i];
    stypes[(size_t)i] = sendtypes[i];
  }
  for (int i = 0; i < nr; i++) {
    rptr[(size_t)i] = (char *)recvbuf + (size_t)rdispls[i];
    rcnt[(size_t)i] = recvcounts[i];
    rtypes[(size_t)i] = recvtypes[i];
  }
  return c_neighbor_general(comm, c, sptr, scnt, stypes, rptr, rcnt,
                            rtypes, recv_from, send_to);
}

}  // namespace

int MPI_Neighbor_allgather(const void *sendbuf, int sendcount,
                           MPI_Datatype sendtype, void *recvbuf,
                           int recvcount, MPI_Datatype recvtype,
                           MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  return dispatch_comm_err(
      comm, c_neighbor_exchange(comm, *c, sendbuf, sendcount, sendtype,
                                recvbuf, recvcount, recvtype, false));
}

int MPI_Neighbor_alltoall(const void *sendbuf, int sendcount,
                          MPI_Datatype sendtype, void *recvbuf,
                          int recvcount, MPI_Datatype recvtype,
                          MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  return dispatch_comm_err(
      comm, c_neighbor_exchange(comm, *c, sendbuf, sendcount, sendtype,
                                recvbuf, recvcount, recvtype, true));
}

int MPI_Neighbor_allgatherv(const void *sendbuf, int sendcount,
                            MPI_Datatype sendtype, void *recvbuf,
                            const int recvcounts[], const int displs[],
                            MPI_Datatype recvtype, MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  return dispatch_comm_err(
      comm, c_neighbor_allgatherv(comm, *c, sendbuf, sendcount,
                                  sendtype, recvbuf, recvcounts, displs,
                                  recvtype));
}

int MPI_Neighbor_alltoallv(const void *sendbuf, const int sendcounts[],
                           const int sdispls[], MPI_Datatype sendtype,
                           void *recvbuf, const int recvcounts[],
                           const int rdispls[], MPI_Datatype recvtype,
                           MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  return dispatch_comm_err(
      comm, c_neighbor_alltoallv(comm, *c, sendbuf, sendcounts, sdispls,
                                 sendtype, recvbuf, recvcounts, rdispls,
                                 recvtype));
}

int MPI_Neighbor_alltoallw(const void *sendbuf, const int sendcounts[],
                           const MPI_Aint sdispls[],
                           const MPI_Datatype sendtypes[], void *recvbuf,
                           const int recvcounts[],
                           const MPI_Aint rdispls[],
                           const MPI_Datatype recvtypes[],
                           MPI_Comm comm) {
  CommObj *c = lookup_comm(comm);
  if (!c) return dispatch_comm_err(comm, MPI_ERR_COMM);
  return dispatch_comm_err(
      comm, c_neighbor_alltoallw(comm, *c, sendbuf, sendcounts, sdispls,
                                 sendtypes, recvbuf, recvcounts,
                                 rdispls, recvtypes));
}

// nonblocking neighborhood collectives (ineighbor_allgather.c family):
// the icoll engine — reserve the tag window, run the blocking form on
// a comm snapshot, retire through the request engine.  Array arguments
// are snapshotted by value (the standard lets the caller reuse them
// the moment the call returns).

int MPI_Ineighbor_allgather(const void *sendbuf, int sendcount,
                            MPI_Datatype sendtype, void *recvbuf,
                            int recvcount, MPI_Datatype recvtype,
                            MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [=]() {
        return c_neighbor_exchange(comm, *snap, sendbuf, sendcount,
                                   sendtype, recvbuf, recvcount,
                                   recvtype, false);
      },
      comm, request);
}

int MPI_Ineighbor_alltoall(const void *sendbuf, int sendcount,
                           MPI_Datatype sendtype, void *recvbuf,
                           int recvcount, MPI_Datatype recvtype,
                           MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [=]() {
        return c_neighbor_exchange(comm, *snap, sendbuf, sendcount,
                                   sendtype, recvbuf, recvcount,
                                   recvtype, true);
      },
      comm, request);
}

int MPI_Ineighbor_allgatherv(const void *sendbuf, int sendcount,
                             MPI_Datatype sendtype, void *recvbuf,
                             const int recvcounts[], const int displs[],
                             MPI_Datatype recvtype, MPI_Comm comm,
                             MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::vector<int> rf, st_;
  int rc = neighbor_lists(comm, *c, rf, st_);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<int> rc_v(recvcounts, recvcounts + rf.size());
  std::vector<int> d_v(displs, displs + rf.size());
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [=]() {
        return c_neighbor_allgatherv(comm, *snap, sendbuf, sendcount,
                                     sendtype, recvbuf, rc_v.data(),
                                     d_v.data(), recvtype);
      },
      comm, request);
}

int MPI_Ineighbor_alltoallv(const void *sendbuf, const int sendcounts[],
                            const int sdispls[], MPI_Datatype sendtype,
                            void *recvbuf, const int recvcounts[],
                            const int rdispls[], MPI_Datatype recvtype,
                            MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::vector<int> rf, st_;
  int rc = neighbor_lists(comm, *c, rf, st_);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<int> sc_v(sendcounts, sendcounts + st_.size());
  std::vector<int> sd_v(sdispls, sdispls + st_.size());
  std::vector<int> rc_v(recvcounts, recvcounts + rf.size());
  std::vector<int> rd_v(rdispls, rdispls + rf.size());
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [=]() {
        return c_neighbor_alltoallv(comm, *snap, sendbuf, sc_v.data(),
                                    sd_v.data(), sendtype, recvbuf,
                                    rc_v.data(), rd_v.data(), recvtype);
      },
      comm, request);
}

int MPI_Ineighbor_alltoallw(const void *sendbuf, const int sendcounts[],
                            const MPI_Aint sdispls[],
                            const MPI_Datatype sendtypes[],
                            void *recvbuf, const int recvcounts[],
                            const MPI_Aint rdispls[],
                            const MPI_Datatype recvtypes[],
                            MPI_Comm comm, MPI_Request *request) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  std::vector<int> rf, st_;
  int rc = neighbor_lists(comm, *c, rf, st_);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<int> sc_v(sendcounts, sendcounts + st_.size());
  std::vector<MPI_Aint> sd_v(sdispls, sdispls + st_.size());
  std::vector<MPI_Datatype> stv(sendtypes, sendtypes + st_.size());
  std::vector<int> rc_v(recvcounts, recvcounts + rf.size());
  std::vector<MPI_Aint> rd_v(rdispls, rdispls + rf.size());
  std::vector<MPI_Datatype> rtv(recvtypes, recvtypes + rf.size());
  auto snap = icoll_reserve(c);
  return icoll_spawn(
      [=]() {
        return c_neighbor_alltoallw(comm, *snap, sendbuf, sc_v.data(),
                                    sd_v.data(), stv.data(), recvbuf,
                                    rc_v.data(), rd_v.data(),
                                    rtv.data());
      },
      comm, request);
}

// ------------------------------------------------------ one-sided RMA

int MPI_Win_create(void *base, MPI_Aint size, int disp_unit, MPI_Info,
                   MPI_Comm comm, MPI_Win *win) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (size < 0 || (size > 0 && !base) || disp_unit <= 0)
    return MPI_ERR_ARG;
  // the wire win-id is deterministic per comm (cid x per-comm counter):
  // every member computes the same id with no agreement round — the
  // same collapse as the deterministic-cid communicator algebra
  int64_t wid = (int64_t)((uint64_t)c->cid_pt2pt * 256u + c->win_seq++);
  WinObj *w = new WinObj;
  w->base = (char *)base;
  w->size = (int64_t)size;
  w->disp_unit = disp_unit;
  w->comm = *c;
  int handle;
  {
    std::lock_guard<std::mutex> lk(g_wins_mu);
    g_wins[wid] = w;
    handle = g_next_win_handle++;
    g_win_handles[handle] = wid;
  }
  // all windows registered before any rank may start an epoch
  int rc = c_barrier(*c);
  if (rc != MPI_SUCCESS) return rc;
  *win = handle;
  return MPI_SUCCESS;
}

namespace {

WinObj *lookup_win(MPI_Win win, int64_t *wid_out = nullptr) {
  std::lock_guard<std::mutex> lk(g_wins_mu);
  auto h = g_win_handles.find(win);
  if (h == g_win_handles.end()) return nullptr;
  auto it = g_wins.find(h->second);
  if (it == g_wins.end()) return nullptr;
  if (wid_out) *wid_out = h->second;
  return it->second;
}

// origin-side packing of (count, dtype) into contiguous base bytes
int pack_origin(const void *addr, int count, MPI_Datatype dt,
                std::vector<char> &out, DtInfo &di) {
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  di = v.di;
  pack_dtype(addr, count, v, out);
  return MPI_SUCCESS;
}

}  // namespace

int MPI_Put(const void *origin_addr, int origin_count,
            MPI_Datatype origin_datatype, int target_rank,
            MPI_Aint target_disp, int target_count,
            MPI_Datatype target_datatype, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (target_rank == MPI_PROC_NULL) return MPI_SUCCESS;
  if (target_rank < 0 || target_rank >= (int)c.group.size())
    return MPI_ERR_ARG;
  DtView tv;
  if (!resolve_dtype(target_datatype, tv)) return MPI_ERR_TYPE;
  // the wire op writes contiguous bytes at the target; a strided
  // target typemap would be silently flattened — reject it
  if (!tv.contiguous()) return MPI_ERR_TYPE;
  std::vector<char> data;
  DtInfo di;
  int rc = pack_origin(origin_addr, origin_count, origin_datatype, data, di);
  if (rc != MPI_SUCCESS) return rc;
  size_t want =
      (size_t)target_count * tv.elems_per_item() * tv.di.item;
  if (data.size() != want) return MPI_ERR_TRUNCATE;
  int64_t disp = (int64_t)target_disp * w->disp_unit;
  int tw = world_of(c, target_rank);
  if (tw == g.rank) {
    char *dst = win_dst(w, disp, (int64_t)data.size());
    if (!dst) return MPI_ERR_ARG;
    std::lock_guard<std::mutex> lk(w->mu);
    memcpy(dst, data.data(), data.size());
    return MPI_SUCCESS;
  }
  std::string t;
  t.push_back((char)T_TUPLE);
  put_varint(t, 4);
  put_str(t, "wput");
  put_int(t, wid);
  put_int(t, disp);
  put_ndarray_1d(t, di.tag, data.data(),
                 data.size() / di.item, di.item);
  rc = win_send_tuple(tw, t);
  if (rc == MPI_SUCCESS) {
    std::lock_guard<std::mutex> lk(w->dirty_mu);
    w->dirty.insert(tw);
  }
  return rc;
}

int MPI_Accumulate(const void *origin_addr, int origin_count,
                   MPI_Datatype origin_datatype, int target_rank,
                   MPI_Aint target_disp, int target_count,
                   MPI_Datatype target_datatype, MPI_Op op, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (target_rank == MPI_PROC_NULL) return MPI_SUCCESS;
  if (target_rank < 0 || target_rank >= (int)c.group.size())
    return MPI_ERR_ARG;
  if (g_user_ops.count(op))
    return MPI_ERR_OP;  // MPI: accumulate takes predefined ops only
  DtView tv;
  if (!resolve_dtype(target_datatype, tv)) return MPI_ERR_TYPE;
  if (!tv.contiguous()) return MPI_ERR_TYPE;  // see MPI_Put
  {
    int oprc = check_acc_op_pairing(
        tv.derived ? tv.derived->base : target_datatype, op);
    if (oprc != MPI_SUCCESS) return oprc;
  }
  std::vector<char> data;
  DtInfo di;
  int rc = pack_origin(origin_addr, origin_count, origin_datatype, data,
                       di);
  if (rc != MPI_SUCCESS) return rc;
  size_t want =
      (size_t)target_count * tv.elems_per_item() * tv.di.item;
  if (data.size() != want) return MPI_ERR_TRUNCATE;
  int64_t disp = (int64_t)target_disp * w->disp_unit;
  int tw = world_of(c, target_rank);
  int n = (int)(data.size() / tv.di.item);
  if (tw == g.rank) {
    char *dst = win_dst(w, disp, (int64_t)data.size());
    if (!dst) return MPI_ERR_ARG;
    std::lock_guard<std::mutex> lk(w->mu);
    return reduce_buf(dst, data.data(), n,
                      tv.derived ? tv.derived->base : target_datatype, op);
  }
  std::string t;
  t.push_back((char)T_TUPLE);
  put_varint(t, 6);
  put_str(t, "wacc");
  put_int(t, wid);
  put_int(t, disp);
  put_int(t, (int64_t)op);
  put_int(t, (int64_t)(tv.derived ? tv.derived->base : target_datatype));
  put_ndarray_1d(t, di.tag, data.data(), data.size() / di.item, di.item);
  rc = win_send_tuple(tw, t);
  if (rc == MPI_SUCCESS) {
    std::lock_guard<std::mutex> lk(w->dirty_mu);
    w->dirty.insert(tw);
  }
  return rc;
}

int MPI_Get(void *origin_addr, int origin_count,
            MPI_Datatype origin_datatype, int target_rank,
            MPI_Aint target_disp, int target_count,
            MPI_Datatype target_datatype, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (target_rank == MPI_PROC_NULL) return MPI_SUCCESS;
  if (target_rank < 0 || target_rank >= (int)c.group.size())
    return MPI_ERR_ARG;
  DtView ov, tv;
  if (!resolve_dtype(origin_datatype, ov) ||
      !resolve_dtype(target_datatype, tv))
    return MPI_ERR_TYPE;
  if (!tv.contiguous()) return MPI_ERR_TYPE;  // see MPI_Put
  size_t nbytes = (size_t)target_count * tv.elems_per_item() * tv.di.item;
  if (nbytes > 0x7FFFFFFFull) return MPI_ERR_COUNT;  // int request count
  size_t obytes = (size_t)origin_count * ov.elems_per_item() * ov.di.item;
  if (nbytes != obytes) return MPI_ERR_TRUNCATE;
  int64_t disp = (int64_t)target_disp * w->disp_unit;
  int tw = world_of(c, target_rank);
  std::vector<char> raw(nbytes);
  if (tw == g.rank) {
    char *src_p = win_dst(w, (int64_t)disp, (int64_t)nbytes);
    if (!src_p) return MPI_ERR_ARG;
    std::lock_guard<std::mutex> lk(w->mu);
    memcpy(raw.data(), src_p, nbytes);
  } else {
    // RPC: post the reply recv, send the request, wait (the epoch is
    // active-target, so a blocking get inside it is the natural shape)
    int64_t rtag = g_next_reply_tag.fetch_add(1);
    Req r;
    r.is_recv = true;
    r.user_buf = raw.data();
    r.count = (int)nbytes;
    DtView bv;
    bv.di = {"|u1", 1};
    int handle = post_recv(&r, bv, WIN_CID, tw, rtag);
    std::string t;
    t.push_back((char)T_TUPLE);
    put_varint(t, 5);
    put_str(t, "wget");
    put_int(t, wid);
    put_int(t, disp);
    put_int(t, (int64_t)nbytes);
    put_int(t, rtag);
    int rc = win_send_tuple(tw, t);
    if (rc != MPI_SUCCESS) {
      std::lock_guard<std::mutex> lk(g.match_mu);
      deregister_locked(handle, &r);
      return rc;
    }
    MPI_Status st{};
    rc = wait_handle_impl(handle, &st, g.cts_timeout);
    if (rc != MPI_SUCCESS) return rc;
    if ((size_t)st._count != nbytes) return MPI_ERR_ARG;  // oob at target
  }
  if (ov.contiguous()) {
    memcpy(origin_addr, raw.data(), nbytes);
  } else {
    unpack_dtype(origin_addr, origin_count, ov, raw.data(), nbytes);
  }
  return MPI_SUCCESS;
}

/* Nonblocking window get (the shmem_get_nbi substrate): posts the
 * reply recv into `dest` and fires the wget RPC, returning a request
 * handle the caller completes with zompi_win_get_wait (normally from
 * shmem_quiet).  Not part of mpi.h. */
std::map<int, long long> g_nbi_want;  // handle -> expected reply bytes
std::mutex g_nbi_want_mu;

int zompi_win_get_start(MPI_Win win, int target_rank,
                        long long disp_bytes, long long nbytes,
                        void *dest, int *handle_out) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (target_rank < 0 || target_rank >= (int)c.group.size())
    return MPI_ERR_ARG;
  if (nbytes <= 0 || nbytes > 0x7FFFFFFFll || disp_bytes < 0)
    return MPI_ERR_ARG;
  int tw = world_of(c, target_rank);
  if (tw == g.rank) {
    if (disp_bytes + nbytes > w->size) return MPI_ERR_ARG;
    {
      std::lock_guard<std::mutex> lk(w->mu);
      memcpy(dest, w->base + disp_bytes, (size_t)nbytes);
    }
    Req *r;
    *handle_out = make_completed_req(MPI_COMM_WORLD, &r);
    r->status._count = nbytes;
    return MPI_SUCCESS;
  }
  int64_t rtag = g_next_reply_tag.fetch_add(1);
  Req *r = new Req;
  r->is_recv = true;
  r->heap = true;
  r->user_buf = dest;
  r->count = (int)nbytes;
  DtView bv;
  bv.di = {"|u1", 1};
  int handle = post_recv(r, bv, WIN_CID, tw, rtag);
  std::string t;
  t.push_back((char)T_TUPLE);
  put_varint(t, 5);
  put_str(t, "wget");
  put_int(t, wid);
  put_int(t, disp_bytes);
  put_int(t, nbytes);
  put_int(t, rtag);
  int rc = win_send_tuple(tw, t);
  if (rc != MPI_SUCCESS) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    deregister_locked(handle, r);
    delete r;
    return rc;
  }
  {
    std::lock_guard<std::mutex> lk(g_nbi_want_mu);
    g_nbi_want[handle] = nbytes;
  }
  *handle_out = handle;
  return MPI_SUCCESS;
}

int zompi_win_get_wait(int handle) {
  long long want = -1;
  {
    std::lock_guard<std::mutex> lk(g_nbi_want_mu);
    auto it = g_nbi_want.find(handle);
    if (it != g_nbi_want.end()) {
      want = it->second;
      g_nbi_want.erase(it);
    }
  }
  MPI_Status st{};
  int rc = wait_handle_impl(handle, &st, g.cts_timeout);
  if (rc != MPI_SUCCESS) return rc;
  // the target answers out-of-range requests with an EMPTY reply
  // (blocking MPI_Get has the same check): a short reply must surface
  if (want >= 0 && st._count != want) return MPI_ERR_ARG;
  return MPI_SUCCESS;
}

/* Fetch-AMO on a window cell (the C OSHMEM layer's substrate; not part
 * of mpi.h).  subkind: "add" | "set" | "swap" | "cas" | "fetch"; for
 * cas `operand` carries [compare][value].  Fills `old_out` (di.item
 * bytes) with the pre-op value.  Atomic at the target: the drain
 * applies under the window lock. */
int zompi_win_amo(MPI_Win win, int target_rank, long long disp_bytes,
                  const char *subkind, MPI_Datatype dt,
                  const void *operand, int operand_items, void *old_out) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (target_rank < 0 || target_rank >= (int)c.group.size())
    return MPI_ERR_ARG;
  DtInfo di;
  if (!base_dtinfo(dt, di)) return MPI_ERR_TYPE;
  // NOTE: no bounds check against w->size here — windows are per-rank
  // sized (asymmetric exposure is legal), so only the TARGET can
  // validate the displacement (apply_amo does, on both paths)
  if (disp_bytes < 0) return MPI_ERR_ARG;
  std::string sub = subkind;
  // operand_items is the ELEMENT count: cas carries [compare][value]
  // (2, one result element), fetch carries none (count = items), the
  // rest carry `items` elements and return as many
  bool is_cas = sub == "cas";
  bool is_fetch = sub == "fetch";
  if (operand_items <= 0 || (is_cas && operand_items != 2))
    return MPI_ERR_ARG;
  int payload_items = is_fetch ? 0 : operand_items;
  if (payload_items > 0 && operand == nullptr) return MPI_ERR_ARG;
  int result_items = is_cas ? 1 : operand_items;
  int tw = world_of(c, target_rank);
  if (tw == g.rank) {
    std::vector<char> old;
    if (!apply_amo(w, disp_bytes, sub, dt, (const char *)operand,
                   (size_t)payload_items * di.item, old,
                   is_fetch ? operand_items : 1))
      return MPI_ERR_ARG;
    memcpy(old_out, old.data(), (size_t)result_items * di.item);
    return MPI_SUCCESS;
  }
  int64_t rtag = g_next_reply_tag.fetch_add(1);
  Req r;
  r.is_recv = true;
  r.user_buf = old_out;
  r.count = (int)((size_t)result_items * di.item);
  DtView bv;
  bv.di = {"|u1", 1};
  int handle = post_recv(&r, bv, WIN_CID, tw, rtag);
  char subbuf[24];
  const char *wire_sub = sub.c_str();
  if (is_fetch) {
    snprintf(subbuf, sizeof subbuf, "fetch:%d", operand_items);
    wire_sub = subbuf;
  }
  std::string t;
  t.push_back((char)T_TUPLE);
  put_varint(t, 7);
  put_str(t, "wamo");
  put_int(t, wid);
  put_int(t, disp_bytes);
  put_str(t, wire_sub);
  put_int(t, (int64_t)dt);
  put_ndarray_1d(t, di.tag, payload_items ? operand : "",
                 (uint64_t)payload_items, di.item);
  put_int(t, rtag);
  int rc = win_send_tuple(tw, t);
  if (rc != MPI_SUCCESS) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    deregister_locked(handle, &r);
    return rc;
  }
  MPI_Status st{};
  rc = wait_handle_impl(handle, &st, g.cts_timeout);
  if (rc != MPI_SUCCESS) return rc;
  if (st._count != (long long)((size_t)result_items * di.item))
    return MPI_ERR_ARG;
  return MPI_SUCCESS;
}

/* Flush this origin's outstanding puts/accumulates on the window (an
 * ack round-trip per dirty target; per-origin FIFO proves application).
 * Exported for the C OSHMEM layer's shmem_quiet, which completes
 * without the fence's closing barrier. */
int zompi_win_flush(MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  std::vector<int> targets;
  {
    std::lock_guard<std::mutex> lk(w->dirty_mu);
    targets.assign(w->dirty.begin(), w->dirty.end());
    w->dirty.clear();
  }
  for (size_t i = 0; i < targets.size(); i++) {
    int tw = targets[i];
    if (tw == g.rank) continue;
    int64_t rtag = g_next_reply_tag.fetch_add(1);
    Req r;
    char dummy;
    r.is_recv = true;
    r.user_buf = &dummy;
    r.count = 0;
    DtView bv;
    bv.di = {"|u1", 1};
    int handle = post_recv(&r, bv, WIN_CID, tw, rtag);
    std::string t;
    t.push_back((char)T_TUPLE);
    put_varint(t, 3);
    put_str(t, "wflush");
    put_int(t, wid);
    put_int(t, rtag);
    int rc = win_send_tuple(tw, t);
    if (rc == MPI_SUCCESS) {
      MPI_Status st{};
      rc = wait_handle_impl(handle, &st, g.cts_timeout);
    } else {
      std::lock_guard<std::mutex> lk(g.match_mu);
      deregister_locked(handle, &r);
    }
    if (rc != MPI_SUCCESS) {
      // unacknowledged targets stay dirty — a later flush/fence must
      // not report completion for unconfirmed puts
      std::lock_guard<std::mutex> lk(w->dirty_mu);
      for (size_t j = i; j < targets.size(); j++)
        w->dirty.insert(targets[j]);
      return rc;
    }
  }
  return MPI_SUCCESS;
}

int MPI_Win_fence(int /*assert_*/, MPI_Win win) {
  // flush every dirty target, then close the exposure epoch collectively
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  int rc = zompi_win_flush(win);
  if (rc != MPI_SUCCESS) return rc;
  return c_barrier(w->comm);
}

void delete_win_attrs(int win);  // defined with the win tier 2 section

int MPI_Win_free(MPI_Win *win) {
  if (!win) return MPI_ERR_ARG;
  int64_t wid;
  WinObj *w = lookup_win(*win, &wid);
  if (!w) return MPI_ERR_WIN;
  // attribute delete callbacks run BEFORE the handle dies (the
  // comm_free ordering, applied to windows)
  delete_win_attrs(*win);
  release_errh_ref(g_win_errh, *win);
  // quiesce: a conforming program has fenced/unlocked, so after this
  // barrier no peer can still address the window
  int rc = c_barrier(w->comm);
  {
    std::lock_guard<std::mutex> lk(g_wins_mu);
    g_wins.erase(wid);
    g_win_handles.erase(*win);
  }
  if (w->shm) {
    munmap(w->shm_map, w->shm_len);
    if (w->comm.local_rank == 0) shm_unlink(w->shm_path.c_str());
  } else if (w->owns_base) {
    free(w->base);
  }
  delete w;
  *win = MPI_WIN_NULL;
  return rc;
}

int MPI_Win_allocate(MPI_Aint size, int disp_unit, MPI_Info info,
                     MPI_Comm comm, void *baseptr, MPI_Win *win) {
  if (size < 0 || !baseptr) return MPI_ERR_ARG;
  void *base = size ? calloc(1, (size_t)size) : nullptr;
  if (size && !base) return MPI_ERR_OTHER;
  int rc = MPI_Win_create(base, size, disp_unit, info, comm, win);
  if (rc != MPI_SUCCESS) {
    free(base);
    return rc;
  }
  lookup_win(*win)->owns_base = true;
  *(void **)baseptr = base;
  return MPI_SUCCESS;
}

// passive target (win_lock.c / the AM plane's _LockManager): the
// target's drain arbitrates grants; a self-target acquire polls the
// local manager (no fairness guarantee, per MPI).

namespace {

int win_lock_rpc(WinObj *w, int64_t wid, int tw, const std::string &kind,
                 int lock_type) {
  int64_t rtag = g_next_reply_tag.fetch_add(1);
  Req r;
  char dummy;
  r.is_recv = true;
  r.user_buf = &dummy;
  r.count = 0;
  DtView bv;
  bv.di = {"|u1", 1};
  int handle = post_recv(&r, bv, WIN_CID, tw, rtag);
  std::string t;
  t.push_back((char)T_TUPLE);
  put_varint(t, kind == "wlock" ? 4 : 3);
  put_str(t, kind);
  put_int(t, wid);
  if (kind == "wlock") put_int(t, lock_type);
  put_int(t, rtag);
  int rc = win_send_tuple(tw, t);
  if (rc != MPI_SUCCESS) {
    std::lock_guard<std::mutex> lk(g.match_mu);
    deregister_locked(handle, &r);
    return rc;
  }
  MPI_Status st{};
  // lock grants legally wait for another origin's unlock: no timeout
  return wait_handle_impl(
      handle, &st, kind == "wlock" ? -1.0 : g.cts_timeout.load());
}

}  // namespace

int MPI_Win_lock(int lock_type, int rank, int /*assert_*/, MPI_Win win) {
  if (lock_type != MPI_LOCK_EXCLUSIVE && lock_type != MPI_LOCK_SHARED)
    return MPI_ERR_ARG;
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (rank < 0 || rank >= (int)c.group.size()) return MPI_ERR_ARG;
  int tw = world_of(c, rank);
  if (tw == g.rank) {
    for (;;) {
      {
        std::lock_guard<std::mutex> lk(w->lock_mu);
        if (lock_type == MPI_LOCK_EXCLUSIVE) {
          if (w->lock_excl_holder < 0 && w->lock_shared == 0) {
            w->lock_excl_holder = g.rank;
            return MPI_SUCCESS;
          }
        } else if (w->lock_excl_holder < 0) {
          w->lock_shared++;
          return MPI_SUCCESS;
        }
      }
      std::this_thread::sleep_for(std::chrono::microseconds(200));
      if (g.closing.load()) return MPI_ERR_OTHER;
    }
  }
  return win_lock_rpc(w, wid, tw, "wlock", lock_type);
}

int MPI_Win_unlock(int rank, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (rank < 0 || rank >= (int)c.group.size()) return MPI_ERR_ARG;
  // MPI: unlock completes all ops of the epoch at origin AND target
  int rc = MPI_Win_flush(rank, win);
  if (rc != MPI_SUCCESS) return rc;
  int tw = world_of(c, rank);
  if (tw == g.rank) {
    auto grants = release_and_grants(w, g.rank);
    for (auto &gr : grants) win_reply(gr[0], gr[2], "", 0);
    return MPI_SUCCESS;
  }
  return win_lock_rpc(w, wid, tw, "wunlock", 0);
}

int MPI_Win_flush(int rank, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (rank < 0 || rank >= (int)c.group.size()) return MPI_ERR_ARG;
  int tw = world_of(c, rank);
  {
    std::lock_guard<std::mutex> lk(w->dirty_mu);
    if (!w->dirty.count(tw)) return MPI_SUCCESS;
    w->dirty.erase(tw);
  }
  if (tw == g.rank) return MPI_SUCCESS;
  int rc = win_lock_rpc(w, wid, tw, "wflush", 0);
  if (rc != MPI_SUCCESS) {
    // an unacknowledged target stays dirty: a later flush/fence/unlock
    // must not report completion for puts that were never confirmed
    std::lock_guard<std::mutex> lk(w->dirty_mu);
    w->dirty.insert(tw);
  }
  return rc;
}

int MPI_Win_flush_all(MPI_Win win) { return zompi_win_flush(win); }

int MPI_Win_get_group(MPI_Win win, MPI_Group *group) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  *group = register_group(w->comm.group);
  return MPI_SUCCESS;
}

// PSCW active-target epochs (win_post.c family; the AM plane's
// identity-checked PSCW): post/complete notifications are plain empty
// messages on WIN_CID in tag ranges disjoint from the RPC reply tags.

namespace {

constexpr int64_t PSCW_POST_BASE = 1LL << 40;
constexpr int64_t PSCW_DONE_BASE = 1LL << 41;

int pscw_notify(int tw, int64_t tag) {
  if (tw == g.rank) {
    Message m;
    m.src = g.rank;
    m.tag = tag;
    m.cid = WIN_CID;
    m.seq = g.seq++;
    push_message(std::move(m));
    return MPI_SUCCESS;
  }
  std::string f;
  put_varint(f, 5);
  put_int(f, g.rank);
  put_int(f, tag);
  put_int(f, WIN_CID);
  put_int(f, g.seq++);
  put_bytes(f, "", 0);
  return peer_send_frame(tw, f);
}

int pscw_await(int from_world, int64_t tag) {
  Req r;
  char dummy;
  r.is_recv = true;
  r.user_buf = &dummy;
  r.count = 0;
  DtView bv;
  bv.di = {"|u1", 1};
  int handle = post_recv(&r, bv, WIN_CID, from_world, tag);
  MPI_Status st{};
  return wait_handle_impl(handle, &st);  // epochs legally wait
}

}  // namespace

int MPI_Win_post(MPI_Group group, int /*assert_*/, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  if (w->pscw_post_open) return MPI_ERR_ARG;  // epoch already open
  // group_ranks handles the MPI_GROUP_EMPTY sentinel (an empty epoch
  // is legal: a rank with no partners this round, MPI-3.1 11.5.2)
  const std::vector<int> *er = group_ranks(group);
  if (!er) return MPI_ERR_GROUP;
  w->pscw_post = *er;
  w->pscw_post_open = true;
  for (int tw : w->pscw_post) {
    int rc = pscw_notify(tw, PSCW_POST_BASE + wid);
    if (rc != MPI_SUCCESS) {
      w->pscw_post.clear();  // a wedged epoch would block forever
      w->pscw_post_open = false;
      return rc;
    }
  }
  return MPI_SUCCESS;  // post never blocks (win_post.c)
}

int MPI_Win_start(MPI_Group group, int /*assert_*/, MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  if (w->pscw_start_open) return MPI_ERR_ARG;
  const std::vector<int> *sr = group_ranks(group);
  if (!sr) return MPI_ERR_GROUP;
  w->pscw_start = *sr;
  w->pscw_start_open = true;
  // access epoch opens when every target has exposed (start MAY block)
  for (int tw : w->pscw_start) {
    int rc = pscw_await(tw, PSCW_POST_BASE + wid);
    if (rc != MPI_SUCCESS) {
      // a half-open epoch would wedge the window AND let a recovery
      // complete() replay DONE into unconsumed POSTs
      w->pscw_start.clear();
      w->pscw_start_open = false;
      return rc;
    }
  }
  return MPI_SUCCESS;
}

int MPI_Win_complete(MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  if (!w->pscw_start_open) return MPI_ERR_ARG;
  // ops must be APPLIED at the targets before the completion signal.
  // The epoch closes WHATEVER happens below: leaving pscw_start set
  // would let a retry re-send DONE to targets that already got one,
  // and a stale DONE would terminate their NEXT exposure epoch early.
  int rc = zompi_win_flush(win);
  for (int tw : w->pscw_start) {
    if (rc != MPI_SUCCESS) break;  // don't signal unflushed ops
    rc = pscw_notify(tw, PSCW_DONE_BASE + wid);
  }
  w->pscw_start.clear();
  w->pscw_start_open = false;
  return rc;
}

int MPI_Win_wait(MPI_Win win) {
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  if (!w->pscw_post_open) return MPI_ERR_ARG;
  for (int ow : w->pscw_post) {
    int rc = pscw_await(ow, PSCW_DONE_BASE + wid);
    if (rc != MPI_SUCCESS) return rc;
  }
  w->pscw_post.clear();
  w->pscw_post_open = false;
  return MPI_SUCCESS;
}

int MPI_Fetch_and_op(const void *origin_addr, void *result_addr,
                     MPI_Datatype dt, int target_rank,
                     MPI_Aint target_disp, MPI_Op op, MPI_Win win) {
  // fetch_and_op.c: single-element atomic fetch+op, predefined ops plus
  // MPI_REPLACE / MPI_NO_OP — all lower onto the wamo substrate
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  if (target_rank == MPI_PROC_NULL) return MPI_SUCCESS;  // RMA no-op
  if (g_user_ops.count(op)) return MPI_ERR_OP;
  {
    int oprc = check_acc_op_pairing(dt, op);  // origin-side, like acc
    if (oprc != MPI_SUCCESS) return oprc;
  }
  int64_t disp = (int64_t)target_disp * w->disp_unit;
  const char *sub;
  char subbuf[16];
  if (op == MPI_NO_OP) sub = "fetch";
  else if (op == MPI_REPLACE) sub = "swap";
  else if (op == MPI_SUM) sub = "add";
  else {
    snprintf(subbuf, sizeof subbuf, "aop:%d", op);
    sub = subbuf;
  }
  return zompi_win_amo(win, target_rank, disp, sub, dt,
                       op == MPI_NO_OP ? nullptr : origin_addr, 1,
                       result_addr);
}

int MPI_Get_accumulate(const void *origin_addr, int origin_count,
                       MPI_Datatype origin_datatype, void *result_addr,
                       int result_count, MPI_Datatype result_datatype,
                       int target_rank, MPI_Aint target_disp,
                       int target_count, MPI_Datatype target_datatype,
                       MPI_Op op, MPI_Win win) {
  // get_accumulate.c: atomic multi-element fetch+op; the whole span is
  // read and updated under the target's window lock (the wamo
  // substrate's generalized form)
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  if (target_rank == MPI_PROC_NULL) return MPI_SUCCESS;
  if (g_user_ops.count(op)) return MPI_ERR_OP;
  DtView tv, rv;
  if (!resolve_dtype(target_datatype, tv) ||
      !resolve_dtype(result_datatype, rv))
    return MPI_ERR_TYPE;
  {
    // origin-side pairing check on the RESOLVED base (see Accumulate)
    int oprc = check_acc_op_pairing(
        tv.derived ? tv.derived->base : target_datatype, op);
    if (oprc != MPI_SUCCESS) return oprc;
  }
  if (!tv.contiguous()) return MPI_ERR_TYPE;  // see MPI_Put
  MPI_Datatype base_dt = tv.derived ? tv.derived->base : target_datatype;
  DtInfo di;
  if (!base_dtinfo(base_dt, di)) return MPI_ERR_TYPE;
  int64_t nelems = (int64_t)target_count * tv.elems_per_item();
  if (nelems == 0) return MPI_SUCCESS;  // zero-count no-op, like Put
  size_t nbytes = (size_t)nelems * di.item;
  if (nbytes > 0x7FFFFFFFull) return MPI_ERR_COUNT;  // int request count
  if ((size_t)result_count * rv.elems_per_item() * rv.di.item != nbytes)
    return MPI_ERR_TRUNCATE;
  int64_t disp = (int64_t)target_disp * w->disp_unit;
  std::vector<char> origin;
  const char *sub;
  char subbuf[16];
  if (op == MPI_NO_OP) {
    sub = "fetch";
  } else {
    DtInfo odi;
    int rc = pack_origin(origin_addr, origin_count, origin_datatype,
                         origin, odi);
    if (rc != MPI_SUCCESS) return rc;
    if (origin.size() != nbytes) return MPI_ERR_TRUNCATE;
    if (op == MPI_REPLACE) sub = "swap";
    else if (op == MPI_SUM) sub = "add";
    else {
      snprintf(subbuf, sizeof subbuf, "aop:%d", op);
      sub = subbuf;
    }
  }
  std::vector<char> old(nbytes);
  int rc = zompi_win_amo(win, target_rank, disp, sub, base_dt,
                         op == MPI_NO_OP ? nullptr : origin.data(),
                         (int)nelems, old.data());
  if (rc != MPI_SUCCESS) return rc;
  if (rv.contiguous()) memcpy(result_addr, old.data(), nbytes);
  else unpack_dtype(result_addr, result_count, rv, old.data(), nbytes);
  return MPI_SUCCESS;
}

int MPI_Compare_and_swap(const void *origin_addr, const void *compare_addr,
                         void *result_addr, MPI_Datatype dt,
                         int target_rank, MPI_Aint target_disp,
                         MPI_Win win) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  if (target_rank == MPI_PROC_NULL) return MPI_SUCCESS;  // RMA no-op
  DtInfo di;
  if (!base_dtinfo(dt, di)) return MPI_ERR_TYPE;
  std::vector<char> opnd(2 * di.item);
  memcpy(opnd.data(), compare_addr, di.item);
  memcpy(opnd.data() + di.item, origin_addr, di.item);
  int64_t disp = (int64_t)target_disp * w->disp_unit;
  return zompi_win_amo(win, target_rank, disp, "cas", dt, opnd.data(), 2,
                       result_addr);
}

// ----------------------------------------------- utilities (round 5)
// Versions/threads, error classes, memory, local reduction, request
// and status utilities, Fortran handle conversion.  Reference bindings:
// get_version.c, init_thread.c, add_error_class.c, alloc_mem.c,
// reduce_local.c, request_get_status.c, waitsome.c, cancel.c,
// sendrecv_replace.c, comm_c2f.c et al.

int MPI_Get_version(int *version, int *subversion) {
  *version = MPI_VERSION;
  *subversion = MPI_SUBVERSION;
  return MPI_SUCCESS;
}

int MPI_Get_library_version(char *version, int *resultlen) {
  snprintf(version, MPI_MAX_LIBRARY_VERSION_STRING,
           "zhpe-ompi-tpu C shim (mpi.h-compatible host plane), "
           "MPI %d.%d surface", MPI_VERSION, MPI_SUBVERSION);
  *resultlen = (int)strlen(version);
  return MPI_SUCCESS;
}

int MPI_Init_thread(int *argc, char ***argv, int required, int *provided) {
  // the engine's internal locks serialize the matching/send paths;
  // SERIALIZED is the honest ceiling (init_thread.c's shape: provided
  // = min(required, ceiling))
  int ceiling = MPI_THREAD_SERIALIZED;
  int rc = MPI_Init(argc, argv);
  if (rc != MPI_SUCCESS) return rc;
  g_thread_level = required < ceiling ? required : ceiling;
  if (g_thread_level < MPI_THREAD_SINGLE)
    g_thread_level = MPI_THREAD_SINGLE;
  if (provided) *provided = g_thread_level;
  extern void build_env_info_hook(void);
  build_env_info_hook();  // the snapshot's thread_level key moved
  return MPI_SUCCESS;
}

int MPI_Query_thread(int *provided) {
  *provided = g_thread_level;
  return MPI_SUCCESS;
}

int MPI_Is_thread_main(int *flag) {
  *flag = std::this_thread::get_id() == g_main_tid ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Finalized(int *flag) {
  *flag = g_finalized_flag ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Error_class(int errorcode, int *errorclass) {
  auto it = g_err_class.find(errorcode);
  *errorclass = it != g_err_class.end() ? it->second : errorcode;
  return MPI_SUCCESS;
}

int MPI_Add_error_class(int *errorclass) {
  int c = g_next_err++;
  g_err_class[c] = c;
  *errorclass = c;
  return MPI_SUCCESS;
}

int MPI_Add_error_code(int errorclass, int *errorcode) {
  int c = g_next_err++;
  g_err_class[c] = errorclass;
  *errorcode = c;
  return MPI_SUCCESS;
}

int MPI_Add_error_string(int errorcode, const char *string) {
  g_err_strings[errorcode] = string ? string : "";
  return MPI_SUCCESS;
}

int MPI_Alloc_mem(MPI_Aint size, MPI_Info, void *baseptr) {
  if (size < 0) return MPI_ERR_ARG;
  void *p = malloc(size ? (size_t)size : 1);
  if (!p) return MPI_ERR_OTHER;
  *(void **)baseptr = p;
  return MPI_SUCCESS;
}

int MPI_Free_mem(void *base) {
  free(base);
  return MPI_SUCCESS;
}

int MPI_Get_address(const void *location, MPI_Aint *address) {
  *address = (MPI_Aint)(uintptr_t)location;
  return MPI_SUCCESS;
}

int MPI_Address(void *location, MPI_Aint *address) {
  return MPI_Get_address(location, address);
}

MPI_Aint MPI_Aint_add(MPI_Aint base, MPI_Aint disp) {
  // aint_add.c: defined in terms of char* arithmetic
  return (MPI_Aint)(uintptr_t)((char *)(uintptr_t)base + disp);
}

MPI_Aint MPI_Aint_diff(MPI_Aint addr1, MPI_Aint addr2) {
  return (MPI_Aint)((char *)(uintptr_t)addr1 -
                    (char *)(uintptr_t)addr2);
}

int MPI_Op_commutative(MPI_Op op, int *commute) {
  auto uit = g_user_ops.find(op);
  if (uit != g_user_ops.end()) {
    *commute = uit->second.commute ? 1 : 0;
    return MPI_SUCCESS;
  }
  if (op < 0 || op > MPI_NO_OP) return MPI_ERR_OP;
  *commute = 1;  // every predefined op here is commutative
  return MPI_SUCCESS;
}

int MPI_Reduce_local(const void *inbuf, void *inoutbuf, int count,
                     MPI_Datatype dt, MPI_Op op) {
  // reduce_local.c: inout = in (op) inout, invec the LEFT operand
  if (count < 0) return MPI_ERR_COUNT;
  auto uit = g_user_ops.find(op);
  if (uit != g_user_ops.end()) {
    // exactly the user-function contract — no copies needed
    int len = count;
    MPI_Datatype d = dt;
    uit->second.fn((void *)inbuf, inoutbuf, &len, &d);
    return MPI_SUCCESS;
  }
  // predefined ops are commutative, so acc-left reduce_buf matches
  return reduce_buf(inoutbuf, inbuf, count, dt, op);
}

int MPI_Request_get_status(MPI_Request request, int *flag,
                           MPI_Status *status) {
  // request_get_status.c: non-destructive completion query — the
  // request is neither freed nor deactivated
  if (request == MPI_REQUEST_NULL) {
    *flag = 1;
    empty_status(status);
    return MPI_SUCCESS;
  }
  int inner = request;
  if (request < MPI_REQUEST_NULL) {
    auto pit = g_persistent.find(-request);
    if (pit == g_persistent.end()) return MPI_ERR_REQUEST;
    if (pit->second.active == MPI_REQUEST_NULL) {
      MPI_Request nullr = MPI_REQUEST_NULL;
      return MPI_Request_get_status(nullr, flag, status);
    }
    inner = pit->second.active;
  }
  Req *r;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    auto it = g.reqs.find(inner);
    if (it == g.reqs.end()) return MPI_ERR_REQUEST;
    r = it->second;
    if (!r->complete) {
      *flag = 0;
      return MPI_SUCCESS;
    }
  }
  // The operation is being REPORTED complete, so the receive buffer
  // must be usable now: run the derived-type unpack (idempotent; the
  // later Wait/Test sees needs_unpack already cleared).  Outside
  // match_mu — a multi-MB unpack must not stall the matching threads —
  // which is safe at the declared MPI_THREAD_SERIALIZED level: only
  // the (single) app thread completes requests, so `r` cannot be
  // Wait-freed concurrently.
  finish_recv(r);
  *flag = 1;
  if (status) {
    *status = r->status;
    translate_status(lookup_comm(r->comm), status);
  }
  return MPI_SUCCESS;
}

namespace {

// one completion sweep shared by Waitsome/Testsome: harvest every
// currently-complete ACTIVE request, Wait-ing each to run its normal
// retire path.  Null handles and inactive persistent handles do not
// participate (waitsome.c: outcount is MPI_UNDEFINED when no handle is
// active).  *any_active reports whether an active handle exists; on an
// error mid-harvest, *outcount counts only the fully-retired entries,
// so indices/statuses[0..outcount) are always valid.  match_mu must
// NOT be held.
int harvest_some(int incount, MPI_Request requests[], int *outcount,
                 int indices[], MPI_Status statuses[], bool *any_active) {
  std::vector<int> ready;
  *any_active = false;
  *outcount = 0;  // defined even on an early MPI_ERR_REQUEST return
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (int i = 0; i < incount; i++) {
      MPI_Request h = requests[i];
      if (h == MPI_REQUEST_NULL) continue;
      int inner = h;
      if (h < MPI_REQUEST_NULL) {
        auto pit = g_persistent.find(-h);
        if (pit == g_persistent.end()) return MPI_ERR_REQUEST;
        if (pit->second.active == MPI_REQUEST_NULL)
          continue;  // inactive persistent: not a participant
        inner = pit->second.active;
      }
      auto it = g.reqs.find(inner);
      if (it == g.reqs.end()) return MPI_ERR_REQUEST;
      *any_active = true;
      if (it->second->complete) ready.push_back(i);
    }
  }
  int first_err = MPI_SUCCESS;
  for (size_t k = 0; k < ready.size(); k++) {
    indices[k] = ready[k];
    MPI_Status tmp;
    MPI_Status *sp = statuses ? &statuses[k] : &tmp;
    int rc = MPI_Wait(&requests[ready[k]], sp);
    *outcount = (int)k + 1;  // the completion is REPORTED even on error
    if (rc != MPI_SUCCESS) {
      // waitsome.c contract: per-request failures surface as
      // MPI_ERR_IN_STATUS with the code in statuses[k].MPI_ERROR; the
      // harvest continues so no completed request is silently lost
      sp->MPI_ERROR = rc;
      if (first_err == MPI_SUCCESS) first_err = MPI_ERR_IN_STATUS;
    }
  }
  return first_err;
}

}  // namespace

int MPI_Waitsome(int incount, MPI_Request requests[], int *outcount,
                 int indices[], MPI_Status statuses[]) {
  while (true) {
    bool any_active = false;
    int rc = harvest_some(incount, requests, outcount, indices, statuses,
                          &any_active);
    if (rc != MPI_SUCCESS) return rc;
    if (!any_active) {
      *outcount = MPI_UNDEFINED;
      return MPI_SUCCESS;
    }
    if (*outcount > 0) return MPI_SUCCESS;
    std::unique_lock<std::mutex> lk(g.match_mu);
    g.match_cv.wait_for(lk, std::chrono::milliseconds(100));
    if (g.closing.load()) return MPI_ERR_OTHER;
  }
}

int MPI_Testsome(int incount, MPI_Request requests[], int *outcount,
                 int indices[], MPI_Status statuses[]) {
  bool any_active = false;
  int rc = harvest_some(incount, requests, outcount, indices, statuses,
                        &any_active);
  if (rc != MPI_SUCCESS) return rc;
  if (!any_active) *outcount = MPI_UNDEFINED;
  return MPI_SUCCESS;
}

int MPI_Cancel(MPI_Request *request) {
  // cancel.c semantics, reduced to the deterministically-cancellable
  // case: an UNMATCHED posted receive is withdrawn and completes with
  // the cancelled bit; anything else (sends, matched receives) is left
  // to complete normally — MPI_Test_cancelled then reports false,
  // which is a legal outcome of MPI_Cancel
  if (!request || *request == MPI_REQUEST_NULL) return MPI_ERR_REQUEST;
  if (*request < MPI_REQUEST_NULL) return MPI_ERR_REQUEST;
  std::lock_guard<std::mutex> lk(g.match_mu);
  auto it = g.reqs.find(*request);
  if (it == g.reqs.end()) return MPI_ERR_REQUEST;
  Req *r = it->second;
  if (!r->is_recv || r->complete) return MPI_SUCCESS;
  for (auto pit = g.posted.begin(); pit != g.posted.end(); ++pit) {
    if (pit->req == r) {
      g.posted.erase(pit);
      r->status.MPI_SOURCE = MPI_ANY_SOURCE;
      r->status.MPI_TAG = MPI_ANY_TAG;
      r->status.MPI_ERROR = MPI_SUCCESS;
      r->status._count = 0;
      r->status._cancelled = 1;
      r->complete = true;
      g.match_cv.notify_all();
      return MPI_SUCCESS;
    }
  }
  return MPI_SUCCESS;  // matched already (e.g. parked rendezvous)
}

int MPI_Test_cancelled(const MPI_Status *status, int *flag) {
  *flag = status->_cancelled ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Status_set_cancelled(MPI_Status *status, int flag) {
  status->_cancelled = flag ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Get_elements_x(const MPI_Status *status, MPI_Datatype dt,
                       MPI_Count *count) {
  // get_elements.c: BASIC-element count, partial items included —
  // _count carries wire bytes of packed base elements.  A pair record
  // holds TWO basic elements (value + index), MPI-3.1 §5.9.4.
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  if (v.di.item == 0) return MPI_ERR_TYPE;
  MPI_Datatype base = v.derived ? v.derived->base : dt;
  long long units = status->_count / (long long)v.di.item;
  if (is_pair_dtype(base)) {
    // 2 basics per record; a half-record remainder counts as 1 (the
    // set_elements inverse stores count*item/2 bytes, so odd counts
    // round-trip exactly)
    long long rem = status->_count % (long long)v.di.item;
    *count = (MPI_Count)(units * 2 + (rem > 0 ? 1 : 0));
  } else {
    *count = (MPI_Count)units;
  }
  return MPI_SUCCESS;
}

int MPI_Get_elements(const MPI_Status *status, MPI_Datatype dt,
                     int *count) {
  MPI_Count n;
  int rc = MPI_Get_elements_x(status, dt, &n);
  if (rc != MPI_SUCCESS) return rc;
  *count = n > 2147483647LL ? MPI_UNDEFINED : (int)n;
  return MPI_SUCCESS;
}

int MPI_Status_set_elements_x(MPI_Status *status, MPI_Datatype dt,
                              MPI_Count count) {
  // status_set_elements.c contract: a subsequent Get_elements returns
  // EXACTLY `count` — for pair types that means count BASIC elements
  // (2 per record), so store half an item per basic
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  MPI_Datatype base = v.derived ? v.derived->base : dt;
  if (is_pair_dtype(base))
    status->_count = (long long)count * (long long)v.di.item / 2;
  else
    status->_count = (long long)count * (long long)v.di.item;
  return MPI_SUCCESS;
}

int MPI_Status_set_elements(MPI_Status *status, MPI_Datatype dt,
                            int count) {
  return MPI_Status_set_elements_x(status, dt, (MPI_Count)count);
}

int MPI_Sendrecv_replace(void *buf, int count, MPI_Datatype dt, int dest,
                         int sendtag, int source, int recvtag,
                         MPI_Comm comm, MPI_Status *status) {
  // sendrecv_replace.c: snapshot the full extent region, post the
  // receive into the user buffer, send from the snapshot (same
  // typemap, so layout is preserved), then wait both
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  DtView v;
  if (!resolve_dtype(dt, v)) return MPI_ERR_TYPE;
  // pack touches only typemap bytes — a raw extent-sized memcpy would
  // overread the trailing gap of a strided type (a conforming buffer
  // may end at the last typemap byte).  The wire carries packed base
  // elements for any send, so the snapshot goes out as base elements
  // directly: identical bytes to sending `buf` with `dt`.
  std::vector<char> packed;
  pack_dtype(buf, count, v, packed);
  MPI_Datatype base_dt = v.derived ? v.derived->base : dt;
  int base_elems = (int)((int64_t)count * v.elems_per_item());
  MPI_Request rreq;
  int rc = MPI_Irecv(buf, count, dt, source, recvtag, comm, &rreq);
  if (rc != MPI_SUCCESS) return rc;
  rc = MPI_Send(packed.data(), base_elems, base_dt, dest, sendtag, comm);
  if (rc != MPI_SUCCESS) {
    // never leave a posted receive aimed at the caller's buffer: a
    // later matching message would land in a dead stack frame
    MPI_Cancel(&rreq);
    MPI_Wait(&rreq, MPI_STATUS_IGNORE);
    return rc;
  }
  return MPI_Wait(&rreq, status);
}

int MPI_Pcontrol(const int, ...) { return MPI_SUCCESS; }

MPI_Fint MPI_Comm_c2f(MPI_Comm comm) { return (MPI_Fint)comm; }
MPI_Comm MPI_Comm_f2c(MPI_Fint comm) { return (MPI_Comm)comm; }
MPI_Fint MPI_Type_c2f(MPI_Datatype dt) { return (MPI_Fint)dt; }
MPI_Datatype MPI_Type_f2c(MPI_Fint dt) { return (MPI_Datatype)dt; }
MPI_Fint MPI_Group_c2f(MPI_Group group) { return (MPI_Fint)group; }
MPI_Group MPI_Group_f2c(MPI_Fint group) { return (MPI_Group)group; }
MPI_Fint MPI_Op_c2f(MPI_Op op) { return (MPI_Fint)op; }
MPI_Op MPI_Op_f2c(MPI_Fint op) { return (MPI_Op)op; }
MPI_Fint MPI_Request_c2f(MPI_Request request) { return (MPI_Fint)request; }
MPI_Request MPI_Request_f2c(MPI_Fint request) {
  return (MPI_Request)request;
}
MPI_Fint MPI_Win_c2f(MPI_Win win) { return (MPI_Fint)win; }
MPI_Win MPI_Win_f2c(MPI_Fint win) { return (MPI_Win)win; }
MPI_Fint MPI_File_c2f(MPI_File file) { return (MPI_Fint)file; }
MPI_File MPI_File_f2c(MPI_Fint file) { return (MPI_File)file; }
MPI_Fint MPI_Info_c2f(MPI_Info info) { return (MPI_Fint)info; }
MPI_Info MPI_Info_f2c(MPI_Fint info) { return (MPI_Info)info; }

int MPI_Status_c2f(const MPI_Status *c_status, MPI_Fint *f_status) {
  f_status[0] = c_status->MPI_SOURCE;
  f_status[1] = c_status->MPI_TAG;
  f_status[2] = c_status->MPI_ERROR;
  f_status[3] = (MPI_Fint)(c_status->_count & 0x7FFFFFFF);
  f_status[4] = (MPI_Fint)(c_status->_count >> 31);
  f_status[5] = c_status->_cancelled;
  return MPI_SUCCESS;
}

int MPI_Status_f2c(const MPI_Fint *f_status, MPI_Status *c_status) {
  c_status->MPI_SOURCE = f_status[0];
  c_status->MPI_TAG = f_status[1];
  c_status->MPI_ERROR = f_status[2];
  c_status->_count =
      (long long)f_status[3] | ((long long)f_status[4] << 31);
  c_status->_cancelled = f_status[5];
  return MPI_SUCCESS;
}

// --------------------------------------------- win tier 2 (round 5)
// win_lock_all.c, win_sync.c, win_test.c, win_create_dynamic.c,
// win_allocate_shared.c, win_create_keyval.c families.

int MPI_Win_lock_all(int /*assert_*/, MPI_Win win) {
  // a shared lock on every member: shared grants coexist, so the
  // rank-ordered loop cannot deadlock (win_lock_all.c semantics)
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  int n = (int)w->comm.group.size();
  for (int r = 0; r < n; r++) {
    int rc = MPI_Win_lock(MPI_LOCK_SHARED, r, 0, win);
    if (rc != MPI_SUCCESS) return rc;
  }
  return MPI_SUCCESS;
}

int MPI_Win_unlock_all(MPI_Win win) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  int n = (int)w->comm.group.size();
  int first_err = MPI_SUCCESS;
  for (int r = 0; r < n; r++) {
    int rc = MPI_Win_unlock(r, win);
    if (rc != MPI_SUCCESS && first_err == MPI_SUCCESS) first_err = rc;
  }
  return first_err;
}

int MPI_Win_flush_local(int rank, MPI_Win win) {
  // every op packs its origin buffer into the wire frame AT CALL TIME
  // (pack_origin / put_ndarray_1d copies), so local completion is
  // immediate — the reference's osc_rdma distinguishes these; here
  // local-flush is a no-op by construction
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  CommObj &c = w->comm;
  if (rank < 0 || rank >= (int)c.group.size()) return MPI_ERR_ARG;
  return MPI_SUCCESS;
}

int MPI_Win_flush_local_all(MPI_Win win) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  return MPI_SUCCESS;
}

int MPI_Win_sync(MPI_Win win) {
  // win_sync.c: memory-barrier the public/private window copies; the
  // shim's window IS process memory, so a hardware fence suffices
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  std::atomic_thread_fence(std::memory_order_seq_cst);
  return MPI_SUCCESS;
}

int MPI_Win_test(MPI_Win win, int *flag) {
  // win_test.c: nonblocking Win_wait — consume whatever DONE
  // notifications have arrived; the epoch closes when all are in
  int64_t wid;
  WinObj *w = lookup_win(win, &wid);
  if (!w) return MPI_ERR_WIN;
  if (!w->pscw_post_open) return MPI_ERR_ARG;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    for (auto it = w->pscw_post.begin(); it != w->pscw_post.end();) {
      bool got = false;
      for (auto u = g.unexpected.begin(); u != g.unexpected.end(); ++u) {
        if (u->mhandle) continue;
        if (u->cid == WIN_CID && u->src == *it &&
            u->tag == PSCW_DONE_BASE + wid) {
          g.unexpected.erase(u);
          got = true;
          break;
        }
      }
      if (got) it = w->pscw_post.erase(it);
      else ++it;
    }
  }
  if (w->pscw_post.empty()) {
    w->pscw_post_open = false;
    *flag = 1;
  } else {
    *flag = 0;
  }
  return MPI_SUCCESS;
}

int MPI_Win_create_dynamic(MPI_Info info, MPI_Comm comm, MPI_Win *win) {
  // win_create_dynamic.c: no storage at creation; Win_attach exposes
  // regions, target_disp addresses absolute bytes (win_dst validates)
  int rc = MPI_Win_create(nullptr, 0, 1, info, comm, win);
  if (rc != MPI_SUCCESS) return rc;
  lookup_win(*win)->dynamic = true;
  return MPI_SUCCESS;
}

int MPI_Win_attach(MPI_Win win, void *base, MPI_Aint size) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  if (!w->dynamic) return MPI_ERR_WIN;
  if (!base || size < 0) return MPI_ERR_ARG;
  std::lock_guard<std::mutex> lk(w->attach_mu);
  w->attached.push_back({(uint64_t)(uintptr_t)base, (uint64_t)size});
  return MPI_SUCCESS;
}

int MPI_Win_detach(MPI_Win win, const void *base) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  if (!w->dynamic) return MPI_ERR_WIN;
  std::lock_guard<std::mutex> lk(w->attach_mu);
  for (auto it = w->attached.begin(); it != w->attached.end(); ++it)
    if (it->first == (uint64_t)(uintptr_t)base) {
      w->attached.erase(it);
      return MPI_SUCCESS;
    }
  return MPI_ERR_ARG;
}

int MPI_Win_allocate_shared(MPI_Aint size, int disp_unit, MPI_Info info,
                            MPI_Comm comm, void *baseptr, MPI_Win *win) {
  // win_allocate_shared.c: one POSIX shm segment per window, every
  // member maps the whole thing; rank r's slice starts at the sum of
  // earlier sizes.  Requires same-host members (Comm_split_type's
  // SHARED comm is the intended parent).
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (size < 0 || disp_unit <= 0 || !baseptr) return MPI_ERR_ARG;
  int n = (int)c->group.size();
  std::vector<int64_t> mine = {(int64_t)size, (int64_t)disp_unit};
  std::vector<int64_t> all(2 * (size_t)n);
  int rc = c_allgather(*c, mine.data(), 2, MPI_LONG, all.data(), 2,
                       MPI_LONG);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<int64_t> sizes((size_t)n), offsets((size_t)n);
  std::vector<int> units((size_t)n);
  int64_t total = 0;
  for (int r = 0; r < n; r++) {
    sizes[(size_t)r] = all[2 * (size_t)r];
    units[(size_t)r] = (int)all[2 * (size_t)r + 1];
    offsets[(size_t)r] = total;
    total += sizes[(size_t)r];
  }
  // deterministic segment name: every member computes the same (the
  // same collapse as the wire win-id)
  char path[128];
  snprintf(path, sizeof path, "/zompi_shm_%s_%llx_%llu", session_tag(),
           (unsigned long long)c->cid_pt2pt,
           (unsigned long long)c->win_seq);
  size_t map_len = total > 0 ? (size_t)total : 1;
  int fd;
  if (c->local_rank == 0) {
    shm_unlink(path);  // stale segment from a crashed job
    fd = shm_open(path, O_CREAT | O_RDWR, 0600);
    if (fd >= 0 && ftruncate(fd, (off_t)map_len) != 0) {
      close(fd);
      fd = -1;
    }
    rc = c_barrier(*c);  // segment exists before peers open
  } else {
    rc = c_barrier(*c);
    fd = rc == MPI_SUCCESS ? shm_open(path, O_RDWR, 0600) : -1;
  }
  char *map = (char *)MAP_FAILED;
  if (fd >= 0) {
    map = (char *)mmap(nullptr, map_len, PROT_READ | PROT_WRITE,
                       MAP_SHARED, fd, 0);
    close(fd);
  }
  // agree on success: a lone failing rank (ENOSPC truncate, failed
  // open/mmap) must produce a UNIFORM error, never a half-entered
  // Win_create barrier (collective hang)
  int64_t ok = (rc == MPI_SUCCESS && fd >= 0 && map != MAP_FAILED)
                   ? 1 : 0;
  std::vector<int64_t> oks((size_t)n);
  int rc2 = c_allgather(*c, &ok, 1, MPI_LONG, oks.data(), 1, MPI_LONG);
  bool all_ok = rc2 == MPI_SUCCESS;
  for (int r = 0; all_ok && r < n; r++) all_ok = oks[(size_t)r] == 1;
  if (!all_ok) {
    if (map != MAP_FAILED) munmap(map, map_len);
    if (c->local_rank == 0) shm_unlink(path);
    return MPI_ERR_OTHER;
  }
  char *my_base = map + offsets[(size_t)c->local_rank];
  rc = MPI_Win_create(size ? my_base : nullptr, size, disp_unit, info,
                      comm, win);
  if (rc != MPI_SUCCESS) {
    munmap(map, map_len);
    return rc;
  }
  WinObj *w = lookup_win(*win);
  w->base = my_base;  // even a zero-size slice keeps its map position
  w->shm = true;
  w->shm_map = map;
  w->shm_len = map_len;
  w->shm_path = path;
  w->shm_sizes = std::move(sizes);
  w->shm_units = std::move(units);
  w->shm_offsets = std::move(offsets);
  *(void **)baseptr = my_base;
  return MPI_SUCCESS;
}

int MPI_Win_shared_query(MPI_Win win, int rank, MPI_Aint *size,
                         int *disp_unit, void *baseptr) {
  WinObj *w = lookup_win(win);
  if (!w) return MPI_ERR_WIN;
  if (!w->shm) return MPI_ERR_WIN;
  int n = (int)w->comm.group.size();
  if (rank == MPI_PROC_NULL) {
    // the lowest rank with a non-zero slice (win_shared_query.c)
    rank = 0;
    for (int r = 0; r < n; r++)
      if (w->shm_sizes[(size_t)r] > 0) {
        rank = r;
        break;
      }
  }
  if (rank < 0 || rank >= n) return MPI_ERR_ARG;
  *size = (MPI_Aint)w->shm_sizes[(size_t)rank];
  *disp_unit = w->shm_units[(size_t)rank];
  *(void **)baseptr = w->shm_map + w->shm_offsets[(size_t)rank];
  return MPI_SUCCESS;
}

// win attribute caching: the comm keyval machinery, instantiated for
// windows (the reference shares one attribute engine; two small maps
// reach the same behavior here)
struct WinKeyvalObj {
  MPI_Win_copy_attr_function *copy_fn;
  MPI_Win_delete_attr_function *delete_fn;
  void *extra_state;
  bool freed = false;
};
static std::map<int, WinKeyvalObj> g_win_keyvals;
static int g_next_win_keyval = 0;
static std::map<std::pair<int, int>, void *> g_win_attrs;

void delete_win_attrs(int win) {
  for (auto it = g_win_attrs.begin(); it != g_win_attrs.end();) {
    if (it->first.first == win) {
      auto kv = g_win_keyvals.find(it->first.second);
      if (kv != g_win_keyvals.end() && kv->second.delete_fn)
        kv->second.delete_fn(win, it->first.second, it->second,
                             kv->second.extra_state);
      it = g_win_attrs.erase(it);
    } else {
      ++it;
    }
  }
}

int MPI_Win_create_keyval(MPI_Win_copy_attr_function *copy_fn,
                          MPI_Win_delete_attr_function *delete_fn,
                          int *keyval, void *extra_state) {
  if (!keyval) return MPI_ERR_ARG;
  int kv = g_next_win_keyval++;
  g_win_keyvals[kv] = {copy_fn, delete_fn, extra_state};
  *keyval = kv;
  return MPI_SUCCESS;
}

int MPI_Win_free_keyval(int *keyval) {
  if (!keyval) return MPI_ERR_ARG;
  auto it = g_win_keyvals.find(*keyval);
  if (it == g_win_keyvals.end()) return MPI_ERR_ARG;
  it->second.freed = true;
  bool referenced = false;
  for (auto &e : g_win_attrs)
    if (e.first.second == *keyval) referenced = true;
  if (!referenced) g_win_keyvals.erase(it);
  *keyval = MPI_KEYVAL_INVALID;
  return MPI_SUCCESS;
}

int MPI_Win_set_attr(MPI_Win win, int keyval, void *attribute_val) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  auto kv = g_win_keyvals.find(keyval);
  if (kv == g_win_keyvals.end() || kv->second.freed) return MPI_ERR_ARG;
  auto it = g_win_attrs.find({win, keyval});
  if (it != g_win_attrs.end() && kv->second.delete_fn)
    kv->second.delete_fn(win, keyval, it->second, kv->second.extra_state);
  g_win_attrs[{win, keyval}] = attribute_val;
  return MPI_SUCCESS;
}

int MPI_Win_get_attr(MPI_Win win, int keyval, void *attribute_val,
                     int *flag) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  auto it = g_win_attrs.find({win, keyval});
  *flag = it != g_win_attrs.end() ? 1 : 0;
  if (*flag) *(void **)attribute_val = it->second;
  return MPI_SUCCESS;
}

int MPI_Win_delete_attr(MPI_Win win, int keyval) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  auto it = g_win_attrs.find({win, keyval});
  if (it == g_win_attrs.end()) return MPI_ERR_ARG;
  auto kv = g_win_keyvals.find(keyval);
  if (kv != g_win_keyvals.end() && kv->second.delete_fn)
    kv->second.delete_fn(win, keyval, it->second, kv->second.extra_state);
  g_win_attrs.erase(it);
  if (kv != g_win_keyvals.end() && kv->second.freed) {
    bool referenced = false;
    for (auto &e : g_win_attrs)
      if (e.first.second == keyval) referenced = true;
    if (!referenced) g_win_keyvals.erase(kv);
  }
  return MPI_SUCCESS;
}

// ------------------------------------ info objects + naming (round 5)
// info_create.c family: ordered string dictionaries (order matters for
// get_nthkey); comm/win/file carry deep COPIES (set_info snapshots,
// get_info returns a fresh dup the caller frees — MPI-3.1 §6.4.4).

struct InfoObj {
  std::vector<std::pair<std::string, std::string>> kv;
  const std::string *find(const char *key) const {
    for (auto &e : kv)
      if (e.first == key) return &e.second;
    return nullptr;
  }
};
static std::map<int, InfoObj> g_infos;
static int g_next_info = 1;  // 0 = MPI_INFO_NULL

// the MPI_INFO_ENV snapshot: built EAGERLY at the end of MPI_Init
// (wdir must be the LAUNCH directory, not wherever the app chdir'd
// before first touching the object — MPI-3.1 10.5.3), read-only after
static InfoObj g_env_info;

void build_env_info() {
  g_env_info.kv.clear();
  char buf[4096];
  ssize_t n2 = readlink("/proc/self/exe", buf, sizeof buf - 1);
  if (n2 > 0) {
    buf[n2] = '\0';
    g_env_info.kv.push_back({"command", buf});
  }
  if (getcwd(buf, sizeof buf)) g_env_info.kv.push_back({"wdir", buf});
  if (gethostname(buf, sizeof buf) == 0)
    g_env_info.kv.push_back({"host", buf});
  g_env_info.kv.push_back(
      {"maxprocs", std::to_string(g.size > 0 ? g.size : 1)});
  const char *lvl = "MPI_THREAD_SINGLE";
  if (g_thread_level >= MPI_THREAD_MULTIPLE)
    lvl = "MPI_THREAD_MULTIPLE";
  else if (g_thread_level == MPI_THREAD_SERIALIZED)
    lvl = "MPI_THREAD_SERIALIZED";
  else if (g_thread_level == MPI_THREAD_FUNNELED)
    lvl = "MPI_THREAD_FUNNELED";
  g_env_info.kv.push_back({"thread_level", lvl});
}

void build_env_info_hook(void) { build_env_info(); }

static InfoObj *lookup_info(MPI_Info h) {
  if (h == MPI_INFO_ENV) return &g_env_info;
  auto it = g_infos.find(h);
  return it == g_infos.end() ? nullptr : &it->second;
}

// object-info snapshots (comm/win handle -> copy); files carry theirs
// in a side map too so FileObj's layout stays untouched
static std::map<int, InfoObj> g_comm_info, g_win_info, g_file_info;
// object names; comm defaults seeded lazily for WORLD/SELF
static std::map<int, std::string> g_comm_names, g_type_names, g_win_names;

static int next_info_handle() {
  int h = g_next_info++;
  if (h == MPI_INFO_ENV) h = g_next_info++;  // never alias the sentinel
  return h;
}

int MPI_Info_create(MPI_Info *info) {
  int h = next_info_handle();
  g_infos[h] = InfoObj{};
  *info = h;
  return MPI_SUCCESS;
}

int MPI_Info_free(MPI_Info *info) {
  if (info && *info == MPI_INFO_ENV) return MPI_ERR_INFO;  // predefined
  if (!info || !g_infos.erase(*info)) return MPI_ERR_INFO;
  *info = MPI_INFO_NULL;
  return MPI_SUCCESS;
}

int MPI_Info_dup(MPI_Info info, MPI_Info *newinfo) {
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  int h = next_info_handle();
  g_infos[h] = *o;
  *newinfo = h;
  return MPI_SUCCESS;
}

int MPI_Info_set(MPI_Info info, const char *key, const char *value) {
  if (info == MPI_INFO_ENV) return MPI_ERR_INFO;  // read-only
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  if (!key || !*key || strlen(key) > MPI_MAX_INFO_KEY)
    return MPI_ERR_INFO_KEY;
  if (!value || strlen(value) > MPI_MAX_INFO_VAL)
    return MPI_ERR_INFO_VALUE;
  for (auto &e : o->kv)
    if (e.first == key) {
      e.second = value;
      return MPI_SUCCESS;
    }
  o->kv.push_back({key, value});
  return MPI_SUCCESS;
}

int MPI_Info_delete(MPI_Info info, const char *key) {
  if (info == MPI_INFO_ENV) return MPI_ERR_INFO;  // read-only
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  for (auto it = o->kv.begin(); it != o->kv.end(); ++it)
    if (it->first == key) {
      o->kv.erase(it);
      return MPI_SUCCESS;
    }
  return MPI_ERR_INFO_NOKEY;
}

int MPI_Info_get(MPI_Info info, const char *key, int valuelen,
                 char *value, int *flag) {
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  const std::string *v = o->find(key);
  *flag = v ? 1 : 0;
  if (v) {
    size_t n = (size_t)valuelen < v->size() ? (size_t)valuelen
                                            : v->size();
    memcpy(value, v->data(), n);
    value[n] = '\0';
  }
  return MPI_SUCCESS;
}

int MPI_Info_get_nkeys(MPI_Info info, int *nkeys) {
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  *nkeys = (int)o->kv.size();
  return MPI_SUCCESS;
}

int MPI_Info_get_nthkey(MPI_Info info, int n, char *key) {
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  if (n < 0 || n >= (int)o->kv.size()) return MPI_ERR_ARG;
  snprintf(key, MPI_MAX_INFO_KEY + 1, "%s", o->kv[n].first.c_str());
  return MPI_SUCCESS;
}

int MPI_Info_get_valuelen(MPI_Info info, const char *key, int *valuelen,
                          int *flag) {
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  const std::string *v = o->find(key);
  *flag = v ? 1 : 0;
  if (v) *valuelen = (int)v->size();
  return MPI_SUCCESS;
}

namespace {

// set_info snapshots (an INFO_NULL set clears); get_info returns a
// fresh handle the caller frees
int object_set_info(std::map<int, InfoObj> &table, int handle,
                    MPI_Info info) {
  if (info == MPI_INFO_NULL) {
    table.erase(handle);
    return MPI_SUCCESS;
  }
  InfoObj *o = lookup_info(info);
  if (!o) return MPI_ERR_INFO;
  table[handle] = *o;
  return MPI_SUCCESS;
}

int object_get_info(std::map<int, InfoObj> &table, int handle,
                    MPI_Info *info_used) {
  int h = next_info_handle();
  auto it = table.find(handle);
  g_infos[h] = it == table.end() ? InfoObj{} : it->second;
  *info_used = h;
  return MPI_SUCCESS;
}

int object_set_name(std::map<int, std::string> &table, int handle,
                    const char *name) {
  table[handle] = name ? name : "";
  return MPI_SUCCESS;
}

int object_get_name(const std::map<int, std::string> &table, int handle,
                    const std::string &fallback, char *name,
                    int *resultlen) {
  auto it = table.find(handle);
  const std::string &s = it == table.end() ? fallback : it->second;
  snprintf(name, MPI_MAX_OBJECT_NAME, "%s", s.c_str());
  *resultlen = (int)strlen(name);
  return MPI_SUCCESS;
}

const char *predefined_type_name(MPI_Datatype dt) {
  switch (dt) {
    case MPI_BYTE:           return "MPI_BYTE";
    case MPI_INT:            return "MPI_INT";
    case MPI_LONG:           return "MPI_LONG";
    case MPI_FLOAT:          return "MPI_FLOAT";
    case MPI_DOUBLE:         return "MPI_DOUBLE";
    case MPI_CHAR:           return "MPI_CHAR";
    case MPI_SIGNED_CHAR:    return "MPI_SIGNED_CHAR";
    case MPI_SHORT:          return "MPI_SHORT";
    case MPI_LONG_LONG:      return "MPI_LONG_LONG";
    case MPI_UNSIGNED_CHAR:  return "MPI_UNSIGNED_CHAR";
    case MPI_UNSIGNED_SHORT: return "MPI_UNSIGNED_SHORT";
    case MPI_UNSIGNED:       return "MPI_UNSIGNED";
    case MPI_UNSIGNED_LONG:  return "MPI_UNSIGNED_LONG";
    case MPI_2INT:           return "MPI_2INT";
    case MPI_FLOAT_INT:      return "MPI_FLOAT_INT";
    case MPI_DOUBLE_INT:     return "MPI_DOUBLE_INT";
    case MPI_LONG_INT:       return "MPI_LONG_INT";
    case MPI_SHORT_INT:      return "MPI_SHORT_INT";
  }
  return "";
}

}  // namespace

int MPI_Comm_set_name(MPI_Comm comm, const char *name) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  return object_set_name(g_comm_names, comm, name);
}

int MPI_Comm_get_name(MPI_Comm comm, char *name, int *resultlen) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  std::string fallback;
  if (comm == MPI_COMM_WORLD) fallback = "MPI_COMM_WORLD";
  else if (comm == MPI_COMM_SELF) fallback = "MPI_COMM_SELF";
  return object_get_name(g_comm_names, comm, fallback, name, resultlen);
}

int MPI_Type_set_name(MPI_Datatype dt, const char *name) {
  if (dt >= DERIVED_BASE && !g_dtypes.count(dt)) return MPI_ERR_TYPE;
  DtInfo di;
  if (dt < DERIVED_BASE && !base_dtinfo(dt, di)) return MPI_ERR_TYPE;
  return object_set_name(g_type_names, dt, name);
}

int MPI_Type_get_name(MPI_Datatype dt, char *name, int *resultlen) {
  if (dt >= DERIVED_BASE && !g_dtypes.count(dt)) return MPI_ERR_TYPE;
  DtInfo di;
  if (dt < DERIVED_BASE && !base_dtinfo(dt, di)) return MPI_ERR_TYPE;
  return object_get_name(g_type_names, dt, predefined_type_name(dt),
                         name, resultlen);
}

int MPI_Win_set_name(MPI_Win win, const char *name) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  return object_set_name(g_win_names, win, name);
}

int MPI_Win_get_name(MPI_Win win, char *name, int *resultlen) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  return object_get_name(g_win_names, win, "", name, resultlen);
}

int MPI_Comm_set_info(MPI_Comm comm, MPI_Info info) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  return object_set_info(g_comm_info, comm, info);
}

int MPI_Comm_get_info(MPI_Comm comm, MPI_Info *info_used) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  return object_get_info(g_comm_info, comm, info_used);
}

int MPI_Win_set_info(MPI_Win win, MPI_Info info) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  return object_set_info(g_win_info, win, info);
}

int MPI_Win_get_info(MPI_Win win, MPI_Info *info_used) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  return object_get_info(g_win_info, win, info_used);
}

int MPI_File_set_info(MPI_File fh, MPI_Info info) {
  if (!g_files.count(fh)) return MPI_ERR_FILE;
  return object_set_info(g_file_info, fh, info);
}

int MPI_File_get_info(MPI_File fh, MPI_Info *info_used) {
  if (!g_files.count(fh)) return MPI_ERR_FILE;
  return object_get_info(g_file_info, fh, info_used);
}

int MPI_File_get_amode(MPI_File fh, int *amode) {
  auto it = g_files.find(fh);
  if (it == g_files.end()) return MPI_ERR_FILE;
  *amode = it->second.amode;
  return MPI_SUCCESS;
}

int MPI_File_get_group(MPI_File fh, MPI_Group *group) {
  auto it = g_files.find(fh);
  if (it == g_files.end()) return MPI_ERR_FILE;
  CommObj *c = lookup_comm(it->second.comm);
  if (!c) return MPI_ERR_COMM;
  *group = register_group(c->group);
  return MPI_SUCCESS;
}

// ------------------------------------ communicator tier 2 (round 5)

int MPI_Comm_split_type(MPI_Comm comm, int split_type, int key,
                        MPI_Info, MPI_Comm *newcomm) {
  // comm_split_type.c: SHARED groups ranks that can share memory —
  // here, ranks whose modex business card names the same host.  The
  // color is the lowest parent rank on my host, so same-host members
  // agree and distinct hosts never collide.
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (split_type != MPI_COMM_TYPE_SHARED && split_type != MPI_UNDEFINED)
    return MPI_ERR_ARG;
  // MPI-3.1 §6.4.2: UNDEFINED ranks still participate in the
  // collective — they enter the allgather with a sentinel card (hosts
  // never start with '\1') so no SHARED rank can match them, then
  // split with MPI_UNDEFINED
  int n = (int)c->group.size();
  char mine[64] = {0};
  if (split_type == MPI_UNDEFINED)
    snprintf(mine, sizeof mine, "\1%d", c->local_rank);
  else
    snprintf(mine, sizeof mine, "%s",
             g.book[c->group[c->local_rank]].first.c_str());
  std::vector<char> all((size_t)n * 64);
  int rc = c_allgather(*c, mine, 64, MPI_BYTE, all.data(), 64, MPI_BYTE);
  if (rc != MPI_SUCCESS) return rc;
  int color = MPI_UNDEFINED;
  if (split_type == MPI_COMM_TYPE_SHARED)
    for (int r = 0; r < n; r++)
      if (strncmp(all.data() + (size_t)r * 64, mine, 64) == 0) {
        color = r;  // lowest parent rank sharing my host
        break;
      }
  return MPI_Comm_split(comm, color, key, newcomm);
}

// Per-(group,tag) creation sequence: members of repeated
// Comm_create_group calls with the same signature advance identically
// (mismatched sequences are erroneous usage), so the derived cids
// agree without any wire traffic — the deterministic-cid contract.
static std::map<std::pair<uint64_t, int>, uint64_t> g_ccg_seq;

int MPI_Comm_create_group(MPI_Comm comm, MPI_Group group, int tag,
                          MPI_Comm *newcomm) {
  // comm_create_group.c: collective over the GROUP only — non-members
  // do not call.  No parent-wide traffic: cids derive from (member
  // world ranks, tag, per-signature sequence).
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  GroupObj *gr = lookup_group(group);
  if (group == MPI_GROUP_EMPTY || (gr && gr->ranks.empty())) {
    *newcomm = MPI_COMM_NULL;
    return MPI_SUCCESS;
  }
  if (!gr) return MPI_ERR_GROUP;
  int my_world = c->group[c->local_rank];
  int my_idx = -1;
  for (size_t i = 0; i < gr->ranks.size(); i++)
    if (gr->ranks[i] == my_world) my_idx = (int)i;
  if (my_idx < 0) {
    *newcomm = MPI_COMM_NULL;
    return MPI_SUCCESS;
  }
  uint64_t h = 0x9E3779B97F4A7C15ULL;
  for (int r : gr->ranks) h = mix64(h ^ (uint64_t)(uint32_t)r);
  uint64_t seq = g_ccg_seq[{h, tag}]++;
  CommObj child;
  uint64_t base = mix64(h ^ mix64((uint64_t)(uint32_t)tag) ^
                        (seq * 0x100000001B3ULL) ^ 0xCC6ULL);
  base = (base & 0x3FFFFFFFFFFFULL) | 0x10000ULL;
  child.cid_pt2pt = (int64_t)base;
  child.cid_coll = (int64_t)base + 1;
  child.cid_bar = (int64_t)base + 2;
  child.group = gr->ranks;
  child.local_rank = my_idx;
  int handle = g_next_comm++;
  g_comms[handle] = child;
  *newcomm = handle;
  return MPI_SUCCESS;
}

int MPI_Comm_dup_with_info(MPI_Comm comm, MPI_Info info,
                           MPI_Comm *newcomm) {
  int rc = MPI_Comm_dup(comm, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  return MPI_Comm_set_info(*newcomm, info);
}

int MPI_Comm_idup(MPI_Comm comm, MPI_Comm *newcomm,
                  MPI_Request *request) {
  // comm_idup.c; dup is wire-free here (deterministic cids), so the
  // request is born complete
  int rc = MPI_Comm_dup(comm, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  *request = make_completed_req(comm);
  return MPI_SUCCESS;
}

int MPI_Comm_remote_group(MPI_Comm comm, MPI_Group *group) {
  CommObj *c = lookup_comm(comm);
  if (!c) return MPI_ERR_COMM;
  if (c->remote.empty()) return MPI_ERR_COMM;  // intracommunicator
  *group = register_group(c->remote);
  return MPI_SUCCESS;
}

// Finalize sweep for this section's state (called from MPI_Finalize)
void clear_info_naming_state(void) {
  g_infos.clear();
  g_next_info = 1;
  g_comm_info.clear();
  g_win_info.clear();
  g_file_info.clear();
  g_comm_names.clear();
  g_type_names.clear();
  g_win_names.clear();
  g_ccg_seq.clear();
}

// ------------------------------------------- error handlers (round 5)

int dispatch_comm_err(int comm, int code) {
  if (code == MPI_SUCCESS) return code;
  int eh = errh_of_comm(comm);
  if (eh == MPI_ERRORS_RETURN) return code;
  if (eh == MPI_ERRORS_ARE_FATAL) {
    char msg[MPI_MAX_ERROR_STRING];
    int len;
    MPI_Error_string(code, msg, &len);
    fprintf(stderr,
            "zompi: MPI_ERRORS_ARE_FATAL on comm %d: %s — aborting\n",
            comm, msg);
    _exit(code > 0 && code < 256 ? code : 1);
  }
  auto it = g_errhandlers.find(eh);
  if (it != g_errhandlers.end() && it->second.kind == 0 &&
      it->second.fn) {
    MPI_Comm c2 = comm;
    ((MPI_Comm_errhandler_function *)it->second.fn)(&c2, &code);
  }
  return code;
}

int MPI_Comm_create_errhandler(MPI_Comm_errhandler_function *fn,
                               MPI_Errhandler *errhandler) {
  int h = g_next_errh++;
  g_errhandlers[h] = {0, (void *)fn};
  *errhandler = h;
  return MPI_SUCCESS;
}

int MPI_Comm_set_errhandler(MPI_Comm comm, MPI_Errhandler errhandler) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  if (!valid_errh(errhandler, 0)) return MPI_ERR_ARG;
  release_errh_ref(g_comm_errh, comm);
  g_comm_errh[comm] = errhandler;
  return MPI_SUCCESS;
}

int MPI_Comm_get_errhandler(MPI_Comm comm, MPI_Errhandler *errhandler) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  *errhandler = errh_of_comm(comm);
  return MPI_SUCCESS;
}

int MPI_Comm_call_errhandler(MPI_Comm comm, int errorcode) {
  if (!lookup_comm(comm)) return MPI_ERR_COMM;
  dispatch_comm_err(comm, errorcode);
  return MPI_SUCCESS;
}

int MPI_Win_create_errhandler(MPI_Win_errhandler_function *fn,
                              MPI_Errhandler *errhandler) {
  int h = g_next_errh++;
  g_errhandlers[h] = {1, (void *)fn};
  *errhandler = h;
  return MPI_SUCCESS;
}

int MPI_Win_set_errhandler(MPI_Win win, MPI_Errhandler errhandler) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  if (!valid_errh(errhandler, 1)) return MPI_ERR_ARG;
  release_errh_ref(g_win_errh, win);
  g_win_errh[win] = errhandler;
  return MPI_SUCCESS;
}

int MPI_Win_get_errhandler(MPI_Win win, MPI_Errhandler *errhandler) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  auto it = g_win_errh.find(win);
  *errhandler = it != g_win_errh.end() ? it->second
                                       : MPI_ERRORS_ARE_FATAL;
  return MPI_SUCCESS;
}

int MPI_Win_call_errhandler(MPI_Win win, int errorcode) {
  if (!g_win_handles.count(win)) return MPI_ERR_WIN;
  auto it = g_win_errh.find(win);
  int eh = it != g_win_errh.end() ? it->second : MPI_ERRORS_ARE_FATAL;
  if (eh == MPI_ERRORS_RETURN) return MPI_SUCCESS;
  if (eh == MPI_ERRORS_ARE_FATAL) {
    fprintf(stderr, "zompi: MPI_ERRORS_ARE_FATAL on win %d: %d\n", win,
            errorcode);
    _exit(errorcode > 0 && errorcode < 256 ? errorcode : 1);
  }
  auto uh = g_errhandlers.find(eh);
  if (uh != g_errhandlers.end() && uh->second.kind == 1 &&
      uh->second.fn) {
    MPI_Win w2 = win;
    ((MPI_Win_errhandler_function *)uh->second.fn)(&w2, &errorcode);
  }
  return MPI_SUCCESS;
}

int MPI_File_create_errhandler(MPI_File_errhandler_function *fn,
                               MPI_Errhandler *errhandler) {
  int h = g_next_errh++;
  g_errhandlers[h] = {2, (void *)fn};
  *errhandler = h;
  return MPI_SUCCESS;
}

int MPI_File_set_errhandler(MPI_File file, MPI_Errhandler errhandler) {
  if (!g_files.count(file)) return MPI_ERR_FILE;
  if (!valid_errh(errhandler, 2)) return MPI_ERR_ARG;
  release_errh_ref(g_file_errh, file);
  g_file_errh[file] = errhandler;
  return MPI_SUCCESS;
}

int MPI_File_get_errhandler(MPI_File file, MPI_Errhandler *errhandler) {
  if (!g_files.count(file)) return MPI_ERR_FILE;
  auto it = g_file_errh.find(file);
  // files default to ERRORS_RETURN (MPI-3.1 §13.7)
  *errhandler = it != g_file_errh.end() ? it->second
                                        : MPI_ERRORS_RETURN;
  return MPI_SUCCESS;
}

int MPI_File_call_errhandler(MPI_File file, int errorcode) {
  if (!g_files.count(file)) return MPI_ERR_FILE;
  auto it = g_file_errh.find(file);
  int eh = it != g_file_errh.end() ? it->second : MPI_ERRORS_RETURN;
  if (eh == MPI_ERRORS_RETURN) return MPI_SUCCESS;
  if (eh == MPI_ERRORS_ARE_FATAL) {
    fprintf(stderr, "zompi: MPI_ERRORS_ARE_FATAL on file %d: %d\n",
            file, errorcode);
    _exit(errorcode > 0 && errorcode < 256 ? errorcode : 1);
  }
  auto uh = g_errhandlers.find(eh);
  if (uh != g_errhandlers.end() && uh->second.kind == 2 &&
      uh->second.fn) {
    MPI_File f2 = file;
    ((MPI_File_errhandler_function *)uh->second.fn)(&f2, &errorcode);
  }
  return MPI_SUCCESS;
}

int MPI_Errhandler_free(MPI_Errhandler *errhandler) {
  if (!errhandler) return MPI_ERR_ARG;
  if (*errhandler >= 0x10) {
    auto it = g_errhandlers.find(*errhandler);
    if (it == g_errhandlers.end()) return MPI_ERR_ARG;
    // stays in effect until the last referencing object detaches
    it->second.freed = true;
    reap_errh(*errhandler);
  }
  *errhandler = MPI_ERRHANDLER_NULL;
  return MPI_SUCCESS;
}

int MPI_Errhandler_create(MPI_Handler_function *fn,
                          MPI_Errhandler *errhandler) {
  return MPI_Comm_create_errhandler(fn, errhandler);
}

int MPI_Errhandler_set(MPI_Comm comm, MPI_Errhandler errhandler) {
  return MPI_Comm_set_errhandler(comm, errhandler);
}

int MPI_Errhandler_get(MPI_Comm comm, MPI_Errhandler *errhandler) {
  return MPI_Comm_get_errhandler(comm, errhandler);
}

MPI_Fint MPI_Errhandler_c2f(MPI_Errhandler errhandler) {
  return (MPI_Fint)errhandler;
}
MPI_Errhandler MPI_Errhandler_f2c(MPI_Fint errhandler) {
  return (MPI_Errhandler)errhandler;
}

// -------------------------------------- batch-8 surface (round 5)
// group_range_incl.c, attr_put.c (MPI-1 names), type_create_keyval.c,
// rput.c, pack_external.c, type_match_size.c, grequest_start.c.

int MPI_Group_compare(MPI_Group group1, MPI_Group group2, int *result) {
  const std::vector<int> *a = group_ranks(group1);
  const std::vector<int> *b = group_ranks(group2);
  if (!a || !b) return MPI_ERR_GROUP;
  if (*a == *b) {
    *result = MPI_IDENT;
    return MPI_SUCCESS;
  }
  std::vector<int> sa(*a), sb(*b);
  std::sort(sa.begin(), sa.end());
  std::sort(sb.begin(), sb.end());
  *result = sa == sb ? MPI_SIMILAR : MPI_UNEQUAL;
  return MPI_SUCCESS;
}

namespace {

// expand (first,last,stride) triplets into group ranks
// (group_range_incl.c's triplet semantics; negative strides walk down)
int expand_ranges(const std::vector<int> &src, int n, int ranges[][3],
                  std::vector<int> &out) {
  for (int i = 0; i < n; i++) {
    int first = ranges[i][0], last = ranges[i][1], stride = ranges[i][2];
    if (stride == 0) return MPI_ERR_ARG;
    if (stride > 0 ? first > last : first < last) return MPI_ERR_ARG;
    for (int r = first; stride > 0 ? r <= last : r >= last;
         r += stride) {
      if (r < 0 || r >= (int)src.size()) return MPI_ERR_ARG;
      out.push_back(r);
    }
  }
  return MPI_SUCCESS;
}

}  // namespace

int MPI_Group_range_incl(MPI_Group group, int n, int ranges[][3],
                         MPI_Group *newgroup) {
  const std::vector<int> *src = group_ranks(group);
  if (!src) return MPI_ERR_GROUP;
  std::vector<int> picks;
  int rc = expand_ranges(*src, n, ranges, picks);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<int> ranks;
  for (int r : picks) ranks.push_back((*src)[(size_t)r]);
  *newgroup = ranks.empty() ? MPI_GROUP_EMPTY : register_group(ranks);
  return MPI_SUCCESS;
}

int MPI_Group_range_excl(MPI_Group group, int n, int ranges[][3],
                         MPI_Group *newgroup) {
  const std::vector<int> *src = group_ranks(group);
  if (!src) return MPI_ERR_GROUP;
  std::vector<int> picks;
  int rc = expand_ranges(*src, n, ranges, picks);
  if (rc != MPI_SUCCESS) return rc;
  std::vector<bool> drop(src->size(), false);
  for (int r : picks) drop[(size_t)r] = true;
  std::vector<int> ranks;
  for (size_t i = 0; i < src->size(); i++)
    if (!drop[i]) ranks.push_back((*src)[i]);
  *newgroup = ranks.empty() ? MPI_GROUP_EMPTY : register_group(ranks);
  return MPI_SUCCESS;
}

// MPI-1 attribute names: straight aliases of the comm attribute engine
int MPI_Keyval_create(MPI_Copy_function *copy_fn,
                      MPI_Delete_function *delete_fn, int *keyval,
                      void *extra_state) {
  return MPI_Comm_create_keyval(copy_fn, delete_fn, keyval, extra_state);
}
int MPI_Keyval_free(int *keyval) { return MPI_Comm_free_keyval(keyval); }
int MPI_Attr_put(MPI_Comm comm, int keyval, void *attribute_val) {
  return MPI_Comm_set_attr(comm, keyval, attribute_val);
}
int MPI_Attr_get(MPI_Comm comm, int keyval, void *attribute_val,
                 int *flag) {
  return MPI_Comm_get_attr(comm, keyval, attribute_val, flag);
}
int MPI_Attr_delete(MPI_Comm comm, int keyval) {
  return MPI_Comm_delete_attr(comm, keyval);
}

// datatype attribute caching: the comm keyval machinery instantiated
// for datatypes (as with windows)
struct TypeKeyvalObj {
  MPI_Type_copy_attr_function *copy_fn;
  MPI_Type_delete_attr_function *delete_fn;
  void *extra_state;
  bool freed = false;
};
static std::map<int, TypeKeyvalObj> g_type_keyvals;
static int g_next_type_keyval = 0;
static std::map<std::pair<int, int>, void *> g_type_attrs;

void reap_type_keyval(int keyval) {
  auto kv = g_type_keyvals.find(keyval);
  if (kv == g_type_keyvals.end() || !kv->second.freed) return;
  for (auto &e : g_type_attrs)
    if (e.first.second == keyval) return;
  g_type_keyvals.erase(kv);  // deferred free completes here
}

void delete_type_attrs(MPI_Datatype dt) {
  for (auto it = g_type_attrs.begin(); it != g_type_attrs.end();) {
    if (it->first.first == dt) {
      int kvid = it->first.second;
      auto kv = g_type_keyvals.find(kvid);
      if (kv != g_type_keyvals.end() && kv->second.delete_fn)
        kv->second.delete_fn(dt, kvid, it->second,
                             kv->second.extra_state);
      it = g_type_attrs.erase(it);
      reap_type_keyval(kvid);
    } else {
      ++it;
    }
  }
}

int MPI_Type_create_keyval(MPI_Type_copy_attr_function *copy_fn,
                           MPI_Type_delete_attr_function *delete_fn,
                           int *keyval, void *extra_state) {
  if (!keyval) return MPI_ERR_ARG;
  int kv = g_next_type_keyval++;
  g_type_keyvals[kv] = {copy_fn, delete_fn, extra_state};
  *keyval = kv;
  return MPI_SUCCESS;
}

int MPI_Type_free_keyval(int *keyval) {
  if (!keyval) return MPI_ERR_ARG;
  auto it = g_type_keyvals.find(*keyval);
  if (it == g_type_keyvals.end()) return MPI_ERR_ARG;
  it->second.freed = true;
  bool referenced = false;
  for (auto &e : g_type_attrs)
    if (e.first.second == *keyval) referenced = true;
  if (!referenced) g_type_keyvals.erase(it);
  *keyval = MPI_KEYVAL_INVALID;
  return MPI_SUCCESS;
}

int MPI_Type_set_attr(MPI_Datatype dt, int keyval, void *attribute_val) {
  if (dt >= DERIVED_BASE && !g_dtypes.count(dt)) return MPI_ERR_TYPE;
  auto kv = g_type_keyvals.find(keyval);
  if (kv == g_type_keyvals.end() || kv->second.freed)
    return MPI_ERR_ARG;
  auto it = g_type_attrs.find({dt, keyval});
  if (it != g_type_attrs.end() && kv->second.delete_fn)
    kv->second.delete_fn(dt, keyval, it->second, kv->second.extra_state);
  g_type_attrs[{dt, keyval}] = attribute_val;
  return MPI_SUCCESS;
}

int MPI_Type_get_attr(MPI_Datatype dt, int keyval, void *attribute_val,
                      int *flag) {
  if (dt >= DERIVED_BASE && !g_dtypes.count(dt)) return MPI_ERR_TYPE;
  auto it = g_type_attrs.find({dt, keyval});
  *flag = it != g_type_attrs.end() ? 1 : 0;
  if (*flag) *(void **)attribute_val = it->second;
  return MPI_SUCCESS;
}

int MPI_Type_delete_attr(MPI_Datatype dt, int keyval) {
  if (dt >= DERIVED_BASE && !g_dtypes.count(dt)) return MPI_ERR_TYPE;
  auto it = g_type_attrs.find({dt, keyval});
  if (it == g_type_attrs.end()) return MPI_ERR_ARG;
  auto kv = g_type_keyvals.find(keyval);
  if (kv != g_type_keyvals.end() && kv->second.delete_fn)
    kv->second.delete_fn(dt, keyval, it->second, kv->second.extra_state);
  g_type_attrs.erase(it);
  reap_type_keyval(keyval);
  return MPI_SUCCESS;
}

// size-matched types (type_match_size.c)
int MPI_Type_match_size(int typeclass, int size, MPI_Datatype *dt) {
  if (typeclass == MPI_TYPECLASS_INTEGER) {
    switch (size) {
      case 1: *dt = MPI_SIGNED_CHAR; return MPI_SUCCESS;
      case 2: *dt = MPI_SHORT; return MPI_SUCCESS;
      case 4: *dt = MPI_INT; return MPI_SUCCESS;
      case 8: *dt = MPI_LONG_LONG; return MPI_SUCCESS;
    }
  } else if (typeclass == MPI_TYPECLASS_REAL) {
    switch (size) {
      case 4: *dt = MPI_FLOAT; return MPI_SUCCESS;
      case 8: *dt = MPI_DOUBLE; return MPI_SUCCESS;
    }
  } else if (typeclass == MPI_TYPECLASS_COMPLEX) {
    // complex = contiguous (re, im) pair; match_size returns a
    // REFERENCE the caller never frees, so the handle is built once
    // per size and cached for the process lifetime
    static MPI_Datatype cached8 = MPI_DATATYPE_NULL;
    static MPI_Datatype cached16 = MPI_DATATYPE_NULL;
    MPI_Datatype *slot;
    MPI_Datatype base;
    if (size == 8) { slot = &cached8; base = MPI_FLOAT; }
    else if (size == 16) { slot = &cached16; base = MPI_DOUBLE; }
    else return MPI_ERR_ARG;
    if (*slot == MPI_DATATYPE_NULL || !g_dtypes.count(*slot)) {
      int rc = MPI_Type_contiguous(2, base, slot);
      if (rc != MPI_SUCCESS) return rc;
      rc = MPI_Type_commit(slot);
      if (rc != MPI_SUCCESS) return rc;
    }
    *dt = *slot;
    return MPI_SUCCESS;
  }
  return MPI_ERR_ARG;
}

// Fortran-parameterized types (type_create_f90_*.c): precision/range
// select the narrowest hosting native type
int MPI_Type_create_f90_integer(int range, MPI_Datatype *newtype) {
  if (range <= 2) *newtype = MPI_SIGNED_CHAR;
  else if (range <= 4) *newtype = MPI_SHORT;
  else if (range <= 9) *newtype = MPI_INT;
  else if (range <= 18) *newtype = MPI_LONG_LONG;
  else return MPI_ERR_ARG;
  return MPI_SUCCESS;
}

int MPI_Type_create_f90_real(int precision, int range,
                             MPI_Datatype *newtype) {
  if (precision <= 6 && range <= 37) *newtype = MPI_FLOAT;
  else if (precision <= 15 && range <= 307) *newtype = MPI_DOUBLE;
  else return MPI_ERR_ARG;
  return MPI_SUCCESS;
}

int MPI_Type_create_f90_complex(int precision, int range,
                                MPI_Datatype *newtype) {
  MPI_Datatype base;
  int rc = MPI_Type_create_f90_real(precision, range, &base);
  if (rc != MPI_SUCCESS) return rc;
  rc = MPI_Type_contiguous(2, base, newtype);
  if (rc != MPI_SUCCESS) return rc;
  DtypeObj &d = g_dtypes[*newtype];
  d.combiner = MPI_COMBINER_F90_COMPLEX;
  d.env_ints = {precision, range};
  d.env_types.clear();
  return MPI_Type_commit(newtype);
}

// canonical packing (pack_external.c): big-endian canonical base
// elements with native sizes (64-bit longs — documented divergence)
namespace {

bool little_endian() {
  const uint16_t probe = 1;
  return *(const uint8_t *)&probe == 1;
}

void swap_elems(char *buf, size_t nbytes, size_t item) {
  if (item <= 1 || !little_endian()) return;
  for (size_t at = 0; at + item <= nbytes; at += item)
    for (size_t i = 0; i < item / 2; i++)
      std::swap(buf[at + i], buf[at + item - 1 - i]);
}

}  // namespace

// canonical element unit of a type's PACKED stream: predefined =
// item size; byte-sealed derived = the recorded constructor unit
// (0 = heterogeneous struct, not canonically packable)
static int packed_unit(const DtView &v, MPI_Datatype dt) {
  return packed_unit_of(v.derived, dt, v.di.item);
}

int MPI_Pack_external(const char datarep[], const void *inbuf,
                      int incount, MPI_Datatype datatype, void *outbuf,
                      MPI_Aint outsize, MPI_Aint *position) {
  if (!datarep || strcmp(datarep, "external32") != 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(datatype, v)) return MPI_ERR_TYPE;
  int unit = packed_unit(v, datatype);
  // unit 0 = no canonical element order: mixed-field structs and pair
  // records, directly or through ANY derived construction — reject,
  // never half-swap
  if (unit == 0) return MPI_ERR_TYPE;
  std::vector<char> packed;
  pack_dtype(inbuf, incount, v, packed);
  swap_elems(packed.data(), packed.size(), (size_t)unit);
  if (*position + (MPI_Aint)packed.size() > outsize)
    return MPI_ERR_TRUNCATE;
  memcpy((char *)outbuf + *position, packed.data(), packed.size());
  *position += (MPI_Aint)packed.size();
  return MPI_SUCCESS;
}

int MPI_Unpack_external(const char datarep[], const void *inbuf,
                        MPI_Aint insize, MPI_Aint *position,
                        void *outbuf, int outcount,
                        MPI_Datatype datatype) {
  if (!datarep || strcmp(datarep, "external32") != 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(datatype, v)) return MPI_ERR_TYPE;
  int unit = packed_unit(v, datatype);
  if (unit == 0) return MPI_ERR_TYPE;  // see Pack_external
  size_t want = (size_t)outcount * v.elems_per_item() * v.di.item;
  if (*position + (MPI_Aint)want > insize) return MPI_ERR_TRUNCATE;
  std::vector<char> tmp((const char *)inbuf + *position,
                        (const char *)inbuf + *position + want);
  swap_elems(tmp.data(), tmp.size(), (size_t)unit);
  unpack_dtype(outbuf, outcount, v, tmp.data(), tmp.size());
  *position += (MPI_Aint)want;
  return MPI_SUCCESS;
}

int MPI_Pack_external_size(const char datarep[], int incount,
                           MPI_Datatype datatype, MPI_Aint *size) {
  if (!datarep || strcmp(datarep, "external32") != 0) return MPI_ERR_ARG;
  DtView v;
  if (!resolve_dtype(datatype, v)) return MPI_ERR_TYPE;
  if (packed_unit(v, datatype) == 0)
    return MPI_ERR_TYPE;  // consistent with Pack_external's rejection
  *size = (MPI_Aint)((int64_t)incount * v.elems_per_item() *
                     (int64_t)v.di.item);
  return MPI_SUCCESS;
}

// generalized requests (grequest_start.c): the engine's Req with
// user-driven completion.  query_fn fills the status at completion,
// free_fn runs right after (this engine has no free hook in the
// retire path; complete -> wait is the ordering that matters).
struct GrequestState {
  MPI_Grequest_query_function *query_fn;
  MPI_Grequest_free_function *free_fn;
  MPI_Grequest_cancel_function *cancel_fn;
  void *extra_state;
};
// guarded by g.match_mu: Grequest_complete is DESIGNED to run on a
// user progress thread concurrent with the main thread's engine calls
static std::map<int, GrequestState> g_grequests;

int MPI_Grequest_start(MPI_Grequest_query_function *query_fn,
                       MPI_Grequest_free_function *free_fn,
                       MPI_Grequest_cancel_function *cancel_fn,
                       void *extra_state, MPI_Request *request) {
  Req *r = new Req;
  r->heap = true;
  r->comm = MPI_COMM_WORLD;
  int handle;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    handle = g.next_req++;
    g.reqs[handle] = r;
    g_grequests[handle] = {query_fn, free_fn, cancel_fn, extra_state};
  }
  *request = handle;
  return MPI_SUCCESS;
}

int MPI_Grequest_complete(MPI_Request request) {
  GrequestState st;
  Req *r;
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    auto git = g_grequests.find(request);
    if (git == g_grequests.end()) return MPI_ERR_REQUEST;
    st = git->second;
    auto it = g.reqs.find(request);
    if (it == g.reqs.end()) return MPI_ERR_REQUEST;
    r = it->second;
  }
  MPI_Status status{};
  status.MPI_SOURCE = MPI_ANY_SOURCE;
  status.MPI_TAG = MPI_ANY_TAG;
  if (st.query_fn) st.query_fn(st.extra_state, &status);
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    r->status = status;
    r->complete = true;
    g.match_cv.notify_all();
  }
  if (st.free_fn) st.free_fn(st.extra_state);
  {
    std::lock_guard<std::mutex> lk(g.match_mu);
    g_grequests.erase(request);
  }
  return MPI_SUCCESS;
}

// request-based RMA (rput.c family): every origin-side operation here
// packs its payload at call time (local completion is immediate), so
// the request is born complete — remote completion is the epoch's
// flush/unlock/fence, exactly as for the non-request forms
int MPI_Rput(const void *origin_addr, int origin_count,
             MPI_Datatype origin_datatype, int target_rank,
             MPI_Aint target_disp, int target_count,
             MPI_Datatype target_datatype, MPI_Win win,
             MPI_Request *request) {
  int rc = MPI_Put(origin_addr, origin_count, origin_datatype,
                   target_rank, target_disp, target_count,
                   target_datatype, win);
  if (rc != MPI_SUCCESS) return rc;
  *request = make_completed_req(MPI_COMM_WORLD);
  return MPI_SUCCESS;
}

int MPI_Rget(void *origin_addr, int origin_count,
             MPI_Datatype origin_datatype, int target_rank,
             MPI_Aint target_disp, int target_count,
             MPI_Datatype target_datatype, MPI_Win win,
             MPI_Request *request) {
  int rc = MPI_Get(origin_addr, origin_count, origin_datatype,
                   target_rank, target_disp, target_count,
                   target_datatype, win);
  if (rc != MPI_SUCCESS) return rc;
  *request = make_completed_req(MPI_COMM_WORLD);
  return MPI_SUCCESS;
}

int MPI_Raccumulate(const void *origin_addr, int origin_count,
                    MPI_Datatype origin_datatype, int target_rank,
                    MPI_Aint target_disp, int target_count,
                    MPI_Datatype target_datatype, MPI_Op op, MPI_Win win,
                    MPI_Request *request) {
  int rc = MPI_Accumulate(origin_addr, origin_count, origin_datatype,
                          target_rank, target_disp, target_count,
                          target_datatype, op, win);
  if (rc != MPI_SUCCESS) return rc;
  *request = make_completed_req(MPI_COMM_WORLD);
  return MPI_SUCCESS;
}

int MPI_Rget_accumulate(const void *origin_addr, int origin_count,
                        MPI_Datatype origin_datatype, void *result_addr,
                        int result_count, MPI_Datatype result_datatype,
                        int target_rank, MPI_Aint target_disp,
                        int target_count, MPI_Datatype target_datatype,
                        MPI_Op op, MPI_Win win, MPI_Request *request) {
  int rc = MPI_Get_accumulate(origin_addr, origin_count,
                              origin_datatype, result_addr,
                              result_count, result_datatype,
                              target_rank, target_disp, target_count,
                              target_datatype, op, win);
  if (rc != MPI_SUCCESS) return rc;
  *request = make_completed_req(MPI_COMM_WORLD);
  return MPI_SUCCESS;
}

// ---------------------------- ports / join / naming (round 5)
// open_port.c / comm_accept.c / comm_connect.c / publish_name.c /
// comm_join.c: client/server connection establishment within one
// universe.  A port is a live listening socket named "host:tcpport";
// accept/connect roots exchange group lists + a seed over it and both
// sides derive the intercommunicator cids from the same hash — the
// deterministic-cid collapse again.  Publish/lookup speak the
// launcher's name-server protocol (tools/mpirun.py hosts it,
// ZMPI_NAMESERVER advertises it — the ompi-server analog).

static std::map<std::string, int> g_ports;  // port name -> listen fd

int MPI_Open_port(MPI_Info, char *port_name) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return MPI_ERR_OTHER;
  set_cloexec(fd);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = 0;
  inet_pton(AF_INET, g.host.c_str(), &a.sin_addr);
  if (bind(fd, (sockaddr *)&a, sizeof a) != 0 || listen(fd, 8) != 0) {
    close(fd);
    return MPI_ERR_OTHER;
  }
  socklen_t alen = sizeof a;
  getsockname(fd, (sockaddr *)&a, &alen);
  snprintf(port_name, MPI_MAX_PORT_NAME, "%s:%d", g.host.c_str(),
           (int)ntohs(a.sin_port));
  g_ports[port_name] = fd;
  return MPI_SUCCESS;
}

int MPI_Close_port(const char *port_name) {
  auto it = g_ports.find(port_name ? port_name : "");
  if (it == g_ports.end()) return MPI_ERR_ARG;
  close(it->second);
  g_ports.erase(it);
  return MPI_SUCCESS;
}

namespace {

// serialize my comm's world-rank group + a seed into one DSS frame
std::string pack_group_frame(const CommObj &c, int64_t seed) {
  std::string f;
  put_varint(f, 2);
  put_int(f, seed);
  f.push_back((char)T_LIST);
  put_varint(f, c.group.size());
  for (int r : c.group) put_int(f, (int64_t)r);
  return f;
}

bool parse_group_frame(const std::string &f, int64_t &seed,
                       std::vector<int> &group) {
  std::vector<DssVal> vals;
  if (!parse_all(f, vals) || vals.size() != 2 ||
      vals[1].tag != T_LIST)
    return false;
  seed = vals[0].i;
  group.clear();
  for (auto &e : vals[1].items) group.push_back((int)e.i);
  return true;
}

// both sides build the identical intercomm from (mine, theirs, seed)
int build_port_intercomm(CommObj *c, const std::vector<int> &remote,
                         int64_t seed, MPI_Comm *newcomm) {
  CommObj inter;
  inter.group = c->group;
  inter.local_rank = c->local_rank;
  inter.remote = remote;
  intercomm_cids(c->group, remote, (int)(seed & 0x7FFFFFFF), inter);
  int handle = g_next_comm++;
  g_comms[handle] = inter;
  *newcomm = handle;
  return MPI_SUCCESS;
}

// distribute (seed, remote group) from the root and build — the tail
// both accept and connect share
int port_epilogue(CommObj *c, int root, int64_t hdr0_seed,
                  std::vector<int> &remote, MPI_Comm comm,
                  MPI_Comm *newcomm) {
  long hdr[2] = {(long)hdr0_seed, (long)remote.size()};
  int rc = c_bcast(*c, hdr, 2, MPI_LONG, root, 0x7E19);
  if (rc != MPI_SUCCESS) return rc;
  if (hdr[0] < 0) return MPI_ERR_OTHER;  // root failure, agreed
  remote.resize((size_t)hdr[1]);
  if (hdr[1] > 0) {
    rc = c_bcast(*c, remote.data(), (int)hdr[1], MPI_INT, root, 0x7E1A);
    if (rc != MPI_SUCCESS) return rc;
  }
  (void)comm;
  return build_port_intercomm(c, remote, hdr[0], newcomm);
}

}  // namespace

int MPI_Comm_accept(const char *port_name, MPI_Info, int root,
                    MPI_Comm comm, MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c || !c->remote.empty()) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  int64_t seed = -1;
  std::vector<int> remote;
  if (c->local_rank == root) {
    auto it = g_ports.find(port_name ? port_name : "");
    if (it != g_ports.end()) {
      int conn = accept(it->second, nullptr, nullptr);
      if (conn >= 0) {
        // the accept side mints the seed (its own counter guarantees
        // distinct cids across repeated accepts on one port)
        static std::atomic<int64_t> accept_seq{1};
        int64_t my_seed =
            (int64_t)(mix64((uint64_t)accept_seq.fetch_add(1) ^
                            ((uint64_t)g.rank << 32)) &
                      0x7FFFFFFF);
        std::string f;
        if (recv_frame(conn, f)) {
          int64_t ignored;
          if (parse_group_frame(f, ignored, remote) &&
              send_frame(conn, pack_group_frame(*c, my_seed)))
            seed = my_seed;
        }
        close(conn);
      }
    }
  }
  return port_epilogue(c, root, seed, remote, comm, newcomm);
}

int MPI_Comm_connect(const char *port_name, MPI_Info, int root,
                     MPI_Comm comm, MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c || !c->remote.empty()) return MPI_ERR_COMM;
  if (root < 0 || root >= (int)c->group.size()) return MPI_ERR_ARG;
  int64_t seed = -1;
  std::vector<int> remote;
  if (c->local_rank == root && port_name) {
    std::string pn = port_name;
    size_t colon = pn.rfind(':');
    if (colon != std::string::npos) {
      int conn = tcp_connect(pn.substr(0, colon),
                             atoi(pn.c_str() + colon + 1));
      if (conn >= 0) {
        // connector sends first, seed comes back from the acceptor
        if (send_frame(conn, pack_group_frame(*c, 0))) {
          std::string f;
          int64_t their_seed;
          if (recv_frame(conn, f) &&
              parse_group_frame(f, their_seed, remote))
            seed = their_seed;
        }
        close(conn);
      }
    }
  }
  return port_epilogue(c, root, seed, remote, comm, newcomm);
}

int MPI_Comm_disconnect(MPI_Comm *comm) {
  // comm_disconnect.c: collective; waits for pending comm traffic.
  // The engine completes sends at the API boundary, so the barrier IS
  // the quiescence point; then the handle dies like Comm_free.
  if (!comm || *comm == MPI_COMM_WORLD || *comm == MPI_COMM_SELF)
    return MPI_ERR_COMM;  // the Comm_free guard, same mistake class
  CommObj *c = lookup_comm(*comm);
  if (!c) return MPI_ERR_COMM;
  if (c->remote.empty()) c_barrier(*c);  // intracomm quiesce
  delete_comm_attrs(*comm);
  release_errh_ref(g_comm_errh, *comm);
  g_comms.erase(*comm);
  *comm = MPI_COMM_NULL;
  return MPI_SUCCESS;
}

int MPI_Comm_join(int fd, MPI_Comm *intercomm) {
  // comm_join.c scoped to one universe: the two processes exchange
  // (world rank, local seed) over the caller's socket; the shared
  // seed is the SUM so both sides compute it identically
  static std::atomic<int64_t> join_seq{1};
  int64_t my_seed = join_seq.fetch_add(1) + g.rank * 1000003LL;
  std::string out;
  put_varint(out, 2);
  put_int(out, (int64_t)g.rank);
  put_int(out, my_seed);
  if (!send_frame(fd, out)) return MPI_ERR_OTHER;
  std::string in;
  if (!recv_frame(fd, in)) return MPI_ERR_OTHER;
  std::vector<DssVal> vals;
  if (!parse_all(in, vals) || vals.size() != 2) return MPI_ERR_OTHER;
  int peer = (int)vals[0].i;
  int64_t seed = my_seed + vals[1].i;
  if (peer < 0 || peer >= (int)g.book.size() || peer == g.rank)
    return MPI_ERR_ARG;
  CommObj inter;
  inter.group = {g.rank};
  inter.local_rank = 0;
  inter.remote = {peer};
  intercomm_cids(inter.group, inter.remote,
                 (int)(seed & 0x7FFFFFFF), inter);
  int handle = g_next_comm++;
  g_comms[handle] = inter;
  *intercomm = handle;
  return MPI_SUCCESS;
}

namespace {

// one round-trip with the launcher-hosted name server; the request is
// ONE list value, the reply ONE value (mpirun.py's protocol)
int nameserver_rpc(const std::vector<std::string> &req, DssVal &reply) {
  const char *ns = getenv("ZMPI_NAMESERVER");
  if (!ns || !*ns) return MPI_ERR_OTHER;  // no ompi-server analog
  std::string addr = ns;
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) return MPI_ERR_OTHER;
  int fd = tcp_connect(addr.substr(0, colon),
                       atoi(addr.c_str() + colon + 1));
  if (fd < 0) return MPI_ERR_OTHER;
  std::string f;
  put_varint(f, 1);
  f.push_back((char)T_LIST);
  put_varint(f, req.size());
  for (auto &s2 : req) put_str(f, s2);
  std::string in;
  bool ok = send_frame(fd, f) && recv_frame(fd, in);
  close(fd);
  if (!ok) return MPI_ERR_OTHER;
  std::vector<DssVal> vals;
  if (!parse_all(in, vals) || vals.size() != 1) return MPI_ERR_OTHER;
  reply = vals[0];
  return MPI_SUCCESS;
}

}  // namespace

int MPI_Publish_name(const char *service_name, MPI_Info,
                     const char *port_name) {
  if (!service_name || !port_name) return MPI_ERR_ARG;
  DssVal reply;
  return nameserver_rpc({"pub", service_name, port_name}, reply);
}

int MPI_Lookup_name(const char *service_name, MPI_Info,
                    char *port_name) {
  if (!service_name || !port_name) return MPI_ERR_ARG;
  DssVal reply;
  int rc = nameserver_rpc({"look", service_name}, reply);
  if (rc != MPI_SUCCESS) return rc;
  if (reply.tag != T_STR) return MPI_ERR_ARG;  // unpublished service
  snprintf(port_name, MPI_MAX_PORT_NAME, "%s", reply.s.c_str());
  return MPI_SUCCESS;
}

int MPI_Unpublish_name(const char *service_name, MPI_Info,
                       const char *port_name) {
  (void)port_name;
  if (!service_name) return MPI_ERR_ARG;
  DssVal reply;
  int rc = nameserver_rpc({"unpub", service_name}, reply);
  if (rc != MPI_SUCCESS) return rc;
  return reply.tag == T_BOOL && reply.i ? MPI_SUCCESS : MPI_ERR_ARG;
}

// general distributed graph (dist_graph_create.c): edges may describe
// ANY node, so one allgatherv round routes every (src, dst, weight)
// triple to everyone; each rank then filters its in/out lists in
// contributor order
int MPI_Dist_graph_create(MPI_Comm comm, int n, const int sources[],
                          const int degrees[], const int destinations[],
                          const int weights[], MPI_Info /*info*/,
                          int /*reorder*/, MPI_Comm *newcomm) {
  CommObj *c = lookup_comm(comm);
  if (!c || !c->remote.empty()) return MPI_ERR_COMM;
  if (n < 0) return MPI_ERR_ARG;
  int csize = (int)c->group.size();
  bool weighted = weights != MPI_UNWEIGHTED;
  std::vector<int64_t> mine;
  {
    int at = 0;
    for (int i = 0; i < n; i++) {
      if (sources[i] < 0 || sources[i] >= csize || degrees[i] < 0)
        return MPI_ERR_ARG;
      for (int e = 0; e < degrees[i]; e++, at++) {
        if (destinations[at] < 0 || destinations[at] >= csize)
          return MPI_ERR_ARG;
        mine.push_back(sources[i]);
        mine.push_back(destinations[at]);
        mine.push_back(
            weighted && weights != MPI_WEIGHTS_EMPTY ? weights[at] : 1);
      }
    }
  }
  int my_n = (int)mine.size();
  std::vector<int> counts((size_t)csize), displs((size_t)csize);
  int rc = c_allgather(*c, &my_n, 1, MPI_INT, counts.data(), 1, MPI_INT);
  if (rc != MPI_SUCCESS) return rc;
  int total = 0;
  for (int r = 0; r < csize; r++) {
    displs[(size_t)r] = total;
    total += counts[(size_t)r];
  }
  std::vector<int64_t> all((size_t)total);
  rc = c_allgatherv(*c, mine.data(), my_n, MPI_LONG, all.data(),
                    counts.data(), displs.data(), MPI_LONG);
  if (rc != MPI_SUCCESS) return rc;
  int me = c->local_rank;
  std::vector<int> in_src, in_w, out_dst, out_w;
  for (int t = 0; t + 2 < total; t += 3) {
    int src = (int)all[(size_t)t], dst = (int)all[(size_t)t + 1];
    int w = (int)all[(size_t)t + 2];
    if (dst == me) {
      in_src.push_back(src);
      in_w.push_back(w);
    }
    if (src == me) {
      out_dst.push_back(dst);
      out_w.push_back(w);
    }
  }
  rc = MPI_Comm_split(comm, 0, me, newcomm);
  if (rc != MPI_SUCCESS) return rc;
  CommObj *nc = lookup_comm(*newcomm);
  nc->dist = true;
  nc->dist_src = std::move(in_src);
  nc->dist_dst = std::move(out_dst);
  nc->dist_weighted = weighted;
  if (weighted) {
    nc->dist_srcw = std::move(in_w);
    nc->dist_dstw = std::move(out_w);
  }
  return MPI_SUCCESS;
}

// predefined attribute functions (attr_fn.c): the do-nothing copy and
// delete callbacks plus the always-copy DUP_FN
int MPI_NULL_COPY_FN(MPI_Comm, int, void *, void *, void *, int *flag) {
  *flag = 0;
  return MPI_SUCCESS;
}
int MPI_NULL_DELETE_FN(MPI_Comm, int, void *, void *) {
  return MPI_SUCCESS;
}
int MPI_DUP_FN(MPI_Comm, int, void *, void *attribute_val_in,
               void *attribute_val_out, int *flag) {
  *(void **)attribute_val_out = attribute_val_in;
  *flag = 1;
  return MPI_SUCCESS;
}

// ------------------------------------------- MPI_T tool interface
// ompi/mpi/tool reduced to this shim's variable set: cvars are the
// MCA-style knobs MPI_Init reads from ZMPI_MCA_* (writable at runtime
// through exactly this interface, the reference's cvar write path);
// pvars read the engine's live counters and queue levels.

static bool g_mpit_up = false;

struct CvarDesc {
  const char *name;
  const char *desc;
  MPI_Datatype dt;
  int scope;  // MPI_T_SCOPE_LOCAL = writable here
};
static const CvarDesc g_cvars[] = {
    {"tcp_eager_limit",
     "protocol switch: payloads above this many bytes go rendezvous",
     MPI_LONG, MPI_T_SCOPE_LOCAL},
    {"rndv_cts_timeout",
     "seconds a rendezvous sender waits for CTS (<0 = forever)",
     MPI_DOUBLE, MPI_T_SCOPE_LOCAL},
};
constexpr int N_CVARS = (int)(sizeof g_cvars / sizeof g_cvars[0]);

struct PvarDesc {
  const char *name;
  const char *desc;
  int var_class;
};
static const PvarDesc g_pvars[] = {
    {"eager_sends", "messages sent on the eager path",
     MPI_T_PVAR_CLASS_COUNTER},
    {"rndv_sends", "messages sent through the rendezvous protocol",
     MPI_T_PVAR_CLASS_COUNTER},
    {"bytes_sent", "payload bytes handed to the wire",
     MPI_T_PVAR_CLASS_COUNTER},
    {"unexpected_msgs", "current unexpected-queue length",
     MPI_T_PVAR_CLASS_LEVEL},
    {"posted_recvs", "current posted-receive-queue length",
     MPI_T_PVAR_CLASS_LEVEL},
};
constexpr int N_PVARS = (int)(sizeof g_pvars / sizeof g_pvars[0]);

static std::set<int> g_pvar_sessions;
static int g_next_pvar_session = 1;

static void mpit_str(const char *src, char *dst, int *len) {
  if (dst && len && *len > 0) {
    snprintf(dst, (size_t)*len, "%s", src);
    *len = (int)strlen(dst);
  } else if (len) {
    *len = (int)strlen(src) + 1;
  }
}

int MPI_T_init_thread(int, int *provided) {
  g_mpit_up = true;
  if (provided) *provided = g_thread_level;
  return MPI_SUCCESS;
}

int MPI_T_finalize(void) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  g_mpit_up = false;
  g_pvar_sessions.clear();
  return MPI_SUCCESS;
}

int MPI_T_cvar_get_num(int *num_cvar) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  *num_cvar = N_CVARS;
  return MPI_SUCCESS;
}

int MPI_T_cvar_get_info(int idx, char *name, int *name_len,
                        int *verbosity, MPI_Datatype *datatype,
                        void *, char *desc, int *desc_len, int *bind,
                        int *scope) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  if (idx < 0 || idx >= N_CVARS) return MPI_T_ERR_INVALID_INDEX;
  mpit_str(g_cvars[idx].name, name, name_len);
  mpit_str(g_cvars[idx].desc, desc, desc_len);
  if (verbosity) *verbosity = MPI_T_VERBOSITY_USER_BASIC;
  if (datatype) *datatype = g_cvars[idx].dt;
  if (bind) *bind = MPI_T_BIND_NO_OBJECT;
  if (scope) *scope = g_cvars[idx].scope;
  return MPI_SUCCESS;
}

int MPI_T_cvar_handle_alloc(int idx, void *, MPI_T_cvar_handle *handle,
                            int *count) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  if (idx < 0 || idx >= N_CVARS) return MPI_T_ERR_INVALID_INDEX;
  *handle = idx;  // the variable set is static; the index IS the handle
  if (count) *count = 1;
  return MPI_SUCCESS;
}

int MPI_T_cvar_handle_free(MPI_T_cvar_handle *handle) {
  if (handle) *handle = -1;
  return MPI_SUCCESS;
}

int MPI_T_cvar_read(MPI_T_cvar_handle h, void *buf) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  switch (h) {
    case 0:
      *(long *)buf = (long)g.eager_limit.load();
      return MPI_SUCCESS;
    case 1:
      *(double *)buf = g.cts_timeout.load();
      return MPI_SUCCESS;
  }
  return MPI_T_ERR_INVALID_HANDLE;
}

int MPI_T_cvar_write(MPI_T_cvar_handle h, const void *buf) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  switch (h) {
    case 0: {
      long v = *(const long *)buf;
      if (v <= 0) return MPI_T_ERR_CVAR_SET_NOT_NOW;
      g.eager_limit = v;
      return MPI_SUCCESS;
    }
    case 1:
      g.cts_timeout = *(const double *)buf;
      return MPI_SUCCESS;
  }
  return MPI_T_ERR_INVALID_HANDLE;
}

int MPI_T_pvar_get_num(int *num_pvar) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  *num_pvar = N_PVARS;
  return MPI_SUCCESS;
}

int MPI_T_pvar_get_info(int idx, char *name, int *name_len,
                        int *verbosity, int *var_class,
                        MPI_Datatype *datatype, void *, char *desc,
                        int *desc_len, int *bind, int *readonly,
                        int *continuous, int *atomic_) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  if (idx < 0 || idx >= N_PVARS) return MPI_T_ERR_INVALID_INDEX;
  mpit_str(g_pvars[idx].name, name, name_len);
  mpit_str(g_pvars[idx].desc, desc, desc_len);
  if (verbosity) *verbosity = MPI_T_VERBOSITY_USER_BASIC;
  if (var_class) *var_class = g_pvars[idx].var_class;
  if (datatype) *datatype = MPI_LONG_LONG;
  if (bind) *bind = MPI_T_BIND_NO_OBJECT;
  if (readonly) *readonly = 1;
  if (continuous) *continuous = 1;  // counters never need start/stop
  if (atomic_) *atomic_ = 0;
  return MPI_SUCCESS;
}

int MPI_T_pvar_session_create(MPI_T_pvar_session *session) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  *session = g_next_pvar_session++;
  g_pvar_sessions.insert(*session);
  return MPI_SUCCESS;
}

int MPI_T_pvar_session_free(MPI_T_pvar_session *session) {
  if (!session || !g_pvar_sessions.erase(*session))
    return MPI_T_ERR_INVALID_HANDLE;
  *session = -1;
  return MPI_SUCCESS;
}

int MPI_T_pvar_handle_alloc(MPI_T_pvar_session session, int idx, void *,
                            MPI_T_pvar_handle *handle, int *count) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  if (!g_pvar_sessions.count(session)) return MPI_T_ERR_INVALID_HANDLE;
  if (idx < 0 || idx >= N_PVARS) return MPI_T_ERR_INVALID_INDEX;
  *handle = idx;
  if (count) *count = 1;
  return MPI_SUCCESS;
}

int MPI_T_pvar_handle_free(MPI_T_pvar_session,
                           MPI_T_pvar_handle *handle) {
  if (handle) *handle = -1;
  return MPI_SUCCESS;
}

int MPI_T_pvar_start(MPI_T_pvar_session session, MPI_T_pvar_handle) {
  // continuous variables: start is a no-op (the reference's behavior)
  return g_pvar_sessions.count(session) ? MPI_SUCCESS
                                        : MPI_T_ERR_INVALID_HANDLE;
}

int MPI_T_pvar_stop(MPI_T_pvar_session session, MPI_T_pvar_handle) {
  return g_pvar_sessions.count(session) ? MPI_SUCCESS
                                        : MPI_T_ERR_INVALID_HANDLE;
}

int MPI_T_pvar_read(MPI_T_pvar_session session, MPI_T_pvar_handle h,
                    void *buf) {
  if (!g_mpit_up) return MPI_T_ERR_NOT_INITIALIZED;
  if (!g_pvar_sessions.count(session)) return MPI_T_ERR_INVALID_HANDLE;
  long long v;
  switch (h) {
    case 0: v = g.ctr_eager_sends.load(); break;
    case 1: v = g.ctr_rndv_sends.load(); break;
    case 2: v = g.ctr_bytes_sent.load(); break;
    case 3: {
      std::lock_guard<std::mutex> lk(g.match_mu);
      v = (long long)g.unexpected.size();
      break;
    }
    case 4: {
      std::lock_guard<std::mutex> lk(g.match_mu);
      v = (long long)g.posted.size();
      break;
    }
    default:
      return MPI_T_ERR_INVALID_HANDLE;
  }
  *(long long *)buf = v;
  return MPI_SUCCESS;
}

// ---------------------------------------------------------------- misc

int MPI_Abort(MPI_Comm, int errorcode) {
  fprintf(stderr, "MPI_Abort(%d)\n", errorcode);
  // best-effort: unlink this rank's ring files so an aborted job does
  // not strand /dev/shm segments (the launcher sweeps the rest; pure
  // syscalls, safe in this context)
  for (auto &e : g_sm_out)
    if (e.second->creator) shm_unlink(e.second->path.c_str());
  _exit(errorcode ? errorcode : 1);
}

double MPI_Wtime(void) {
  using clock = std::chrono::steady_clock;
  return std::chrono::duration<double>(clock::now().time_since_epoch())
      .count();
}

double MPI_Wtick(void) { return 1e-9; }

}  // extern "C"

// PMPI profiling layer: weak MPI_X + PMPI_X aliases (generated)
#include "zompi_pmpi.inc"
