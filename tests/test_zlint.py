"""zlint — the AST concurrency-and-protocol analyzer.

Per-rule fixture matrix (one minimal tripping snippet and one clean
twin each), suppression and baseline semantics, the CLI surface, and
the tier-1 wiring: the whole package must lint clean against the
checked-in baseline — a regression into any guarded bug class fails
HERE, not three PRs later.
"""

from __future__ import annotations

import os

import pytest

from zhpe_ompi_tpu.tools.zlint import __main__ as zlint_cli
from zhpe_ompi_tpu.tools.zlint.engine import (
    default_baseline_path,
    lint_paths,
)
from zhpe_ompi_tpu.tools.zlint.rules import all_rules, rule_table

PKG = os.path.dirname(os.path.dirname(os.path.abspath(
    __import__("zhpe_ompi_tpu").__file__))) + "/zhpe_ompi_tpu"


def lint_src(tmp_path, src: str, name: str = "snippet.py",
             baseline: str | None = None, extra: dict | None = None):
    """Write ``src`` (and optional extra files) into tmp and lint."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    p = tmp_path / name
    p.write_text(src)
    for fname, fsrc in (extra or {}).items():
        (tmp_path / fname).write_text(fsrc)
    return lint_paths([str(tmp_path)], baseline=baseline)


def rules_of(result) -> set[str]:
    return {f.rule for f in result.findings}


# -- the fixture matrix: trip + clean twin per rule ---------------------

TRIP_ZL001 = """
def exchange(ep, obj, dest, source):
    ep.isend(obj, dest)          # fire-and-forget: the PR 7 bug shape
    return ep.recv(source)
"""

CLEAN_ZL001 = """
def exchange(ep, obj, dest, source):
    sreq = ep.isend(obj, dest)
    value = ep.recv(source)
    sreq.wait()
    return value
"""

TRIP_ZL002_CYCLE = """
class Proc:
    def a_then_b(self):
        with self._ch_lock:
            with self._rndv_lock:
                pass

    def b_then_a(self):
        with self._rndv_lock:
            with self._ch_lock:
                pass
"""

CLEAN_ZL002_CYCLE = """
class Proc:
    def a_then_b(self):
        with self._ch_lock:
            with self._rndv_lock:
                pass

    def also_a_then_b(self):
        with self._ch_lock:
            with self._rndv_lock:
                pass
"""

TRIP_ZL002_BLOCKING = """
class Proc:
    def beat(self, sock, frame):
        with self._send_lock:
            sock.sendall(frame)
"""

CLEAN_ZL002_BLOCKING = """
class Proc:
    def beat(self, sock, frame):
        with self._send_lock:
            queued = self._queue.copy()
        sock.sendall(frame)
"""

TRIP_ZL003 = """
import time

def drain(ch):
    while ch.busy():
        time.sleep(0.0002)
"""

CLEAN_ZL003 = """
import time

def drain(ch):
    delay = 0.0002
    while ch.busy():
        time.sleep(delay)
        delay = min(delay * 2, 0.005)
"""

TRIP_ZL004 = """
def classify(req, peer):
    try:
        peer.poke()
    except Exception:
        pass
"""

CLEAN_ZL004 = """
def classify(req, peer):
    try:
        peer.poke()
    except Exception as e:
        req.complete_error(e)
"""

TRIP_ZL005 = """
import threading

def flood(fn):
    t = threading.Thread(target=fn)
    t.start()
"""

CLEAN_ZL005 = """
import threading

def flood(fn, registry):
    t = threading.Thread(target=fn, daemon=True)
    registry.append(t)
    t.start()
"""

# ZL006 anchors on a file named spc.py carrying the doc table
SPC_DOC = '''
"""Counters.

- ``documented_counter`` — a counter with a doc entry.
"""
'''

TRIP_ZL006 = """
from runtime import spc

def op():
    spc.record("mystery_counter", 1)
"""

CLEAN_ZL006 = """
from runtime import spc

def op():
    spc.record("documented_counter", 1)
"""

# ZL007 anchors on a file named var.py
VAR_PY = "registry = None\n"

TRIP_ZL007_UNREG = """
from mca import var as mca_var

def geometry():
    return int(mca_var.get("ghost_var", 4096))
"""

TRIP_ZL007_DRIFT = """
from mca import var as mca_var

mca_var.register("ring_bytes", 4 << 20, "ring capacity")

def geometry():
    return int(mca_var.get("ring_bytes", 2 << 20))
"""

CLEAN_ZL007 = """
from mca import var as mca_var

mca_var.register("ring_bytes", 4 << 20, "ring capacity")

def geometry():
    return int(mca_var.get("ring_bytes", 4 << 20))
"""

TRIP_ZL008 = """
def decide(opname, size, text):
    if opname not in ("allreduce", "bcast"):
        raise ValueError(opname)
    return int(text)
"""

# ZL009 anchors on spc.py exactly like ZL006; templated doc entries
# (``coll_<op>_calls``) belong to IT, not to the exact-name parity
SPC_DOC_TPL = '''
"""Counters.

- ``coll_<op>_calls`` — templated per-operation family.
"""
'''

TRIP_ZL009_TABLE = """
from runtime import spc

PLANE = {"fast": "mystery_dynamic_counter"}

class Seam:
    def __init__(self, plane):
        self._ctr = PLANE.get(plane, "documented_counter")

    def op(self, n):
        spc.record(self._ctr, n)
"""

CLEAN_ZL009_TABLE = """
from runtime import spc

PLANE = {"fast": "documented_counter"}

class Seam:
    def __init__(self, plane):
        self._ctr = PLANE.get(plane, "documented_counter")

    def op(self, n):
        spc.record(self._ctr, n)
"""

TRIP_ZL009_FSTRING = """
from runtime import spc

def op(kind):
    spc.record(f"zz_{kind}_calls", 1)
"""

CLEAN_ZL009_FSTRING = """
from runtime import spc

def op(kind):
    spc.record(f"coll_{kind}_calls", 1)
"""

TRIP_ZL009_UNRESOLVABLE = """
from runtime import spc

def op(make_name):
    spc.record(make_name(), 1)
"""

CLEAN_ZL008 = """
def decide(opname, size, text):
    if opname not in ("allreduce", "bcast"):
        return "auto"
    try:
        return int(text)
    except ValueError:
        return "auto"
"""

# ZL010 anchors on flightrec.py / ztrace.py carrying the type tables
FLIGHTREC_PY = '''
SEND = "send"
RECV = "recv"
ALL_EVENTS = (SEND, RECV)
'''

ZTRACE_PY = '''
SEND = "send"
DELIVER = "deliver"
STRAY = "stray"  # declared but NOT listed in ALL_KINDS
ALL_KINDS = (SEND, DELIVER)
'''

TRIP_ZL010_LITERAL = """
from runtime import flightrec

def seam():
    flightrec.record("sennd", dest=1)
"""

TRIP_ZL010_UNDECLARED = """
from runtime import ztrace

def seam(rank):
    ztrace.instant(ztrace.STRAY, rank)
"""

TRIP_ZL010_UNRESOLVABLE = """
from runtime import ztrace

def seam(rank, kind):
    ztrace.record_span(kind, rank, 0, 0)
"""

CLEAN_ZL010 = """
from runtime import flightrec, ztrace

def seam(rank, unexpected):
    flightrec.record(flightrec.SEND, dest=1)
    flightrec.record("recv", src=0)
    ztrace.instant(ztrace.DELIVER if unexpected else ztrace.SEND, rank)
"""


class TestRuleMatrix:
    """Each rule: the tripping snippet fires exactly that rule, the
    clean twin is silent."""

    @pytest.mark.parametrize("rule,trip,clean,extra", [
        ("ZL001", TRIP_ZL001, CLEAN_ZL001, None),
        ("ZL002", TRIP_ZL002_CYCLE, CLEAN_ZL002_CYCLE, None),
        ("ZL002", TRIP_ZL002_BLOCKING, CLEAN_ZL002_BLOCKING, None),
        ("ZL003", TRIP_ZL003, CLEAN_ZL003, None),
        ("ZL004", TRIP_ZL004, CLEAN_ZL004, None),
        ("ZL005", TRIP_ZL005, CLEAN_ZL005, None),
        ("ZL006", TRIP_ZL006, CLEAN_ZL006, {"spc.py": SPC_DOC}),
        ("ZL007", TRIP_ZL007_UNREG, CLEAN_ZL007, {"var.py": VAR_PY}),
        ("ZL007", TRIP_ZL007_DRIFT, CLEAN_ZL007, {"var.py": VAR_PY}),
        ("ZL008", TRIP_ZL008, CLEAN_ZL008, None),
        ("ZL009", TRIP_ZL009_TABLE, CLEAN_ZL009_TABLE,
         {"spc.py": SPC_DOC}),
        ("ZL009", TRIP_ZL009_FSTRING, CLEAN_ZL009_FSTRING,
         {"spc.py": SPC_DOC_TPL}),
        ("ZL009", TRIP_ZL009_UNRESOLVABLE, CLEAN_ZL009_TABLE,
         {"spc.py": SPC_DOC}),
        ("ZL010", TRIP_ZL010_LITERAL, CLEAN_ZL010,
         {"flightrec.py": FLIGHTREC_PY, "ztrace.py": ZTRACE_PY}),
        ("ZL010", TRIP_ZL010_UNDECLARED, CLEAN_ZL010,
         {"flightrec.py": FLIGHTREC_PY, "ztrace.py": ZTRACE_PY}),
        ("ZL010", TRIP_ZL010_UNRESOLVABLE, CLEAN_ZL010,
         {"flightrec.py": FLIGHTREC_PY, "ztrace.py": ZTRACE_PY}),
    ])
    def test_trip_and_clean(self, tmp_path, rule, trip, clean, extra):
        tripped = lint_src(tmp_path / "trip", trip, extra=extra)
        assert rule in rules_of(tripped), (
            f"{rule} did not fire on its tripping fixture: "
            f"{[f.render() for f in tripped.findings]}"
        )
        cleaned = lint_src(tmp_path / "clean", clean, extra=extra)
        assert rule not in rules_of(cleaned), (
            f"{rule} fired on its clean twin: "
            f"{[f.render() for f in cleaned.findings]}"
        )

    def test_zl002_cycle_names_both_locks(self, tmp_path):
        res = lint_src(tmp_path, TRIP_ZL002_CYCLE)
        msgs = [f.message for f in res.findings if f.rule == "ZL002"]
        assert any("_ch_lock" in m and "_rndv_lock" in m for m in msgs)

    def test_zl006_documented_but_never_recorded(self, tmp_path):
        res = lint_src(tmp_path, "x = 1\n", extra={"spc.py": SPC_DOC})
        details = {f.detail for f in res.findings if f.rule == "ZL006"}
        assert "unrecorded:documented_counter" in details

    def test_zl007_inert_without_anchor(self, tmp_path):
        # linting a lone file must not flag unregistered reads — the
        # registry is simply not in the scan set
        res = lint_src(tmp_path, TRIP_ZL007_UNREG)
        assert "ZL007" not in rules_of(res)

    def test_zl009_inert_without_anchor(self, tmp_path):
        res = lint_src(tmp_path, TRIP_ZL009_TABLE)
        assert "ZL009" not in rules_of(res)

    def test_zl009_names_the_leaked_counter(self, tmp_path):
        res = lint_src(tmp_path, TRIP_ZL009_TABLE,
                       extra={"spc.py": SPC_DOC})
        details = {f.detail for f in res.findings if f.rule == "ZL009"}
        assert "undocumented:mystery_dynamic_counter" in details
        # the documented arm of the same table is NOT flagged
        assert not any("documented_counter" in d for d in details)

    def test_zl009_unresolvable_dynamic_name(self, tmp_path):
        res = lint_src(tmp_path, TRIP_ZL009_UNRESOLVABLE,
                       extra={"spc.py": SPC_DOC})
        details = {f.detail for f in res.findings if f.rule == "ZL009"}
        assert "unresolvable" in details

    def test_zl010_inert_without_anchor(self, tmp_path):
        # no flightrec.py/ztrace.py in the scan set = no type table
        res = lint_src(tmp_path, TRIP_ZL010_LITERAL)
        assert "ZL010" not in rules_of(res)

    def test_zl010_names_the_bad_kind(self, tmp_path):
        res = lint_src(
            tmp_path, TRIP_ZL010_LITERAL,
            extra={"flightrec.py": FLIGHTREC_PY,
                   "ztrace.py": ZTRACE_PY})
        details = {f.detail for f in res.findings if f.rule == "ZL010"}
        assert "unknown:flightrec:sennd" in details

    def test_zl010_declared_but_unlisted_kind_flagged(self, tmp_path):
        # STRAY exists as a constant but ALL_KINDS does not list it:
        # consumers enumerate the table, so the kind is undocumented
        res = lint_src(
            tmp_path, TRIP_ZL010_UNDECLARED,
            extra={"flightrec.py": FLIGHTREC_PY,
                   "ztrace.py": ZTRACE_PY})
        details = {f.detail for f in res.findings if f.rule == "ZL010"}
        assert "undeclared:ztrace:STRAY" in details

    def test_rule_table_documents_history(self):
        table = rule_table()
        assert len(table) == 10
        assert all(guards for _, _, guards in table), (
            "every rule must cite the historical bug it encodes"
        )


class TestSuppressions:
    def test_inline_suppression_with_reason(self, tmp_path):
        src = TRIP_ZL003.replace(
            "time.sleep(0.0002)",
            "time.sleep(0.0002)  "
            "# zlint: disable=ZL003 -- test fixture spin",
        )
        res = lint_src(tmp_path, src)
        assert "ZL003" not in rules_of(res)
        assert res.suppressed == 1

    def test_suppression_on_previous_line(self, tmp_path):
        src = TRIP_ZL003.replace(
            "        time.sleep(0.0002)",
            "        # zlint: disable=ZL003 -- fixture\n"
            "        time.sleep(0.0002)",
        )
        res = lint_src(tmp_path, src)
        assert "ZL003" not in rules_of(res)

    def test_reasonless_suppression_is_inert_and_flagged(self, tmp_path):
        src = TRIP_ZL003.replace(
            "time.sleep(0.0002)",
            "time.sleep(0.0002)  # zlint: disable=ZL003",
        )
        res = lint_src(tmp_path, src)
        assert "ZL003" in rules_of(res), "reasonless suppression held"
        assert "ZL000" in rules_of(res), "missing-reason not flagged"

    def test_unrelated_rule_suppression_does_not_cover(self, tmp_path):
        src = TRIP_ZL003.replace(
            "time.sleep(0.0002)",
            "time.sleep(0.0002)  # zlint: disable=ZL001 -- wrong rule",
        )
        res = lint_src(tmp_path, src)
        assert "ZL003" in rules_of(res)


class TestBaseline:
    def test_baselined_finding_is_grandfathered(self, tmp_path):
        raw = lint_src(tmp_path, TRIP_ZL003)
        (key,) = [f.key() for f in raw.findings if f.rule == "ZL003"]
        bl = tmp_path / "baseline.txt"
        bl.write_text(f"# grandfathered\n{key} -- legacy spin fixture\n")
        res = lint_paths([str(tmp_path / "snippet.py")],
                         baseline=str(bl))
        assert "ZL003" not in rules_of(res)
        assert res.baselined == 1

    def test_unjustified_baseline_entry_grandfathers_nothing(self,
                                                            tmp_path):
        raw = lint_src(tmp_path, TRIP_ZL003)
        (key,) = [f.key() for f in raw.findings if f.rule == "ZL003"]
        bl = tmp_path / "baseline.txt"
        bl.write_text(f"{key}\n")  # no ' -- justification'
        res = lint_paths([str(tmp_path / "snippet.py")],
                         baseline=str(bl))
        assert "ZL003" in rules_of(res)

    def test_stale_entries_reported(self, tmp_path):
        (tmp_path / "clean.py").write_text("x = 1\n")
        bl = tmp_path / "baseline.txt"
        bl.write_text("gone.py|ZL003|f|sleep:0 -- was fixed\n")
        res = lint_paths([str(tmp_path / "clean.py")], baseline=str(bl))
        assert res.stale_baseline == ["gone.py|ZL003|f|sleep:0"]

    def test_key_is_line_number_stable(self, tmp_path):
        r1 = lint_src(tmp_path / "a", TRIP_ZL003)
        r2 = lint_src(tmp_path / "b", "\n\n\n# moved down\n" + TRIP_ZL003)
        k1 = [f.key() for f in r1.findings if f.rule == "ZL003"]
        k2 = [f.key() for f in r2.findings if f.rule == "ZL003"]
        assert k1 == k2, "baseline keys must survive line-number drift"


class TestCli:
    def test_exit_codes(self, tmp_path, capsys):
        (tmp_path / "bad.py").write_text(TRIP_ZL003)
        assert zlint_cli.main([str(tmp_path), "--no-baseline"]) == 1
        (tmp_path / "bad.py").write_text(CLEAN_ZL003)
        assert zlint_cli.main([str(tmp_path), "--no-baseline"]) == 0
        capsys.readouterr()

    def test_list_rules(self, capsys):
        assert zlint_cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("ZL001", "ZL008"):
            assert rid in out

    def test_write_baseline_roundtrip(self, tmp_path, capsys):
        (tmp_path / "bad.py").write_text(TRIP_ZL003)
        bl = tmp_path / "bl.txt"
        assert zlint_cli.main([str(tmp_path),
                               "--write-baseline", str(bl)]) == 0
        # the TODO justification counts as a reason — the point of
        # --write-baseline is a reviewable starting file
        assert zlint_cli.main([str(tmp_path),
                               "--baseline", str(bl)]) == 0
        capsys.readouterr()

    def test_parse_error_is_a_finding(self, tmp_path, capsys):
        (tmp_path / "broken.py").write_text("def f(:\n")
        assert zlint_cli.main([str(tmp_path), "--no-baseline"]) == 1
        capsys.readouterr()


class TestWholePackage:
    """The tier-1 wiring: the shipped package lints clean against the
    checked-in baseline.  A new finding anywhere in zhpe_ompi_tpu/
    fails this fast test — the bug classes PRs 1-9 paid to find stay
    mechanically locked out."""

    def test_package_lints_clean(self):
        res = lint_paths([PKG], baseline=default_baseline_path())
        assert res.files > 100, "scan set suspiciously small"
        assert not res.findings, (
            "zlint findings in the package (fix them or justify in "
            "the baseline):\n"
            + "\n".join(f.render() for f in res.findings)
        )

    def test_no_stale_baseline_entries(self):
        res = lint_paths([PKG], baseline=default_baseline_path())
        assert not res.stale_baseline, (
            "baseline entries no longer matched by any finding — "
            f"delete them: {res.stale_baseline}"
        )

    def test_every_suppression_in_package_has_reason(self):
        # reasonless suppressions surface as ZL000 engine findings,
        # which the clean-pass above would catch; this asserts the
        # mechanism itself is exercised by the package (the sanctioned
        # spin sites exist)
        res = lint_paths([PKG], baseline=None)
        assert res.suppressed >= 1, (
            "expected at least one justified inline suppression in "
            "the package (the sanctioned spin sites)"
        )

    def test_fresh_rule_instances_are_reentrant(self):
        # cross-file rules carry per-run state; two back-to-back runs
        # must agree (a leaky registry would double-report)
        r1 = lint_paths([PKG], baseline=None, rules=all_rules())
        r2 = lint_paths([PKG], baseline=None, rules=all_rules())
        assert [f.key() for f in r1.findings] == \
            [f.key() for f in r2.findings]
