"""Deterministic fault injection — the test harness of the ULFM path.

Every recovery mechanism in :mod:`.ulfm` must be exercisable on CPU in
tier-1 without real process death, and *deterministically*: the same plan
kills the same rank at the same operation count every run.  A
:class:`FaultPlan` is that schedule; :meth:`FaultPlan.arm` wraps a rank's
endpoint so its point-to-point operations are counted, and at the chosen
count the rank "dies":

- its heartbeats stop (the universe's :class:`~.ulfm.HeartbeatBoard`
  slot is killed, or the TCP endpoint stops emitting), so the ring
  detector discovers it;
- its transport is severed (TCP sockets closed abruptly, no quiescence —
  the peer sees connection reset, exactly like a real crash);
- :class:`~.ulfm.RankKilled` unwinds the rank's program (a
  ``BaseException``, so recovery code catching ``MpiError`` never
  swallows its own death).

Kill modes: ``"exit"`` (default) — the rank's thread/process unwinds and
the runtime marks the death immediately (a crash); ``"mute"`` — only the
heartbeats stop and nothing is marked, so the *detector* is the only
discovery path (a hang/partition).

Replay integration (:mod:`.vprotocol`): a killed rank that was running
under pessimistic logging can be restarted against its log and, once the
log is exhausted, continue live — see
:class:`~.vprotocol.RejoinContext` and :func:`replay_rejoin`.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Any

from ..coll.host import HostCollectives
from ..coll.nbc import NonblockingCollectives
from ..core import errors
from . import ulfm


class FaultPlan:
    """A deterministic kill schedule: which rank dies after how many
    point-to-point operations (each send/recv/sendrecv counts one),
    and which rank's DEVICE plane wedges after how many train steps
    (:meth:`wedge_device` — the device-plane twin; both schedules
    compose in one plan, the mixed host+device fault storm).

    ``seed`` drives :meth:`random_kill`'s choices, so randomized stress
    runs replay exactly from the seed alone."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._kills: dict[int, tuple[int, str]] = {}
        self._wedges: dict[int, int] = {}
        self._respawns: set[int] = set()
        self._ckpt_faults: dict[int, list[dict]] = {}

    def kill_rank(self, rank: int, after_ops: int,
                  mode: str = "exit") -> "FaultPlan":
        """Schedule `rank` to die when it attempts operation
        ``after_ops + 1`` (i.e. it completes exactly `after_ops` ops)."""
        if mode not in ("exit", "mute"):
            raise errors.ArgError(f"unknown kill mode {mode!r}")
        if after_ops < 0:
            raise errors.ArgError("after_ops must be >= 0")
        self._kills[int(rank)] = (int(after_ops), mode)
        return self

    def random_kill(self, size: int, max_ops: int = 8,
                    mode: str = "exit") -> "FaultPlan":
        """Seed-derived kill: one victim in [0, size), one op count in
        [1, max_ops] — deterministic given the constructor seed."""
        rank = self._rng.randrange(size)
        ops = self._rng.randint(1, max_ops)
        return self.kill_rank(rank, ops, mode)

    def kill_ranks(self, ranks, after_ops: int, mode: str = "exit",
                   respawn: bool = False) -> "FaultPlan":
        """Schedule N victims at once — the multi-failure plan the
        batched recovery pipeline (:func:`~zhpe_ompi_tpu.ft.recovery.
        respawn_victims`) recovers in ONE agree → shrink → respawn
        pass.  ``respawn=True`` marks every victim for respawn."""
        for r in ranks:
            if respawn:
                self.kill_then_respawn(int(r), after_ops, mode)
            else:
                self.kill_rank(int(r), after_ops, mode)
        return self

    def kill_then_respawn(self, rank: int, after_ops: int,
                          mode: str = "exit") -> "FaultPlan":
        """Schedule a kill AND mark the victim for respawn: the recovery
        pipeline (:mod:`.recovery`) queries :attr:`respawn_victims` to
        grow the job back to full size after shrink + rollback — the
        kill-then-respawn plan of the checkpoint-integrated restart
        test harness."""
        self.kill_rank(rank, after_ops, mode)
        self._respawns.add(int(rank))
        return self

    def wedge_device(self, rank: int, after_steps: int) -> "FaultPlan":
        """Schedule `rank`'s DEVICE plane to wedge when it begins step
        ``after_steps + 1`` (it completes exactly `after_steps` steps).
        Nothing exits and nothing stops heartbeating — the process is
        healthy, only its device collective hangs (the XLA-wedge
        failure mode) — so the device liveness probe is the ONLY
        discovery path, exactly the scenario the probe exists for.
        Composes with :meth:`kill_rank`/:meth:`kill_ranks` in one plan
        (mixed host+device fault storms)."""
        if after_steps < 0:
            raise errors.ArgError("after_steps must be >= 0")
        self._wedges[int(rank)] = int(after_steps)
        return self

    def kill_for(self, rank: int) -> tuple[int, str] | None:
        return self._kills.get(rank)

    def wedge_for(self, rank: int) -> int | None:
        return self._wedges.get(int(rank))

    def wants_respawn(self, rank: int) -> bool:
        return int(rank) in self._respawns

    @property
    def victims(self) -> frozenset:
        return frozenset(self._kills)

    @property
    def device_victims(self) -> frozenset:
        return frozenset(self._wedges)

    @property
    def respawn_victims(self) -> frozenset:
        return frozenset(self._respawns)

    # -- checkpoint-seam faults (io/ckptio.py fault points) ---------------

    _CKPT_SEAMS = ("gather", "aggregate", "write", "manifest")

    def ckpt_fault(self, rank: int, seam: str, after: int = 0,
                   action: str = "exit", hold_s: float = 0.0,
                   times: int = 1) -> "FaultPlan":
        """Schedule a fault at a checkpoint seam of ``rank``: the seam
        fires on its occurrence ``after + 1`` (``after`` occurrences
        complete cleanly).  Seams — ``"gather"`` (a non-aggregator's
        shard send), ``"aggregate"`` (an aggregator collecting one of
        its group's shards: the mid-two-phase-exchange kill point),
        ``"write"`` (one deadline-bounded fbtl stream attempt: the
        mid-stream kill / wedge point), ``"manifest"`` (rank 0 about to
        publish).  Actions — ``"exit"`` (thread-plane crash:
        :class:`~.ulfm.RankKilled` unwinds the writer), ``"kill9"``
        (real-process crash: SIGKILL self at the seam), ``"wedge"``
        (sleep ``hold_s`` inside the attempt, pushing it past the
        ``ckpt_write_deadline_s`` watchdog — fires ``times`` times then
        goes inert, so the retry ladder's later attempts succeed)."""
        if seam not in self._CKPT_SEAMS:
            raise errors.ArgError(f"unknown ckpt seam {seam!r}")
        if action not in ("exit", "kill9", "wedge"):
            raise errors.ArgError(f"unknown ckpt fault action {action!r}")
        if after < 0:
            raise errors.ArgError("after must be >= 0")
        self._ckpt_faults.setdefault(int(rank), []).append({
            "seam": seam, "after": int(after), "action": action,
            "hold_s": float(hold_s), "times": int(times),
        })
        return self

    def ckpt_kill_aggregator(self, rank: int, after_shards: int = 0,
                             action: str = "exit") -> "FaultPlan":
        """Kill ``rank`` (an aggregator) mid two-phase exchange, after
        it has collected ``after_shards`` of its group's shards."""
        return self.ckpt_fault(rank, "aggregate", after_shards, action)

    def ckpt_kill_writer(self, rank: int, after_writes: int = 0,
                         action: str = "exit") -> "FaultPlan":
        """Kill ``rank`` mid-stream, after ``after_writes`` completed
        fbtl write attempts."""
        return self.ckpt_fault(rank, "write", after_writes, action)

    def ckpt_wedge_write(self, rank: int, hold_s: float,
                         after: int = 0, times: int = 1) -> "FaultPlan":
        """Wedge ``rank``'s fbtl stream write past its deadline for
        ``times`` attempts (then inert — the retry ladder recovers)."""
        return self.ckpt_fault(rank, "write", after, "wedge",
                               hold_s=hold_s, times=times)

    def ckpt_faults_for(self, rank: int) -> list[dict]:
        return [dict(f) for f in self._ckpt_faults.get(int(rank), [])]

    @property
    def ckpt_victims(self) -> frozenset:
        return frozenset(r for r, fs in self._ckpt_faults.items()
                         if any(f["action"] != "wedge" for f in fs))

    def arm(self, ep) -> "InjectedContext":
        """Wrap one rank's endpoint with op counting + the kill trigger."""
        return InjectedContext(ep, self)

    def arm_ckpt(self, rank: int, ep=None,
                 state=None) -> "CkptSeamContext":
        """Arm one rank's checkpoint-seam faults: the returned context
        manager installs itself as an :func:`~zhpe_ompi_tpu.io.ckptio.
        install_fault_hook` hook for its scope (a no-op forever if this
        rank has no ckpt faults in the plan).  ``ep``/``state`` give the
        ``"exit"`` action its detector bookkeeping + transport kill, the
        :meth:`InjectedContext.die` semantics at a checkpoint seam."""
        return CkptSeamContext(self, int(rank), ep=ep, state=state)

    def arm_device(self, rank: int, state=None,
                   hold: bool = False) -> "WedgedDevice":
        """Arm one rank's device-plane wedge: the returned
        :class:`WedgedDevice` is ticked once per guarded train step and
        fires at the scheduled count (a no-op forever if this rank has
        no wedge in the plan).  ``hold=True`` makes the fired wedge
        ignore :meth:`WedgedDevice.release` — the TRUE-wedge drill: the
        victim process stays parked until the recovery pipeline's
        respawn SIGKILLs the declared-dead incarnation."""
        return WedgedDevice(int(rank), self.wedge_for(rank), state,
                            hold=hold)


class WedgedDevice:
    """One rank's armed device wedge — the injectable stand-in for a
    TPU participant freezing mid-``psum``.

    ``tick()`` once per guarded device-collective region; at the
    scheduled step the wedge FIRES: it registers the expected failure
    (detector-accuracy bookkeeping), exports the probe-child wedge
    hook (``coll/tpu.WEDGE_ENV`` — the rank's own liveness probes now
    hang exactly like its collective would), and parks the calling
    thread.  The park resolves one of two ways:

    - :meth:`release` (the ``DeviceLivenessProbe`` on_fault hook in
      thread-plane drills): the parked "collective" unwinds by raising
      typed :class:`~zhpe_ompi_tpu.core.errors.DeviceFault` — CI can
      drive the whole classify→shrink→remesh ladder in one process;
    - never (real-process drills): the rank stays wedged — healthy
      heartbeats, hung device — until the recovery pipeline's respawn
      SIGKILLs the declared-dead incarnation (the PRRTE contract).
    """

    def __init__(self, rank: int, after_steps: int | None, state=None,
                 hold: bool = False):
        self.rank = int(rank)
        self._at = after_steps
        self._state = state
        self.hold = bool(hold)
        self.steps = 0
        self.fired = False
        self._release = threading.Event()
        self._fault: errors.DeviceFault | None = None

    def tick(self) -> None:
        """One guarded step begins.  Fires the wedge at its count."""
        self.steps += 1
        if self._at is not None and self.steps > self._at \
                and not self.fired:
            self.fire()

    def fire(self) -> None:
        """The wedge: park this thread as the hung collective would.
        The probe-child hook is scoped to THIS rank's probes (a healthy
        survivor sharing the process must not inherit the wedge — its
        own probe answering ok is exactly what keeps it from
        self-classifying); a real-process drill's probes all carry this
        rank's number anyway."""
        self.fired = True
        if self._state is not None:
            ulfm.expect_failure(self._state, self.rank)
        from ..coll import tpu as coll_tpu

        os.environ[coll_tpu.WEDGE_ENV] = str(self.rank)
        self._release.wait()
        raise self._fault or errors.DeviceFault(
            f"rank {self.rank}: wedged device collective classified",
            failed_ranks=[self.rank],
        )

    def release(self, fault: errors.DeviceFault | None = None) -> None:
        """Unwind the parked wedge (classification happened): the
        ``DeviceLivenessProbe`` on_fault hook for in-process drills.
        Also clears the probe-child wedge hook so post-recovery probes
        in this process answer again.  A ``hold=True`` wedge ignores
        this — a real wedge has no unwind; only the respawn's SIGKILL
        ends it."""
        if self.hold:
            return
        from ..coll import tpu as coll_tpu

        self._fault = fault
        os.environ.pop(coll_tpu.WEDGE_ENV, None)
        self._release.set()


class CkptSeamContext:
    """One rank's armed checkpoint-seam faults — the injectable
    stand-in for a writer crashing (or wedging) inside the collective
    checkpoint plane.

    Installed as an ``io/ckptio.py`` fault hook for its ``with`` scope;
    every :func:`~zhpe_ompi_tpu.io.ckptio.fault_point` call for this
    rank counts against the plan's seam schedules.  Firing semantics
    per action:

    - ``"exit"``: the thread-plane crash — expected-failure
      bookkeeping, transport severed, :class:`~.ulfm.RankKilled`
      unwinds whichever thread hit the seam (the async writer's death
      surfaces at the owner's next ``save``/``wait``);
    - ``"kill9"``: the real-process crash — ``SIGKILL`` self, nothing
      unwinds, survivors classify the corpse (the drill that proves a
      torn stream never becomes a complete manifest);
    - ``"wedge"``: sleep inside the write attempt until the
      ``ckpt_write_deadline_s`` watchdog expires it — then inert, so
      the retry ladder's next attempt lands (the bounded-wedge drill).
    """

    def __init__(self, plan: FaultPlan, rank: int, ep=None, state=None):
        self.rank = int(rank)
        self._ep = ep
        self._state = state if state is not None else (
            _state_of(ep) if ep is not None else None)
        self._faults = [dict(f, count=0, fired=0)
                        for f in plan.ckpt_faults_for(rank)]
        self._remove = None
        self._lock = threading.Lock()

    def __enter__(self) -> "CkptSeamContext":
        from ..io import ckptio

        self._remove = ckptio.install_fault_hook(self)
        return self

    def __exit__(self, *exc) -> bool:
        if self._remove is not None:
            self._remove()
            self._remove = None
        return False

    def __call__(self, seam: str, rank: int, **info: Any) -> None:
        if rank != self.rank:
            return
        fire = None
        with self._lock:
            for f in self._faults:
                if f["seam"] != seam:
                    continue
                f["count"] += 1
                if f["count"] <= f["after"] or f["fired"] >= f["times"]:
                    continue
                f["fired"] += 1
                fire = f
                break
        if fire is not None:
            self._fire(fire, seam)

    def _fire(self, f: dict, seam: str) -> None:
        if f["action"] == "wedge":
            time.sleep(f["hold_s"])
            return
        if f["action"] == "kill9":
            os.kill(os.getpid(), signal.SIGKILL)
        if self._state is not None:
            ulfm.expect_failure(self._state, self.rank)
        if self._ep is not None:
            _kill_transport(self._ep, "exit")
        raise ulfm.RankKilled(self.rank, f"ckpt-{seam}")


def corrupt_ckpt_shard(directory: str, step: int | None = None,
                       leaf: int = 0, rank: int = 0) -> str:
    """The corrupt-a-shard-on-disk fault point: flip one manifest-
    recorded shard's bytes (delegates to :func:`~zhpe_ompi_tpu.io.
    ckptio.corrupt_shard`).  Restore must reject the step by digest
    (``ckpt_integrity_rejects``) and degrade to the previous complete
    one — never a silent acceptance, never a raise mid-recovery."""
    from ..io import ckptio

    return ckptio.corrupt_shard(directory, step, leaf, rank)


def _state_of(ep) -> "ulfm.FailureState | None":
    state = getattr(ep, "ft_state", None)
    if state is not None:
        return state
    uni = getattr(ep, "universe", None)
    return getattr(uni, "ft_state", None) if uni is not None else None


def _kill_transport(ep, mode: str) -> None:
    """Make the endpoint look dead to the outside world: silence its
    heartbeats, and for a crash ("exit") sever its transport."""
    uni = getattr(ep, "universe", None)
    if uni is not None and getattr(uni, "ft_board", None) is not None:
        uni.ft_board.kill(ep.rank)
    if hasattr(ep, "sever"):
        if mode == "exit":
            ep.sever()
        else:
            ep.mute()


class InjectedContext:
    """Endpoint proxy that counts operations and fires the plan's kill.

    The point-to-point surface is counted directly (send/recv/sendrecv/
    isend/irecv); collective methods are re-bound to THIS proxy, so their
    internal pt2pt traffic runs through the counted surface and a kill
    scheduled inside a collective fires mid-operation, at a pt2pt
    boundary, the way a real crash lands.  Everything else (ULFM calls,
    attributes) passes through to the wrapped endpoint untouched."""

    # public methods of the collective surfaces get re-bound to the proxy
    _COLL_NAMES = frozenset(
        name
        for base in (HostCollectives, NonblockingCollectives)
        for name in vars(base)
        if not name.startswith("_")
    )

    def __init__(self, ep, plan: FaultPlan):
        self._ep = ep
        self._plan = plan
        self.ops = 0
        kill = plan.kill_for(ep.rank)
        self._kill_at, self._kill_mode = kill if kill else (None, "exit")

    @property
    def rank(self) -> int:
        return self._ep.rank

    @property
    def size(self) -> int:
        return self._ep.size

    @property
    def endpoint(self):
        return self._ep

    def _tick(self) -> None:
        self.ops += 1
        if self._kill_at is not None and self.ops > self._kill_at:
            self.die()

    def die(self) -> None:
        """The kill: register the expected failure (detector-accuracy
        bookkeeping), silence/sever the transport, unwind the program."""
        state = _state_of(self._ep)
        if state is not None:
            ulfm.expect_failure(state, self._ep.rank)
        _kill_transport(self._ep, self._kill_mode)
        raise ulfm.RankKilled(self._ep.rank, self._kill_mode)

    # -- counted pt2pt surface -------------------------------------------

    def send(self, obj: Any, dest: int, tag: int = 0, cid: int = 0) -> None:
        self._tick()
        return self._ep.send(obj, dest, tag, cid)

    def recv(self, *args, **kwargs):
        self._tick()
        return self._ep.recv(*args, **kwargs)

    def sendrecv(self, *args, **kwargs):
        self._tick()
        return self._ep.sendrecv(*args, **kwargs)

    def isend(self, obj: Any, dest: int, tag: int = 0, cid: int = 0):
        self._tick()
        return self._ep.isend(obj, dest, tag, cid)

    def irecv(self, *args, **kwargs):
        self._tick()
        return self._ep.irecv(*args, **kwargs)

    def __getattr__(self, name: str):
        if name in self._COLL_NAMES:
            # look the method up on the endpoint's TYPE (an override like
            # TcpProc.barrier wins) and bind it to the proxy: its
            # self.send/self.recv land on the counted surface above
            fn = getattr(type(self._ep), name, None)
            if callable(fn):
                return fn.__get__(self)
        return getattr(self._ep, name)


def replay_rejoin(logger, rank: int, live_ep):
    """Restart a killed rank: deterministic replay from its pessimistic
    log, then live continuation on `live_ep` once the log is exhausted
    (see :class:`~.vprotocol.RejoinContext`).  Clears the rank's failure
    record so survivors stop classifying it dead — the
    checkpoint-integrated restart hook."""
    state = _state_of(live_ep)
    if state is not None:
        state.restore(rank)
    from .vprotocol import RejoinContext

    return RejoinContext(logger.replay_context(rank), live_ep)
