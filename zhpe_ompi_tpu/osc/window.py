"""One-sided communication (RMA windows) — host plane.

Re-design of ``ompi/mca/osc/rdma`` (SURVEY.md §2.3, §3.5): the reference
drives BTL put/get/atomics directly against registered remote memory
(``osc_rdma_comm.c:98,455,616``).  In the thread-rank universe, every rank's
window buffer IS directly addressable — put/get are memory copies with no
target-side involvement (the literal meaning of RDMA), and accumulate takes
a per-target lock for atomicity (the btl_atomic_op analog).

Synchronization epochs:
- ``fence``   — active target, collective (MPI_Win_fence)
- ``lock/unlock`` — passive target (MPI_Win_lock SHARED/EXCLUSIVE)
- ``post/start/complete/wait_sync`` — PSCW generalized active target

In-process visibility is immediate (stronger than MPI requires); the epoch
calls still enforce the ordering contract so programs written against them
stay correct on the multi-host transport.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from .. import ops as zops
from ..core import errhandler as errh
from ..core import errors
from ..core import info as info_mod
from ..runtime import spc
from . import rma_util

LOCK_SHARED = 1
LOCK_EXCLUSIVE = 2


class _RwLock:
    """Reader-writer lock for passive-target epochs: SHARED holders
    coexist, EXCLUSIVE serializes, FIFO hand-off so writers are not
    starved by a stream of late readers (round-3 fix of shared-behaving-
    exclusive; matches the AM plane's lock manager semantics)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._waiting_writers = 0

    def acquire(self, exclusive: bool) -> None:
        with self._cond:
            if exclusive:
                self._waiting_writers += 1
                self._cond.wait_for(
                    lambda: not self._writer and self._readers == 0
                )
                self._waiting_writers -= 1
                self._writer = True
            else:
                # queue behind any waiting writer (no reader starvation
                # of writers)
                self._cond.wait_for(
                    lambda: not self._writer
                    and self._waiting_writers == 0
                )
                self._readers += 1

    def release(self, exclusive: bool) -> None:
        with self._cond:
            if exclusive:
                self._writer = False
            else:
                self._readers -= 1
            self._cond.notify_all()


class _WinRegistry:
    """Universe-level shared state for one window id."""

    def __init__(self, size: int):
        self.buffers: list[np.ndarray | None] = [None] * size
        # atomic-op serialization (accumulate/CAS): plain mutexes
        self.locks = [threading.RLock() for _ in range(size)]
        # passive-target epochs (MPI_Win_lock): reader-writer semantics
        self.epoch_locks = [_RwLock() for _ in range(size)]
        # dynamic-window state (create_dynamic/attach): per-rank attached
        # regions keyed by displacement (built here, not lazily — lazy init
        # from racing rank threads would clobber attachments)
        self.dynamic: list[dict[int, np.ndarray]] = [
            dict() for _ in range(size)
        ]
        self.dynamic_next = [0] * size
        # PSCW state: per-rank exposure epoch counter (incremented by
        # post) and the identity set of origins completed this epoch
        self.cond = threading.Condition()
        self.post_epochs = [0] * size
        self.completed_by: list[set[int]] = [set() for _ in range(size)]
        self.expected_origins: list[set[int] | None] = [None] * size


class HostWindow(errh.HasErrhandler, rma_util.FetchOpMixin):
    """Per-rank handle to a collectively-created window.

    Windows default to MPI_ERRORS_RETURN (the reference's win default)
    and accept an Info of hints; "no_locks" (an MPI-reserved window key)
    disables the passive-target path."""

    _default_errhandler = errh.ERRORS_RETURN

    _registries: dict[tuple[int, int], _WinRegistry] = {}
    _reg_lock = threading.Lock()
    _next_id = [0]

    @classmethod
    def create(cls, ctx, local_buffer: np.ndarray,
               info=None) -> "HostWindow":
        """MPI_Win_create: collective over the universe."""
        if not isinstance(local_buffer, np.ndarray):
            raise errors.WinError("window buffer must be a numpy array")
        if not local_buffer.flags["C_CONTIGUOUS"]:
            # reshape(-1) on a non-contiguous array returns a COPY; RMA
            # writes would silently vanish
            raise errors.WinError(
                "window buffer must be C-contiguous (RMA writes go through "
                "a flat view)"
            )
        # collective id agreement: rank 0 allocates, broadcasts over pt2pt
        if ctx.rank == 0:
            with cls._reg_lock:
                win_id = cls._next_id[0]
                cls._next_id[0] += 1
                cls._registries[(id(ctx.universe), win_id)] = _WinRegistry(
                    ctx.size
                )
            for r in range(1, ctx.size):
                ctx.send(win_id, dest=r, tag=0x7FFE, cid=0x7FFE)
        else:
            win_id = ctx.recv(source=0, tag=0x7FFE, cid=0x7FFE)
        reg = cls._registries[(id(ctx.universe), win_id)]
        reg.buffers[ctx.rank] = local_buffer
        ctx.barrier()
        return cls(ctx, win_id, reg, info=info)

    def __init__(self, ctx, win_id: int, reg: _WinRegistry, info=None):
        self.ctx = ctx
        self.win_id = win_id
        self._reg = reg
        self.info = info_mod.coerce(info)
        self.name = f"win{win_id}"
        self._held: dict[int, list[int]] = {}  # target -> lock types held
        self._started: list[int] = []  # PSCW access-epoch targets
        self._seen_post = [0] * ctx.size  # last observed exposure epoch

    # -- communication ---------------------------------------------------

    def _target_buf(self, target: int) -> np.ndarray:
        buf = self._reg.buffers[target]
        if buf is None:
            raise errors.WinError(f"rank {target} has no window buffer")
        return buf

    def put(self, data, target: int, offset: int = 0) -> None:
        """MPI_Put: direct write into the target's window."""
        from ..utils import memchecker

        memchecker.check_send_buffer(data, "MPI_Put")
        data = np.asarray(data)
        buf = self._target_buf(target)
        flat = buf.reshape(-1)
        n = data.size
        if offset < 0 or offset + n > flat.size:
            raise errors.WinError(
                f"put of {n} at {offset} overruns window of {flat.size}"
            )
        spc.record("osc_puts", 1)
        spc.record("osc_bytes_put", int(data.nbytes))
        flat[offset : offset + n] = data.reshape(-1).astype(flat.dtype)

    def get(self, target: int, offset: int = 0, count: int | None = None
            ) -> np.ndarray:
        """MPI_Get: direct read of the target's window."""
        buf = self._target_buf(target).reshape(-1)
        count = buf.size - offset if count is None else count
        if offset < 0 or offset + count > buf.size:
            raise errors.WinError("get overruns window")
        spc.record("osc_gets", 1)
        return buf[offset : offset + count].copy()

    def accumulate(self, data, target: int, offset: int = 0,
                   op: zops.Op = zops.SUM) -> None:
        """MPI_Accumulate: atomic read-modify-write (btl_atomic_op analog:
        per-target lock serializes concurrent accumulates)."""
        from ..utils import memchecker

        memchecker.check_send_buffer(data, "MPI_Accumulate")
        data = np.asarray(data)
        flat = self._target_buf(target).reshape(-1)
        n = data.size
        if offset < 0 or offset + n > flat.size:
            raise errors.WinError("accumulate overruns window")
        with self._reg.locks[target]:
            cur = flat[offset : offset + n]
            flat[offset : offset + n] = op(
                data.reshape(-1).astype(flat.dtype), cur
            )

    def get_accumulate(self, data, target: int, offset: int = 0,
                       op: zops.Op = zops.SUM) -> np.ndarray:
        """MPI_Get_accumulate: fetch-and-op."""
        from ..utils import memchecker

        memchecker.check_send_buffer(data, "MPI_Get_accumulate")
        data = np.asarray(data)
        flat = self._target_buf(target).reshape(-1)
        n = data.size
        if offset < 0 or offset + n > flat.size:
            raise errors.WinError(
                f"get_accumulate of {n} at {offset} overruns window of "
                f"{flat.size}"
            )
        with self._reg.locks[target]:
            old = flat[offset : offset + n].copy()
            flat[offset : offset + n] = op(
                data.reshape(-1).astype(flat.dtype), old
            )
        return old

    def compare_and_swap(self, value, compare, target: int, offset: int = 0):
        """MPI_Compare_and_swap (single element)."""
        flat = self._target_buf(target).reshape(-1)
        if not 0 <= offset < flat.size:
            raise errors.WinError(
                f"compare_and_swap offset {offset} outside window of "
                f"{flat.size}"
            )
        with self._reg.locks[target]:
            old = flat[offset].copy()
            if old == compare:
                flat[offset] = value
        return old

    # -- request-based RMA (MPI_Rput/Rget/Raccumulate family) -------------
    # In-process RMA completes immediately (direct memory); the request
    # form exists so programs written against it are portable to the AM
    # plane, where rget/rget_accumulate genuinely overlap.

    def rput(self, data, target: int, offset: int = 0):
        """MPI_Rput."""
        self.put(data, target, offset)
        return rma_util.completed_request()

    def raccumulate(self, data, target: int, offset: int = 0,
                    op: zops.Op = zops.SUM):
        """MPI_Raccumulate."""
        self.accumulate(data, target, offset, op)
        return rma_util.completed_request()

    def rget(self, target: int, offset: int = 0, count: int | None = None):
        """MPI_Rget."""
        return rma_util.completed_request(self.get(target, offset, count))

    def rget_accumulate(self, data, target: int, offset: int = 0,
                        op: zops.Op = zops.SUM):
        """MPI_Rget_accumulate."""
        return rma_util.completed_request(
            self.get_accumulate(data, target, offset, op)
        )

    # -- synchronization -------------------------------------------------

    def fence(self) -> None:
        """MPI_Win_fence: collective epoch boundary."""
        self.ctx.barrier()

    def lock(self, target: int, lock_type: int = LOCK_EXCLUSIVE) -> None:
        """MPI_Win_lock (passive target): genuine reader-writer
        semantics — SHARED holders coexist, EXCLUSIVE serializes
        (round-3 fix; previously shared behaved exclusive)."""
        if self.info.get_bool("no_locks"):
            raise errors.WinError(
                "window created with no_locks=true (MPI info assertion)"
            )
        self._reg.epoch_locks[target].acquire(
            lock_type == LOCK_EXCLUSIVE
        )
        self._held.setdefault(target, []).append(lock_type)

    def unlock(self, target: int) -> None:
        held = self._held.get(target)
        if not held:
            raise errors.WinError(f"unlock of {target} without lock")
        lock_type = held.pop()
        self._reg.epoch_locks[target].release(
            lock_type == LOCK_EXCLUSIVE
        )

    def lock_all(self) -> None:
        """MPI_Win_lock_all: shared access epoch at every target; locks are
        taken in rank order so concurrent lock_all calls cannot deadlock."""
        for t in range(self.ctx.size):
            self.lock(t, LOCK_SHARED)

    def unlock_all(self) -> None:
        for t in range(self.ctx.size):
            self.unlock(t)

    def flush(self, target: int | None = None) -> None:
        """MPI_Win_flush: in-process operations are already visible."""

    def flush_all(self) -> None:
        """MPI_Win_flush_all."""

    def flush_local(self, target: int | None = None) -> None:
        """MPI_Win_flush_local."""

    # -- allocation variants ---------------------------------------------

    @classmethod
    def allocate(cls, ctx, nbytes: int, dtype=np.uint8) -> "HostWindow":
        """MPI_Win_allocate: the window owns its buffer."""
        buf = np.zeros(nbytes // np.dtype(dtype).itemsize, dtype)
        win = cls.create(ctx, buf)
        win.base = buf
        return win

    @classmethod
    def allocate_shared(cls, ctx, nbytes: int, dtype=np.uint8
                        ) -> "HostWindow":
        """MPI_Win_allocate_shared: all ranks' buffers are directly
        loadable/storable by every rank (shared_query).  In-process every
        window is already shared; this variant exposes the direct view."""
        win = cls.allocate(ctx, nbytes, dtype)
        win._shared = True
        return win

    def shared_query(self, target: int) -> np.ndarray:
        """MPI_Win_shared_query: the target's buffer for direct load/store
        (only windows from allocate_shared)."""
        if not getattr(self, "_shared", False):
            raise errors.WinError(
                "shared_query requires a window from allocate_shared"
            )
        return self._target_buf(target)

    # -- dynamic windows --------------------------------------------------
    # MPI_Win_create_dynamic + attach/detach (reference: osc/rdma's dynamic
    # region tree, ompi_osc_rdma_attach).  Dynamic windows are
    # BYTE-addressed, as MPI's are (displacements against MPI_BOTTOM):
    # dyn_put writes raw bytes into the target's attached region, dyn_get
    # returns bytes — the window resolves (displacement -> region) and
    # writes through to the user's array, never a copy.

    @classmethod
    def create_dynamic(cls, ctx) -> "HostWindow":
        """MPI_Win_create_dynamic: starts with no memory."""
        win = cls.create(ctx, np.zeros(0, np.uint8))
        win._is_dynamic = True
        return win

    def attach(self, region: np.ndarray) -> int:
        """Attach local memory; returns the displacement other ranks use
        to address it (MPI hands out the raw address; a handle is the safe
        equivalent)."""
        if not getattr(self, "_is_dynamic", False):
            raise errors.WinError("attach requires a dynamic window")
        if not region.flags["C_CONTIGUOUS"]:
            raise errors.WinError("attached region must be C-contiguous")
        me = self.ctx.rank
        disp = self._reg.dynamic_next[me]
        self._reg.dynamic_next[me] += max(1, region.nbytes)
        self._reg.dynamic[me][disp] = region
        return disp

    def detach(self, disp: int) -> None:
        regions = self._reg.dynamic[self.ctx.rank]
        if disp not in regions:
            raise errors.WinError(f"no region attached at {disp}")
        del regions[disp]

    def _resolve_dynamic(self, target: int, disp: int, nbytes: int
                         ) -> tuple[np.ndarray, int]:
        for base, region in self._reg.dynamic[target].items():
            if base <= disp and disp + nbytes <= base + region.nbytes:
                return region.reshape(-1).view(np.uint8), disp - base
        raise errors.WinError(
            f"RMA [{disp}, {disp + nbytes}) outside attached regions of "
            f"rank {target}"
        )

    def dyn_put(self, data, target: int, disp: int) -> None:
        """Put into a dynamic window: raw bytes of `data` land at byte
        displacement `disp` of the target's attached memory (write-through
        to the attached array)."""
        raw = np.frombuffer(np.ascontiguousarray(data).tobytes(), np.uint8)
        with self._reg.locks[target]:
            view, off = self._resolve_dynamic(target, disp, raw.size)
            view[off : off + raw.size] = raw

    def dyn_get(self, target: int, disp: int, nbytes: int) -> np.ndarray:
        """Get raw bytes from the target's attached memory."""
        with self._reg.locks[target]:
            view, off = self._resolve_dynamic(target, disp, nbytes)
            return view[off : off + nbytes].copy()

    # PSCW generalized active target (MPI_Win_post/start/complete/wait)
    def post(self, origins: list[int] | None = None) -> None:
        """Open an exposure epoch for `origins` (default: all other
        ranks).  The origin IDENTITIES are recorded — wait_sync completes
        only when exactly these origins have completed (round-3 fix:
        counting alone let an uninvited origin satisfy the epoch)."""
        origins = (
            [r for r in range(self.ctx.size) if r != self.ctx.rank]
            if origins is None else list(origins)
        )
        reg = self._reg
        me = self.ctx.rank
        with reg.cond:
            reg.completed_by[me].clear()
            reg.expected_origins[me] = set(origins)
            reg.post_epochs[me] += 1
            reg.cond.notify_all()

    def start(self, targets: list[int], timeout: float = 10.0) -> None:
        """Open an access epoch: wait for each target to post a NEW epoch
        (epoch counters, so back-to-back epochs can't race)."""
        reg = self._reg
        with reg.cond:
            for t in targets:
                if not reg.cond.wait_for(
                    lambda t=t: reg.post_epochs[t] > self._seen_post[t],
                    timeout=timeout,
                ):
                    raise errors.WinError("start: target never posted")
                self._seen_post[t] = reg.post_epochs[t]
        self._started = list(targets)

    def complete(self) -> None:
        """Close the access epoch: notify every started target that this
        origin's RMA operations are done (with the origin's identity)."""
        reg = self._reg
        me = self.ctx.rank
        with reg.cond:
            for t in self._started:
                reg.completed_by[t].add(me)
            reg.cond.notify_all()
        self._started = []

    def wait_sync(self, timeout: float = 10.0) -> None:
        """Close the exposure epoch: block until exactly the posted
        origins have called complete()."""
        reg = self._reg
        me = self.ctx.rank
        with reg.cond:
            expected = reg.expected_origins[me]
            if expected is None:
                raise errors.WinError("wait_sync without a post")
            if not reg.cond.wait_for(
                lambda: expected <= reg.completed_by[me],
                timeout=timeout,
            ):
                missing = expected - reg.completed_by[me]
                raise errors.WinError(
                    f"wait_sync: origins {sorted(missing)} never completed"
                )
            reg.completed_by[me].clear()
            reg.expected_origins[me] = None

    def free(self) -> None:
        """MPI_Win_free: collective; the registry entry is dropped so
        buffers/locks don't leak for the process lifetime."""
        self.ctx.barrier()
        self._reg.buffers[self.ctx.rank] = None
        self.ctx.barrier()
        with HostWindow._reg_lock:
            HostWindow._registries.pop(
                (id(self.ctx.universe), self.win_id), None
            )
