"""Multi-host DVM tree — the routed half of the PRRTE analog.

PR 8's :mod:`.dvm` is ONE resident daemon: every rank of every job
modexes into one PMIx listener, every fault event fans out of one
socket, and launch traffic scales O(n) into one accept loop.  PRRTE's
whole value is that it is a *routed tree* of daemons — one ``prted``
per host, parent/child links, launch/modex/fault traffic climbing and
descending the tree so no single socket sees more than its subtree.
This module is that layer:

- **routed store** (:class:`RoutedStore`): a child daemon's store-verb
  surface.  Writes (``put``/``commit``/``fence``/``mkns``/…) forward UP
  the tree to the root's authoritative :class:`~zhpe_ompi_tpu.runtime.
  pmix.PmixStore`; reads (``get``) serve from a leaf-local cache, so a
  rank only ever talks to ITS host's daemon and the root's listener
  sees one fetch per (daemon, key) instead of one per (rank, key).
  Cache coherence rides the store's generation machinery: published
  entries are immutable within a namespace generation (the store
  contract — republishing a key is always preceded by a generation
  bump, e.g. a respawn window), and generation bumps ride the parent
  link DOWN the tree as invalidations (:meth:`RoutedStore.
  invalidate_ns`).  ``lookup`` (the non-blocking introspection verb —
  metrics, resize events) always forwards: its keys are mutable.
- **tree links**: a child daemon holds ONE persistent connection to its
  parent's control port (:class:`TreeLink`) — ``["up", kind, payload]``
  frames climb (IOF, exit accounting, daemon membership), ``["down",
  kind, payload]`` frames descend (spawn commands, fault floods,
  generation invalidations).  The parent half (:class:`ChildLink`)
  lives inside the parent daemon's attach handler.
- **tree shape** (:func:`plan_tree`): parent assignment per
  ``dvm_tree_fanout`` — ``f >= 1`` builds the classic fanout-f tree
  (daemon ``i``'s parent is ``(i-1)//f``), ``f <= 0`` the flat star
  (every child attaches straight to the root).
- **harness** (:func:`spawn_tree`): build an n-daemon tree in-process
  (tests, thread-fast) or as real ``zprted --parent`` OS processes
  (the kill-a-daemon drill, the launch-latency ladder's depth rows).

Counters (documented in :mod:`zhpe_ompi_tpu.runtime.spc`):
``dvm_tree_forwards`` (verbs a child pushed up), ``dvm_store_cache_hits``
(gets served leaf-locally).  The OSU ``--launch`` ladder gates on the
two moving in opposite directions at depth >= 1.

Hygiene is observable: every routed store registers weakly and
:func:`stale_cache_state` must be empty at session end — a closed
child daemon holds no cached keys, and no routed store outlives its
daemon (the conftest session gate asserts it).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import weakref
from typing import Any, Callable

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from . import pmix as pmix_mod
from . import spc

_stream = mca_output.open_stream("dvmtree")

mca_var.register(
    "dvm_tree_fanout", 2,
    "Children per daemon when building a DVM tree (plan_tree/"
    "spawn_tree): f >= 1 is the fanout-f tree (daemon i's parent is "
    "(i-1)//f), f <= 0 the flat star (every child attaches straight "
    "to the root)",
    type=int,
)

mca_var.register(
    "dvm_store_cache_ttl", 0.0,
    "Age bound (seconds) on a child daemon's leaf-local store cache "
    "entries; 0 (the default) trusts generation invalidations alone — "
    "published keys are immutable within a namespace generation, so "
    "expiry is only a belt-and-braces bound for foreign stores that "
    "break that contract",
    type=float,
)

_live_routed: weakref.WeakSet = weakref.WeakSet()


def stale_cache_state() -> list[str]:
    """Routed-store cache state still held at session end — a closed
    store holds nothing, and no store may outlive its daemon's stop()
    (the session gate's view)."""
    out = []
    for store in list(_live_routed):
        if store.open:
            out.append(f"routed-store:{store.parent[0]}:{store.parent[1]}"
                       ":still-open")
            continue
        keys = store.cached_keys()
        if keys:
            out.append(
                f"routed-store:{store.parent[0]}:{store.parent[1]}:"
                f"{len(keys)} cached keys past close()")
    return out


def plan_tree(n: int, fanout: int | None = None) -> list[int | None]:
    """Parent INDEX per daemon for an n-daemon tree (index 0 is the
    root, parent ``None``).  ``fanout`` defaults to the
    ``dvm_tree_fanout`` MCA var; ``<= 0`` means flat star."""
    f = int(mca_var.get("dvm_tree_fanout", 2)) if fanout is None \
        else int(fanout)
    out: list[int | None] = [None]
    for i in range(1, max(1, int(n))):
        out.append(0 if f <= 0 else (i - 1) // f)
    return out


def block_placement(ranks: list[int], daemons: list[str]
                    ) -> dict[int, str]:
    """Contiguous near-even blocks of ``ranks`` over ``daemons`` (the
    by-host placement PRRTE's round-robin-by-node defaults to for
    dense jobs): rank r lands on ``daemons[(i * len(daemons)) //
    len(ranks)]`` for its position i."""
    if not daemons:
        raise errors.InternalError("dvm tree: no daemons to place on")
    n = len(ranks)
    return {
        r: daemons[(i * len(daemons)) // n]
        for i, r in enumerate(sorted(int(r) for r in ranks))
    }


# -- multi-tenant placement -------------------------------------------------

#: the placement ladder: every policy degrades to the one before it
#: when the tree is too small, never the other way around
PLACEMENT_POLICIES = ("pack", "spread", "exclusive")

mca_var.register(
    "dvm_placement", "pack",
    "Multi-tenant placement policy for daemon-tree jobs: 'pack' "
    "block-places over all daemons in attach order (the single-tenant "
    "default), 'spread' block-places over the daemons ordered "
    "least-loaded first (co-tenants naturally claim different "
    "subtrees while capacity allows), 'exclusive' claims only "
    "daemons no live job uses and fails over to spread — loudly, "
    "counted in dvm_placement_fallbacks — when none are free; a "
    "launch spec's placement= overrides per job",
)


def place_job(ranks: list[int], daemons: list[str],
              busy: dict[str, int], policy: str
              ) -> tuple[dict[int, str], bool]:
    """Placement for one new job under the multi-tenant ladder.

    ``busy`` maps daemon id -> count of LIVE jobs already placed on it
    (the root computes it from its job table).  Returns ``(placement,
    fell_back)`` — ``fell_back`` is True only for an exclusive request
    that found no free daemon and degraded to spread (the caller
    reports it loudly and counts ``dvm_placement_fallbacks``).

    - ``pack``: :func:`block_placement` over attach order — dense,
      single-tenant shape, co-tenants overlap.
    - ``spread``: block placement over the first ``len(ranks)``
      daemons sorted least-loaded first (ties broken by attach
      order).  The minimal claim is the point: a k-rank job touches
      only the k least-loaded daemons, so two spread tenants land on
      disjoint subtrees whenever there are enough daemons — claiming
      the whole load order (an earlier draft) put rank k-1 back onto
      a busy daemon and broke exactly that.
    - ``exclusive``: place ONLY on daemons with zero live jobs,
      claiming the minimal prefix (len(ranks) at most) so successive
      exclusive tenants can coexist; no free daemon at all means
      fallback to spread.
    """
    policy = str(policy or "pack")
    if policy not in PLACEMENT_POLICIES:
        raise errors.ArgError(
            f"dvm placement: unknown policy {policy!r} "
            f"(one of {'/'.join(PLACEMENT_POLICIES)})")
    if not daemons:
        raise errors.InternalError("dvm tree: no daemons to place on")
    if policy == "pack":
        return block_placement(ranks, daemons), False
    order = {d: i for i, d in enumerate(daemons)}
    by_load = sorted(daemons,
                     key=lambda d: (busy.get(d, 0), order[d]))
    if policy == "spread":
        return block_placement(
            ranks, by_load[:max(1, len(ranks))]), False
    free = [d for d in by_load if busy.get(d, 0) == 0]
    if not free:
        return block_placement(ranks, by_load), True
    return block_placement(ranks, free[:max(1, len(ranks))]), False


_audit_failures: list[str] = []
_audit_lock = threading.Lock()


def placement_audit_failures() -> list[str]:
    """Recorded placement-audit violations — must be [] at session end
    (the conftest gate): an audit failure means two live jobs were
    about to share sm-segment prefixes, namespaces, or an exclusive
    subtree, and the offending launch was failed loudly."""
    with _audit_lock:
        return list(_audit_failures)


def clear_placement_audit_failures() -> None:
    with _audit_lock:
        _audit_failures.clear()


def _sessions_collide(a: str, b: str) -> bool:
    # the /dev/shm sweep keys on "<prefix>_{session}_": equality OR a
    # prefix-with-underscore relation would let one job's sweep (or
    # segment namespace) reach the other's files
    return a == b or b.startswith(a + "_") or a.startswith(b + "_")


def audit_placement(new_job: dict, live_jobs: list[dict]) -> None:
    """Per-job placement audit at admission: prove the new job's
    runtime state is disjoint from every LIVE co-tenant's.

    Each job dict carries ``id`` (the PMIx namespace — cid windows are
    coordinated per namespace, so distinct ids imply disjoint cid
    state), ``session`` (the sm-segment / sweep prefix tag) and
    ``daemons`` (the placed daemon set) plus ``exclusive`` (the job
    demanded — and got — an exclusive subtree).  A violation is typed
    (:class:`~zhpe_ompi_tpu.core.errors.PlacementViolation`), recorded
    for the session gate, counted (``dvm_placement_audit_failures``),
    and raised so the launch fails loudly instead of admitting a
    tenant that could corrupt a neighbour."""
    for other in live_jobs:
        if other["id"] == new_job["id"]:
            viol = errors.PlacementViolation(
                f"placement audit: job id/namespace {new_job['id']!r} "
                "already live (cid windows would collide)",
                jobs=(new_job["id"], other["id"]), prop="namespace")
        elif _sessions_collide(str(new_job["session"]),
                               str(other["session"])):
            viol = errors.PlacementViolation(
                f"placement audit: session tag {new_job['session']!r} "
                f"collides with live job {other['id']!r}'s "
                f"{other['session']!r} (sm segments / shm sweep would "
                "cross tenants)",
                jobs=(new_job["id"], other["id"]), prop="session")
        elif (new_job.get("exclusive") or other.get("exclusive")) \
                and set(new_job["daemons"]) & set(other["daemons"]):
            shared = sorted(set(new_job["daemons"])
                            & set(other["daemons"]))
            viol = errors.PlacementViolation(
                f"placement audit: exclusive subtree violated — jobs "
                f"{new_job['id']!r}/{other['id']!r} share daemons "
                f"{shared}",
                jobs=(new_job["id"], other["id"]), prop="subtree")
        else:
            continue
        with _audit_lock:
            _audit_failures.append(str(viol))
        spc.record("dvm_placement_audit_failures")
        raise viol


class RoutedStore:
    """Store-verb surface of a CHILD daemon: same method signatures as
    :class:`~zhpe_ompi_tpu.runtime.pmix.PmixStore` (so a
    ``PmixServer`` serves ranks from either), but writes forward UP to
    the parent and ``get`` serves a leaf-local cache.

    Forwarding is per-calling-thread (one persistent
    :class:`~zhpe_ompi_tpu.runtime.pmix.PmixClient` per handler
    thread): a blocking verb — a rank's modex ``fence`` parked at the
    root until the whole namespace enters — parks only ITS handler
    thread's upstream connection, never another rank's ``get``.

    Cache-miss fetches are SINGLE-FLIGHT per (ns, key): concurrent
    first readers of one key coalesce into one upward fetch, and the
    waiters count as cache hits once it lands — the hit/forward
    counters the launch ladder gates on are deterministic, not
    scheduling noise.
    """

    def __init__(self, parent_pmix: "tuple[str, int] | str",
                 timeout: float = 30.0):
        self.parent = pmix_mod.parse_addr(parent_pmix)
        self._timeout = timeout
        self.open = True
        # ns -> key -> (generation, value, cached_at, fill_floor)
        self._cache: dict[
            str, dict[str, tuple[int, Any, float, int]]] = {}
        # ns -> highest namespace generation this daemon has LEARNED
        # (gen-carrying invalidations + observed fill tags).  Entries
        # filled under an older floor are never served again: a
        # respawn's republished card can overwrite a key at the root,
        # and a warm leaf entry fetched before the bump would otherwise
        # keep serving the corpse incarnation's value to default-
        # min_generation getters (the PR 8 race, through the tree path)
        self._ns_gen: dict[str, int] = {}
        self._fetching: set[tuple[str, str]] = set()
        self._cv = threading.Condition()
        self._tls = threading.local()
        self._clients: list[pmix_mod.PmixClient] = []
        self._clients_lock = threading.Lock()
        _live_routed.add(self)

    # -- upstream plumbing ------------------------------------------------

    def _up(self) -> pmix_mod.PmixClient:
        cli = getattr(self._tls, "client", None)
        if cli is None:
            cli = pmix_mod.PmixClient(self.parent, timeout=self._timeout)
            self._tls.client = cli
            with self._clients_lock:
                self._clients.append(cli)
        return cli

    def _forward(self, verb: str, *args, **kw) -> Any:
        if not self.open:
            raise errors.InternalError(
                "routed store closed (daemon stopping)")
        spc.record("dvm_tree_forwards")
        return getattr(self._up(), verb)(*args, **kw)

    # -- cached read path -------------------------------------------------

    def get(self, ns: str, key: str, timeout: float = 30.0,
            min_generation: int = 0) -> Any:
        value, _gen = self.get_meta(ns, key, timeout, min_generation)
        return value

    def get_meta(self, ns: str, key: str, timeout: float = 30.0,
                 min_generation: int = 0) -> tuple[Any, int]:
        """Blocking get-until-published with the leaf cache in front:
        a fresh-enough cached entry is served locally
        (``dvm_store_cache_hits``); a miss forwards up
        (``dvm_tree_forwards``) and caches the result.  ``min_generation``
        is honored against the cached entry's tag — a recovery window's
        insistence on a fresh card can never be satisfied by the
        corpse's cached one."""
        ns, key = str(ns), str(key)
        ttl = float(mca_var.get("dvm_store_cache_ttl", 0.0))
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                floor = self._ns_gen.get(ns, 0)
                hit = self._cache.get(ns, {}).get(key)
                if hit is not None and hit[0] >= int(min_generation) \
                        and hit[3] >= floor \
                        and (ttl <= 0
                             or time.monotonic() - hit[2] <= ttl):
                    spc.record("dvm_store_cache_hits")
                    spc.record("store_leaf_cache_hits")
                    return hit[1], hit[0]
                if not self.open:
                    raise errors.InternalError(
                        "routed store closed (daemon stopping)")
                if (ns, key) not in self._fetching:
                    self._fetching.add((ns, key))
                    fill_floor = floor
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    raise errors.InternalError(
                        f"routed get({ns!r}, {key!r}): in-flight fetch "
                        f"did not land within {timeout}s")
                self._cv.wait(min(left, 0.25))
        try:
            # the forward happens OUTSIDE the cache lock: a parked
            # get-until-published upstream must never wedge local hits
            spc.record("dvm_tree_forwards")
            spc.record("store_leaf_cache_misses")
            value, gen = self._up().get_meta(ns, key, timeout,
                                             min_generation)
        except BaseException:
            with self._cv:
                self._fetching.discard((ns, key))
                self._cv.notify_all()
            raise
        # cache fill and marker discard are ONE critical section: a
        # waiter waking between them would see miss + no in-flight
        # marker and launch a duplicate upstream fetch (the hit
        # counters the launch ladder gates on must be deterministic)
        with self._cv:
            if self.open:
                # a fill tag NEWER than the known floor teaches us the
                # namespace moved on; a floor that advanced DURING the
                # fetch (a bump invalidation raced the forward) marks
                # this value as possibly the pre-bump incarnation's —
                # cache it under the old floor so it is never served
                if int(gen) > self._ns_gen.get(ns, 0):
                    self._ns_gen[ns] = int(gen)
                self._cache.setdefault(ns, {})[key] = (
                    int(gen), value, time.monotonic(),
                    max(fill_floor, int(gen)))
            self._fetching.discard((ns, key))
            self._cv.notify_all()
        return value, int(gen)

    # -- forwarded verbs --------------------------------------------------

    def put(self, ns: str, rank: int, key: str, value: Any) -> None:
        self._forward("put", ns, int(rank), str(key), value)

    def commit(self, ns: str, rank: int) -> int:
        return int(self._forward("commit", ns, int(rank)))

    def fence(self, ns: str, rank: int, timeout: float = 30.0) -> None:
        self._forward("fence", ns, int(rank), float(timeout))

    def ensure_ns(self, ns: str, size: int) -> None:
        self._forward("ensure_ns", ns, int(size))

    def destroy_ns(self, ns: str) -> bool:
        self.forget_ns(ns)
        return bool(self._forward("destroy_ns", ns))

    def bump_generation(self, ns: str) -> int:
        # a bump through THIS daemon invalidates its own cache eagerly;
        # the root's broadcast covers every other daemon
        self.invalidate_ns(ns)
        gen = int(self._forward("bump_generation", ns))
        self.invalidate_ns(ns, gen=gen)  # raise the bucket floor too
        return gen

    def generation(self, ns: str) -> int:
        return int(self._forward("generation", ns))

    def lookup(self, ns: str, prefix: str | None = None) -> dict:
        # NEVER cached: lookup keys (metrics snapshots, resize events)
        # are the mutable part of the store contract
        return self._forward("lookup", ns, prefix)

    def namespaces(self) -> list[str]:
        return list(self._forward("stat").keys())

    def stat(self) -> dict:
        return self._forward("stat")

    # -- coherence / lifecycle --------------------------------------------

    def invalidate_ns(self, ns: str, gen: "int | None" = None) -> None:
        """Drop every cached entry of ``ns`` — the generation-bump (or
        namespace-destroy) invalidation riding the parent link.  A
        gen-carrying invalidation also raises the bucket's generation
        FLOOR, so an in-flight fetch that started before the bump can
        never park its (possibly pre-bump) value back into the warm
        cache as servable."""
        with self._cv:
            self._cache.pop(str(ns), None)
            if gen is not None:
                self._ns_gen[str(ns)] = max(
                    self._ns_gen.get(str(ns), 0), int(gen))
            self._cv.notify_all()

    def forget_ns(self, ns: str) -> None:
        """Namespace DESTROYED: drop its cache bucket AND its
        generation floor — a later namespace reusing the name starts
        over at generation 0, and a stale floor would wrongly embargo
        every entry it publishes."""
        with self._cv:
            self._cache.pop(str(ns), None)
            self._ns_gen.pop(str(ns), None)
            self._cv.notify_all()

    def cached_keys(self) -> list[str]:
        with self._cv:
            return sorted(
                f"{ns}:{key}"
                for ns, kv in self._cache.items()
                for key in kv
            )

    def cache_info(self) -> dict[str, int]:
        with self._cv:
            return {ns: len(kv) for ns, kv in self._cache.items()}

    def close(self) -> None:
        """Drop the cache, close every upstream connection, error out
        parked fetch waiters — the owning PmixServer calls this on its
        own close (store-compatible surface)."""
        with self._cv:
            self.open = False
            self._cache.clear()
            self._cv.notify_all()
        with self._clients_lock:
            clients, self._clients = list(self._clients), []
        for cli in clients:
            cli.close()


class ChildLink:
    """Parent half of one tree link: registered by the attach handler,
    holds the child's identity, its known subtree membership, and the
    connection downward frames ride."""

    def __init__(self, info: dict, conn, conn_lock):
        self.id = str(info["id"])
        self.control = tuple(info.get("control") or ("", 0))
        self.pmix = tuple(info.get("pmix") or ("", 0))
        self.conn = conn
        self.conn_lock = conn_lock
        # every daemon id reachable through this link (the child plus
        # whatever it later reports via daemon-up) — targeted downward
        # routing resolves against this set
        self.daemons: set[str] = {self.id}
        self.detached = False

    def send_down(self, kind: str, payload: Any) -> None:
        from ..pt2pt.tcp import _send_frame
        from ..utils import dss

        with self.conn_lock:
            _send_frame(self.conn, dss.pack(["down", str(kind), payload]))


class TreeLink:
    """Child half of the parent link: one persistent connection to the
    parent daemon's control port.  The constructor performs the attach
    handshake synchronously (send ``["attach", info]``, read the
    ``["ok", meta]`` reply); :meth:`start` launches the reader thread
    that dispatches downward frames and reports a lost parent."""

    def __init__(self, parent_addr: tuple[str, int], info: dict,
                 on_down: Callable[[str, Any], None],
                 on_lost: Callable[[], None], timeout: float = 30.0):
        import socket as socket_mod

        from ..pt2pt.tcp import _recv_frame, _send_frame
        from ..utils import dss

        self.parent = pmix_mod.parse_addr(parent_addr)
        self._on_down = on_down
        self._on_lost = on_lost
        self._closed = False
        self._send_lock = threading.Lock()
        self._sock = socket_mod.socket(socket_mod.AF_INET,
                                       socket_mod.SOCK_STREAM)
        self._sock.settimeout(timeout)
        try:
            self._sock.connect(self.parent)
            _send_frame(self._sock, dss.pack(["attach", info]))
            frame = _recv_frame(self._sock)
            if frame is None:
                raise errors.InternalError(
                    f"dvm tree: parent at {self.parent} closed the "
                    "attach handshake")
            [status, meta] = dss.unpack(frame)[0]
            if status != "ok":
                raise errors.InternalError(f"dvm tree attach: {meta}")
            self.meta = meta
        except (OSError, errors.MpiError) as e:
            try:
                self._sock.close()
            except OSError:
                pass
            if isinstance(e, errors.MpiError):
                raise
            raise errors.InternalError(
                f"dvm tree: no parent daemon at {self.parent}: {e}"
            ) from e
        self._sock.settimeout(None)
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"dvm-tree-link-{self.parent[1]}",
        )

    def start(self) -> None:
        self._reader.start()

    def _read_loop(self) -> None:
        from ..pt2pt.tcp import _recv_frame
        from ..utils import dss

        try:
            while not self._closed:
                frame = _recv_frame(self._sock)
                if frame is None:
                    break
                try:
                    [msg] = dss.unpack(frame)
                    if msg[0] != "down":
                        continue  # foreign frame shape: ignore, stay up
                    self._on_down(str(msg[1]), msg[2])
                except errors.MpiError as e:
                    # a handler that raises must not kill the link —
                    # but the drop is LOUD: a swallowed down-frame is a
                    # lost fault flood or invalidation
                    mca_output.emit(
                        _stream,
                        "tree link: down-frame handler failed (%s) — "
                        "frame dropped", e,
                    )
        except OSError:
            pass
        finally:
            if not self._closed:
                self._on_lost()

    def send_up(self, kind: str, payload: Any) -> None:
        """One upward frame; raises ``OSError`` when the parent is gone
        (the reader's on_lost owns the policy)."""
        from ..pt2pt.tcp import _send_frame
        from ..utils import dss

        with self._send_lock:
            _send_frame(self._sock, dss.pack(["up", str(kind), payload]))

    def detach(self) -> None:
        """Orderly goodbye: tell the parent this daemon is leaving on
        purpose (no ranks re-classified), then close the link."""
        try:
            self.send_up("detach", None)
        except OSError:
            pass
        self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        import socket as socket_mod

        try:
            self._sock.shutdown(socket_mod.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._reader.is_alive() \
                and self._reader is not threading.current_thread():
            self._reader.join(timeout=5.0)


class DvmTree:
    """Harness handle over an n-daemon tree (tests/benchmarks): the
    root first, children in :func:`plan_tree` order.  ``stop()`` tears
    the tree down leaves-first so no child ever classifies an orderly
    shutdown as a lost parent."""

    def __init__(self, nodes: list[dict]):
        self.nodes = nodes

    @property
    def root(self):
        return self.nodes[0].get("dvm")

    @property
    def root_address(self) -> tuple[str, int]:
        return tuple(self.nodes[0]["address"])

    def addresses(self) -> list[tuple[str, int]]:
        return [tuple(n["address"]) for n in self.nodes]

    def stop(self) -> None:
        from . import dvm as dvm_mod

        for node in reversed(self.nodes):
            d = node.get("dvm")
            if d is not None:
                d.stop()
                continue
            p: subprocess.Popen | None = node.get("proc")
            if p is None or p.poll() is not None:
                continue
            try:
                cli = dvm_mod.DvmClient(tuple(node["address"]),
                                        timeout=10.0)
                try:
                    cli.stop()
                finally:
                    cli.close()
            except errors.MpiError:
                pass  # already dying: the kill below reaps it
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def kill_node(self, index: int, sig) -> None:
        """SIGKILL-style death injection for subprocess nodes (the
        kill-a-daemon drill)."""
        p = self.nodes[index].get("proc")
        if p is None:
            raise errors.ArgError(
                "kill_node needs a subprocess daemon (in_process trees "
                "stop, they don't die)")
        p.send_signal(sig)
        p.wait(timeout=10.0)


def spawn_tree(n: int, fanout: int | None = None,
               host: str = "127.0.0.1", in_process: bool = True,
               timeout: float = 60.0) -> DvmTree:
    """Build an n-daemon DVM tree: the root, then each child attached
    per :func:`plan_tree`.  ``in_process=True`` constructs
    :class:`~zhpe_ompi_tpu.runtime.dvm.Dvm` objects in this process
    (thread-fast tests; counters shared); ``False`` spawns real
    ``zprted --parent`` OS processes (the drill / ladder shape) and
    parses their ready lines."""
    from . import dvm as dvm_mod

    parents = plan_tree(n, fanout)
    nodes: list[dict] = []
    try:
        for i, parent_idx in enumerate(parents):
            parent_addr = None if parent_idx is None \
                else tuple(nodes[parent_idx]["address"])
            if in_process:
                d = dvm_mod.Dvm(host=host, parent=parent_addr)
                nodes.append({"address": d.address,
                              "pmix": d.pmix.address, "dvm": d,
                              "proc": None})
                continue
            cmd = [sys.executable, "-m", "zhpe_ompi_tpu.runtime.dvm",
                   "--host", host]
            if parent_addr is not None:
                cmd += ["--parent", f"{parent_addr[0]}:{parent_addr[1]}"]
            env = dict(os.environ)
            pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            parts = env.get("PYTHONPATH", "").split(os.pathsep)
            if pkg_root not in parts:
                env["PYTHONPATH"] = os.pathsep.join(
                    [pkg_root] + [p for p in parts if p])
            p = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            ready = _read_ready_line(p, timeout)
            addr = pmix_mod.parse_addr(ready.split("dvm=")[1].split()[0])
            pmix_addr = pmix_mod.parse_addr(
                ready.split("pmix=")[1].split()[0])
            nodes.append({"address": addr, "pmix": pmix_addr,
                          "dvm": None, "proc": p})
        # the whole tree is placeable before the harness returns: a
        # DIRECT child registers synchronously inside its attach
        # handshake, but a grandchild's daemon-up frame relays through
        # its parent asynchronously — a launch racing that relay would
        # place ranks on a partial tree
        deadline = time.monotonic() + timeout
        while True:
            root = nodes[0].get("dvm")
            known = len(root._placement_ids) if root is not None \
                else len(dvm_mod._tree_query(tuple(nodes[0]["address"]))
                         .get("daemons") or ())
            if known >= len(nodes):
                break
            if time.monotonic() > deadline:
                raise errors.InternalError(
                    f"dvm tree: root knows {known}/{len(nodes)} "
                    "daemons after spawn")
            time.sleep(0.01)
    except BaseException:
        DvmTree(nodes).stop()
        raise
    return DvmTree(nodes)


def _read_ready_line(p: subprocess.Popen, timeout: float) -> str:
    """Bounded read of a zprted subprocess's ready line: a daemon that
    dies before announcing must fail the spawn, not hang it."""
    import select

    deadline = time.monotonic() + timeout
    r, _, _ = select.select([p.stdout], [], [],
                            max(0.0, deadline - time.monotonic()))
    if not r:
        raise errors.InternalError(
            "zprted child never printed its ready line")
    line = p.stdout.readline()
    if not line.startswith("zprted ready"):
        err = p.stderr.read() if p.poll() is not None else ""
        raise errors.InternalError(
            f"zprted child failed to start: {line!r} {err}")
    return line
