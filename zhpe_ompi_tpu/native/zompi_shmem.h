/* zompi_shmem.h — shmem.h-compatible C OSHMEM surface over the host
 * plane (reference: ``oshmem/shmem/c``, 56 binding files; the OpenSHMEM
 * C API the reference ships next to mpi.h).
 *
 * Re-designed over the shim's window engine instead of a fabric's RDMA
 * verbs: the symmetric heap is a malloc'd arena registered as an
 * internal MPI window over WORLD; symmetric allocation is a lockstep
 * deterministic allocator (identical call sequences -> identical
 * offsets, the reference memheap contract, memheap_base_alloc.c); RMA
 * lowers onto the window's drain-applied put/get tuples; atomics are
 * the fetch-AMO RPC applied under the target's window lock
 * (oshmem/shmem/c/shmem_fadd.c semantics: the service loop is the
 * serialization point); collectives ride the MPI collectives
 * (scoll/mpi's reuse trick).
 *
 * Launch contract: same ZMPI_* env as mpi.h ranks (one universe; a
 * program may use both APIs).  Heap size: ZMPI_SHMEM_HEAP bytes
 * (default 1 MiB) — the SHMEM_SYMMETRIC_SIZE analog.
 *
 * Reductions use the OpenSHMEM-1.4 style (dest, source, nreduce)
 * signatures (no pWrk/pSync scratch arrays — the transport needs none).
 */

#ifndef ZOMPI_SHMEM_H
#define ZOMPI_SHMEM_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* init / identity (shmem_init.c) */
int shmem_init(void);
void shmem_finalize(void);
int shmem_my_pe(void);
int shmem_n_pes(void);

/* symmetric heap (shmem_malloc.c; collective) */
void *shmem_malloc(size_t size);
void *shmem_calloc(size_t count, size_t size);
void shmem_free(void *ptr);

/* ordering / completion (shmem_quiet.c, shmem_fence.c) */
void shmem_quiet(void);
void shmem_fence(void);
void shmem_barrier_all(void);

/* contiguous RMA (shmem_put.c / shmem_get.c family) */
void shmem_putmem(void *dest, const void *source, size_t nbytes, int pe);
void shmem_getmem(void *dest, const void *source, size_t nbytes, int pe);
/* implicit-handle nonblocking RMA (shmem_put_nb.c / shmem_get_nb.c):
 * completion no later than shmem_quiet / shmem_barrier_all */
void shmem_putmem_nbi(void *dest, const void *source, size_t nbytes,
                      int pe);
void shmem_getmem_nbi(void *dest, const void *source, size_t nbytes,
                      int pe);
void shmem_long_put(long *dest, const long *source, size_t nelems, int pe);
void shmem_long_get(long *dest, const long *source, size_t nelems, int pe);
void shmem_double_put(double *dest, const double *source, size_t nelems,
                      int pe);
void shmem_double_get(double *dest, const double *source, size_t nelems,
                      int pe);

/* single-element RMA (shmem_p.c / shmem_g.c) */
void shmem_long_p(long *addr, long value, int pe);
long shmem_long_g(const long *addr, int pe);
void shmem_double_p(double *addr, double value, int pe);
double shmem_double_g(const double *addr, int pe);

/* atomics (shmem_fadd.c / shmem_swap.c / shmem_cswap.c family) */
void shmem_long_atomic_add(long *target, long value, int pe);
long shmem_long_atomic_fetch_add(long *target, long value, int pe);
void shmem_long_atomic_inc(long *target, int pe);
long shmem_long_atomic_fetch_inc(long *target, int pe);
long shmem_long_atomic_swap(long *target, long value, int pe);
long shmem_long_atomic_compare_swap(long *target, long cond, long value,
                                    int pe);
long shmem_long_atomic_fetch(const long *target, int pe);
void shmem_long_atomic_set(long *target, long value, int pe);

/* point synchronization (shmem_wait.c) */
#define SHMEM_CMP_EQ 0
#define SHMEM_CMP_NE 1
#define SHMEM_CMP_GT 2
#define SHMEM_CMP_GE 3
#define SHMEM_CMP_LT 4
#define SHMEM_CMP_LE 5
void shmem_long_wait_until(long *ivar, int cmp, long value);

/* collectives (shmem_broadcast.c / shmem_reduce.c, 1.4 signatures) */
void shmem_broadcastmem(void *dest, const void *source, size_t nbytes,
                        int pe_root);
void shmem_long_sum_reduce(long *dest, const long *source, size_t nreduce);
void shmem_long_max_reduce(long *dest, const long *source, size_t nreduce);
void shmem_double_sum_reduce(double *dest, const double *source,
                             size_t nreduce);
void shmem_double_max_reduce(double *dest, const double *source,
                             size_t nreduce);
void shmem_fcollectmem(void *dest, const void *source, size_t nbytes);

/* distributed locks (shmem_lock.c) */
void shmem_set_lock(long *lock);
void shmem_clear_lock(long *lock);
int shmem_test_lock(long *lock);

/* round-5 completion tier: the rest of the reference's binding
 * families (shmem_align.c, shmem_realloc.c, shmem_ptr.c,
 * shmem_pe_accessible.c, shmem_iput.c/iget.c, shmem_alltoall.c,
 * shmem_collect.c, shmem_sync.c, shmem_global_exit.c, shmem_info.c,
 * the deprecated cache ops, and the legacy start_pes-era names). */
void *shmem_align(size_t alignment, size_t size);
void *shmem_realloc(void *ptr, size_t size);
/* load/store access: only the local PE's heap is addressable here */
void *shmem_ptr(const void *dest, int pe);
int shmem_pe_accessible(int pe);
int shmem_addr_accessible(const void *addr, int pe);
/* strided RMA (element strides, shmem_iput.c semantics) */
void shmem_long_iput(long *dest, const long *source, ptrdiff_t dst,
                     ptrdiff_t sst, size_t nelems, int pe);
void shmem_long_iget(long *dest, const long *source, ptrdiff_t dst,
                     ptrdiff_t sst, size_t nelems, int pe);
void shmem_double_iput(double *dest, const double *source, ptrdiff_t dst,
                       ptrdiff_t sst, size_t nelems, int pe);
void shmem_double_iget(double *dest, const double *source, ptrdiff_t dst,
                       ptrdiff_t sst, size_t nelems, int pe);
/* collectives over all PEs (house 1.4 style: no pSync/pWrk) */
void shmem_alltoallmem(void *dest, const void *source, size_t nbytes);
void shmem_collectmem(void *dest, const void *source, size_t nbytes);
void shmem_sync_all(void);
void shmem_global_exit(int status);
#define SHMEM_MAX_NAME_LEN 64
#define SHMEM_MAJOR_VERSION 1
#define SHMEM_MINOR_VERSION 4
void shmem_info_get_version(int *major, int *minor);
void shmem_info_get_name(char *name);
/* deprecated cache ops (shmem_set_cache_inv.c family): no-ops on a
 * coherent host, kept so legacy codes link */
void shmem_set_cache_inv(void);
void shmem_clear_cache_inv(void);
void shmem_set_cache_line_inv(void *dest);
void shmem_clear_cache_line_inv(void *dest);
void shmem_udcflush(void);
void shmem_udcflush_line(void *dest);
/* legacy start_pes-era names */
void start_pes(int npes);
int _my_pe(void);
int _num_pes(void);
void shmem_long_wait(long *ivar, long value);
long shmem_swap(long *target, long value, int pe);

#ifdef __cplusplus
}
#endif

#endif /* ZOMPI_SHMEM_H */
