"""MCA-equivalent substrate: variables, output streams, components."""

from . import component, output, var

__all__ = ["var", "output", "component"]
