"""TCP transport tests (btl/tcp analog) — N procs over localhost sockets,
the wire-level counterpart of the thread-rank loopback tests."""

import threading

import numpy as np
import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.pt2pt.matching import ANY_SOURCE
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc

N = 4


def run_tcp(n, fn, timeout=60.0):
    """Launch n TcpProcs in threads sharing a localhost coordinator."""
    coord_ready = threading.Event()
    coord_addr = [None]
    results = [None] * n
    excs = [None] * n

    def publish(addr):
        # ephemeral coordinator port -> other threads (on real deployments
        # this is the launcher's job, like prte forwarding the PMIx URI)
        coord_addr[0] = addr
        coord_ready.set()

    def main(rank):
        try:
            if rank == 0:
                proc = TcpProc(0, n, coordinator=("127.0.0.1", 0),
                               on_coordinator_bound=publish)
            else:
                coord_ready.wait(10)
                proc = TcpProc(rank, n, coordinator=coord_addr[0])
            try:
                results[rank] = fn(proc)
            finally:
                proc.close()
        except BaseException as e:  # noqa: BLE001
            excs[rank] = e
            coord_ready.set()

    threads = [threading.Thread(target=main, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "tcp rank hung"
    for e in excs:
        if e is not None:
            raise e
    return results


class TestWire:
    def test_ring_token(self):
        def prog(p):
            token = p.rank
            p.send(token, dest=(p.rank + 1) % N, tag=1)
            return p.recv(source=(p.rank - 1) % N, tag=1)

        assert run_tcp(N, prog) == [(r - 1) % N for r in range(N)]

    def test_ndarray_payload(self):
        def prog(p):
            arr = np.arange(1000, dtype=np.float64) * p.rank
            p.send(arr, dest=(p.rank + 1) % N, tag=2)
            got = p.recv(source=(p.rank - 1) % N, tag=2)
            return float(got.sum())

        expect = [float(np.arange(1000).sum() * ((r - 1) % N))
                  for r in range(N)]
        assert run_tcp(N, prog) == expect

    def test_any_source_gather(self):
        def prog(p):
            if p.rank == 0:
                vals = sorted(p.recv(source=ANY_SOURCE, tag=3)
                              for _ in range(N - 1))
                return vals
            p.send(p.rank * 10, dest=0, tag=3)
            return None

        assert run_tcp(N, prog)[0] == [10, 20, 30]

    def test_tag_and_cid_isolation(self):
        def prog(p):
            if p.rank == 0:
                p.send("cid7", dest=1, tag=5, cid=7)
                p.send("cid9", dest=1, tag=5, cid=9)
                return True
            if p.rank == 1:
                # receive in the opposite cid order
                later = p.recv(source=0, tag=5, cid=9)
                first = p.recv(source=0, tag=5, cid=7)
                return (first, later)
            return None

        out = run_tcp(N, prog)
        assert out[1] == ("cid7", "cid9")

    def test_barrier_and_sendrecv(self):
        def prog(p):
            p.barrier()
            out = p.sendrecv(
                {"from": p.rank}, dest=(p.rank + 1) % N,
                source=(p.rank - 1) % N, sendtag=6, recvtag=6,
            )
            p.barrier()
            return out["from"]

        assert run_tcp(N, prog) == [(r - 1) % N for r in range(N)]

    def test_self_send_loopback(self):
        def prog(p):
            p.send(b"self", dest=p.rank, tag=8)
            return p.recv(source=p.rank, tag=8)

        assert run_tcp(2, prog) == [b"self", b"self"]

    def test_large_message(self):
        big = np.random.default_rng(0).normal(size=(512, 256))

        def prog(p):
            if p.rank == 0:
                p.send(big, dest=1, tag=9)
                return True
            if p.rank == 1:
                got = p.recv(source=0, tag=9)
                return bool(np.array_equal(got, big))
            return None

        assert run_tcp(2, prog) == [True, True]

    def test_recv_timeout(self):
        def prog(p):
            if p.rank == 0:
                with pytest.raises(errors.InternalError, match="timeout"):
                    p.recv(source=1, tag=99, timeout=0.3)
            p.barrier()
            return True

        assert run_tcp(2, prog) == [True, True]

    def test_message_survives_abandoned_recv(self):
        """A message stolen by a timed-out receive must be re-injected so a
        retry still finds it."""

        def prog(p):
            if p.rank == 0:
                with pytest.raises(errors.InternalError, match="timeout"):
                    p.recv(source=1, tag=42, timeout=0.3)
                p.barrier()  # now rank 1 sends
                return p.recv(source=1, tag=42, timeout=5.0)
            p.barrier()
            p.send("late", dest=0, tag=42)
            return None

        assert run_tcp(2, prog)[0] == "late"

    def test_writable_ndarray_delivery(self):
        """Wire-delivered arrays must be writable, matching the thread
        universe's eager-copy semantics."""

        def prog(p):
            if p.rank == 0:
                p.send(np.arange(4, dtype=np.int64), dest=1, tag=11)
                return True
            got = p.recv(source=0, tag=11)
            got += 1  # raises on a read-only frombuffer view
            return got.tolist()

        assert run_tcp(2, prog)[1] == [1, 2, 3, 4]

    def test_ft_logging_over_sockets(self):
        """LoggedContext/BookmarkedContext-style wrapping works over the
        socket transport (return_status + irecv/isend compatibility)."""
        from zhpe_ompi_tpu.ft.vprotocol import LoggedContext, _RankLog
        import threading as _t

        def prog(p):
            log = _RankLog()
            wrapped = LoggedContext(p, log, _t.Lock())
            if p.rank == 0:
                wrapped.send(7, dest=1, tag=1)
                got = wrapped.recv(source=1, tag=2)
            else:
                got = wrapped.recv(source=0, tag=1)
                wrapped.send(got * 2, dest=0, tag=2)
            return (got, len(log.sends), len(log.recvs))

        out = run_tcp(2, prog)
        assert out[0] == (14, 1, 1) and out[1] == (7, 1, 1)
