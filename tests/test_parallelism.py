"""sp/ep/pp parallelism built on framework primitives: exactness tests.

Each strategy's multi-device output is compared against a single-device
dense reference — the framework's answer to "long-context and distributed
are first-class".
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu.models import moe, pipeline, ring_attention

N = 8


@pytest.fixture(scope="module")
def world():
    return zmpi.init()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, world, causal):
        B, S, H, D = 2, 32, 4, 16  # S sharded into 8 blocks of 4
        r = np.random.default_rng(0)
        q = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        v = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)

        dense = ring_attention._block_attention_single(q, k, v, causal)

        spec = P(None, "world")
        sharding = NamedSharding(world.mesh, spec)
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        out = world.run(
            lambda a, b, c: ring_attention.ring_attention(
                world, a, b, c, causal=causal
            ),
            qs, ks, vs,
            in_specs=(spec, spec, spec), out_specs=spec,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dense), rtol=2e-4, atol=2e-5
        )

    def test_long_sequence_jit(self, world):
        """Longer-than-memory-naive sequence: 8 x 64 = 512 under jit."""
        B, S, H, D = 1, 512, 2, 8
        r = np.random.default_rng(1)
        mk = lambda: jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        q, k, v = mk(), mk(), mk()
        spec = P(None, "world")
        sharding = NamedSharding(world.mesh, spec)
        out = world.run(
            lambda a, b, c: ring_attention.ring_attention(world, a, b, c),
            *(jax.device_put(t, sharding) for t in (q, k, v)),
            in_specs=(spec, spec, spec), out_specs=spec,
        )
        dense = ring_attention._block_attention_single(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dense), rtol=2e-4, atol=2e-5
        )


class TestZigzagRingAttention:
    """Round 4: load-balanced causal ring attention — the zigzag
    chunk-pair layout where every (rank, step) computes exactly the
    live sub-blocks."""

    def _run_zigzag(self, world, q, k, v):
        n = world.size
        qz = ring_attention.zigzag_shard(q, n)
        kz = ring_attention.zigzag_shard(k, n)
        vz = ring_attention.zigzag_shard(v, n)
        # (n, B, Sc*2, H, D) sharded on dim 0 -> each rank's pair block
        spec = P("world")
        out = world.run(
            lambda a, b, c: ring_attention.ring_attention_zigzag(
                world, a[0], b[0], c[0])[None],
            *(world.device_put_sharded(t) for t in (qz, kz, vz)),
            in_specs=(spec, spec, spec), out_specs=spec,
        )
        return ring_attention.zigzag_unshard(out, n)

    def test_matches_dense_causal(self, world):
        B, S, H, D = 2, 64, 4, 16  # 2n = 16 chunks of 4
        r = np.random.default_rng(2)
        q = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        v = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        dense = ring_attention._block_attention_single(q, k, v, True)
        out = self._run_zigzag(world, q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dense), rtol=2e-4, atol=2e-5
        )

    def test_shard_unshard_roundtrip(self, world):
        r = np.random.default_rng(3)
        x = jnp.asarray(r.normal(size=(2, 32, 3)), jnp.float32)
        z = ring_attention.zigzag_shard(x, world.size)
        back = ring_attention.zigzag_unshard(z, world.size)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_grads_flow(self, world):
        """Differentiable through the switch + scan (training path)."""
        B, S, H, D = 1, 32, 2, 8
        r = np.random.default_rng(4)
        q = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        v = jnp.asarray(r.normal(size=(B, S, H, D)), jnp.float32)
        n = world.size
        spec = P("world")

        def loss_zig(q, k, v):
            qz = ring_attention.zigzag_shard(q, n)
            kz = ring_attention.zigzag_shard(k, n)
            vz = ring_attention.zigzag_shard(v, n)
            out = world.run(
                lambda a, b, c: ring_attention.ring_attention_zigzag(
                    world, a[0], b[0], c[0])[None],
                qz, kz, vz,
                in_specs=(spec, spec, spec), out_specs=spec,
            )
            return (ring_attention.zigzag_unshard(out, n) ** 2).sum()

        def loss_ref(q, k, v):
            return (ring_attention._block_attention_single(
                q, k, v, True) ** 2).sum()

        gz = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gz, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)


class TestMoE:
    def test_matches_dense_reference(self, world):
        D, F, T_local = 16, 32, 8
        params = moe.init_moe_params(jax.random.PRNGKey(0), D, F, N)
        r = np.random.default_rng(2)
        x_all = jnp.asarray(r.normal(size=(N * T_local, D)), jnp.float32)

        # big capacity so nothing drops -> exact equivalence
        spec_x = P("world")
        px = jax.device_put(x_all, NamedSharding(world.mesh, spec_x))
        param_specs = {
            "router": P(),
            "w_in": P("world"),
            "w_out": P("world"),
        }
        pp = {
            k: jax.device_put(v, NamedSharding(world.mesh, param_specs[k]))
            for k, v in params.items()
        }

        def body(prm, xs):
            y, keep = moe.moe_ffn(world, prm, xs, capacity_factor=float(N))
            return y

        out = world.run(
            body, pp, px,
            in_specs=(param_specs, spec_x), out_specs=spec_x,
        )
        ref = moe.moe_reference_dense(params, x_all, N, capacity=10**9)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
        )

    def test_capacity_drops_dont_crash(self, world):
        D, F, T_local = 8, 16, 4
        params = moe.init_moe_params(jax.random.PRNGKey(1), D, F, N)
        r = np.random.default_rng(3)
        x_all = jnp.asarray(r.normal(size=(N * T_local, D)), jnp.float32)
        spec_x = P("world")
        param_specs = {"router": P(), "w_in": P("world"), "w_out": P("world")}
        pp = {
            k: jax.device_put(v, NamedSharding(world.mesh, param_specs[k]))
            for k, v in params.items()
        }

        def body(prm, xs):
            y, keep = moe.moe_ffn(world, prm, xs, capacity_factor=0.5)
            return y

        out = world.run(
            body, pp,
            jax.device_put(x_all, NamedSharding(world.mesh, spec_x)),
            in_specs=(param_specs, spec_x), out_specs=spec_x,
        )
        assert np.isfinite(np.asarray(out)).all()
        # exact parity with the dense reference at the same binding capacity
        cap = max(1, int(0.5 * T_local / N))
        ref = moe.moe_reference_dense(
            params, x_all, N, capacity=cap, block_tokens=T_local
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
        )


class TestPipeline:
    def test_matches_sequential(self, world):
        """8-stage pipeline of affine layers == sequential application."""
        M, mb, D = 6, 3, 8
        r = np.random.default_rng(4)
        # stage s applies x -> x @ W_s + 1  (W per stage, sharded over pp)
        Ws = jnp.asarray(r.normal(size=(N, D, D)) * 0.3, jnp.float32)
        xs = jnp.asarray(r.normal(size=(M, mb, D)), jnp.float32)

        def stage_fn(W, x):
            return x @ W[0] + 1.0

        spec_w = P("world")
        out = world.run(
            lambda W, x: pipeline.pipeline_apply(world, stage_fn, W, x),
            jax.device_put(Ws, NamedSharding(world.mesh, spec_w)),
            xs,
            in_specs=(spec_w, P()), out_specs=P("world"),
        )
        # sequential reference
        ref = xs
        for s in range(N):
            ref = ref @ Ws[s] + 1.0
        # per-stage outputs are stacked along dim 0; results live on the
        # LAST stage's block (other stages hold zeros)
        out = np.asarray(out).reshape(N, M, mb, D)[N - 1]
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4, atol=1e-5)


class TestZigzagTransformer:
    def test_sp_train_loss_matches_dense(self, world):
        """cfg.zigzag_sp end to end: the sp train step on zigzag-ordered
        tokens reproduces the dense single-device loss (the model has no
        positional encoding, so the token->rank assignment must not
        change the math — only the causal structure, which the zigzag
        ring preserves by global position)."""
        import zhpe_ompi_tpu as zmpi
        from jax.sharding import Mesh, NamedSharding
        from zhpe_ompi_tpu.models import transformer as tfm

        n = 8
        devs = np.asarray(jax.devices()[:n]).reshape(1, 1, n)
        mesh = Mesh(devs, ("dp", "tp", "sp"))
        dp_comm = zmpi.Communicator(mesh, "dp", name="zz_dp")
        sp_comm = zmpi.Communicator(mesh, "sp", name="zz_sp")
        cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, seq=64, dtype=jnp.float32,
                         zigzag_sp=True)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        r = np.random.default_rng(7)
        tok = r.integers(0, cfg.vocab, (2, cfg.seq))
        tgt = r.integers(0, cfg.vocab, (2, cfg.seq))

        # dense reference on the ORIGINAL ordering (no sp)
        dense_cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                               n_layers=2, seq=64, dtype=jnp.float32)
        ref = float(tfm.loss_fn(params, jnp.asarray(tok),
                                jnp.asarray(tgt), dense_cfg))

        # zigzag column permutation: rank i's contiguous sp slice holds
        # global chunks (i, 2n-1-i)
        tz = np.concatenate(
            [np.asarray(ring_attention.zigzag_shard(
                jnp.asarray(tok)[..., None], n))[i, :, :, 0]
             for i in range(n)], axis=1)
        gz = np.concatenate(
            [np.asarray(ring_attention.zigzag_shard(
                jnp.asarray(tgt)[..., None], n))[i, :, :, 0]
             for i in range(n)], axis=1)

        step, specs = tfm.make_train_step(cfg, mesh, dp_comm, None,
                                          sp_comm)
        sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                   for k, v in params.items()}
        dspec = NamedSharding(mesh, P("dp", "sp"))
        _, loss = step(sharded, jax.device_put(jnp.asarray(tz), dspec),
                       jax.device_put(jnp.asarray(gz), dspec))
        assert abs(float(loss) - ref) < 5e-4, (float(loss), ref)
