"""MPI attribute caching — keyvals with copy/delete callbacks.

The reference's ``ompi/attribute/attribute.c`` implements one keyval
system shared by communicators, windows, and datatypes: a keyval is
created with a copy callback (invoked at MPI_Comm_dup to decide whether
and what to propagate) and a delete callback (invoked at attribute
deletion/object free).  This is that system, re-derived:

- :func:`create_keyval` → integer keyval + callbacks.  The MPI
  predefined policies are module constants: :data:`NULL_COPY_FN`
  (never propagate on dup) and :data:`DUP_FN` (propagate by reference).
- :class:`AttrHost` — mixin for attribute-bearing objects (communicator
  / window / file here).  ``set_attr/get_attr/delete_attr`` plus the
  dup-time (:meth:`_copy_attrs_to`) and free-time
  (:meth:`_delete_all_attrs`) hooks.

Copy callbacks return ``(flag, value)``: flag False drops the attribute
on the new object (MPI's copy_fn contract).  Delete callbacks may raise;
the error propagates to the caller of delete/free exactly as
MPI_ERR_OTHER would.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable

from . import errors

# copy_fn(oldobj, keyval, extra_state, value) -> (keep: bool, newvalue)
CopyFn = Callable[[Any, int, Any, Any], tuple[bool, Any]]
# delete_fn(obj, keyval, value, extra_state) -> None
DeleteFn = Callable[[Any, int, Any, Any], None]


def NULL_COPY_FN(oldobj, keyval, extra, value):
    """MPI_NULL_COPY_FN: attribute does not propagate on dup."""
    return False, None


def DUP_FN(oldobj, keyval, extra, value):
    """MPI_DUP_FN: attribute propagates by reference on dup."""
    return True, value


def NULL_DELETE_FN(obj, keyval, value, extra):
    """MPI_NULL_DELETE_FN."""


class _Keyval:
    __slots__ = ("id", "copy_fn", "delete_fn", "extra_state", "freed")

    def __init__(self, kid: int, copy_fn: CopyFn, delete_fn: DeleteFn,
                 extra_state: Any):
        self.id = kid
        self.copy_fn = copy_fn
        self.delete_fn = delete_fn
        self.extra_state = extra_state
        self.freed = False


_keyvals: dict[int, _Keyval] = {}
_next_id = itertools.count(1000)  # distinct from any predefined space
_lock = threading.Lock()

KEYVAL_INVALID = -1


def create_keyval(copy_fn: CopyFn = NULL_COPY_FN,
                  delete_fn: DeleteFn = NULL_DELETE_FN,
                  extra_state: Any = None) -> int:
    """MPI_Comm_create_keyval (also serves win/type keyvals, as the
    reference's unified attribute machinery does)."""
    with _lock:
        kid = next(_next_id)
        _keyvals[kid] = _Keyval(kid, copy_fn or NULL_COPY_FN,
                                delete_fn or NULL_DELETE_FN, extra_state)
        return kid


def free_keyval(keyval: int) -> int:
    """MPI_Comm_free_keyval: marks the keyval dead; objects still
    holding attributes under it keep their values (MPI semantics — the
    keyval is reference-counted in the reference; here deletion
    callbacks still run at object free).  Returns KEYVAL_INVALID."""
    with _lock:
        kv = _keyvals.get(keyval)
        if kv is None:
            raise errors.ArgError(f"unknown keyval {keyval}")
        kv.freed = True
        return KEYVAL_INVALID


def _get_keyval(keyval: int) -> _Keyval:
    with _lock:
        kv = _keyvals.get(keyval)
    if kv is None:
        raise errors.ArgError(f"unknown keyval {keyval}")
    return kv


class AttrHost:
    """Mixin for attribute-bearing objects.  Storage lives in
    ``self.attributes`` (keyval -> value)."""

    attributes: dict[int, Any]

    def set_attr(self, keyval: int, value: Any) -> None:
        """MPI_Comm_set_attr: replacing an existing value runs the old
        value's delete callback first (MPI semantics)."""
        kv = _get_keyval(keyval)
        if keyval in self.attributes:
            kv.delete_fn(self, keyval, self.attributes[keyval],
                         kv.extra_state)
        self.attributes[keyval] = value

    def get_attr(self, keyval: int) -> tuple[bool, Any]:
        """MPI_Comm_get_attr: (found, value)."""
        _get_keyval(keyval)
        if keyval in self.attributes:
            return True, self.attributes[keyval]
        return False, None

    def delete_attr(self, keyval: int) -> None:
        """MPI_Comm_delete_attr: runs the delete callback."""
        kv = _get_keyval(keyval)
        if keyval not in self.attributes:
            raise errors.ArgError(f"no attribute under keyval {keyval}")
        value = self.attributes.pop(keyval)
        kv.delete_fn(self, keyval, value, kv.extra_state)

    # -- object lifecycle hooks ------------------------------------------

    def _copy_attrs_to(self, newobj: "AttrHost") -> None:
        """Dup-time propagation: run each attribute's copy callback
        against the OLD object (MPI_Comm_dup's attribute pass)."""
        for keyval, value in list(self.attributes.items()):
            kv = _get_keyval(keyval)
            keep, newval = kv.copy_fn(self, keyval, kv.extra_state, value)
            if keep:
                newobj.attributes[keyval] = newval

    def _delete_all_attrs(self) -> None:
        """Free-time pass: delete callbacks for every cached attribute
        (ompi_attr_delete_all)."""
        first_err = None
        for keyval in list(self.attributes):
            kv = _get_keyval(keyval)
            value = self.attributes.pop(keyval)
            try:
                kv.delete_fn(self, keyval, value, kv.extra_state)
            except Exception as e:  # noqa: BLE001 - collect, finish pass
                first_err = first_err or e
        if first_err is not None:
            raise first_err
