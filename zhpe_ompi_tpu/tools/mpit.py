"""MPI_T tool interface analog — cvar/pvar/category introspection.

Re-design of ``ompi/mpi/tool`` (SURVEY.md §5): the MPI_T surface is a typed
window onto (a) the MCA var system (control variables) and (b) the runtime
counter plane (performance variables).  The reference's handle/session
machinery is kept because it carries real semantics:

- **cvar handles** read and (scope permitting) write an MCA var through the
  same precedence machinery as env/file/CLI — a write is an API-source set.
- **pvar sessions** isolate measurement intervals: a counter handle records
  its baseline at ``start`` and reads deltas, so two tools can watch the
  same global counter without trampling each other (the reason MPI_T has
  sessions at all).
- **categories** group variables for tool discovery, derived from the var
  registry's framework prefixes rather than a hand-maintained tree.

Counter pvars come from SPC (``runtime/spc.py``); state pvars are provided
by live subsystems via :func:`register_pvar` (e.g. matching-queue depths,
the PERUSE-adjacent surface of ``test/monitoring/test_pvar_access.c``).

Two properties the reference's tool plane has that this surface keeps:

- **deterministic discovery**: counter pvars enumerate the DOCUMENTED
  counter table of ``runtime/spc.py`` (parsed with zlint's ZL006
  parser), not merely counters that happen to have fired — so
  ``pvar_get_num`` is stable from init and a tool that allocated
  handle indices at startup never watches them shift under traffic.
- **remote sessions**: ``PvarSession(remote=(dvm_addr, job, rank))``
  reads a LIVE job's published store snapshots through the zprted
  ``metrics`` RPC — the MPI_T-reads-SPCs-from-running-jobs surface of
  the reference (PAPER.md §5), against the fleet instead of the local
  process.  Remote counter handles keep the same baseline-isolated
  delta semantics.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable

from ..core import errors
from ..mca import var as mca_var
from ..runtime import spc

# -- scopes (MPI_T_SCOPE_*) -------------------------------------------------

SCOPE_CONSTANT = "constant"  # read-only forever
SCOPE_READONLY = "readonly"  # read-only in this build
SCOPE_LOCAL = "local"        # writable, affects this controller only
SCOPE_ALL = "all"            # writable, affects every device (SPMD: same)

# -- pvar classes (MPI_T_PVAR_CLASS_*) --------------------------------------

PVAR_COUNTER = "counter"
PVAR_STATE = "state"
PVAR_WATERMARK = "highwatermark"


# =========================== control variables =============================


def cvar_get_num() -> int:
    return len(mca_var.registry.all_vars())


def cvar_names() -> list[str]:
    return [v.name for v in mca_var.registry.all_vars()]


def cvar_get_info(name: str) -> dict[str, Any]:
    """MPI_T_cvar_get_info: metadata without allocating a handle."""
    v = mca_var.registry.lookup(name)
    if v is None:
        raise errors.ArgError(f"no such cvar {name!r}")
    return {
        "name": v.name,
        "description": v.description,
        "type": v.type.__name__,
        "scope": SCOPE_ALL if v.settable else SCOPE_READONLY,
        "value": v.value,
        "source": v.source.name,
    }


class CvarHandle:
    """MPI_T_cvar_handle_alloc product: read/write one control variable."""

    def __init__(self, name: str) -> None:
        self._var = mca_var.registry.lookup(name)
        if self._var is None:
            raise errors.ArgError(f"no such cvar {name!r}")
        self.name = name

    def read(self) -> Any:
        return self._var.value

    def write(self, value: Any) -> None:
        if not self._var.settable:
            raise errors.ArgError(f"cvar {self.name} is read-only")
        mca_var.registry.set(self.name, value)


# ========================= performance variables ===========================


@dataclass
class _PvarDef:
    name: str
    klass: str
    description: str
    reader: Callable[[], int | float]
    writable_reset: bool = False
    resetter: Callable[[], None] | None = None
    #: counter-class defs may carry the backing store's reset-epoch
    #: reader: an open handle whose baseline predates a reset observes
    #: the epoch change and rebases instead of reading a negative delta
    epoch: Callable[[], int] | None = None


_pvars: dict[str, _PvarDef] = {}
_pvar_lock = threading.Lock()


def register_pvar(name: str, reader: Callable[[], int | float],
                  klass: str = PVAR_STATE, description: str = "",
                  resetter: Callable[[], None] | None = None) -> None:
    """Publish a performance variable backed by a live reader callable.
    Idempotent by name (last registration wins — subsystems re-register on
    re-init)."""
    with _pvar_lock:
        _pvars[name] = _PvarDef(
            name, klass, description, reader,
            resetter is not None, resetter,
        )


def _spc_defs() -> dict[str, _PvarDef]:
    """Every SPC counter is a counter-class pvar named spc_<counter>
    (the reference surfaces SPCs as MPI_T pvars, ompi_spc.c).

    The universe is the DOCUMENTED counter table — deterministic from
    init, zero-valued until a counter first fires — plus any dynamic
    names (templated families) that actually recorded: discovery never
    shrinks and never depends on which code paths traffic happened to
    warm."""
    out = {}
    names = set(spc.documented_counters())
    names.update(spc.snapshot())
    for cname in names:
        klass = PVAR_WATERMARK if cname in spc.WATERMARK else PVAR_COUNTER
        out[f"spc_{cname}"] = _PvarDef(
            f"spc_{cname}", klass, f"SPC counter {cname}",
            (lambda c=cname: spc.read(c)),
            epoch=spc.reset_epoch,
        )
    return out


def registered_pvars() -> dict[str, _PvarDef]:
    """Live-subsystem pvars only (the :func:`register_pvar` products,
    state/watermark readers) — the metrics publisher sweeps THESE per
    tick without rebuilding the whole counter universe."""
    with _pvar_lock:
        return dict(_pvars)


def pvar_defs() -> dict[str, _PvarDef]:
    defs = registered_pvars()
    defs.update(_spc_defs())
    return defs


def pvar_get_num() -> int:
    return len(pvar_defs())


def pvar_names() -> list[str]:
    return sorted(pvar_defs())


class _RemoteMetrics:
    """Reader plane of a remote pvar session: one rank's published
    store snapshots, fetched through the zprted ``metrics`` RPC.  Each
    handle read fetches the LATEST snapshot — staleness is bounded by
    the publisher interval, which is exactly the remote contract
    ("within one publish interval of the rank's own counters")."""

    def __init__(self, dvm_addr, job: str, rank: int):
        from ..runtime.dvm import DvmClient

        self.job = str(job)
        self.rank = int(rank)
        self._client = DvmClient(dvm_addr, timeout=10.0)

    def fetch(self) -> dict:
        """The rank's latest snapshot — {} while nothing is published
        yet (a session bound before the first publish reads the same
        zero-filled universe the publisher will ship; a DEAD daemon
        still raises — absence of data and absence of the daemon are
        different failures)."""
        try:
            return self._client.metrics(self.job, self.rank)
        except errors.MpiError as e:
            if "published" in str(e):
                return {}
            raise

    def counter(self, cname: str) -> int:
        return int((self.fetch().get("counters") or {}).get(cname, 0))

    def state(self, pname: str):
        return (self.fetch().get("pvars") or {}).get(pname, 0)

    def defs(self) -> dict[str, _PvarDef]:
        """The remote rank's pvar universe: the documented counter
        table (deterministic, exactly like local discovery) plus
        whatever the latest snapshot carries — extra fired counters
        and the publisher's state-pvar sweep."""
        names = set(spc.documented_counters())
        watermarks = set(spc.WATERMARK)
        states: dict[str, object] = {}
        try:
            snap = self.fetch()
            names.update(snap.get("counters") or {})
            watermarks.update(snap.get("watermark") or ())
            states = dict(snap.get("pvars") or {})
        except errors.MpiError:
            pass  # nothing published yet: the documented table stands
        out: dict[str, _PvarDef] = {}
        for cname in names:
            klass = PVAR_WATERMARK if cname in watermarks \
                else PVAR_COUNTER
            out[f"spc_{cname}"] = _PvarDef(
                f"spc_{cname}", klass,
                f"SPC counter {cname} of {self.job}:{self.rank}",
                (lambda c=cname: self.counter(c)),
            )
        for pname in states:
            out[pname] = _PvarDef(
                pname, PVAR_STATE,
                f"state pvar {pname} of {self.job}:{self.rank}",
                (lambda n=pname: self.state(n)),
            )
        return out

    def close(self) -> None:
        self._client.close()


class PvarSession:
    """MPI_T_pvar_session_create: an isolation scope for handles.

    ``remote=(dvm_addr, job, rank)`` binds the session to a LIVE job's
    published metrics instead of the local process: handles read
    baseline-isolated deltas from the rank's store snapshots via the
    daemon's ``metrics`` RPC.  ``free()`` releases the RPC socket —
    the session owns it."""

    def __init__(self, remote: tuple | None = None) -> None:
        self._handles: list[PvarHandle] = []
        self._remote: _RemoteMetrics | None = None
        if remote is not None:
            dvm_addr, job, rank = remote
            self._remote = _RemoteMetrics(dvm_addr, job, rank)

    def handle_alloc(self, name: str) -> "PvarHandle":
        defs = self._remote.defs() if self._remote is not None \
            else pvar_defs()
        if name not in defs:
            raise errors.ArgError(f"no such pvar {name!r}")
        h = PvarHandle(defs[name])
        self._handles.append(h)
        return h

    def free(self) -> None:
        self._handles.clear()
        if self._remote is not None:
            self._remote.close()
            self._remote = None


class PvarHandle:
    """Counter handles measure deltas from their ``start`` baseline so
    concurrent sessions don't interfere; state/watermark handles read the
    live value.

    A handle's baseline can outlive a store reset (``spc.reset()``
    between ``start`` and ``read``): the handle tracks the store's
    reset epoch and rebases to zero when it advances — a read after a
    reset reports the counts since the reset, never a negative delta.
    Remote handles (and any def without an epoch reader) keep the same
    contract through the monotonicity guard: a value below the
    baseline proves an upstream reset, so the baseline rebases."""

    def __init__(self, d: _PvarDef) -> None:
        self._def = d
        self._running = False
        self._baseline: int | float = 0
        self._epoch: int | None = None

    @property
    def name(self) -> str:
        return self._def.name

    @property
    def klass(self) -> str:
        return self._def.klass

    def start(self) -> None:
        if self._def.klass == PVAR_COUNTER:
            self._baseline = self._def.reader()
            if self._def.epoch is not None:
                self._epoch = self._def.epoch()
        self._running = True

    def stop(self) -> None:
        self._running = False

    def read(self) -> int | float:
        v = self._def.reader()
        if self._def.klass != PVAR_COUNTER:
            return v
        if self._def.epoch is not None:
            epoch = self._def.epoch()
            if self._epoch is not None and epoch != self._epoch:
                # the store was reset under the open handle: the old
                # baseline measures a dead incarnation
                self._baseline = 0
                self._epoch = epoch
        if v < self._baseline:
            # counters are monotonic: going backwards proves a reset
            # this handle could not observe (no epoch reader — e.g. a
            # remote rank restarted)
            self._baseline = 0
        return v - self._baseline

    def reset(self) -> None:
        """Counter handles rebase; others delegate to their resetter."""
        if self._def.klass == PVAR_COUNTER:
            self._baseline = self._def.reader()
            if self._def.epoch is not None:
                self._epoch = self._def.epoch()
        elif self._def.resetter is not None:
            self._def.resetter()
        else:
            raise errors.UnsupportedError(
                f"pvar {self._def.name} is not resettable"
            )


# =============================== categories ================================


def _pvar_category(pname: str) -> str:
    """Category of one pvar: ``spc_<counter>`` pvars land in the
    per-family ``spc.<family>`` bucket (``spc.tcp``, ``spc.han``, ...);
    other pvars bucket by their own name's family."""
    if pname.startswith("spc_"):
        return f"spc.{mca_var.family_of(pname[len('spc_'):])}"
    return mca_var.family_of(pname)


def category_names() -> list[str]:
    """Categories derived from the REGISTERED framework prefix table
    (``mca_var.register_family`` — the <framework>_<component> naming
    contract), not a bare first-``_``-segment split: ``coll_han_*``
    vars sit together in ``han`` instead of scattering across
    ``coll``/``han``/``sm`` buckets, and counter pvars land in
    per-family ``spc.<family>`` subcategories under the ``spc``
    umbrella (MPI_T_category_get_num analog)."""
    cats = {mca_var.family_of(v.name)
            for v in mca_var.registry.all_vars()}
    for pname in pvar_names():
        cats.add(_pvar_category(pname))
    cats.add("spc")  # the umbrella over every counter subcategory
    return sorted(cats)


def category_info(cat: str) -> dict[str, list[str]]:
    cvars = [
        v.name for v in mca_var.registry.all_vars()
        if mca_var.family_of(v.name) == cat
    ]
    if cat == "spc":
        pvars = [n for n in pvar_names() if n.startswith("spc_")]
    else:
        pvars = [n for n in pvar_names() if _pvar_category(n) == cat]
    if not cvars and not pvars:
        raise errors.ArgError(f"no such category {cat!r}")
    return {"cvars": cvars, "pvars": pvars}
