"""Mixture-of-Experts layer — expert parallelism over framework alltoall.

Expert parallelism (ep) is the MPI_Alltoall workload par excellence: tokens
are routed to experts living on other devices, processed, and routed back.
Both transposes go through the framework's ``comm.alltoall`` (XLA
``all_to_all`` on ICI via the coll table, so `--mca coll` selection and
monitoring interposition apply to the model's hot path).

Design: top-1 switch routing with static capacity (compiler-friendly: no
dynamic shapes).  Each device hosts one expert; tokens overflowing a
device's capacity are dropped (standard switch-transformer semantics) and
their outputs fall back to zero (residual carries them).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts), jnp.float32)
        * d_model**-0.5,
        # per-device expert slice (shard over 'ep' axis at dim 0)
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_ff), jnp.float32)
        * d_model**-0.5,
        "w_out": jax.random.normal(k3, (n_experts, d_ff, d_model), jnp.float32)
        * d_ff**-0.5,
    }


def moe_ffn(comm, params, x, capacity_factor: float = 1.25):
    """Expert-parallel FFN: x is (T_local, D) tokens on this device; the
    device holds expert weights w_in/w_out of shape (1, D, F)/(1, F, D)
    (its shard of the expert dim).  Returns (T_local, D).
    """
    n = comm.size  # == number of experts
    T, D = x.shape
    cap = max(1, int(capacity_factor * T / n))

    logits = x.astype(jnp.float32) @ params["router"]  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, n, dtype=jnp.int32)  # (T, E)
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot  # (T, E)
    pos = jnp.sum(pos_in_expert, axis=-1)  # (T,)
    keep = pos < cap

    # scatter tokens into (E, cap, D) dispatch buffer
    buf = jnp.zeros((n, cap, D), x.dtype)
    tok_idx = jnp.where(keep, expert * cap + pos, n * cap)  # overflow -> oob
    buf = buf.reshape(n * cap, D).at[tok_idx].set(
        jnp.where(keep[:, None], x, 0), mode="drop"
    ).reshape(n, cap, D)

    # ep transpose #1: every device sends expert-e's buffer to device e
    dispatched = comm.alltoall(buf.reshape(n * cap, D))  # (n*cap, D)
    dispatched = dispatched.reshape(n, cap, D)  # n source-device blocks

    # local expert applies to all received tokens
    w_in = params["w_in"][0]
    w_out = params["w_out"][0]
    h = jax.nn.gelu(dispatched.astype(jnp.float32) @ w_in)
    out = (h @ w_out).astype(x.dtype)  # (n, cap, D)

    # ep transpose #2: route results back to their source devices
    returned = comm.alltoall(out.reshape(n * cap, D)).reshape(n, cap, D)

    # gather back into token order; dropped tokens get zeros
    flat = returned.reshape(n * cap, D)
    y = jnp.where(
        keep[:, None],
        jnp.take(flat, jnp.clip(tok_idx, 0, n * cap - 1), axis=0),
        0.0,
    )
    return (y * gate[:, None].astype(y.dtype)), keep


def moe_host_ffn(ep, params, x, capacity_factor: float = 1.25):
    """:func:`moe_ffn` on the HOST plane: the same top-1 routing and
    static-capacity math, but both ep transposes ride the host
    endpoint's ``alltoall`` — which the coll layer routes through the
    hierarchical han schedule when the topology qualifies (intra
    gather → one aggregated wire message per host pair → intra
    scatter), the serving plane's expert-dispatch path.  ``ep`` is any
    host endpoint carrying ``HostCollectives`` (a RankContext, a
    TcpProc, a shrunken live window); one expert per rank.  Returns
    ``(y, keep)`` exactly like :func:`moe_ffn`."""
    import numpy as np

    n = ep.size
    T, D = x.shape
    cap = max(1, int(capacity_factor * T / n))

    logits = x.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert, n, dtype=jnp.int32)
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot
    pos = jnp.sum(pos_in_expert, axis=-1)
    keep = pos < cap

    buf = jnp.zeros((n, cap, D), x.dtype)
    tok_idx = jnp.where(keep, expert * cap + pos, n * cap)
    buf = buf.reshape(n * cap, D).at[tok_idx].set(
        jnp.where(keep[:, None], x, 0), mode="drop"
    ).reshape(n, cap, D)

    # ep transpose #1 on the host plane: one rank-indexed block per
    # destination expert (np blocks — host collectives move host
    # payloads; the han path aggregates them per host on the wire)
    dispatched = ep.alltoall([np.asarray(buf[e]) for e in range(n)])

    w_in = params["w_in"][0]
    w_out = params["w_out"][0]
    stacked = jnp.stack([jnp.asarray(b) for b in dispatched])  # (n,cap,D)
    h = jax.nn.gelu(stacked.astype(jnp.float32) @ w_in)
    out = (h @ w_out).astype(x.dtype)

    # ep transpose #2: results ride back to their source ranks
    returned = ep.alltoall([np.asarray(out[s]) for s in range(n)])
    flat = jnp.stack([jnp.asarray(b) for b in returned]).reshape(n * cap, D)

    y = jnp.where(
        keep[:, None],
        jnp.take(flat, jnp.clip(tok_idx, 0, n * cap - 1), axis=0),
        0.0,
    )
    return (y * gate[:, None].astype(y.dtype)), keep


def moe_reference_dense(
    params, x_all, n_experts: int, capacity: int, block_tokens: int | None = None
):
    """Single-device reference for tests: same routing/capacity semantics as
    :func:`moe_ffn`, no communication.

    `capacity` is per (source block, expert), matching moe_ffn where each
    device owns `cap` dispatch slots per expert; `block_tokens` is the
    per-device token count T_local (default: all of x_all is one block).
    Dropped tokens produce zero output, as in moe_ffn.
    """
    T, D = x_all.shape
    bt = block_tokens or T
    logits = x_all.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    # first-come-first-served capacity per (block, expert), as in moe_ffn
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    blocks = onehot.reshape(T // bt, bt, n_experts)
    pos = jnp.sum((jnp.cumsum(blocks, axis=1) - 1) * blocks, axis=-1)
    keep = (pos < capacity).reshape(T)
    out = jnp.zeros((T, D), jnp.float32)
    for e in range(n_experts):
        w_in = params["w_in"][e]
        w_out = params["w_out"][e]
        h = jax.nn.gelu(x_all.astype(jnp.float32) @ w_in)
        y = h @ w_out
        out = jnp.where((expert == e)[:, None], y, out)
    return out * gate[:, None] * keep[:, None]
