"""The fleet-visible metrics plane: flight recorder, rank-side SPC
publisher, zprted metrics RPC + Prometheus scrape endpoint, and the
real-process end-to-end acceptance (reference surface: MPI_T reading
SPCs from live jobs, ompi/mpi/tool + ompi_spc.c — PAPER.md §5)."""

import re
import socket
import threading
import time

import numpy as np
import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.ft import ulfm
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc
from zhpe_ompi_tpu.runtime import dvm as dvm_mod
from zhpe_ompi_tpu.runtime import flightrec, peruse, spc
from zhpe_ompi_tpu.runtime import pmix as pmix_mod


# ============================ flight recorder ==============================


class TestFlightRecorder:
    def test_ring_window_order_and_overflow_accounting(self):
        rec = flightrec.FlightRecorder(capacity=8)
        d0 = spc.read("flightrec_events_dropped")
        for i in range(11):
            rec.record(flightrec.SEND, i=i)
        win = rec.window()
        # last 8 in record order, seq-stamped
        assert [e["i"] for e in win] == list(range(3, 11))
        assert [e["seq"] for e in win] == list(range(3, 11))
        assert all(e["type"] == flightrec.SEND for e in win)
        # 3 displaced events were lost to the postmortem window — loudly
        assert spc.read("flightrec_events_dropped") - d0 == 3
        assert rec.total() == 11
        assert len(rec.window(2)) == 2
        rec.clear()
        assert rec.window() == [] and rec.total() == 0

    def test_unarmed_recorder_costs_nothing(self):
        """No publisher ⇒ the module gate is False and the seams skip
        the record call entirely (the peruse cost discipline applied
        to the whole recorder)."""
        assert not flightrec.active
        flightrec.clear()
        flightrec.record(flightrec.SEND, dest=1)  # gated: no-op
        assert flightrec.window() == []

    def test_ft_classification_is_tail_entry(self):
        flightrec.arm()
        try:
            flightrec.clear()
            state = ulfm.FailureState(4)
            seen = []
            state.add_failure_listener(
                lambda r, c: seen.append(flightrec.window()))
            state.mark_failed(2, cause="daemon")
            # the listener (the publisher's hook in production) observed
            # the window WITH the classification event already at its tail
            assert seen and seen[0][-1]["type"] == flightrec.FT_CLASS
            assert seen[0][-1]["rank"] == 2
            assert seen[0][-1]["cause"] == "daemon"
        finally:
            flightrec.disarm()

    def test_revoke_event_recorded(self):
        flightrec.arm()
        try:
            flightrec.clear()
            state = ulfm.FailureState(2)
            state.revoke(0x77)
            events = [e for e in flightrec.window()
                      if e["type"] == flightrec.REVOKE]
            assert events and events[-1]["cid"] == 0x77
        finally:
            flightrec.disarm()

    def test_match_events_ride_peruse_refcounted(self):
        from zhpe_ompi_tpu.pt2pt import matching

        assert not peruse.active and not flightrec.active
        flightrec.arm()
        flightrec.arm()  # second publisher
        try:
            flightrec.clear()
            eng = matching.MatchingEngine()
            eng.incoming(matching.Envelope(0, 5, 0, 0), "payload")
            eng.post_recv(0, 5, 0, lambda e, p: None)
            matches = [e for e in flightrec.window()
                       if e["type"] == flightrec.MATCH]
            assert matches and matches[-1]["src"] == 0
            assert matches[-1]["tag"] == 5
            assert matches[-1]["unexpected"] is True
        finally:
            flightrec.disarm()
            assert peruse.active  # one publisher still holds the hook
            assert flightrec.active
            flightrec.disarm()
        # the last disarm restores the inactive-costs-nothing contract
        assert not peruse.active and not flightrec.active

    def test_wire_send_recv_events(self):
        from tests.test_tcp import run_tcp

        flightrec.arm()
        try:
            flightrec.clear()

            def prog(p):
                p.send(np.arange(4.0), dest=1 - p.rank, tag=9)
                return p.recv(source=1 - p.rank, tag=9).sum()

            assert run_tcp(2, prog, sm=False) == [6.0, 6.0]
            kinds = {e["type"] for e in flightrec.window()}
            assert flightrec.SEND in kinds and flightrec.RECV in kinds
        finally:
            flightrec.disarm()


# ====================== publisher + store + daemon =========================


def _run_metrics_job(dvm, n=2, ns="jobmet", traffic=True, rank_fn=None):
    """n thread-plane TcpProcs modexed through the daemon's store with
    the publisher armed; returns after every rank closed (final flush
    published)."""
    pmix_addr = ("127.0.0.1", dvm.pmix.address[1])
    excs = [None] * n

    def main(rank):
        try:
            proc = TcpProc(rank, n, pmix=pmix_addr, namespace=ns,
                           metrics=True, sm=False)
            try:
                if traffic:
                    proc.send(np.arange(64.0), dest=(rank + 1) % n, tag=3)
                    proc.recv(source=(rank - 1) % n, tag=3)
                if rank_fn is not None:
                    rank_fn(proc)
                # every rank's work lands before ANY rank's close-time
                # final flush snapshots the shared registry
                proc.barrier()
            finally:
                proc.close()
        except BaseException as e:  # noqa: BLE001
            excs[rank] = e

    threads = [threading.Thread(target=main, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "metrics job rank hung"
    if any(excs):
        raise next(e for e in excs if e is not None)


class TestPublisher:
    def test_interval_floor_is_hard(self, fresh_vars):
        from zhpe_ompi_tpu.mca import var as mca_var

        mca_var.set_var("spc_publish_interval_ms", 50)
        d = dvm_mod.Dvm()
        try:
            pub = spc.MetricsPublisher(
                ("127.0.0.1", d.pmix.address[1]), "default", 0)
            # never sub-interval polling: 50ms clamps to the 250ms floor
            assert pub.interval >= spc.PUBLISH_FLOOR_S
            pub.stop()  # never started: releases the client socket
        finally:
            d.stop()
        assert spc.live_publisher_threads() == []

    def test_publish_final_flush_and_hygiene(self):
        d = dvm_mod.Dvm()
        pubs0 = spc.read("spc_publishes")
        try:
            _run_metrics_job(d, n=2, ns="jobflush")
            # final flush at close: both ranks' snapshots in the store
            entries = d.store.lookup("jobflush", "metrics:")
            assert set(entries) == {"metrics:jobflush:0",
                                    "metrics:jobflush:1"}
            for payload in entries.values():
                assert payload["final"] is True
                assert payload["interval_ms"] >= 250
                # the documented table is zero-filled: every documented
                # counter is fleet-visible even if it never fired
                missing = spc.documented_counters() \
                    - set(payload["counters"])
                assert not missing, missing
                assert payload["counters"]["tcp_bytes_sent"] > 0
                # state pvars ride the snapshot
                assert "tcp_posted_recvs" in payload["pvars"]
            assert spc.read("spc_publishes") - pubs0 >= 2
            assert spc.live_publisher_threads() == []
            # namespace destroy drops the job's whole keyspace — the
            # zero-stale-metrics-keys contract
            d.store.destroy_ns("jobflush")
            assert pmix_mod.stale_metric_keys() == []
        finally:
            d.stop()

    def test_sever_kills_publisher_without_final_flush(self):
        """The crash contract: a severed (simulated-crash) proc's
        publisher dies with it but ships NO final snapshot — a clean
        final flush from a corpse would lie to the fleet."""
        d = dvm_mod.Dvm()
        try:
            proc = TcpProc(0, 1, pmix=("127.0.0.1", d.pmix.address[1]),
                           namespace="jobsev", metrics=True, sm=False)
            deadline = time.monotonic() + 10.0
            while not d.store.lookup("jobsev", "metrics:") \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            proc.sever()
            assert spc.live_publisher_threads() == []
            payload = d.store.lookup("jobsev",
                                     "metrics:")["metrics:jobsev:0"]
            assert payload["final"] is False
            d.store.destroy_ns("jobsev")
        finally:
            d.stop()

    def test_explicit_metrics_without_store_is_an_error(self):
        with pytest.raises(errors.ArgError):
            TcpProc(0, 1, metrics=True)

    def test_env_metrics_without_store_degrades_loudly(self, monkeypatch):
        monkeypatch.setenv("ZMPI_METRICS", "1")
        proc = TcpProc(0, 1, sm=False)  # coordinator modex, no store
        try:
            assert proc._metrics_pub is None
        finally:
            proc.close()


class TestDvmMetricsRpc:
    def test_per_rank_job_and_aggregate_views(self):
        d = dvm_mod.Dvm()
        try:
            _run_metrics_job(d, n=2, ns="jobrpc")
            cli = dvm_mod.DvmClient(d.address)
            try:
                view = cli.metrics("jobrpc")
                assert view["job"] == "jobrpc"
                assert set(view["ranks"]) == {0, 1}
                for rec in view["ranks"].values():
                    assert rec["staleness_s"] >= 0.0
                # counters sum across ranks (shared-process registry:
                # aggregate == 2x each rank's global view)
                agg = view["aggregate"]
                assert agg["tcp_bytes_sent"] == sum(
                    r["counters"]["tcp_bytes_sent"]
                    for r in view["ranks"].values())
                one = cli.metrics("jobrpc", 1)
                assert one["counters"] == view["ranks"][1]["counters"]
                with pytest.raises(errors.MpiError):
                    cli.metrics("jobrpc", 7)
                with pytest.raises(errors.MpiError):
                    cli.metrics("no_such_job")
            finally:
                cli.close()
            d.store.destroy_ns("jobrpc")
        finally:
            d.stop()


_PROM_LINE = re.compile(
    r'^(zmpi_[a-z0-9_]+)\{job="([^"]+)",rank="(\d+)"\} '
    r'(-?\d+(?:\.\d+)?)$')


def _http_get(addr, path="/metrics"):
    s = socket.create_connection(addr, 5.0)
    try:
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return head.decode(), body.decode()


class TestMetricsHttp:
    def test_scrape_endpoint_prometheus_exposition(self):
        d = dvm_mod.Dvm(metrics_port=0)
        try:
            assert d.metrics_http is not None
            _run_metrics_job(d, n=2, ns="jobhttp")
            head, body = _http_get(d.metrics_http.address)
            assert "200 OK" in head
            samples = {}
            seen_families: list[str] = []
            for line in body.splitlines():
                if line.startswith("#"):
                    assert line.startswith("# TYPE zmpi_")
                    continue
                m = _PROM_LINE.match(line)
                assert m, f"unparseable exposition line: {line!r}"
                samples[(m.group(1), m.group(2), m.group(3))] = m.group(4)
                if not seen_families or seen_families[-1] != m.group(1):
                    seen_families.append(m.group(1))
            # one CONTIGUOUS block per metric family (the exposition
            # format's rule — strict scrapers reject interleaving)
            assert len(seen_families) == len(set(seen_families))
            # every documented counter scrapes, per rank
            for rank in ("0", "1"):
                for c in spc.documented_counters():
                    assert (f"zmpi_spc_{c}", "jobhttp", rank) in samples
                assert (f"zmpi_metrics_age_seconds", "jobhttp",
                        rank) in samples
            head404, _ = _http_get(d.metrics_http.address, "/nope")
            assert "404" in head404
            d.store.destroy_ns("jobhttp")
        finally:
            d.stop()
        assert dvm_mod.live_metrics_listeners() == []

    def test_off_by_default(self):
        d = dvm_mod.Dvm()
        try:
            assert d.metrics_http is None
        finally:
            d.stop()


# ===================== end-to-end acceptance (slow) ========================


_E2E_PROG = '''
import os, signal, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu.runtime.pmix import PmixClient

VICTIM = int(os.environ["TEST_VICTIM"])

proc = zmpi.host_init()
rank, job = proc.rank, os.environ["ZMPI_JOB"]
proc.barrier()
# survivor-to-survivor traffic so every ring has send/recv/match events
peer = {{0: 1, 1: 0, 2: 3, 3: 2}}[rank]
proc.send(np.arange(32.0) * rank, dest=peer, tag=5)
got = proc.recv(source=peer, tag=5)
proc.barrier()
if rank == VICTIM:
    os.kill(os.getpid(), signal.SIGKILL)
assert proc.ft_state.wait_failed(VICTIM, timeout=15.0), "no classification"
# park until the parent has read our published windows out of the store
pmix_host, rest = os.environ["ZMPI_PMIX"].rsplit(":", 1)
pmix_port = int(rest.split("/")[0])
cl = PmixClient((pmix_host, pmix_port))
try:
    cl.get(job, "release", timeout=60.0)
finally:
    cl.close()
print(f"SURVIVOR-OK rank={{rank}} sum={{float(got.sum())}}", flush=True)
zmpi.host_finalize()
'''


@pytest.mark.slow
class TestMetricsPlaneEndToEnd:
    """The acceptance path: a DVM-launched real-process 4-rank ft job
    publishes metrics; the zprted metrics RPC and GET /metrics serve
    every documented SPC counter per rank; kill -9 one rank and the
    survivors' flight-recorder windows land in the store with the FT
    classification as the tail entry; deterministic teardown gates."""

    def test_kill9_survivor_windows_and_scrape(self, tmp_path,
                                               monkeypatch):
        import io
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        prog = tmp_path / "metrics_e2e.py"
        prog.write_text(_E2E_PROG.format(repo=repo))
        victim = 2
        monkeypatch.setenv("TEST_VICTIM", str(victim))
        d = dvm_mod.Dvm(metrics_port=0)
        try:
            cli = dvm_mod.DvmClient(d.address)
            # pmix_puts rises ONLY on metrics-enabled rows: a plain job
            # touches the store exactly once per rank (its modex card)
            base_puts = spc.read("pmix_puts")
            plain = tmp_path / "plain.py"
            plain.write_text(
                "import sys; sys.path.insert(0, %r)\n"
                "import zhpe_ompi_tpu as zmpi\n"
                "p = zmpi.host_init(); p.barrier(); zmpi.host_finalize()\n"
                % repo)
            plain_cli = dvm_mod.DvmClient(d.address)
            try:
                rc = plain_cli.launch(2, [str(plain)], timeout=60.0)
            finally:
                plain_cli.close()
            assert rc == 0
            plain_puts = spc.read("pmix_puts") - base_puts
            assert plain_puts == 2  # one card put per rank, nothing else

            out, err = io.StringIO(), io.StringIO()
            result = {}

            def run_job():
                result["rc"] = cli.launch(
                    4, [str(prog)], ft=True, metrics=True, timeout=120.0,
                    mca=[("ft_detector_period", "2.0"),
                         ("ft_detector_timeout", "60.0"),
                         ("spc_publish_interval_ms", "50")],
                    stdout=out, stderr=err,
                )

            t = threading.Thread(target=run_job, daemon=True)
            base_puts = spc.read("pmix_puts")
            t.start()
            # wait for the job id, then for the survivors' windows
            deadline = time.monotonic() + 60.0
            while cli.last_job_id is None \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
            job = cli.last_job_id
            assert job, err.getvalue()
            survivors = sorted({0, 1, 2, 3} - {victim})
            view = None
            poll = dvm_mod.DvmClient(d.address)
            try:
                while time.monotonic() < deadline:
                    try:
                        view = poll.metrics(job)
                    except errors.MpiError:
                        view = None
                    if view is not None and all(
                            "flightrec" in view["ranks"].get(r, {})
                            for r in survivors):
                        break
                    time.sleep(0.25)
            finally:
                poll.close()
            assert view is not None, (out.getvalue(), err.getvalue())
            doc = spc.documented_counters()
            for r in survivors:
                rec = view["ranks"][r]
                # every documented counter, per rank, zero-filled
                assert not doc - set(rec["counters"])
                # spc_publishes rises; the floor held (50 → 250ms)
                assert rec["counters"]["spc_publishes"] >= 1
                assert rec["interval_ms"] >= 250
                # the postmortem: the last-N window's TAIL is the typed
                # classification of the victim, OS truth from the daemon
                # the publication carries the ring's clock anchor so
                # the monotonic event stamps are mappable off-process
                assert rec["flightrec"]["anchor_mono_ns"] > 0
                assert rec["flightrec"]["anchor_wall"] > 0
                window = rec["flightrec"]["events"]
                assert window, f"rank {r}: empty flight recorder"
                tail = window[-1]
                assert tail["type"] == "ft_class"
                assert tail["rank"] == victim
                assert tail["cause"] == "daemon"
                kinds = {e["type"] for e in window}
                assert "send" in kinds and "recv" in kinds
            # the scrape endpoint serves the same plane (lines parse)
            head, body = _http_get(d.metrics_http.address)
            assert "200 OK" in head
            for r in survivors:
                for c in sorted(doc):
                    pat = f'zmpi_spc_{c}{{job="{job}",rank="{r}"}} '
                    assert any(line.startswith(pat)
                               for line in body.splitlines()), (c, r)
            # release the survivors; the job runs out
            d.store.put(job, 99, "release", True)
            d.store.commit(job, 99)
            t.join(90)
            assert not t.is_alive(), "job never exited"
            # ft job, victim killed by signal 9: rc = 128 + 9
            assert result["rc"] == 137, (out.getvalue(), err.getvalue())
            assert len(re.findall(r"SURVIVOR-OK rank=(\d+)",
                                  out.getvalue())) == 3
            # metrics-enabled row moved the store far beyond modex
            assert spc.read("pmix_puts") - base_puts > 4
            # job end destroys the namespace: zero stale metrics keys.
            # The exit frame streams BEFORE the daemon's finalize runs,
            # so give the async destroy its moment
            finalize_deadline = time.monotonic() + 5.0
            while pmix_mod.stale_metric_keys() \
                    and time.monotonic() < finalize_deadline:
                time.sleep(0.05)
            assert pmix_mod.stale_metric_keys() == []
            cli.stop()
            cli.close()
        finally:
            d.stop()
        # zero leaked sockets/threads/listeners at teardown
        assert dvm_mod.live_metrics_listeners() == []
        assert dvm_mod.live_dvms() == []
        assert spc.live_publisher_threads() == []
