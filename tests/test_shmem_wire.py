"""OpenSHMEM over the wire plane: the PGAS surface re-run against the AM
backend over N real socket procs (round-3 unweld proof — a DCN job gets
symmetric-heap put/get/AMOs/locks/collectives without a shared address
space)."""

import numpy as np
import pytest

from test_tcp import run_tcp
from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.shmem.api import shmem_wire_pe

N = 4


def run_shmem(n, fn, heap_bytes=1 << 16, timeout=60.0):
    """Launch n wire PEs and run fn(pe) on each."""

    def main(p):
        pe = shmem_wire_pe(p, heap_bytes)
        return fn(pe)

    return run_tcp(n, main, timeout=timeout)


class TestWireShmem:
    def test_circular_shift(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(4, np.float64)
            pe.local(sym)[...] = me
            pe.barrier_all()
            pe.put(sym, np.full(4, float(me)), (me + 1) % n)
            pe.barrier_all()
            got = pe.local(sym).copy()
            pe.barrier_all()
            pe.shfree(sym)
            return got.tolist()

        res = run_shmem(N, prog)
        for r in range(N):
            assert res[r] == [float((r - 1) % N)] * 4

    def test_symmetric_offsets_agree(self):
        """Lockstep allocators must produce identical offsets on every PE."""

        def prog(pe):
            a = pe.shmalloc(8, np.float32)
            b = pe.shmalloc(3, np.int64)
            offs = (a.offset, b.offset)
            pe.barrier_all()
            pe.shfree(b)
            pe.shfree(a)
            return offs

        res = run_shmem(N, prog)
        assert all(r == res[0] for r in res)

    def test_p_g_single_element(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(8, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            pe.p(sym, me + 100, (me + 1) % n, index=me)
            pe.barrier_all()
            # read back what our left neighbor wrote into our slot
            left = (me - 1) % n
            val = int(pe.g(sym, me, index=left))
            pe.barrier_all()
            pe.shfree(sym)
            return val

        res = run_shmem(N, prog)
        assert res == [((r - 1) % N) + 100 for r in range(N)]

    def test_strided_iput_iget(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(16, np.float64)
            pe.local(sym)[...] = -1.0
            pe.barrier_all()
            # every PE writes [me, me, me, me] at stride 4 into neighbor
            pe.iput(sym, np.full(4, float(me)), (me + 1) % n, tst=4, sst=1)
            pe.quiet()
            pe.barrier_all()
            local = pe.local(sym).copy()
            # strided fetch of our own neighbor's instance
            got = pe.iget(sym, (me + 1) % n, n=4, sst=4)
            pe.barrier_all()
            pe.shfree(sym)
            return (local[::4].tolist(), got.tolist())

        res = run_shmem(N, prog)
        for r in range(N):
            left = float((r - 1) % N)
            assert res[r][0] == [left] * 4
            assert res[r][1] == [float(r)] * 4

    def test_fetch_add_all_pes(self):
        def prog(pe):
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            old = int(pe.atomic_fetch_add(sym, 1, 0))
            pe.barrier_all()
            total = int(pe.local(sym)[0]) if pe.my_pe() == 0 else None
            pe.barrier_all()
            pe.shfree(sym)
            return (old, total)

        res = run_shmem(N, prog)
        assert sorted(o for o, _ in res) == list(range(N))
        assert res[0][1] == N

    def test_compare_swap(self):
        def prog(pe):
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            old = int(pe.atomic_compare_swap(
                sym, cond=0, value=pe.my_pe() + 1, pe=0
            ))
            pe.barrier_all()
            winner = int(pe.local(sym)[0]) if pe.my_pe() == 0 else None
            pe.barrier_all()
            pe.shfree(sym)
            return (old, winner)

        res = run_shmem(N, prog)
        assert [o for o, _ in res].count(0) == 1
        assert res[0][1] in range(1, N + 1)

    def test_swap_set_fetch(self):
        def prog(pe):
            me = pe.my_pe()
            sym = pe.shmalloc(N, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            pe.atomic_set(sym, me * 10, 0, index=me)
            pe.barrier_all()
            seen = int(pe.atomic_fetch(sym, 0, index=me))
            old = int(pe.atomic_swap(sym, -1, 0, index=me))
            pe.barrier_all()
            pe.shfree(sym)
            return (seen, old)

        res = run_shmem(N, prog)
        assert res == [(r * 10, r * 10) for r in range(N)]

    def test_wait_until(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            if me == 0:
                for r in range(1, n):
                    pe.p(sym, 7, r, index=0)
                pe.quiet()
                pe.barrier_all()
                return 7
            pe.wait_until(sym, "eq", 7, timeout=15.0)
            got = int(pe.local(sym)[0])
            pe.barrier_all()
            return got

        assert run_shmem(N, prog) == [7] * N

    def test_lock_mutual_exclusion(self):
        """shmem_set_lock over the wire: unlocked read-modify-write would
        lose updates; the home-PE lock manager must serialize them."""

        def prog(pe):
            lock = pe.shmalloc(1, np.int64)
            ctr = pe.shmalloc(1, np.int64)
            pe.local(ctr)[...] = 0
            pe.barrier_all()
            for _ in range(10):
                pe.set_lock(lock)
                v = int(pe.g(ctr, 0, index=0))
                pe.p(ctr, v + 1, 0, index=0)
                pe.quiet()
                pe.clear_lock(lock)
            pe.barrier_all()
            out = int(pe.local(ctr)[0]) if pe.my_pe() == 0 else None
            pe.barrier_all()
            pe.shfree(ctr)
            pe.shfree(lock)
            return out

        assert run_shmem(N, prog)[0] == 10 * N

    def test_test_lock(self):
        def prog(pe):
            lock = pe.shmalloc(1, np.int64)
            pe.barrier_all()
            if pe.my_pe() == 0:
                assert pe.test_lock(lock) is True
                pe.barrier_all()  # rank 1 tries while we hold it
                pe.barrier_all()
                pe.clear_lock(lock)
                pe.barrier_all()
                pe.shfree(lock)
                return True
            if pe.my_pe() == 1:
                pe.barrier_all()
                got = pe.test_lock(lock)
                pe.barrier_all()
                pe.barrier_all()
                pe.shfree(lock)
                return got
            pe.barrier_all()
            pe.barrier_all()
            pe.barrier_all()
            pe.shfree(lock)
            return None

        assert run_shmem(3, prog)[1] is False

    def test_broadcast_and_reductions(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            src = pe.shmalloc(4, np.float64)
            dst = pe.shmalloc(4, np.float64)
            pe.local(src)[...] = float(me + 1)
            pe.barrier_all()
            pe.sum_to_all(dst, src)
            total = pe.local(dst).copy()
            pe.local(src)[...] = float(me)
            pe.broadcast(src, root=2)
            bcast = pe.local(src).copy()
            pe.barrier_all()
            pe.shfree(dst)
            pe.shfree(src)
            return (total.tolist(), bcast.tolist())

        res = run_shmem(N, prog)
        expect_sum = [float(sum(range(1, N + 1)))] * 4
        for r in range(N):
            assert res[r][0] == expect_sum
            assert res[r][1] == [2.0] * 4

    def test_fcollect_alltoall(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            src = pe.shmalloc(2, np.int64)
            dst = pe.shmalloc(2 * n, np.int64)
            pe.local(src)[...] = [me * 2, me * 2 + 1]
            pe.barrier_all()
            pe.fcollect(dst, src)
            coll = pe.local(dst).copy()
            a2a_src = pe.shmalloc(n, np.int64)
            a2a_dst = pe.shmalloc(n, np.int64)
            pe.local(a2a_src)[...] = [me * 10 + i for i in range(n)]
            pe.barrier_all()
            pe.alltoall(a2a_dst, a2a_src)
            a2a = pe.local(a2a_dst).copy()
            pe.barrier_all()
            for s in (a2a_dst, a2a_src, dst, src):
                pe.shfree(s)
            return (coll.tolist(), a2a.tolist())

        res = run_shmem(N, prog)
        for r in range(N):
            assert res[r][0] == list(range(2 * N))
            assert res[r][1] == [i * 10 + r for i in range(N)]

    def test_exhaustion_raises_on_every_pe(self):
        def prog(pe):
            got_err = False
            try:
                pe.shmalloc(1 << 22, np.uint8)  # bigger than the heap
            except errors.MpiError:
                got_err = True
            pe.barrier_all()
            return got_err

        assert run_shmem(2, prog, heap_bytes=1 << 12) == [True, True]


class TestNonblockingRMA:
    """VERDICT round-4 Missing #4: shmem_put_nbi/get_nbi with completion
    at shmem_quiet (``oshmem/shmem/c/shmem_put_nb.c``, ``shmem_get_nb.c``)
    on the AM backend."""

    def test_put_nbi_completes_at_quiet(self):
        """nb puts overlap local compute; after quiet + barrier the data
        is remotely visible."""

        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(8, np.float64)
            pe.local(sym)[...] = -1.0
            pe.barrier_all()
            pe.put_nbi(sym, np.full(8, float(me)), (me + 1) % n)
            # overlapped "compute" while the AM is in flight
            acc = float(np.sum(np.arange(1000)))
            pe.quiet()
            pe.barrier_all()
            got = pe.local(sym).copy()
            pe.barrier_all()
            pe.shfree(sym)
            return (acc, got.tolist())

        res = run_shmem(N, prog)
        for r in range(N):
            assert res[r][0] == 499500.0
            assert res[r][1] == [float((r - 1) % N)] * 8

    def test_get_nbi_target_fills_only_at_quiet(self):
        """The deferred scatter: the caller's buffer holds its sentinel
        until quiet, then the remote data."""

        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(4, np.int64)
            pe.local(sym)[...] = me * 10
            pe.barrier_all()
            buf = np.full(4, -7, np.int64)
            pe.get_nbi(sym, (me + 1) % n, buf)
            before = buf.copy()
            pe.quiet()
            after = buf.copy()
            pe.barrier_all()
            pe.shfree(sym)
            return (before.tolist(), after.tolist())

        res = run_shmem(N, prog)
        for r in range(N):
            before, after = res[r]
            assert before == [-7] * 4          # untouched pre-quiet
            assert after == [((r + 1) % N) * 10] * 4

    def test_many_nbi_in_flight_drain_in_one_quiet(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(2, np.float32)
            pe.local(sym)[...] = float(me)
            pe.barrier_all()
            bufs = [np.zeros(2, np.float32) for _ in range(n)]
            for p in range(n):
                pe.get_nbi(sym, p, bufs[p])
            pe.quiet()
            pe.barrier_all()
            pe.shfree(sym)
            return [b.tolist() for b in bufs]

        res = run_shmem(N, prog)
        for r in range(N):
            assert res[r] == [[float(p)] * 2 for p in range(N)]

    def test_get_nbi_rejects_bad_target(self):
        """Out-parameter validation is uniform at the dispatch level:
        wrong size, wrong dtype (even at equal byte size), non-array, and
        non-contiguous targets all fail loudly at call time."""

        def prog(pe):
            sym = pe.shmalloc(4, np.float64)
            hits = 0
            for bad in (np.zeros(3, np.float64),      # size
                        np.zeros(8, np.float32),      # dtype, same nbytes
                        [0.0] * 4,                    # coerced temporary
                        np.zeros(8, np.float64)[::2]):  # non-contiguous
                try:
                    pe.get_nbi(sym, 0, bad)
                except errors.ArgError:
                    hits += 1
            pe.barrier_all()
            pe.shfree(sym)
            return hits

        assert run_shmem(N, prog) == [4] * N

    def test_barrier_all_is_implicit_quiet(self):
        """The spec: barrier_all completes outstanding nbi ops."""

        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(2, np.int32)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            pe.put_nbi(sym, np.full(2, me + 1, np.int32), (me + 1) % n)
            buf = np.zeros(2, np.int32)
            pe.get_nbi(sym, me, buf)  # self-get, also pending
            pe.barrier_all()          # implicit quiet
            got = pe.local(sym).copy()
            pe.barrier_all()
            pe.shfree(sym)
            return got.tolist()

        res = run_shmem(N, prog)
        for r in range(N):
            assert res[r] == [((r - 1) % N) + 1] * 2
