"""Sharded-array IO — the TPU-native MPI_File_write_all.

The insight this module encodes: a JAX ``NamedSharding`` is exactly an
MPI-IO *file view* — each device owns a disjoint index-set of the global
array, as each MPI rank's (disp, etype, filetype) view tiles a disjoint
byte-set of the file (``common_ompio_file_view.c``).  So collective array
IO needs no new machinery: every addressable shard reads/writes its own
extent of one flat file, which is what ``fcoll``'s aggregation strategies
(two_phase/vulcan, SURVEY.md §2.3) reconstruct laboriously from per-rank
requests.

Format: a fixed 512-byte JSON header (magic, dtype, shape) followed by the
array in C order.  Multi-host note: each controller writes only its
addressable shards, so the format works under ``jax.distributed`` when all
hosts see a shared filesystem — the same contract MPI-IO itself assumes.
"""

from __future__ import annotations

import json

import numpy as np

import jax

from ..core import errors

_MAGIC = "ZMPIARR1"
_HEADER = 512


def _header_bytes(arr) -> bytes:
    h = json.dumps({
        "magic": _MAGIC,
        "dtype": str(np.dtype(arr.dtype)),
        "shape": list(arr.shape),
    }).encode()
    if len(h) > _HEADER - 1:
        raise errors.ArgError("header overflow (shape rank too large?)")
    return h + b" " * (_HEADER - len(h))


def _read_header(path: str) -> tuple[np.dtype, tuple[int, ...]]:
    with open(path, "rb") as f:
        raw = f.read(_HEADER)
    try:
        meta = json.loads(raw.decode().strip())
        if meta.get("magic") != _MAGIC:
            raise ValueError
    except (ValueError, UnicodeDecodeError):
        raise errors.ArgError(f"{path} is not a zmpi sharded-array file")
    return np.dtype(meta["dtype"]), tuple(meta["shape"])


def save_sharded(path: str, arr) -> None:
    """Write a (possibly sharded) jax array: every addressable shard stores
    its slice at the file offsets its sharding index dictates."""
    header = _header_bytes(arr)
    with open(path, "wb") as f:
        f.write(header)
        f.truncate(_HEADER + int(np.prod(arr.shape or (1,)))
                   * np.dtype(arr.dtype).itemsize)
    mm = np.memmap(path, dtype=np.dtype(arr.dtype), mode="r+",
                   offset=_HEADER, shape=tuple(arr.shape))
    if hasattr(arr, "addressable_shards"):
        seen = set()
        for shard in arr.addressable_shards:
            key = tuple(
                (s.start, s.stop, s.step) for s in shard.index
            ) if shard.index else ("scalar",)
            if key in seen:  # replicated shards: write once
                continue
            seen.add(key)
            mm[shard.index] = np.asarray(shard.data)
    else:
        mm[...] = np.asarray(arr)
    mm.flush()
    del mm


def load_sharded(path: str, sharding=None):
    """Read an array saved by :func:`save_sharded`.  With a `sharding`,
    each device materializes only its own extent (the collective-read
    path); without one, returns a host numpy array."""
    dtype, shape = _read_header(path)
    mm = np.memmap(path, dtype=dtype, mode="r", offset=_HEADER, shape=shape)
    if sharding is None:
        return np.array(mm)
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: np.array(mm[idx])
    )
