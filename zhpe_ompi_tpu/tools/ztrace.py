"""ztrace CLI — merged timelines and critical-path postmortems.

The consumer half of the tracing plane (:mod:`zhpe_ompi_tpu.runtime.
ztrace` is the recorder): collect every rank's published
``trace:<job>:<rank>`` buffer from the DVM's PMIx store, correct the
per-process monotonic stamps onto ONE timeline — wall anchors by
default, refined by mpisync offsets when the job published a
``tracesync:<job>`` measurement (:func:`publish_clock_sync`) — and
emit:

- **Chrome trace-event JSON** (``chrome://tracing`` / Perfetto): one
  tid per rank, duration events for spans, flow arrows for every
  wire-propagated send→deliver edge;
- a text **critical-path report**: per collective instance the
  straggler rank and a late-sender / late-receiver /
  ring-backpressure classification of its pt2pt pairs, and per FT
  event the recovery's legs (classification→agree→shrink→respawn)
  with the longest leg named.

Clock model: every span stamps ``monotonic_ns`` in its process; the
payload carries the recorder's back-to-back ``(anchor_wall,
anchor_mono_ns)`` pair, defining the rank's *trace clock*
``T_r(t) = anchor_wall + (t − anchor_mono)/1e9``.  mpisync measures
``theta_r = T_r − T_0`` directly (the ``clock`` hook feeds it
:func:`~zhpe_ompi_tpu.runtime.ztrace.trace_clock`), so the corrected
time is ``T_r(t) − theta_r`` — rank 0's trace clock is the merged
timeline's time base, and a deliver span can never precede its parent
send span by more than the estimator's error.
"""

from __future__ import annotations

import json

from ..core import errors
from ..runtime import ztrace as ztrace_rt

_EPS_S = 2e-5  # pairing tolerance: below the min-RTT/2 estimator error


# -- collection --------------------------------------------------------------


def collect(pmix_addr, job: str, timeout: float = 10.0
            ) -> tuple[list[dict], list[float] | None]:
    """Read every published ``trace:<job>:<rank>`` buffer (plus the
    optional ``tracesync:<job>`` offsets) from the store — the
    non-blocking ``lookup`` verb, so ranks that never published are
    simply absent (a kill -9'd victim's LAST periodic buffer is what
    the store holds)."""
    from ..runtime.pmix import PmixClient

    client = PmixClient(pmix_addr, timeout=timeout)
    try:
        view = client.lookup(job, "trace:")
        offsets = None
        sync = client.lookup(job, "tracesync:")
        for _key, value in sorted(sync.items()):
            if isinstance(value, (list, tuple)):
                offsets = [float(v) for v in value]
                break
    finally:
        client.close()
    payloads = []
    for key, payload in sorted(view.items()):
        if not isinstance(payload, dict) or "spans" not in payload:
            continue  # foreign key shape
        payloads.append(payload)
    return payloads, offsets


def publish_clock_sync(ep, rounds: int = 16) -> list[float] | None:
    """Collective over a PMIx-served job's endpoints: run the mpisync
    ping-pong with each process's wall-anchored TRACE clock as the
    measured clock, and publish rank 0's offsets as
    ``tracesync:<job>`` so the ztrace CLI refines its merge with a
    real measurement instead of raw wall anchors.  Returns the offsets
    on rank 0, None elsewhere."""
    from . import mpisync

    offsets = mpisync.sync_clocks(
        ep, rounds=rounds,
        clock=lambda _r: ztrace_rt.trace_clock(),
    )
    if offsets is None:
        return None
    addr = getattr(ep, "_pmix_addr", None)
    ns = getattr(ep, "_pmix_ns", None)
    if addr is None:
        raise errors.UnsupportedError(
            "publish_clock_sync needs a PMIx-served endpoint (the "
            "tracesync key lives in the job's namespace)"
        )
    from ..runtime.pmix import PmixClient

    client = PmixClient(addr, timeout=10.0)
    try:
        client.put(ns, ep.rank, f"tracesync:{ns}",
                   [float(o) for o in offsets])
        client.commit(ns, ep.rank)
    finally:
        client.close()
    return offsets


# -- clock correction + merge ------------------------------------------------


def corrected_spans(payloads: list[dict],
                    offsets: list[float] | None = None) -> list[dict]:
    """One flat span list on the merged timeline: every span gains
    ``ts``/``dur`` (seconds, rank 0's trace clock) and ``tid`` (the
    publishing rank).  ``offsets[r]`` is rank r's trace clock minus
    rank 0's (the mpisync estimate); absent offsets fall back to the
    raw wall anchors (exact for same-host jobs whose wall clock is
    shared, the loopback-emulation case)."""
    def theta_of(r: int) -> float:
        if offsets is not None and 0 <= r < len(offsets):
            return float(offsets[r])
        return 0.0

    out = []
    seen: set[int] = set()
    for payload in payloads:
        rank = int(payload.get("rank", -1))
        wall = float(payload.get("anchor_wall", 0.0))
        mono = int(payload.get("anchor_mono_ns", 0))
        for span in payload.get("spans", ()):
            sid = span.get("sid")
            # thread-plane jobs share ONE per-process ring: every
            # rank's publisher ships the same spans, so dedup by sid
            # and attribute each span to ITS recording rank, not the
            # publishing payload's — else the merge holds every span
            # N-fold with wrong rank attribution
            if sid is not None:
                if sid in seen:
                    continue
                seen.add(sid)
            s = dict(span)
            srank = int(s.get("rank", -1))
            tid = srank if srank >= 0 else rank
            theta = theta_of(tid)
            t0 = wall + (int(s["t0"]) - mono) / 1e9 - theta
            t1 = wall + (int(s["t1"]) - mono) / 1e9 - theta
            s["ts"] = t0
            s["dur"] = max(0.0, t1 - t0)
            s["tid"] = tid
            out.append(s)
    out.sort(key=lambda s: s["ts"])
    return out


def happens_before_violations(spans: list[dict],
                              tolerance: float = _EPS_S) -> list[tuple]:
    """Clock-corrected causality check: a deliver/cts span whose
    corrected start precedes its parent send span's START (beyond the
    estimator tolerance) is a correction failure — the merged-timeline
    test gate."""
    by_sid = {s["sid"]: s for s in spans}
    bad = []
    for s in spans:
        parent = s.get("parent")
        if parent is None or s["kind"] not in ("deliver", "cts"):
            continue
        src = by_sid.get(parent)
        if src is None:
            continue
        if s["ts"] < src["ts"] - tolerance:
            bad.append((src, s, src["ts"] - s["ts"]))
    return bad


# -- Chrome trace-event output ----------------------------------------------


def chrome_trace(payloads: list[dict],
                 offsets: list[float] | None = None,
                 job: str = "zmpi") -> dict:
    """The ``chrome://tracing`` / Perfetto JSON object: one pid for
    the job, one tid per rank, ``X`` (complete) events for spans,
    flow arrows (``s``/``f``) along every cross-rank parent edge."""
    spans = corrected_spans(payloads, offsets)
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = min(s["ts"] for s in spans)
    by_sid = {s["sid"]: s for s in spans}
    events: list[dict] = []
    for rank in sorted({s["tid"] for s in spans}):
        events.append({
            "name": "thread_name", "ph": "M", "pid": job,
            "tid": rank, "args": {"name": f"rank {rank}"},
        })
    for s in spans:
        args = {k: v for k, v in s.items()
                if k not in ("ts", "dur", "tid", "sid", "kind", "t0",
                             "t1")}
        name = s["kind"]
        if "op" in s:
            name = f"{s['kind']}:{s['op']}"
        elif "name" in s:
            name = f"{s['kind']}:{s['name']}"
        events.append({
            "name": name, "ph": "X", "cat": s["kind"],
            "ts": (s["ts"] - t_base) * 1e6,
            "dur": max(s["dur"] * 1e6, 1.0),
            "pid": job, "tid": s["tid"], "args": args,
        })
        parent = s.get("parent")
        src = by_sid.get(parent) if parent is not None else None
        if src is not None and src["tid"] != s["tid"]:
            # a cross-rank causal edge: draw the flow arrow
            fid = f"f{parent}-{s['sid']}"
            events.append({
                "name": "msg", "ph": "s", "cat": "flow", "id": fid,
                "ts": (src["ts"] - t_base) * 1e6, "pid": job,
                "tid": src["tid"],
            })
            events.append({
                "name": "msg", "ph": "f", "bp": "e", "cat": "flow",
                "id": fid, "ts": (s["ts"] - t_base) * 1e6, "pid": job,
                "tid": s["tid"],
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- critical-path report ----------------------------------------------------


def _pair_messages(spans: list[dict]) -> list[dict]:
    """Deliver→send→recv triples: each deliver span references its
    parent send by sid; the matching recv on the deliver's rank is the
    earliest compatible recv span (cid equal, src/tag wildcard-aware)
    completing at/after the delivery."""
    by_sid = {s["sid"]: s for s in spans}
    recvs_by_rank: dict[int, list[dict]] = {}
    for s in spans:
        if s["kind"] == "recv":
            recvs_by_rank.setdefault(s["tid"], []).append(s)
    for rs in recvs_by_rank.values():
        rs.sort(key=lambda r: r["ts"])
    used: set[int] = set()
    pairs = []
    for d in spans:
        # eager/loopback/sm messages pair at their deliver span; a
        # rendezvous message pairs at its receiver-side CTS leg (the
        # user-visible envelope — the tcp data frame rides a protocol
        # cid, and the thread plane's data deliver is marked leg=data)
        if d["kind"] not in ("deliver", "cts"):
            continue
        if d.get("leg") == "data":
            continue  # rndv bulk leg: already paired at its CTS
        send = by_sid.get(d.get("parent"))
        if send is None or send["kind"] != "send":
            continue
        recv = None
        for r in recvs_by_rank.get(d["tid"], ()):
            if id(r) in used:
                continue
            if r.get("cid") != d.get("cid"):
                continue  # recv spans stamp the posted cid exactly
            if r.get("src", -1) not in (-1, d.get("src")):
                continue
            if r.get("tag", -1) not in (-1, d.get("tag")):
                continue
            if r["ts"] + r["dur"] + _EPS_S < d["ts"]:
                continue  # completed before this delivery: other msg
            recv = r
            used.add(id(r))
            break
        if recv is not None:
            pairs.append({"send": send, "deliver": d, "recv": recv})
    return pairs


def _classify_pair(pair: dict) -> str:
    """The mpiP/Vampir taxonomy on one message: the receiver posted
    before the message arrived → it WAITED on a late sender; the
    message arrived (parked unexpected) before the post → late
    receiver; otherwise balanced."""
    d, r = pair["deliver"], pair["recv"]
    if pair["send"].get("bp"):
        return "ring-backpressure"
    if r["ts"] + _EPS_S < d["ts"]:
        return "late-sender"
    if d["ts"] + _EPS_S < r["ts"]:
        return "late-receiver"
    return "balanced"


def _coll_instances(spans: list[dict]) -> list[dict]:
    """COLL spans grouped into per-instance windows: the i-th
    occurrence of op X on every rank is one collective instance (the
    schedules are collective-ordered by construction — the same
    counter discipline the tag windows use)."""
    per_rank: dict[tuple, list[dict]] = {}
    for s in spans:
        if s["kind"] != "coll":
            continue
        per_rank.setdefault((s["tid"], s.get("op", "?")), []).append(s)
    for v in per_rank.values():
        v.sort(key=lambda s: s["ts"])
    instances: dict[tuple, dict] = {}
    for (rank, op), rows in per_rank.items():
        for i, s in enumerate(rows):
            inst = instances.setdefault((op, i), {
                "op": op, "index": i, "ranks": {},
            })
            inst["ranks"][rank] = s
    out = []
    for (op, i), inst in sorted(instances.items()):
        rows = inst["ranks"]
        inst["t0"] = min(s["ts"] for s in rows.values())
        inst["t1"] = max(s["ts"] + s["dur"] for s in rows.values())
        inst["straggler"] = max(rows, key=lambda r: rows[r]["ts"])
        inst["straggler_lag"] = rows[inst["straggler"]]["ts"] - inst["t0"]
        out.append(inst)
    return out


def _recovery_legs(spans: list[dict]) -> list[dict]:
    """Per FT classification (crash causes only): the recovery spans
    that follow it — agreement, shrink, respawn, and the rollback
    (checkpoint-restore) leg — with the longest leg named.  Goodbyes
    are orderly departures, not recoveries."""
    events = []
    for ft in spans:
        if ft["kind"] != "ft_class" or ft.get("cause") == "goodbye":
            continue
        events.append(ft)
    # one recovery per failed rank: the earliest classification wins
    # (every survivor records one; they describe the same recovery)
    seen: set[int] = set()
    roots = []
    for ft in sorted(events, key=lambda s: s["ts"]):
        victim = ft.get("failed", -1)
        if victim in seen:
            continue
        seen.add(victim)
        roots.append(ft)
    out = []
    for i, ft in enumerate(roots):
        # a recovery's legs live between ITS classification and the
        # NEXT victim's — without the upper bound, a later failure's
        # (usually long) respawn would be misattributed to every
        # earlier recovery in a multi-failure postmortem
        upper = roots[i + 1]["ts"] if i + 1 < len(roots) \
            else float("inf")
        legs = [
            s for s in spans
            if s["kind"] in ("agree", "shrink", "respawn", "rollback")
            and ft["ts"] - _EPS_S <= s["ts"] < upper - _EPS_S
        ]
        out.append({
            "victim": ft.get("failed", -1),
            "cause": ft.get("cause", "?"),
            "t": ft["ts"],
            "legs": legs,
            "longest": max(legs, key=lambda s: s["dur"])
            if legs else None,
        })
    return out


def critical_path_report(payloads: list[dict],
                         offsets: list[float] | None = None) -> str:
    """The text postmortem: per collective instance its straggler and
    message-pair classification, per FT event the recovery legs and
    the longest one."""
    spans = corrected_spans(payloads, offsets)
    lines = [
        f"ztrace critical-path report — {len(payloads)} rank buffer(s), "
        f"{len(spans)} span(s), offsets "
        f"{'mpisync' if offsets is not None else 'wall-anchor'}",
    ]
    dropped = {
        int(p.get("rank", -1)): int(p.get("dropped", 0))
        for p in payloads if int(p.get("dropped", 0)) > 0
    }
    if dropped:
        # a truncated ring breaks the per-rank occurrence pairing the
        # collective instances below rely on — say so up front rather
        # than letting a misaligned merge read as authoritative
        lines.append(
            "WARNING: span ring overwrote on "
            + ", ".join(f"rank {r} ({n} dropped)"
                        for r, n in sorted(dropped.items()))
            + " — collective instance pairing may be misaligned "
            "(raise ztrace_capacity)"
        )
    pairs = _pair_messages(spans)
    insts = _coll_instances(spans)
    if insts:
        lines.append("")
        lines.append("collectives:")
        for inst in insts:
            window_pairs = [
                p for p in pairs
                if inst["t0"] - _EPS_S <= p["deliver"]["ts"]
                <= inst["t1"] + _EPS_S
            ]
            counts: dict[str, int] = {}
            for p in window_pairs:
                c = _classify_pair(p)
                counts[c] = counts.get(c, 0) + 1
            if counts.get("ring-backpressure"):
                label = "ring-backpressure"
            elif counts.get("late-sender", 0) > counts.get(
                    "late-receiver", 0):
                label = "late-sender"
            elif counts.get("late-receiver", 0) > 0:
                label = "late-receiver"
            else:
                label = "balanced"
            detail = ", ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            ) or "no pairs in window"
            lines.append(
                f"  {inst['op']}[{inst['index']}]: "
                f"{len(inst['ranks'])} rank(s), straggler rank "
                f"{inst['straggler']} "
                f"(+{inst['straggler_lag'] * 1e3:.2f} ms), "
                f"classification {label} ({detail})"
            )
    recoveries = _recovery_legs(spans)
    if recoveries:
        lines.append("")
        lines.append("ft recoveries:")
        for rec in recoveries:
            lines.append(
                f"  rank {rec['victim']} ({rec['cause']}): "
                f"{len(rec['legs'])} recovery leg span(s)"
            )
            for s in sorted(rec["legs"], key=lambda s: s["ts"]):
                mark = "  <-- longest leg" \
                    if s is rec["longest"] else ""
                lines.append(
                    f"    {s['kind']:8s} rank {s['tid']} "
                    f"{s['dur'] * 1e3:9.2f} ms{mark}"
                )
    hb = happens_before_violations(spans)
    lines.append("")
    lines.append(
        f"happens-before: {len(hb)} violation(s) after clock correction"
    )
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:  # pragma: no cover - CLI
    import argparse

    p = argparse.ArgumentParser(
        description="merged-timeline trace collector (ztrace)")
    p.add_argument("--pmix", required=True,
                   help="the DVM store address host:port (zprted "
                        "prints it at startup)")
    p.add_argument("--job", required=True, help="job id / namespace")
    p.add_argument("-o", "--out", default=None,
                   help="write Chrome trace-event JSON here")
    p.add_argument("--report", action="store_true",
                   help="print the critical-path report")
    args = p.parse_args(argv)
    host, port = args.pmix.rsplit(":", 1)
    payloads, offsets = collect((host, int(port)), args.job)
    if not payloads:
        print(f"no trace:{args.job}:* buffers published — launch with "
              f"--trace / ZMPI_TRACE=1")
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(chrome_trace(payloads, offsets, job=args.job), f)
        print(f"wrote {args.out} "
              f"({sum(len(p.get('spans', ())) for p in payloads)} "
              f"spans, {len(payloads)} ranks)")
    if args.report or not args.out:
        print(critical_path_report(payloads, offsets))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
