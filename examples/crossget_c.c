#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"
#define N (6 * 1024 * 1024 / 8)  /* 6 MB window: reply > ring capacity */
int main(int argc, char **argv) {
  MPI_Init(&argc, &argv);
  int rank;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  double *base = malloc(N * sizeof(double));
  for (int i = 0; i < N; i++) base[i] = rank * 1000.0 + i % 997;
  MPI_Win win;
  MPI_Win_create(base, N * sizeof(double), sizeof(double),
                 MPI_INFO_NULL, MPI_COMM_WORLD, &win);
  MPI_Win_fence(0, win);
  double *got = malloc(N * sizeof(double));
  if (rank < 2) {
    int peer = 1 - rank;
    /* ranks 0/1 Get each other's ENTIRE 6 MB window at once: the
     * replies exceed the 4 MiB ring, crossing in both directions
     * (ranks >= 2 stay in the fence, proving their inbound frames
     * are not frozen by the pair's spill) */
    if (MPI_Get(got, N, MPI_DOUBLE, peer, 0, N, MPI_DOUBLE, win) !=
        MPI_SUCCESS) return 3;
    MPI_Win_fence(0, win);
    for (int i = 0; i < N; i += 4099)
      if (got[i] != peer * 1000.0 + i % 997) return 4;
  } else {
    MPI_Win_fence(0, win);
  }
  MPI_Win_free(&win);
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("crossget OK\n");
  MPI_Finalize();
  free(base); free(got);
  return 0;
}
