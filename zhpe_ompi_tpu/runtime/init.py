"""Runtime init/finalize — ``ompi_mpi_init`` re-designed for SPMD.

The reference's init sequence (``ompi/runtime/ompi_mpi_init.c:384``, SURVEY.md
§3.1) is: OPAL init → RTE/PMIx wire-up → open frameworks → select PML → modex
→ build COMM_WORLD → add_procs → coll select.  The TPU-native sequence
collapses the wire-up (the platform knows the topology) to:

    init() → [jax.distributed.initialize if multi-process] → build world mesh
           → open frameworks → construct COMM_WORLD / COMM_SELF

There is no modex (no endpoint addresses to exchange), no add_procs (the mesh
IS the proc table), and per-communicator coll selection is lazy.
"""

from __future__ import annotations

import threading
import time

from ..comm.communicator import Communicator
from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..parallel import mesh as mesh_mod
from . import spc

_stream = mca_output.open_stream("runtime")

_global = {
    "initialized": False,
    "finalized": False,
    "world": None,
    "self": None,
    "mesh": None,
    "init_time": None,
}
_lock = threading.Lock()


def initialized() -> bool:
    return _global["initialized"]


def init(devices=None, axis_name: str = "world",
         distributed: bool | None = None) -> Communicator:
    """MPI_Init analog; returns COMM_WORLD.  Idempotent."""
    with _lock:
        if _global["initialized"]:
            return _global["world"]
        t0 = time.perf_counter()
        if distributed is None:
            distributed = bool(mca_var.get("rte_distributed_init", False))
        if distributed:
            mesh_mod.distributed_initialize()
        m = mesh_mod.world_mesh(axis_name=axis_name, devices=devices)
        world = Communicator(m, axis_name, name="MPI_COMM_WORLD")
        # COMM_SELF: every device its own group — the btl/self analog
        from ..comm.group import Group

        self_comm = Communicator(
            m, axis_name,
            partition=[Group([i]) for i in range(m.shape[axis_name])],
            name="MPI_COMM_SELF",
        )
        _global.update(
            initialized=True, finalized=False, world=world, self=self_comm,
            mesh=m, init_time=time.perf_counter() - t0,
        )
        spc.record("init_count", 1)
        mca_output.verbose(
            1, _stream, "initialized: %d devices, %.1fms",
            m.devices.size, _global["init_time"] * 1e3,
        )
    # hook interposition, bottom of init (ompi/mca/hook semantics) — outside
    # the lock so a hook may call back into the (idempotent) runtime API
    from ..hook import run_init_hooks

    run_init_hooks(world)
    return world


def world() -> Communicator:
    if not _global["initialized"]:
        raise errors.NotInitializedError()
    return _global["world"]


def comm_self() -> Communicator:
    if not _global["initialized"]:
        raise errors.NotInitializedError()
    return _global["self"]


def world_mesh():
    if not _global["initialized"]:
        raise errors.NotInitializedError()
    return _global["mesh"]


def finalize() -> None:
    """MPI_Finalize analog."""
    from ..hook import run_finalize_hooks

    run_finalize_hooks()
    with _lock:
        _global.update(
            initialized=False, finalized=True, world=None, self=None,
            mesh=None,
        )


def is_finalized() -> bool:
    return _global["finalized"]


# -- host plane (launcher-started multi-process jobs) ----------------------

_host = {"proc": None}
_host_lock = threading.Lock()


def host_init(timeout: float = 30.0):
    """Wire this process into a launcher-started host-plane universe.

    The PMIx-client side of ``zmpirun`` (``tools/mpirun.py``): reads the
    ``ZMPI_RANK/SIZE/COORD_HOST/COORD_PORT`` environment contract — the
    same one the C ABI shim's ``MPI_Init`` reads (``native/zompi_mpi.cpp``)
    — and performs the TcpProc modex, mirroring the reference's
    ``ompi_rte_init`` → PMIx_Init connect-to-local-prted step
    (``ompi_mpi_init.c:508``).  Idempotent; returns this process's
    :class:`~zhpe_ompi_tpu.pt2pt.tcp.TcpProc` endpoint (rank, size,
    send/recv, collectives).
    """
    import os

    with _host_lock:
        if _host["proc"] is not None:
            return _host["proc"]
        pmix_uri = os.environ.get("ZMPI_PMIX")
        try:
            rank = int(os.environ["ZMPI_RANK"])
            size = int(os.environ["ZMPI_SIZE"])
            if pmix_uri is None:
                chost = os.environ["ZMPI_COORD_HOST"]
                cport = int(os.environ["ZMPI_COORD_PORT"])
        except (KeyError, ValueError) as e:
            raise errors.NotInitializedError(
                f"host_init: bad ZMPI_* contract ({e}) — run under zmpirun "
                "(python -m zhpe_ompi_tpu.tools.mpirun) or export "
                "ZMPI_RANK/SIZE/COORD_HOST/COORD_PORT (or ZMPI_PMIX for "
                "a daemon-hosted job)"
            ) from None
        from ..pt2pt.tcp import TcpProc

        # ft=True is the daemon-hosted recovery contract (zprted floods
        # authoritative fault events that need a FailureState to land in)
        ft = os.environ.get("ZMPI_FT") == "1"
        t0 = time.perf_counter()
        if pmix_uri is not None:
            # PMIx-served wire-up (zprted hosts the store): ZMPI_PMIX is
            # "host:port/namespace"; a respawned replacement additionally
            # carries ZMPI_REJOIN=1 and re-modexes through the store
            if "/" not in pmix_uri or ":" not in pmix_uri.split("/")[0]:
                raise errors.NotInitializedError(
                    f"host_init: malformed ZMPI_PMIX {pmix_uri!r} — "
                    "expected host:port/namespace (zprted exports this)"
                )
            addr, ns = pmix_uri.rsplit("/", 1)
            rejoin_ranks = os.environ.get("ZMPI_REJOIN_RANKS", "")
            elastic_live = os.environ.get("ZMPI_ELASTIC_LIVE", "")
            proc = TcpProc(
                rank, size, pmix=addr, namespace=ns, timeout=timeout,
                ft=ft, rejoin=os.environ.get("ZMPI_REJOIN") == "1",
                rejoin_gen=int(os.environ.get("ZMPI_REJOIN_GEN", 0)),
                rejoin_ranks=[int(r) for r in rejoin_ranks.split(",")
                              if r],
                # elastic jobs: only the live slots started (the rest
                # wire up as pre-acknowledged departures a later grow
                # restores) — the DVM resize contract
                live_ranks=[int(r) for r in elastic_live.split(",")
                            if r] or None,
            )
            lifeline = os.environ.get("ZMPI_LIFELINE")
            if lifeline:
                _arm_lifeline(lifeline)
            # warm the ztune decision-table cache from the daemon's
            # store (coll/ztable.py; negative-cached, never raises):
            # every job launched after a sweep published its table
            # resolves the tuned decisions for ITS topology at init,
            # with zero re-sweeping — and the first collective pays
            # no fetch
            from ..coll import ztable

            ztable.prefetch()
        else:
            proc = TcpProc(
                rank, size, coordinator=(chost, cport), timeout=timeout,
                ft=ft,
                external_coordinator=os.environ.get(
                    "ZMPI_COORD_EXTERNAL") == "1",
            )
        _host["proc"] = proc
        spc.record("init_count", 1)
        mca_output.verbose(
            1, _stream, "host plane up: rank %d/%d in %.1fms", rank, size,
            (time.perf_counter() - t0) * 1e3,
        )
        return proc


def _arm_lifeline(address: str) -> None:
    """Park one connection on the host daemon's control port for this
    process's whole life (the ``ZMPI_LIFELINE`` contract): the daemon
    never replies, and the connection dying means the daemon died —
    a rank must not outlive the daemon that owns its store, its fault
    routing, and its exit accounting (the PRRTE local-procs-die-with-
    their-prted contract, made explicit).  Exit code 143 mirrors the
    SIGTERM teardown the daemon itself would have applied."""
    import os
    import socket
    import sys

    from ..pt2pt.tcp import _recv_frame, _send_frame
    from ..utils import dss

    host, port = address.rsplit(":", 1)
    try:
        sock = socket.create_connection((host, int(port)), 10.0)
        _send_frame(sock, dss.pack(["lifeline"]))
        sock.settimeout(None)
    except OSError:
        # the daemon is already gone: the modex above only succeeded
        # against a live store, so this is a teardown race — exit the
        # way the severed lifeline would have made us
        os._exit(143)

    def watch():
        try:
            while True:
                if _recv_frame(sock) is None:
                    break
        except OSError:
            pass
        try:
            sys.stderr.write(
                "zmpi: host daemon lifeline severed — exiting\n")
            sys.stderr.flush()
        except OSError:
            # stderr IS the daemon's IOF pipe: a dead daemon broke it
            # too, and the farewell must never outrank the exit
            pass
        os._exit(143)

    t = threading.Thread(target=watch, daemon=True,
                         name="zmpi-lifeline")
    t.start()
    _host["lifeline"] = (sock, t)


def host_world():
    """The TcpProc endpoint created by :func:`host_init` (or None)."""
    return _host["proc"]


def host_finalize() -> None:
    with _host_lock:
        proc, _host["proc"] = _host["proc"], None
        if proc is not None:
            proc.close()
