"""Dynamic process management over the wire plane — multi-process dpm.

The reference's dpm launches and connects REAL processes through PMIx
(``ompi/dpm/dpm.c:774`` spawns via PMIx_Spawn; connect/accept rendezvous
through published port names).  Round 3 makes this framework's dpm real
in the same sense:

- **ports** are live rendezvous sockets; their name is ``host:port``
  (the reference's port name is likewise a PMIx-routable address string).
- **connect/accept** bridge two *independent TcpProc groups* — possibly
  in different OS processes — by exchanging address books through the
  port and minting a bridge CID; data then flows directly between group
  members over lazily-established bridge connections
  (:meth:`~zhpe_ompi_tpu.pt2pt.tcp.TcpProc.bridge_send`).
- **spawn** forks genuine child processes (``multiprocessing``), wires
  them into their own TcpProc universe, and connects the two universes
  with an intercommunicator — the MPI_Comm_spawn shape: parent group ↔
  child group, children find the bridge via :func:`child_parent` (the
  MPI_Comm_get_parent analog).

Intercomm collectives come from
:class:`~zhpe_ompi_tpu.coll.inter.InterCollectives` — the same coll/inter
composition the thread-plane bridge uses.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import secrets
import socket
import threading
from typing import Any, Callable

from ..coll.inter import InterCollectives
from ..core import errors
from ..pt2pt.matching import ANY_SOURCE, ANY_TAG
from ..pt2pt.tcp import TcpProc, _recv_frame, _send_frame
from ..utils import dss

# Bridge CIDs live far above intra-group cids; random high bits make
# independent accepting groups collision-free without negotiation.
_BRIDGE_CID_BASE = 0x40000


def _new_bridge_cid() -> int:
    return _BRIDGE_CID_BASE + secrets.randbits(40)


class Port:
    """An open MPI port: a live rendezvous listener (MPI_Open_port)."""

    def __init__(self, host: str = "127.0.0.1"):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, 0))
        self._srv.listen(8)
        addr = self._srv.getsockname()
        self.name = f"{addr[0]}:{addr[1]}"

    def close(self) -> None:
        """MPI_Close_port."""
        try:
            self._srv.close()
        except OSError:
            pass


def open_port(host: str = "127.0.0.1") -> Port:
    """MPI_Open_port: mint a connectable rendezvous name."""
    return Port(host)


# -- name publishing (MPI_Publish_name / Lookup_name / Unpublish_name) ----
#
# The reference routes these through a PMIx server that outlasts any one
# rank (the separate ``ompi-server`` daemon).  Under zmpirun the launcher
# hosts that registry (ZMPI_NAMESERVER env, tools/mpirun.py); outside a
# launcher job there is no server and these raise.

def _name_server_request(req: list) -> Any:
    import os

    addr = os.environ.get("ZMPI_NAMESERVER")
    if not addr:
        raise errors.InternalError(
            "MPI name publishing needs a name server: run under zmpirun "
            "(which hosts one) or unset service names and exchange port "
            "names out of band"
        )
    host, port = addr.rsplit(":", 1)
    cli = socket.create_connection((host, int(port)), timeout=10.0)
    try:
        _send_frame(cli, dss.pack(req))
        [out] = dss.unpack(_recv_frame(cli))
        return out
    finally:
        cli.close()


def publish_name(service: str, port_name: str) -> None:
    """MPI_Publish_name: service -> port name, visible to every rank of
    the job (and to other jobs launched with the same name server)."""
    _name_server_request(["pub", service, port_name])


def lookup_name(service: str) -> str:
    """MPI_Lookup_name; raises if the service is not published."""
    out = _name_server_request(["look", service])
    if out is None:
        raise errors.ArgError(f"service {service!r} is not published")
    return out


def unpublish_name(service: str) -> None:
    """MPI_Unpublish_name; raises (MPI_ERR_SERVICE shape) when the
    service was never published — matching lookup_name."""
    if not _name_server_request(["unpub", service]):
        raise errors.ArgError(f"service {service!r} is not published")


class TcpIntercomm(InterCollectives):
    """Intercommunicator between two TcpProc groups (possibly in
    different OS processes).  MPI addressing: send/recv name ranks of the
    REMOTE group; the bridge cid isolates matching from in-group
    traffic."""

    def __init__(self, proc: TcpProc, remote_book: list[tuple[str, int]],
                 cid: int, info=None):
        from ..core import info as info_mod

        self._ctx = proc
        self._proc = proc
        self._remote_book = [tuple(a) for a in remote_book]
        self.cid = cid
        self.info = info_mod.coerce(info)

    @property
    def rank(self) -> int:
        return self._proc.rank

    @property
    def size(self) -> int:
        """Local group size."""
        return self._proc.size

    @property
    def remote_size(self) -> int:
        return len(self._remote_book)

    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        if not 0 <= dest < self.remote_size:
            raise errors.RankError(f"remote rank {dest} out of range")
        self._proc.bridge_send(
            obj, self.cid, dest, self._remote_book[dest], tag
        )

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             timeout: float | None = None) -> Any:
        return self._proc.recv(source, tag, cid=self.cid, timeout=timeout)

    def disconnect(self) -> None:
        """MPI_Comm_disconnect: quiesce (collective over the local
        group)."""
        self._proc.barrier()


def accept(port: Port | None, proc: TcpProc,
           timeout: float = 30.0) -> TcpIntercomm:
    """MPI_Comm_accept — collective over `proc`'s group; rank 0 owns the
    port (others pass None) and blocks until a connector arrives."""
    if proc.rank == 0:
        if port is None:
            raise errors.ArgError("accept: rank 0 must pass the open port")
        port._srv.settimeout(timeout)
        conn, _ = port._srv.accept()
        [remote_book] = dss.unpack(_recv_frame(conn))
        cid = _new_bridge_cid()
        _send_frame(conn, dss.pack([list(a) for a in proc.address_book],
                                   cid))
        conn.close()
        payload = (remote_book, cid)
    else:
        payload = None
    remote_book, cid = proc.bcast(payload, root=0)
    return TcpIntercomm(proc, remote_book, cid)


def connect(name: str, proc: TcpProc,
            timeout: float = 30.0) -> TcpIntercomm:
    """MPI_Comm_connect — collective over `proc`'s group; rank 0
    rendezvouses with the port owner."""
    if proc.rank == 0:
        host, port_no = name.rsplit(":", 1)
        cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        cli.settimeout(timeout)
        import time

        err = None
        for _ in range(200):  # the acceptor may not be listening yet
            try:
                cli.connect((host, int(port_no)))
                break
            except OSError as e:
                err = e
                time.sleep(0.05)
                cli.close()
                cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                cli.settimeout(timeout)
        else:
            raise errors.InternalError(
                f"connect: cannot reach port {name}: {err}"
            )
        _send_frame(cli, dss.pack([list(a) for a in proc.address_book]))
        [remote_book, cid] = dss.unpack(_recv_frame(cli))
        cli.close()
        payload = (remote_book, cid)
    else:
        payload = None
    remote_book, cid = proc.bcast(payload, root=0)
    return TcpIntercomm(proc, remote_book, cid)


# ---------------------------------------------------------------- spawn

def _free_port_addr(host: str = "127.0.0.1") -> tuple[str, int]:
    """Reserve an ephemeral port number for the child universe's modex
    coordinator (the launcher-assigns-the-PMIx-URI step)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    addr = s.getsockname()
    s.close()
    return addr


def _child_bootstrap(rank: int, n: int, coord_addr, parent_port: str,
                     target: Callable) -> None:
    """Entry point of a spawned child process: build the child universe,
    connect back to the parent's port, run the user main."""
    proc = TcpProc(rank, n, coordinator=tuple(coord_addr))
    try:
        parent = connect(parent_port, proc)
        target(proc, parent)
    finally:
        proc.close()


class SpawnHandle:
    """Owner of the spawned processes (the reference's children outlive
    the call under prte's supervision; here the parent supervises)."""

    def __init__(self, procs: list[mp.Process]):
        self._procs = procs

    def join(self, timeout: float = 60.0) -> None:
        """Wait for every child to exit; raises if any failed."""
        for p in self._procs:
            p.join(timeout)
            if p.is_alive():
                p.terminate()
                raise errors.InternalError("spawned child hung")
        bad = [p.exitcode for p in self._procs if p.exitcode != 0]
        if bad:
            raise errors.InternalError(
                f"spawned children exited nonzero: {bad}"
            )


def spawn(proc: TcpProc, target: Callable, n_children: int,
          timeout: float = 30.0, info=None, method: str = "spawn"
          ) -> tuple[TcpIntercomm, SpawnHandle]:
    """MPI_Comm_spawn over real processes — collective over the parent
    group.  Launches `n_children` OS processes running
    ``target(child_proc, parent_intercomm)``, wires them into their own
    TcpProc universe, and returns the parent↔child intercommunicator plus
    a supervision handle.

    ``method="spawn"`` (default) execs fresh interpreters — the same
    contract as the launcher (``tools/mpirun.py``) — so it is safe in a
    parent with an initialized JAX backend; the target must be a
    picklable module-level function.  ``method="fork"`` is opt-in for
    fork-safe callers that need closure targets: forking a multithreaded
    JAX process is a latent deadlock, so opting in warns."""
    if method == "fork":
        import warnings

        warnings.warn(
            "dpm_wire.spawn(method='fork') can deadlock children when the "
            "parent holds locks in background threads (an initialized JAX "
            "backend always does); prefer the default method='spawn'",
            RuntimeWarning, stacklevel=2,
        )
    ctx = mp.get_context(method)
    if proc.rank == 0:
        port = open_port()
        coord_addr = _free_port_addr()
        procs = [
            ctx.Process(
                target=_child_bootstrap,
                args=(r, n_children, coord_addr, port.name, target),
                daemon=True,
            )
            for r in range(n_children)
        ]
        for p in procs:
            p.start()
        handle = SpawnHandle(procs)
    else:
        port = None
        handle = SpawnHandle([])
    icomm = accept(port, proc, timeout=timeout)
    if port is not None:
        port.close()
    from ..core import info as info_mod

    icomm.info = info_mod.coerce(info)  # launch hints (PMIx_Spawn analog)
    return icomm, handle
