/* fileio_c.c — MPI-IO acceptance for the C ABI (round 4).
 *
 * The byte-view C file surface: collective open with CREATE, disjoint
 * per-rank write_at stripes, sync, cross-rank read_at verification,
 * individual-pointer read/write with seek/get_position, derived-type
 * file IO (a strided vector written as its packed image), get/set_size,
 * and DELETE_ON_CLOSE teardown.
 *
 * Usage: fileio_c <path>
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "zompi_mpi.h"

#define CHECK(cond, msg)                                      \
  do {                                                        \
    if (!(cond)) {                                            \
      fprintf(stderr, "FAIL rank %d: %s\n", rank, msg);       \
      return 1;                                               \
    }                                                         \
  } while (0)

int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  CHECK(argc > 1, "need a path argument");
  const char *path = argv[1];

  /* collective create + disjoint stripes */
  MPI_File fh;
  CHECK(MPI_File_open(MPI_COMM_WORLD, path,
                      MPI_MODE_CREATE | MPI_MODE_RDWR, MPI_INFO_NULL,
                      &fh) == MPI_SUCCESS, "open");
  double stripe[4];
  for (int i = 0; i < 4; i++) stripe[i] = rank * 10.0 + i;
  MPI_Status st;
  CHECK(MPI_File_write_at(fh, rank * 32, stripe, 4, MPI_DOUBLE, &st) ==
            MPI_SUCCESS, "write_at");
  int wn = -1;
  MPI_Get_count(&st, MPI_DOUBLE, &wn);
  CHECK(wn == 4, "write_at count");
  CHECK(MPI_File_sync(fh) == MPI_SUCCESS, "sync");  /* + barrier */

  /* read the RIGHT neighbor's stripe */
  int nbr = (rank + 1) % size;
  double peer[4];
  CHECK(MPI_File_read_at(fh, nbr * 32, peer, 4, MPI_DOUBLE, &st) ==
            MPI_SUCCESS, "read_at");
  for (int i = 0; i < 4; i++)
    CHECK(peer[i] == nbr * 10.0 + i, "neighbor stripe");

  /* size queries */
  MPI_Offset sz = -1;
  CHECK(MPI_File_get_size(fh, &sz) == MPI_SUCCESS && sz == 32 * size,
        "get_size");

  /* individual pointer: seek to own stripe, read through the pointer */
  CHECK(MPI_File_seek(fh, rank * 32, MPI_SEEK_SET) == MPI_SUCCESS,
        "seek");
  double mine2[2];
  CHECK(MPI_File_read(fh, mine2, 2, MPI_DOUBLE, &st) == MPI_SUCCESS,
        "read");
  MPI_Offset pos = -1;
  CHECK(MPI_File_get_position(fh, &pos) == MPI_SUCCESS &&
            pos == rank * 32 + 16, "get_position");
  CHECK(mine2[0] == rank * 10.0 && mine2[1] == rank * 10.0 + 1,
        "pointer read");
  /* everyone's size/pointer checks done before anyone extends the
   * file below (a slow rank must not observe a neighbor's later
   * write) */
  MPI_Barrier(MPI_COMM_WORLD);

  /* derived type through the file: every rank appends its column image
   * past the stripes (packed vector = 3 doubles) */
  MPI_Datatype col;
  MPI_Type_vector(3, 1, 2, MPI_DOUBLE, &col);
  MPI_Type_commit(&col);
  double mat[6];
  for (int i = 0; i < 6; i++) mat[i] = rank * 100.0 + i;
  MPI_Offset base = 32 * (MPI_Offset)size + rank * 24;
  CHECK(MPI_File_write_at(fh, base, mat, 1, col, &st) == MPI_SUCCESS,
        "vector write_at");
  CHECK(MPI_File_sync(fh) == MPI_SUCCESS, "sync 2");
  double flat[3];
  CHECK(MPI_File_read_at(fh, base, flat, 3, MPI_DOUBLE, &st) ==
            MPI_SUCCESS, "flat read of packed vector");
  CHECK(flat[0] == rank * 100.0 && flat[1] == rank * 100.0 + 2 &&
            flat[2] == rank * 100.0 + 4, "packed vector image");
  MPI_Type_free(&col);

  /* truncate collectively, verify */
  CHECK(MPI_File_set_size(fh, 32 * size) == MPI_SUCCESS, "set_size");
  CHECK(MPI_File_get_size(fh, &sz) == MPI_SUCCESS && sz == 32 * size,
        "size after truncate");

  CHECK(MPI_File_close(&fh) == MPI_SUCCESS && fh == MPI_FILE_NULL,
        "close");

  /* DELETE_ON_CLOSE on a scratch file */
  char scratch[1024];
  snprintf(scratch, sizeof scratch, "%s.scratch", path);
  MPI_File fh2;
  CHECK(MPI_File_open(MPI_COMM_WORLD, scratch,
                      MPI_MODE_CREATE | MPI_MODE_WRONLY |
                      MPI_MODE_DELETE_ON_CLOSE, MPI_INFO_NULL,
                      &fh2) == MPI_SUCCESS, "scratch open");
  CHECK(MPI_File_close(&fh2) == MPI_SUCCESS, "scratch close");
  MPI_File fh3;
  CHECK(MPI_File_open(MPI_COMM_WORLD, scratch, MPI_MODE_RDONLY,
                      MPI_INFO_NULL, &fh3) == MPI_ERR_NO_SUCH_FILE,
        "scratch deleted on close");

  MPI_Barrier(MPI_COMM_WORLD);
  printf("fileio_c rank %d/%d OK\n", rank, size);
  MPI_Finalize();
  return 0;
}
