/* subcomm_c.c — round-4 C ABI acceptance program (VERDICT item 3).
 *
 * Exercises the broadened mpi.h surface end to end:
 *   1. MPI_Comm_split of COMM_WORLD into odd/even sub-communicators and
 *      an allreduce inside each (comm_split.c:40 + allreduce.c:113 shape),
 *   2. MPI_Comm_dup + MPI_Comm_free,
 *   3. Isend/Irecv overlapped with local compute, completed by
 *      MPI_Test polling then MPI_Waitall (isend.c:46 semantics),
 *   4. MPI_Sendrecv ring shift,
 *   5. rooted collectives: Reduce, Gather, Scatter + Allgather/Alltoall,
 *   6. derived datatypes: MPI_Type_vector strided column send and
 *      MPI_Type_contiguous, committed and freed,
 *   7. logical/bitwise reduction ops (MPI_LAND, MPI_BXOR),
 *   8. MPI_Get_processor_name / MPI_Wtick.
 *
 * Every stage validates its result; any mismatch exits nonzero with a
 * message, so the harness only has to check the exit code and the final
 * OK line.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "zompi_mpi.h"

#define CHECK(cond, msg)                                        \
  do {                                                          \
    if (!(cond)) {                                              \
      fprintf(stderr, "FAIL rank %d: %s\n", world_rank, msg);   \
      return 1;                                                 \
    }                                                           \
  } while (0)

int main(int argc, char **argv) {
  int world_rank, world_size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &world_rank);
  MPI_Comm_size(MPI_COMM_WORLD, &world_size);

  /* 1. split odd/even; allreduce inside the sub-communicator */
  MPI_Comm sub;
  int color = world_rank % 2;
  CHECK(MPI_Comm_split(MPI_COMM_WORLD, color, world_rank, &sub) ==
            MPI_SUCCESS, "Comm_split");
  int sub_rank, sub_size;
  MPI_Comm_rank(sub, &sub_rank);
  MPI_Comm_size(sub, &sub_size);
  int expect_size = world_size / 2 + (color == 0 ? world_size % 2 : 0);
  CHECK(sub_size == expect_size, "sub size");
  long my = world_rank, total = -1;
  CHECK(MPI_Allreduce(&my, &total, 1, MPI_LONG, MPI_SUM, sub) ==
            MPI_SUCCESS, "sub allreduce");
  long want = 0;
  for (int r = color; r < world_size; r += 2) want += r;
  CHECK(total == want, "sub allreduce value");

  /* barrier on the sub-communicator too */
  CHECK(MPI_Barrier(sub) == MPI_SUCCESS, "sub barrier");

  /* 2. dup + free */
  MPI_Comm dup;
  CHECK(MPI_Comm_dup(sub, &dup) == MPI_SUCCESS, "Comm_dup");
  long total2 = -1;
  CHECK(MPI_Allreduce(&my, &total2, 1, MPI_LONG, MPI_SUM, dup) ==
            MPI_SUCCESS && total2 == want, "dup allreduce");
  CHECK(MPI_Comm_free(&dup) == MPI_SUCCESS && dup == MPI_COMM_NULL,
        "Comm_free");

  /* 3. nonblocking ring: Irecv posted first, Isend, local compute
   * overlaps, Test polls, Waitall completes */
  int next = (world_rank + 1) % world_size;
  int prev = (world_rank + world_size - 1) % world_size;
  double out[8], in[8];
  for (int i = 0; i < 8; i++) out[i] = world_rank * 100.0 + i;
  MPI_Request reqs[2];
  CHECK(MPI_Irecv(in, 8, MPI_DOUBLE, prev, 31, MPI_COMM_WORLD,
                  &reqs[0]) == MPI_SUCCESS, "Irecv");
  CHECK(MPI_Isend(out, 8, MPI_DOUBLE, next, 31, MPI_COMM_WORLD,
                  &reqs[1]) == MPI_SUCCESS, "Isend");
  /* the overlapped "compute" */
  double acc = 0.0;
  for (int i = 0; i < 100000; i++) acc += i * 1e-9;
  int flag = 0;
  CHECK(MPI_Test(&reqs[1], &flag, MPI_STATUS_IGNORE) == MPI_SUCCESS,
        "Test");
  MPI_Status sts[2];
  CHECK(MPI_Waitall(2, reqs, sts) == MPI_SUCCESS, "Waitall");
  CHECK(reqs[0] == MPI_REQUEST_NULL && reqs[1] == MPI_REQUEST_NULL,
        "requests nulled");
  CHECK(sts[0].MPI_SOURCE == prev && sts[0].MPI_TAG == 31, "status");
  int got_n = -1;
  MPI_Get_count(&sts[0], MPI_DOUBLE, &got_n);
  CHECK(got_n == 8, "Get_count");
  for (int i = 0; i < 8; i++)
    CHECK(in[i] == prev * 100.0 + i, "ring payload");

  /* 4. Sendrecv shift the other way */
  long sv = world_rank * 7L, rv = -1;
  MPI_Status st;
  CHECK(MPI_Sendrecv(&sv, 1, MPI_LONG, prev, 32, &rv, 1, MPI_LONG, next,
                     32, MPI_COMM_WORLD, &st) == MPI_SUCCESS, "Sendrecv");
  CHECK(rv == next * 7L, "Sendrecv payload");

  /* 5. rooted collectives on WORLD */
  int root = world_size - 1;
  long red = -1;
  CHECK(MPI_Reduce(&my, &red, 1, MPI_LONG, MPI_SUM, root,
                   MPI_COMM_WORLD) == MPI_SUCCESS, "Reduce");
  if (world_rank == root) {
    long all = (long)world_size * (world_size - 1) / 2;
    CHECK(red == all, "Reduce value");
  }
  int *gath = malloc(sizeof(int) * world_size);
  int mine_i = world_rank + 1000;
  CHECK(MPI_Gather(&mine_i, 1, MPI_INT, gath, 1, MPI_INT, 0,
                   MPI_COMM_WORLD) == MPI_SUCCESS, "Gather");
  if (world_rank == 0)
    for (int r = 0; r < world_size; r++)
      CHECK(gath[r] == r + 1000, "Gather value");
  int *scat = malloc(sizeof(int) * world_size);
  for (int r = 0; r < world_size; r++) scat[r] = r * 3;
  int pick = -1;
  CHECK(MPI_Scatter(scat, 1, MPI_INT, &pick, 1, MPI_INT, 0,
                    MPI_COMM_WORLD) == MPI_SUCCESS, "Scatter");
  CHECK(pick == world_rank * 3, "Scatter value");
  int *ag = malloc(sizeof(int) * world_size);
  CHECK(MPI_Allgather(&mine_i, 1, MPI_INT, ag, 1, MPI_INT,
                      MPI_COMM_WORLD) == MPI_SUCCESS, "Allgather");
  for (int r = 0; r < world_size; r++)
    CHECK(ag[r] == r + 1000, "Allgather value");
  int *a2a_s = malloc(sizeof(int) * world_size);
  int *a2a_r = malloc(sizeof(int) * world_size);
  for (int r = 0; r < world_size; r++)
    a2a_s[r] = world_rank * 100 + r;
  CHECK(MPI_Alltoall(a2a_s, 1, MPI_INT, a2a_r, 1, MPI_INT,
                     MPI_COMM_WORLD) == MPI_SUCCESS, "Alltoall");
  for (int r = 0; r < world_size; r++)
    CHECK(a2a_r[r] == r * 100 + world_rank, "Alltoall value");

  /* 6. derived datatypes: vector = one column of a 4x4 row-major
   * matrix; the receiver takes it as 4 contiguous doubles */
  MPI_Datatype col, quad;
  CHECK(MPI_Type_vector(4, 1, 4, MPI_DOUBLE, &col) == MPI_SUCCESS &&
            MPI_Type_commit(&col) == MPI_SUCCESS, "Type_vector");
  CHECK(MPI_Type_contiguous(4, MPI_DOUBLE, &quad) == MPI_SUCCESS &&
            MPI_Type_commit(&quad) == MPI_SUCCESS, "Type_contiguous");
  int tsize = -1;
  CHECK(MPI_Type_size(col, &tsize) == MPI_SUCCESS && tsize == 32,
        "Type_size");
  if (world_rank == 0) {
    double m[16];
    for (int i = 0; i < 16; i++) m[i] = i;
    /* send column 1: elements 1, 5, 9, 13 */
    CHECK(MPI_Send(m + 1, 1, col, 1 % world_size, 41, MPI_COMM_WORLD) ==
              MPI_SUCCESS, "vector send");
  }
  if (world_rank == 1 % world_size) {
    double colv[4];
    CHECK(MPI_Recv(colv, 1, quad, 0, 41, MPI_COMM_WORLD, &st) ==
              MPI_SUCCESS, "vector recv");
    int cn = -1;
    MPI_Get_count(&st, MPI_DOUBLE, &cn);
    CHECK(cn == 4, "vector count");
    CHECK(colv[0] == 1 && colv[1] == 5 && colv[2] == 9 && colv[3] == 13,
          "vector payload");
    /* and receive INTO a strided layout: scatter the quad back out */
    double back[16];
    memset(back, 0, sizeof back);
    if (world_size > 1) {
      CHECK(MPI_Send(colv, 1, quad, 0, 42, MPI_COMM_WORLD) ==
                MPI_SUCCESS, "quad send");
    } else {
      CHECK(MPI_Send(colv, 1, quad, 0, 42, MPI_COMM_WORLD) ==
                MPI_SUCCESS, "quad send self");
    }
    (void)back;
  }
  if (world_rank == 0) {
    double back[16];
    memset(back, 0, sizeof back);
    CHECK(MPI_Recv(back + 1, 1, col, 1 % world_size, 42, MPI_COMM_WORLD,
                   &st) == MPI_SUCCESS, "strided recv");
    CHECK(back[1] == 1 && back[5] == 5 && back[9] == 9 && back[13] == 13,
          "strided recv payload");
    CHECK(back[0] == 0 && back[2] == 0, "strided recv gaps untouched");
  }
  CHECK(MPI_Type_free(&col) == MPI_SUCCESS &&
            col == MPI_DATATYPE_NULL, "Type_free");
  MPI_Type_free(&quad);

  /* 7. logical/bitwise ops */
  int lv = world_rank == 0 ? 1 : 1, land = -1;
  CHECK(MPI_Allreduce(&lv, &land, 1, MPI_INT, MPI_LAND,
                      MPI_COMM_WORLD) == MPI_SUCCESS && land == 1,
        "LAND");
  unsigned xv = 1u << (world_rank % 8), bx = 0;
  CHECK(MPI_Allreduce(&xv, &bx, 1, MPI_UNSIGNED, MPI_BXOR,
                      MPI_COMM_WORLD) == MPI_SUCCESS, "BXOR");
  unsigned want_bx = 0;
  for (int r = 0; r < world_size; r++) want_bx ^= 1u << (r % 8);
  CHECK(bx == want_bx, "BXOR value");

  /* 8. identity queries */
  char pname[MPI_MAX_PROCESSOR_NAME];
  int plen = -1;
  CHECK(MPI_Get_processor_name(pname, &plen) == MPI_SUCCESS && plen > 0,
        "Get_processor_name");
  CHECK(MPI_Wtick() > 0.0 && MPI_Wtick() < 1.0, "Wtick");

  MPI_Comm_free(&sub);
  MPI_Barrier(MPI_COMM_WORLD);
  printf("subcomm_c rank %d/%d OK (acc=%.3f host=%s)\n", world_rank,
         world_size, acc, pname);
  free(gath); free(scat); free(ag); free(a2a_s); free(a2a_r);
  MPI_Finalize();
  return 0;
}
