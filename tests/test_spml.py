"""spml framework: MCA-selected SHMEM transport (oshmem/mca/spml analog).

Selection is a priority decision over components whose preconditions the
endpoint meets: direct (thread ranks) > mmap (same-host wire procs) >
am (any wire).  ZMPI_MCA_spml include/exclude must steer it like every
other framework.
"""

import numpy as np
import pytest

from test_tcp import run_tcp
from zhpe_ompi_tpu.mca import var as mca_var
from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse
from zhpe_ompi_tpu.shmem import spml
from zhpe_ompi_tpu.shmem.api import _AmBackend, _DirectBackend
from zhpe_ompi_tpu.shmem.segment import MmapBackend


def test_selects_direct_for_thread_ranks():
    uni = LocalUniverse(2)
    comp = spml.select_spml(uni.contexts[0])
    assert comp.name == "direct"


def test_selects_mmap_for_samehost_wire():
    def prog(p):
        return spml.select_spml(p).name

    assert run_tcp(2, prog) == ["mmap", "mmap"]


def test_exclude_steers_to_am():
    mca_var.set_var("spml", "^mmap")
    try:
        def prog(p):
            return spml.select_spml(p).name

        assert run_tcp(2, prog) == ["am", "am"]
    finally:
        mca_var.unset("spml")


def test_pe_construction_roundtrip_each_component():
    # direct
    uni = LocalUniverse(2)

    def direct_prog(ctx):
        pe = spml.shmem_pe(ctx, 1 << 14)
        assert isinstance(pe._backend, _DirectBackend)
        sym = pe.shmalloc(2, np.int32)
        pe.local(sym)[...] = ctx.rank
        pe.barrier_all()
        got = pe.get(sym, 1 - ctx.rank).tolist()
        pe.barrier_all()
        return got

    res = uni.run(direct_prog)
    assert res == [[1, 1], [0, 0]]

    # mmap via auto-selection over wire ranks
    def wire_prog(p):
        pe = spml.shmem_pe(p, 1 << 14)
        assert isinstance(pe._backend, MmapBackend)
        sym = pe.shmalloc(1, np.int64)
        pe.local(sym)[...] = 10 + p.rank
        pe.barrier_all()
        got = int(pe.g(sym, 1 - p.rank))
        pe.barrier_all()
        pe.finalize()
        return got

    assert run_tcp(2, wire_prog) == [11, 10]


def test_no_candidate_raises():
    from zhpe_ompi_tpu.core import errors

    class FakeEp:
        rank, size = 0, 1

    with pytest.raises(errors.InternalError):
        spml.select_spml(FakeEp())
