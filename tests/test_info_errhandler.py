"""Info objects, attachable errhandlers, generalized requests —
VERDICT round-2 item 9 (reference: ompi/info/info.h:41,
ompi/errhandler/errhandler.h:94-136, ompi/request/grequest.h:29-61)."""

import threading

import numpy as np
import pytest

import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu.core import errhandler, errors
from zhpe_ompi_tpu.core import info as info_mod
from zhpe_ompi_tpu.pt2pt.requests import GeneralizedRequest
from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse


class TestInfo:
    def test_set_get_delete_nkeys(self):
        info = info_mod.Info()
        info.set("coll_tuned_priority", 30)
        info.set("no_locks", "true")
        assert info.get("coll_tuned_priority") == "30"
        assert info.get_bool("no_locks") is True
        assert info.get("absent") is None
        assert info.get("absent", "dflt") == "dflt"
        assert info.nkeys() == 2
        assert info.nthkey(0) == "coll_tuned_priority"
        info.delete("no_locks")
        assert info.nkeys() == 1
        with pytest.raises(errors.KeyvalError):
            info.delete("no_locks")  # MPI: deleting unset key errors

    def test_dup_is_independent(self):
        a = info_mod.Info({"k": "v"})
        b = a.dup()
        b.set("k", "w")
        assert a.get("k") == "v" and b.get("k") == "w"

    def test_coerce(self):
        assert info_mod.coerce(None) is info_mod.NULL
        info = info_mod.coerce({"a": 1})
        assert info.get("a") == "1"
        with pytest.raises(errors.ArgError):
            info_mod.coerce(42)

    def test_env_info(self):
        env = info_mod.create_env()
        assert env.get("arch") is not None

    def test_key_bounds(self):
        info = info_mod.Info()
        with pytest.raises(errors.ArgError):
            info.set("", "x")
        with pytest.raises(errors.ArgError):
            info.set("k" * 300, "x")

    def test_comm_carries_info(self):
        world = zmpi.init()
        comm = zmpi.Communicator(
            world.mesh, world.axis, info={"mpi_assert_no_any_tag": "true"}
        )
        assert comm.info.get_bool("mpi_assert_no_any_tag")
        comm.set_info({"x": "y"})
        assert comm.info.get("x") == "y"

    def test_window_no_locks_assertion(self):
        from zhpe_ompi_tpu.osc.window import HostWindow

        uni = LocalUniverse(2)

        def main(ctx):
            win = HostWindow.create(
                ctx, np.zeros(2, np.float32), info={"no_locks": "true"}
            )
            win.fence()
            err = None
            try:
                win.lock(0)
            except errors.MpiError as e:
                err = str(e)
            win.fence()
            win.free()
            return err

        res = uni.run(main)
        assert all("no_locks" in r for r in res)

    def test_file_accepts_info(self, tmp_path):
        from zhpe_ompi_tpu.io.file import MODE_CREATE, MODE_WRONLY, File

        f = File(None, str(tmp_path / "x.bin"),
                 MODE_CREATE | MODE_WRONLY,
                 info={"striping_factor": "4"})
        assert f.info.get("striping_factor") == "4"
        f.close()

    def test_spawn_accepts_info(self):
        from zhpe_ompi_tpu.comm import dpm

        uni = LocalUniverse(2)

        def child_main(ctx):
            return ctx.rank

        def main(ctx):
            ic, handle = dpm.spawn(uni, ctx, child_main, 2,
                                   info={"host": "localhost"})
            hint = ic.info.get("host")
            if ctx.rank == 0:
                handle.join()
            return hint

        assert uni.run(main) == ["localhost", "localhost"]


class TestErrhandler:
    def _bad_call(self, comm):
        # a collective dispatch failure: unknown op name
        return comm._coll_call("definitely_not_an_op")

    def test_default_is_fatal(self):
        world = zmpi.init()
        comm = zmpi.Communicator(world.mesh, world.axis)
        with pytest.raises(errhandler.JobAbort) as ei:
            self._bad_call(comm)
        assert ei.value.errclass == errors.ERR_UNSUPPORTED

    def test_errors_return(self):
        world = zmpi.init()
        comm = zmpi.Communicator(world.mesh, world.axis)
        comm.set_errhandler(errhandler.ERRORS_RETURN)
        with pytest.raises(errors.UnsupportedError):
            self._bad_call(comm)  # typed error reaches the caller

    def test_user_handler_recovers(self):
        world = zmpi.init()
        comm = zmpi.Communicator(world.mesh, world.axis)
        seen = []

        def handler(obj, exc):
            seen.append((obj.name, exc.errclass))
            return "recovered"

        comm.set_errhandler(errhandler.create(handler))
        assert self._bad_call(comm) == "recovered"
        assert seen == [(comm.name, errors.ERR_UNSUPPORTED)]

    def test_call_errhandler_directly(self):
        world = zmpi.init()
        comm = zmpi.Communicator(world.mesh, world.axis)
        comm.set_errhandler(errhandler.ERRORS_RETURN)
        with pytest.raises(errors.RankError):
            comm.call_errhandler(errors.RankError("user-detected"))

    def test_window_default_is_return(self):
        from zhpe_ompi_tpu.osc.window import HostWindow

        uni = LocalUniverse(2)

        def main(ctx):
            win = HostWindow.create(ctx, np.zeros(2, np.float32))
            name = win.get_errhandler().name
            win.fence()
            win.free()
            return name

        assert uni.run(main) == ["MPI_ERRORS_RETURN"] * 2

    def test_jobabort_not_catchable_as_mpierror(self):
        with pytest.raises(BaseException) as ei:
            try:
                raise errhandler.JobAbort("c", errors.RankError("x"))
            except errors.MpiError:  # must NOT catch the abort
                pytest.fail("JobAbort was caught as MpiError")
        assert isinstance(ei.value, errhandler.JobAbort)


class TestGeneralizedRequest:
    def test_complete_then_wait(self):
        events = []
        req = GeneralizedRequest.start(
            query_fn=lambda extra, status: events.append(("query", extra)),
            free_fn=lambda extra: events.append(("free", extra)),
            extra_state="st",
        )
        flag, _ = req.test()
        assert not flag
        req.complete("the-result")
        assert req.wait() == "the-result"
        assert events == [("query", "st"), ("free", "st")]

    def test_driver_thread_completion(self):
        """The user's async operation completes the request from another
        thread; wait() unblocks (the grequest use-case)."""
        req = GeneralizedRequest.start()

        def driver():
            req.complete(42)

        t = threading.Thread(target=driver)
        t.start()
        assert req.wait(timeout=5.0) == 42
        t.join()

    def test_cancel_callback(self):
        cancels = []

        def cancel_fn(extra, completed):
            cancels.append(completed)
            return True

        req = GeneralizedRequest.start(cancel_fn=cancel_fn)
        assert req.cancel() is True
        assert req.status.cancelled
        assert cancels == [False]

    def test_query_runs_once(self):
        calls = []
        req = GeneralizedRequest.start(
            query_fn=lambda extra, status: calls.append(1)
        )
        req.complete()
        req.test()
        req.test()
        req.wait()
        assert len(calls) == 1
