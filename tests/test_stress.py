"""Concurrency soak: overlapping nonblocking collectives, RMA, and
pt2pt traffic over real sockets — the schedule-interleaving torture the
per-instance tag discipline exists for."""

import numpy as np
import pytest

from test_tcp import run_tcp
from zhpe_ompi_tpu import ops as zops

N = 4
ROUNDS = 12


class TestOverlapSoak:
    def test_overlapping_nonblocking_collectives(self):
        def prog(p):
            rng = np.random.default_rng(100 + p.rank)
            for it in range(ROUNDS):
                a = p.iallreduce(float(p.rank + it), zops.SUM)
                b = p.iallgather((p.rank, it))
                c = p.ibcast(f"r{it}" if p.rank == it % N else None,
                             root=it % N)
                d = p.ialltoall([(p.rank, dst, it) for dst in range(N)])
                # complete intentionally out of issue order
                got_d = d.wait()
                got_b = b.wait()
                got_a = a.wait()
                got_c = c.wait()
                assert got_a == sum(r + it for r in range(N))
                assert got_b == [(r, it) for r in range(N)]
                assert got_c == f"r{it}"
                assert got_d == [(src, p.rank, it) for src in range(N)]
            return True

        assert run_tcp(N, prog, timeout=120.0) == [True] * N

    def test_collectives_interleaved_with_pt2pt_and_rma(self):
        from zhpe_ompi_tpu.osc.am import AmWindow

        def prog(p):
            win = AmWindow.create(p, np.zeros(N, np.float64))
            for it in range(ROUNDS):
                req = p.iallreduce(1, zops.SUM)
                # pt2pt ring exchange while the collective is in flight
                nxt, prv = (p.rank + 1) % N, (p.rank - 1) % N
                p.send((p.rank, it), nxt, tag=0x600 + it)
                got = p.recv(source=prv, tag=0x600 + it)
                assert got == (prv, it)
                # one-sided accumulate into the neighbor's window slot
                win.lock(nxt)
                win.accumulate(np.asarray([1.0]), nxt,
                               offset=p.rank, op=zops.SUM)
                win.unlock(nxt)
                assert req.wait() == N
            # unlock already completed every op at the target; one
            # barrier orders all ranks' epochs before the read-back
            p.barrier()
            local = win.local_buffer.tolist()
            win.free()
            return local

        res = run_tcp(N, prog, timeout=120.0)
        for r in range(N):
            # neighbor (r-1) accumulated ROUNDS ones into slot (r-1)
            want = [0.0] * N
            want[(r - 1) % N] = float(ROUNDS)
            assert res[r] == want, (r, res[r])


class TestAsyncIoSoak:
    def test_many_inflight_requests_then_drain(self, tmp_path):
        """Dozens of overlapping nonblocking reads/writes against one
        file, interleaved completions, then close() drains whatever is
        still in flight — the aio-queue soak (fbtl_posix sizes its
        queue for exactly this shape)."""
        import numpy as np

        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu import io as zio

        world = zmpi.init()
        p = str(tmp_path / "soak.bin")
        ROUNDS, SLOTS = 6, 16
        with zio.File(world, p, zio.MODE_CREATE | zio.MODE_RDWR) as f:
            for rnd in range(ROUNDS):
                wreqs = [
                    f.iwrite_at(s * 64,
                                np.full(64, (rnd * SLOTS + s) % 251,
                                        np.uint8))
                    for s in range(SLOTS)
                ]
                # wait in reverse order (completion order independence)
                for s in reversed(range(SLOTS)):
                    assert wreqs[s].wait(timeout=60) == 64
                rreqs = [f.iread_at(s * 64, 64) for s in range(SLOTS)]
                for s, rq in enumerate(rreqs):
                    got = rq.wait(timeout=60)
                    assert got[0] == (rnd * SLOTS + s) % 251, (rnd, s)
            # leave a few in flight for close() to drain
            tail = [f.iwrite_at(s * 64, np.full(64, 7, np.uint8))
                    for s in range(4)]
        # drained at close: file reflects the tail writes
        data = np.fromfile(p, np.uint8)
        for s in range(4):
            assert data[s * 64] == 7
        assert all(t.done for t in tail)

    def test_wire_collective_io_interleaved_with_pt2pt(self, tmp_path):
        """Nonblocking collective IO overlapping user pt2pt on the SAME
        endpoint: the reserved tag windows must keep them separate."""
        import numpy as np

        from test_tcp import run_tcp
        from zhpe_ompi_tpu.io.file import MODE_CREATE, MODE_RDWR
        from zhpe_ompi_tpu.io.wirefile import WireFile
        from zhpe_ompi_tpu.datatype import INT32_T, create_resized, \
            create_vector

        path = str(tmp_path / "mix.bin")
        N = 4

        def prog(p):
            with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                ft = create_resized(create_vector(1, 1, 1, INT32_T),
                                    0, 4 * N)
                f.set_view(4 * p.rank, INT32_T, ft)
                for rnd in range(4):
                    data = np.arange(4, dtype=np.int32) + 100 * p.rank \
                        + rnd
                    wreq = f.iwrite_all(data)
                    # pt2pt chatter on the same endpoint while the
                    # collective body runs on the worker
                    p.send(("r", rnd, p.rank), dest=(p.rank + 1) % N,
                           tag=55 + rnd)
                    got = p.recv(source=(p.rank - 1) % N, tag=55 + rnd)
                    assert got == ("r", rnd, (p.rank - 1) % N)
                    assert wreq.wait(timeout=60) == 4
                    f.seek(0)
                    back = f.iread_all(4).wait(timeout=60)
                    assert back.tolist() == data.tolist(), (rnd, back)
                    f.seek(0)
            return True

        assert run_tcp(N, prog) == [True] * N


class TestZsoakSmoke:
    @pytest.mark.slow
    def test_three_cycle_storm_clean(self, tmp_path):
        """The fault-storm soak harness end to end, small: 3 seeded
        cycles of overlapping multi-tenant launch/kill/resize/recover
        on a real daemon tree must finish with ZERO invariant
        violations (rc 1 and a replay hint otherwise)."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        res = subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.zsoak",
             "--cycles", "3", "--seed", "3",
             "--workdir", str(tmp_path)],
            env=env, capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, (res.stdout, res.stderr)
        assert "violations=0" in res.stdout, res.stdout
