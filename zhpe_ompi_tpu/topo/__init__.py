"""Topo framework — process topologies, TPU-native.

Re-design of ``ompi/mca/topo`` (interface ``ompi/mca/topo/topo.h:296-343``,
base implementations ``ompi/mca/topo/base/topo_base_cart_create.c`` et al.)
for the SPMD single-controller machine:

- A topology is a *static host-side description* attached to a communicator.
  Rank↔coordinate maps are numpy tables baked into the compiled program, not
  per-process state — XLA sees only static permutation patterns.
- ``MPI_Cart_shift`` + sendrecv collapses into one ``ppermute`` with a
  uniform shift pattern; neighbor collectives compile to a short sequence of
  ``ppermute`` rounds (one per cart direction, or per color class of a greedy
  edge coloring for general graphs) instead of per-edge send/recv.
- On TPU the cartesian grid of devices IS the physical ICI torus, so
  ``reorder=True`` for cartesian topologies is the identity (the reference's
  ``cart_map``/``treematch`` exist because MPI ranks land on arbitrary
  cluster nodes; JAX device order already encodes ICI adjacency).  For
  distributed graphs we still provide a treematch-style greedy traffic
  reorder (``graph.reorder_greedy``,
  cf. ``ompi/mca/topo/treematch/topo_treematch_dist_graph_create.c``).
"""

from __future__ import annotations

from .cart import CartTopology, dims_create
from .graph import DistGraphTopology, GraphTopology, reorder_greedy
from .neighbor import (
    neighbor_allgather,
    neighbor_alltoall,
)

__all__ = [
    "CartTopology",
    "GraphTopology",
    "DistGraphTopology",
    "dims_create",
    "reorder_greedy",
    "neighbor_allgather",
    "neighbor_alltoall",
]
