"""Capture the per-algorithm collective baselines (VERDICT round-2 item 4).

Sweeps every tuned algorithm of the four headline collectives over the
OSU size ladder on the 8-virtual-CPU loopback mesh (the btl/self+sm
analog), plus the host-plane ping-pong, and writes the artifact
``benchmarks/baseline_cpu8.json`` that BASELINE.md cites.  The measured
crossovers set the tuned thresholds' defaults (provenance comments in
coll/tuned.py point back here).

Run (CPU-pinned so the sweep never rides a TPU tunnel):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/capture_baseline.py
"""

import json
import os
import platform
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the sweep's per-algorithm matrix: every tuned table entry that runs on
# the auto path or exists for forced selection
SWEEPS = {
    "allreduce": ["xla", "linear", "nonoverlapping", "recursive_doubling",
                  "ring", "segmented_ring", "rabenseifner"],
    "bcast": ["xla", "linear", "chain", "pipeline", "split_binary",
              "binary", "binomial", "knomial", "scatter_allgather"],
    "allgather": ["xla", "linear", "bruck", "recursive_doubling", "ring",
                  "neighbor_exchange"],
    "alltoall": ["xla", "linear", "pairwise", "bruck", "linear_sync"],
}

SMALL_MAX = 4 << 20    # per-algorithm ladder: 4B .. 4MB (x16 steps)
LARGE_MAX = 64 << 20   # crossover ladder for the allreduce contenders


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from benchmarks.osu_zmpi import _sizes, bench_collective, bench_pt2pt

    n_dev = len(jax.devices())
    rows = []
    for opname, algs in SWEEPS.items():
        for algname in algs:
            print(f"sweep {opname}/{algname} ...", flush=True)
            rows += bench_collective(
                opname, algname, max_size=SMALL_MAX, iters=10
            )
    # fine ladder for the auto-path contenders at large sizes
    for algname in ("recursive_doubling", "ring", "rabenseifner"):
        print(f"sweep allreduce/{algname} large ...", flush=True)
        rows += [
            dict(r, ladder="large")
            for r in bench_collective(
                "allreduce", algname, max_size=LARGE_MAX, iters=5
            )
        ]
    print("sweep pt2pt ...", flush=True)
    rows += bench_pt2pt(max_size=SMALL_MAX, iters=30)

    artifact = {
        "host": platform.node(),
        "platform": "cpu-loopback",
        "n_devices": n_dev,
        "rows": rows,
    }
    out = os.path.join(REPO, "benchmarks", "baseline_cpu8.json")
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1)
    print(f"wrote {out} ({len(rows)} rows)")

    # crossover report: for each op/size, which algorithm won
    by_size: dict = {}
    for r in rows:
        if r.get("ladder") or r["op"] == "pt2pt_pingpong":
            continue
        key = (r["op"], r["bytes"])
        if key not in by_size or r["latency_us"] < by_size[key][1]:
            by_size[key] = (r["algorithm"], r["latency_us"])
    for (op, nbytes), (algname, lat) in sorted(by_size.items()):
        print(f"best {op:>10} @{nbytes:>9}B: {algname:<20} {lat:9.1f} us")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
