"""Dynamic process management tests (reference: ompi/dpm, exercised by
test/simple/{concurrent_spawn,intercomm_create}.c and
MPI_Comm_connect/accept examples)."""

import numpy as np
import pytest

from zhpe_ompi_tpu.comm import dpm
from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse


class TestSpawn:
    def test_spawn_and_pingpong(self):
        """Parent universe spawns children; each parent rank sends to its
        mirror child over the intercomm, children reply via get_parent."""
        parent = LocalUniverse(2)

        def child_main(cctx):
            up = dpm.get_parent(cctx)
            assert up is not None
            assert up.remote_size == 2
            val = up.recv(source=cctx.rank, tag=5)
            up.send(val * 10, dest=cctx.rank, tag=6)
            return val

        def parent_main(ctx):
            inter, handle = dpm.spawn(parent, ctx, child_main, 2)
            assert inter.remote_size == 2
            inter.send(ctx.rank + 1, dest=ctx.rank, tag=5)
            echoed = inter.recv(source=ctx.rank, tag=6)
            if ctx.rank == 0:
                kids = handle.join()
                assert kids == [1, 2]
            return echoed

        results = parent.run(parent_main)
        assert results == [10, 20]

    def test_get_parent_none_for_root(self):
        uni = LocalUniverse(1)
        assert dpm.get_parent(uni.contexts[0]) is None

    def test_spawn_child_failure_surfaces_in_join(self):
        parent = LocalUniverse(1)

        def child_main(cctx):
            raise RuntimeError("child exploded")

        def parent_main(ctx):
            _, handle = dpm.spawn(parent, ctx, child_main, 2)
            with pytest.raises(RuntimeError, match="child exploded"):
                handle.join()
            return True

        assert parent.run(parent_main) == [True]


class TestConnectAccept:
    def test_connect_accept_bridge(self):
        """Two independent universes rendezvous on a port (the
        MPI_Open_port / MPI_Comm_accept / MPI_Comm_connect triple)."""
        server = LocalUniverse(2)
        client = LocalUniverse(3)
        port = dpm.open_port()
        out = {}

        import threading

        def server_side():
            def main(ctx):
                inter = dpm.accept(port, server, ctx)
                assert inter.remote_size == 3
                if ctx.rank == 0:
                    # gather one value from every client rank
                    vals = sorted(
                        inter.recv(tag=9) for _ in range(inter.remote_size)
                    )
                    return vals
                return None

            out["server"] = server.run(main)

        def client_side():
            def main(ctx):
                inter = dpm.connect(port, client, ctx)
                assert inter.remote_size == 2
                inter.send(100 + ctx.rank, dest=0, tag=9)
                return True

            out["client"] = client.run(main)

        ts = threading.Thread(target=server_side)
        tc = threading.Thread(target=client_side)
        ts.start()
        tc.start()
        ts.join(30)
        tc.join(30)
        dpm.close_port(port)
        assert out["server"][0] == [100, 101, 102]
        assert out["client"] == [True, True, True]

    def test_unknown_port(self):
        uni = LocalUniverse(1)

        def main(ctx):
            with pytest.raises(errors.ArgError):
                dpm.connect("no-such-port", uni, ctx)
            return True

        assert uni.run(main) == [True]

    def test_intercomm_barrier(self):
        a = LocalUniverse(2)
        b = LocalUniverse(2)
        port = dpm.open_port()
        import threading

        res = {}

        def side(uni, fn_name, key):
            def main(ctx):
                inter = getattr(dpm, fn_name)(port, uni, ctx)
                inter.barrier()
                inter.disconnect()
                return True

            res[key] = uni.run(main)

        t1 = threading.Thread(target=side, args=(a, "accept", "a"))
        t2 = threading.Thread(target=side, args=(b, "connect", "b"))
        t1.start(); t2.start()
        t1.join(30); t2.join(30)
        dpm.close_port(port)
        assert res["a"] == [True, True] and res["b"] == [True, True]
