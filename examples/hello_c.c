/* hello_c.c — the reference's examples/hello_c.c acceptance shape:
 * init, identity, version string, finalize. */
#include <stdio.h>
#include "zompi_mpi.h"

int main(int argc, char **argv) {
  int rank, size, len;
  char version[MPI_MAX_LIBRARY_VERSION_STRING];
  MPI_Init(&argc, &argv);
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  MPI_Get_library_version(version, &len);
  printf("Hello, world, I am %d of %d, (%s, %d)\n", rank, size, version,
         len);
  MPI_Finalize();
  return 0;
}
