"""bench.py supervisor plumbing — the probe deadline path (BENCH_r05:
five 240 s probe hangs produced an error record instead of a number).
Fast: every case uses a stub probe source, never a real backend."""

import json
import time

import bench


def _watchdog_prelude() -> str:
    """The watchdog must be armed before the jax import — that
    ordering IS the deadline guarantee for a wedged jax.devices().
    It now lives in utils/deadline: run_probe prepends
    watchdog_preamble() to every child, so the ASSEMBLED bench probe
    is checked here (one probe idiom, one place the guarantee holds)."""
    from zhpe_ompi_tpu.utils import deadline

    assembled = deadline.watchdog_preamble() + bench._PROBE_SRC
    head, sep, _ = assembled.partition("import jax")
    assert sep, "_PROBE_SRC no longer imports jax?"
    assert "threading.Thread" in head, (
        "the probe watchdog must start BEFORE the jax import — a hang "
        "inside jax.devices() is exactly what it exists to kill"
    )
    return ""  # run_probe arms the preamble itself; callers pass bodies


class TestProbeDeadline:
    def test_hung_probe_dies_on_internal_deadline(self):
        """A probe that wedges after arming the watchdog exits by
        itself, well inside the outer subprocess timeout."""
        src = _watchdog_prelude() + "import time as _t\n_t.sleep(60)\n"
        t0 = time.perf_counter()
        kind, detail = bench._run_probe(timeout_s=30.0, deadline_s=0.5,
                                        src=src)
        elapsed = time.perf_counter() - t0
        assert kind == "deadline"
        assert "internal deadline" in detail
        assert elapsed < 10.0, (
            f"deadline probe took {elapsed:.1f}s — the internal "
            "watchdog did not fire"
        )

    def test_outer_timeout_still_backstops(self):
        """A probe that hangs with the watchdog DISABLED (deadline 0)
        is killed by the outer subprocess timeout — the backstop the
        internal deadline rides inside."""
        src = _watchdog_prelude() + "import time as _t\n_t.sleep(60)\n"
        kind, detail = bench._run_probe(timeout_s=1.0, deadline_s=0.0,
                                        src=src)
        assert kind == "hung"
        assert "hung" in detail

    def test_healthy_probe_reports_devices(self):
        src = ("import json\n"
               "print(json.dumps({'n': 1, 'platform': 'stub'}))\n")
        kind, detail = bench._run_probe(timeout_s=30.0, deadline_s=30.0,
                                         src=src)
        assert kind == "ok"
        assert json.loads(detail) == {"n": 1, "platform": "stub"}

    def test_failing_probe_reports_rc_and_stderr(self):
        src = "import sys\nsys.stderr.write('boom')\nsys.exit(7)\n"
        kind, detail = bench._run_probe(timeout_s=30.0, deadline_s=30.0,
                                         src=src)
        assert kind == "error"
        assert "rc=7" in detail and "boom" in detail

    def test_error_with_deadline_word_is_not_a_hang(self):
        """A fast FAILURE whose stderr happens to say DEADLINE_EXCEEDED
        (a common transient accelerator status) must classify as an
        ordinary error — the retry ladder rides errors out with
        backoff, and only true hangs cut it short."""
        src = ("import sys\n"
               "sys.stderr.write('DEADLINE_EXCEEDED: tpu busy')\n"
               "sys.exit(1)\n")
        kind, detail = bench._run_probe(timeout_s=30.0, deadline_s=30.0,
                                        src=src)
        assert kind == "error"


class TestCpuFallback:
    def test_fallback_env_pins_cpu(self, monkeypatch):
        """The CPU-mesh fallback child must run with JAX_PLATFORMS=cpu
        even when the parent asked for an accelerator — the fallback
        exists because that accelerator just failed to probe."""
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        env = bench._cpu_env()
        assert env["JAX_PLATFORMS"] == "cpu"

    def test_fallback_env_preserves_everything_else(self, monkeypatch):
        monkeypatch.setenv("ZMPI_BENCH_SMOKE", "1")
        env = bench._cpu_env()
        assert env["ZMPI_BENCH_SMOKE"] == "1"
