"""Fault-tolerance tests: pessimistic message logging + replay
(vprotocol/pessimist analog) and bookmark quiescence (crcp/bkmrk analog)."""

import numpy as np
import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.ft import BookmarkCoordinator, UniverseLogger
from zhpe_ompi_tpu.pt2pt.matching import ANY_SOURCE
from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse

N = 4


def ring_program(ctx):
    """Each rank passes an accumulating token around the ring twice, plus
    an any-source gather at rank 0 — enough nondeterminism to make replay
    meaningful."""
    acc = ctx.rank
    for lap in range(2):
        if ctx.rank == 0:
            ctx.send(acc, dest=1, tag=lap)
            acc = ctx.recv(source=N - 1, tag=lap)
        else:
            got = ctx.recv(source=ctx.rank - 1, tag=lap)
            acc = acc + got
            ctx.send(acc, dest=(ctx.rank + 1) % N, tag=lap)
    # any-source phase: rank 0 collects one message from everyone
    if ctx.rank == 0:
        for _ in range(N - 1):
            acc += ctx.recv(source=ANY_SOURCE, tag=99)
    else:
        ctx.send(ctx.rank * 100, dest=0, tag=99)
    return acc


class TestVprotocol:
    def test_logged_run_matches_plain(self):
        plain = LocalUniverse(N).run(ring_program)
        logger = UniverseLogger(LocalUniverse(N))
        logged = logger.run_logged(ring_program)
        assert logged == plain

    def test_replay_reproduces_rank(self):
        """Restart each rank against the logs: identical result, no other
        rank involved — the pessimist guarantee."""
        logger = UniverseLogger(LocalUniverse(N))
        live = logger.run_logged(ring_program)
        for rank in range(N):
            replay_ctx = logger.replay_context(rank)
            assert ring_program(replay_ctx) == live[rank]
            assert replay_ctx.fully_replayed

    def test_replay_detects_divergence(self):
        logger = UniverseLogger(LocalUniverse(2))

        def prog(ctx):
            if ctx.rank == 0:
                ctx.send(b"x", dest=1, tag=3)
                return 0
            return ctx.recv(source=0, tag=3)

        logger.run_logged(prog)
        bad = logger.replay_context(0)
        with pytest.raises(errors.InternalError, match="divergence"):
            bad.send(b"x", dest=1, tag=4)  # logged tag was 3

    def test_event_counts(self):
        logger = UniverseLogger(LocalUniverse(N))
        logger.run_logged(ring_program)
        sends, recvs = logger.event_counts(0)
        # rank 0: 2 ring sends; 2 ring recvs + 3 any-source recvs
        assert sends == 2 and recvs == 5


class TestCrcp:
    def test_quiescent_after_balanced_traffic(self):
        coord = BookmarkCoordinator(LocalUniverse(N))

        def prog(ctx):
            b = coord.wrap(ctx)
            b.send(ctx.rank, dest=(ctx.rank + 1) % N, tag=0)
            b.recv(source=(ctx.rank - 1) % N, tag=0)
            return True

        coord._uni.run(prog)
        assert coord.quiescent()
        coord.require_quiescent()  # no raise
        sent, recvd = coord.bookmarks()
        assert sent.sum() == N and recvd.sum() == N

    def test_in_flight_detected(self):
        uni = LocalUniverse(2)
        coord = BookmarkCoordinator(uni)

        def prog(ctx):
            b = coord.wrap(ctx)
            if ctx.rank == 0:
                b.send(b"dangling", dest=1, tag=7)  # never received
            return True

        uni.run(prog)
        assert not coord.quiescent()
        assert coord.in_flight()[0, 1] == 1
        with pytest.raises(errors.InternalError, match="0->1"):
            coord.require_quiescent()


class TestMpisync:
    def test_zero_offset_shared_clock(self):
        from zhpe_ompi_tpu.tools.mpisync import sync_clocks

        offsets = sync_clocks(LocalUniverse(3))
        assert offsets[0] == 0.0
        assert all(abs(o) < 0.05 for o in offsets)

    def test_recovers_injected_skew(self):
        import time

        from zhpe_ompi_tpu.tools.mpisync import sync_clocks

        skew = [0.0, 0.25, -0.5, 1.0]
        offsets = sync_clocks(
            LocalUniverse(4),
            clock=lambda r: time.monotonic() + skew[r],
        )
        for r in range(1, 4):
            assert abs(offsets[r] - skew[r]) < 0.05, (r, offsets)


class TestMemchecker:
    def test_nan_send_rejected_when_enabled(self):
        from zhpe_ompi_tpu.mca import var as mca_var
        from zhpe_ompi_tpu.utils import memchecker

        mca_var.set_var("memchecker_enable", True)
        try:
            uni = LocalUniverse(2)

            def prog(ctx):
                if ctx.rank == 0:
                    bad = np.array([1.0, np.nan], np.float32)
                    with pytest.raises(errors.MpiError, match="NaN"):
                        ctx.send(bad, dest=1)
                    ctx.send(np.ones(2, np.float32), dest=1)
                    return True
                return ctx.recv(source=0) is not None

            assert uni.run(prog) == [True, True]
        finally:
            mca_var.set_var("memchecker_enable", False)

    def test_disabled_by_default(self):
        from zhpe_ompi_tpu.utils import memchecker

        assert not memchecker.enabled()
        uni = LocalUniverse(2)

        def prog(ctx):
            if ctx.rank == 0:
                ctx.send(np.array([np.nan], np.float32), dest=1)
                return True
            return bool(np.isnan(ctx.recv(source=0))[0])

        assert uni.run(prog) == [True, True]


class TestPmpi:
    def test_interposition_sees_collectives(self):
        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.tools import pmpi

        world = zmpi.init()
        calls = []

        def tracer(opname, comm, args, kwargs, call_next):
            calls.append((opname, comm.name))
            return call_next()

        pmpi.attach(tracer)
        try:
            import jax.numpy as jnp

            x = np.ones((world.size, 2), np.float32)
            xs = world.device_put_sharded(jnp.asarray(x))
            out = np.asarray(world.run(lambda s: world.allreduce(s), xs))
            np.testing.assert_allclose(
                out.reshape(world.size, 2), world.size
            )
        finally:
            pmpi.detach(tracer)
        assert ("allreduce", "MPI_COMM_WORLD") in calls

    def test_chain_order_outermost_last(self):
        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.tools import pmpi

        world = zmpi.init()
        order = []

        def layer(name):
            def f(opname, comm, args, kwargs, call_next):
                order.append(f"{name}-in")
                out = call_next()
                order.append(f"{name}-out")
                return out

            return f

        l1, l2 = layer("first"), layer("second")
        pmpi.attach(l1)
        pmpi.attach(l2)
        try:
            import jax.numpy as jnp

            xs = world.device_put_sharded(
                jnp.ones((world.size, 1), jnp.float32)
            )
            world.run(lambda s: world.allreduce(s), xs)
        finally:
            pmpi.detach(l1)
            pmpi.detach(l2)
        assert order[:2] == ["second-in", "first-in"]
        assert order[-2:] == ["first-out", "second-out"]
