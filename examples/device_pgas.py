"""Device-plane PGAS acceptance example (round 4).

The OpenSHMEM circular-shift example (the reference's
examples/oshmem_circular_shift.c shape) executed on the DEVICE plane:
the symmetric heap lives in HBM as jax Arrays sharded one-shard-per-PE
over an 8-device mesh, and every put/get/fetch-add is part of a
compiled epoch (ppermute + dynamic-update schedules —
zhpe_ompi_tpu/shmem/device.py, the spml/ucx fast-fabric inversion).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python examples/device_pgas.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.shmem import spml

    world = zmpi.init()
    n = world.axis_size

    # shmem_init on a device communicator selects the "device" spml
    heap = spml.shmem_pe(world, heap_bytes=1 << 14)
    assert heap.plane == "device", heap
    src = heap.shmalloc(4, np.float32)
    counter = heap.shmalloc(1, np.float32)

    def epoch(pe, _):
        me = pe.my_pe().astype(jnp.float32)
        pe = pe.local_set(src, me)
        pe = pe.local_set(counter, 0.0)
        pe = pe.barrier()
        # circular shift: put my block into my right neighbor's heap
        pe = pe.put(src, jnp.full(4, me), pe_of=lambda r, k: (r + 1) % k)
        # and bump their visit counter (one writer per target per epoch)
        old, pe = pe.fadd(counter, 1.0, pe_of=lambda r, k: (r + 1) % k)
        # read back what my LEFT neighbor now holds (two hops of data)
        got = pe.get(src, pe_of=lambda r, k: (r - 1) % k)
        return pe, got[None]

    out = np.asarray(heap.epoch(epoch, jnp.zeros((n, 1))))
    shifted = heap.read(src)
    counts = heap.read(counter)

    for r in range(n):
        assert np.allclose(shifted[r], (r - 1) % n), shifted[r]
        assert counts[r] == 1.0, counts[r]
        # PE r read PE r-1's post-shift block, which holds r-2's rank
        assert np.allclose(out[r], (r - 2) % n), out[r]
    heap.finalize()
    print(f"device_pgas: {n} PEs, HBM symmetric heap, compiled "
          f"put/fadd/get epochs — PASSED")


if __name__ == "__main__":
    main()
