"""Ring attention — sequence/context parallelism over the framework's ring.

Long-context support (first-class per the design brief): Q/K/V are sharded
over the sequence on the 'sp' mesh axis; each step computes one block of the
attention matrix with the MXU while the K/V blocks rotate one hop around the
ICI ring via the framework's ``comm.shift`` (a single ``collective_permute``
per step, overlappable with the block matmul by XLA's scheduler).

Numerics are the flash-attention online-softmax recurrence (running max,
running denominator, rescaled accumulator) in float32, so arbitrarily long
sequences never materialize an (S, S) matrix — memory is O(S_local^2) per
step and exact (not approximate).

The structural analog in the reference is large-message segmentation &
pipelining — segmented ring allreduce (``coll_base_allreduce.c:618``),
pipelined trees (``coll_base_bcast.c:273``) — SURVEY.md §5 "long-context";
ring attention is the same ring-segment idea applied to the attention
operator itself.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_attn(qf, kb, vb, m, l, acc, mask=None):
    """One (Sc x Sc) online-softmax block update; qf pre-scaled f32."""
    scores = jnp.einsum("bshd,bthd->bhst", qf, kb.astype(jnp.float32))
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    blk_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, blk_max)
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    l = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhst,bthd->bshd", p, vb.astype(jnp.float32))
    acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return new_m, l, acc


def ring_attention(comm, q, k, v, causal: bool = True):
    """Exact attention over a sequence sharded on `comm`'s axis.

    q, k, v: (B, S_local, H, D) — this device's sequence block.
    Returns (B, S_local, H, D).  Must run inside shard_map over comm's mesh.
    """
    n = comm.size
    if n == 1:
        return _block_attention_single(q, k, v, causal)
    rank = comm.rank()
    B, S, H, D = q.shape
    scale = D ** -0.5
    qf = q.astype(jnp.float32) * scale

    m0 = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    acc0 = jnp.zeros((B, S, H, D), jnp.float32)
    q_pos = rank * S + jnp.arange(S)

    def step(carry, i):
        m, l, acc, kb, vb = carry
        src = (rank - i) % n  # whose K/V block we hold this step
        mask = None
        if causal:
            k_pos = src * S + jnp.arange(S)
            mask = q_pos[:, None] >= k_pos[None, :]
        # the shared online-softmax block update (_chunk_attn): ONE home
        # for the numerically delicate recurrence, used by both the
        # contiguous and zigzag rings
        m, l, acc = _chunk_attn(qf, kb, vb, m, l, acc, mask=mask)
        # rotate K/V one hop around the ring (framework ppermute)
        kb = comm.shift(kb, 1)
        vb = comm.shift(vb, 1)
        return (m, l, acc, kb, vb), None

    # lax.scan (not fori_loop): reverse-mode AD needs a scan so training
    # can differentiate through the ring
    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def _block_attention_single(q, k, v, causal):
    B, S, H, D = q.shape
    scores = jnp.einsum(
        "bshd,bthd->bhst", q.astype(jnp.float32) * D**-0.5,
        k.astype(jnp.float32),
    )
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhst,bthd->bshd", w, v.astype(jnp.float32)
    ).astype(q.dtype)


# ---------------------------------------------------------------- zigzag
# Load-balanced causal ring attention (round 4).  With the contiguous
# layout above, causality makes the ring LOCKSTEP-IMBALANCED: every rank
# computes a full (S_local x S_local) score block each step, but for
# rank i only steps with src <= i carry unmasked work — the per-step
# wall time is set by the busiest rank while the others burn FLOPs on
# fully-masked blocks.  The zigzag layout gives rank i the chunk PAIR
# (i, 2n-1-i) of 2n global chunks; then every (rank, step) pair has
# EXACTLY the equivalent of two unmasked half-chunks (one of
# {2 full | 1 full + 2 half-diagonals}), so computing only the live
# sub-blocks halves the attention FLOPs uniformly — balanced AND
# cheaper, the standard zigzag/striped context-parallel scheme expressed
# over the framework's ring.


def zigzag_shard(x, n: int):
    """Global (B, S, ...) -> (n, B, S/n, ...) zigzag blocks: rank i gets
    chunks (i, 2n-1-i) of the 2n-chunk split, concatenated."""
    S = x.shape[1]
    assert S % (2 * n) == 0, "sequence must split into 2n chunks"
    c = S // (2 * n)
    chunks = [x[:, i * c:(i + 1) * c] for i in range(2 * n)]
    return jnp.stack(
        [jnp.concatenate([chunks[i], chunks[2 * n - 1 - i]], axis=1)
         for i in range(n)]
    )


def zigzag_unshard(blocks, n: int):
    """(n, B, S/n, ...) zigzag blocks -> global (B, S, ...)."""
    parts = [None] * (2 * n)
    for i in range(n):
        b = blocks[i]
        c = b.shape[1] // 2
        parts[i] = b[:, :c]
        parts[2 * n - 1 - i] = b[:, c:]
    return jnp.concatenate(parts, axis=1)


def ring_attention_zigzag(comm, q, k, v):
    """Exact CAUSAL attention over a zigzag-sharded sequence.

    q, k, v: (B, S_local, H, D) where the first half is this rank's
    EARLY chunk (global chunk ``rank``) and the second half its LATE
    chunk (global chunk ``2n-1-rank``) — the :func:`zigzag_shard`
    layout.  Must run inside shard_map over comm's mesh.  Each ring
    step computes only the causally-live sub-blocks (two full-chunk
    equivalents), so attention FLOPs are half the contiguous ring's and
    identical on every rank.
    """
    n = comm.size
    if n == 1:
        return _block_attention_single(q, k, v, True)
    rank = comm.rank()
    B, S, H, D = q.shape
    c = S // 2
    scale = D ** -0.5
    qf = q.astype(jnp.float32) * scale
    qa, qb = qf[:, :c], qf[:, c:]  # early / late chunks

    causal = jnp.tril(jnp.ones((c, c), bool))

    def init(sq):
        return (jnp.full((B, H, sq), -jnp.inf, jnp.float32),
                jnp.zeros((B, H, sq), jnp.float32),
                jnp.zeros((B, sq, H, D), jnp.float32))

    ma, la, acca = init(c)
    mb, lb, accb = init(c)

    def step(carry, i):
        ma, la, acca, mb, lb, accb, kb, vb = carry
        src = (rank - i) % n  # whose zigzag pair we hold this step
        kc, kd = kb[:, :c], kb[:, c:]   # src's early / late chunks
        vc, vd = vb[:, :c], vb[:, c:]
        # live sub-blocks (chunk ids: a=rank, b=2n-1-rank, c=src,
        # d=2n-1-src):
        #   rank > src: (a,c) full, (b,c) full
        #   rank < src: (b,c) full, (b,d) full
        #   rank == src: (a,c) diag, (b,c) full, (b,d) diag
        # (b,c) is full in EVERY case except the diagonal-on-self of
        # (b,d); (a,d) is never live.  Dispatch the two variable
        # sub-blocks with a 3-way branch on the traced comparison.
        def gt_case(ops):
            ma, la, acca, mb, lb, accb = ops
            ma, la, acca = _chunk_attn(qa, kc, vc, ma, la, acca)
            mb, lb, accb = _chunk_attn(qb, kc, vc, mb, lb, accb)
            return ma, la, acca, mb, lb, accb

        def lt_case(ops):
            ma, la, acca, mb, lb, accb = ops
            mb, lb, accb = _chunk_attn(qb, kc, vc, mb, lb, accb)
            mb, lb, accb = _chunk_attn(qb, kd, vd, mb, lb, accb)
            return ma, la, acca, mb, lb, accb

        def eq_case(ops):
            ma, la, acca, mb, lb, accb = ops
            ma, la, acca = _chunk_attn(qa, kc, vc, ma, la, acca,
                                       mask=causal)
            mb, lb, accb = _chunk_attn(qb, kc, vc, mb, lb, accb)
            mb, lb, accb = _chunk_attn(qb, kd, vd, mb, lb, accb,
                                       mask=causal)
            return ma, la, acca, mb, lb, accb

        idx = jnp.where(rank > src, 0, jnp.where(rank < src, 1, 2))
        ma, la, acca, mb, lb, accb = lax.switch(
            idx, (gt_case, lt_case, eq_case),
            (ma, la, acca, mb, lb, accb),
        )
        kb = comm.shift(kb, 1)
        vb = comm.shift(vb, 1)
        return (ma, la, acca, mb, lb, accb, kb, vb), None

    (ma, la, acca, mb, lb, accb, _, _), _ = lax.scan(
        step, (ma, la, acca, mb, lb, accb, k, v), jnp.arange(n)
    )

    def finish(m, l, acc):
        denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return acc / denom

    out = jnp.concatenate(
        [finish(ma, la, acca), finish(mb, lb, accb)], axis=1
    )
    return out.astype(q.dtype)
