/* osu_c — OSU-style ping-pong over the C ABI shim (the C-plane analog
 * of benchmarks/osu_zmpi.py --op tcp): quantifies the shim's engine
 * (drain threads, posted-receive matching, DSS framing) without the
 * Python interpreter in the data path.
 *
 *   python -m zhpe_ompi_tpu.tools.zmpicc benchmarks/osu_c.c -o osu_c
 *   python -m zhpe_ompi_tpu.tools.mpirun -n 2 ./osu_c
 *
 * Prints one line per size: bytes, one-way latency (us), bandwidth
 * (MB/s), median of 5 reps of `iters` round trips each.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"

static int cmp_double(const void *a, const void *b) {
  double d = *(const double *)a - *(const double *)b;
  return d < 0 ? -1 : d > 0 ? 1 : 0;
}

static void allreduce_ladder(int rank, int size) {
  /* osu_allreduce shape over the shim's recursive-doubling engine */
  size_t elems[] = {1, 16, 256, 4096, 65536, 1048576};
  double *in = malloc(elems[5] * sizeof(double));
  double *out = malloc(elems[5] * sizeof(double));
  for (size_t i = 0; i < elems[5]; i++) in[i] = (double)i;
  for (int s = 0; s < 6; s++) {
    size_t n = elems[s];
    int iters = n <= 4096 ? 100 : 20;
    double reps[5];
    for (int rep = 0; rep < 5; rep++) {
      MPI_Barrier(MPI_COMM_WORLD);
      double t0 = MPI_Wtime();
      for (int it = 0; it < iters; it++)
        MPI_Allreduce(in, out, (int)n, MPI_DOUBLE, MPI_SUM,
                      MPI_COMM_WORLD);
      reps[rep] = (MPI_Wtime() - t0) / iters;
    }
    if (rank == 0) {
      qsort(reps, 5, sizeof(double), cmp_double);
      printf("{\"op\": \"c_allreduce\", \"ranks\": %d, \"bytes\": %zu, "
             "\"latency_us\": %.2f}\n",
             size, n * sizeof(double), reps[2] * 1e6);
      fflush(stdout);
    }
  }
  free(in);
  free(out);
}

int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (size != 2) {
    if (size < 2) {
      if (rank == 0)
        fprintf(stderr, "osu_c needs >= 2 ranks (2 = pt2pt ladder, "
                        ">2 = allreduce ladder)\n");
      MPI_Finalize();
      return 1;
    }
    /* >2 ranks runs the collective ladder instead */
    allreduce_ladder(rank, size);
    MPI_Finalize();
    return 0;
  }
  size_t sizes[] = {8, 64, 1024, 4096, 16384, 65536, 262144, 1048576,
                    4194304};
  char *buf = malloc(sizes[8]);
  memset(buf, 7, sizes[8]);
  for (int s = 0; s < 9; s++) {
    size_t n = sizes[s];
    int iters = n <= 4096 ? 200 : n <= 65536 ? 80 : 20;
    double reps[5];
    for (int rep = 0; rep < 5; rep++) {
      MPI_Barrier(MPI_COMM_WORLD);
      double t0 = MPI_Wtime();
      for (int it = 0; it < iters; it++) {
        if (rank == 0) {
          MPI_Send(buf, (int)n, MPI_BYTE, 1, 1, MPI_COMM_WORLD);
          MPI_Recv(buf, (int)n, MPI_BYTE, 1, 2, MPI_COMM_WORLD,
                   MPI_STATUS_IGNORE);
        } else {
          MPI_Recv(buf, (int)n, MPI_BYTE, 0, 1, MPI_COMM_WORLD,
                   MPI_STATUS_IGNORE);
          MPI_Send(buf, (int)n, MPI_BYTE, 0, 2, MPI_COMM_WORLD);
        }
      }
      reps[rep] = (MPI_Wtime() - t0) / (2.0 * iters);  /* one-way s */
    }
    if (rank == 0) {
      qsort(reps, 5, sizeof(double), cmp_double);
      double lat = reps[2];  /* median */
      printf("{\"op\": \"c_pingpong\", \"bytes\": %zu, "
             "\"latency_us\": %.2f, \"bandwidth_MBps\": %.1f}\n",
             n, lat * 1e6, n / lat / 1e6);
      fflush(stdout);
    }
  }
  free(buf);
  MPI_Finalize();
  return 0;
}
