"""fs framework — filesystem operation components.

Analog of OMPIO's ``fs`` sub-framework (``ompi/mca/fs/{ufs,lustre,...}``):
a component supplies open/pread/pwrite/resize/sync/delete primitives; the
File layer above is filesystem-agnostic.  One component ships (posix, the
``fs/ufs`` analog); parallel filesystems would register siblings selected
by priority or ``ZMPI_MCA_fs=...``.
"""

from __future__ import annotations

import os

from ..core import errors
from ..mca import component as mca_component


class FsComponent(mca_component.Component):
    framework_name = "fs"

    def open(self, path: str, flags: int) -> int:
        raise NotImplementedError

    def close(self, fd: int) -> None:
        raise NotImplementedError

    def pread(self, fd: int, nbytes: int, offset: int) -> bytes:
        raise NotImplementedError

    def pwrite(self, fd: int, data, offset: int) -> int:
        raise NotImplementedError

    def size(self, fd: int) -> int:
        raise NotImplementedError

    def resize(self, fd: int, size: int) -> None:
        raise NotImplementedError

    def sync(self, fd: int) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError


class PosixFs(FsComponent):
    """fs/ufs analog over POSIX fds (pread/pwrite are atomic at-offset ops,
    the property the fbtl/posix component relies on)."""

    name = "posix"
    default_priority = 10

    def open(self, path: str, flags: int) -> int:
        try:
            return os.open(path, flags, 0o644)
        except FileExistsError:
            raise errors.ArgError(f"file exists: {path}")
        except FileNotFoundError:
            raise errors.ArgError(f"no such file: {path}")
        except PermissionError:
            raise errors.ArgError(f"permission denied: {path}")

    def close(self, fd: int) -> None:
        os.close(fd)

    def pread(self, fd: int, nbytes: int, offset: int) -> bytes:
        return os.pread(fd, nbytes, offset)

    def pwrite(self, fd: int, data, offset: int) -> int:
        return os.pwrite(fd, data, offset)

    def size(self, fd: int) -> int:
        return os.fstat(fd).st_size

    def resize(self, fd: int, size: int) -> None:
        os.ftruncate(fd, size)

    def sync(self, fd: int) -> None:
        os.fsync(fd)

    def delete(self, path: str) -> None:
        try:
            os.unlink(path)
        except FileNotFoundError:
            raise errors.ArgError(f"no such file: {path}")


def fs_framework() -> mca_component.Framework:
    return mca_component.build_framework(
        "fs", "filesystem operations", (PosixFs,)
    )


def select_fs() -> FsComponent:
    return fs_framework().select_one()
