"""MPI-IO over the wire plane (``io/wirefile.py``): per-rank views,
lockedfile shared pointer, fcoll-aggregated collective IO — with thread
ranks for speed and real launcher processes for the cross-process
sharedfp/lockedfile property (reference: ``ompi/mca/sharedfp/lockedfile``).
"""

import io
import os
import textwrap

import numpy as np

from test_tcp import run_tcp
from zhpe_ompi_tpu.datatype import (
    FLOAT,
    INT32_T,
    create_contiguous,
    create_resized,
    create_vector,
)
from zhpe_ompi_tpu.io.file import (
    MODE_CREATE,
    MODE_RDONLY,
    MODE_RDWR,
    MODE_WRONLY,
)
from zhpe_ompi_tpu.io.wirefile import WireFile
from zhpe_ompi_tpu.tools import mpirun

N = 4
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestWireFileThreads:
    def test_interleaved_views_write_all(self, tmp_path):
        """Each rank's filetype tiles the file rank-interleaved; a
        collective write composes the full array."""
        path = str(tmp_path / "data.bin")

        def prog(p):
            with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                # rank r owns int32 slot r of every n-slot tile
                ft = create_resized(create_vector(1, 1, 1, INT32_T), 0, 4 * N)
                f.set_view(4 * p.rank, INT32_T, ft)
                data = np.arange(8, dtype=np.int32) + 100 * p.rank
                f.write_all(data)
            return True

        run_tcp(N, prog)
        got = np.fromfile(path, dtype=np.int32)
        want = np.empty(8 * N, np.int32)
        for r in range(N):
            want[r::N] = np.arange(8, dtype=np.int32) + 100 * r
        assert got.tolist() == want.tolist()

    def test_read_all_scatters(self, tmp_path):
        path = str(tmp_path / "data.bin")
        full = np.arange(8 * N, dtype=np.int32)
        full.tofile(path)

        def prog(p):
            with WireFile(p, path, MODE_RDONLY) as f:
                ft = create_resized(create_vector(1, 1, 1, INT32_T), 0, 4 * N)
                f.set_view(4 * p.rank, INT32_T, ft)
                got = f.read_all(8)
            return got.tolist()

        res = run_tcp(N, prog)
        for r in range(N):
            assert res[r] == full[r::N].tolist()

    def test_shared_pointer_disjoint(self, tmp_path):
        """Concurrent write_shared from every rank: regions must be
        disjoint and cover the file exactly."""
        path = str(tmp_path / "log.bin")
        PER = 16

        def prog(p):
            with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                f.set_view(0, FLOAT, create_contiguous(1, FLOAT))
                for _ in range(PER):
                    f.write_shared(np.full(2, float(p.rank), np.float32))
                f.sync()
            return True

        run_tcp(N, prog)
        got = np.fromfile(path, dtype=np.float32)
        assert got.size == 2 * PER * N
        # every 2-float record is rank-constant and counts are exact
        recs = got.reshape(-1, 2)
        assert (recs[:, 0] == recs[:, 1]).all()
        for r in range(N):
            assert (recs[:, 0] == r).sum() == PER

    def test_explicit_offsets_and_size(self, tmp_path):
        path = str(tmp_path / "x.bin")

        def prog(p):
            with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                f.set_view(0, INT32_T)
                f.write_at(p.rank * 4, np.full(4, p.rank, np.int32))
                f.sync()
                back = f.read_at(p.rank * 4, 4)
                sz = f.get_size()
            return back.tolist(), sz

        res = run_tcp(N, prog)
        for r in range(N):
            assert res[r][0] == [r] * 4
            assert res[r][1] == 4 * N * 4


class TestWireFileProcesses:
    def test_cross_process_shared_pointer(self, tmp_path):
        prog_path = tmp_path / "prog.py"
        data_path = str(tmp_path / "shared.bin")
        prog_path.write_text(
            "import sys\n"
            f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(f"""
            import numpy as np
            import zhpe_ompi_tpu as zmpi
            from zhpe_ompi_tpu.io.file import MODE_CREATE, MODE_RDWR
            from zhpe_ompi_tpu.io.wirefile import WireFile
            from zhpe_ompi_tpu.datatype import INT32_T

            proc = zmpi.host_init()
            with WireFile(proc, {data_path!r},
                          MODE_RDWR | MODE_CREATE) as f:
                f.set_view(0, INT32_T)
                for _ in range(25):
                    f.write_shared(np.full(1, proc.rank, np.int32))
                f.sync()
                total = f.tell_shared()
                if proc.rank == 0:
                    assert total == 25 * proc.size, total
                    print("SHFP-OK")
            zmpi.host_finalize()
            """)
        )
        out, err = io.StringIO(), io.StringIO()
        rc = mpirun.launch(3, [str(prog_path)], stdout=out, stderr=err,
                           timeout=120.0)
        assert rc == 0, err.getvalue()
        assert "SHFP-OK" in out.getvalue()
        got = np.fromfile(data_path, dtype=np.int32)
        assert got.size == 75
        for r in range(3):
            assert (got == r).sum() == 25


class TestVulcanAggregation:
    """fcoll_wire_aggregators > 1: the vulcan shape — stripe sets owned
    round-robin by several aggregator ranks (ompi/mca/fcoll/vulcan)."""

    def _with_vulcan(self, fn):
        from zhpe_ompi_tpu.mca import var as mca_var

        mca_var.register("fcoll_wire_aggregators", 1, "test", type=int)
        mca_var.register("fcoll_dynamic_stripe", 4 << 20, "test", type=int)
        mca_var.set_var("fcoll_wire_aggregators", 2)
        mca_var.set_var("fcoll_dynamic_stripe", 64)
        try:
            return fn()
        finally:
            mca_var.unset("fcoll_wire_aggregators")
            mca_var.unset("fcoll_dynamic_stripe")

    def test_multi_aggregator_roundtrip(self, tmp_path):
        path = str(tmp_path / "vulcan.bin")

        def run():
            def prog(p):
                with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                    ft = create_resized(
                        create_vector(1, 1, 1, INT32_T), 0, 4 * N)
                    f.set_view(4 * p.rank, INT32_T, ft)
                    data = np.arange(64, dtype=np.int32) + 1000 * p.rank
                    f.write_all(data)
                    f.seek(0)
                    back = f.read_all(64)
                return back.tolist()

            return run_tcp(N, prog)

        res = self._with_vulcan(run)
        for r in range(N):
            assert res[r] == (np.arange(64, dtype=np.int32)
                              + 1000 * r).tolist()
        got = np.fromfile(path, dtype=np.int32)
        want = np.empty(64 * N, np.int32)
        for r in range(N):
            want[r::N] = np.arange(64, dtype=np.int32) + 1000 * r
        assert got.tolist() == want.tolist()


class TestWireNonblocking:
    """Round-4 (VERDICT Missing #2): iread/iwrite(_at) on the wire-plane
    file — each rank overlaps its own IO with compute."""

    def test_iwrite_disjoint_then_iread(self, tmp_path):
        path = str(tmp_path / "nb.bin")

        def prog(p):
            with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                f.set_view(16 * p.rank, INT32_T)  # disjoint 16B stripes
                data = np.arange(4, dtype=np.int32) + 10 * p.rank
                wreq = f.iwrite_at(0, data)
                # overlapped compute
                acc = sum(i for i in range(20000))
                assert wreq.wait(timeout=30) == 4 and acc > 0
                f.sync()  # collective: all writes visible
                rreq = f.iread_at(0, 4)
                got = rreq.wait(timeout=30)
            return got.tolist()

        res = run_tcp(N, prog)
        for r in range(N):
            assert res[r] == [10 * r, 10 * r + 1, 10 * r + 2, 10 * r + 3]

    def test_iread_pending_until_gate(self, tmp_path):
        """Wire-plane overlap proof: gate one rank's fbtl; its request
        stays pending through test() until released."""
        import threading

        path = str(tmp_path / "gate.bin")
        np.arange(32, dtype=np.uint8).tofile(path)

        class Gated:
            def __init__(self, base):
                self.base = base
                self.gate = threading.Event()

            def preadv(self, fd, runs, total):
                assert self.gate.wait(30)
                return self.base.preadv(fd, runs, total)

            def pwritev(self, fd, runs, data):
                return self.base.pwritev(fd, runs, data)

        def prog(p):
            with WireFile(p, path, MODE_RDONLY) as f:
                if p.rank == 0:
                    gated = Gated(f._fbtl)
                    f._fbtl = gated
                    req = f.iread_at(0, 8)
                    flag, _ = req.test()
                    assert not flag and not req.done
                    gated.gate.set()
                    got = req.wait(timeout=30)
                else:
                    got = f.iread_at(0, 8).wait(timeout=30)
            return got.tolist()

        res = run_tcp(2, prog)
        assert res[0] == list(range(8)) and res[1] == list(range(8))


class TestWireNonblockingCollective:
    """iwrite_all/iread_all on the wire plane: every rank's collective
    body (aggregation exchange + transfers) retires on its worker."""

    def test_iwrite_all_then_iread_all(self, tmp_path):
        path = str(tmp_path / "nbcoll.bin")

        def prog(p):
            with WireFile(p, path, MODE_RDWR | MODE_CREATE) as f:
                ft = create_resized(create_vector(1, 1, 1, INT32_T),
                                    0, 4 * N)
                f.set_view(4 * p.rank, INT32_T, ft)
                data = np.arange(8, dtype=np.int32) + 100 * p.rank
                wreq = f.iwrite_all(data)
                acc = sum(i for i in range(10000))  # overlapped compute
                assert wreq.wait(timeout=30) == 8 and acc > 0
                f.seek(0)
                rreq = f.iread_all(8)
                got = rreq.wait(timeout=30)
            return got.tolist()

        res = run_tcp(N, prog)
        for r in range(N):
            assert res[r] == (np.arange(8, dtype=np.int32)
                              + 100 * r).tolist()

    def test_iwrite_all_overlaps_blocking_collective(self, tmp_path):
        """Regression (round-4 review): collective tags are reserved at
        CALL time, so a blocking collective issued while the nonblocking
        body still runs on the worker cannot steal its tag window."""
        path = str(tmp_path / "overlap.bin")
        pre = np.arange(4 * N, dtype=np.int32)
        pre.tofile(path)

        def prog(p):
            with WireFile(p, path, MODE_RDWR) as f:
                ft = create_resized(create_vector(1, 1, 1, INT32_T),
                                    0, 4 * N)
                f.set_view(4 * p.rank, INT32_T, ft)
                data = np.arange(4, dtype=np.int32) + 1000 * p.rank
                wreq = f.iwrite_all(data)
                # a blocking collective on the SAME endpoint while the
                # write body may still be in flight on the worker
                f.seek(0)
                first = f.read_all(4)
                assert wreq.wait(timeout=30) == 4
                f.seek(0)
                final = f.read_all(4)
            return first.tolist(), final.tolist()

        res = run_tcp(N, prog)
        for r in range(N):
            want_final = (np.arange(4, dtype=np.int32) + 1000 * r).tolist()
            assert res[r][1] == want_final
            # the overlapped read saw either the old or the new image
            # per element (non-atomic mode), but never corrupt tags —
            # completing at all, with a valid final image, is the proof
