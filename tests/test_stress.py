"""Concurrency soak: overlapping nonblocking collectives, RMA, and
pt2pt traffic over real sockets — the schedule-interleaving torture the
per-instance tag discipline exists for."""

import numpy as np

from test_tcp import run_tcp
from zhpe_ompi_tpu import ops as zops

N = 4
ROUNDS = 12


class TestOverlapSoak:
    def test_overlapping_nonblocking_collectives(self):
        def prog(p):
            rng = np.random.default_rng(100 + p.rank)
            for it in range(ROUNDS):
                a = p.iallreduce(float(p.rank + it), zops.SUM)
                b = p.iallgather((p.rank, it))
                c = p.ibcast(f"r{it}" if p.rank == it % N else None,
                             root=it % N)
                d = p.ialltoall([(p.rank, dst, it) for dst in range(N)])
                # complete intentionally out of issue order
                got_d = d.wait()
                got_b = b.wait()
                got_a = a.wait()
                got_c = c.wait()
                assert got_a == sum(r + it for r in range(N))
                assert got_b == [(r, it) for r in range(N)]
                assert got_c == f"r{it}"
                assert got_d == [(src, p.rank, it) for src in range(N)]
            return True

        assert run_tcp(N, prog, timeout=120.0) == [True] * N

    def test_collectives_interleaved_with_pt2pt_and_rma(self):
        from zhpe_ompi_tpu.osc.am import AmWindow

        def prog(p):
            win = AmWindow.create(p, np.zeros(N, np.float64))
            for it in range(ROUNDS):
                req = p.iallreduce(1, zops.SUM)
                # pt2pt ring exchange while the collective is in flight
                nxt, prv = (p.rank + 1) % N, (p.rank - 1) % N
                p.send((p.rank, it), nxt, tag=0x600 + it)
                got = p.recv(source=prv, tag=0x600 + it)
                assert got == (prv, it)
                # one-sided accumulate into the neighbor's window slot
                win.lock(nxt)
                win.accumulate(np.asarray([1.0]), nxt,
                               offset=p.rank, op=zops.SUM)
                win.unlock(nxt)
                assert req.wait() == N
            # unlock already completed every op at the target; one
            # barrier orders all ranks' epochs before the read-back
            p.barrier()
            local = win.local_buffer.tolist()
            win.free()
            return local

        res = run_tcp(N, prog, timeout=120.0)
        for r in range(N):
            # neighbor (r-1) accumulated ROUNDS ones into slot (r-1)
            want = [0.0] * N
            want[(r - 1) % N] = float(ROUNDS)
            assert res[r] == want, (r, res[r])
