"""Communicators and groups (ompi/communicator + ompi/group analog)."""
from .communicator import Communicator
from .group import Group

__all__ = ["Communicator", "Group"]
