"""DSS — typed data serialization for the out-of-band plane.

Re-design of ``opal/dss`` (SURVEY.md §2.1, 6.2k LoC): the reference packs
typed values (ints of every width, strings, byte objects, nested
containers) into self-describing buffers for PMIx modex payloads and tool
messages.  Same role here: the host plane's wire format for the multi-host
DCN transport and for checkpoint metadata — numpy arrays carry their dtype
and shape, containers nest, and every value round-trips exactly.

Format: one type byte, then a varint length where needed, then the
payload; containers recurse.  Little-endian fixed-width scalars (the
reference's heterogeneous-arch conversion lives in the datatype engine's
external32 path, not here).
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

from ..core import errors

_T_NONE = 0
_T_BOOL = 1
_T_INT = 2       # arbitrary-precision python int (zigzag varint)
_T_FLOAT = 3     # python float, f64
_T_STR = 4
_T_BYTES = 5
_T_LIST = 6
_T_TUPLE = 7
_T_DICT = 8
_T_NDARRAY = 9


def _pack_varint(n: int, out: bytearray) -> None:
    if n < 0:
        raise errors.ArgError("varint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _unpack_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _pack_one(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(_T_NONE)
    elif isinstance(obj, bool):
        out.append(_T_BOOL)
        out.append(1 if obj else 0)
    elif isinstance(obj, int):
        out.append(_T_INT)
        # zigzag so negatives stay compact
        z = (obj << 1) if obj >= 0 else ((-obj << 1) | 1)
        _pack_varint(z, out)
    elif isinstance(obj, float):
        out.append(_T_FLOAT)
        out.extend(struct.pack("<d", obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(_T_STR)
        _pack_varint(len(raw), out)
        out.extend(raw)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(_T_BYTES)
        _pack_varint(len(obj), out)
        out.extend(obj)
    elif isinstance(obj, np.ndarray):
        out.append(_T_NDARRAY)
        dt = obj.dtype.str.encode("ascii")  # e.g. b'<f4'
        _pack_varint(len(dt), out)
        out.extend(dt)
        _pack_varint(obj.ndim, out)
        for d in obj.shape:
            _pack_varint(d, out)
        raw = np.ascontiguousarray(obj).tobytes()
        _pack_varint(len(raw), out)
        out.extend(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(_T_LIST if isinstance(obj, list) else _T_TUPLE)
        _pack_varint(len(obj), out)
        for item in obj:
            _pack_one(item, out)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        _pack_varint(len(obj), out)
        for k, v in obj.items():
            _pack_one(k, out)
            _pack_one(v, out)
    elif isinstance(obj, np.generic):
        # numpy scalar: pack as a 0-d array so the dtype survives
        _pack_one(np.asarray(obj), out)
    else:
        raise errors.TypeError_(
            f"dss cannot pack {type(obj).__name__}"
        )


def _unpack_one(buf: memoryview, pos: int) -> tuple[Any, int]:
    t = buf[pos]
    pos += 1
    if t == _T_NONE:
        return None, pos
    if t == _T_BOOL:
        return bool(buf[pos]), pos + 1
    if t == _T_INT:
        z, pos = _unpack_varint(buf, pos)
        return ((z >> 1) if not z & 1 else -(z >> 1)), pos
    if t == _T_FLOAT:
        (v,) = struct.unpack_from("<d", buf, pos)
        return v, pos + 8
    if t == _T_STR:
        n, pos = _unpack_varint(buf, pos)
        return bytes(buf[pos : pos + n]).decode("utf-8"), pos + n
    if t == _T_BYTES:
        n, pos = _unpack_varint(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if t == _T_NDARRAY:
        n, pos = _unpack_varint(buf, pos)
        dt = np.dtype(bytes(buf[pos : pos + n]).decode("ascii"))
        pos += n
        ndim, pos = _unpack_varint(buf, pos)
        shape = []
        for _ in range(ndim):
            d, pos = _unpack_varint(buf, pos)
            shape.append(d)
        nbytes, pos = _unpack_varint(buf, pos)
        # copy: frombuffer over bytes yields a read-only array, which would
        # diverge from the writable copies the thread universe delivers
        arr = np.frombuffer(
            bytes(buf[pos : pos + nbytes]), dtype=dt
        ).reshape(shape).copy()
        return arr, pos + nbytes
    if t in (_T_LIST, _T_TUPLE):
        n, pos = _unpack_varint(buf, pos)
        items = []
        for _ in range(n):
            item, pos = _unpack_one(buf, pos)
            items.append(item)
        return (items if t == _T_LIST else tuple(items)), pos
    if t == _T_DICT:
        n, pos = _unpack_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _unpack_one(buf, pos)
            v, pos = _unpack_one(buf, pos)
            d[k] = v
        return d, pos
    raise errors.TypeError_(f"dss: unknown type tag {t}")


def pack(*objs: Any) -> bytes:
    """Pack values into one self-describing buffer (opal_dss.pack)."""
    out = bytearray()
    _pack_varint(len(objs), out)
    for obj in objs:
        _pack_one(obj, out)
    return bytes(out)


def unpack(data: bytes) -> list[Any]:
    """Unpack every value from a buffer (opal_dss.unpack)."""
    buf = memoryview(data)
    n, pos = _unpack_varint(buf, 0)
    out = []
    for _ in range(n):
        obj, pos = _unpack_one(buf, pos)
        out.append(obj)
    if pos != len(buf):
        raise errors.TruncateError(
            f"dss: {len(buf) - pos} trailing bytes after unpack"
        )
    return out
