"""Error model: MPI-style error classes and exceptions.

TPU-native re-design of the reference's error machinery
(``ompi/errhandler/errhandler.h``, error codes in ``ompi/include/mpi.h.in``).
The reference attaches error handlers to communicators/windows/files and maps
every failure to an integer error class; here the Python-native idiom is an
exception hierarchy that still carries the MPI error class so tooling and
tests can assert on codes.
"""

from __future__ import annotations

# MPI error classes (numbering follows the MPI standard; the reference defines
# these in ompi/include/mpi.h.in).
SUCCESS = 0
ERR_BUFFER = 1
ERR_COUNT = 2
ERR_TYPE = 3
ERR_TAG = 4
ERR_COMM = 5
ERR_RANK = 6
ERR_REQUEST = 7
ERR_ROOT = 8
ERR_GROUP = 9
ERR_OP = 10
ERR_TOPOLOGY = 11
ERR_DIMS = 12
ERR_ARG = 13
ERR_UNKNOWN = 14
ERR_TRUNCATE = 15
ERR_OTHER = 16
ERR_INTERN = 17
ERR_IN_STATUS = 18
ERR_PENDING = 19
ERR_NO_MEM = 34
ERR_WIN = 45
ERR_KEYVAL = 48
ERR_NOT_INITIALIZED = 60
ERR_UNSUPPORTED = 52
# ULFM fault-tolerance classes (numbering follows the reference fork's
# MPIX_ERR_* extension slots in ompi/include/mpi.h.in)
ERR_PROC_ABORTED = 74
ERR_PROC_FAILED = 75
ERR_PROC_FAILED_PENDING = 76
ERR_REVOKED = 77
# device-plane fault class (no reference slot: the reference watches
# processes only — a wedged accelerator participant is this repro's
# extension, carved from the same MPIX_ERR_* block)
ERR_DEVICE_FAULT = 78

_ERROR_STRINGS = {
    SUCCESS: "MPI_SUCCESS: no error",
    ERR_BUFFER: "MPI_ERR_BUFFER: invalid buffer pointer",
    ERR_COUNT: "MPI_ERR_COUNT: invalid count argument",
    ERR_TYPE: "MPI_ERR_TYPE: invalid datatype argument",
    ERR_TAG: "MPI_ERR_TAG: invalid tag argument",
    ERR_COMM: "MPI_ERR_COMM: invalid communicator",
    ERR_RANK: "MPI_ERR_RANK: invalid rank",
    ERR_REQUEST: "MPI_ERR_REQUEST: invalid request",
    ERR_ROOT: "MPI_ERR_ROOT: invalid root",
    ERR_GROUP: "MPI_ERR_GROUP: invalid group",
    ERR_OP: "MPI_ERR_OP: invalid reduce operation",
    ERR_TOPOLOGY: "MPI_ERR_TOPOLOGY: invalid topology",
    ERR_DIMS: "MPI_ERR_DIMS: invalid dimension argument",
    ERR_ARG: "MPI_ERR_ARG: invalid argument",
    ERR_UNKNOWN: "MPI_ERR_UNKNOWN: unknown error",
    ERR_TRUNCATE: "MPI_ERR_TRUNCATE: message truncated",
    ERR_OTHER: "MPI_ERR_OTHER: known error not in list",
    ERR_INTERN: "MPI_ERR_INTERN: internal error",
    ERR_IN_STATUS: "MPI_ERR_IN_STATUS: error code in status",
    ERR_PENDING: "MPI_ERR_PENDING: pending request",
    ERR_WIN: "MPI_ERR_WIN: invalid window",
    ERR_KEYVAL: "MPI_ERR_KEYVAL: invalid key value",
    ERR_NOT_INITIALIZED: "MPI_ERR_NOT_INITIALIZED: runtime not initialized",
    ERR_UNSUPPORTED: "MPI_ERR_UNSUPPORTED_OPERATION: unsupported operation",
    ERR_PROC_ABORTED: "MPIX_ERR_PROC_ABORTED: process aborted",
    ERR_PROC_FAILED: "MPIX_ERR_PROC_FAILED: process failed",
    ERR_PROC_FAILED_PENDING:
        "MPIX_ERR_PROC_FAILED_PENDING: pending failure blocks a wildcard "
        "receive; acknowledge with failure_ack to continue",
    ERR_REVOKED: "MPIX_ERR_REVOKED: communicator revoked",
    ERR_DEVICE_FAULT:
        "ZMPIX_ERR_DEVICE_FAULT: a device-plane participant missed its "
        "liveness deadline (wedged collective, lost accelerator)",
}


def error_string(errclass: int) -> str:
    """MPI_Error_string equivalent."""
    return _ERROR_STRINGS.get(errclass, f"unknown error class {errclass}")


class MpiError(Exception):
    """Base exception carrying an MPI error class."""

    errclass = ERR_UNKNOWN

    def __init__(self, message: str = "", errclass: int | None = None):
        if errclass is not None:
            self.errclass = errclass
        super().__init__(message or error_string(self.errclass))


class CommError(MpiError):
    errclass = ERR_COMM


class RankError(MpiError):
    errclass = ERR_RANK


class RootError(MpiError):
    errclass = ERR_ROOT


class TagError(MpiError):
    errclass = ERR_TAG


class CountError(MpiError):
    errclass = ERR_COUNT


class TypeError_(MpiError):
    errclass = ERR_TYPE


class OpError(MpiError):
    errclass = ERR_OP


class GroupError(MpiError):
    errclass = ERR_GROUP


class ArgError(MpiError):
    errclass = ERR_ARG


class TruncateError(MpiError):
    errclass = ERR_TRUNCATE


class RequestError(MpiError):
    errclass = ERR_REQUEST


class WinError(MpiError):
    errclass = ERR_WIN


class KeyvalError(MpiError):
    errclass = ERR_KEYVAL


class ResourceError(MpiError):
    errclass = ERR_NO_MEM


class InternalError(MpiError):
    errclass = ERR_INTERN


class NotInitializedError(MpiError):
    errclass = ERR_NOT_INITIALIZED


class UnsupportedError(MpiError):
    errclass = ERR_UNSUPPORTED


class ProcFailed(MpiError):
    """MPIX_ERR_PROC_FAILED: a named peer the operation depends on is dead
    (the ULFM live-failure path — distinct from a stall/timeout).  Carries
    the set of global ranks known failed when it was raised."""

    errclass = ERR_PROC_FAILED

    def __init__(self, message: str = "", failed_ranks=(),
                 errclass: int | None = None):
        super().__init__(message, errclass)
        self.failed_ranks = tuple(sorted(int(r) for r in failed_ranks))


class ProcFailedPending(ProcFailed):
    """MPIX_ERR_PROC_FAILED_PENDING: a wildcard (ANY_SOURCE) receive
    cannot complete because an unacknowledged failure means the awaited
    sender may be dead.  ``failure_ack`` re-enables wildcard receives
    (the ULFM pending contract)."""

    errclass = ERR_PROC_FAILED_PENDING


class DeviceFault(ProcFailed):
    """ZMPIX_ERR_DEVICE_FAULT: a device-plane participant missed its
    liveness deadline — the device-plane twin of :class:`ProcFailed`
    (a subclass, so every host-plane recovery path that catches typed
    process failure recovers device faults too).  Carries the probe's
    structured outcome (``kind`` in "hung"/"deadline"/"error") so a
    postmortem can tell an outer kill from an internal watchdog expiry."""

    errclass = ERR_DEVICE_FAULT

    def __init__(self, message: str = "", failed_ranks=(),
                 kind: str = "deadline"):
        super().__init__(message, failed_ranks)
        self.kind = str(kind)


class PlacementViolation(InternalError):
    """A multi-tenant placement audit failed: two live jobs on one DVM
    tree were caught sharing state that the tenancy contract requires
    disjoint — sm-segment session prefixes, PMIx namespaces, or (for
    exclusive placements) daemon subtrees.  Typed so the daemon can
    count it (``dvm_placement_audit_failures``) and fail the offending
    launch loudly rather than let two tenants corrupt each other.
    Carries the two job ids and which property collided."""

    def __init__(self, message: str = "", jobs=(),
                 prop: str = "unknown"):
        super().__init__(message)
        self.jobs = tuple(str(j) for j in jobs)
        self.prop = str(prop)


class Revoked(MpiError):
    """MPIX_ERR_REVOKED: the communicator (cid) was revoked — every
    pending and future operation on it must raise on all live ranks."""

    errclass = ERR_REVOKED

    def __init__(self, message: str = "", cid: int = -1):
        super().__init__(message)
        self.cid = cid
